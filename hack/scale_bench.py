"""Fleet-scale orchestration bench: rollout cost vs pool size.

Drives a FULL rolling CC reconfiguration over a simulated fleet of
100 / 1k / 10k nodes — thousands of simulated node agents (FakeKube
backed, each behind its own seeded-FaultPlan chaos client) converging on
the desired-mode labels the orchestrator writes — and measures what the
orchestrator costs the apiserver, per verb:

- **legacy** mode is the pre-informer orchestrator: every await poll and
  window boundary re-lists the pool — O(pool) requests AND O(pool)
  response bytes per decision;
- **informer** mode is the watch-driven cache (ccmanager/informer.py)
  plus sharded rollout waves: one chunked listing, one watch, awaits
  wake on cache events — O(changes).

The artifact (SCALE_r01.json) records rollout wall-clock and the
orchestrator's per-verb apiserver request counts at each pool size, so
the O(pool)→O(changes) drop is a measured number, not an assertion. The
acceptance bar: ≥10× fewer list requests at 1k nodes in informer mode.

Resumable: ``--partial FILE`` appends one JSON line per completed
(mode, size) run and skips combos already recorded — the evidence ladder
(hack/evidence_r5.sh) re-runs the script after an interruption without
re-buying finished pools.

Legacy mode at 10k nodes is skipped by default (--full enables it): its
O(pool) listings make the run minutes-long by construction, which is the
very pathology the informer exists to remove; the 1k comparison already
quantifies it.

``--apiserver`` swaps FakeKube for the real HTTP ``hack/mock_apiserver.py``
behind RestKube — chunked listings, selector watches and lease CAS ride
the wire — with the fleet's agents emulated server-side (ServerAgentSim);
defaults to the 1k-node fleet and SCALE_r02.json (mock-apiserver scale
parity, ROADMAP item 1 headroom).

Usage:
    python hack/scale_bench.py                       # full bench
    python hack/scale_bench.py --sizes 100,1000      # subset
    python hack/scale_bench.py --apiserver           # 1k nodes over HTTP
    python hack/scale_bench.py --out SCALE_r01.json --partial artifacts/scale_partial.jsonl
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import random
import sys
import tempfile
import threading
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_cc_manager.ccmanager import rollout_state  # noqa: E402
from tpu_cc_manager.ccmanager.informer import NodeInformer  # noqa: E402
from tpu_cc_manager.ccmanager.rolling import (  # noqa: E402
    RollingReconfigurator,
    ZONE_LABEL,
)
from tpu_cc_manager.faults.kube import FaultyKubeClient  # noqa: E402
from tpu_cc_manager.faults.plan import FaultPlan, OrchestratorKilled  # noqa: E402
from tpu_cc_manager.kubeclient.api import (  # noqa: E402
    KubeApiError,
    classify_kube_error,
    node_labels,
)
from tpu_cc_manager.kubeclient.fake import FakeKube  # noqa: E402
from tpu_cc_manager.labels import (  # noqa: E402
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    SLICE_ID_LABEL,
)
from tpu_cc_manager.lint import expo as expo_lint  # noqa: E402
from tpu_cc_manager.obs import fleet as fleet_mod  # noqa: E402
from tpu_cc_manager.obs import flight as flight_mod  # noqa: E402
from tpu_cc_manager.utils import retry as retry_mod  # noqa: E402
from tpu_cc_manager.utils.metrics import MetricsRegistry  # noqa: E402

SELECTOR = "pool=tpu"
DEFAULT_SEED = 20260803


class CountingKube:
    """Pass-through wrapper counting the ORCHESTRATOR's per-verb requests
    (FakeKube.request_counts sees the whole fleet — agents included — so
    the orchestrator's own apiserver footprint needs its own ledger)."""

    _VERBS = {
        "get_node": "get", "list_nodes": "list", "list_nodes_page": "list",
        "list_pods": "list", "patch_node_labels": "patch",
        "patch_node_annotations": "patch", "patch_node_taints": "patch",
        "watch_nodes": "watch", "watch_nodes_pool": "watch",
        "create_event": "create", "get_lease": "get",
        "create_lease": "create", "update_lease": "update",
        "delete_lease": "delete",
    }

    def __init__(self, inner):
        self.inner = inner
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.retries_internally = getattr(inner, "retries_internally", False)

    def _count(self, verb: str) -> None:
        with self._lock:
            self.counts[verb] = self.counts.get(verb, 0) + 1

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        verb = self._VERBS.get(name)
        if verb is None or not callable(attr):
            return attr

        def counted(*args, **kwargs):
            self._count(verb)
            return attr(*args, **kwargs)

        return counted


class AgentSim:
    """Thousands of simulated node agents without thousands of threads.

    A FakeKube patch reactor models each agent's watch: when a node's
    desired mode diverges from its state, the agent schedules a
    transition (seeded per-node latency), executed by a small worker pool
    through that node's own FaultyKubeClient — so every agent's apiserver
    traffic rides a seeded FaultPlan, like the chaos soak's single agent,
    and the fleet's convergence is exercised under per-node weather."""

    def __init__(
        self,
        fake: FakeKube,
        seed: int,
        fault_rate: float = 0.02,
        workers: int = 24,
        min_delay_s: float = 0.02,
        max_delay_s: float = 0.08,
    ) -> None:
        self.fake = fake
        self.seed = seed
        self.fault_rate = fault_rate
        self.min_delay_s = min_delay_s
        self.max_delay_s = max_delay_s
        self._cond = threading.Condition()
        self._heap: list[tuple[float, str, str]] = []
        self._scheduled: set[str] = set()
        self._stop = False
        self._clients: dict[str, FaultyKubeClient] = {}
        self._rngs: dict[str, random.Random] = {}
        self.transitions = 0
        self.errors = 0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(workers)
        ]
        fake.add_patch_reactor(self._react)
        for t in self._threads:
            t.start()

    def _client(self, node: str) -> FaultyKubeClient:
        client = self._clients.get(node)
        if client is None:
            # crc32, not hash(): tuple/str hashes are randomized per
            # process (PYTHONHASHSEED), and the whole point of a seeded
            # FaultPlan is same-seed-same-schedule across runs.
            node_seed = zlib.crc32(f"{self.seed}:{node}".encode())
            plan = FaultPlan(
                seed=node_seed,
                rate=self.fault_rate,
                retry_after_s=0.01,
                slow_s=0.005,
            )
            client = FaultyKubeClient(self.fake, plan)
            self._clients[node] = client
            self._rngs[node] = random.Random(node_seed ^ 0xDE1A)
        return client

    def _react(self, name: str, node: dict) -> None:
        labels = node_labels(node)
        desired = labels.get(CC_MODE_LABEL)
        state = labels.get(CC_MODE_STATE_LABEL)
        if not desired or desired == state:
            return
        with self._cond:
            if name in self._scheduled:
                return
            self._client(name)  # seed rng/client outside the worker
            delay = self._rngs[name].uniform(self.min_delay_s, self.max_delay_s)
            heapq.heappush(
                self._heap, (time.monotonic() + delay, name, desired)
            )
            self._scheduled.add(name)
            self._cond.notify()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stop and (
                    not self._heap or self._heap[0][0] > time.monotonic()
                ):
                    timeout = (
                        self._heap[0][0] - time.monotonic()
                        if self._heap else 0.2
                    )
                    self._cond.wait(timeout=max(0.001, min(timeout, 0.2)))
                if self._stop:
                    return
                _, name, desired = heapq.heappop(self._heap)
            self._transition(name, desired)
            with self._cond:
                self._scheduled.discard(name)

    def _transition(self, name: str, desired: str) -> None:
        api = self._client(name)
        policy = retry_mod.RetryPolicy(
            max_attempts=5, base_delay_s=0.01, max_delay_s=0.1
        )
        try:
            # The agent's confirm read + truthful state report — the same
            # two requests a real reconcile's cheap path costs.
            policy.call(
                lambda: api.get_node(name),
                op="agent.confirm", classify=classify_kube_error,
            )
            policy.call(
                lambda: api.patch_node_labels(
                    name, {CC_MODE_STATE_LABEL: desired}
                ),
                op="agent.report", classify=classify_kube_error,
            )
            self.transitions += 1
        except KubeApiError:
            # Exhausted the ladder under seeded weather: the reactor fires
            # again on the next desired-label event; count it.
            self.errors += 1

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=1.0)


def fleet_labels(i: int, n: int, hosts_per_slice: int, zones: int) -> dict:
    slice_count = max(1, n // hosts_per_slice)
    sid = i % slice_count
    return {
        "pool": "tpu",
        SLICE_ID_LABEL: f"scale-s{sid:05d}",
        ZONE_LABEL: f"zone-{sid % zones}",
        CC_MODE_STATE_LABEL: "off",
    }


def build_fleet(
    fake: FakeKube, n: int, hosts_per_slice: int = 4, zones: int = 8
) -> None:
    for i in range(n):
        fake.add_node(
            f"scale-n{i:05d}", fleet_labels(i, n, hosts_per_slice, zones)
        )


# ---------------------------------------------------------------------------
# --apiserver mode: the SAME rollout, but the orchestrator speaks real
# HTTP to hack/mock_apiserver.py through RestKube — chunked listings,
# selector watches, lease CAS and merge-patches all ride the wire, so
# the informer-vs-legacy comparison covers serialization and transport,
# not just FakeKube method calls (ROADMAP item 1's "mock-apiserver scale
# parity" headroom).
# ---------------------------------------------------------------------------

_MOCK_THREADS_STARTED = [False]


def _load_mock():
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)))
    )
    import mock_apiserver as mock

    return mock


def _reset_mock(mock) -> None:
    with mock.lock:
        mock.nodes.clear()
        mock.pods.clear()
        mock.leases.clear()
        mock.request_counts.clear()
        mock.page_snapshots.clear()
        mock.events.clear()
        mock.sticky_pods.clear()
        mock.compacted_below[0] = 0


class ServerAgentSim:
    """The fleet's agents, emulated server-side: a scheduler thread scans
    the mock's node table for desired≠state, schedules each flip after a
    seeded per-node latency, and applies it under the mock's lock (state
    label + rv bump + watch event) — exactly the churn a real fleet's
    DaemonSet generates, without 1k HTTP clients. The ORCHESTRATOR is the
    process under test here; its traffic is what rides the wire."""

    def __init__(
        self,
        mock,
        seed: int,
        min_delay_s: float = 0.02,
        max_delay_s: float = 0.08,
        scan_interval_s: float = 0.01,
        dead_nodes: set[str] | None = None,
    ) -> None:
        self.mock = mock
        self.seed = seed
        self.min_delay_s = min_delay_s
        self.max_delay_s = max_delay_s
        self.scan_interval_s = scan_interval_s
        #: Names whose agents never flip (simulated hardware failure).
        #: The set is live: clearing it mid-run models the hardware
        #: recovering — the next scan schedules the flip normally.
        self.dead_nodes = dead_nodes if dead_nodes is not None else set()
        self.transitions = 0
        self._due: list[tuple[float, str, str]] = []
        self._scheduled: set[str] = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _delay(self, name: str) -> float:
        rng = random.Random(zlib.crc32(f"{self.seed}:{name}".encode()))
        return rng.uniform(self.min_delay_s, self.max_delay_s)

    def _loop(self) -> None:
        mock = self.mock
        while not self._stop.wait(self.scan_interval_s):
            now = time.monotonic()
            with mock.lock:
                for name, node in mock.nodes.items():
                    labels = node["metadata"]["labels"]
                    desired = labels.get(CC_MODE_LABEL)
                    state = labels.get(CC_MODE_STATE_LABEL)
                    if (
                        desired
                        and desired != state
                        and name not in self._scheduled
                        and name not in self.dead_nodes
                    ):
                        self._scheduled.add(name)
                        heapq.heappush(
                            self._due,
                            (now + self._delay(name), name, desired),
                        )
            while self._due and self._due[0][0] <= time.monotonic():
                _, name, desired = heapq.heappop(self._due)
                with mock.lock:
                    node = mock.nodes.get(name)
                    if node is None:
                        continue
                    node["metadata"]["labels"][CC_MODE_STATE_LABEL] = desired
                    mock.bump_rv(node)
                    mock.emit_watch_event(node)
                self._scheduled.discard(name)
                self.transitions += 1

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def run_pool_apiserver(
    n: int,
    mode: str,
    seed: int = DEFAULT_SEED,
    shards: int = 8,
    per_shard_unavailable: int = 4,
    poll_interval_s: float = 0.2,
    node_timeout_s: float = 300.0,
    hosts_per_slice: int = 4,
) -> dict:
    """One full rollout over an n-node fleet served by the real HTTP mock
    apiserver; the orchestrator runs RestKube end-to-end."""
    from http.server import ThreadingHTTPServer

    from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

    mock = _load_mock()
    _reset_mock(mock)
    with mock.lock:
        for i in range(n):
            name = f"scale-n{i:05d}"
            mock.nodes[name] = {
                "kind": "Node",
                "apiVersion": "v1",
                "metadata": {
                    "name": name,
                    "resourceVersion": "1",
                    "labels": fleet_labels(i, n, hosts_per_slice, zones=8),
                },
            }
    if not _MOCK_THREADS_STARTED[0]:
        threading.Thread(target=mock._watch_writer, daemon=True).start()
        _MOCK_THREADS_STARTED[0] = True
    srv = ThreadingHTTPServer(("127.0.0.1", 0), mock.Handler)
    srv_thread = threading.Thread(target=srv.serve_forever, daemon=True)
    srv_thread.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    sim = ServerAgentSim(mock, seed=seed)
    client = RestKube(ClusterConfig(server=url, token="scale-bench"))
    counting = CountingKube(client)
    informer = None
    total_unavailable = shards * per_shard_unavailable
    try:
        if mode == "informer":
            informer = NodeInformer(
                counting, SELECTOR, page_limit=500,
            ).start(sync_timeout_s=120.0)
            roller = RollingReconfigurator(
                counting, SELECTOR,
                max_unavailable=per_shard_unavailable,
                poll_interval_s=poll_interval_s,
                node_timeout_s=node_timeout_s,
                informer=informer,
                wave_shards=shards,
            )
        else:
            roller = RollingReconfigurator(
                counting, SELECTOR,
                max_unavailable=total_unavailable,
                poll_interval_s=poll_interval_s,
                node_timeout_s=node_timeout_s,
            )
        t0 = time.monotonic()
        result = roller.rollout("on")
        seconds = time.monotonic() - t0
        with mock.lock:
            converged = all(
                node["metadata"]["labels"].get(CC_MODE_STATE_LABEL) == "on"
                for node in mock.nodes.values()
            )
            server_requests = dict(sorted(mock.request_counts.items()))
    finally:
        if informer is not None:
            informer.stop()
        sim.stop()
        srv.shutdown()
        srv_thread.join(timeout=5.0)
    return {
        "nodes": n,
        "mode": mode,
        "transport": "http",
        "ok": bool(result.ok and converged),
        "converged": converged,
        "seconds": round(seconds, 2),
        "groups": len(result.groups),
        "wave_shards": shards if mode == "informer" else 1,
        "max_unavailable_total": total_unavailable,
        "orchestrator_requests": dict(sorted(counting.counts.items())),
        "apiserver_requests": server_requests,
        "agent_transitions": sim.transitions,
    }


# ---------------------------------------------------------------------------
# --federation mode: the ISSUE 17 acceptance bench (SCALE_r03). One
# federated rollout over >=100k nodes sharded across >=10 REGIONS, each
# region served by its OWN mock apiserver instance (mock_apiserver
# .MockState + make_handler) with its own server-side agent sim — plus a
# dedicated control-plane apiserver hosting only the parent record's CAS
# lease (ccmanager/federation.py). Every regional orchestrator holds a
# regional lease, checkpoints a regional record, and settles the single
# global failure budget through the parent at wave boundaries.
#
# Three things are measured and gated:
#  - per-apiserver load: each region's HTTP request count, normalized
#    per node, must stay within the SCALE_r02 1k-node informer baseline
#    plus a small allowance for what r02 did not carry (regional lease
#    checkpoints + acquire traffic);
#  - regional failure: one region's orchestrator is SIGKILL-simulated
#    mid-rollout (OrchestratorKilled at a crash point) and a successor
#    resumes from the regional record, re-attaching to the live parent;
#  - cross-region observability: every region writes its own flight
#    file; stitch_files + reconstruct must rebuild ONE timeline with
#    every node's outcome exactly once across all regions and the kill.
# ---------------------------------------------------------------------------

#: SCALE_r02's measured per-node apiserver cost for the 1k informer run
#: ({list: 2, patch: 1000, watch: 1} ≈ 1.003 req/node), re-read from the
#: committed artifact when present so the gate tracks the actual
#: baseline, not a stale constant.
R02_FALLBACK_PER_NODE = 1.003
#: The r03 run adds traffic r02 did not have: regional lease
#: create/acquire + one CAS checkpoint per window + the resume leg's
#: re-list. All are O(windows) or O(1), not O(nodes); 0.25 req/node
#: bounds them with room at 10k nodes/region.
FEDERATION_PER_NODE_ALLOWANCE = 0.25


def _r02_baseline_per_node() -> float:
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SCALE_r02.json",
    )
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        for row in doc.get("pools", []):
            if row.get("mode") == "informer" and row.get("apiserver_requests"):
                return sum(row["apiserver_requests"].values()) / row["nodes"]
    except (OSError, ValueError, KeyError, ZeroDivisionError):
        pass
    return R02_FALLBACK_PER_NODE


def _federation_region_fleet(state, region: str, n: int,
                             hosts_per_slice: int = 4) -> None:
    from tpu_cc_manager.ccmanager import federation as federation_mod

    for i in range(n):
        name = f"{region}-n{i:05d}"
        labels = fleet_labels(i, n, hosts_per_slice, zones=8)
        labels[federation_mod.REGION_LABEL] = region
        state.nodes[name] = {
            "kind": "Node",
            "apiVersion": "v1",
            "metadata": {
                "name": name,
                "resourceVersion": "1",
                "labels": labels,
            },
        }


def run_federation(
    total_nodes: int = 100_000,
    regions_count: int = 10,
    seed: int = DEFAULT_SEED,
    shards: int = 8,
    per_shard_unavailable: int = 25,
    poll_interval_s: float = 0.05,
    node_timeout_s: float = 600.0,
    kill_region_index: int = 3,
    kill_at: int | None = None,
) -> dict:
    """One federated rollout across ``regions_count`` regional mock
    apiservers, one region killed mid-flight and resumed; returns the
    SCALE_r03 row."""
    from http.server import ThreadingHTTPServer

    from tpu_cc_manager.ccmanager import federation as federation_mod
    from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

    mock = _load_mock()
    ns = "tpu-operator"
    nodes_per_region = total_nodes // regions_count
    regions = [f"r{i:02d}" for i in range(regions_count)]
    kill_region = regions[kill_region_index % len(regions)]
    if kill_at is None:
        # Deep enough to be mid-rollout, shallow enough that small smoke
        # fleets (tests) still reach it before the region completes.
        kill_at = 40 if nodes_per_region >= 1000 else 8
    flight_dir = tempfile.mkdtemp(prefix="scale-federation-")

    servers: list = []
    region_urls: dict[str, str] = {}
    region_states: dict[str, object] = {}
    sims: dict[str, ServerAgentSim] = {}

    def start_server(state) -> str:
        state.start_threads()
        srv = ThreadingHTTPServer(
            ("127.0.0.1", 0), mock.make_handler(state)
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    # The control plane: ONLY the parent record's CAS lease lives here,
    # so the per-region load gate measures regional traffic alone.
    control_state = mock.MockState()
    control_url = start_server(control_state)
    for region in regions:
        state = mock.MockState()
        _federation_region_fleet(state, region, nodes_per_region)
        region_urls[region] = start_server(state)
        region_states[region] = state
        sims[region] = ServerAgentSim(
            state, seed=seed, min_delay_s=0.01, max_delay_s=0.04,
            scan_interval_s=0.1,
        )

    def control_client():
        return RestKube(ClusterConfig(server=control_url, token="scale-bench"))

    parent = federation_mod.ParentStore(
        control_client(), namespace=ns
    ).initialize(
        federation_mod.ParentRecord.fresh(
            "on", SELECTOR, regions,
            max_unavailable=shards * per_shard_unavailable,
        ),
        resume=False,
    )

    results: dict[str, dict] = {}
    errors: dict[str, BaseException] = {}
    flight_files: dict[str, list[str]] = {region: [] for region in regions}
    results_lock = threading.Lock()

    def run_leg(region, client, lease, resume_record, gate, flight_path):
        informer = NodeInformer(
            client, federation_mod.regional_selector(SELECTOR, region),
            page_limit=500,
        ).start(sync_timeout_s=120.0)
        crash_hook = None
        if region == kill_region and resume_record is None:
            calls = {"n": 0}

            def killer(point):
                if calls["n"] == kill_at:
                    raise OrchestratorKilled(point, calls["n"])
                calls["n"] += 1

            crash_hook = killer
        try:
            roller = RollingReconfigurator(
                client,
                federation_mod.regional_selector(SELECTOR, region),
                max_unavailable=per_shard_unavailable,
                poll_interval_s=poll_interval_s,
                node_timeout_s=node_timeout_s,
                informer=informer,
                wave_shards=shards,
                lease=lease,
                resume_record=resume_record,
                crash_hook=crash_hook,
                flight=flight_mod.FlightRecorder(
                    flight_path, generation=lease.generation
                ),
                federation=gate,
            )
            mode = resume_record.mode if resume_record is not None else "on"
            return roller.rollout(mode)
        finally:
            informer.stop()

    def run_region(region: str) -> None:
        client = CountingKube(
            RestKube(
                ClusterConfig(server=region_urls[region], token="scale-bench")
            )
        )
        store = federation_mod.ParentStore(control_client(), namespace=ns)
        # Injected lease clock (gateway-stitch idiom): time stands still
        # during a leg, so leases never lapse mid-run without a renewer,
        # and the kill leg advances past the dead holder's TTL exactly.
        clk = _BenchClock()
        killed = resumed = False
        t0 = time.monotonic()
        result = None
        try:
            lease = rollout_state.RolloutLease(
                client, holder=f"bench-{region}-a", namespace=ns,
                name=federation_mod.regional_lease_name(region),
                duration_s=30.0, wall=clk, clock=clk,
            )
            lease.acquire()
            gate = federation_mod.FederationGate(store, region)
            gate.attach(parent)
            path_a = os.path.join(flight_dir, f"orch-{region}-a.jsonl")
            flight_files[region].append(path_a)
            try:
                result = run_leg(region, client, lease, None, gate, path_a)
            except OrchestratorKilled:
                killed = True
                clk.advance(31.0)  # dead holder's lease TTL lapses
                lease_b = rollout_state.RolloutLease(
                    client, holder=f"bench-{region}-b", namespace=ns,
                    name=federation_mod.regional_lease_name(region),
                    duration_s=30.0, wall=clk, clock=clk,
                )
                record = lease_b.acquire()
                if record is None or not record.federation:
                    raise RuntimeError(
                        f"{region}: resumed record lost its federation "
                        "attachment"
                    )
                gate_b = federation_mod.FederationGate.from_record_dict(
                    control_client(), record.federation
                )
                resumed = True
                path_b = os.path.join(flight_dir, f"orch-{region}-b.jsonl")
                flight_files[region].append(path_b)
                lease = lease_b
                result = run_leg(
                    region, client, lease_b, record, gate_b, path_b
                )
            lease.release(clear_record=bool(result.ok))
        except BaseException as e:  # noqa: BLE001 - surfaced to the caller
            with results_lock:
                errors[region] = e
            return
        with results_lock:
            results[region] = {
                "ok": bool(result.ok),
                "groups": len(result.groups),
                "seconds": round(time.monotonic() - t0, 2),
                "killed": killed,
                "resumed": resumed,
            }

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=run_region, args=(region,), daemon=True)
        for region in regions
    ]
    final = None
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        seconds = time.monotonic() - t0
        if not errors:
            final = federation_mod.ParentStore(
                control_client(), namespace=ns
            ).load()
    finally:
        for sim in sims.values():
            sim.stop()
        for srv in servers:
            srv.shutdown()
    if errors:
        region, err = sorted(errors.items())[0]
        raise RuntimeError(f"region {region} failed: {err!r}") from err

    baseline = _r02_baseline_per_node()
    per_node_budget = round(baseline + FEDERATION_PER_NODE_ALLOWANCE, 3)
    per_apiserver: dict[str, dict] = {}
    load_ok = True
    for region in regions:
        state = region_states[region]
        with state.lock:
            counts = dict(sorted(state.request_counts.items()))
            converged = all(
                node["metadata"]["labels"].get(CC_MODE_STATE_LABEL) == "on"
                for node in state.nodes.values()
            )
        total = sum(counts.values())
        per_node = round(total / max(1, nodes_per_region), 3)
        ok_region = per_node <= per_node_budget and converged
        load_ok = load_ok and ok_region
        per_apiserver[region] = {
            "requests": counts,
            "total": total,
            "per_node": per_node,
            "converged": converged,
        }
    with control_state.lock:
        control_requests = dict(sorted(control_state.request_counts.items()))

    all_paths = [p for region in regions for p in flight_files[region]]
    stitched, torn = flight_mod.stitch_files(all_paths)
    rec = flight_mod.reconstruct(stitched)
    all_nodes = {
        f"{region}-n{i:05d}"
        for region in regions
        for i in range(nodes_per_region)
    }
    exactly_once = (
        set(rec["nodes"]) == all_nodes
        and not rec["duplicate_node_events"]
        and all(
            e["outcome"] == "node-converged" for e in rec["nodes"].values()
        )
    )
    killed_row = results.get(kill_region, {})
    ok = bool(
        results
        and all(r["ok"] for r in results.values())
        and final is not None
        and final.status == federation_mod.PARENT_COMPLETE
        and load_ok
        and killed_row.get("killed")
        and killed_row.get("resumed")
        and torn == 0
        and exactly_once
    )
    return {
        "mode": "federation",
        "nodes": total_nodes,
        "transport": "http",
        "ok": ok,
        "seconds": round(seconds, 2),
        "regions": regions_count,
        "nodes_per_region": nodes_per_region,
        "wave_shards": shards,
        "max_unavailable_per_region": per_shard_unavailable * shards,
        "killed_region": kill_region,
        "kill_at": kill_at,
        "parent_status": final.status if final is not None else "missing",
        "budget_spend": len(final.budget_spend) if final is not None else -1,
        "region_results": {r: results[r] for r in sorted(results)},
        "per_apiserver": per_apiserver,
        "baseline_per_node_r02": round(baseline, 3),
        "per_node_budget": per_node_budget,
        "apiserver_load_ok": load_ok,
        "control_plane_requests": control_requests,
        "stitch": {
            "files": len(all_paths),
            "events": len(stitched),
            "torn_lines": torn,
            "resumes": rec["resumes"],
            "generations": sorted(rec["generations"]),
            "exactly_once": exactly_once,
        },
    }


# ---------------------------------------------------------------------------
# --federation-blackout mode: the ISSUE 18 acceptance bench (SCALE_r04).
# Same federated topology as --federation, but the PARENT PLANE goes
# dark mid-rollout — per-region FaultyKubeClient wrappers around the
# control-plane client refuse every parent CAS while a blackout window
# is open. What the bench must prove:
#  - every region either completes or escrow-halts WITHOUT the parent:
#    healthy regions ride seeded blackout windows, charge nothing, and
#    reconcile on reconnect; the escrow region times out a dead slice
#    while dark, charges its escrowed budget slice, and halts
#    `escrow-exhausted` the moment dark spend would exceed it;
#  - a SIGKILL at the `parent-offline` crash point (mid-blackout) is
#    survivable: the successor takes the regional lease over through the
#    skew-proof observation window (its wall clock disagrees with the
#    dead holder's by ~135 s) and dark-resumes from the checkpointed
#    escrow ledger;
#  - reconciliation is exactly-once: after every region reconnects, the
#    parent's budget_spend is EXACTLY the dead slice — no dark charge
#    lost, none double-counted, every unused escrow slice returned;
#  - the stitched cross-region timeline has zero torn lines and every
#    node's final outcome is converged exactly once.
# ---------------------------------------------------------------------------


def run_federation_blackout(
    total_nodes: int = 100_000,
    regions_count: int = 10,
    seed: int = DEFAULT_SEED,
    shards: int = 8,
    per_shard_unavailable: int = 25,
    poll_interval_s: float = 0.05,
    # Healthy nodes converge in up to ~60 s under full 100k-node thread
    # contention; only the escrow region's dead slice may time out, so
    # the bar sits at 2x the observed worst case.
    node_timeout_s: float = 120.0,
    kill_region_index: int = 3,
    escrow_region_index: int = 5,
    hetero_region_index: int = 2,
    max_clock_skew_s: float = 150.0,
) -> dict:
    """One federated rollout through a parent-plane blackout; returns
    the SCALE_r04 row."""
    from http.server import ThreadingHTTPServer

    from tpu_cc_manager.ccmanager import federation as federation_mod
    from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

    if regions_count < 4:
        raise ValueError("--federation-blackout needs >= 4 regions")
    mock = _load_mock()
    ns = "tpu-operator"
    nodes_per_region = total_nodes // regions_count
    windows = -(-nodes_per_region // max(1, shards * per_shard_unavailable))
    regions = [f"r{i:02d}" for i in range(regions_count)]
    kill_region = regions[kill_region_index % regions_count]
    escrow_region = regions[escrow_region_index % regions_count]
    hetero_region = regions[hetero_region_index % regions_count]
    if len({kill_region, escrow_region, hetero_region}) != 3:
        raise ValueError(
            "kill/escrow/hetero region indices must map to distinct regions"
        )
    # The dead slice: ALL hosts of ONE slice (hosts are striped across
    # the region: slice s = {s + j*slice_count}). A fully-dead slice
    # keeps the stitched timeline exactly-once — its resume re-drives
    # only FAILED nodes (the designed re-drive path), never re-bouncing
    # a converged one — and its whole charge lands at one boundary, so
    # the escrow halt is deterministic.
    hosts_per_slice = 4
    slice_count = max(1, nodes_per_region // hosts_per_slice)
    dead_slice = int(slice_count * 0.3)
    dead_live = {
        f"{escrow_region}-n{dead_slice + j * slice_count:05d}"
        for j in range(hosts_per_slice)
    }
    dead_nodes = set(dead_live)
    offline_grace_s = 0.05
    skew_rng = random.Random(seed ^ 0x51E11)
    region_skews = {r: skew_rng.uniform(-120.0, 120.0) for r in regions}
    flight_dir = tempfile.mkdtemp(prefix="scale-blackout-")

    servers: list = []
    region_urls: dict[str, str] = {}
    region_states: dict[str, object] = {}
    sims: dict[str, ServerAgentSim] = {}

    def start_server(state) -> str:
        state.start_threads()
        srv = ThreadingHTTPServer(
            ("127.0.0.1", 0), mock.make_handler(state)
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    control_state = mock.MockState()
    control_url = start_server(control_state)
    for region in regions:
        state = mock.MockState()
        _federation_region_fleet(state, region, nodes_per_region)
        region_urls[region] = start_server(state)
        region_states[region] = state
        sims[region] = ServerAgentSim(
            state, seed=seed, min_delay_s=0.01, max_delay_s=0.04,
            scan_interval_s=0.1,
            dead_nodes=dead_live if region == escrow_region else None,
        )

    def control_client():
        return RestKube(ClusterConfig(server=control_url, token="scale-bench"))

    # Per-region chaos plans over the PARENT client only: the regional
    # apiservers stay healthy — this is a parent-plane partition, not a
    # regional outage. Spans are sized in parent CALLS (one per dark
    # boundary sync) to end well before the terminal status push.
    parent_plans = {
        region: FaultPlan(
            seed=seed * 1009 + idx, rate=0.0, watch_rate=0.0,
            blackout_min_calls=max(2, windows // 3),
            blackout_max_calls=max(max(2, windows // 3) + 1, windows // 2),
        )
        for idx, region in enumerate(regions)
    }
    faulty_controls = {
        region: FaultyKubeClient(
            control_client(), parent_plans[region], sleep=lambda s: None
        )
        for region in regions
    }

    parent = federation_mod.ParentStore(
        control_client(), namespace=ns
    ).initialize(
        federation_mod.ParentRecord.fresh(
            "on", SELECTOR, regions,
            max_unavailable=shards * per_shard_unavailable,
            # Global budget == region count: fair-share escrow resolves
            # to exactly 1 per region, so the escrow region (2 dead
            # hosts) MUST halt while dark, and the total spend stays
            # within budget. One region carries an explicit per-region
            # cap so the heterogeneous-budget parent format (v2) is what
            # this artifact actually serializes.
            failure_budget=regions_count,
            region_budgets={hetero_region: 2},
        ),
        resume=False,
    )

    results: dict[str, dict] = {}
    errors: dict[str, BaseException] = {}
    flight_files: dict[str, list[str]] = {region: [] for region in regions}
    results_lock = threading.Lock()

    def run_leg(region, client, lease, resume_record, gate, flight_path,
                crash_hook):
        informer = NodeInformer(
            client, federation_mod.regional_selector(SELECTOR, region),
            page_limit=500,
        ).start(sync_timeout_s=120.0)
        try:
            roller = RollingReconfigurator(
                client,
                federation_mod.regional_selector(SELECTOR, region),
                max_unavailable=per_shard_unavailable,
                poll_interval_s=poll_interval_s,
                node_timeout_s=node_timeout_s,
                informer=informer,
                wave_shards=shards,
                lease=lease,
                resume_record=resume_record,
                crash_hook=crash_hook,
                # A timed-out slice must CHARGE the budget and press on
                # (degraded-mode semantics), not halt the whole region.
                continue_on_failure=True,
                flight=flight_mod.FlightRecorder(
                    flight_path, generation=lease.generation
                ),
                federation=gate,
            )
            mode = resume_record.mode if resume_record is not None else "on"
            return roller.rollout(mode)
        finally:
            informer.stop()

    def regional_lease(client, region, holder, clk, skew):
        return rollout_state.RolloutLease(
            client, holder=holder, namespace=ns,
            name=federation_mod.regional_lease_name(region),
            duration_s=30.0, wall=lambda: clk() + skew, clock=clk,
            max_clock_skew_s=max_clock_skew_s,
        )

    def run_region(region: str) -> None:
        client = CountingKube(
            RestKube(
                ClusterConfig(server=region_urls[region], token="scale-bench")
            )
        )
        plan = parent_plans[region]
        parent_api = faulty_controls[region]
        store = federation_mod.ParentStore(parent_api, namespace=ns)
        clk = _BenchClock()
        skew_a = region_skews[region]
        killed = resumed = resumed_dark = False
        escrow_halted_dark = escrow_resumed = False
        t0 = time.monotonic()
        result = None
        try:
            lease = regional_lease(
                client, region, f"bench-{region}-a", clk, skew_a
            )
            lease.acquire()
            gate = federation_mod.FederationGate(
                store, region, offline_grace_s=offline_grace_s
            )
            gate.attach(parent)  # attach is LIGHT: escrow reserved via CAS

            boundaries = {"n": 0}

            def hook(point):
                if point == "federation-boundary":
                    boundaries["n"] += 1
                    if region in (kill_region, escrow_region):
                        # Forced open-ended blackout from the FIRST
                        # boundary: every charge these regions make is
                        # guaranteed dark; the bench closes the window.
                        if boundaries["n"] == 1:
                            plan.begin_blackout()
                    elif boundaries["n"] == 2:
                        # Healthy regions ride a finite SEEDED window —
                        # the production chaos path — and reconnect
                        # before their terminal push.
                        plan.seed_blackout_window()
                if region == kill_region and point == "parent-offline":
                    raise OrchestratorKilled(point, boundaries["n"])

            path_a = os.path.join(flight_dir, f"orch-{region}-a.jsonl")
            flight_files[region].append(path_a)
            try:
                result = run_leg(region, client, lease, None, gate, path_a,
                                 hook)
            except OrchestratorKilled:
                killed = True
                clk.advance(31.0)  # dead holder's lease TTL lapses
                # The successor's wall clock disagrees with the dead
                # holder's by ~135 s, forcing acquire() through the
                # skew-proof observation window (expired OR
                # future-stamped, depending on sign). The observation
                # deadline runs on LOCAL monotonic time — a ticker
                # drives the injected bench clock through it.
                skew_b = skew_a + (135.0 if skew_a < 0 else -135.0)
                lease_b = regional_lease(
                    client, region, f"bench-{region}-b", clk, skew_b
                )
                stop_tick = threading.Event()

                def _tick():
                    while not stop_tick.wait(0.1):
                        clk.advance(4.0)

                ticker = threading.Thread(target=_tick, daemon=True)
                ticker.start()
                try:
                    record = lease_b.acquire()
                finally:
                    stop_tick.set()
                    ticker.join(timeout=2.0)
                if record is None or not record.federation:
                    raise RuntimeError(
                        f"{region}: resumed record lost its federation "
                        "attachment"
                    )
                # The successor comes up with the parent STILL dark (a
                # bounded re-armed window): the dark-resume path must
                # adopt the checkpointed escrow ledger, then reconcile
                # when the window expires. Two calls — the dark attach
                # plus one boundary — so even a successor with almost
                # nothing left to do still pushes its terminal status
                # through a LIVE parent.
                plan.end_blackout()
                plan.begin_blackout(calls=2)
                refusals_before = plan.blackout_refusals
                gate_b = federation_mod.FederationGate.from_record_dict(
                    parent_api, record.federation,
                    offline_grace_s=offline_grace_s,
                )
                resumed = True
                resumed_dark = plan.blackout_refusals > refusals_before
                path_b = os.path.join(flight_dir, f"orch-{region}-b.jsonl")
                flight_files[region].append(path_b)
                lease = lease_b
                result = run_leg(
                    region, client, lease_b, record, gate_b, path_b, None
                )
            if (
                region == escrow_region
                and result is not None
                and not result.ok
                and result.halted_reason
                == federation_mod.ESCROW_EXHAUSTED_REASON
            ):
                # The region halted autonomously, in the dark, with its
                # escrow slice spent on the dead hosts. Hardware
                # recovers, the parent plane comes back, and an operator
                # re-drives: the resume must reconcile the dark charges
                # exactly once and finish the remaining windows.
                escrow_halted_dark = plan.in_blackout
                plan.end_blackout()
                lease.release(clear_record=False)
                lease_c = regional_lease(
                    client, region, f"bench-{region}-c", clk, skew_a
                )
                record = lease_c.acquire()
                if record is None or not record.federation:
                    raise RuntimeError(
                        f"{region}: halted record lost its federation "
                        "attachment"
                    )
                gate_c = federation_mod.FederationGate.from_record_dict(
                    parent_api, record.federation,
                    offline_grace_s=offline_grace_s,
                )
                escrow_resumed = True
                path_c = os.path.join(flight_dir, f"orch-{region}-c.jsonl")
                flight_files[region].append(path_c)
                lease = lease_c

                # The dead hardware recovers only once the successor has
                # taken its pre-recovery listing and committed to
                # RE-DRIVING the failed group — window-start fires
                # strictly after the resume plan, so the timeline always
                # shows the designed `redriven` supersede instead of a
                # timing-dependent already-at-target re-observation (the
                # agent sim's scan loop would otherwise race the resume
                # listing and self-heal the slice, leaving node-failed
                # as the reconstructed outcome).
                def recovery_hook(point):
                    if point == "window-start" and dead_live:
                        dead_live.clear()

                result = run_leg(
                    region, client, lease_c, record, gate_c, path_c,
                    recovery_hook,
                )
            lease.release(clear_record=bool(result.ok))
        except BaseException as e:  # noqa: BLE001 - surfaced to the caller
            with results_lock:
                errors[region] = e
            return
        with results_lock:
            results[region] = {
                "ok": bool(result.ok),
                "groups": len(result.groups),
                "seconds": round(time.monotonic() - t0, 2),
                "killed": killed,
                "resumed": resumed,
                "resumed_dark": resumed_dark,
                "escrow_halted_dark": escrow_halted_dark,
                "escrow_resumed": escrow_resumed,
                "parent_blackout_windows": plan.blackout_windows,
                "parent_refusals": plan.blackout_refusals,
            }

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=run_region, args=(region,), daemon=True)
        for region in regions
    ]
    final = None
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        seconds = time.monotonic() - t0
        if not errors:
            final = federation_mod.ParentStore(
                control_client(), namespace=ns
            ).load()
    finally:
        for sim in sims.values():
            sim.stop()
        for srv in servers:
            srv.shutdown()
    if errors:
        region, err = sorted(errors.items())[0]
        raise RuntimeError(f"region {region} failed: {err!r}") from err

    baseline = _r02_baseline_per_node()
    per_node_budget = round(baseline + FEDERATION_PER_NODE_ALLOWANCE, 3)
    per_apiserver: dict[str, dict] = {}
    load_ok = True
    for region in regions:
        state = region_states[region]
        with state.lock:
            counts = dict(sorted(state.request_counts.items()))
            converged = all(
                node["metadata"]["labels"].get(CC_MODE_STATE_LABEL) == "on"
                for node in state.nodes.values()
            )
        total = sum(counts.values())
        per_node = round(total / max(1, nodes_per_region), 3)
        load_ok = load_ok and per_node <= per_node_budget and converged
        per_apiserver[region] = {
            "requests": counts,
            "total": total,
            "per_node": per_node,
            "converged": converged,
        }
    with control_state.lock:
        control_requests = dict(sorted(control_state.request_counts.items()))

    all_paths = [p for region in regions for p in flight_files[region]]
    stitched, torn = flight_mod.stitch_files(all_paths)
    rec = flight_mod.reconstruct(stitched)
    all_nodes = {
        f"{region}-n{i:05d}"
        for region in regions
        for i in range(nodes_per_region)
    }
    exactly_once = (
        set(rec["nodes"]) == all_nodes
        and not rec["duplicate_node_events"]
        and all(
            e["outcome"] == "node-converged" for e in rec["nodes"].values()
        )
    )
    offline_events = sum(
        1 for e in stitched
        if e.get("event") == flight_mod.EVENT_PARENT_OFFLINE
    )
    reconnect_events = sum(
        1 for e in stitched
        if e.get("event") == flight_mod.EVENT_PARENT_RECONNECT
    )
    spend = sorted(final.budget_spend) if final is not None else []
    # Exactly-once reconciliation, ledger-level: the parent's spend is
    # PRECISELY the dead slice (no dark charge lost or double-counted)
    # and every escrow slice went back to zero on terminal sync.
    spend_exact = spend == sorted(dead_nodes)
    escrow_zeroed = final is not None and all(
        v == 0 for v in final.escrow.values()
    )
    killed_row = results.get(kill_region, {})
    escrow_row = results.get(escrow_region, {})
    ok = bool(
        results
        and all(r["ok"] for r in results.values())
        and final is not None
        and final.status == federation_mod.PARENT_COMPLETE
        and final.region_budgets.get(hetero_region) == 2
        and killed_row.get("killed")
        and killed_row.get("resumed")
        and killed_row.get("resumed_dark")
        and escrow_row.get("escrow_halted_dark")
        and escrow_row.get("escrow_resumed")
        and spend_exact
        and escrow_zeroed
        and offline_events >= regions_count
        and reconnect_events >= regions_count - 2
        and torn == 0
        and exactly_once
    )
    return {
        "mode": "federation-blackout",
        "nodes": total_nodes,
        "transport": "http",
        "ok": ok,
        "seconds": round(seconds, 2),
        "regions": regions_count,
        "nodes_per_region": nodes_per_region,
        "wave_shards": shards,
        "max_unavailable_per_region": per_shard_unavailable * shards,
        "failure_budget": regions_count,
        "region_budgets": {hetero_region: 2},
        "killed_region": kill_region,
        "escrow_region": escrow_region,
        "dead_nodes": sorted(dead_nodes),
        "max_clock_skew_s": max_clock_skew_s,
        "parent_status": final.status if final is not None else "missing",
        "budget_spend": spend,
        "budget_spend_exactly_dead_slice": spend_exact,
        "escrow_zeroed": escrow_zeroed,
        "parent_offline_events": offline_events,
        "parent_reconnect_events": reconnect_events,
        "region_results": {r: results[r] for r in sorted(results)},
        "per_apiserver": per_apiserver,
        "baseline_per_node_r02": round(baseline, 3),
        "per_node_budget": per_node_budget,
        # Informational here (the load acceptance gate is SCALE_r03):
        # this bench gates partition-tolerance invariants, but a load
        # regression would still show up in these rows.
        "apiserver_load_ok": load_ok,
        "control_plane_requests": control_requests,
        "stitch": {
            "files": len(all_paths),
            "events": len(stitched),
            "torn_lines": torn,
            "resumes": rec["resumes"],
            "generations": sorted(rec["generations"]),
            "exactly_once": exactly_once,
        },
    }


def run_pool(
    n: int,
    mode: str,
    seed: int = DEFAULT_SEED,
    shards: int = 8,
    per_shard_unavailable: int = 4,
    poll_interval_s: float = 0.2,
    node_timeout_s: float = 120.0,
    hosts_per_slice: int = 4,
) -> dict:
    """One full rollout over an n-node fleet; returns the measured row."""
    fake = FakeKube()
    build_fleet(fake, n, hosts_per_slice=hosts_per_slice)
    sim = AgentSim(fake, seed=seed)
    counting = CountingKube(fake)
    informer = None
    total_unavailable = shards * per_shard_unavailable
    try:
        if mode == "informer":
            informer = NodeInformer(
                counting, SELECTOR, page_limit=500,
            ).start(sync_timeout_s=60.0)
            roller = RollingReconfigurator(
                counting, SELECTOR,
                max_unavailable=per_shard_unavailable,
                poll_interval_s=poll_interval_s,
                node_timeout_s=node_timeout_s,
                informer=informer,
                wave_shards=shards,
            )
        else:
            roller = RollingReconfigurator(
                counting, SELECTOR,
                max_unavailable=total_unavailable,
                poll_interval_s=poll_interval_s,
                node_timeout_s=node_timeout_s,
            )
        t0 = time.monotonic()
        result = roller.rollout("on")
        seconds = time.monotonic() - t0
    finally:
        if informer is not None:
            informer.stop()
        sim.stop()
    converged = all(
        node_labels(node).get(CC_MODE_STATE_LABEL) == "on"
        for node in fake.list_nodes(SELECTOR)
    )
    return {
        "nodes": n,
        "mode": mode,
        "ok": bool(result.ok and converged),
        "converged": converged,
        "seconds": round(seconds, 2),
        "groups": len(result.groups),
        "wave_shards": shards if mode == "informer" else 1,
        "max_unavailable_total": total_unavailable,
        "orchestrator_requests": dict(sorted(counting.counts.items())),
        "fleet_requests": dict(sorted(fake.request_counts.items())),
        "agent_transitions": sim.transitions,
        "agent_errors": sim.errors,
    }


# ---------------------------------------------------------------------------
# --gateway mode: the fleet observability plane (obs/fleet.py) over a
# simulated 100-node fleet — the ISSUE 16 acceptance bench. Three legs:
# a full-fleet scrape+merge must converge inside one gateway interval
# with a lint-clean merged exposition and a correct capacity ledger;
# killed agents must be marked stale within 2 intervals; and a sharded
# rollout killed mid-flight and resumed by a successor — each run
# writing its OWN flight file, like per-region orchestrators — must
# stitch back into one federated timeline that reconstructs every
# node's outcome exactly once.
# ---------------------------------------------------------------------------


class _BenchClock:
    """Injected lease clock for the stitch leg (advance past the lease
    TTL without waiting it out)."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def build_fleet_registries(
    n: int, seed: int
) -> tuple[dict[str, MetricsRegistry], set[str], set[str]]:
    """n per-node agent registries with seeded serve telemetry, plus the
    (disjoint) sets of quarantined and prestaging nodes — so the
    capacity ledger's expected headroom count is computable exactly."""
    registries: dict[str, MetricsRegistry] = {}
    quarantined: set[str] = set()
    prestaging: set[str] = set()
    for i in range(n):
        name = f"fleet-n{i:05d}"
        rng = random.Random(zlib.crc32(f"{seed}:obs:{name}".encode()))
        reg = MetricsRegistry()
        for _ in range(rng.randint(3, 8)):
            reg.observe_serve_request(name, rng.uniform(0.01, 0.5))
        reg.set_serve_queue_depth(name, rng.randint(0, 6))
        reg.set_serve_inflight(name, rng.randint(0, 4))
        reg.record_serve_outcome(name, "completed", rng.randint(5, 40))
        reg.set_serve_hbm_bw_util(name, rng.uniform(0.30, 0.85))
        if i % 29 == 7:
            reg.set_quarantined(True)
            quarantined.add(name)
        elif i % 31 == 11:
            reg.set_prestage_in_progress(True)
            prestaging.add(name)
        registries[name] = reg
    return registries, quarantined, prestaging


def run_gateway_scrape(
    n: int,
    seed: int = DEFAULT_SEED,
    interval_s: float = 5.0,
    kill: int = 10,
    workers: int = 16,
) -> dict:
    """Legs 1+2: full-fleet scrape+merge convergence and staleness."""
    registries, quarantined, prestaging = build_fleet_registries(n, seed)
    alive = {name: True for name in registries}

    def target(name: str, reg: MetricsRegistry):
        inner = fleet_mod.local_target(reg)

        def fetch(path: str) -> str:
            if not alive[name]:
                raise ConnectionError("agent killed by bench chaos")
            return inner(path)

        return fetch

    gateway = fleet_mod.FleetGateway(
        targets={name: target(name, reg) for name, reg in registries.items()},
        interval_s=interval_s,
        scrape_deadline_s=1.0,
        stale_after_sweeps=2,
        workers=workers,
    )
    t0 = time.monotonic()
    fleetz = gateway.scrape_once()
    sweep_seconds = time.monotonic() - t0
    merged = gateway.metrics_text()
    lint_problems = expo_lint.lint(merged)
    expected_headroom = n - len(quarantined) - len(prestaging)
    headroom_ok = fleetz["fleet"]["headroom_nodes"] == expected_headroom

    killed = sorted(alive)[:kill]
    for name in killed:
        alive[name] = False
    gateway.scrape_once()
    after_one = set(gateway.fleetz()["fleet"]["stale_nodes"])
    gateway.scrape_once()
    after_two = set(gateway.fleetz()["fleet"]["stale_nodes"])
    stale_ok = after_one.issubset(set(killed)) and after_two == set(killed)

    return {
        "nodes": n,
        "sweep_seconds": round(sweep_seconds, 3),
        "interval_s": interval_s,
        "converged_one_interval": bool(sweep_seconds <= interval_s),
        "merged_lines": len(merged.splitlines()),
        "merged_lint_problems": lint_problems,
        "headroom_nodes": fleetz["fleet"]["headroom_nodes"],
        "expected_headroom_nodes": expected_headroom,
        "quarantined": len(quarantined),
        "prestaging": len(prestaging),
        "fleet_p99_present": "tpu_cc_fleet_serve_p99_seconds" in merged,
        "killed_agents": len(killed),
        "stale_after_two_sweeps": sorted(after_two),
        "ok": bool(
            sweep_seconds <= interval_s
            and not lint_problems
            and headroom_ok
            and stale_ok
        ),
    }


def run_gateway_stitch(
    n: int = 16,
    seed: int = DEFAULT_SEED,
    shards: int = 4,
    kill_at: int = 6,
) -> dict:
    """Leg 3: a sharded rollout (wave_shards > 1) killed mid-flight and
    resumed by a successor orchestrator, each writing its OWN flight
    file; stitch_files must reconstruct exactly-once node outcomes
    across the kill."""
    fake = FakeKube()
    build_fleet(fake, n)
    sim = AgentSim(fake, seed=seed, fault_rate=0.0)
    clk = _BenchClock()
    metrics = MetricsRegistry()
    hook_calls = {"n": 0}

    def killer(point):
        if hook_calls["n"] == kill_at:
            raise OrchestratorKilled(point, hook_calls["n"])
        hook_calls["n"] += 1

    stitch_dir = tempfile.mkdtemp(prefix="scale-gateway-stitch-")
    path_a = os.path.join(stitch_dir, "orch-a.jsonl")
    path_b = os.path.join(stitch_dir, "orch-b.jsonl")

    def lease_for(holder: str) -> rollout_state.RolloutLease:
        return rollout_state.RolloutLease(
            fake, holder=holder, namespace="tpu-operator",
            duration_s=30.0, metrics=metrics, wall=clk, clock=clk,
        )

    killed = False
    try:
        lease_a = lease_for("orch-a")
        lease_a.acquire()
        roller_a = RollingReconfigurator(
            fake, SELECTOR, max_unavailable=4, node_timeout_s=10,
            poll_interval_s=0.02, wave_shards=shards, lease=lease_a,
            crash_hook=killer, metrics=metrics,
            flight=flight_mod.FlightRecorder(
                path_a, generation=lease_a.generation
            ),
        )
        try:
            result = roller_a.rollout("on")
        except OrchestratorKilled:
            killed = True
            clk.advance(31.0)  # the dead holder's lease TTL lapses
            lease_b = lease_for("orch-b")
            record = lease_b.acquire()
            roller_b = RollingReconfigurator(
                fake, SELECTOR, max_unavailable=4, node_timeout_s=10,
                poll_interval_s=0.02, wave_shards=shards, lease=lease_b,
                resume_record=record, metrics=metrics,
                flight=flight_mod.FlightRecorder(
                    path_b, generation=lease_b.generation
                ),
            )
            result = roller_b.rollout(record.mode if record else "on")
    finally:
        sim.stop()
    stitched, torn = flight_mod.stitch_files([path_a, path_b])
    rec = flight_mod.reconstruct(stitched)
    all_nodes = {f"scale-n{i:05d}" for i in range(n)}
    exactly_once = (
        set(rec["nodes"]) == all_nodes
        and not rec["duplicate_node_events"]
        and all(
            e["outcome"] == "node-converged" for e in rec["nodes"].values()
        )
    )
    streams = sorted({e.get("stream") for e in stitched})
    return {
        "nodes": n,
        "wave_shards": shards,
        "kill_at": kill_at,
        "killed": killed,
        "rollout_ok": bool(result.ok),
        "flight_files": 2,
        "streams_in_stitch": streams,
        "stitched_events": len(stitched),
        "torn_lines": torn,
        "resumes": rec["resumes"],
        "generations": rec["generations"],
        "exactly_once": exactly_once,
        "ok": bool(
            killed
            and result.ok
            and torn == 0
            and exactly_once
            and rec["resumes"] == 1
            and len(rec["generations"]) == 2
        ),
    }


def run_gateway_bench(
    n: int = 100, seed: int = DEFAULT_SEED, shards: int = 4
) -> dict:
    scrape = run_gateway_scrape(n, seed=seed)
    stitch = run_gateway_stitch(seed=seed, shards=max(2, shards))
    return {
        "bench": "fleet_gateway",
        "unit": "one gateway sweep / stitched rollout",
        "fleet_rollup": scrape,
        "stitch": stitch,
        "ok": bool(scrape["ok"] and stitch["ok"]),
    }


def summarize(rows: list[dict]) -> dict:
    by_key = {(r["mode"], r["nodes"]): r for r in rows}
    out: dict = {
        "bench": "scale_rollout",
        "unit": "apiserver requests / rollout",
        "selector": SELECTOR,
        "pools": sorted(rows, key=lambda r: (r["nodes"], r["mode"])),
    }
    drops = {}
    for n in sorted({r["nodes"] for r in rows}):
        legacy = by_key.get(("legacy", n))
        informer = by_key.get(("informer", n))
        if legacy and informer:
            llists = legacy["orchestrator_requests"].get("list", 0)
            ilists = max(1, informer["orchestrator_requests"].get("list", 0))
            drops[str(n)] = round(llists / ilists, 1)
    out["list_request_drop"] = drops
    out["ok"] = bool(
        rows
        and all(r["ok"] for r in rows)
        # The acceptance bar: >=10x fewer list requests at 1k nodes.
        and (drops.get("1000") is None or drops["1000"] >= 10.0)
    )
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sizes", default=None)
    parser.add_argument("--modes", default="legacy,informer")
    parser.add_argument("--seed", type=int, default=DEFAULT_SEED)
    parser.add_argument("--shards", type=int, default=8)
    parser.add_argument("--out", default=None)
    parser.add_argument(
        "--apiserver", action="store_true",
        help="drive the rollouts over real HTTP through "
        "hack/mock_apiserver.py + RestKube instead of in-process FakeKube "
        "calls (chunked listings, selector watches, lease CAS on the "
        "wire); defaults to the 1k-node fleet and SCALE_r02.json",
    )
    parser.add_argument(
        "--gateway", action="store_true",
        help="run the fleet observability gateway bench instead of the "
        "rollout benches: 100-node scrape+merge convergence inside one "
        "interval, stale marking of killed agents within 2 intervals, "
        "and a sharded kill+resume rollout stitched back from two "
        "flight files (obs/fleet.py); defaults to FLEET_r01.json",
    )
    parser.add_argument(
        "--federation", action="store_true",
        help="run the federated region-sharded bench instead: one "
        "rollout over --sizes total nodes split across --regions "
        "per-region mock apiservers, a single global failure budget "
        "CAS-settled through a control-plane parent record, one region "
        "killed mid-rollout and resumed, and all regional flight files "
        "stitched into one exactly-once timeline; defaults to 100000 "
        "nodes, 10 regions, SCALE_r03.json",
    )
    parser.add_argument(
        "--federation-blackout", action="store_true",
        help="run the parent-plane partition bench instead: the "
        "--federation topology with every region's parent client riding "
        "a chaos blackout mid-rollout — healthy regions reconnect and "
        "reconcile, one region SIGKILLed at the parent-offline crash "
        "point dark-resumes through the skew-proof lease observation "
        "window, and one region escrow-halts on a dead slice while dark "
        "then resumes to completion; defaults to 100000 nodes, 10 "
        "regions, SCALE_r04.json",
    )
    parser.add_argument(
        "--regions", type=int, default=10,
        help="region (= per-region apiserver) count for --federation "
        "and --federation-blackout",
    )
    parser.add_argument(
        "--partial", default=None,
        help="JSONL of completed (mode,size) rows; existing rows are "
        "skipped on re-run (resume after an interruption)",
    )
    parser.add_argument(
        "--full", action="store_true",
        help="also run legacy mode at 10k nodes (minutes of O(pool) "
        "listings by construction; skipped by default)",
    )
    args = parser.parse_args(argv)
    if args.gateway:
        out = args.out or "FLEET_r01.json"
        sizes = [int(s) for s in (args.sizes or "100").split(",") if s]
        summary = run_gateway_bench(
            n=sizes[0], seed=args.seed, shards=args.shards
        )
        summary["seed"] = args.seed
        with open(out, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(summary))
        return 0 if summary["ok"] else 1
    if args.federation or args.federation_blackout:
        blackout = args.federation_blackout
        bench_mode = "federation-blackout" if blackout else "federation"
        out = args.out or ("SCALE_r04.json" if blackout else "SCALE_r03.json")
        total = int((args.sizes or "100000").split(",")[0])
        summary = None
        if args.partial and os.path.exists(args.partial):
            with open(args.partial, encoding="utf-8") as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    if (
                        row.get("mode") == bench_mode
                        and row.get("nodes") == total
                        and row.get("ok")
                    ):
                        summary = row
            if summary is not None:
                print(
                    f">>> resuming: {bench_mode}@{total} already completed "
                    f"in {args.partial}", file=sys.stderr,
                )
        if summary is None:
            print(
                f">>> federated rollout{' (parent blackout)' if blackout else ''}: "
                f"{total} node(s) across "
                f"{args.regions} regional apiserver(s)", file=sys.stderr,
            )
            runner = run_federation_blackout if blackout else run_federation
            summary = runner(
                total_nodes=total, regions_count=args.regions,
                seed=args.seed, shards=args.shards,
            )
            if args.partial:
                os.makedirs(
                    os.path.dirname(args.partial) or ".", exist_ok=True
                )
                with open(args.partial, "a", encoding="utf-8") as f:
                    f.write(json.dumps(summary) + "\n")
        summary["bench"] = (
            "federated_blackout_rollout" if blackout
            else "federated_scale_rollout"
        )
        summary["unit"] = (
            "partition-tolerance invariants / federated rollout"
            if blackout
            else "per-apiserver requests / federated rollout"
        )
        summary["seed"] = args.seed
        with open(out, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps(summary))
        return 0 if summary["ok"] else 1
    if args.sizes is None:
        args.sizes = "1000" if args.apiserver else "100,1000,10000"
    if args.out is None:
        args.out = "SCALE_r02.json" if args.apiserver else "SCALE_r01.json"
    runner = run_pool_apiserver if args.apiserver else run_pool
    sizes = [int(s) for s in args.sizes.split(",") if s]
    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    rows: list[dict] = []
    done: set[tuple[str, int]] = set()
    if args.partial and os.path.exists(args.partial):
        dropped = 0
        with open(args.partial, encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    row = json.loads(line)
                    # Only SUCCESSFUL rows are resume-skippable: keeping
                    # an ok:false row would pin the combo as "done", so
                    # every later run would recompute the same failed
                    # summary without ever re-attempting the pool.
                    if not row.get("ok"):
                        dropped += 1
                        continue
                    rows.append(row)
                    done.add((row["mode"], row["nodes"]))
        if done or dropped:
            print(
                f">>> resuming: {len(done)} completed run(s) in "
                f"{args.partial}"
                + (f"; re-buying {dropped} failed row(s)" if dropped
                   else ""),
                file=sys.stderr,
            )
    for n in sizes:
        for mode in modes:
            if (mode, n) in done:
                continue
            if mode == "legacy" and n >= 10000 and not args.full:
                print(
                    f">>> skipping legacy@{n} (O(pool) by construction; "
                    "--full to run it anyway)", file=sys.stderr,
                )
                continue
            print(
                f">>> rollout: {mode} mode, {n} node(s)"
                + (" over HTTP (mock apiserver)" if args.apiserver else ""),
                file=sys.stderr,
            )
            row = runner(n, mode, seed=args.seed, shards=args.shards)
            print(
                f">>> {mode}@{n}: ok={row['ok']} {row['seconds']}s "
                f"requests={row['orchestrator_requests']}",
                file=sys.stderr,
            )
            rows.append(row)
            if args.partial:
                os.makedirs(
                    os.path.dirname(args.partial) or ".", exist_ok=True
                )
                with open(args.partial, "a", encoding="utf-8") as f:
                    f.write(json.dumps(row) + "\n")
    summary = summarize(rows)
    summary["seed"] = args.seed
    # Every SCALE artifact carries a fleet-rollup section: one gateway
    # sweep over a seeded 100-agent fleet (obs/fleet.py) — cheap, and it
    # keeps the federation path exercised wherever the rollout bench
    # runs. The rollup is informational here; the full acceptance gate
    # is the --gateway bench (FLEET_r01.json).
    rollup = run_gateway_scrape(100, seed=args.seed)
    summary["fleet_rollup"] = {
        k: rollup[k] for k in (
            "nodes", "sweep_seconds", "converged_one_interval",
            "headroom_nodes", "stale_after_two_sweeps", "ok",
        )
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
