#!/usr/bin/env bash
# Multi-host slice demo: TWO real agents (one per "host" of a 2-host slice)
# against hack/mock_apiserver.py over real HTTP. Shows the slice-wide commit
# barrier (ccmanager/slicecoord.py): flipping only host 0's desired label
# leaves it waiting at the barrier (failed soft after the timeout would be
# the production behavior); flipping both lets the barrier form, the leader
# commit, and both hosts converge to mode.state=slice.
set -euo pipefail

PORT="${PORT:-18081}"
MOCK_NODES=2
source "$(dirname "${BASH_SOURCE[0]}")/demo_lib.sh"

start_mock_apiserver

start_host() { # $1 = host index
  start_agent "demo-node-$1" \
    TPU_CC_FAKE_NUM_HOSTS=2 \
    TPU_CC_FAKE_HOST_INDEX="$1" \
    TPU_CC_FAKE_SLICE_ID=demo-slice \
    CC_SLICE_BARRIER_TIMEOUT_S=120 \
    -- --smoke-workload none --debug
}

echo ">>> starting two agents (hosts 0 and 1 of a 2-host slice)"
start_host 0
start_host 1
sleep 6

echo ">>> desired mode slice -> host 0 ONLY (must wait at the barrier)"
set_label demo-node-0 "cloud.google.com/tpu-cc.mode" '"slice"'
sleep 6
staged=$(get_label demo-node-0 "cloud.google.com/tpu-cc.slice.staged")
s0=$(get_label demo-node-0 "cloud.google.com/tpu-cc.mode.state")
echo "    host0 staged-marker=$staged state=$s0"
[ "$staged" = slice ] || { echo ">>> FAILED: host 0 did not publish its staged marker"; exit 1; }
[ "$s0" != slice ] || { echo ">>> FAILED: host 0 committed without its peer"; exit 1; }

echo ">>> desired mode slice -> host 1 (barrier forms; leader commits)"
set_label demo-node-1 "cloud.google.com/tpu-cc.mode" '"slice"'

await_label demo-node-0 "cloud.google.com/tpu-cc.mode.state" "slice" 120
await_label demo-node-1 "cloud.google.com/tpu-cc.mode.state" "slice" 120
echo ">>> final states:"
curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' | python3 -m json.tool
echo ">>> multi-host barrier demo OK"
