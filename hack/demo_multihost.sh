#!/usr/bin/env bash
# Multi-host slice demo: TWO real agents (one per "host" of a 2-host slice)
# against hack/mock_apiserver.py over real HTTP. Shows the slice-wide commit
# barrier (ccmanager/slicecoord.py): flipping only host 0's desired label
# leaves it waiting at the barrier (failed soft after the timeout would be
# the production behavior); flipping both lets the barrier form, the leader
# commit, and both hosts converge to mode.state=slice.
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PORT="${PORT:-18081}"
WORK="$(mktemp -d)"
PIDS=()
trap 'kill "${PIDS[@]}" 2>/dev/null || true; rm -rf "$WORK"' EXIT

cat > "$WORK/kubeconfig.yaml" <<EOF
apiVersion: v1
kind: Config
clusters:
- cluster: {server: "http://127.0.0.1:$PORT"}
  name: mock
contexts:
- context: {cluster: mock, user: mock}
  name: mock
current-context: mock
users:
- name: mock
  user: {}
EOF

echo ">>> starting mock apiserver on :$PORT (2 nodes)"
PYTHONPATH="$REPO_ROOT" python "$REPO_ROOT/hack/mock_apiserver.py" "$PORT" 2 &
PIDS+=($!)
sleep 1

start_agent() { # $1 = host index
  NODE_NAME="demo-node-$1" \
  KUBECONFIG="$WORK/kubeconfig.yaml" \
  JAX_PLATFORMS=cpu \
  CC_READINESS_FILE="$WORK/readiness-$1" \
  OPERATOR_NAMESPACE=tpu-operator \
  TPU_CC_FAKE_NUM_HOSTS=2 \
  TPU_CC_FAKE_HOST_INDEX="$1" \
  TPU_CC_FAKE_SLICE_ID=demo-slice \
  CC_SLICE_BARRIER_TIMEOUT_S=120 \
  PYTHONPATH="$REPO_ROOT" \
  python -m tpu_cc_manager --tpu-backend fake --smoke-workload none --debug &
  PIDS+=($!)
}

echo ">>> starting two agents (hosts 0 and 1 of a 2-host slice)"
start_agent 0
start_agent 1
sleep 6

state_of() { # $1 = node
  curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' |
    python -c "import json,sys; print(json.load(sys.stdin)['nodes']['demo-node-$1'].get('cloud.google.com/tpu-cc.mode.state',''))"
}

echo ">>> desired mode slice -> host 0 ONLY (must wait at the barrier)"
curl -fsS -X POST "localhost:$PORT/_ctl/set-label" \
  -d '{"node":"demo-node-0","key":"cloud.google.com/tpu-cc.mode","value":"slice"}' > /dev/null
sleep 6
staged=$(curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' |
  python -c "import json,sys; print(json.load(sys.stdin)['nodes']['demo-node-0'].get('cloud.google.com/tpu-cc.slice.staged',''))")
s0=$(state_of 0)
echo "    host0 staged-marker=$staged state=$s0"
[ "$staged" = slice ] || { echo ">>> FAILED: host 0 did not publish its staged marker"; exit 1; }
[ "$s0" != slice ] || { echo ">>> FAILED: host 0 committed without its peer"; exit 1; }

echo ">>> desired mode slice -> host 1 (barrier forms; leader commits)"
curl -fsS -X POST "localhost:$PORT/_ctl/set-label" \
  -d '{"node":"demo-node-1","key":"cloud.google.com/tpu-cc.mode","value":"slice"}' > /dev/null

for _ in $(seq 1 60); do
  s0=$(state_of 0); s1=$(state_of 1)
  [ "$s0" = slice ] && [ "$s1" = slice ] && break
  sleep 2
done
echo ">>> final states: host0=$s0 host1=$s1"
curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' | python -m json.tool
if [ "$s0" = slice ] && [ "$s1" = slice ]; then
  echo ">>> multi-host barrier demo OK"
else
  echo ">>> demo FAILED"; exit 1
fi
