"""Serving bench: one JSON line, ok-gated (SERVE_r01 / SERVE_r02).

Converts the "millions of users" north star into measurable artifacts:

**Default (SERVE_r01)**: a closed-loop TrafficDriver sustains batched
synthetic inference across a pool of REAL node agents while a REAL
rolling CC flip runs mid-traffic (tpu_cc_manager/serve/). The line
reports p50/p99 latency and error rate DURING the rollout vs steady
state, and the headline claim: ``requests_lost_per_node_bounced`` == 0.

**--prestage (BENCH_r09)**: the whole-fleet zero-bounce artifact.
Finds the knee with the SERVE_r02 sweep machinery, then runs a 10-node
rolling flip under open-loop traffic at ``--knee-frac`` (80 %) of it
with CONTINUOUS prestage on: the orchestrator's capacity ledger
CAS-reserves knee-slack headroom (``headroom_gate_from_source`` reading
the harness's live ``tpu_cc_serve_offered_rps``) and prestages wave
N+1 while wave N flips. Gates: every node's effective flip wall (its
window-close seconds) ≤ that node's drain+readmit bar, zero
prestage-attributable SLO pauses, zero lost requests — plus a control
leg (same pool, no prestage) whose walls must exceed the bar, and a
crash leg where a seeded SIGKILL lands mid-prestage of wave N+1
(FaultPlan ``seed_prestage_kill``) and the successor resumes BOTH
waves with the ledger balancing to zero and no node double-charged.

**--brownout (GRAY_r01)**: the fail-slow containment artifact. One
seeded node (FaultPlan ``seed_brownout``) degrades to a fraction of its
token rate MID-FLIP while its watchdog stays green — the gray failure.
Two traffic legs at ``--knee-frac`` of the knee: detector-on (the
peer-relative vetter de-weights the suspect within one vetting window
and the remediation ladder escalates runtime-restart -> quarantine
``reason=fail-slow``) and a detector-off control. Gates: detector-on
during-brownout p99 within ``--gray-ratio-bar`` (1.3x) of healthy
steady while the control exceeds 2x, zero lost requests, quarantine
within <=2 vetting windows of onset, probation lift restores the node
after recovery — plus a crash leg where a seeded SIGKILL lands at the
``failslow-vetted`` crash point mid-vetting and the successor resumes
the journaled verdict to the SAME single quarantine, ledger balanced.

**--sweep (SERVE_r02)**: the open-loop overload artifact. A resumable
rate sweep (seeded Poisson arrivals, per-request deadlines, admission
control) finds the KNEE — the last rate where goodput tracks offered
load and queue-delay p99 stays bounded — and proves shedding holds
goodput near the knee past it instead of collapsing. Then a full
rolling CC flip runs AT the knee under open-loop traffic, with the
orchestrator's wave-boundary SLO gate armed from the harness's live
evaluator: ``ok`` requires the knee found, goodput held past it, the
flip converged, and ZERO accepted requests lost (sheds are counted,
never lost).

Usage:
  python3 hack/serve_bench.py [--nodes 3] [--traffic-s 8] [--out FILE]
      [--calibrate-smoke]  # calibrate the executor model from a real
                           # llama smoke run (ms_per_token, hbm_bw_util)
      [--sweep 150,300,600,1200,2400] [--rate-s 2.5] [--deadline-ms 500]
      [--partial artifacts/serve_sweep_partial.jsonl]  # resumable rows

``ok`` gates the evidence ladder's skip-when-ok:true stage
(hack/evidence_r5.sh) for both artifact shapes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _load_partial(path: str | None, config: dict) -> dict[float, dict]:
    """Completed sweep rows from a previous interrupted run, keyed by
    rate. Only ok:true rows measured under the SAME configuration
    (deadline/nodes/seed/point duration — every field in ``config``) are
    reused: mixing rows from different deadlines or pool sizes would
    report a knee that corresponds to no single configuration. Failed or
    mismatched rows are re-bought on resume (same discipline as
    scale_bench --partial)."""
    rows: dict[float, dict] = {}
    if not path or not os.path.exists(path):
        return rows
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if (
                row.get("ok") is True
                and "rate_rps" in row
                and all(row.get(k) == v for k, v in config.items())
            ):
                rows[float(row["rate_rps"])] = row
    return rows


def _fleet_rollup(metrics) -> dict:
    """One in-process fleet-gateway sweep over the harness's registry
    (obs/fleet.py): SERVE artifacts carry the same federated view —
    merged families, capacity-ledger headroom, pooled p99 — an operator
    would read off the real gateway's /metrics during the flip."""
    from tpu_cc_manager.lint import expo as expo_lint
    from tpu_cc_manager.obs import fleet as fleet_mod

    gateway = fleet_mod.FleetGateway(
        targets={"serve-harness": fleet_mod.local_target(metrics)},
    )
    fleetz = gateway.scrape_once()
    merged = gateway.metrics_text()
    p99 = None
    for line in merged.splitlines():
        if line.startswith("tpu_cc_fleet_serve_p99_seconds "):
            p99 = float(line.split()[1])
    return {
        "merged_lines": len(merged.splitlines()),
        "merged_lint_ok": not expo_lint.lint(merged),
        "headroom_nodes": fleetz["fleet"]["headroom_nodes"],
        "max_slo_burn": fleetz["fleet"]["max_slo_burn"],
        "fleet_serve_p99_s": p99,
    }


def _flip_at_knee(args, executor_factory, knee, deadline_s, handoff) -> dict:
    """One full rolling flip AT the knee under open-loop traffic — the
    SERVE_r02 flip leg, parameterized by ``handoff`` so SERVE_r03 can
    measure the zero-bounce path against the same baseline."""
    from tpu_cc_manager.serve import ServeHarness
    from tpu_cc_manager.serve.driver import PoissonSchedule

    harness = ServeHarness(
        n_nodes=args.nodes,
        tmp_dir=tempfile.mkdtemp(prefix="tpu-cc-serve-flip-"),
        executor_factory=executor_factory,
        handoff=handoff,
        driver_kwargs={
            "schedule": PoissonSchedule(
                knee["rate_rps"], seed=args.seed + 1
            ),
            "deadline_s": deadline_s,
            "initial_batch": knee["batch"],
            "min_batch": knee["batch"],
            "max_batch": knee["batch"],
        },
        slo_windows_s=(2.0, 30.0),
        slo_error_budget=0.05,
    )
    harness.build()
    try:
        report = harness.run(
            traffic_s=args.traffic_s,
            rollout_mode=args.mode,
            max_unavailable=args.max_unavailable,
            slo_max_burn_rate=2.0,
            slo_window_s=2.0,
            slo_max_pause_s=30.0,
        )
        report["fleet_rollup"] = _fleet_rollup(harness.metrics)
        return report
    finally:
        harness.shutdown()


def _p99_ratio(flip: dict) -> float | None:
    """during-rollout p99 / steady-state p99 — the number a user FEELS
    during a flip (1.0 = the rollout is invisible)."""
    during = (flip.get("latency_during_rollout") or {}).get("p99_ms")
    steady = (flip.get("latency_steady_state") or {}).get("p99_ms")
    if not during or not steady:
        return None
    return round(during / steady, 3)


def run_handoff(args, executor_factory, calibration) -> dict:
    """SERVE_r03: the zero-bounce flip artifact. Re-finds the knee with
    the SAME sweep machinery as SERVE_r02 (resumable partial rows), then
    runs the flip-at-the-knee twice — control (checkpoint-and-requeue,
    today's path) and handoff (parked requests migrate to an accepting
    peer inside the ack window) — and gates on the handoff flip's
    during-rollout/steady p99 ratio."""
    sweep = run_sweep(args, executor_factory, calibration, flip=False)
    knee = sweep.get("knee")
    control = handoff_flip = None
    if knee is not None:
        control = _flip_at_knee(
            args, executor_factory, knee, args.deadline_ms / 1e3,
            handoff=False,
        )
        handoff_flip = _flip_at_knee(
            args, executor_factory, knee, args.deadline_ms / 1e3,
            handoff=True,
        )
    ratio = _p99_ratio(handoff_flip) if handoff_flip else None
    control_ratio = _p99_ratio(control) if control else None
    accepted = (
        (handoff_flip.get("handoffs") or {}).get("accepted", 0)
        if handoff_flip else 0
    )
    return {
        "metric": "zero_bounce_flip_p99_ratio",
        "nodes": args.nodes,
        "deadline_ms": args.deadline_ms,
        "seed": args.seed,
        "knee": knee,
        "ratio_bar": args.ratio_bar,
        # Control: the SERVE_r02-shaped flip (local checkpoint+requeue).
        "control_flip": control,
        "control_p99_ratio": control_ratio,
        # The zero-bounce flip: in-flight handoff to accepting peers.
        "handoff_flip": handoff_flip,
        "handoff_p99_ratio": ratio,
        "handoffs": (handoff_flip or {}).get("handoffs"),
        "calibration": calibration,
        "ok": bool(
            knee is not None
            and sweep["ok"]
            and handoff_flip is not None
            and handoff_flip["rollout_ok"]
            and handoff_flip["requests_lost"] == 0
            and handoff_flip["conserved"]
            and handoff_flip["nodes_bounced"] == args.nodes
            and accepted > 0
            and ratio is not None
            and ratio <= args.ratio_bar
        ),
    }


def _flight_node_walls(flight_path: str) -> dict[str, float]:
    """Per-node EFFECTIVE flip wall from the rollout flight timeline:
    each node is assigned the wave-0 window its desired-patch landed in,
    and charged that window's close seconds. With continuous prestage a
    held node's window closes as fast as the convergence poll — the
    reset/boot cost was paid off-wave — which is exactly the number the
    BENCH_r09 bar compares against drain+readmit."""
    window_s: dict[int, float] = {}
    node_window: dict[str, int] = {}
    if not os.path.exists(flight_path):
        return {}
    with open(flight_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("wave") != 0:
                continue
            if e.get("event") == "node-desired-patch":
                node_window[e["node"]] = e.get("window")
            elif e.get("event") == "window-close":
                window_s[e.get("window")] = float(e.get("seconds") or 0.0)
    return {
        node: window_s[w]
        for node, w in node_window.items() if w in window_s
    }


def _drain_readmit_bar(metrics_text: str) -> float | None:
    """One node's drain+readmit bar from its agent registry: the mean
    drain phase latency plus the mean readmit phase latency (parsed
    from the ``tpu_cc_phase_seconds`` histogram's _sum/_count series).
    None until the agent has run both phases."""
    import re

    bar = 0.0
    for phase in ("drain", "readmit"):
        total = count = 0.0
        for kind in ("sum", "count"):
            pat = (
                r"tpu_cc_phase_seconds_%s\{(?=[^}]*phase=\"%s\")[^}]*\}"
                r"\s+([0-9.eE+-]+)" % (kind, phase)
            )
            acc = sum(float(m) for m in re.findall(pat, metrics_text))
            if kind == "sum":
                total = acc
            else:
                count = acc
        if count <= 0:
            return None
        bar += total / count
    return bar


def _prestage_flip(
    args, executor_factory, knee, deadline_s, prestage: bool
) -> dict:
    """One BENCH_r09 traffic leg: args.nodes real agents with nonzero
    reset/boot latencies (so a full flip visibly costs more than
    drain+readmit), open-loop Poisson at ``--knee-frac`` of the knee,
    rolling flip mid-traffic — with continuous prestage on (the
    measured leg) or off (the control leg that proves the bar bites)."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod
    from tpu_cc_manager.serve import ServeHarness
    from tpu_cc_manager.serve.driver import PoissonSchedule

    rate = knee["rate_rps"] * args.knee_frac
    tmp = tempfile.mkdtemp(prefix="tpu-cc-serve-prestage-")
    harness = ServeHarness(
        n_nodes=args.nodes,
        tmp_dir=tmp,
        executor_factory=executor_factory,
        reset_latency_s=args.reset_s,
        boot_latency_s=args.boot_s,
        driver_kwargs={
            "schedule": PoissonSchedule(rate, seed=args.seed + 2),
            "deadline_s": deadline_s,
            "initial_batch": knee["batch"],
            "min_batch": knee["batch"],
            "max_batch": knee["batch"],
        },
        slo_windows_s=(2.0, 30.0),
        slo_error_budget=0.05,
    )
    harness.build()
    try:
        roller_kwargs = None
        if prestage:
            # The REAL remote-gate path, fed in-process: the gate
            # scrapes tpu_cc_serve_offered_rps off the harness registry
            # and converts the slack under the knee into whole nodes.
            gate = rolling_mod.headroom_gate_from_source(
                "inproc://serve-harness", knee["rate_rps"], args.nodes,
                fetch=lambda _url: harness.metrics.render_prometheus(),
            )
            roller_kwargs = {
                "continuous_prestage": True,
                "headroom_gate": gate,
                "prestage_timeout_s": 30.0,
            }
        report = harness.run(
            traffic_s=args.traffic_s,
            rollout_mode=args.mode,
            max_unavailable=args.max_unavailable,
            slo_max_burn_rate=2.0,
            slo_window_s=2.0,
            slo_max_pause_s=30.0,
            roller_kwargs=roller_kwargs,
        )
        walls = _flight_node_walls(os.path.join(tmp, "flight.jsonl"))
        bars = {
            mgr.node_name: _drain_readmit_bar(
                mgr.metrics.render_prometheus()
            )
            for mgr in harness.agents
        }
        report["offered_rps"] = round(rate, 1)
        report["node_walls_s"] = {n: round(w, 3) for n, w in walls.items()}
        report["node_bars_s"] = {
            n: (round(b, 3) if b is not None else None)
            for n, b in bars.items()
        }
        report["prestage_totals"] = harness.metrics.prestage_totals()
        report["fleet_rollup"] = _fleet_rollup(harness.metrics)
        return report
    finally:
        harness.shutdown()


def _prestage_crash_leg(args, executor_factory) -> dict:
    """The BENCH_r09 crash leg: a seeded orchestrator SIGKILL lands at
    a prestage crash point — mid-prestage of wave N+1 while wave N
    drains — under a REAL short-TTL lease, and however many successors
    it takes resume BOTH waves from the checkpointed record. No
    traffic (the ledger/resume claims are record semantics, measured
    here without paying another open-loop leg); reserve/arm points
    only, since prestage-invalidate never fires in clean weather."""
    import time as time_mod

    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
    from tpu_cc_manager.faults.plan import FaultPlan, OrchestratorKilled
    from tpu_cc_manager.serve import ServeHarness
    from tpu_cc_manager.serve.harness import NS, POOL_SELECTOR
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    harness = ServeHarness(
        n_nodes=args.crash_nodes,
        tmp_dir=tempfile.mkdtemp(prefix="tpu-cc-serve-crash-"),
        executor_factory=executor_factory,
        reset_latency_s=0.05,
        boot_latency_s=0.05,
    )
    harness.build()
    plan = FaultPlan(seed=args.seed, rate=0.0, kill_rate=0.0)
    target = plan.seed_prestage_kill(
        points=("prestage-reserved", "prestage-armed"),
    )
    metrics = MetricsRegistry()
    result = None
    ledger = None
    try:
        for attempt in range(8):
            lease = rollout_state.RolloutLease(
                harness.kube, holder=f"bench-orch-{attempt}", namespace=NS,
                duration_s=2.0, metrics=metrics,
            )
            record = lease.acquire()
            roller = RollingReconfigurator(
                harness.kube, POOL_SELECTOR,
                max_unavailable=2,
                node_timeout_s=30.0,
                poll_interval_s=0.02,
                lease=lease,
                resume_record=(
                    record
                    if record is not None
                    and record.status == rollout_state.RECORD_IN_PROGRESS
                    else None
                ),
                crash_hook=plan.decide_orchestrator_kill,
                metrics=metrics,
                continuous_prestage=True,
                prestage_timeout_s=10.0,
                headroom_gate=lambda: args.crash_nodes,
            )
            try:
                result = roller.rollout(args.mode)
                ledger = roller._ledger
                lease.release(clear_record=result.ok)
                break
            except OrchestratorKilled:
                # SIGKILL semantics: no cleanup, lease NOT released —
                # the successor waits out the real-clock TTL.
                time_mod.sleep(2.2)
    finally:
        harness.shutdown()
    kills = [f for f in plan.injected if f.kind == "orch-kill"]
    return {
        "nodes": args.crash_nodes,
        "kill_point_armed": target,
        "kills": len(kills),
        "kill_landed_at": kills[0].op if kills else None,
        "resumes": metrics.rollout_totals()["resumes"],
        "rollout_ok": bool(result is not None and result.ok),
        "ledger_charges": ledger.charges_total() if ledger else None,
        "ledger_releases": ledger.releases_total() if ledger else None,
        "ledger_balanced": bool(ledger is not None and ledger.balanced()),
        "ledger_open_entries": len(ledger.entries) if ledger else None,
        "double_charged": ledger.double_charged() if ledger else None,
        "ok": bool(
            result is not None and result.ok
            and kills
            and kills[0].op == target
            and metrics.rollout_totals()["resumes"] == len(kills)
            and ledger is not None
            and ledger.balanced()
            and not ledger.entries
            and ledger.double_charged() == []
        ),
    }


def run_prestage(args, executor_factory, calibration) -> dict:
    """BENCH_r09: whole-fleet zero-bounce under the capacity ledger.
    Knee sweep → prestage leg at 80 % of knee (every node's effective
    flip wall ≤ its drain+readmit bar, zero prestage SLO pauses, zero
    lost requests) → control leg (no prestage: walls MUST exceed the
    bar, proving the bar bites) → seeded mid-prestage crash leg."""
    sweep = run_sweep(args, executor_factory, calibration, flip=False)
    knee = sweep.get("knee")
    deadline_s = args.deadline_ms / 1e3
    flip = control = None
    walls_ok = False
    control_exceeds = None
    if knee is not None:
        flip = _prestage_flip(
            args, executor_factory, knee, deadline_s, prestage=True,
        )
        control = _prestage_flip(
            args, executor_factory, knee, deadline_s, prestage=False,
        )
        # Every node's effective wall ≤ its own drain+readmit bar
        # (+0.25 s of convergence-poll/scheduler noise).
        walls_ok = bool(flip["node_walls_s"]) and all(
            flip["node_bars_s"].get(n) is not None
            and w <= flip["node_bars_s"][n] + 0.25
            for n, w in flip["node_walls_s"].items()
        )
        # The control leg pays reset+boot inside the window: its walls
        # exceeding the SAME bar is what makes walls_ok non-trivial.
        control_exceeds = sum(
            1 for n, w in control["node_walls_s"].items()
            if control["node_bars_s"].get(n) is not None
            and w > control["node_bars_s"][n] + 0.25
        )
    crash = _prestage_crash_leg(args, executor_factory)
    pt = (flip or {}).get("prestage_totals") or {}
    return {
        "metric": "zero_bounce_fleet_prestage",
        "nodes": args.nodes,
        "knee_frac": args.knee_frac,
        "deadline_ms": args.deadline_ms,
        "seed": args.seed,
        "knee": knee,
        "prestage_flip": flip,
        "control_flip": control,
        "walls_ok": walls_ok,
        "control_walls_exceeding_bar": control_exceeds,
        "crash_leg": crash,
        "calibration": calibration,
        "ok": bool(
            knee is not None
            and sweep["ok"]
            and flip is not None
            and flip["rollout_ok"]
            and flip["requests_lost"] == 0
            and flip["conserved"]
            and flip["nodes_bounced"] == args.nodes
            # Every node rode the prestage path (held == pool size) and
            # SLO burn never paused a top-up at 80 % of knee.
            and pt.get("held", 0) == args.nodes
            and pt.get("paused", 0) == 0
            and flip["rollout_slo_pauses"] == 0
            and walls_ok
            and control is not None
            and (control_exceeds or 0) > 0
            and crash["ok"]
        ),
    }


def _brownout_flip(
    args, executor_factory, knee, detector: bool
) -> dict:
    """One GRAY_r01 traffic leg: open-loop Poisson at ``--knee-frac``
    of the knee, a rolling flip mid-traffic, and ONE seeded node
    browning out (token rate cut by the plan's factor) right as the
    flip begins — with the peer-relative fail-slow vetter armed
    (``detector=True``) or off (the control leg that proves the bar
    bites). The request deadline is stretched by the brownout factor
    on BOTH legs: a tight deadline would shed the gray node's requests
    at admission, turning fail-slow into fail-stop — the easy case
    this artifact exists to NOT measure."""
    import threading
    import time as time_mod

    from tpu_cc_manager.ccmanager import remediation as remediation_mod
    from tpu_cc_manager.faults.plan import FaultPlan
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.serve import ServeHarness
    from tpu_cc_manager.serve.driver import PoissonSchedule
    from tpu_cc_manager.utils import retry as retry_mod

    plan = FaultPlan(seed=args.seed, rate=0.0)
    victim = f"serve-node-{plan.seed_brownout(args.nodes)}"
    factor = plan.brownout_token_rate_factor
    window_s = args.vet_window_s
    rate = knee["rate_rps"] * args.knee_frac
    warmup_frac = 0.25
    harness = ServeHarness(
        n_nodes=args.nodes,
        tmp_dir=tempfile.mkdtemp(prefix="tpu-cc-serve-gray-"),
        executor_factory=executor_factory,
        failslow=detector,
        failslow_kwargs={
            "window_s": window_s,
            "threshold": 2.0,
            # min_windows=1 + re-concluding verdicts: verdict #1 lands
            # at the first window close after onset (runtime restart),
            # verdict #2 one window later (quarantine) — the <=2-window
            # containment bound by construction.
            "min_windows": 1,
            "min_peers": 3,
            "min_samples": 3,
            "clear_windows": 2,
        },
        failslow_probation_s=2 * window_s,
        driver_kwargs={
            "schedule": PoissonSchedule(rate, seed=args.seed + 3),
            "deadline_s": (args.deadline_ms / 1e3) * factor,
            "initial_batch": knee["batch"],
            # min_batch=1 is what the suspect trickle de-weights down
            # to (driver._dispatch_round) — pinning it at the knee
            # batch would turn de-weighting off.
            "min_batch": 1,
            "max_batch": knee["batch"],
        },
        slo_windows_s=(2.0, 30.0),
        slo_error_budget=0.05,
    )
    harness.build()
    events: dict[str, float] = {}

    def drive() -> None:
        # Onset rides the same warmup fraction run() sleeps before the
        # flip, so the brownout begins as the rollout does.
        retry_mod.wait(args.traffic_s * warmup_frac, None)
        harness.set_brownout(victim, factor)
        events["onset"] = time_mod.monotonic()
        deadline = events["onset"] + args.brownout_s
        while time_mod.monotonic() < deadline:
            labels = node_labels(harness.kube.get_node(victim))
            if (
                labels.get(remediation_mod.QUARANTINED_LABEL)
                and "quarantined" not in events
            ):
                events["quarantined"] = time_mod.monotonic()
            time_mod.sleep(0.02)
        harness.set_brownout(victim, 1.0)
        plan.clear_brownout()
        events["cleared"] = time_mod.monotonic()

    t = threading.Thread(target=drive, daemon=True, name="gray-drive")
    t.start()
    try:
        report = harness.run(
            traffic_s=args.traffic_s,
            rollout_mode=args.mode,
            warmup_frac=warmup_frac,
            max_unavailable=args.max_unavailable,
            roller_kwargs={
                # Straggler-proof waves: a browned-out node mid-flip is
                # cut at the peer-relative wall, not the absolute node
                # timeout.
                "straggler_factor": 4.0,
                "straggler_floor_s": 2.0,
            },
        )
        t.join(timeout=args.brownout_s + args.traffic_s)
        restored = None
        if detector:
            ladder = harness.ladders[victim]
            # Probation lift: the vet loop keeps running after the
            # traffic stops — recovered peer stats clear the verdict,
            # healthy probes accrue, the quarantine lifts.
            restored = retry_mod.poll_until(
                lambda: (
                    not ladder.quarantined
                    and not node_labels(
                        harness.kube.get_node(victim)
                    ).get(remediation_mod.QUARANTINED_LABEL)
                ),
                20.0, 0.1,
            )
        # Custom buckets off the SAME completion log: "healthy steady"
        # is everything before onset; "during brownout" starts two
        # vetting windows in (the containment bound this artifact
        # separately asserts) and runs to the seeded clear.
        healthy = harness.driver.report(
            rollout_window=(0.0, events["onset"])
        )["latency_during_rollout"]
        brown = harness.driver.report(
            rollout_window=(
                events["onset"] + 2 * window_s, events["cleared"],
            )
        )["latency_during_rollout"]
        detection_windows = (
            round((events["quarantined"] - events["onset"]) / window_s, 2)
            if "quarantined" in events else None
        )
        ratio = (
            round(brown["p99_ms"] / healthy["p99_ms"], 3)
            if brown.get("p99_ms") and healthy.get("p99_ms") else None
        )
        report["victim"] = victim
        report["brownout_factor"] = factor
        report["vet_window_s"] = window_s
        report["healthy_steady"] = healthy
        report["during_brownout"] = brown
        report["brownout_p99_ratio"] = ratio
        report["detection_windows"] = detection_windows
        report["quarantined"] = "quarantined" in events
        report["restored"] = restored
        if detector:
            report["verdicts"] = harness.failslow_vetter.concluded()[:8]
            totals = harness.metrics.failslow_totals()
            report["failslow_verdict_totals"] = {
                f"{node}/{verdict}": count
                for (node, verdict), count in totals["verdicts"].items()
            }
        return report
    finally:
        harness.shutdown()


def _gray_crash_leg(args, executor_factory) -> dict:
    """The GRAY_r01 crash leg: a scripted vetter concludes two
    confirmed fail-slow verdicts for one node, a seeded SIGKILL lands
    at the ``failslow-vetted`` crash point — AFTER the verdicts are
    journaled in the record, BEFORE containment acts — and the
    successor resumes the journal to the SAME single quarantine
    (restart once, quarantine once, no double-escalation), with the
    continuous-prestage capacity ledger balancing to zero around it.
    No traffic: the journal/resume claims are record semantics."""
    import time as time_mod

    from tpu_cc_manager.ccmanager import rollout_state
    from tpu_cc_manager.ccmanager.remediation import (
        STEP_QUARANTINE,
        STEP_RUNTIME_RESTART,
        RemediationLadder,
    )
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
    from tpu_cc_manager.faults.plan import FaultPlan, OrchestratorKilled
    from tpu_cc_manager.serve import ServeHarness
    from tpu_cc_manager.serve.harness import NS, POOL_SELECTOR
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    harness = ServeHarness(
        n_nodes=args.crash_nodes,
        tmp_dir=tempfile.mkdtemp(prefix="tpu-cc-serve-grayk-"),
        executor_factory=executor_factory,
    )
    harness.build()
    plan = FaultPlan(seed=args.seed, rate=0.0, kill_rate=0.0)
    victim = f"serve-node-{plan.seed_brownout(args.crash_nodes)}"
    target = plan.seed_prestage_kill(points=("failslow-vetted",))

    class ScriptedVetter:
        """Concludes two confirmed verdicts for the victim — the
        escalation pair — on a fixed script; non-draining like the
        real one, so every successor re-reads the same list."""

        def concluded(self):
            return [
                {"id": 1, "node": victim, "verdict": "confirmed",
                 "deviation": 4.0},
                {"id": 2, "node": victim, "verdict": "confirmed",
                 "deviation": 4.0},
            ]

        def suspects(self):
            return {victim}

    metrics = MetricsRegistry()
    acts: list[str] = []

    def failslow_act(node: str, entry: dict) -> None:
        # A fresh ladder per act = a fresh successor process: the
        # exactly-once proof must come from the RECORD journal plus the
        # annotation-persisted ladder state, not in-memory dedup.
        ladder = RemediationLadder(harness.kube, node, metrics=metrics)
        acts.append(ladder.note_failslow(entry.get("deviation")))

    result = None
    ledger = None
    try:
        for attempt in range(8):
            lease = rollout_state.RolloutLease(
                harness.kube, holder=f"gray-orch-{attempt}", namespace=NS,
                duration_s=2.0, metrics=metrics,
            )
            record = lease.acquire()
            roller = RollingReconfigurator(
                harness.kube, POOL_SELECTOR,
                max_unavailable=2,
                node_timeout_s=30.0,
                poll_interval_s=0.02,
                lease=lease,
                resume_record=(
                    record
                    if record is not None
                    and record.status == rollout_state.RECORD_IN_PROGRESS
                    else None
                ),
                crash_hook=plan.decide_orchestrator_kill,
                metrics=metrics,
                continuous_prestage=True,
                prestage_timeout_s=10.0,
                headroom_gate=lambda: args.crash_nodes,
                failslow_vetter=ScriptedVetter(),
                failslow_act=failslow_act,
            )
            try:
                result = roller.rollout(args.mode)
                ledger = roller._ledger
                lease.release(clear_record=result.ok)
                break
            except OrchestratorKilled:
                time_mod.sleep(2.2)
    finally:
        harness.shutdown()
    kills = [f for f in plan.injected if f.kind == "orch-kill"]
    final = RemediationLadder(harness.kube, victim, metrics=metrics)
    return {
        "nodes": args.crash_nodes,
        "victim": victim,
        "kill_point_armed": target,
        "kills": len(kills),
        "kill_landed_at": kills[0].op if kills else None,
        "acts": acts,
        "quarantined": final.quarantined,
        "quarantine_reason": final.last_reason,
        "ledger_balanced": bool(ledger is not None and ledger.balanced()),
        "ledger_open_entries": len(ledger.entries) if ledger else None,
        "ok": bool(
            result is not None and result.ok
            and kills
            and kills[0].op == target
            # Exactly-once containment across the SIGKILL: one restart,
            # one quarantine, nothing doubled.
            and acts == [STEP_RUNTIME_RESTART, STEP_QUARANTINE]
            and final.quarantined
            and final.last_reason == "fail-slow"
            and ledger is not None
            and ledger.balanced()
            and not ledger.entries
        ),
    }


def run_brownout(args, executor_factory, calibration) -> dict:
    """GRAY_r01: fail-slow detection & containment. Knee sweep →
    detector-on brownout flip (containment holds the tail) →
    detector-off control (the tail blows out, proving the bar bites) →
    seeded ``failslow-vetted`` SIGKILL crash leg."""
    sweep = run_sweep(args, executor_factory, calibration, flip=False)
    knee = sweep.get("knee")
    detect = control = None
    if knee is not None:
        detect = _brownout_flip(args, executor_factory, knee, detector=True)
        control = _brownout_flip(
            args, executor_factory, knee, detector=False,
        )
    crash = _gray_crash_leg(args, executor_factory)
    d_ratio = (detect or {}).get("brownout_p99_ratio")
    c_ratio = (control or {}).get("brownout_p99_ratio")
    dw = (detect or {}).get("detection_windows")
    return {
        "metric": "failslow_containment_brownout",
        "nodes": args.nodes,
        "knee_frac": args.knee_frac,
        "vet_window_s": args.vet_window_s,
        "brownout_s": args.brownout_s,
        "seed": args.seed,
        "knee": knee,
        "detector_flip": detect,
        "control_flip": control,
        "detector_p99_ratio": d_ratio,
        "control_p99_ratio": c_ratio,
        "gray_ratio_bar": args.gray_ratio_bar,
        "detection_windows": dw,
        "crash_leg": crash,
        "calibration": calibration,
        "ok": bool(
            knee is not None
            and sweep["ok"]
            and detect is not None
            and detect["rollout_ok"]
            and detect["requests_lost"] == 0
            and detect["conserved"]
            and detect["quarantined"]
            and detect["restored"]
            # Containment bound: quarantine within <=2 vetting windows
            # of onset (+ half a window of vet-loop phase alignment).
            and dw is not None
            and dw <= 2.5
            and d_ratio is not None
            and d_ratio <= args.gray_ratio_bar
            # The control leg must HURT, or the detector leg's clean
            # tail proves nothing.
            and control is not None
            and control["requests_lost"] == 0
            and c_ratio is not None
            and c_ratio >= 2.0
            and crash["ok"]
        ),
    }


def run_sweep(args, executor_factory, calibration, flip: bool = True) -> dict:
    from tpu_cc_manager.serve import sweep as sweep_mod

    rates = sorted(float(r) for r in args.sweep.split(",") if r.strip())
    deadline_s = args.deadline_ms / 1e3
    done = _load_partial(args.partial, {
        "deadline_ms": round(1e3 * deadline_s, 1),
        "nodes": args.nodes,
        "seed": args.seed,
        "traffic_s": args.rate_s,
        # A calibrated executor has a different capacity, hence a
        # different knee: rows from the other executor model must not
        # be mixed in on resume.
        "calibrated": calibration is not None,
    })
    rows: list[dict] = []
    for rate in rates:
        if rate in done:
            print(f">>> rate {rate} already captured; skipping",
                  file=sys.stderr)
            rows.append(done[rate])
            continue
        row = sweep_mod.run_rate_point(
            rate,
            n_nodes=args.nodes,
            traffic_s=args.rate_s,
            deadline_s=deadline_s,
            seed=args.seed,
            executor_factory=executor_factory,
        )
        row["calibrated"] = calibration is not None
        rows.append(row)
        if args.partial:
            os.makedirs(os.path.dirname(args.partial) or ".", exist_ok=True)
            with open(args.partial, "a", encoding="utf-8") as f:
                f.write(json.dumps(row) + "\n")
    knee = sweep_mod.find_knee(rows)
    holds = (
        sweep_mod.goodput_holds_past_knee(rows, knee)
        if knee is not None else False
    )
    swept_past = knee is not None and any(
        r["rate_rps"] > knee["rate_rps"] for r in rows
    )

    flip_report = None
    slo_pauses = None
    if knee is not None and flip:
        # The other half of the claim: a rolling CC flip AT the knee,
        # open-loop traffic still arriving on schedule, SLO gate armed
        # (lenient burn threshold: the gate must pace, not veto — the
        # artifact's bar is zero ACCEPTED losses, sheds counted).
        flip_report = _flip_at_knee(
            args, executor_factory, knee, deadline_s, handoff=False,
        )
        slo_pauses = flip_report.get("rollout_slo_pauses")

    sweep_ok = bool(
        knee is not None
        and swept_past
        and holds
        and all(r["ok"] for r in rows)
    )
    return {
        "metric": "open_loop_overload_sweep",
        "nodes": args.nodes,
        "rate_s": args.rate_s,
        "deadline_ms": args.deadline_ms,
        "seed": args.seed,
        "rates": rows,
        "knee": knee,
        "goodput_holds_past_knee": holds,
        "flip_at_knee": flip_report,
        "rollout_slo_pauses": slo_pauses,
        "calibration": calibration,
        "ok": bool(
            sweep_ok
            and (
                not flip
                or (
                    flip_report is not None
                    and flip_report["rollout_ok"]
                    and flip_report["requests_lost"] == 0
                    and flip_report["nodes_bounced"] == args.nodes
                    and flip_report["conserved"]
                )
            )
        ),
    }


def main(argv: list[str] | None = None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--traffic-s", type=float, default=8.0)
    parser.add_argument("--mode", default="on")
    parser.add_argument("--max-unavailable", type=int, default=1)
    parser.add_argument("--calibrate-smoke", action="store_true",
                        help="run one real llama smoke and calibrate the "
                        "executor's latency/bandwidth model from it")
    parser.add_argument("--sweep", default=None,
                        help="comma-separated offered rates (rps): run the "
                        "open-loop overload sweep + flip-at-the-knee "
                        "(SERVE_r02) instead of the closed-loop flip")
    parser.add_argument("--handoff", action="store_true",
                        help="zero-bounce flip artifact (SERVE_r03): find "
                        "the knee (same sweep machinery as SERVE_r02), "
                        "then flip at it twice — control vs in-flight "
                        "handoff to peers — and gate on the handoff "
                        "flip's during/steady p99 ratio")
    parser.add_argument("--prestage", action="store_true",
                        help="whole-fleet zero-bounce artifact (BENCH_r09): "
                        "find the knee, flip the pool under open-loop "
                        "traffic at --knee-frac of it with continuous "
                        "prestage under the capacity ledger, run a "
                        "no-prestage control leg, and a seeded "
                        "mid-prestage orchestrator-SIGKILL crash leg")
    parser.add_argument("--brownout", action="store_true",
                        help="fail-slow containment artifact (GRAY_r01): "
                        "find the knee, brown out ONE seeded node during "
                        "a rolling flip at load with the peer-relative "
                        "vetter on, run a detector-off control leg that "
                        "must blow the tail, and a seeded SIGKILL at the "
                        "failslow-vetted crash point")
    parser.add_argument("--vet-window-s", type=float, default=0.75,
                        help="--brownout fail-slow vetting window (the "
                        "<=2-window containment bar is in these units)")
    parser.add_argument("--brownout-s", type=float, default=4.0,
                        help="--brownout seconds the victim stays browned "
                        "out before the seeded recovery")
    parser.add_argument("--gray-ratio-bar", type=float, default=1.3,
                        help="--brownout ok-gate: detector-on "
                        "during-brownout p99 must stay within this "
                        "multiple of healthy-steady p99 (control must "
                        "exceed 2x)")
    parser.add_argument("--knee-frac", type=float, default=0.8,
                        help="--prestage offered load as a fraction of "
                        "the knee (the ISSUE bar: 80%%)")
    parser.add_argument("--reset-s", type=float, default=0.3,
                        help="--prestage simulated device reset latency: "
                        "the cost prestage moves off the flip window")
    parser.add_argument("--boot-s", type=float, default=0.2,
                        help="--prestage simulated runtime boot latency")
    parser.add_argument("--crash-nodes", type=int, default=6,
                        help="--prestage crash-leg pool size")
    parser.add_argument("--ratio-bar", type=float, default=1.3,
                        help="--handoff ok-gate: during-rollout p99 must "
                        "stay within this multiple of steady-state p99")
    parser.add_argument("--rate-s", type=float, default=2.5,
                        help="traffic seconds per sweep rate point")
    parser.add_argument("--deadline-ms", type=float, default=500.0,
                        help="per-request completion deadline (admission "
                        "control sheds when the budget is provably spent)")
    parser.add_argument("--seed", type=int, default=20260804)
    parser.add_argument("--partial", default=None,
                        help="resumable sweep rows (JSONL): ok:true rates "
                        "are skipped on re-run")
    parser.add_argument("--out", default=None,
                        help="also write the JSON line to this file")
    args = parser.parse_args(argv)

    import logging

    logging.basicConfig(level=logging.WARNING)  # stdout carries ONE line

    from tpu_cc_manager.serve import ServeHarness, SimulatedExecutor

    executor_factory = SimulatedExecutor
    calibration = None
    if args.calibrate_smoke:
        from tpu_cc_manager.smoke.runner import run_workload_subprocess

        smoke = run_workload_subprocess(
            "llama", timeout_s=600.0, cwd=repo_root,
        )
        calibration = {
            "ms_per_token": smoke.get("ms_per_token"),
            "hbm_bw_util": smoke.get("hbm_bw_util"),
            "hbm_bw_util_lower_bound": smoke.get("hbm_bw_util_lower_bound"),
            "backend": smoke.get("backend"),
            "batch": smoke.get("batch"),
        }
        executor_factory = (
            lambda: SimulatedExecutor.from_smoke_result(smoke)
        )

    if args.brownout:
        if not args.sweep:
            args.sweep = "200,400,800,1600,3200,6400"
        result = run_brownout(args, executor_factory, calibration)
        line = json.dumps(result)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0 if result["ok"] else 1

    if args.prestage:
        if not args.sweep:
            args.sweep = "200,400,800,1600,3200,6400"
        result = run_prestage(args, executor_factory, calibration)
        line = json.dumps(result)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0 if result["ok"] else 1

    if args.handoff:
        if not args.sweep:
            args.sweep = "200,400,800,1600,3200,6400"
        result = run_handoff(args, executor_factory, calibration)
        line = json.dumps(result)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0 if result["ok"] else 1

    if args.sweep:
        result = run_sweep(args, executor_factory, calibration)
        line = json.dumps(result)
        print(line)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(line + "\n")
        return 0 if result["ok"] else 1

    harness = ServeHarness(
        n_nodes=args.nodes,
        tmp_dir=tempfile.mkdtemp(prefix="tpu-cc-serve-bench-"),
        executor_factory=executor_factory,
    )
    harness.build()
    try:
        report = harness.run(
            traffic_s=args.traffic_s,
            rollout_mode=args.mode,
            max_unavailable=args.max_unavailable,
        )
        report["fleet_rollup"] = _fleet_rollup(harness.metrics)
    finally:
        harness.shutdown()

    result = {
        "metric": "serving_disruption_per_rollout",
        "nodes": args.nodes,
        "traffic_s": args.traffic_s,
        "mode": args.mode,
        **report,
        "calibration": calibration,
        "ok": bool(
            report["rollout_ok"]
            and report["requests_lost"] == 0
            and report["nodes_bounced"] == args.nodes
            and (report["latency_during_rollout"]["count"] or 0) > 0
            and (report["latency_steady_state"]["count"] or 0) > 0
        ),
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
