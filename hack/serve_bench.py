"""Serving-under-the-flip bench: one JSON line, ok-gated (SERVE_r01).

Converts the "millions of users" north star into a measurable artifact:
a TrafficDriver sustains batched synthetic inference across a pool of
REAL node agents while a REAL rolling CC flip runs mid-traffic
(tpu_cc_manager/serve/). The line reports p50/p99 latency and error
rate DURING the rollout vs steady state, and the headline claim:
``requests_lost_per_node_bounced`` == 0 — every in-flight request
checkpoints through the drain handshake and completes.

Usage:
  python3 hack/serve_bench.py [--nodes 3] [--traffic-s 8] [--out FILE]
      [--calibrate-smoke]  # calibrate the executor model from a real
                           # llama smoke run (ms_per_token, hbm_bw_util)

``ok`` is true only when the rollout converged, zero requests were
lost, and both latency buckets have data — the evidence ladder's
skip-when-ok:true gate (hack/evidence_r5.sh) reads it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def main(argv: list[str] | None = None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--traffic-s", type=float, default=8.0)
    parser.add_argument("--mode", default="on")
    parser.add_argument("--max-unavailable", type=int, default=1)
    parser.add_argument("--calibrate-smoke", action="store_true",
                        help="run one real llama smoke and calibrate the "
                        "executor's latency/bandwidth model from it")
    parser.add_argument("--out", default=None,
                        help="also write the JSON line to this file")
    args = parser.parse_args(argv)

    import logging

    logging.basicConfig(level=logging.WARNING)  # stdout carries ONE line

    from tpu_cc_manager.serve import ServeHarness, SimulatedExecutor

    executor_factory = SimulatedExecutor
    calibration = None
    if args.calibrate_smoke:
        from tpu_cc_manager.smoke.runner import run_workload_subprocess

        smoke = run_workload_subprocess(
            "llama", timeout_s=600.0, cwd=repo_root,
        )
        calibration = {
            "ms_per_token": smoke.get("ms_per_token"),
            "hbm_bw_util": smoke.get("hbm_bw_util"),
            "hbm_bw_util_lower_bound": smoke.get("hbm_bw_util_lower_bound"),
            "backend": smoke.get("backend"),
            "batch": smoke.get("batch"),
        }
        executor_factory = (
            lambda: SimulatedExecutor.from_smoke_result(smoke)
        )

    harness = ServeHarness(
        n_nodes=args.nodes,
        tmp_dir=tempfile.mkdtemp(prefix="tpu-cc-serve-bench-"),
        executor_factory=executor_factory,
    )
    harness.build()
    try:
        report = harness.run(
            traffic_s=args.traffic_s,
            rollout_mode=args.mode,
            max_unavailable=args.max_unavailable,
        )
    finally:
        harness.shutdown()

    result = {
        "metric": "serving_disruption_per_rollout",
        "nodes": args.nodes,
        "traffic_s": args.traffic_s,
        "mode": args.mode,
        **report,
        "calibration": calibration,
        "ok": bool(
            report["rollout_ok"]
            and report["requests_lost"] == 0
            and report["nodes_bounced"] == args.nodes
            and (report["latency_during_rollout"]["count"] or 0) > 0
            and (report["latency_steady_state"]["count"] or 0) > 0
        ),
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
