#!/usr/bin/env bash
# Local end-to-end demo: the real agent against hack/mock_apiserver.py with
# the fake TPU backend. Shows the full drain -> stage/reset -> attest ->
# smoke -> re-admit cycle on a laptop (no cluster, no TPU).
set -euo pipefail

PORT="${PORT:-18080}"
METRICS_PORT="${METRICS_PORT:-19090}"
source "$(dirname "${BASH_SOURCE[0]}")/demo_lib.sh"
NODE=demo-node-0

start_mock_apiserver

echo ">>> starting tpu-cc-manager (fake backend, CPU smoke)"
start_agent "$NODE" -- --smoke-workload matmul --debug \
  --metrics-port "$METRICS_PORT"
sleep 5

echo ">>> desired mode -> on"
set_label "$NODE" "cloud.google.com/tpu-cc.mode" '"on"'
# The smoke's first JAX compile takes a few seconds; poll generously.
await_label "$NODE" "cloud.google.com/tpu-cc.mode.state" "on" 120

echo ">>> node state:"
curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' | python3 -m json.tool
echo ">>> phase metrics:"
curl -fsS "localhost:$METRICS_PORT/metrics" | grep -E '^tpu_cc_(phase|reconcile)'
echo ">>> demo OK"
