#!/usr/bin/env bash
# Local end-to-end demo: the real agent against hack/mock_apiserver.py with
# the fake TPU backend. Shows the full drain -> stage/reset -> attest ->
# smoke -> re-admit cycle on a laptop (no cluster, no TPU).
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PORT="${PORT:-18080}"
METRICS_PORT="${METRICS_PORT:-19090}"
WORK="$(mktemp -d)"
PIDS=()
trap 'kill "${PIDS[@]}" 2>/dev/null || true; rm -rf "$WORK"' EXIT

cat > "$WORK/kubeconfig.yaml" <<EOF
apiVersion: v1
kind: Config
clusters:
- cluster: {server: "http://127.0.0.1:$PORT"}
  name: mock
contexts:
- context: {cluster: mock, user: mock}
  name: mock
current-context: mock
users:
- name: mock
  user: {}
EOF

echo ">>> starting mock apiserver on :$PORT"
PYTHONPATH="$REPO_ROOT" python "$REPO_ROOT/hack/mock_apiserver.py" "$PORT" &
PIDS+=($!)
sleep 1

echo ">>> starting tpu-cc-manager (fake backend, CPU smoke)"
NODE_NAME=demo-node-0 \
KUBECONFIG="$WORK/kubeconfig.yaml" \
JAX_PLATFORMS=cpu \
CC_READINESS_FILE="$WORK/readiness" \
OPERATOR_NAMESPACE=tpu-operator \
PYTHONPATH="$REPO_ROOT" \
python -m tpu_cc_manager --tpu-backend fake --smoke-workload matmul \
  --debug --metrics-port "$METRICS_PORT" &
PIDS+=($!)
sleep 5

echo ">>> desired mode -> on"
curl -fsS -X POST "localhost:$PORT/_ctl/set-label" \
  -d '{"key":"cloud.google.com/tpu-cc.mode","value":"on"}' > /dev/null

for _ in $(seq 1 60); do
  state=$(curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' |
    python -c 'import json,sys; print(json.load(sys.stdin)["labels"].get("cloud.google.com/tpu-cc.mode.state",""))')
  [ "$state" = on ] && break
  sleep 2
done

echo ">>> node state:"
curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' | python -m json.tool
echo ">>> phase metrics:"
curl -fsS "localhost:$METRICS_PORT/metrics" | grep -E '^tpu_cc_(phase|reconcile)'
[ "$state" = on ] && echo ">>> demo OK" || { echo ">>> demo FAILED"; exit 1; }
