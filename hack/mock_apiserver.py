"""Localhost mock apiserver speaking the 4 verbs the agent uses.

For the local demos (`hack/demo_local.sh`, `hack/demo_multihost.sh`) and
manual end-to-end verification on machines without kind/kubectl: node
GET/PATCH (merge-patch on metadata.labels/annotations), node LIST with
label selectors, pod LIST with selectors, node WATCH as chunked JSON
lines. Serves N nodes (second CLI arg, default 1: ``demo-node-0..N-1``)
so multi-host slice-barrier flows can run against the real HTTP surface.

Two real-apiserver behaviors are ENFORCED, not just mimicked (VERDICT r4
missing #2 — no kind/kubectl in this image, so the admission/authz claims
are at least mock-enforced against the genuine wire surface):

- **Label/annotation validation**: every PATCHed label key and value is
  checked against the apiserver's actual rules (qualified-name key with
  optional DNS-1123 prefix; 63-char alphanumeric-bounded values;
  annotation total size cap). Violations return 422 with a k8s-shaped
  Status, exactly what a real apiserver answers — a regression in
  labels.py's ``label_safe`` fails the demos instead of passing silently.
- **RBAC**: every route is authorized against the verb set parsed from
  THE REAL ClusterRole in deployments/manifests/daemonset.yaml (fallback:
  the same set hardcoded). A verb outside the DaemonSet's grants gets a
  403 Forbidden Status, so an agent that grows an ung-ranted apiserver
  call breaks loudly in CI's demo jobs. SSAR answers from the same set.

Includes an "operator reaction" thread — the external behavior the drain
protocol relies on (SURVEY.md §5): deletes component pods ~0.5 s after
their google.com/tpu.deploy.* label becomes paused, restores them on
unpause. Control endpoints (not part of k8s): POST /_ctl/set-label
(optional "node"), POST /_ctl/stick-pod, POST /_ctl/state,
POST /_ctl/compact (410-expire watches resuming below a rv floor).
"""
import copy
import json
import os
import queue
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs

try:
    from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS as COMPONENTS
except ImportError:  # standalone use without the package on sys.path
    COMPONENTS = {
        "google.com/tpu.deploy.device-plugin": "tpu-device-plugin",
        "google.com/tpu.deploy.dra-driver": "tpu-dra-driver",
        "google.com/tpu.deploy.metrics-agent": "tpu-metrics-agent",
        "google.com/tpu.deploy.sandbox-validator": "tpu-sandbox-validator",
        "google.com/tpu.deploy.workload-validator": "tpu-workload-validator",
    }

NS = "tpu-operator"
DEFAULT_NODE = "demo-node-0"

# ---------------------------------------------------------------------------
# Apiserver validation rules (staging/src/k8s.io/apimachinery validation):
# label values: empty or 63-char alphanumeric-bounded; label/annotation
# keys: [prefix/]name, name 63-char qualified, prefix a DNS-1123 subdomain
# of <=253 chars; total annotation payload <=256KiB.
# ---------------------------------------------------------------------------

_VALUE_RE = re.compile(r"^(?:[A-Za-z0-9](?:[A-Za-z0-9_.-]*[A-Za-z0-9])?)?$")
_NAME_RE = re.compile(r"^[A-Za-z0-9](?:[A-Za-z0-9_.-]*[A-Za-z0-9])?$")
_DNS1123_RE = re.compile(
    r"^[a-z0-9](?:[a-z0-9-]*[a-z0-9])?(?:\.[a-z0-9](?:[a-z0-9-]*[a-z0-9])?)*$"
)
_ANNOTATIONS_MAX_BYTES = 256 * 1024


def _invalid_key(key: str) -> str | None:
    prefix, slash, name = key.rpartition("/")
    # fullmatch, not match: Python's $ would admit a trailing newline the
    # real apiserver rejects.
    if slash and (len(prefix) > 253 or not _DNS1123_RE.fullmatch(prefix)):
        return f"key prefix {prefix!r} is not a valid DNS-1123 subdomain"
    if len(name) > 63 or not _NAME_RE.fullmatch(name):
        return (
            f"key name {name!r} must be 63 chars or less, alphanumeric-"
            "bounded [A-Za-z0-9_.-]"
        )
    return None


def validate_label_patch(patch: dict) -> str | None:
    """First validation failure in a metadata.labels merge-patch, or None."""
    for key, value in patch.items():
        bad = _invalid_key(key)
        if bad:
            return f"metadata.labels: {bad}"
        if value is None:
            continue  # merge-patch delete
        if not isinstance(value, str):
            return f"metadata.labels[{key!r}]: value must be a string"
        if len(value) > 63 or not _VALUE_RE.fullmatch(value):
            return (
                f"metadata.labels[{key!r}]: invalid value {value!r}: must "
                "be 63 characters or less, begin and end with an "
                "alphanumeric, with [A-Za-z0-9_.-] between"
            )
    return None


def validate_annotation_patch(patch: dict, existing: dict) -> str | None:
    total = 0
    merged = dict(existing)
    for key, value in patch.items():
        bad = _invalid_key(key)
        if bad:
            return f"metadata.annotations: {bad}"
        if value is None:
            merged.pop(key, None)
        elif not isinstance(value, str):
            return f"metadata.annotations[{key!r}]: value must be a string"
        else:
            merged[key] = value
    for k, v in merged.items():
        total += len(k.encode()) + len(v.encode())
    if total > _ANNOTATIONS_MAX_BYTES:
        return (
            f"metadata.annotations: total size {total} exceeds "
            f"{_ANNOTATIONS_MAX_BYTES} bytes"
        )
    return None


def _load_cluster_role_grants() -> set[tuple[str, str]]:
    """(verb, resource) pairs from the REAL ClusterRole manifest, so the
    mock's authz IS the DaemonSet's RBAC — editing one without the other
    fails the demos."""
    manifest = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "deployments", "manifests", "daemonset.yaml",
    )
    try:
        import yaml

        with open(manifest, encoding="utf-8") as f:
            docs = list(yaml.safe_load_all(f))
        grants = set()
        for doc in docs:
            if (doc or {}).get("kind") != "ClusterRole":
                continue
            for rule_ in doc.get("rules", []):
                for resource in rule_.get("resources", []):
                    for verb in rule_.get("verbs", []):
                        grants.add((verb, resource))
        if grants:
            return grants
    except Exception as e:  # noqa: BLE001 - fall back, but say so
        print(f"mock apiserver: could not parse ClusterRole ({e}); "
              "using built-in grant set", flush=True)
    return {
        ("get", "nodes"), ("list", "nodes"), ("watch", "nodes"),
        ("patch", "nodes"), ("list", "pods"), ("create", "events"),
        ("get", "leases"), ("create", "leases"), ("update", "leases"),
        ("delete", "leases"),
    }


GRANTS = _load_cluster_role_grants()

_LEASE_PATH_RE = re.compile(
    r"^/apis/coordination\.k8s\.io/v1/namespaces/([^/]+)/leases(?:/([^/]+))?$"
)

# Real apiservers send periodic BOOKMARK events (metadata-only, fresh
# resourceVersion) to watchers that asked via allowWatchBookmarks=true —
# that is what keeps quiet nodes from 410-expiring after etcd compaction,
# and the manager's watch loop has a dedicated branch for them
# (ccmanager/manager.py). Emit them faithfully so the demos exercise that
# branch over real HTTP. Interval is short (real servers use ~1/min;
# demos want coverage, not realism) and env-tunable for tests.
BOOKMARK_INTERVAL_S = float(os.environ.get("MOCK_BOOKMARK_INTERVAL_S", "5"))
_BOOKMARK = object()  # queue sentinel: broadcast a bookmark frame


class MockState:
    """One mock apiserver's complete state: nodes, pods, leases, watch
    plumbing, request counters. Instance-scoped so a federation bench
    (hack/scale_bench.py --federation) can run ten independent
    per-region apiservers in one process — each region gets its own
    ``MockState`` + ``make_handler(state)``. The original module-global
    surface (``nodes``, ``lock``, ``add_node`` ...) stays intact as
    aliases of the module-level DEFAULT_STATE below, so the demos and
    the validation tests keep working unchanged."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.rv = [1]
        # Per-verb request counters (get/list/watch/patch/create/update/
        # delete): served at POST /_ctl/requests so the demos and the
        # scale harness can read the apiserver-side QPS the orchestrator
        # generated.
        self.request_counts: dict = {}
        # Watch resumes below this resourceVersion answer 410 Gone, like
        # a real apiserver after etcd compaction (POST /_ctl/compact).
        self.compacted_below = [0]
        self.nodes: dict[str, dict] = {}
        self.pods: dict[str, dict] = {}  # pod name -> pod dict
        # coordination.k8s.io/v1 Leases ((namespace, name) -> Lease):
        # the rolling orchestrator's single-writer lock + checkpoint
        # record (ccmanager/rollout_state.py). Updates enforce
        # resourceVersion CAS.
        self.leases: dict[tuple[str, str], dict] = {}
        # In-flight chunked listings: a continue token serves from the
        # snapshot taken at the FIRST page (real apiservers pin continues
        # to the first page's etcd revision) so a label flip between
        # pages can't shift the name sort and drop a node from the
        # listing. token -> (items, rv).
        self.page_snapshots: dict[str, tuple[list, str]] = {}
        self.page_snapshot_seq = [0]
        # watchers: list of (chunk_writer, node_name_filter or None,
        # label_selector or None, in_view name set, wants_bookmarks).
        # in_view tracks which nodes a selector-scoped watcher currently
        # "sees", so a node whose labels stop matching is delivered as
        # DELETED — the rule a real apiserver applies and an informer
        # cache depends on.
        self.watchers: list = []
        self.sticky_pods: set = set()  # pods the operator refuses to delete
        self.events: list[dict] = []  # core/v1 Events POSTed by the agent
        # name is a node name (str) or the _BOOKMARK sentinel object.
        self._event_queue: "queue.Queue[tuple[object, bytes]]" = queue.Queue()
        self._threads_started = False

    def count_request(self, verb: str) -> None:
        with self.lock:
            self.request_counts[verb] = self.request_counts.get(verb, 0) + 1

    def add_node(self, name: str) -> None:
        self.nodes[name] = {
            "kind": "Node",
            "apiVersion": "v1",
            "metadata": {
                "name": name,
                "resourceVersion": "1",
                "labels": {k: "true" for k in COMPONENTS},
            },
        }
        for key, app in COMPONENTS.items():
            self.pods[f"{app}-{name}"] = {
                "metadata": {
                    "name": f"{app}-{name}", "namespace": NS,
                    "labels": {"app": app},
                },
                "spec": {"nodeName": name},
                "status": {"phase": "Running"},
            }

    def bump_rv(self, node: dict) -> None:
        self.rv[0] += 1
        node["metadata"]["resourceVersion"] = str(self.rv[0])

    def emit_watch_event(self, node: dict) -> None:
        """Snapshot under the caller's lock, enqueue for the single
        writer thread: writes happen OUTSIDE the lock (a stalled watch
        client must not wedge the other endpoints by blocking sendall
        while holding it), and one writer preserves both frame integrity
        and event ordering. The writer serializes per watcher, because
        selector-scoped watchers each need their own event type
        (MODIFIED vs ADDED vs synthesized DELETED, depending on what
        that watcher saw before)."""
        name = node["metadata"]["name"]
        snapshot = json.loads(json.dumps(node))  # frozen at emit time
        self._event_queue.put((name, snapshot))

    def _bookmark_ticker(self) -> None:
        while True:
            time.sleep(BOOKMARK_INTERVAL_S)
            self._event_queue.put((_BOOKMARK, b""))

    def _watch_writer(self) -> None:
        while True:
            name, node = self._event_queue.get()
            # (writer, frame) pairs resolved under the lock, written
            # outside.
            deliveries = []
            if name is _BOOKMARK:
                with self.lock:
                    frame = (json.dumps({
                        "type": "BOOKMARK",
                        "object": {
                            "metadata": {"resourceVersion": str(self.rv[0])}
                        },
                    }) + "\n").encode()
                    deliveries = [
                        (wf, frame)
                        for wf, _, _, _, bm in self.watchers if bm
                    ]
            else:
                with self.lock:
                    for wf, flt, lsel, in_view, _ in self.watchers:
                        if flt is not None and flt != name:
                            continue
                        matches = _match_label_selector(
                            node["metadata"].get("labels") or {}, lsel
                        )
                        if matches:
                            etype = "MODIFIED" if name in in_view else "ADDED"
                            in_view.add(name)
                        elif name in in_view:
                            # Left the watcher's selector: a real
                            # apiserver sends DELETED so caches drop the
                            # node.
                            in_view.discard(name)
                            etype = "DELETED"
                        else:
                            continue
                        deliveries.append((wf, (json.dumps(
                            {"type": etype, "object": node}
                        ) + "\n").encode()))
            dead = []
            for wf, frame in deliveries:
                try:
                    wf.write(frame)
                    wf.flush()
                except Exception:
                    dead.append(wf)
            if dead:
                with self.lock:
                    self.watchers[:] = [
                        w for w in self.watchers if w[0] not in dead
                    ]

    def operator_reactor(self) -> None:
        """Delete component pods shortly after their node's deploy label
        pauses; restore them when unpaused. Pods marked sticky
        (POST /_ctl/stick-pod) are never deleted — simulates a wedged
        drain for strict-eviction testing."""
        while True:
            time.sleep(0.5)
            with self.lock:
                for node_name, node in self.nodes.items():
                    labels = node["metadata"]["labels"]
                    for key, app in COMPONENTS.items():
                        name = f"{app}-{node_name}"
                        if is_paused(labels.get(key)):
                            if name not in self.sticky_pods:
                                self.pods.pop(name, None)
                        elif labels.get(key) == "true" and name not in self.pods:
                            self.pods[name] = {
                                "metadata": {"name": name, "namespace": NS,
                                             "labels": {"app": app}},
                                "spec": {"nodeName": node_name},
                                "status": {"phase": "Running"},
                            }

    def start_threads(self, reactor: bool = False) -> None:
        """Start this instance's watch writer + bookmark ticker (and,
        for the demos, the operator reactor). Idempotent."""
        if self._threads_started:
            return
        self._threads_started = True
        threading.Thread(target=self._watch_writer, daemon=True).start()
        threading.Thread(target=self._bookmark_ticker, daemon=True).start()
        if reactor:
            threading.Thread(target=self.operator_reactor, daemon=True).start()


#: The module-level default instance: every original module-global name
#: below is an alias INTO this instance (same objects, mutated in
#: place), so existing consumers — demo scripts, the validation tests,
#: scale_bench's _reset_mock — see the exact pre-refactor surface.
DEFAULT_STATE = MockState()

lock = DEFAULT_STATE.lock
rv = DEFAULT_STATE.rv
request_counts = DEFAULT_STATE.request_counts
compacted_below = DEFAULT_STATE.compacted_below
nodes = DEFAULT_STATE.nodes
pods = DEFAULT_STATE.pods
leases = DEFAULT_STATE.leases
page_snapshots = DEFAULT_STATE.page_snapshots
page_snapshot_seq = DEFAULT_STATE.page_snapshot_seq
watchers = DEFAULT_STATE.watchers
sticky_pods = DEFAULT_STATE.sticky_pods
events = DEFAULT_STATE.events
_event_queue = DEFAULT_STATE._event_queue


def count_request(verb: str) -> None:
    DEFAULT_STATE.count_request(verb)


def add_node(name: str) -> None:
    DEFAULT_STATE.add_node(name)


def bump_rv(node: dict) -> None:
    DEFAULT_STATE.bump_rv(node)


def emit_watch_event(node: dict) -> None:
    DEFAULT_STATE.emit_watch_event(node)


def _bookmark_ticker():
    DEFAULT_STATE._bookmark_ticker()


def _watch_writer():
    DEFAULT_STATE._watch_writer()


def is_paused(v):
    return v is not None and "paused-for" in v


def _match_label_selector(labels: dict, selector: str | None) -> bool:
    if not selector:
        return True
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        if "=" in term:
            k, _, v = term.partition("=")
            if labels.get(k.strip()) != v.strip():
                return False
        elif labels.get(term) is None:
            return False
    return True


def operator_reactor():
    DEFAULT_STATE.operator_reactor()


class Handler(BaseHTTPRequestHandler):
    #: The MockState this handler serves. The module-level Handler binds
    #: the default instance; make_handler() subclasses with another.
    state: MockState = DEFAULT_STATE

    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _forbid(self, verb, resource):
        """403 with a k8s-shaped Status, as a real authorizer answers."""
        return self._json({
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "code": 403, "reason": "Forbidden",
            "message": (
                f'{resource} is forbidden: User "system:serviceaccount:'
                f'{NS}:tpu-cc-manager" cannot {verb} resource '
                f'"{resource}" (mock RBAC: ClusterRole grants {sorted(GRANTS)})'
            ),
        }, 403)

    def _invalid(self, detail):
        """422 with a k8s-shaped Status, as apiserver validation answers."""
        return self._json({
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "code": 422, "reason": "Invalid", "message": detail,
        }, 422)

    def _conflict(self, detail):
        """409 with a k8s-shaped Status — what a real apiserver answers to
        an update whose metadata.resourceVersion is stale (optimistic
        concurrency) or a create of an existing object."""
        return self._json({
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "code": 409, "reason": "Conflict", "message": detail,
        }, 409)

    def _authorized(self, verb, resource) -> bool:
        return (verb, resource) in GRANTS

    def do_GET(self):
        st = self.state
        u = urlparse(self.path)
        q = parse_qs(u.query)
        m = re.match(r"^/api/v1/nodes/([^/]+)$", u.path)
        if m and not self._authorized("get", "nodes"):
            return self._forbid("get", "nodes")
        if m:
            st.count_request("get")
            with st.lock:
                node = st.nodes.get(m.group(1))
            if node is None:
                return self._json(
                    {"kind": "Status", "code": 404, "message": "no such node"},
                    404,
                )
            with st.lock:
                return self._json(node)
        if u.path == "/api/v1/nodes" and q.get("watch") == ["true"]:
            if not self._authorized("watch", "nodes"):
                return self._forbid("watch", "nodes")
            st.count_request("watch")
            # Real apiservers 410-Gone a watch resuming from a
            # resourceVersion older than the compaction floor; the
            # manager's resync path (re-GET + conditional re-apply,
            # ccmanager/manager.py) exists for exactly this answer.
            # resourceVersion="0" is exempt: real apiservers define it as
            # "any version / serve from cache" and never 410 it
            # (ADVICE.md round 5).
            rv_param = q.get("resourceVersion", [None])[0]
            if rv_param is not None and rv_param != "0":
                try:
                    too_old = int(rv_param) < st.compacted_below[0]
                except ValueError:
                    too_old = False
                if too_old:
                    return self._json(
                        {"kind": "Status", "code": 410, "reason": "Expired",
                         "message":
                         f"too old resource version: {rv_param}"},
                        410,
                    )
            # Field selector metadata.name=<n> scopes the stream to one node
            # (the agent's watch); absent means all nodes. A labelSelector
            # scopes it to a pool (the informer cache's watch).
            flt = None
            fsel = q.get("fieldSelector", [None])[0]
            fm = re.match(r"^metadata\.name=(.+)$", fsel or "")
            if fm:
                flt = fm.group(1)
            lsel = q.get("labelSelector", [None])[0]
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            class ChunkWriter:
                def __init__(self, raw):
                    self.raw = raw

                def write(self, data):
                    self.raw.write(b"%x\r\n" % len(data) + data + b"\r\n")
                    return len(data)

                def flush(self):
                    self.raw.flush()

            # Bound writes to this watcher: a stalled client (full TCP
            # buffer) must raise, get dropped by the writer thread, and
            # never block delivery to the healthy watchers.
            self.connection.settimeout(10.0)
            cw = ChunkWriter(self.wfile)
            with st.lock:
                in_view = set()
                for name, node in st.nodes.items():
                    if (flt is None or flt == name) and _match_label_selector(
                        node["metadata"].get("labels") or {}, lsel
                    ):
                        in_view.add(name)
                        ev = json.dumps({"type": "ADDED", "object": node}) + "\n"
                        cw.write(ev.encode())
                cw.flush()
                wants_bookmarks = q.get("allowWatchBookmarks") == ["true"]
                st.watchers.append((cw, flt, lsel, in_view, wants_bookmarks))
            # Hold the connection open; events pushed by emit_watch_event.
            timeout = float(q.get("timeoutSeconds", ["300"])[0])
            time.sleep(timeout)
            try:
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                pass
            with st.lock:
                st.watchers[:] = [w for w in st.watchers if w[0] is not cw]
            return
        if u.path == "/api/v1/nodes":
            if not self._authorized("list", "nodes"):
                return self._forbid("list", "nodes")
            st.count_request("list")
            sel = q.get("labelSelector", [None])[0]
            # limit/continue chunking, as the real apiserver pages big
            # listings: the first page snapshots the name-sorted matching
            # set and the token walks THAT snapshot, so a label change
            # between pages can't shift the sort and drop a node. An
            # unknown or unparseable token answers 410 Expired, which
            # clients treat as "restart the listing".
            limit = q.get("limit", [None])[0]
            token = q.get("continue", [None])[0]
            with st.lock:
                if token is not None:
                    snap = st.page_snapshots.pop(token, None)
                    if snap is None:
                        return self._json(
                            {"kind": "Status", "code": 410,
                             "reason": "Expired",
                             "message": f"continue token {token!r} expired"},
                            410,
                        )
                    items, list_rv = snap
                    offset = int(token.split(":")[-1])
                else:
                    items = [
                        copy.deepcopy(n) for _, n in sorted(st.nodes.items())
                        if _match_label_selector(n["metadata"]["labels"], sel)
                    ]
                    list_rv = str(st.rv[0])
                    offset = 0
                meta = {"resourceVersion": list_rv}
                end = offset + max(1, int(limit)) if limit else len(items)
                if end < len(items):
                    st.page_snapshot_seq[0] += 1
                    new_token = f"{st.page_snapshot_seq[0]}:{end}"
                    st.page_snapshots[new_token] = (items, list_rv)
                    meta["continue"] = new_token
                    # Abandoned paginations must not pin snapshots forever.
                    while len(st.page_snapshots) > 8:
                        del st.page_snapshots[next(iter(st.page_snapshots))]
                return self._json({"kind": "NodeList",
                                   "items": items[offset:end],
                                   "metadata": meta})
        lm = _LEASE_PATH_RE.match(u.path)
        if lm and lm.group(2):
            if not self._authorized("get", "leases"):
                return self._forbid("get", "leases")
            st.count_request("get")
            with st.lock:
                lease = st.leases.get((lm.group(1), lm.group(2)))
                if lease is None:
                    return self._json(
                        {"kind": "Status", "code": 404,
                         "message": "no such lease"}, 404,
                    )
                return self._json(lease)
        if u.path == f"/api/v1/namespaces/{NS}/pods":
            if not self._authorized("list", "pods"):
                return self._forbid("list", "pods")
            st.count_request("list")
            sel = q.get("labelSelector", [None])[0]
            fsel = q.get("fieldSelector", [None])[0]
            with st.lock:
                items = list(st.pods.values())
            if sel:
                m = re.match(r"^([^=]+)=(.+)$", sel)
                k, v = m.group(1), m.group(2)
                items = [p for p in items if p["metadata"]["labels"].get(k) == v]
            if fsel:
                m = re.match(r"^spec\.nodeName=(.+)$", fsel)
                if m:
                    items = [p for p in items if p["spec"]["nodeName"] == m.group(1)]
            return self._json({"kind": "PodList", "items": items})
        self._json({"kind": "Status", "code": 404, "message": "not found"}, 404)

    def do_PATCH(self):
        st = self.state
        u = urlparse(self.path)
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        m = re.match(r"^/api/v1/nodes/([^/]+)$", u.path)
        if m:
            if not self._authorized("patch", "nodes"):
                return self._forbid("patch", "nodes")
            st.count_request("patch")
            with st.lock:
                node = st.nodes.get(m.group(1))
                if node is None:
                    return self._json({"kind": "Status", "code": 404}, 404)
                meta = body.get("metadata") or {}
                # Optimistic concurrency, as the real apiserver enforces
                # it: a patch that names a resourceVersion is a
                # conditional update — a stale one gets 409 Conflict, not
                # last-write-wins.
                sent_rv = meta.get("resourceVersion")
                if sent_rv is not None and str(sent_rv) != str(
                    node["metadata"]["resourceVersion"]
                ):
                    return self._conflict(
                        f"Operation cannot be fulfilled on nodes "
                        f"\"{m.group(1)}\": the object has been modified "
                        f"(sent resourceVersion {sent_rv}, current "
                        f"{node['metadata']['resourceVersion']})"
                    )
                patch_labels = meta.get("labels") or {}
                patch_annotations = meta.get("annotations") or {}
                bad = validate_label_patch(patch_labels)
                if bad is None and patch_annotations:
                    bad = validate_annotation_patch(
                        patch_annotations,
                        node["metadata"].get("annotations") or {},
                    )
                if bad is not None:
                    return self._invalid(bad)
                for k, v in patch_labels.items():
                    if v is None:
                        node["metadata"]["labels"].pop(k, None)
                    else:
                        node["metadata"]["labels"][k] = v
                if patch_annotations:
                    anns = node["metadata"].setdefault("annotations", {})
                    for k, v in patch_annotations.items():
                        if v is None:
                            anns.pop(k, None)
                        else:
                            anns[k] = v
                spec_patch = body.get("spec") or {}
                if "taints" in spec_patch:
                    # Merge-patch semantics on a LIST: wholesale replace
                    # (the client does the read-modify-write; quarantine
                    # taints ride this path, ccmanager/remediation.py).
                    taints = spec_patch["taints"]
                    if not isinstance(taints, list) or any(
                        not isinstance(t, dict) or not t.get("key")
                        for t in taints
                    ):
                        return self._invalid("spec.taints entries need a key")
                    node.setdefault("spec", {})["taints"] = taints
                st.bump_rv(node)
                st.emit_watch_event(node)
                return self._json(node)
        self._json({"kind": "Status", "code": 404}, 404)

    def do_PUT(self):
        """Full-object update — only Leases use it. Enforces the same
        optimistic concurrency a real apiserver does: the sent
        metadata.resourceVersion must match the stored one or the update
        409s, which is exactly what makes the rollout lease's fencing
        token trustworthy against a stale orchestrator."""
        u = urlparse(self.path)
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        st = self.state
        lm = _LEASE_PATH_RE.match(u.path)
        if lm and lm.group(2):
            if not self._authorized("update", "leases"):
                return self._forbid("update", "leases")
            st.count_request("update")
            key = (lm.group(1), lm.group(2))
            with st.lock:
                stored = st.leases.get(key)
                if stored is None:
                    return self._json(
                        {"kind": "Status", "code": 404,
                         "message": "no such lease"}, 404,
                    )
                sent_rv = (body.get("metadata") or {}).get("resourceVersion")
                if str(sent_rv) != stored["metadata"]["resourceVersion"]:
                    return self._conflict(
                        f'Operation cannot be fulfilled on leases '
                        f'"{lm.group(2)}": the object has been modified '
                        f"(sent resourceVersion {sent_rv}, current "
                        f"{stored['metadata']['resourceVersion']})"
                    )
                st.rv[0] += 1
                updated = {
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {
                        **(body.get("metadata") or {}),
                        "name": lm.group(2), "namespace": lm.group(1),
                        "resourceVersion": str(st.rv[0]),
                    },
                    "spec": body.get("spec") or {},
                }
                st.leases[key] = updated
                return self._json(updated)
        self._json({"kind": "Status", "code": 404}, 404)

    def do_DELETE(self):
        st = self.state
        u = urlparse(self.path)
        lm = _LEASE_PATH_RE.match(u.path)
        if lm and lm.group(2):
            if not self._authorized("delete", "leases"):
                return self._forbid("delete", "leases")
            st.count_request("delete")
            with st.lock:
                if st.leases.pop((lm.group(1), lm.group(2)), None) is None:
                    return self._json(
                        {"kind": "Status", "code": 404,
                         "message": "no such lease"}, 404,
                    )
                return self._json({
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Success", "code": 200,
                })
        self._json({"kind": "Status", "code": 404}, 404)

    def do_POST(self):
        st = self.state
        u = urlparse(self.path)
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        if u.path == "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews":
            # SSAR for `tpu-cc-ctl rbac-check`: the mock allows exactly the
            # verbs the DaemonSet ClusterRole grants
            # (deployments/manifests/daemonset.yaml), so the check's
            # pass/fail logic is exercised for real over HTTP.
            attrs = ((body.get("spec") or {}).get("resourceAttributes")) or {}
            allowed = (attrs.get("verb"), attrs.get("resource")) in GRANTS
            return self._json({
                "kind": "SelfSubjectAccessReview",
                "apiVersion": "authorization.k8s.io/v1",
                "status": {"allowed": allowed},
            }, 201)
        m = re.match(r"^/api/v1/namespaces/([^/]+)/events$", u.path)
        if m:
            if not self._authorized("create", "events"):
                return self._forbid("create", "events")
            st.count_request("create")
            with st.lock:
                st.events.append(body)
            return self._json(body, 201)
        lm = _LEASE_PATH_RE.match(u.path)
        if lm and not lm.group(2):
            if not self._authorized("create", "leases"):
                return self._forbid("create", "leases")
            st.count_request("create")
            name = ((body.get("metadata") or {}).get("name")) or ""
            if not name:
                return self._invalid("lease create: metadata.name required")
            with st.lock:
                key = (lm.group(1), name)
                if key in st.leases:
                    return self._conflict(
                        f'leases.coordination.k8s.io "{name}" already exists'
                    )
                st.rv[0] += 1
                lease = {
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {
                        "name": name, "namespace": lm.group(1),
                        "resourceVersion": str(st.rv[0]),
                    },
                    "spec": body.get("spec") or {},
                }
                st.leases[key] = lease
                return self._json(lease, 201)
        if u.path == "/_ctl/set-label":
            with st.lock:
                node = st.nodes.get(body.get("node", DEFAULT_NODE))
                if node is None:
                    return self._json({"ok": False, "error": "no such node"}, 404)
                if body.get("value") is None:
                    node["metadata"]["labels"].pop(body["key"], None)
                else:
                    node["metadata"]["labels"][body["key"]] = body["value"]
                st.bump_rv(node)
                st.emit_watch_event(node)
                return self._json({"ok": True, "labels": node["metadata"]["labels"]})
        if u.path == "/_ctl/compact":
            # Emulate etcd compaction: watches resuming below the floor
            # (default: the current rv) get 410 Gone.
            with st.lock:
                st.compacted_below[0] = int(body.get("below_rv", st.rv[0]))
                return self._json(
                    {"ok": True, "compacted_below": st.compacted_below[0]}
                )
        if u.path == "/_ctl/stick-pod":
            with st.lock:
                if body.get("stuck", True):
                    st.sticky_pods.add(body["name"])
                else:
                    st.sticky_pods.discard(body["name"])
                return self._json(
                    {"ok": True, "sticky": sorted(st.sticky_pods)}
                )
        if u.path == "/_ctl/requests":
            with st.lock:
                return self._json({"requests": dict(st.request_counts)})
        if u.path == "/_ctl/state":
            with st.lock:
                evs = [
                    f"{e.get('type', '?')}/{e.get('reason', '?')}"
                    for e in st.events
                ]
                if len(st.nodes) == 1:
                    # Single-node shape kept for demo_local.sh compat.
                    (node,) = st.nodes.values()
                    return self._json({"labels": node["metadata"]["labels"],
                                       "pods": sorted(st.pods),
                                       "events": evs})
                return self._json({
                    "nodes": {
                        name: n["metadata"]["labels"]
                        for name, n in st.nodes.items()
                    },
                    "pods": sorted(st.pods),
                    "events": evs,
                })
        self._json({"kind": "Status", "code": 404}, 404)


def make_handler(state: MockState) -> type:
    """A Handler subclass bound to ``state`` — hand it to an
    http.server so one process can serve many independent apiservers
    (one per federation region in hack/scale_bench.py)."""
    return type("BoundHandler", (Handler,), {"state": state})


if __name__ == "__main__":
    import sys
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 18080
    n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    for i in range(n_nodes):
        add_node(f"demo-node-{i}")
    threading.Thread(target=operator_reactor, daemon=True).start()
    threading.Thread(target=_watch_writer, daemon=True).start()
    threading.Thread(target=_bookmark_ticker, daemon=True).start()
    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    print(f"mock apiserver on :{port} ({n_nodes} node(s))", flush=True)
    srv.serve_forever()
