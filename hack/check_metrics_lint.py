"""Prometheus exposition-format lint — standalone shim.

The implementation moved into the package as
:mod:`tpu_cc_manager.lint.expo` so the cclint driver
(``python -m tpu_cc_manager.lint``) runs every static pass in one
command; this entrypoint keeps the historical invocation working:

  python3 hack/check_metrics_lint.py                # lint a seeded live registry
  python3 hack/check_metrics_lint.py --url URL      # lint a live /metrics scrape
  python3 hack/check_metrics_lint.py --file PATH    # lint a saved exposition
  python3 hack/check_metrics_lint.py --fleet        # lint the gateway's MERGED exposition

tests/test_metrics_lint.py imports this module's names; they re-export
from the package unchanged.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tpu_cc_manager.lint.expo import (  # noqa: E402,F401 - re-exports
    lint,
    main,
    _seeded_fleet_text,
    _seeded_registry_text,
)

if __name__ == "__main__":
    sys.exit(main())
