# Shared helpers for the one-command on-chip sweep scripts
# (tune_pallas.sh, batch_ladder.sh). Source, don't execute.
#
# Why these exist (r5 postmortem): on the axon-tunnel bench rig a dead
# remote-compile terminal makes every smoke dispatch block FOREVER with
# no error — r5's first pallas sweep hung on config 1 for the lifetime
# of the outage. The fixes are (a) a free, TPU-state-untouching port
# probe before each rung so a dead tunnel stops the sweep cleanly and
# resumably instead of hanging it, and (b) resume support so the rungs
# already captured before an outage are never re-bought — including
# FAILED rungs (an OOM ceiling is itself a result): every recorded line
# is tagged with its rung identity, so a smoke error line (which carries
# no batch/blocks key of its own) still resume-matches.

# tunnel_gate: succeed immediately off the tunnel rig; on it (detected
# by PALLAS_AXON_POOL_IPS, the env the image's sitecustomize keys the
# axon backend on), wait up to TUNNEL_WAIT_S (default 60) for the
# remote-compile listener to appear. The listener's ports are rig
# config; override TUNNEL_PORT_REGEX if anything unrelated listens in
# the default 8080-8099 window (observed tunnel ports: 8083/8093).
# Returns 1 when the budget expires — callers should stop the sweep and
# point at RESUME=1.
tunnel_gate() {
  [ -n "${PALLAS_AXON_POOL_IPS:-}" ] || return 0
  command -v ss >/dev/null 2>&1 || return 0
  local port_re=${TUNNEL_PORT_REGEX:-':80[89][0-9][[:space:]]'}
  local wait_s=${TUNNEL_WAIT_S:-60}
  local deadline=$(( $(date +%s) + wait_s ))
  while ! ss -tln 2>/dev/null | grep -qE "$port_re"; do
    if [ "$(date +%s)" -ge "$deadline" ]; then
      echo ">>> axon tunnel listener absent after ${wait_s}s; stopping" \
           "the sweep (re-run with RESUME=1 to keep captured rungs)" >&2
      return 1
    fi
    echo ">>> waiting for the axon tunnel listener..." >&2
    sleep 10
  done
  return 0
}

# sweep_init OUT ERRLOG: truncate both for a fresh sweep, or keep OUT's
# rows when RESUME=1 (mixing generations/sizes across resumes is the
# caller's responsibility — resume only the same ladder).
sweep_init() {
  local out=$1 errlog=$2
  if [ "${RESUME:-0}" = "1" ] && [ -s "$out" ]; then
    echo ">>> RESUME=1: keeping $(grep -c . "$out") existing row(s) in $out"
    # The error detail behind kept (possibly failed) rungs lives in
    # ERRLOG — append across resumes, don't destroy it.
    { echo "=== resume $(date -u +%FT%TZ) ==="; } >> "$errlog"
  else
    : > "$out"
    : > "$errlog"
  fi
}

# sweep_done OUT TAG: true when a prior (RESUME=1) run already recorded
# this rung — success OR failure — via run_rung's "rung" tag.
sweep_done() {
  [ "${RESUME:-0}" = "1" ] && grep -qF "\"rung\": \"$2\"" "$1"
}

# run_rung OUT ERRLOG TAG CMD...: run one rung, append its last stdout
# line to OUT with `"rung": TAG` injected (JSON lines only; a non-JSON
# crash tail is preserved verbatim so the error log trail stays
# honest). A failing rung records its line and returns 0 — one bad rung
# must not cost the rest of an expensive on-chip ladder.
run_rung() {
  local out=$1 errlog=$2 tag=$3
  shift 3
  { echo "=== $tag ==="; } >> "$errlog"
  "$@" 2>>"$errlog" | tail -1 | RUNG_TAG="$tag" python3 -c '
import json, os, sys
line = sys.stdin.read().strip()
if line:
    try:
        obj = json.loads(line)
        obj["rung"] = os.environ["RUNG_TAG"]
        line = json.dumps(obj)
    except ValueError:
        pass
    print(line)
' | tee -a "$out" || true
}
