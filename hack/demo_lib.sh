# Shared scaffolding for the hack/demo_*.sh scripts. Source AFTER setting
# PORT (and optionally MOCK_NODES); provides:
#   $REPO_ROOT $WORK $KUBECONFIG_FILE  — paths (WORK auto-cleaned on exit)
#   track_pid PID                      — register a child for exit cleanup
#   start_mock_apiserver               — hack/mock_apiserver.py on $PORT
#   set_label NODE KEY JSON_VALUE      — _ctl/set-label ('null' clears)
#   get_label NODE KEY                 — one label value (multi-node aware)
#   await_label NODE KEY WANT [TRIES]  — poll until equal (1 s period)
#
# One copy of the kubeconfig heredoc / trap / control-endpoint plumbing:
# a mock-apiserver API change lands here, not in three demos.

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
WORK="$(mktemp -d)"
KUBECONFIG_FILE="$WORK/kubeconfig.yaml"
DEMO_PIDS=()
trap 'kill "${DEMO_PIDS[@]}" 2>/dev/null || true; rm -rf "$WORK"' EXIT

track_pid() { DEMO_PIDS+=("$1"); }

cat > "$KUBECONFIG_FILE" <<EOF
apiVersion: v1
kind: Config
clusters:
- cluster: {server: "http://127.0.0.1:$PORT"}
  name: mock
contexts:
- context: {cluster: mock, user: mock}
  name: mock
current-context: mock
users:
- name: mock
  user: {}
EOF

start_mock_apiserver() {
  echo ">>> starting mock apiserver on :$PORT${MOCK_NODES:+ ($MOCK_NODES nodes)}"
  PYTHONPATH="$REPO_ROOT" \
    python3 "$REPO_ROOT/hack/mock_apiserver.py" "$PORT" ${MOCK_NODES:-} &
  track_pid $!
  sleep 1
}

start_agent() { # NODE [KEY=VAL ...] [-- EXTRA_AGENT_FLAGS...]
  # One copy of the agent launch env; per-demo extras ride as KEY=VAL
  # arguments and agent flags after --. The started PID is exported as
  # AGENT_PID (and tracked for cleanup).
  local node="$1"; shift
  local extra_env=()
  while [ $# -gt 0 ] && [ "$1" != "--" ]; do extra_env+=("$1"); shift; done
  [ "${1:-}" = "--" ] && shift
  env NODE_NAME="$node" \
    KUBECONFIG="$KUBECONFIG_FILE" \
    JAX_PLATFORMS=cpu \
    CC_READINESS_FILE="$WORK/readiness-$node" \
    OPERATOR_NAMESPACE=tpu-operator \
    PYTHONPATH="$REPO_ROOT" \
    ${extra_env[@]+"${extra_env[@]}"} \
    python3 -m tpu_cc_manager --tpu-backend fake "$@" &
  AGENT_PID=$!
  track_pid "$AGENT_PID"
}

set_label() { # NODE KEY JSON_VALUE
  curl -fsS -X POST "localhost:$PORT/_ctl/set-label" \
    -d "{\"node\":\"$1\",\"key\":\"$2\",\"value\":$3}" > /dev/null
}

get_label() { # NODE KEY  (handles both single- and multi-node state shapes)
  curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' |
    python3 -c "
import json, sys
state = json.load(sys.stdin)
labels = state['labels'] if 'labels' in state else state['nodes']['$1']
print(labels.get('$2', ''))"
}

await_label() { # NODE KEY WANT [TRIES]
  want="$3"
  got=""
  for _ in $(seq 1 "${4:-30}"); do
    got=$(get_label "$1" "$2")
    [ "$got" = "$want" ] && return 0
    sleep 1
  done
  echo ">>> FAILED: $2 on $1 never reached '$want' (got '$got')" >&2
  # Full state dump for red-CI debugging — one label value is not enough
  # to see where a reconcile wedged.
  curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' |
    python3 -m json.tool >&2 || true
  return 1
}
