# Shared scaffolding for the hack/demo_*.sh scripts. Source AFTER setting
# PORT (and optionally MOCK_NODES); provides:
#   $REPO_ROOT $WORK $KUBECONFIG_FILE  — paths (WORK auto-cleaned on exit)
#   track_pid PID                      — register a child for exit cleanup
#   start_mock_apiserver               — hack/mock_apiserver.py on $PORT
#   set_label NODE KEY JSON_VALUE      — _ctl/set-label ('null' clears)
#   get_label NODE KEY                 — one label value (multi-node aware)
#   await_label NODE KEY WANT [TRIES]  — poll until equal (1 s period)
#
# One copy of the kubeconfig heredoc / trap / control-endpoint plumbing:
# a mock-apiserver API change lands here, not in three demos.

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
WORK="$(mktemp -d)"
KUBECONFIG_FILE="$WORK/kubeconfig.yaml"
DEMO_PIDS=()
trap 'kill "${DEMO_PIDS[@]}" 2>/dev/null || true; rm -rf "$WORK"' EXIT

track_pid() { DEMO_PIDS+=("$1"); }

cat > "$KUBECONFIG_FILE" <<EOF
apiVersion: v1
kind: Config
clusters:
- cluster: {server: "http://127.0.0.1:$PORT"}
  name: mock
contexts:
- context: {cluster: mock, user: mock}
  name: mock
current-context: mock
users:
- name: mock
  user: {}
EOF

start_mock_apiserver() {
  echo ">>> starting mock apiserver on :$PORT${MOCK_NODES:+ ($MOCK_NODES nodes)}"
  PYTHONPATH="$REPO_ROOT" \
    python3 "$REPO_ROOT/hack/mock_apiserver.py" "$PORT" ${MOCK_NODES:-} &
  track_pid $!
  sleep 1
}

set_label() { # NODE KEY JSON_VALUE
  curl -fsS -X POST "localhost:$PORT/_ctl/set-label" \
    -d "{\"node\":\"$1\",\"key\":\"$2\",\"value\":$3}" > /dev/null
}

get_label() { # NODE KEY  (handles both single- and multi-node state shapes)
  curl -fsS -X POST "localhost:$PORT/_ctl/state" -d '{}' |
    python3 -c "
import json, sys
state = json.load(sys.stdin)
labels = state['labels'] if 'labels' in state else state['nodes']['$1']
print(labels.get('$2', ''))"
}

await_label() { # NODE KEY WANT [TRIES]
  want="$3"
  got=""
  for _ in $(seq 1 "${4:-30}"); do
    got=$(get_label "$1" "$2")
    [ "$got" = "$want" ] && return 0
    sleep 1
  done
  echo ">>> FAILED: $2 on $1 never reached '$want' (got '$got')" >&2
  return 1
}
