"""Cold-vs-warm smoke compilation-cache bench: one JSON line, ok-gated.

Proves `utils/compilation_cache.py` holds the smoke phase down across a
CC bounce (VERDICT weak #2) as a standalone, resumable evidence stage:
the cold run starts from an empty cache directory, the warm run reuses
it from a FRESH subprocess — exactly what a CC bounce does to the verify
phase (the runtime restart kills the process; only the disk cache
survives). The delta is the compile time the cache saves.

Usage:
  python3 hack/smoke_cache_bench.py [--workload matmul] [--out FILE]

Prints exactly one JSON line (also written to --out when given) with
``ok`` true only when both runs passed and the cold run actually
populated the cache — the evidence ladder's skip-when-ok:true gate
(hack/evidence_r5.sh) reads it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: list[str] | None = None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workload", default="matmul")
    parser.add_argument("--timeout-s", type=float, default=600.0)
    parser.add_argument("--out", default=None,
                        help="also write the JSON line to this file")
    args = parser.parse_args(argv)

    import bench  # repo-root bench.py: the shared measurement helpers

    tpu_usable = bench._tpu_preflight()
    result = bench.measure_smoke_cache(
        tpu_usable, workload=args.workload, timeout_s=args.timeout_s,
    )
    result["metric"] = "smoke_cache_cold_warm_s"
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
