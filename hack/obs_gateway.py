"""Fleet observability gateway server (obs/fleet.py, standalone).

Runs a :class:`~tpu_cc_manager.obs.fleet.FleetGateway` as a process:
discovers agent endpoints (informer over the node pool, or an explicit
``--targets`` list), sweeps them on an interval, and serves the merged
fleet truth:

- ``/metrics``  — the federated ``tpu_cc_*`` rollups plus the
  ``tpu_cc_fleet_*`` families (capacity ledger included);
- ``/fleetz``   — JSON per-node freshness/headroom/SLO-burn ledger;
  ``/fleetz?rollout=`` adds the stitched cross-shard rollout timeline;
- ``/healthz``  — liveness.

Usage:
    python hack/obs_gateway.py --selector pool=tpu             # informer discovery
    python hack/obs_gateway.py --targets a=http://h1:9100 b=http://h2:9100
    python hack/obs_gateway.py --smoke                         # CI self-test, no cluster

``--smoke`` needs no cluster and no sockets beyond an ephemeral
loopback port: it builds an in-process 3-agent fleet (seeded
registries), runs two sweeps, asserts the merged exposition passes the
exposition lint, kills an agent and asserts it goes stale — the fast
gateway check the cclint CI job runs.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_cc_manager.obs import fleet as fleet_mod  # noqa: E402

log = logging.getLogger("obs_gateway")

DEFAULT_AGENT_PORT = 9100


def parse_targets(pairs: list[str]) -> dict[str, str]:
    out: dict[str, str] = {}
    for pair in pairs:
        name, sep, url = pair.partition("=")
        if not sep or not name or not url:
            raise SystemExit(f"--targets entries are name=url, got {pair!r}")
        out[name] = url
    return out


def discover_loop(gateway, selector: str, agent_port: int, stop) -> None:
    """Keep the gateway's target set synced to the informer's node list
    (nodes joining the pool start being scraped next sweep; nodes
    leaving drop out of the ledger)."""
    from tpu_cc_manager.ccmanager.informer import NodeInformer
    from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube
    from tpu_cc_manager.utils import retry as retry_mod

    api = RestKube(ClusterConfig.load(None))
    informer = NodeInformer(api, selector)
    informer.start(sync_timeout_s=30.0)
    try:
        while not stop.is_set():
            gateway.set_targets(
                fleet_mod.targets_from_nodes(informer.list(), agent_port)
            )
            if retry_mod.wait(gateway.interval_s, stop):
                return
    finally:
        informer.stop()


def smoke() -> int:
    """CI self-test: merged exposition lints clean over a live loopback
    server, and a killed agent is marked stale within 2 sweeps."""
    from tpu_cc_manager.lint import expo
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    registries = {}
    for i in range(3):
        reg = MetricsRegistry()
        reg.observe_serve_request(f"smoke-node-{i}", 0.02 * (i + 1))
        reg.observe_serve_request(f"smoke-node-{i}", 0.3)
        reg.set_serve_queue_depth(f"smoke-node-{i}", i)
        reg.set_serve_hbm_bw_util(f"smoke-node-{i}", 0.5 + 0.1 * i)
        # Each scraped agent doubles as a regional rollout shard: the
        # merged exposition must carry the federation families too.
        reg.record_federation_sync("ok")
        if i == 1:
            reg.record_federation_fence("parent-generation")
        reg.set_federation_budget_spent(i)
        registries[f"smoke-node-{i}"] = reg

    alive = {name: True for name in registries}

    def target(name, reg):
        inner = fleet_mod.local_target(reg)

        def fetch(path: str) -> str:
            if not alive[name]:
                raise ConnectionError("agent killed")
            return inner(path)

        return fetch

    gateway = fleet_mod.FleetGateway(
        targets={n: target(n, r) for n, r in registries.items()},
        scrape_deadline_s=1.0,
        stale_after_sweeps=2,
    )
    gateway.scrape_once()
    server = gateway.serve(port=0)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            merged = resp.read().decode()
        problems = expo.lint(merged)
        assert not problems, f"merged exposition lint: {problems}"
        assert "tpu_cc_fleet_headroom_nodes 3" in merged, merged
        assert 'tpu_cc_hbm_bw_util{node="smoke-node-1"}' in merged
        # Federation leg: regional-shard families survive the merge —
        # labelled counters aggregate by label, the unlabeled spend
        # gauge sums across shards (0+1+2).
        assert 'tpu_cc_federation_syncs_total{outcome="ok"} 3' in merged
        assert 'tpu_cc_federation_fences_total' \
            '{reason="parent-generation"} 1' in merged, merged
        assert "tpu_cc_federation_budget_spent 3" in merged, merged

        alive["smoke-node-2"] = False
        gateway.scrape_once()
        gateway.scrape_once()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleetz", timeout=5
        ) as resp:
            fleetz = json.load(resp)
        assert fleetz["fleet"]["stale_nodes"] == ["smoke-node-2"], fleetz
        assert fleetz["fleet"]["headroom_nodes"] == 2, fleetz
        also_lint = expo.lint(gateway.metrics_text())
        assert not also_lint, also_lint
    finally:
        server.shutdown()
    print("obs_gateway smoke: OK (merged exposition lints clean; "
          "killed agent stale within 2 sweeps)")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--selector", default=None,
                        help="node selector for informer target discovery")
    parser.add_argument("--targets", nargs="+", default=None,
                        metavar="NAME=URL",
                        help="explicit agent endpoints (skips the informer)")
    parser.add_argument("--agent-port", type=int, default=DEFAULT_AGENT_PORT,
                        help="agent /metrics port for discovered nodes")
    parser.add_argument("--port", type=int, default=9200)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument("--scrape-deadline", type=float, default=2.0)
    parser.add_argument("--stale-after", type=int, default=2)
    parser.add_argument("--workers", type=int, default=8)
    parser.add_argument("--smoke", action="store_true",
                        help="in-process CI self-test; no cluster needed")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(levelname)s %(message)s"
    )

    if args.smoke:
        return smoke()
    if not args.selector and not args.targets:
        parser.error("one of --selector, --targets or --smoke is required")

    gateway = fleet_mod.FleetGateway(
        targets=parse_targets(args.targets) if args.targets else None,
        interval_s=args.interval,
        scrape_deadline_s=args.scrape_deadline,
        stale_after_sweeps=args.stale_after,
        workers=args.workers,
    )
    stop = threading.Event()
    if args.selector:
        threading.Thread(
            target=discover_loop,
            args=(gateway, args.selector, args.agent_port, stop),
            name="fleet-discover", daemon=True,
        ).start()
    server = gateway.serve(port=args.port, bind=args.bind)
    try:
        gateway.run(stop)  # blocks; Ctrl-C winds down
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
