#!/usr/bin/env bash
# One-command pallas matmul tiling sweep on the current accelerator.
#
# The Mosaic kernel (ops/matmul.py) defaults to 512^3 blocks (~76% MFU on
# v5e, vs ~98% for the XLA path); this sweep measures a config ladder so
# the default can be retuned per generation with evidence. Each config is
# one smoke subprocess; results are written as JSON lines to $OUT (fresh
# per sweep — mixing generations/sizes would mislabel the ranking).
#
# CAUTION on the shared bench rig: the TPU tunnel is single-client and a
# killed mid-dispatch client wedges it (see .claude/skills/verify). Run
# this only on a healthy chip you own, and give it time — no kill -9.
set -u

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"
. "$REPO_ROOT/hack/sweep_lib.sh"
OUT=${OUT:-pallas_sweep.jsonl}
ERRLOG=${ERRLOG:-pallas_sweep.stderr.log}
SIZE=${SIZE:-4096}
# Rung order is most-promising-first so an outage mid-sweep still
# captures the valuable ones: the r4 default (512^3, the comparison
# anchor) first, then FULL-K blocks — K=size makes k_steps=1, so the
# accumulator walk disappears and each output tile is one MXU pass
# (a 512x4096 bf16 block pair is ~8 MB, far under v5e's VMEM even
# double-buffered) — then partial-K refinements.
CONFIGS=${CONFIGS:-"512,512,512 512,512,4096 1024,1024,4096 1024,512,4096 512,1024,4096 1024,1024,2048 512,512,2048 1024,1024,1024 1024,512,512 512,1024,512 512,512,1024 1024,1024,512 256,256,512"}

sweep_init "$OUT" "$ERRLOG"
echo ">>> sweeping pallas tilings at size $SIZE -> $OUT (stderr -> $ERRLOG)"
for cfg in $CONFIGS; do
  # RESUME=1 skips rungs a pre-outage run already captured — success or
  # recorded failure alike (run_rung tags every line with its rung).
  if sweep_done "$OUT" "blocks=$cfg"; then
    echo ">>> blocks=$cfg already recorded; skipping"
    continue
  fi
  # A dead tunnel blocks a dispatch forever (no error); stop resumably
  # instead of hanging an expensive ladder on one rung.
  tunnel_gate || exit 3
  echo ">>> blocks=$cfg"
  run_rung "$OUT" "$ERRLOG" "blocks=$cfg" \
    python3 -m tpu_cc_manager.smoke --workload matmul --kernel pallas \
    --size "$SIZE" --pallas-blocks "$cfg"
done

echo ">>> best configs:"
N_CONFIGS=$(echo "$CONFIGS" | wc -w)
python3 - "$OUT" "$N_CONFIGS" <<'EOF'
import json, sys
rows = []
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        rows.append(json.loads(line))
    except json.JSONDecodeError:
        pass  # a crashed config left a non-JSON tail; details in ERRLOG
ok = [r for r in rows if r.get("ok") and r.get("timing_valid")]
for r in sorted(ok, key=lambda r: -(r.get("tflops") or 0))[:5]:
    print(f"  blocks={r.get('blocks')}  {r.get('tflops')} TF/s  mfu={r.get('mfu')}")
failed = [r for r in rows if not r.get("ok")]
if failed:
    print(f"  ({len(failed)} config(s) failed; see the error log)")
# Hard crashes (segfault, OOM, import error) leave NO row at all — a
# sweep that silently lost rungs must not read as complete coverage.
missing = int(sys.argv[2]) - len(rows)
if missing > 0:
    print(f"  WARNING: {missing} config(s) produced no result line at "
          f"all (crashed?); see the error log")
EOF
