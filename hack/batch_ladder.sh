#!/usr/bin/env bash
# One-command batch ladder for the resnet / llama smokes on the current
# accelerator.
#
# VERDICT r4 asks for on-chip batch-scaling evidence: ResNet-50 MFU
# scales with batch until HBM runs out (configs[3] had zero TPU evidence
# through r4), and the llama decode smoke's HBM-BW utilization has a
# batch knob nobody had measured. Each rung is one smoke subprocess;
# results append as JSON lines to $OUT so the artifact carries the whole
# ladder, not one cherry-picked point.
#
# Usage:
#   WORKLOAD=resnet BATCHES="32 64 128 256" hack/batch_ladder.sh
#   WORKLOAD=llama SIZE=llama3.2-1b BATCHES="1 4 8 16" hack/batch_ladder.sh
#   RESUME=1 ... hack/batch_ladder.sh     # keep rungs captured pre-outage
#
# CAUTION on the shared bench rig: the TPU tunnel is single-client and a
# killed mid-dispatch client wedges it (see .claude/skills/verify). The
# resnet smoke in particular is compile-heavy (>9 min observed through
# the tunnel's remote compile) — give it no deadline you're not willing
# to have wedge the chip.
set -u

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"
. "$REPO_ROOT/hack/sweep_lib.sh"

WORKLOAD=${WORKLOAD:-resnet}
SIZE=${SIZE:-}
BATCHES=${BATCHES:-"32 64 128 256"}
OUT=${OUT:-${WORKLOAD}_ladder.jsonl}
ERRLOG=${ERRLOG:-${WORKLOAD}_ladder.stderr.log}

sweep_init "$OUT" "$ERRLOG"
echo ">>> $WORKLOAD batch ladder${SIZE:+ (size=$SIZE)}: $BATCHES -> $OUT"
for b in $BATCHES; do
  if sweep_done "$OUT" "batch=$b"; then
    echo ">>> batch=$b already recorded; skipping"
    continue
  fi
  tunnel_gate || exit 3
  echo ">>> batch=$b"
  # One OOM/config-error rung records its JSON error line and the ladder
  # continues — the HBM ceiling is itself a result worth capturing.
  run_rung "$OUT" "$ERRLOG" "batch=$b" \
    python3 -m tpu_cc_manager.smoke --workload "$WORKLOAD" \
    ${SIZE:+--size "$SIZE"} --batch "$b"
done

echo ">>> ladder summary (throughput per rung):"
python3 - "$OUT" <<'EOF'
import json, sys
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        r = json.loads(line)
    except json.JSONDecodeError:
        continue
    if not r.get("ok"):
        rung = r.get("batch") or r.get("rung")
        print(f"  {rung}: FAILED ({r.get('error', '?')})")
        continue
    tp = r.get("images_per_sec") or r.get("tokens_per_sec")
    extra = ""
    for k in ("mfu", "hbm_bw_util", "prefill_tokens_per_sec", "prefill_mfu"):
        if r.get(k) is not None:
            extra += f"  {k}={r[k]}"
    print(f"  batch={r.get('batch')}: {tp}{extra}")
EOF
