#!/usr/bin/env python3
"""Turn a pallas sweep artifact into a DEFAULT_BLOCKS retune.

Reads a `hack/tune_pallas.sh` JSONL artifact, ranks the valid rungs, and
prints the winner plus the exact `ops/matmul.py` DEFAULT_BLOCKS line to
commit — the retune workflow VERDICT r4 asks for ("retune
ops/matmul.py's default blocks per generation from the evidence"), with
the evidence path printed alongside so the table edit stays traceable.

Usage:
    python3 hack/apply_sweep.py artifacts/pallas_sweep_r05.jsonl
    python3 hack/apply_sweep.py --write artifacts/pallas_sweep_r05.jsonl

--write edits tpu_cc_manager/ops/matmul.py in place — replacing the
generation's existing entry, or inserting a new one at the top of the
table — so a healthy-chip session can capture + retune in two commands.
A sweep that ran off-TPU (generation null) never touches the table.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

MATMUL_PY = Path(__file__).resolve().parent.parent / (
    "tpu_cc_manager/ops/matmul.py"
)


def load_rungs(path: str) -> list[dict]:
    rungs = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rungs.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # crashed rung left a non-JSON tail; errlog has it
    return rungs


def best_rung(rungs: list[dict]) -> dict | None:
    ok = [
        r for r in rungs
        if r.get("ok") and r.get("timing_valid") and r.get("tflops")
        and r.get("blocks")
    ]
    if not ok:
        return None
    return max(ok, key=lambda r: r["tflops"])


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("sweep", help="pallas sweep JSONL artifact")
    parser.add_argument(
        "--write", action="store_true",
        help="edit DEFAULT_BLOCKS in ops/matmul.py in place",
    )
    args = parser.parse_args()

    rungs = load_rungs(args.sweep)
    if not rungs:
        print(f"no rungs in {args.sweep} (empty or all crashed)")
        return 1
    best = best_rung(rungs)
    if best is None:
        print(f"no valid timed rung among {len(rungs)} in {args.sweep}")
        return 1

    gen = best.get("generation")
    blocks = tuple(best["blocks"])
    print(f"rungs: {len(rungs)} ({sum(1 for r in rungs if r.get('ok'))} ok)")
    print(
        f"best: blocks={list(blocks)} {best['tflops']} TF/s "
        f"mfu={best.get('mfu')} on {gen or best.get('backend')}"
    )
    if gen is None:
        print("sweep did not run on a TPU generation; not retuning the table")
        return 1
    entry = f'    "{gen}": {blocks!r},'
    print(f"DEFAULT_BLOCKS entry (evidence: {args.sweep}):")
    print(entry)

    if not args.write:
        return 0
    src = MATMUL_PY.read_text()
    pattern = re.compile(
        r'^(    "' + re.escape(gen) + r'": )\([0-9, ]+\),', re.M
    )
    if pattern.search(src):
        new_src = pattern.sub(rf"\g<1>{blocks!r},", src, count=1)
    else:
        # Insert the new generation right after the table opening brace.
        table_open = re.compile(
            r"(DEFAULT_BLOCKS: dict\[str, tuple\[int, int, int\]\] = \{\n)"
        )
        if not table_open.search(src):
            print("could not find DEFAULT_BLOCKS in ops/matmul.py; "
                  "apply the printed entry by hand")
            return 1
        new_src = table_open.sub(rf"\g<1>{entry}\n", src, count=1)
    if new_src == src:
        print("table already carries this entry; nothing to write")
        return 0
    MATMUL_PY.write_text(new_src)
    print(f"wrote {MATMUL_PY} — remember to cite {args.sweep} in the commit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
