#!/usr/bin/env bash
# Real-apiserver integration (BASELINE.json configs[0]: "single-node kind
# cluster, CC reconcile dry-run, no accelerator").
#
# The in-repo test tiers use an in-process fake and an HTTP mock
# (hack/mock_apiserver.py); this script is the tier above: a REAL apiserver
# (kind) with REAL RBAC. The agent runs authenticated as the DaemonSet's
# ServiceAccount — so what this proves is exactly what production gets:
#   1. the ClusterRole in deployments/manifests/daemonset.yaml is
#      sufficient for every verb the agent uses (also asserted explicitly
#      via `tpu-cc-ctl rbac-check` / SelfSubjectAccessReview),
#   2. real watch semantics (streamed MODIFIED events, server-side
#      timeouts, resourceVersion tracking) drive the reconcile,
#   3. strategic/merge-patch label writes behave on a real apiserver.
#
# Requires: kind, kubectl, docker (not present in the build image — run on
# a workstation or the optional CI job in .github/workflows/ci.yml).
set -euo pipefail

CLUSTER=${CLUSTER:-tpu-cc-it}
NS=tpu-operator
REPO="$(cd "$(dirname "$0")/.." && pwd)"
MODE_LABEL="cloud.google.com/tpu-cc.mode"
STATE_LABEL="cloud.google.com/tpu-cc.mode.state"

cleanup() {
  [ -n "${AGENT_PID:-}" ] && kill "$AGENT_PID" 2>/dev/null || true
  [ -n "${PROXY_PID:-}" ] && kill "$PROXY_PID" 2>/dev/null || true
  [ -n "${FAKE_AGENTS_PID:-}" ] && kill "$FAKE_AGENTS_PID" 2>/dev/null || true
  [ -n "${FED_PID:-}" ] && kill "$FED_PID" 2>/dev/null || true
  kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
}
trap cleanup EXIT

echo ">>> creating kind cluster $CLUSTER"
kind create cluster --name "$CLUSTER" --wait 120s
kubectl create namespace "$NS"

echo ">>> applying the DaemonSet manifest's ServiceAccount + RBAC"
# First three documents = ServiceAccount, ClusterRole, ClusterRoleBinding;
# the DaemonSet itself needs the container image, which this dry-run
# replaces with a host-side agent process using the SAME identity.
python3 - "$REPO/deployments/manifests/daemonset.yaml" <<'EOF' | kubectl apply -f -
import sys
docs = open(sys.argv[1]).read().split("\n---\n")
print("\n---\n".join(d for d in docs if "kind: DaemonSet" not in d))
EOF

NODE=$(kubectl get nodes -o jsonpath='{.items[0].metadata.name}')
echo ">>> building a kubeconfig authenticated as the ServiceAccount"
SERVER=$(kubectl config view --minify -o jsonpath='{.clusters[0].cluster.server}')
CA_FILE=$(mktemp)
kubectl config view --minify --raw \
  -o jsonpath='{.clusters[0].cluster.certificate-authority-data}' \
  | base64 -d > "$CA_FILE"
TOKEN=$(kubectl create token tpu-cc-manager -n "$NS")
SA_KUBECONFIG=$(mktemp)
cat > "$SA_KUBECONFIG" <<EOF
apiVersion: v1
kind: Config
clusters:
- name: kind
  cluster: {server: "$SERVER", certificate-authority: "$CA_FILE"}
users:
- name: sa
  user: {token: "$TOKEN"}
contexts:
- name: it
  context: {cluster: kind, user: sa}
current-context: it
EOF

echo ">>> rbac-check as the ServiceAccount (all five verbs)"
PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rbac-check --namespace "$NS"

echo ">>> seeding a drainable component label (exercises pods-list RBAC)"
kubectl label node "$NODE" google.com/tpu.deploy.device-plugin=true --overwrite

echo ">>> starting the agent as the ServiceAccount (fake device layer)"
AGENT_METRICS_PORT=9188
NODE_NAME="$NODE" KUBECONFIG="$SA_KUBECONFIG" JAX_PLATFORMS=cpu \
  PALLAS_AXON_POOL_IPS= CC_READINESS_FILE=$(mktemp -u) \
  CC_METRICS_PORT="$AGENT_METRICS_PORT" CC_METRICS_BIND=127.0.0.1 \
  OPERATOR_NAMESPACE="$NS" PYTHONPATH="$REPO" \
  python3 -m tpu_cc_manager --tpu-backend fake --smoke-workload none --debug &
AGENT_PID=$!

await_state() {
  want=$1
  for _ in $(seq 1 60); do
    got=$(kubectl get node "$NODE" \
      -o jsonpath="{.metadata.labels.cloud\.google\.com/tpu-cc\.mode\.state}" \
      || true)
    [ "$got" = "$want" ] && return 0
    sleep 2
  done
  echo "FAIL: $STATE_LABEL never reached $want (got '$got')" >&2
  kubectl get node "$NODE" --show-labels >&2
  return 1
}

echo ">>> driving mode changes through the real watch"
kubectl label node "$NODE" "$MODE_LABEL=on" --overwrite
await_state on
kubectl label node "$NODE" "$MODE_LABEL=off" --overwrite
await_state off
# Component label restored after the drain/re-admit cycle.
dp=$(kubectl get node "$NODE" \
  -o jsonpath="{.metadata.labels.google\.com/tpu\.deploy\.device-plugin}")
[ "$dp" = "true" ] || { echo "FAIL: component label not restored ($dp)"; exit 1; }

echo ">>> rolling reconfiguration via tpu-cc-ctl against the real apiserver"
kubectl label node "$NODE" pool=tpu-it --overwrite
PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rollout \
    --selector pool=tpu-it --mode on --node-timeout 120
await_state on

echo ">>> crash-safe rollout drill: SIGKILL mid-window, resume under real Lease RBAC"
# Stop the agent so the pool cannot converge and the rollout stays
# in-window, then SIGKILL the orchestrator: no cleanup runs, the lease
# and its checkpointed record survive in the apiserver.
kill "$AGENT_PID" 2>/dev/null || true
wait "$AGENT_PID" 2>/dev/null || true
PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rollout \
    --selector pool=tpu-it --mode off --node-timeout 120 \
    --lease-duration 5 &
ROLLOUT_PID=$!
sleep 4
kill -9 "$ROLLOUT_PID" 2>/dev/null || true
wait "$ROLLOUT_PID" 2>/dev/null || true
# The dead orchestrator left a durable record in the Lease (real
# coordination.k8s.io RBAC: the ClusterRole's get/create/update grants).
record=$(kubectl get lease tpu-cc-rollout -n "$NS" \
  -o jsonpath='{.metadata.annotations.cloud\.google\.com/tpu-cc\.rollout-record}')
echo "$record" | grep -q '"status":"in-progress"' || {
  echo "FAIL: no in-progress rollout record survived the SIGKILL"; exit 1; }
PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl status --selector pool=tpu-it \
  | grep -q "ROLLOUT" || {
  echo "FAIL: ctl status does not surface the interrupted rollout"; exit 1; }
echo ">>> restarting the agent; resuming the rollout after lease expiry"
NODE_NAME="$NODE" KUBECONFIG="$SA_KUBECONFIG" JAX_PLATFORMS=cpu \
  PALLAS_AXON_POOL_IPS= CC_READINESS_FILE=$(mktemp -u) \
  CC_METRICS_PORT="$AGENT_METRICS_PORT" CC_METRICS_BIND=127.0.0.1 \
  OPERATOR_NAMESPACE="$NS" PYTHONPATH="$REPO" \
  python3 -m tpu_cc_manager --tpu-backend fake --smoke-workload none --debug &
AGENT_PID=$!
sleep 6   # the dead orchestrator's 5 s lease lapses
RESUME_OUT=$(PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rollout \
    --selector pool=tpu-it --resume --node-timeout 120 --lease-duration 5)
echo "$RESUME_OUT"
echo "$RESUME_OUT" | grep -q '"resumed": true' || {
  echo "FAIL: successor did not resume from the persisted record"; exit 1; }
await_state off
kubectl label node "$NODE" "$MODE_LABEL=on" --overwrite
await_state on

echo ">>> autoscaler scale-down drill: Node object deleted mid-rollout"
# A phantom second Node (a real apiserver accepts Node objects with no
# kubelet behind them) joins the pool; it can never converge, so the
# rollout holds its window open — then "the autoscaler" deletes the Node
# object mid-window. The orchestrator must retire it immediately (no
# phantom timeout), spend ZERO failure budget (--failure-budget 0: any
# charge would halt), and report the pool rollout ok.
PHANTOM="kind-drill-phantom"
kubectl apply -f - <<EOF
apiVersion: v1
kind: Node
metadata:
  name: $PHANTOM
  labels:
    pool: tpu-it
EOF
( sleep 6; kubectl delete node "$PHANTOM" --ignore-not-found ) &
DELETER_PID=$!
# Observability drill (ISSUE 12): while the phantom holds the window
# open, scrape the ORCHESTRATOR's /rolloutz (live flight-recorder
# snapshot, served by --metrics-port) and the node agent's /metrics
# MID-ROLLOUT, and assert the rollout/reconcile families are present.
ORCH_METRICS_PORT=9189
OBS_DIR=$(mktemp -d)
( sleep 3
  curl -fsS "http://127.0.0.1:$ORCH_METRICS_PORT/rolloutz" \
    > "$OBS_DIR/rolloutz.json" 2>/dev/null || true
  curl -fsS "http://127.0.0.1:$AGENT_METRICS_PORT/metrics" \
    > "$OBS_DIR/node_metrics.txt" 2>/dev/null || true ) &
SCRAPER_PID=$!
SCALE_DOWN_OUT=$(PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rollout \
    --selector pool=tpu-it --mode off --max-unavailable 2 \
    --failure-budget 0 --node-timeout 120 \
    --metrics-port "$ORCH_METRICS_PORT") || {
  echo "FAIL: rollout did not survive the mid-window node deletion";
  echo "$SCALE_DOWN_OUT"; kill "$DELETER_PID" 2>/dev/null || true; exit 1; }
wait "$DELETER_PID" 2>/dev/null || true
wait "$SCRAPER_PID" 2>/dev/null || true
echo "$SCALE_DOWN_OUT"
echo "$SCALE_DOWN_OUT" | grep -q "$PHANTOM" || {
  echo "FAIL: deleted node not reported as retired"; exit 1; }
grep -q '"enabled": *true' "$OBS_DIR/rolloutz.json" || {
  echo "FAIL: /rolloutz not served mid-rollout"; exit 1; }
grep -q '"plan"' "$OBS_DIR/rolloutz.json" || {
  echo "FAIL: /rolloutz snapshot carries no rollout events"; exit 1; }
grep -q 'tpu_cc_reconciles_total' "$OBS_DIR/node_metrics.txt" || {
  echo "FAIL: node /metrics not scrapeable mid-rollout"; exit 1; }
echo ">>> rollout-timeline reconstructs the drill from the flight file"
PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rollout-timeline --selector pool=tpu-it \
  | grep -q "node-retired-deleted" || {
  echo "FAIL: rollout-timeline does not show the retired phantom"; exit 1; }
await_state off
kubectl label node "$NODE" "$MODE_LABEL=on" --overwrite
await_state on

echo ">>> quarantine drill: the taint patch verb against real RBAC"
PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl quarantine --node "$NODE" --reason kind-drill
effect=$(kubectl get node "$NODE" -o jsonpath\
='{.spec.taints[?(@.key=="cloud.google.com/tpu-cc.quarantined")].effect}')
[ "$effect" = "NoSchedule" ] || {
  echo "FAIL: quarantine taint not applied (effect='$effect')"; exit 1; }
q=$(kubectl get node "$NODE" \
  -o jsonpath="{.metadata.labels.cloud\.google\.com/tpu-cc\.quarantined}")
[ "$q" = "true" ] || { echo "FAIL: quarantine label not applied ($q)"; exit 1; }

echo ">>> pool failure budget halts a rollout over the quarantined pool"
if PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
   python3 -m tpu_cc_manager.ctl rollout \
     --selector pool=tpu-it --mode off --failure-budget 0 --node-timeout 30; then
  echo "FAIL: rollout did not halt on an exceeded failure budget"; exit 1
fi

PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl unquarantine --node "$NODE" --reason kind-drill
effect=$(kubectl get node "$NODE" -o jsonpath\
='{.spec.taints[?(@.key=="cloud.google.com/tpu-cc.quarantined")].effect}')
[ -z "$effect" ] || { echo "FAIL: quarantine taint not removed"; exit 1; }
# The agent still reconciles after the drill.
kubectl label node "$NODE" "$MODE_LABEL=off" --overwrite
await_state off

echo ">>> apiserver outage drill: intent journal + disconnected-mode restart"
# A local TCP proxy in front of the (127.0.0.1-served) kind apiserver is
# the blackout switch: the agent's kubeconfig dials the proxy, so killing
# the proxy is a TOTAL outage for the agent while kubectl keeps working.
PROXY_PORT=$(python3 -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')
API_HOST_PORT=${SERVER#https://}
start_proxy() {
  python3 - "$PROXY_PORT" "${API_HOST_PORT%:*}" "${API_HOST_PORT##*:}" <<'PYEOF' &
import socket, sys, threading
lport, host, port = int(sys.argv[1]), sys.argv[2], int(sys.argv[3])
srv = socket.socket()
srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
srv.bind(("127.0.0.1", lport)); srv.listen(64)
def pump(a, b):
    try:
        while True:
            data = a.recv(65536)
            if not data:
                break
            b.sendall(data)
    except OSError:
        pass
    finally:
        for s in (a, b):
            try: s.close()
            except OSError: pass
def serve(c):
    try:
        u = socket.create_connection((host, port), timeout=5)
    except OSError:
        c.close(); return
    threading.Thread(target=pump, args=(c, u), daemon=True).start()
    threading.Thread(target=pump, args=(u, c), daemon=True).start()
while True:
    c, _ = srv.accept()
    threading.Thread(target=serve, args=(c,), daemon=True).start()
PYEOF
  PROXY_PID=$!
  sleep 1
}
start_proxy
PROXY_KUBECONFIG=$(mktemp)
sed "s|$SERVER|https://127.0.0.1:$PROXY_PORT|" "$SA_KUBECONFIG" > "$PROXY_KUBECONFIG"

kill "$AGENT_PID" 2>/dev/null || true
wait "$AGENT_PID" 2>/dev/null || true
STATE_DIR=$(mktemp -d)
AGENT_LOG=$(mktemp)
JOURNALZ_PORT=$(python3 -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')
start_proxied_agent() {
  NODE_NAME="$NODE" KUBECONFIG="$PROXY_KUBECONFIG" JAX_PLATFORMS=cpu \
    PALLAS_AXON_POOL_IPS= CC_READINESS_FILE=$(mktemp -u) \
    OPERATOR_NAMESPACE="$NS" PYTHONPATH="$REPO" \
    CC_STATE_DIR="$STATE_DIR" CC_OFFLINE_GRACE_S=2 \
    CC_METRICS_PORT="$JOURNALZ_PORT" CC_METRICS_BIND=127.0.0.1 \
    python3 -m tpu_cc_manager --tpu-backend fake --smoke-workload none \
    --debug >> "$AGENT_LOG" 2>&1 &
  AGENT_PID=$!
}
start_proxied_agent
kubectl label node "$NODE" "$MODE_LABEL=on" --overwrite
await_state on

echo ">>> blackout: killing the apiserver proxy, flipping the mode unseen"
kill "$PROXY_PID" 2>/dev/null || true
wait "$PROXY_PID" 2>/dev/null || true
kubectl label node "$NODE" "$MODE_LABEL=off" --overwrite   # agent is dark
sleep 3   # outlast CC_OFFLINE_GRACE_S so disconnected mode engages

echo ">>> SIGKILL the agent; restart it while still dark"
kill -9 "$AGENT_PID" 2>/dev/null || true
wait "$AGENT_PID" 2>/dev/null || true
start_proxied_agent
sleep 5
kill -0 "$AGENT_PID" 2>/dev/null || {
  echo "FAIL: agent did not survive the dark restart (startup GET used to be fatal)"
  tail -40 "$AGENT_LOG"; exit 1; }
grep -q "last-known desired mode" "$AGENT_LOG" || {
  echo "FAIL: restarted agent never reported serving journaled local truth"
  tail -40 "$AGENT_LOG"; exit 1; }
[ -s "$STATE_DIR/intent.journal" ] || {
  echo "FAIL: no intent journal written under $STATE_DIR"; exit 1; }

echo ">>> restoring connectivity; asserting convergence + flushed journal"
start_proxy
await_state off
JOURNALZ=$(PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl journal \
    --url "http://127.0.0.1:$JOURNALZ_PORT/journalz")
echo "$JOURNALZ"
echo "$JOURNALZ" | grep -q "open intents: 0" || {
  echo "FAIL: intent journal still holds open intents after convergence"
  exit 1; }
echo "$JOURNALZ" | grep -q "deferred label patches: 0" || {
  echo "FAIL: deferred label patches were not flushed after reconnect"
  exit 1; }

echo ">>> federated parent-plane partition drill: escrowed budget + degraded mode"
# The federated rollout keeps the PARENT record on the kubeconfig's
# current context while each --regions shard drives its own named
# context. Pointing the current context at the TCP proxy and the region
# context straight at the real apiserver makes killing the proxy a
# PARENT-ONLY blackout: the shard keeps flipping nodes against a live
# regional apiserver while the coordination plane is unreachable — the
# SCALE_r04 scenario on a real apiserver with real Lease/CAS RBAC.
FED_KUBECONFIG=$(mktemp)
cat > "$FED_KUBECONFIG" <<EOF
apiVersion: v1
kind: Config
clusters:
- name: parent-proxied
  cluster: {server: "https://127.0.0.1:$PROXY_PORT", certificate-authority: "$CA_FILE"}
- name: kind-direct
  cluster: {server: "$SERVER", certificate-authority: "$CA_FILE"}
users:
- name: sa
  user: {token: "$TOKEN"}
contexts:
- name: parent
  context: {cluster: parent-proxied, user: sa}
- name: direct
  context: {cluster: kind-direct, user: sa}
current-context: parent
EOF

# The real agent would race the drill's stand-in agents on $NODE (and it
# dials the proxy, which is about to die again); stop it for the drill.
kill "$AGENT_PID" 2>/dev/null || true
wait "$AGENT_PID" 2>/dev/null || true
AGENT_PID=

# Phantom pool members stretch the rollout across enough windows that
# several federation boundaries land inside the blackout (grace = 2 s).
FED_PHANTOMS="fed-ph-1 fed-ph-2 fed-ph-3 fed-ph-4 fed-ph-5"
for ph in $FED_PHANTOMS; do
  kubectl apply -f - <<EOF
apiVersion: v1
kind: Node
metadata:
  name: $ph
  labels: {pool: tpu-it}
EOF
done

# Stand-in region agents: converge each node's state label ~3 s after
# the orchestrator stamps its desired label — kubectl uses the admin
# kubeconfig, so the "agents" stay up through the parent blackout just
# like real per-region agents would.
fake_region_agents() {
  while true; do
    for n in $NODE $FED_PHANTOMS; do
      want=$(kubectl get node "$n" -o jsonpath="{.metadata.labels.cloud\.google\.com/tpu-cc\.mode}" 2>/dev/null || true)
      got=$(kubectl get node "$n" -o jsonpath="{.metadata.labels.cloud\.google\.com/tpu-cc\.mode\.state}" 2>/dev/null || true)
      if [ -n "$want" ] && [ "$want" != "$got" ]; then
        sleep 3
        kubectl label node "$n" "$STATE_LABEL=$want" --overwrite >/dev/null
      fi
    done
    sleep 1
  done
}
fake_region_agents &
FAKE_AGENTS_PID=$!

FED_LOG=$(mktemp)
CC_FEDERATION_OFFLINE_GRACE_S=2 PYTHONPATH="$REPO" KUBECONFIG="$FED_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl rollout \
    --selector pool=tpu-it --mode on --regions ka=direct \
    --failure-budget 1 --max-unavailable 1 --node-timeout 120 \
    > "$FED_LOG" 2>&1 &
FED_PID=$!

sleep 6   # attach + escrow CAS + first window boundary land on a live parent
echo ">>> parent blackout: killing the proxy mid-rollout (region traffic unaffected)"
kill "$PROXY_PID" 2>/dev/null || true
wait "$PROXY_PID" 2>/dev/null || true
sleep 12  # several window boundaries sync dark, past the 2 s offline grace
echo ">>> restoring the parent plane"
start_proxy

wait "$FED_PID" || {
  echo "FAIL: federated rollout did not survive the parent-plane blackout"
  tail -60 "$FED_LOG"; kill "$FAKE_AGENTS_PID" 2>/dev/null || true; exit 1; }
kill "$FAKE_AGENTS_PID" 2>/dev/null || true
grep -q "parent plane offline past grace" "$FED_LOG" || {
  echo "FAIL: shard never declared degraded mode during the blackout"
  tail -60 "$FED_LOG"; exit 1; }
grep -q "parent plane reconnected" "$FED_LOG" || {
  echo "FAIL: shard never reconciled its dark spend after the blackout"
  tail -60 "$FED_LOG"; exit 1; }
FED_STATUS=$(PYTHONPATH="$REPO" KUBECONFIG="$SA_KUBECONFIG" \
  python3 -m tpu_cc_manager.ctl status --selector pool=tpu-it)
echo "$FED_STATUS" | grep -q "federation: mode=on status=complete" || {
  echo "FAIL: parent record not complete after reconnect reconciliation"
  echo "$FED_STATUS"; exit 1; }

for ph in $FED_PHANTOMS; do
  kubectl delete node "$ph" --ignore-not-found >/dev/null
done

echo ">>> kind integration OK (RBAC incl. taints + leases + real watch + merge-patch + rollout + SIGKILL/resume + quarantine + apiserver-outage + mid-rollout /rolloutz+/metrics + federated parent-blackout drill verified)"
