#!/usr/bin/env bash
# Failure-and-recovery demo: the real agent against hack/mock_apiserver.py,
# driven through the FAIL-SOFT path the happy-path demo never touches.
#
#   1. desired mode -> "bogus"  => mode.state=failed +
#      failed.reason=invalid-mode, and the agent KEEPS WATCHING (the
#      reference would refuse silently; a crash loop can't be fixed by a
#      label edit the agent never sees — ccmanager/manager.py).
#   2. desired mode -> "on"     => full reconcile, reason label cleared,
#      mode.state=on.
set -euo pipefail

PORT="${PORT:-18082}"
source "$(dirname "${BASH_SOURCE[0]}")/demo_lib.sh"
NODE=demo-node-0

start_mock_apiserver

echo ">>> starting tpu-cc-manager (fake backend, no smoke)"
start_agent "$NODE" -- --smoke-workload none --debug
AGENT=$AGENT_PID
sleep 3

echo ">>> desired mode -> bogus (fail-soft path)"
set_label "$NODE" "cloud.google.com/tpu-cc.mode" '"bogus"'
await_label "$NODE" "cloud.google.com/tpu-cc.mode.state" "failed"
reason=$(get_label "$NODE" "cloud.google.com/tpu-cc.failed.reason")
[ "$reason" = "invalid-mode" ] || { echo ">>> FAILED: reason='$reason'"; exit 1; }
kill -0 "$AGENT" || { echo ">>> FAILED: agent died on bad input"; exit 1; }
echo ">>> failed + reason=invalid-mode reported; agent still alive"

echo ">>> desired mode -> on (recovery)"
set_label "$NODE" "cloud.google.com/tpu-cc.mode" '"on"'
await_label "$NODE" "cloud.google.com/tpu-cc.mode.state" "on"
reason=$(get_label "$NODE" "cloud.google.com/tpu-cc.failed.reason")
[ -z "$reason" ] || { echo ">>> FAILED: stale reason '$reason'"; exit 1; }
echo ">>> recovered to mode.state=on, reason label cleared"
echo ">>> failure demo OK"
