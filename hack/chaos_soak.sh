#!/usr/bin/env bash
# Seeded chaos soak: the long-form version of the tier-1 chaos subset
# (tests/test_chaos.py, `chaos` pytest marker).
#
# Drives the REAL manager loop — watch, drain, stage/reset, attest,
# readmit, watchdog — through a seeded schedule of apiserver faults
# (429+Retry-After, 5xx, connection resets, watch hangups, stale-rv 410s)
# plus device-layer faults, for CC_CHAOS_ROUNDS rounds per seed, and
# asserts convergence: correct final mode labels, no stuck pause labels,
# bounded retry counts, a watchdog demote→restore cycle.
#
# Terminal-fault mode (on by default, CC_CHAOS_TERMINAL=0 disables): the
# same suite also seeds a device fault that NEVER clears and asserts the
# remediation ladder (ccmanager/remediation.py) escalates end-to-end —
# backoff retry → device re-reset → runtime restart → quarantine (taint +
# label + event + halted rollouts) → probation auto-lift once the fault
# clears. Its REMEDIATION_SUMMARY counters land in the JSON summary.
#
#   CC_CHAOS_SEED     base seed (default 20260803); each iteration offsets it
#   CC_CHAOS_ROUNDS   mode-drive rounds per soak (default 5; tier-1 runs 2)
#   CC_CHAOS_ITERS    how many seeds to soak (default 5)
#   CC_CHAOS_TERMINAL 1 (default) runs the terminal-fault ladder leg too
#   OUT               JSON summary artifact (default artifacts/chaos_soak.json)
#
# Exit 0 only when every seed converged. The summary records per-seed
# fault/retry counts (grepped from the test's CHAOS_SOAK_SUMMARY line),
# remediation-ladder counters (REMEDIATION_SUMMARY), the fleet-churn
# scenarios' outcomes (PREEMPTION_SUMMARY: preemption fast-drain +
# handoff resume, slice fencing of a departed peer), and the
# serving-under-the-flip soak (SERVE_SUMMARY: rolling flip under
# sustained traffic, zero lost requests), the zero-bounce handoff leg
# (HANDOFF_SUMMARY: flip with the in-flight-handoff sink wired — zero
# lost, nonzero accepted handoffs, conserved), the flight-recorder
# crash leg (OBS_SUMMARY: events written across kill+resume at every
# crash point, zero torn JSONL lines), the fleet-gateway leg
# (FLEET_SUMMARY: the federation gateway keeps serving a lint-clean
# merged exposition while seeded chaos kills and resurrects scraped
# agents, staleness tracking the kill schedule), and the federated
# regional-rollout leg (FEDERATION_SUMMARY: seeded mid-rollout regional
# orchestrator kill + successor resume, then a regional apiserver
# blackout that stalls only its own region — parent record completes
# with exactly-once budget accounting), and the continuous-prestage
# crash leg (PRESTAGE_SUMMARY: a seeded SIGKILL lands mid-prestage of
# wave N+1 while wave N drains; successors resume BOTH waves, the
# capacity ledger balances to zero with no double-charge, no node lost
# or double-bounced), and the gray-failure brownout leg (GRAY_SUMMARY:
# a mid-run brownout slows one node without failing anything; the
# peer-relative vetter detects it, the ladder escalates
# runtime-restart -> quarantine reason=fail-slow with zero lost
# requests, and the cleared verdict + probation lift it) so the
# evidence ladder can cite them.
set -u

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS=cpu

SEED="${CC_CHAOS_SEED:-20260803}"
ROUNDS="${CC_CHAOS_ROUNDS:-5}"
ITERS="${CC_CHAOS_ITERS:-5}"
TERMINAL="${CC_CHAOS_TERMINAL:-1}"
OUT="${OUT:-artifacts/chaos_soak.json}"
mkdir -p "$(dirname "$OUT")" artifacts

# The terminal-fault leg is one named test; deselect it when disabled.
# test_preemption.py carries the churn scenarios (preemption fast-drain +
# handoff, slice fencing of a departed peer) — seeded from the same
# CC_CHAOS_SEED, summarized via PREEMPTION_SUMMARY lines.
# test_serve.py carries the serving-under-the-flip soak (rolling CC flip
# under sustained traffic, zero lost requests) — SERVE_SUMMARY lines —
# plus the open-loop overload leg (rate-driven arrivals, admission
# control shedding, flip under overload with zero accepted losses) —
# SERVE_OVERLOAD_SUMMARY lines.
# test_flight.py carries the flight-recorder crash leg (kill the
# orchestrator at every crash point, resume, assert ONE exactly-once
# timeline with zero torn JSONL lines) — OBS_SUMMARY lines.
# test_obs_fleet.py carries the fleet-gateway leg (merged exposition
# stays lint-clean while seeded chaos kills scraped agents) —
# FLEET_SUMMARY lines.
# test_federation.py carries the federated regional-rollout leg (seeded
# regional kill + resume, regional apiserver blackout, exactly-once
# shared budget) — FEDERATION_SUMMARY lines.
# test_prestage_ledger.py carries the continuous-prestage crash leg
# (seeded orchestrator SIGKILL mid-prestage of wave N+1 while wave N
# drains; dual-wave resume, ledger balanced, no double-charge) —
# PRESTAGE_SUMMARY lines.
# test_failslow.py carries the gray-failure brownout leg (peer-relative
# detection -> de-weight -> restart -> quarantine -> probation lift,
# zero lost requests) — GRAY_SUMMARY lines.
PYTEST_ARGS=(tests/test_chaos.py tests/test_preemption.py tests/test_serve.py tests/test_flight.py tests/test_obs_fleet.py tests/test_federation.py tests/test_prestage_ledger.py tests/test_failslow.py -q -m chaos -p no:cacheprovider -p no:randomly -s)
if [ "$TERMINAL" = "0" ]; then
  PYTEST_ARGS+=(--deselect \
    "tests/test_chaos.py::test_terminal_fault_escalates_full_ladder_to_quarantine_and_lifts")
fi

results=()
failed=0
for i in $(seq 0 $((ITERS - 1))); do
  seed=$((SEED + i))
  log="artifacts/chaos_soak_seed${seed}.log"
  echo "=== chaos soak: seed=$seed rounds=$ROUNDS terminal=$TERMINAL ==="
  if CC_CHAOS_SEED=$seed CC_CHAOS_ROUNDS=$ROUNDS \
     timeout -k 10 600 python -m pytest "${PYTEST_ARGS[@]}" > "$log" 2>&1; then
    ok=true
  else
    ok=false
    failed=$((failed + 1))
    echo ">>> seed $seed FAILED (see $log)"
    tail -40 "$log"
  fi
  # -q progress dots share the line, so match anywhere, not just column 0.
  summary=$(grep -ao "CHAOS_SOAK_SUMMARY.*" "$log" | tail -1 | sed 's/^CHAOS_SOAK_SUMMARY //')
  remediation=$(grep -ao "REMEDIATION_SUMMARY.*" "$log" | tail -1 | sed "s/^REMEDIATION_SUMMARY //; s/'/ /g; s/\"/ /g")
  offline=$(grep -ao "OFFLINE_SUMMARY.*" "$log" | tail -1 | sed "s/^OFFLINE_SUMMARY //; s/'/ /g; s/\"/ /g")
  preemption=$(grep -ao "PREEMPTION_SUMMARY.*" "$log" | sed "s/^PREEMPTION_SUMMARY //; s/'/ /g; s/\"/ /g" | paste -sd'; ' -)
  serve=$(grep -ao "SERVE_SUMMARY.*" "$log" | tail -1 | sed "s/^SERVE_SUMMARY //; s/'/ /g; s/\"/ /g")
  serve_overload=$(grep -ao "SERVE_OVERLOAD_SUMMARY.*" "$log" | tail -1 | sed "s/^SERVE_OVERLOAD_SUMMARY //; s/'/ /g; s/\"/ /g")
  handoff=$(grep -ao "HANDOFF_SUMMARY.*" "$log" | tail -1 | sed "s/^HANDOFF_SUMMARY //; s/'/ /g; s/\"/ /g")
  obs=$(grep -ao "OBS_SUMMARY.*" "$log" | tail -1 | sed "s/^OBS_SUMMARY //; s/'/ /g; s/\"/ /g")
  fleet=$(grep -ao "FLEET_SUMMARY.*" "$log" | tail -1 | sed "s/^FLEET_SUMMARY //; s/'/ /g; s/\"/ /g")
  federation=$(grep -ao "FEDERATION_SUMMARY.*" "$log" | tail -1 | sed "s/^FEDERATION_SUMMARY //; s/'/ /g; s/\"/ /g")
  prestage=$(grep -ao "PRESTAGE_SUMMARY.*" "$log" | tail -1 | sed "s/^PRESTAGE_SUMMARY //; s/'/ /g; s/\"/ /g")
  gray=$(grep -ao "GRAY_SUMMARY.*" "$log" | tail -1 | sed "s/^GRAY_SUMMARY //; s/'/ /g; s/\"/ /g")
  results+=("{\"seed\": $seed, \"ok\": $ok, \"summary\": \"${summary}\", \"remediation\": \"${remediation}\", \"offline\": \"${offline}\", \"preemption\": \"${preemption}\", \"serve\": \"${serve}\", \"serve_overload\": \"${serve_overload}\", \"handoff\": \"${handoff}\", \"obs\": \"${obs}\", \"fleet\": \"${fleet}\", \"federation\": \"${federation}\", \"prestage\": \"${prestage}\", \"gray\": \"${gray}\"}")
done

{
  printf '{"ok": %s, "rounds": %s, "iterations": %s, "terminal_faults": %s, "results": [' \
    "$([ "$failed" -eq 0 ] && echo true || echo false)" "$ROUNDS" "$ITERS" \
    "$([ "$TERMINAL" = "0" ] && echo false || echo true)"
  (IFS=,; printf '%s' "${results[*]}")
  printf ']}\n'
} > "$OUT"
echo "=== chaos soak: $((ITERS - failed))/$ITERS seed(s) converged -> $OUT ==="
[ "$failed" -eq 0 ]
