#!/usr/bin/env bash
# Round-5 on-chip evidence ladder, one command, outage-resumable.
#
# VERDICT r4's remaining asks, in priority order (cheapest/highest-value
# first so a short healthy-tunnel window still captures maximally):
#   1. pallas tiling sweep            -> artifacts/pallas_sweep_r05.jsonl
#   2. llama3.2-1b decode+prefill     -> artifacts/smoke_llama1b_tpu_r05.json
#   3. resnet batch ladder            -> artifacts/resnet_ladder_r05.jsonl
#   4. llama3.2-3b decode+prefill     -> artifacts/smoke_llama3b_tpu_r05.json
#   5. llama batch ladder (1b)        -> artifacts/llama_ladder_r05.jsonl
#   6. A/B matmul+llama+resnet        -> AB_r05.json
# Stage order rationale: the sweep answers the round's #1 verdict item;
# the 1b llama is the quick scale-up datapoint; resnet is compile-heavy
# (>9 min observed) so it goes mid-ladder; the A/B is the longest
# (cycles x reps x workloads) and runs last.
#
# Each stage is gated on the tunnel listener (hack/sweep_lib.sh) so an
# outage stops the ladder at the next stage boundary (a rung already
# mid-dispatch when the transport dies still blocks — the gate can only
# probe between dispatches). Single-point .json stages are skipped when
# their artifact already exists and is non-empty (capture_to only ever
# promotes an ok:true result, so non-empty == complete); .jsonl ladder
# stages are ALWAYS re-invoked — a partial ladder is non-empty too, and
# only the ladder script's own RESUME=1 sweep_done logic knows which
# rungs are still missing (ADVICE.md round 5). A stage command exiting
# non-zero stops the ladder at that boundary instead of falling through
# with an incomplete artifact. The exit code is honest: 0 only when
# every artifact exists.
#
# CAUTION: single-client tunnel — make sure nothing else TPU-touching is
# running first (pgrep -f "tpu_cc_manager.smoke|bench.py"). No kills.
set -u

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"
export PYTHONPATH="$REPO_ROOT${PYTHONPATH:+:$PYTHONPATH}"
. "$REPO_ROOT/hack/sweep_lib.sh"
export RESUME=1
mkdir -p artifacts

# The full artifact set, declared upfront so finish() reports honestly
# even when the ladder stops at an early stage.
ARTIFACTS=(
  artifacts/chaos_soak.json
  SCALE_r01.json
  SCALE_r03.json
  SCALE_r04.json
  FLEET_r01.json
  SERVE_r01.json
  SERVE_r02.json
  SERVE_r03.json
  BENCH_r08.json
  BENCH_r09.json
  artifacts/GRAY_r01.json
  artifacts/smoke_cache_r06.json
  artifacts/pallas_sweep_r05.jsonl
  artifacts/smoke_llama1b_tpu_r05.json
  artifacts/resnet_ladder_r05.jsonl
  artifacts/smoke_llama3b_tpu_r05.json
  artifacts/llama_ladder_r05.jsonl
  AB_r05.json
)

stage() {  # stage NAME ARTIFACT CMD...
  local name=$1 artifact=$2 rc
  shift 2
  echo "=== stage: $name ==="
  case "$artifact" in
    *.jsonl)
      # Ladder artifacts are appended rung by rung: non-empty does NOT
      # mean complete. Always re-invoke; the ladder script's RESUME=1
      # sweep_done logic skips rungs already captured.
      ;;
    *)
      if [ -s "$artifact" ]; then
        echo ">>> $artifact already captured; skipping"
        return 0
      fi
      ;;
  esac
  tunnel_gate || { echo ">>> tunnel down; stopping at stage '$name' (re-run to resume)"; finish; }
  "$@"
  rc=$?
  if [ "$rc" -ne 0 ]; then
    # A ladder that aborted (e.g. tunnel died mid-sweep, exit 3) must not
    # fall through to later stages with its artifact silently incomplete.
    echo ">>> stage '$name' exited rc=$rc; stopping ladder (re-run to resume)"
    finish
  fi
}

# capture_to ARTIFACT CMD...: run CMD, keep its LAST stdout line, and
# promote it to ARTIFACT only when it is a JSON object with ok==true —
# a single-point stage must never mark itself captured with a failure
# line (ladders keep failure rows by design; these artifacts are the
# round's headline evidence and a failed stage should retry on re-run).
capture_to() {
  local artifact=$1
  shift
  "$@" 2>>artifacts/evidence_r5.stderr.log | tail -1 | tee "$artifact.tmp"
  if python3 - "$artifact.tmp" <<'EOF'
import json, sys
try:
    ok = json.load(open(sys.argv[1])).get("ok") is True
except Exception:
    ok = False
sys.exit(0 if ok else 1)
EOF
  then
    mv "$artifact.tmp" "$artifact"
  else
    echo ">>> stage result not ok; NOT promoting to $artifact (see artifacts/evidence_r5.stderr.log)"
    rm -f "$artifact.tmp"
  fi
}

finish() {  # honest exit: 0 only when every artifact exists non-empty
  local missing=0 a
  for a in "${ARTIFACTS[@]}"; do
    if [ ! -s "$a" ]; then
      echo ">>> MISSING: $a"
      missing=$((missing + 1))
    fi
  done
  if [ "$missing" -eq 0 ]; then
    echo "=== evidence ladder complete ==="
    exit 0
  fi
  echo "=== evidence ladder INCOMPLETE: $missing artifact(s) missing (re-run to resume) ==="
  exit 3
}

# Robustness evidence first: the seeded chaos soak is CPU-only (fake
# backend + in-memory apiserver), needs no tunnel, and is the cheapest
# stage — so it runs before the gated on-chip ladder and its artifact is
# captured even when the tunnel never comes up. Skipped only when the
# artifact records ok:true — chaos_soak.sh writes the summary even on a
# failed soak (for inspection), so non-empty alone must NOT read as
# captured or a failed soak would silently pass on re-run.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("artifacts/chaos_soak.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> artifacts/chaos_soak.json already captured (ok:true); skipping"
else
  echo "=== stage: chaos-soak (local, no tunnel) ==="
  bash hack/chaos_soak.sh || {
    # Park the failed summary where finish()'s exists-non-empty check
    # cannot mistake it for captured evidence.
    [ -s artifacts/chaos_soak.json ] && \
      mv artifacts/chaos_soak.json artifacts/chaos_soak.failed.json
    echo ">>> chaos soak FAILED; stopping ladder (robustness evidence gates the rest; summary in artifacts/chaos_soak.failed.json)"
    finish
  }
fi

# Fleet-scale evidence: the scale bench is CPU-only too (simulated
# FakeKube fleets), so it also runs before the tunnel-gated ladder.
# Resumable at two grains: completed (mode, size) rows persist in the
# partial JSONL and are skipped on re-run (the 10k pool takes minutes —
# an interruption must not re-buy finished pools), and the whole stage is
# skipped once the summary records ok:true. A failed summary is parked
# like the chaos soak's so finish() can't mistake it for captured.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SCALE_r01.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SCALE_r01.json already captured (ok:true); skipping"
else
  echo "=== stage: scale-bench (local, no tunnel) ==="
  python3 hack/scale_bench.py --out SCALE_r01.json \
      --partial artifacts/scale_partial.jsonl \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SCALE_r01.json ] && mv SCALE_r01.json artifacts/SCALE_r01.failed.json
    echo ">>> scale bench FAILED; stopping ladder (summary in artifacts/SCALE_r01.failed.json; partial rows kept for resume)"
    finish
  }
fi

# Fleet observability evidence (FLEET_r01): the federation gateway over
# a 100-agent in-process fleet — one-interval scrape+merge convergence,
# merged exposition through the exposition lint, killed agents stale
# within 2 sweeps, and a sharded kill+resume rollout whose stitched
# cross-shard timeline reconstructs exactly-once outcomes. CPU-only,
# single point, same skip/park discipline as the other stages.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("FLEET_r01.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> FLEET_r01.json already captured (ok:true); skipping"
else
  echo "=== stage: scale-bench --gateway (fleet gateway, no tunnel) ==="
  python3 hack/scale_bench.py --gateway --out FLEET_r01.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s FLEET_r01.json ] && mv FLEET_r01.json artifacts/FLEET_r01.failed.json
    echo ">>> fleet gateway bench FAILED; stopping ladder (summary in artifacts/FLEET_r01.failed.json)"
    finish
  }
fi

# Mock-apiserver scale parity (PR 7): the same 1k-node rollout over real
# HTTP through RestKube + hack/mock_apiserver.py. Cheaper than the
# FakeKube ladder (one size), same skip/park discipline.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SCALE_r02.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SCALE_r02.json already captured (ok:true); skipping"
else
  echo "=== stage: scale-bench --apiserver (HTTP mock, no tunnel) ==="
  python3 hack/scale_bench.py --apiserver --out SCALE_r02.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SCALE_r02.json ] && mv SCALE_r02.json artifacts/SCALE_r02.failed.json
    echo ">>> HTTP scale bench FAILED; stopping ladder (summary in artifacts/SCALE_r02.failed.json)"
    finish
  }
fi

# Federated fleet-scale evidence (SCALE_r03): a 100k-node rollout
# region-sharded across 10 per-region mock apiservers — shared failure
# budget through one CAS-fenced parent record, a mid-rollout regional
# orchestrator kill + successor resume, per-apiserver load no worse than
# SCALE_r02's per-node baseline, and the per-region flight recorders
# stitched into one exactly-once cross-region timeline. CPU-only;
# resumable at two grains like SCALE_r01 (completed federation rows
# persist in the partial JSONL; the stage skips once the summary records
# ok:true; a failed summary is parked).
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SCALE_r03.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SCALE_r03.json already captured (ok:true); skipping"
else
  echo "=== stage: scale-bench --federation (region-sharded, no tunnel) ==="
  python3 hack/scale_bench.py --federation --out SCALE_r03.json \
      --partial artifacts/scale_federation_partial.jsonl \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SCALE_r03.json ] && mv SCALE_r03.json artifacts/SCALE_r03.failed.json
    echo ">>> federation scale bench FAILED; stopping ladder (summary in artifacts/SCALE_r03.failed.json; partial rows kept for resume)"
    finish
  }
fi

# Parent-plane partition evidence (SCALE_r04): the SCALE_r03 federation
# driven through a TOTAL parent-apiserver blackout mid-rollout — healthy
# regions keep flipping against escrowed budget slices, one region is
# SIGKILLed mid-blackout and a successor resumes DARK from the
# checkpointed escrow ledger under a ±135 s clock skew, another spends
# its escrow dry and halts (then resumes after reconnect), and the
# stitched cross-region timeline stays exactly-once with zero torn
# writes. Same skip/park/resume discipline as SCALE_r03.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SCALE_r04.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SCALE_r04.json already captured (ok:true); skipping"
else
  echo "=== stage: scale-bench --federation-blackout (parent partition, no tunnel) ==="
  python3 hack/scale_bench.py --federation-blackout --out SCALE_r04.json \
      --partial artifacts/scale_blackout_partial.jsonl \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SCALE_r04.json ] && mv SCALE_r04.json artifacts/SCALE_r04.failed.json
    echo ">>> parent-blackout scale bench FAILED; stopping ladder (summary in artifacts/SCALE_r04.failed.json; partial rows kept for resume)"
    finish
  }
fi

# Serving-under-the-flip evidence (ROADMAP item 3): a rolling CC flip
# over a pool of real agents under sustained synthetic traffic — zero
# lost requests, p50/p99 during vs steady. CPU-only (fake pool), so it
# runs before the tunnel-gated ladder with the same skip/park
# discipline as the other single-point stages.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SERVE_r01.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SERVE_r01.json already captured (ok:true); skipping"
else
  echo "=== stage: serve-bench (local, no tunnel) ==="
  python3 hack/serve_bench.py --out SERVE_r01.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SERVE_r01.json ] && mv SERVE_r01.json artifacts/SERVE_r01.failed.json
    echo ">>> serve bench FAILED; stopping ladder (summary in artifacts/SERVE_r01.failed.json)"
    finish
  }
fi

# Open-loop overload evidence (ROADMAP item 1, SERVE_r02): the rate
# sweep finds the knee (goodput tracks offered load below it, bounded
# queue-delay p99), proves shedding holds goodput past it, then a full
# rolling flip AT the knee under open-loop traffic with zero accepted
# losses. CPU-only. Resumable at two grains: completed ok:true sweep
# rates persist in the partial JSONL and are skipped on re-run, and the
# whole stage skips once the summary records ok:true; a failed summary
# is parked like the chaos soak's.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SERVE_r02.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SERVE_r02.json already captured (ok:true); skipping"
else
  echo "=== stage: serve-bench --sweep (open-loop overload, no tunnel) ==="
  python3 hack/serve_bench.py --sweep 200,400,800,1600,3200,6400 \
      --partial artifacts/serve_sweep_partial.jsonl \
      --out SERVE_r02.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SERVE_r02.json ] && mv SERVE_r02.json artifacts/SERVE_r02.failed.json
    echo ">>> open-loop serve bench FAILED; stopping ladder (summary in artifacts/SERVE_r02.failed.json; partial sweep rows kept for resume)"
    finish
  }
fi

# Zero-bounce flip evidence (ROADMAP item 5, SERVE_r03): the same knee
# setup as SERVE_r02, flipped twice — control (checkpoint+requeue) vs
# in-flight handoff to accepting peers — gated on the handoff flip's
# during/steady p99 ratio <= 1.3, zero lost, nonzero accepted handoffs.
# CPU-only; same two-grain resume discipline as SERVE_r02 (its own
# partial file — the handoff sweep must not poison SERVE_r02's rows).
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("SERVE_r03.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> SERVE_r03.json already captured (ok:true); skipping"
else
  echo "=== stage: serve-bench --handoff (zero-bounce flip, no tunnel) ==="
  python3 hack/serve_bench.py --handoff \
      --partial artifacts/serve_handoff_sweep_partial.jsonl \
      --out SERVE_r03.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s SERVE_r03.json ] && mv SERVE_r03.json artifacts/SERVE_r03.failed.json
    echo ">>> zero-bounce serve bench FAILED; stopping ladder (summary in artifacts/SERVE_r03.failed.json; partial sweep rows kept for resume)"
    finish
  }
fi

# Pre-staged spare evidence (ROADMAP item 5, BENCH_r08): a surge spare
# pre-stages its full flip + warmup ahead of the wave; the artifact
# gates on effective flip wall <= the spare's own drain+readmit cost
# AND strictly below BENCH_r07's full-path wall. CPU-only, single
# point, same skip/park discipline.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("BENCH_r08.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> BENCH_r08.json already captured (ok:true); skipping"
else
  echo "=== stage: bench --spare (pre-staged spare flip, no tunnel) ==="
  python3 bench.py --spare --out BENCH_r08.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s BENCH_r08.json ] && mv BENCH_r08.json artifacts/BENCH_r08.failed.json
    echo ">>> spare-prestage bench FAILED; stopping ladder (summary in artifacts/BENCH_r08.failed.json)"
    finish
  }
fi

# Whole-fleet zero-bounce evidence (ROADMAP item, BENCH_r09): a 10-node
# rolling flip under open-loop traffic at 80 % of the knee with
# CONTINUOUS prestage under the crash-journaled capacity ledger —
# every node's effective flip wall <= its drain+readmit bar, zero
# prestage-attributable SLO pauses, zero lost requests, a no-prestage
# control leg whose walls exceed the bar, and a seeded mid-prestage
# orchestrator SIGKILL resumed with the ledger balancing to zero and
# no double-charge. CPU-only, single point, same skip/park discipline.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("BENCH_r09.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> BENCH_r09.json already captured (ok:true); skipping"
else
  echo "=== stage: serve-bench --prestage (fleet zero-bounce, no tunnel) ==="
  python3 hack/serve_bench.py --prestage --nodes 10 \
      --partial artifacts/serve_prestage_sweep_partial.jsonl \
      --out BENCH_r09.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s BENCH_r09.json ] && mv BENCH_r09.json artifacts/BENCH_r09.failed.json
    echo ">>> fleet-prestage bench FAILED; stopping ladder (summary in artifacts/BENCH_r09.failed.json; partial sweep rows kept for resume)"
    finish
  }
fi

# Fail-slow containment evidence (GRAY_r01): a seeded brownout slows
# one node 4x without failing anything; the detector leg must hold
# during-brownout p99 within 1.3x of healthy steady state with zero
# lost requests, quarantine the node within <=2 vetting windows
# (reason=fail-slow) and restore it after the brownout clears, while
# the detector-off control leg shows >=2x p99 degradation — plus a
# SIGKILL crash leg at the failslow-vetted journal point proving
# exactly-once containment. CPU-only, single point, same skip/park
# discipline as the other serve stages.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("artifacts/GRAY_r01.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> artifacts/GRAY_r01.json already captured (ok:true); skipping"
else
  echo "=== stage: serve-bench --brownout (fail-slow containment, no tunnel) ==="
  python3 hack/serve_bench.py --brownout --nodes 6 --knee-frac 0.6 \
      --rate-s 1.5 --out artifacts/GRAY_r01.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s artifacts/GRAY_r01.json ] && \
      mv artifacts/GRAY_r01.json artifacts/GRAY_r01.failed.json
    echo ">>> fail-slow bench FAILED; stopping ladder (summary in artifacts/GRAY_r01.failed.json)"
    finish
  }
fi

# Compilation-cache proof (VERDICT weak #2): cold vs warm smoke across a
# simulated CC bounce. Resumable the same way as the other single-point
# stages — skipped once the artifact records ok:true, parked as
# .failed.json otherwise so finish() can't mistake a failed capture for
# evidence. Runs before the tunnel-gated ladder: the measurement is
# honest on whatever backend the smoke reaches (the artifact records it),
# and a tunnel outage must not cost us the cache evidence.
if python3 -c 'import json,sys; sys.exit(0 if json.load(open("artifacts/smoke_cache_r06.json")).get("ok") is True else 1)' 2>/dev/null; then
  echo ">>> artifacts/smoke_cache_r06.json already captured (ok:true); skipping"
else
  echo "=== stage: smoke-cache cold-vs-warm (local) ==="
  python3 hack/smoke_cache_bench.py --out artifacts/smoke_cache_r06.json \
      2>>artifacts/evidence_r5.stderr.log || {
    [ -s artifacts/smoke_cache_r06.json ] && \
      mv artifacts/smoke_cache_r06.json artifacts/smoke_cache_r06.failed.json
    echo ">>> smoke-cache bench FAILED; stopping ladder (summary in artifacts/smoke_cache_r06.failed.json)"
    finish
  }
fi

stage "pallas-sweep" artifacts/pallas_sweep_r05.jsonl \
  env OUT=artifacts/pallas_sweep_r05.jsonl ERRLOG=artifacts/pallas_sweep_r05.stderr.log \
  bash hack/tune_pallas.sh

stage "llama3.2-1b" artifacts/smoke_llama1b_tpu_r05.json \
  capture_to artifacts/smoke_llama1b_tpu_r05.json \
  python3 -m tpu_cc_manager.smoke --workload llama --size llama3.2-1b

stage "resnet-ladder" artifacts/resnet_ladder_r05.jsonl \
  env WORKLOAD=resnet BATCHES="32 64 128 256" \
      OUT=artifacts/resnet_ladder_r05.jsonl ERRLOG=artifacts/resnet_ladder_r05.stderr.log \
  bash hack/batch_ladder.sh

stage "llama3.2-3b" artifacts/smoke_llama3b_tpu_r05.json \
  capture_to artifacts/smoke_llama3b_tpu_r05.json \
  python3 -m tpu_cc_manager.smoke --workload llama --size llama3.2-3b

stage "llama-ladder" artifacts/llama_ladder_r05.jsonl \
  env WORKLOAD=llama SIZE=llama3.2-1b BATCHES="1 4 8 16 32" \
      OUT=artifacts/llama_ladder_r05.jsonl ERRLOG=artifacts/llama_ladder_r05.stderr.log \
  bash hack/batch_ladder.sh

# --timeout-s 1200: the resnet smoke's tunnel remote compile has exceeded
# 9 min; the default 300 s would timeout-kill it MID-DISPATCH — the known
# r4 wedge trigger (.claude/skills/verify). A generous deadline trades a
# slower worst case for never killing a live dispatch.
stage "ab" AB_r05.json \
  capture_to AB_r05.json \
  python3 bench_ab.py --cycles 3 --reps 2 --timeout-s 1200 \
    --workloads matmul,llama,resnet --llama-size llama3.2-1b

finish
