"""HTTP-level tests of the stdlib REST client (kubeclient/rest.py).

Everything else in the suite exercises the control plane against the
in-process FakeKube; these tests put a real HTTP apiserver mock behind
``RestKube`` so the wire layer itself is covered: URL/query construction,
bearer-token header, merge-patch bodies, selector pass-through, HTTPError →
KubeApiError mapping, and the streaming JSON-lines watch protocol
(chunked transfer, server-side close on timeout) that the reference consumed
via ``watch.Watch().stream`` (reference main.py:622-632).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from tpu_cc_manager.kubeclient.api import KubeApiError
from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

NODE = "node-a"


class _MockApiserver:
    """Minimal nodes/pods/watch apiserver over stdlib http.server."""

    def __init__(self):
        self.node = {
            "kind": "Node",
            "metadata": {"name": NODE, "resourceVersion": "1", "labels": {}},
        }
        self.pods = [
            {"metadata": {"name": "p1", "labels": {"app": "x"}},
             "spec": {"nodeName": NODE}},
            {"metadata": {"name": "p2", "labels": {"app": "y"}},
             "spec": {"nodeName": "other"}},
        ]
        # Recorded for assertions.
        self.requests: list[dict] = []
        # Events served to the next watch request, then the stream closes.
        self.watch_events: list[dict] = []

        state = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: D102 - silence
                pass

            def _record(self, body=None):
                state.requests.append({
                    "method": self.command,
                    "path": urlparse(self.path).path,
                    "query": parse_qs(urlparse(self.path).query),
                    "headers": dict(self.headers),
                    "body": body,
                })

            def _json(self, obj, code=200):
                raw = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                self._record()
                u = urlparse(self.path)
                q = parse_qs(u.query)
                if u.path == f"/api/v1/nodes/{NODE}":
                    return self._json(state.node)
                if u.path.startswith("/api/v1/nodes/"):
                    return self._json(
                        {"kind": "Status", "code": 404, "message": "nope"}, 404
                    )
                if u.path == "/api/v1/nodes" and q.get("watch") == ["true"]:
                    # Chunked JSON-lines stream: emit queued events, close.
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    for ev in state.watch_events:
                        data = (json.dumps(ev) + "\n").encode()
                        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                    self.wfile.write(b"0\r\n\r\n")
                    return None
                if u.path == "/api/v1/nodes":
                    return self._json({"kind": "NodeList", "items": [state.node]})
                if u.path.endswith("/pods"):
                    items = list(state.pods)
                    sel = q.get("labelSelector", [None])[0]
                    if sel:
                        k, v = sel.split("=", 1)
                        items = [p for p in items
                                 if p["metadata"]["labels"].get(k) == v]
                    fsel = q.get("fieldSelector", [None])[0]
                    if fsel and fsel.startswith("spec.nodeName="):
                        want = fsel.split("=", 1)[1]
                        items = [p for p in items
                                 if p["spec"]["nodeName"] == want]
                    return self._json({"kind": "PodList", "items": items})
                return self._json({"kind": "Status", "code": 404}, 404)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                self._record(body)
                u = urlparse(self.path)
                if u.path == (
                    "/apis/authorization.k8s.io/v1/selfsubjectaccessreviews"
                ):
                    attrs = (
                        (body.get("spec") or {}).get("resourceAttributes")
                    ) or {}
                    allowed = (attrs.get("verb"), attrs.get("resource")) in {
                        ("get", "nodes"), ("list", "nodes"),
                        ("watch", "nodes"), ("patch", "nodes"),
                        ("list", "pods"), ("create", "events"),
                        ("get", "leases"), ("create", "leases"),
                        ("update", "leases"), ("delete", "leases"),
                    }
                    return self._json({"status": {"allowed": allowed}}, 201)
                if u.path.endswith("/events"):
                    return self._json(body, 201)
                return self._json({"kind": "Status", "code": 404}, 404)

            def do_PATCH(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                self._record(body)
                if urlparse(self.path).path == f"/api/v1/nodes/{NODE}":
                    for k, v in (body.get("metadata", {}).get("labels") or {}).items():
                        if v is None:
                            state.node["metadata"]["labels"].pop(k, None)
                        else:
                            state.node["metadata"]["labels"][k] = v
                    return self._json(state.node)
                return self._json({"kind": "Status", "code": 404}, 404)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    @property
    def url(self) -> str:
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def apiserver():
    srv = _MockApiserver()
    yield srv
    srv.close()


@pytest.fixture()
def client(apiserver):
    return RestKube(ClusterConfig(server=apiserver.url, token="sekret"))


def test_get_node_and_bearer_token(apiserver, client):
    node = client.get_node(NODE)
    assert node["metadata"]["name"] == NODE
    auth = apiserver.requests[-1]["headers"].get("Authorization")
    assert auth == "Bearer sekret"


def test_get_unknown_node_maps_to_kube_api_error(client):
    with pytest.raises(KubeApiError) as exc:
        client.get_node("ghost")
    assert exc.value.status == 404


def test_patch_node_labels_is_merge_patch(apiserver, client):
    client.patch_node_labels(NODE, {"a": "1", "gone": None})
    req = apiserver.requests[-1]
    assert req["method"] == "PATCH"
    assert req["headers"].get("Content-Type") == "application/merge-patch+json"
    # Only metadata.labels in the body — never a full read-modify-write of
    # the node object (reference bug, SURVEY.md §8.3).
    assert req["body"] == {"metadata": {"labels": {"a": "1", "gone": None}}}
    assert apiserver.node["metadata"]["labels"] == {"a": "1"}


def test_list_pods_passes_selectors(apiserver, client):
    pods = client.list_pods("ns", label_selector="app=x",
                            field_selector=f"spec.nodeName={NODE}")
    assert [p["metadata"]["name"] for p in pods] == ["p1"]
    q = apiserver.requests[-1]["query"]
    assert q["labelSelector"] == ["app=x"]
    assert q["fieldSelector"] == [f"spec.nodeName={NODE}"]


def test_list_nodes(client):
    nodes = client.list_nodes()
    assert [n["metadata"]["name"] for n in nodes] == [NODE]


def test_watch_streams_json_lines_until_server_close(apiserver, client):
    apiserver.watch_events = [
        {"type": "ADDED", "object": {"metadata": {"name": NODE,
                                                  "resourceVersion": "2"}}},
        {"type": "MODIFIED", "object": {"metadata": {"name": NODE,
                                                     "resourceVersion": "3"}}},
    ]
    events = list(client.watch_nodes(NODE, resource_version="1",
                                     timeout_seconds=5))
    assert [e.type for e in events] == ["ADDED", "MODIFIED"]
    assert events[-1].object["metadata"]["resourceVersion"] == "3"
    q = apiserver.requests[-1]["query"]
    assert q["fieldSelector"] == [f"metadata.name={NODE}"]
    assert q["timeoutSeconds"] == ["5"]
    assert q["resourceVersion"] == ["1"]


def test_watch_bad_frame_raises(apiserver):
    import io

    bad = RestKube(ClusterConfig(server=apiserver.url))
    bad._open = lambda *a, **kw: io.BytesIO(b"not-json\n")  # type: ignore[method-assign]
    with pytest.raises(KubeApiError):
        list(bad.watch_nodes(NODE))


def test_connection_refused_maps_to_kube_api_error():
    client = RestKube(
        ClusterConfig(server="http://127.0.0.1:1"), retry_attempts=1
    )
    with pytest.raises(KubeApiError) as exc:
        client.get_node(NODE)
    assert exc.value.status is None


def test_transient_5xx_is_retried():
    """One transient 503 on a non-watch verb must not fail the call
    (VERDICT r2 weak #8)."""
    import io
    import json as _json

    client = RestKube(
        ClusterConfig(server="http://x"), retry_attempts=3,
        retry_base_delay_s=0.01,
    )
    calls = {"n": 0}

    def flaky_open(method, path, query=None, body=None, content_type=None,
                   read_timeout=30.0):
        calls["n"] += 1
        if calls["n"] == 1:
            raise KubeApiError(503, "apiserver hiccup")
        return io.BytesIO(_json.dumps({"metadata": {"name": NODE}}).encode())

    client._open = flaky_open  # type: ignore[method-assign]
    assert client.get_node(NODE)["metadata"]["name"] == NODE
    assert calls["n"] == 2


def test_client_errors_are_not_retried():
    client = RestKube(
        ClusterConfig(server="http://x"), retry_attempts=3,
        retry_base_delay_s=0.01,
    )
    calls = {"n": 0}

    def not_found(method, path, query=None, body=None, content_type=None,
                  read_timeout=30.0):
        calls["n"] += 1
        raise KubeApiError(404, "no such node")

    client._open = not_found  # type: ignore[method-assign]
    with pytest.raises(KubeApiError):
        client.get_node(NODE)
    assert calls["n"] == 1  # a 404 will not improve with repetition


def test_self_subject_access_review(apiserver, client):
    """SSAR over real HTTP: allowed verbs come back True, others False,
    and the request carries the documented resourceAttributes shape."""
    assert client.self_subject_access_review("get", "nodes") is True
    assert client.self_subject_access_review("patch", "nodes") is True
    assert client.self_subject_access_review("delete", "nodes") is False
    assert client.self_subject_access_review(
        "list", "pods", namespace="tpu-operator"
    ) is True
    post = [r for r in apiserver.requests if r["method"] == "POST"][-1]
    attrs = post["body"]["spec"]["resourceAttributes"]
    assert attrs == {
        "verb": "list", "resource": "pods", "namespace": "tpu-operator"
    }


def test_create_event_posts_to_namespace(apiserver, client):
    body = {"reason": "CCModeApplied", "type": "Normal",
            "involvedObject": {"kind": "Node", "name": NODE}}
    client.create_event("tpu-operator", body)
    post = [r for r in apiserver.requests if r["method"] == "POST"][-1]
    assert post["path"] == "/api/v1/namespaces/tpu-operator/events"
    assert post["body"]["reason"] == "CCModeApplied"


def test_rbac_check_command(apiserver, tmp_path):
    """`tpu-cc-ctl rbac-check` end-to-end against the HTTP mock."""
    from tpu_cc_manager import ctl

    kubeconfig = tmp_path / "kc"
    kubeconfig.write_text(json.dumps({
        "clusters": [{"name": "m", "cluster": {"server": apiserver.url}}],
        "users": [{"name": "u", "user": {"token": "sekret"}}],
        "contexts": [{"name": "c",
                      "context": {"cluster": "m", "user": "u"}}],
        "current-context": "c",
    }))
    assert ctl.main(["--kubeconfig", str(kubeconfig), "rbac-check"]) == 0


def test_non_idempotent_verbs_are_never_retried():
    """The retry loop is gated on method in (GET, PATCH) in code, not by
    docstring convention (ADVICE r3): a future POST route must not inherit
    retry-after-ambiguous-failure, where the first attempt may have taken
    effect server-side."""
    client = RestKube(
        ClusterConfig(server="http://x"), retry_attempts=3,
        retry_base_delay_s=0.01,
    )
    calls = {"n": 0}

    def transient(method, path, query=None, body=None, content_type=None,
                  read_timeout=30.0):
        calls["n"] += 1
        raise KubeApiError(503, "ambiguous failure")

    client._open = transient  # type: ignore[method-assign]
    with pytest.raises(KubeApiError):
        client._request_json("POST", "/api/v1/namespaces/x/pods/y/eviction")
    assert calls["n"] == 1  # exactly one attempt despite retry_attempts=3

    # The same transient status IS retried for idempotent verbs.
    calls["n"] = 0
    with pytest.raises(KubeApiError):
        client.get_node(NODE)
    assert calls["n"] == 3
