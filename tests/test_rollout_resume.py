"""Crash-safe rollouts: lease fencing, checkpointed records, resume.

The acceptance bar (ISSUE 4): across seeded orchestrator deaths a
successor resumes from the persisted record and converges the pool with
ZERO double-bounced groups, and a deliberately stale (fenced-out)
orchestrator's write is refused. Both are asserted here, in tier-1.
"""

import threading

import pytest

from tpu_cc_manager.ccmanager import federation as federation_mod
from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.faults.plan import FaultPlan, OrchestratorKilled
from tpu_cc_manager.kubeclient.api import KubeApiError, node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    QUARANTINED_LABEL,
    STATE_FAILED,
)
from tpu_cc_manager.utils.metrics import MetricsRegistry

POOL = "pool=tpu"
NS = "tpu-operator"

#: The orchestrator's named crash points, spelled out as literals on
#: purpose (not imported): the cclint crash-point coverage checker keys
#: on these strings, and the runtime assertion in
#: test_successor_converges_after_kill_at_every_crash_point keeps the
#: list honest against rolling.CRASH_POINTS — a new point added to the
#: orchestrator fails lint until it is named here, and fails this suite
#: until the kill loop actually reaches it.
ROLLING_CRASH_POINTS = [
    "planned",
    "window-start",
    "mid-window",
    "awaited",
    "window-boundary",
    "slo-paused",
    "spare-prestaged",
    "federation-boundary",
    "parent-offline",
    "prestage-reserved",
    "prestage-armed",
    "prestage-invalidate",
    "failslow-vetted",
]


class OneClearVetter:
    """Duck-typed fail-slow vetter (the orchestrator only polls
    concluded()/suspects()) that concludes ONE benign "cleared" verdict
    — enough to open the failslow-vetted crash point on the first
    window without quarantining anything, so the exhaustive kill loop
    reaches the point while every node still converges exactly once.
    Non-draining like the real one: the successor re-reads the same
    list and must dedup via the record journal, not this stub."""

    def concluded(self):
        return [
            {"id": 1, "node": "node-0", "verdict": "cleared",
             "deviation": 0.97},
        ]

    def suspects(self):
        return set()


class ParentBlackoutKube:
    """A kube client wrapper for the PARENT STORE only: refuses its
    verbs while a seeded FaultPlan blackout window is open (advancing
    the injected clock per refusal so the offline grace elapses
    deterministically), then delegates. Only the parent plane goes
    dark — the regional pool keeps answering, which is exactly the
    partition the parent-offline crash point models."""

    def __init__(self, inner, plan, clk) -> None:
        self._inner = inner
        self._plan = plan
        self._clk = clk

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _gate(self, op: str) -> None:
        fault = self._plan.decide(op)
        if fault is not None:
            self._clk.advance(2.0)
            raise KubeApiError(None, f"parent blackout: {fault.describe()}")

    def get_lease(self, namespace, name):
        self._gate("get_lease")
        return self._inner.get_lease(namespace, name)

    def update_lease(self, namespace, name, lease):
        self._gate("update_lease")
        return self._inner.update_lease(namespace, name, lease)

    def create_lease(self, namespace, name, spec):
        self._gate("create_lease")
        return self._inner.create_lease(namespace, name, spec)


def one_breach_gate():
    """An SLO gate that reports breached on its FIRST poll and recovered
    ever after — the cheapest deterministic way to drive the orchestrator
    through its slo-paused crash point (pause -> recover -> resume)."""
    polls = {"n": 0}

    def gate() -> bool:
        polls["n"] += 1
        return polls["n"] == 1

    return gate


class Clock:
    """Injectable wall/monotonic clock for deterministic lease expiry."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def add_pool(fake, n=4, slice_map=None):
    for i in range(n):
        labels = {"pool": "tpu"}
        if slice_map and i in slice_map:
            labels["cloud.google.com/tpu-slice-id"] = slice_map[i]
        fake.add_node(f"node-{i}", labels)


def agent_simulator(fake, fail_nodes=(), converge_counts=None):
    """Emulate per-node agents, counting how often each node actually
    reconciles — the double-bounce detector."""
    in_flight = set()

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired and name not in in_flight:
            in_flight.add(name)
            if converge_counts is not None:
                converge_counts[name] = converge_counts.get(name, 0) + 1

            def fire():
                target = STATE_FAILED if name in fail_nodes else desired
                in_flight.discard(name)
                fake.set_node_label(name, CC_MODE_STATE_LABEL, target)

            t = threading.Timer(0.03, fire)
            t.daemon = True
            t.start()

    fake.add_patch_reactor(reactor)


def make_lease(fake, holder, clk, metrics=None, duration_s=30.0):
    return rollout_state.RolloutLease(
        fake, holder=holder, namespace=NS, duration_s=duration_s,
        metrics=metrics or MetricsRegistry(), wall=clk, clock=clk,
    )


def make_roller(fake, lease=None, resume_record=None, **kw):
    kw.setdefault("node_timeout_s", 5)
    kw.setdefault("poll_interval_s", 0.02)
    kw.setdefault("metrics", MetricsRegistry())
    return RollingReconfigurator(
        fake, POOL, lease=lease, resume_record=resume_record, **kw
    )


# ---------------------------------------------------------------------------
# Lease mechanics
# ---------------------------------------------------------------------------


def test_lease_is_single_writer(fake_kube):
    clk = Clock()
    a = make_lease(fake_kube, "orch-a", clk)
    assert a.acquire() is None
    assert a.generation == 1
    b = make_lease(fake_kube, "orch-b", clk)
    with pytest.raises(rollout_state.LeaseHeld):
        b.acquire()
    # Released cleanly -> immediately claimable, fencing token moves on.
    a.release()
    assert b.acquire() is None
    assert b.generation == 2


def test_expired_lease_is_taken_over_with_higher_generation(fake_kube):
    clk = Clock()
    a = make_lease(fake_kube, "orch-a", clk, duration_s=10)
    a.acquire()
    clk.advance(11)  # orch-a died; its hold lapsed
    b = make_lease(fake_kube, "orch-b", clk, duration_s=10)
    b.acquire()
    assert b.generation == 2
    assert b.valid


def test_stale_orchestrator_writes_are_refused(fake_kube):
    """The fencing property itself: a paused pre-crash orchestrator whose
    lease a successor took over gets RolloutFenced on every write, the
    refusal is counted, and the pool never sees the stale patch."""
    fake_kube.add_node("node-0", {"pool": "tpu"})
    wall = Clock()
    a_clock = Clock()  # orch-a's process clock FREEZES (suspended VM)
    metrics = MetricsRegistry()
    a = rollout_state.RolloutLease(
        fake_kube, holder="orch-a", namespace=NS, duration_s=10,
        metrics=metrics, wall=wall, clock=a_clock,
    )
    a.acquire()
    fenced_api = rollout_state.FencedKube(fake_kube, a, metrics=metrics)
    fenced_api.patch_node_labels("node-0", {CC_MODE_LABEL: "on"})  # live: ok
    wall.advance(11)  # real time passes; orch-a's clock does not
    b = make_lease(fake_kube, "orch-b", wall, duration_s=10)
    b.acquire()
    assert a.valid  # orch-a still BELIEVES it holds the lease...
    with pytest.raises(rollout_state.RolloutFenced):
        # ...but its next write CAS-discovers the takeover and is refused.
        a.checkpoint()
    with pytest.raises(rollout_state.RolloutFenced):
        fenced_api.patch_node_labels("node-0", {CC_MODE_LABEL: "off"})
    assert metrics.rollout_totals()["fenced_writes"] == 1
    # The stale patch never reached the pool.
    assert node_labels(fake_kube.get_node("node-0"))[CC_MODE_LABEL] == "on"


def test_lease_local_expiry_fences_without_apiserver(fake_kube):
    """A holder that slept past its own duration must refuse writes even
    BEFORE any CAS disproves it — the successor may already be flipping
    nodes."""
    clk = Clock()
    a = make_lease(fake_kube, "orch-a", clk, duration_s=10)
    a.acquire()
    clk.advance(11)
    assert not a.valid
    with pytest.raises(rollout_state.RolloutFenced):
        a.check()


def test_checkpoint_survives_own_ambiguous_write(fake_kube):
    """A 409 caused by our OWN earlier write landing (retry after an
    ambiguous failure) must re-adopt, not self-fence."""
    clk = Clock()
    a = make_lease(fake_kube, "orch-a", clk)
    a.acquire()
    # Simulate the ambiguity: the stored lease advanced (our write landed)
    # while our in-memory copy still has the old resourceVersion.
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    stored["spec"]["renewTime"] = rollout_state._now_rfc3339(clk)
    fake_kube.update_lease(NS, rollout_state.LEASE_NAME, stored)
    a.checkpoint()  # 409 -> re-read -> still our holder -> adopt
    assert a.valid


def test_checkpoint_conflicting_with_own_renew_still_persists_record(
    fake_kube,
):
    """The renewer-race case: a bare renew CASes the lease between the
    checkpointing thread's read and write. Resolving the 409 as
    'still ours' must RETRY the record write, not adopt-and-drop it — a
    dropped window-boundary checkpoint means a successor resumes from a
    stale record and re-bounces converged groups."""
    clk = Clock()
    a = make_lease(fake_kube, "orch-a", clk)
    a.acquire()
    import copy as _copy

    before_renew = _copy.deepcopy(a._lease)
    a.renew()  # what the renewer thread does: bumps the stored rv
    a._lease = before_renew  # checkpointing thread read BEFORE the renew
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[("g", ("node-0",))],
    )
    rec.note_group("g", ok=True, states={"node-0": "on"}, seconds=1.0)
    a.checkpoint(rec)  # 409 -> still ours -> RETRY this write
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    back = rollout_state.record_of_lease(stored)
    assert back is not None and back.done["g"]["ok"] is True
    # Same for the final clear: a conflicted clear_record must not leave
    # the stale record behind.
    before_renew = _copy.deepcopy(a._lease)
    a.renew()
    a._lease = before_renew
    a.checkpoint(clear_record=True)
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert rollout_state.record_of_lease(stored) is None


def test_record_round_trip():
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=3,
        groups=[("s1", ("n0", "n1")), ("node/n2", ("n2",))],
        failure_budget=2,
    )
    rec.note_group("s1", ok=True, states={"n0": "on", "n1": "on"}, seconds=1.5)
    rec.charge_budget(["n2"])
    back = rollout_state.RolloutRecord.from_json(rec.to_json())
    assert back.groups == rec.groups
    assert back.done["s1"]["ok"] is True
    assert back.budget_spend == ["n2"]
    assert back.failure_budget == 2
    with pytest.raises(rollout_state.RolloutFenced):
        rollout_state.RolloutRecord.from_json("{not json")


# ---------------------------------------------------------------------------
# Resumable rollouts
# ---------------------------------------------------------------------------


def test_fenced_rollout_checkpoints_and_stamps_generation(fake_kube):
    add_pool(fake_kube, 2)
    counts = {}
    agent_simulator(fake_kube, converge_counts=counts)
    clk = Clock()
    lease = make_lease(fake_kube, "orch-a", clk)
    lease.acquire()
    result = make_roller(fake_kube, lease=lease).rollout("on")
    assert result.ok and result.generation == 1
    for i in range(2):
        labels = node_labels(fake_kube.get_node(f"node-{i}"))
        assert labels[rollout_state.ROLLOUT_GEN_LABEL] == "1"
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    record = rollout_state.record_of_lease(stored)
    assert record.status == rollout_state.RECORD_COMPLETE
    assert len(record.done) == 2 and all(
        d["ok"] for d in record.done.values()
    )


#: The node `_run_crash_resume` pre-stages (state already at target, a
#: valid PRESTAGED record published) so the kill loop reaches the
#: spare-prestaged crash point: its surge flip converges with ZERO
#: reconciles during the rollout — the zero-bounce property itself.
PRESTAGED_SPARE = "node-2"


def _run_crash_resume(kill_at: int, points_seen: set | None = None):
    """One crash/resume cycle: orchestrator A is SIGKILLed at the
    ``kill_at``-th crash point (no cleanup, lease not released), successor
    B takes over after lease expiry and resumes from the checkpoint.
    Returns (killed, counts, result, fake). ``points_seen`` (when given)
    accumulates every crash-point NAME the hook observed — the coverage
    evidence the exhaustive test asserts against ROLLING_CRASH_POINTS."""
    import json as _json

    from tpu_cc_manager import labels as labels_mod

    fake = FakeKube()
    add_pool(fake, 4, slice_map={0: "s1", 1: "s1"})  # s1 + 2 singles
    counts: dict = {}
    agent_simulator(fake, converge_counts=counts)
    # node-2 is an already-pre-staged spare (armed ahead of the rollout,
    # the --prestage-only shape): the surge phase must detect it, journal
    # spare-prestaged, and flip it with NO reconcile — and a kill
    # anywhere around that must leave a successor that still converges
    # without ever bouncing it.
    fake.set_node_label(PRESTAGED_SPARE, CC_MODE_STATE_LABEL, "on")
    fake.patch_node_annotations(PRESTAGED_SPARE, {
        labels_mod.PRESTAGED_ANNOTATION: _json.dumps(
            {"mode": "on", "prior": "off", "seconds": 12.3, "ts": 0}
        ),
    })
    clk = Clock()
    metrics = MetricsRegistry()
    hook_calls = {"n": 0}

    def killer(point):
        if points_seen is not None:
            points_seen.add(point)
        if hook_calls["n"] == kill_at:
            raise OrchestratorKilled(point, hook_calls["n"])
        hook_calls["n"] += 1

    lease_a = make_lease(fake, "orch-a", clk, metrics=metrics, duration_s=30)
    lease_a.acquire()
    # Every run is a regional shard of a 2-region federation so the kill
    # loop reaches the federation-boundary crash point too — a kill
    # landing INSIDE a parent sync is the "shard dies mid-CAS" scenario,
    # and the successor must reconnect to the parent from the record.
    store = federation_mod.ParentStore(fake, namespace=NS)
    parent = store.initialize(
        federation_mod.ParentRecord.fresh("on", POOL, ["r1", "r2"]),
        resume=False,
    )
    # The shard's OWN parent-store client rides through a seeded
    # blackout window (the regional pool stays up): the attach and the
    # first boundary sync go dark — 3 retried refusals each, the
    # injected clock advancing 2 s per refusal past the 1 s grace — so
    # the SECOND exchange deterministically fires the offline edge and
    # the parent-offline crash point, and the one after that reconnects.
    pclk = Clock()
    blackout_plan = FaultPlan(seed=7, rate=0.0, watch_rate=0.0)
    blackout_plan.begin_blackout(calls=6)
    dark_store = federation_mod.ParentStore(
        ParentBlackoutKube(fake, blackout_plan, pclk), namespace=NS
    )
    fed_a = federation_mod.FederationGate(
        dark_store, "r1", metrics=metrics, offline_grace_s=1.0, clock=pclk,
    )
    fed_a.attach(parent)
    # Every run carries a one-breach SLO gate so the kill loop reaches
    # the slo-paused crash point too (pause at the first boundary,
    # recover on the next poll) — a kill landing INSIDE the pause is the
    # "orchestrator dies while latency-paused" scenario.
    # continuous_prestage carries the run through the capacity-ledger
    # crash points too (prestage-reserved / prestage-armed /
    # prestage-invalidate): the ledger tops up ahead of the wave, the
    # simulated agents never publish a PRESTAGED record for the armed
    # node, and the short prestage timeout degrades it back to the full
    # flip path — so every node still bounces exactly once.
    # Every run carries the one-clear stub vetter so the kill loop
    # reaches the failslow-vetted crash point too — a kill landing
    # between the journaled verdict and its act is the "orchestrator
    # dies mid-vetting" scenario, and the successor must resume the
    # SAME verdict from the record without double-acting it.
    vetter = OneClearVetter()
    acts: list[str] = []
    roller_a = make_roller(
        fake, lease=lease_a, crash_hook=killer, slo_gate=one_breach_gate(),
        surge=1, prestage=True, federation=fed_a,
        continuous_prestage=True, prestage_timeout_s=0.25,
        failslow_vetter=vetter,
        failslow_act=lambda node, entry: acts.append(str(entry.get("id"))),
    )
    killed = False
    try:
        result = roller_a.rollout("on")
    except OrchestratorKilled:
        killed = True
        # SIGKILL semantics: nothing released, nothing finalized.
        clk.advance(31)  # the dead orchestrator's lease lapses
        lease_b = make_lease(
            fake, "orch-b", clk, metrics=metrics, duration_s=30
        )
        record = lease_b.acquire()
        assert record is not None, "no resumable record after the kill"
        assert record.status == rollout_state.RECORD_IN_PROGRESS
        # The gate config survived the kill: the record stays
        # latency-gated and the successor re-arms it.
        assert record.slo_gate is not None
        # So did the federation attachment: the successor rebuilds its
        # parent gate from the record, exactly like ctl --resume.
        assert record.federation is not None
        fed_b = federation_mod.FederationGate.from_record_dict(
            fake, record.federation, metrics=metrics
        )
        roller_b = make_roller(
            fake, lease=lease_b, resume_record=record, metrics=metrics,
            slo_gate=one_breach_gate(),
            # What ctl does on resume: surge inherited from the record
            # (a resume never re-surges; stale taints are reclaimed).
            surge=record.surge, prestage=True, federation=fed_b,
            continuous_prestage=True, prestage_timeout_s=0.25,
            failslow_vetter=vetter,
            failslow_act=lambda node, entry: acts.append(
                str(entry.get("id"))
            ),
        )
        result = roller_b.rollout(record.mode)
        assert result.resumed is True
        assert result.generation == 2
        assert metrics.rollout_totals()["resumes"] == 1
    # Exactly-once acting across the kill: the stub's single verdict is
    # journaled in the record and acted ONCE, whether the kill landed
    # before, at, or after failslow-vetted (the non-draining stub keeps
    # offering id 1 to the successor; the journal must dedup it).
    assert acts == ["1"], f"verdict 1 acted {len(acts)} times: {acts}"
    return killed, counts, result, fake


def test_successor_converges_after_kill_at_every_crash_point():
    """The ISSUE's property test: kill the orchestrator at EVERY crash
    point (checkpoint boundaries, inside windows, between windows) in
    turn; the successor must converge the pool with each node bounced
    exactly once and no group dropped. Also the crash-point COVERAGE
    proof: the run must visit every declared point name, and the
    declared list must equal rolling.CRASH_POINTS — so a new point
    cannot land without this suite exercising it."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    assert set(ROLLING_CRASH_POINTS) == set(rolling_mod.CRASH_POINTS), (
        "ROLLING_CRASH_POINTS is out of date with rolling.CRASH_POINTS — "
        "update the list (the cclint coverage checker keys on it)"
    )
    points_seen: set = set()
    exhausted = False
    for kill_at in range(48):
        killed, counts, result, fake = _run_crash_resume(
            kill_at, points_seen=points_seen
        )
        assert result.ok, f"kill_at={kill_at}: successor did not converge"
        for i in range(4):
            name = f"node-{i}"
            labels = node_labels(fake.get_node(name))
            assert labels[CC_MODE_STATE_LABEL] == "on", f"kill_at={kill_at}"
            # The pre-staged spare converges with ZERO reconciles during
            # the rollout (its flip ran ahead of the wave) — everyone
            # else exactly once, crash or no crash.
            expected = 0 if name == PRESTAGED_SPARE else 1
            assert counts.get(name, 0) == expected, (
                f"kill_at={kill_at}: {name} reconciled "
                f"{counts.get(name, 0)} times (expected {expected} — "
                "no double bounce, no bounced spare)"
            )
        if not killed:
            exhausted = True  # ran past the last crash point: all covered
            break
    assert exhausted, "never exhausted the crash points; raise the range"
    assert points_seen == set(ROLLING_CRASH_POINTS), (
        f"kill loop never reached {set(ROLLING_CRASH_POINTS) - points_seen} "
        "— a declared crash point with no coverage is exactly what the "
        "crash-point lint exists to prevent"
    )


def test_resume_skips_done_groups_without_relisting_their_state(fake_kube):
    """A resumed record's converged groups are skipped on the record's
    say-so: no desired-label rewrite at the new generation, no await."""
    add_pool(fake_kube, 3)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    clk = Clock()
    lease_a = make_lease(fake_kube, "orch-a", clk, duration_s=30)
    lease_a.acquire()

    def kill_after_first_boundary(point):
        if point == "window-boundary":
            raise OrchestratorKilled(point, 0)

    with pytest.raises(OrchestratorKilled):
        make_roller(
            fake_kube, lease=lease_a, crash_hook=kill_after_first_boundary
        ).rollout("on")
    clk.advance(31)
    lease_b = make_lease(fake_kube, "orch-b", clk, duration_s=30)
    record = lease_b.acquire()
    assert set(record.done) == {"node/node-0"}
    result = make_roller(
        fake_kube, lease=lease_b, resume_record=record
    ).rollout("on")
    assert result.ok
    by_group = {g.group: g for g in result.groups}
    assert by_group["node/node-0"].skipped is True
    # node-0 kept generation 1: the successor never re-patched it.
    labels = node_labels(fake_kube.get_node("node-0"))
    assert labels[rollout_state.ROLLOUT_GEN_LABEL] == "1"
    assert node_labels(fake_kube.get_node("node-2"))[
        rollout_state.ROLLOUT_GEN_LABEL
    ] == "2"
    assert counts == {"node-0": 1, "node-1": 1, "node-2": 1}


def test_resume_preserves_failure_budget_spend(fake_kube):
    """Pre-crash failures still count: a node that failed under the dead
    orchestrator stays charged against --failure-budget in the successor,
    so one more bleeding node halts a resumed rollout that a fresh one
    would have accepted."""
    add_pool(fake_kube, 4)
    fails = {"node-1"}
    agent_simulator(fake_kube, fail_nodes=fails)
    clk = Clock()
    lease_a = make_lease(fake_kube, "orch-a", clk, duration_s=30)
    lease_a.acquire()
    first = make_roller(
        fake_kube, lease=lease_a, failure_budget=1
    ).rollout("on")
    assert first.ok is False  # halted on node-1's failure
    lease_a.release()  # keep the record (halted), release the hold

    # The operator fixes node-1 but ANOTHER node gets quarantined.
    fails.clear()
    fake_kube.set_node_label("node-3", QUARANTINED_LABEL, "true")

    lease_b = make_lease(fake_kube, "orch-b", clk, duration_s=30)
    record = lease_b.acquire()
    assert record is not None and record.budget_spend == ["node-1"]
    resumed = make_roller(
        fake_kube, lease=lease_b, resume_record=record, failure_budget=1
    ).rollout("on")
    # spend = pre-crash failure (node-1) + fresh quarantine (node-3) = 2 > 1.
    assert resumed.halted_reason == "failure-budget-exceeded"
    lease_b.release()

    # Control: WITHOUT the persisted spend the same pool passes the budget
    # (only node-3 is quarantined) — the halt above really came from the
    # pre-crash charge.
    lease_c = make_lease(fake_kube, "orch-c", clk, duration_s=30)
    lease_c.acquire()
    fresh = make_roller(
        fake_kube, lease=lease_c, failure_budget=1
    ).rollout("on")
    assert fresh.halted_reason is None


def test_resume_recomputes_quarantine_fresh(fake_kube):
    """Quarantined-node skips are recomputed at resume time: a node
    quarantined AFTER the crash is skipped even though the record
    predates its quarantine."""
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube)
    clk = Clock()
    lease_a = make_lease(fake_kube, "orch-a", clk, duration_s=30)
    lease_a.acquire()

    def kill_at_first_boundary(point):
        if point == "window-boundary":
            raise OrchestratorKilled(point, 0)

    with pytest.raises(OrchestratorKilled):
        make_roller(
            fake_kube, lease=lease_a, crash_hook=kill_at_first_boundary
        ).rollout("on")
    fake_kube.set_node_label("node-2", QUARANTINED_LABEL, "true")
    clk.advance(31)
    lease_b = make_lease(fake_kube, "orch-b", clk, duration_s=30)
    record = lease_b.acquire()
    result = make_roller(
        fake_kube, lease=lease_b, resume_record=record
    ).rollout("on")
    assert result.ok
    assert {g.group for g in result.groups} == {
        "node/node-0", "node/node-1"
    }
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("node-2"))


# ---------------------------------------------------------------------------
# Seeded chaos: orchestrator kills from the FaultPlan
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_seeded_orchestrator_kill_soak():
    """The FaultPlan kill mode end-to-end: seeded SIGKILLs land at crash
    points across successive orchestrators until the plan's kill budget
    runs dry; every successor resumes from the checkpoint and the pool
    converges with zero double-bounced groups. Same seed -> same kill
    schedule (the chaos reproducibility contract)."""
    fake = FakeKube()
    add_pool(fake, 6, slice_map={0: "s1", 1: "s1", 2: "s2", 3: "s2"})
    counts: dict = {}
    agent_simulator(fake, converge_counts=counts)
    clk = Clock()
    metrics = MetricsRegistry()
    plan = FaultPlan(seed=20260803, kill_rate=0.6, max_kills=3)

    result = None
    for attempt in range(16):
        lease = make_lease(
            fake, f"orch-{attempt}", clk, metrics=metrics, duration_s=30
        )
        record = lease.acquire()
        roller = make_roller(
            fake, lease=lease,
            resume_record=(
                record
                if record is not None
                and record.status == rollout_state.RECORD_IN_PROGRESS
                else None
            ),
            metrics=metrics, crash_hook=plan.decide_orchestrator_kill,
        )
        try:
            result = roller.rollout("slice")
            lease.release(clear_record=result.ok)
            break
        except OrchestratorKilled:
            clk.advance(31)  # SIGKILL: no release; wait out the lease
    assert result is not None and result.ok
    kills = [f for f in plan.injected if f.kind == "orch-kill"]
    assert kills, "seed produced no kills; pick a different seed"
    for i in range(6):
        assert counts.get(f"node-{i}") == 1, (
            f"node-{i} bounced {counts.get(f'node-{i}')} times under kills "
            f"at {[f.op for f in kills]}"
        )
    assert metrics.rollout_totals()["lease_transitions"] == len(kills) + 1
    assert metrics.rollout_totals()["resumes"] == len(kills)


@pytest.mark.chaos
def test_kill_schedule_is_seed_deterministic():
    """Same seed + same call sequence -> the kill lands at the same
    decision index; a different seed reshuffles it."""

    def schedule(seed):
        plan = FaultPlan(seed=seed, kill_rate=0.5, max_kills=2)
        out = []
        for i in range(40):
            try:
                plan.decide_orchestrator_kill(f"p{i}")
            except OrchestratorKilled as k:
                out.append((k.point, k.seq))
        return out

    assert schedule(7) == schedule(7)
    assert schedule(7), "seed 7 produced no kills in 40 points"


# ---------------------------------------------------------------------------
# ctl plumbing
# ---------------------------------------------------------------------------


def test_ctl_rollout_resume_and_status(fake_kube, capsys):
    """`ctl rollout` acquires the lease; a crashed run leaves a record
    that `ctl status` surfaces and a plain re-run auto-resumes."""
    import argparse

    from tpu_cc_manager import ctl

    add_pool(fake_kube, 2)
    agent_simulator(fake_kube)

    def ns(**kw):
        base = dict(
            selector=POOL, mode="on", max_unavailable=1, node_timeout=5.0,
            continue_on_failure=False, rollback_on_failure=False,
            failure_budget=None, resume=False, abort_rollout=False,
            no_lease=False, lease_duration=30.0, lease_namespace=NS,
        )
        base.update(kw)
        return argparse.Namespace(**base)

    # Seed a dead orchestrator's record + expired lease by hand.
    clk = Clock()
    lease = make_lease(fake_kube, "orch-dead", clk, duration_s=0.001)
    lease.acquire()
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[("node/node-0", ("node-0",)), ("node/node-1", ("node-1",))],
    )
    lease.checkpoint(rec)

    import os
    os.environ["CC_ROLLOUT_LEASE_NAMESPACE"] = NS
    try:
        rc = ctl.cmd_status(fake_kube, ns())
        out = capsys.readouterr().out
        assert rc == 0
        assert "ROLLOUT" in out and "orch-dead" in out
        assert "groups=0/2 done" in out and "EXPIRED (resumable)" in out

        import time as _time
        # cclint: test-sleep-ok(the 1ms lease TTL must lapse on the real clock)
        _time.sleep(0.01)
        rc = ctl.cmd_rollout(fake_kube, ns())
        out = capsys.readouterr().out
        assert rc == 0
        assert '"resumed": true' in out
        # Finished: the record is cleared, the lease released.
        stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
        assert rollout_state.record_of_lease(stored) is None
        assert (stored["spec"].get("holderIdentity") or "") == ""
        # A released, record-less leftover lease must NOT keep printing a
        # ROLLOUT header on every status run forever.
        rc = ctl.cmd_status(fake_kube, ns())
        assert rc == 0
        assert "ROLLOUT" not in capsys.readouterr().out

        # --abort on a released lease: record discarded, but the Lease
        # OBJECT (and its transitions counter) survives so the fencing
        # generation stays monotonic.
        rc = ctl.cmd_rollout(fake_kube, ns(abort_rollout=True))
        assert rc == 0
        stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
        assert (stored["spec"].get("holderIdentity") or "") == ""
        assert rollout_state.record_of_lease(stored) is None
        assert int(stored["spec"]["leaseTransitions"]) >= 2
    finally:
        os.environ.pop("CC_ROLLOUT_LEASE_NAMESPACE", None)


def test_ctl_rollout_refuses_concurrent_invocation(fake_kube, capsys):
    import argparse

    from tpu_cc_manager import ctl

    import time as _time

    add_pool(fake_kube, 1)
    # The live holder's renewTime must be fresh in REAL wall time: ctl's
    # own lease uses time.time to judge expiry.
    clk = Clock(_time.time())
    live = make_lease(fake_kube, "orch-live", clk, duration_s=3600)
    live.acquire()
    args = argparse.Namespace(
        selector=POOL, mode="on", max_unavailable=1, node_timeout=5.0,
        continue_on_failure=False, rollback_on_failure=False,
        failure_budget=None, resume=False, abort_rollout=False,
        no_lease=False, lease_duration=30.0, lease_namespace=NS,
    )
    assert ctl.cmd_rollout(fake_kube, args) == 1
    # The live holder's lease was untouched.
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert stored["spec"]["holderIdentity"] == "orch-live"


def test_pre_plan_budget_halt_leaves_no_resumable_record(fake_kube):
    """A fresh rollout halted by the budget BEFORE planning persisted
    nothing worth resuming: an empty-groups record would make a later
    --resume no-op with ok=true while no node was ever touched."""
    add_pool(fake_kube, 2)
    fake_kube.set_node_label("node-0", QUARANTINED_LABEL, "true")
    clk = Clock()
    lease = make_lease(fake_kube, "orch-a", clk)
    lease.acquire()
    result = make_roller(
        fake_kube, lease=lease, failure_budget=0
    ).rollout("on")
    assert result.halted_reason == "failure-budget-exceeded"
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert rollout_state.record_of_lease(stored) is None
    # The same halt on a RESUMED record keeps its (real) plan persisted.
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[("node/node-1", ("node-1",))],
    )
    halted = make_roller(
        fake_kube, lease=lease, resume_record=rec, failure_budget=0
    ).rollout("on")
    assert halted.halted_reason == "failure-budget-exceeded"
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    back = rollout_state.record_of_lease(stored)
    assert back is not None and back.groups == [("node/node-1", ("node-1",))]
    assert back.status == rollout_state.RECORD_HALTED


def test_resumed_halted_record_checkpoints_in_progress(fake_kube):
    """Resuming a halted record flips its persisted status back to
    in-progress, so a crash of the RESUMED run is itself auto-resumable
    (auto-resume only adopts in-progress records)."""
    add_pool(fake_kube, 3)
    fails = {"node-1"}
    agent_simulator(fake_kube, fail_nodes=fails)
    clk = Clock()
    lease_a = make_lease(fake_kube, "orch-a", clk)
    lease_a.acquire()
    first = make_roller(fake_kube, lease=lease_a).rollout("on")
    assert first.ok is False
    lease_a.release()
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert rollout_state.record_of_lease(stored).status == (
        rollout_state.RECORD_HALTED
    )
    # Operator fixes the node and resumes — but the resumed run is
    # killed mid-flight. The record it checkpointed must say
    # in-progress, not the stale halted.
    fails.clear()
    lease_b = make_lease(fake_kube, "orch-b", clk)
    record = lease_b.acquire()

    def kill_at_boundary(point):
        if point == "window-boundary":
            raise OrchestratorKilled(point, 0)

    with pytest.raises(OrchestratorKilled):
        make_roller(
            fake_kube, lease=lease_b, resume_record=record,
            crash_hook=kill_at_boundary,
        ).rollout("on")
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert rollout_state.record_of_lease(stored).status == (
        rollout_state.RECORD_IN_PROGRESS
    )


def test_corrupt_record_is_a_clean_ctl_error(fake_kube, capsys):
    """An unreadable checkpointed record must surface as a clean error
    pointing at --abort, not a RolloutFenced traceback."""
    import argparse

    from tpu_cc_manager import ctl

    add_pool(fake_kube, 1)
    clk = Clock()
    seed = make_lease(fake_kube, "orch-dead", clk, duration_s=0.001)
    seed.acquire()
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    stored["metadata"].setdefault("annotations", {})[
        rollout_state.RECORD_ANNOTATION
    ] = "{truncated"
    fake_kube.update_lease(NS, rollout_state.LEASE_NAME, stored)
    import time as _time
    # cclint: test-sleep-ok(the 1ms lease TTL must lapse on the real clock)
    _time.sleep(0.01)
    args = argparse.Namespace(
        selector=POOL, mode="on", max_unavailable=1, node_timeout=5.0,
        continue_on_failure=False, rollback_on_failure=False,
        failure_budget=None, resume=False, abort_rollout=False,
        no_lease=False, lease_duration=30.0, lease_namespace=NS,
    )
    assert ctl.cmd_rollout(fake_kube, args) == 1
    # --abort is the documented way out.
    args.abort_rollout = True
    assert ctl.cmd_rollout(fake_kube, args) == 0


def test_resume_restores_persisted_budget_and_concurrency(fake_kube, capsys):
    """A plain auto-resume must inherit the record's --failure-budget
    (and max-unavailable): the fleet circuit breaker — with its
    pre-crash spend — must not vanish because the re-run omitted the
    flag."""
    import argparse

    from tpu_cc_manager import ctl

    add_pool(fake_kube, 3)
    agent_simulator(fake_kube)
    clk = Clock()
    seed = make_lease(fake_kube, "orch-dead", clk, duration_s=0.001)
    seed.acquire()
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[(f"node/node-{i}", (f"node-{i}",)) for i in range(3)],
        budget_spend=["node-9a", "node-9b"],  # two pre-crash charges
        failure_budget=1, max_unavailable=2,
    )
    seed.checkpoint(rec)
    import time as _time
    # cclint: test-sleep-ok(the 1ms lease TTL must lapse on the real clock)
    _time.sleep(0.01)
    args = argparse.Namespace(
        selector=POOL, mode="on",
        max_unavailable=None,  # flags omitted on the re-run: the
        failure_budget=None,   # record's persisted settings must apply
        node_timeout=5.0,
        continue_on_failure=False, rollback_on_failure=False,
        resume=False, abort_rollout=False, no_lease=False,
        lease_duration=30.0, lease_namespace=NS,
    )
    import os
    os.environ["CC_ROLLOUT_LEASE_NAMESPACE"] = NS
    try:
        rc = ctl.cmd_rollout(fake_kube, args)
    finally:
        os.environ.pop("CC_ROLLOUT_LEASE_NAMESPACE", None)
    out = capsys.readouterr().out
    # spend (2 pre-crash charges) > restored budget 1 -> halted, even
    # though the re-run never passed --failure-budget.
    assert rc == 1
    assert '"halted": "failure-budget-exceeded"' in out


def test_resume_with_no_lease_is_rejected(fake_kube):
    import argparse

    from tpu_cc_manager import ctl

    args = argparse.Namespace(
        selector=POOL, mode="on", max_unavailable=1, node_timeout=5.0,
        continue_on_failure=False, rollback_on_failure=False,
        failure_budget=None, resume=True, abort_rollout=False,
        no_lease=True, lease_duration=30.0, lease_namespace=NS,
    )
    with pytest.raises(ValueError, match="--no-lease"):
        ctl.cmd_rollout(fake_kube, args)


def test_invalid_mode_does_not_strand_a_held_lease(fake_kube):
    """A typo'd --mode must fail BEFORE the lease is acquired; otherwise
    the corrected retry is refused with 'another rollout in progress'
    for a whole lease duration."""
    import argparse

    from tpu_cc_manager import ctl

    add_pool(fake_kube, 1)
    args = argparse.Namespace(
        selector=POOL, mode="onn", max_unavailable=1, node_timeout=5.0,
        continue_on_failure=False, rollback_on_failure=False,
        failure_budget=None, resume=False, abort_rollout=False,
        no_lease=False, lease_duration=30.0, lease_namespace=NS,
    )
    with pytest.raises(ValueError, match="invalid CC mode"):
        ctl.cmd_rollout(fake_kube, args)
    with pytest.raises(KubeApiError) as exc:
        fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert exc.value.status == 404  # the lease was never even created


def test_unfenced_fallback_on_lease_less_client(fake_kube, capsys):
    """A client without Lease support degrades to the legacy unfenced
    rollout instead of crashing."""
    import argparse

    from tpu_cc_manager import ctl

    add_pool(fake_kube, 1)
    agent_simulator(fake_kube)

    class NoLease(FakeKube):
        def get_lease(self, namespace, name):
            raise KubeApiError(None, self.LEASE_UNSUPPORTED)

    api = NoLease()
    api.add_node("node-0", {"pool": "tpu"})
    agent_simulator(api)
    args = argparse.Namespace(
        selector=POOL, mode="on", max_unavailable=1, node_timeout=5.0,
        continue_on_failure=False, rollback_on_failure=False,
        failure_budget=None, resume=False, abort_rollout=False,
        no_lease=False, lease_duration=30.0, lease_namespace=NS,
    )
    assert ctl.cmd_rollout(api, args) == 0
    assert '"ok": true' in capsys.readouterr().out


def test_abort_refuses_live_holder_without_force(fake_kube, capsys):
    """--abort against a LIVE holder is the split-brain foot-gun the
    lease exists to prevent: refused without --force; --force fences the
    wedged holder out (its next write is refused) and keeps the
    transitions counter monotonic."""
    import argparse
    import time as _time

    from tpu_cc_manager import ctl

    clk = Clock(_time.time())  # live in REAL wall time (ctl judges expiry)
    metrics = MetricsRegistry()
    wedged = rollout_state.RolloutLease(
        fake_kube, holder="wedged", namespace=NS, duration_s=3600,
        metrics=metrics, wall=clk, clock=clk,
    )
    wedged.acquire()

    def ns(**kw):
        base = dict(
            selector=POOL, mode=None, max_unavailable=None,
            node_timeout=5.0, continue_on_failure=False,
            rollback_on_failure=False, failure_budget=None, resume=False,
            abort_rollout=True, force=False, no_lease=False,
            lease_duration=30.0, lease_namespace=NS,
        )
        base.update(kw)
        return argparse.Namespace(**base)

    assert ctl.cmd_rollout(fake_kube, ns()) == 1  # refused
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert stored["spec"]["holderIdentity"] == "wedged"

    assert ctl.cmd_rollout(fake_kube, ns(force=True)) == 0
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    assert (stored["spec"].get("holderIdentity") or "") == ""
    # The fenced-out holder's next write is refused (CAS-discovers the
    # takeover), not silently applied.
    with pytest.raises(rollout_state.RolloutFenced):
        wedged.checkpoint()
    # Generation monotonicity across the abort: the next acquire
    # continues the counter instead of restarting at 1.
    nxt = make_lease(fake_kube, "orch-next", Clock())
    nxt.acquire()
    assert nxt.generation == 2


def test_checkpoint_retries_transients_over_internally_retrying_client(
    fake_kube,
):
    """Production sizing: RestKube never retries the lease PUT, so the
    checkpoint path must carry its own attempts even when
    caller_retry_attempts collapses to 1 (retries_internally=True). One
    connection blip must not abort an otherwise healthy rollout."""
    add_pool(fake_kube, 2)
    agent_simulator(fake_kube)
    fake_kube.retries_internally = True  # what RestKube advertises
    failures = {"n": 2}
    real_update = fake_kube.update_lease

    def flaky_update(ns_, name, lease):
        if failures["n"] > 0:
            failures["n"] -= 1
            raise KubeApiError(503, "transient blip")
        return real_update(ns_, name, lease)

    clk = Clock()
    lease = make_lease(fake_kube, "orch-a", clk)
    lease.acquire()
    fake_kube.update_lease = flaky_update
    try:
        result = make_roller(fake_kube, lease=lease).rollout("on")
    finally:
        fake_kube.update_lease = real_update
    assert result.ok is True
    assert failures["n"] == 0  # the blips were absorbed, not fatal


def test_rfc3339_never_emits_seven_digit_micros():
    """A wall clock within half a microsecond of the next second must
    carry into the integer second, not emit '.1000000Z' (a real
    apiserver's MicroTime parser rejects 7-digit fractions)."""
    stamp = rollout_state._now_rfc3339(lambda: 999.99999996)
    assert stamp == "1970-01-01T00:16:40.000000Z"
    back = rollout_state._parse_rfc3339(stamp)
    assert abs(back - 1000.0) < 1e-6


def test_status_honors_lease_namespace_flag(fake_kube, capsys):
    """A rollout run with --lease-namespace must stay visible to a
    status invocation passing the same flag."""
    import argparse

    from tpu_cc_manager import ctl

    add_pool(fake_kube, 1)
    clk = Clock()
    lease = rollout_state.RolloutLease(
        fake_kube, holder="orch-a", namespace="custom-ns", duration_s=30,
        metrics=MetricsRegistry(), wall=clk, clock=clk,
    )
    lease.acquire()
    lease.checkpoint(rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[("node/node-0", ("node-0",))],
    ))
    args = argparse.Namespace(selector=POOL, lease_namespace="custom-ns")
    assert ctl.cmd_status(fake_kube, args) == 0
    assert "ROLLOUT" in capsys.readouterr().out
    # Without the flag (default namespace) the lease is elsewhere: no line.
    args = argparse.Namespace(selector=POOL, lease_namespace=None)
    assert ctl.cmd_status(fake_kube, args) == 0
    assert "ROLLOUT" not in capsys.readouterr().out


def test_resume_redrive_of_rolled_back_groups(fake_kube):
    """Rollback amends the checkpoint: a group whose desired label was
    just REVERTED must not stay done:ok in the record, or a later
    --resume skips it and reports a half-flipped pool green."""
    add_pool(fake_kube, 2)
    for i in range(2):
        fake_kube.set_node_label(f"node-{i}", CC_MODE_LABEL, "off")
        fake_kube.set_node_label(f"node-{i}", CC_MODE_STATE_LABEL, "off")
    fails = {"node-1"}
    agent_simulator(fake_kube, fail_nodes=fails)
    clk = Clock()
    lease_a = make_lease(fake_kube, "orch-a", clk)
    lease_a.acquire()
    first = make_roller(
        fake_kube, lease=lease_a, rollback_on_failure=True
    ).rollout("on")
    assert first.ok is False
    assert [g.group for g in first.rolled_back] == ["node/node-0"]
    # node-0 was reverted: its desired label is back to 'off'.
    assert node_labels(fake_kube.get_node("node-0"))[CC_MODE_LABEL] == "off"
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    rec = rollout_state.record_of_lease(stored)
    assert "node/node-0" not in rec.done  # amended by the rollback
    lease_a.release()

    fails.clear()
    lease_b = make_lease(fake_kube, "orch-b", clk)
    record = lease_b.acquire()
    resumed = make_roller(
        fake_kube, lease=lease_b, resume_record=record
    ).rollout("on")
    assert resumed.ok is True
    # The rolled-back group was RE-DRIVEN, not skipped on stale say-so.
    by_group = {g.group: g for g in resumed.groups}
    assert by_group["node/node-0"].skipped is False
    for i in range(2):
        labels = node_labels(fake_kube.get_node(f"node-{i}"))
        assert labels[CC_MODE_LABEL] == "on"
        assert labels[CC_MODE_STATE_LABEL] == "on"


def test_crash_mid_rollback_leaves_no_false_done_claims(fake_kube):
    """The done entries of groups ABOUT to be reverted are popped and
    checkpointed BEFORE any revert write: an apiserver error (or kill)
    mid-rollback must not leave a durable record claiming reverted
    groups converged. The successor re-judges every popped group by the
    fresh desired==state check: not-yet-reverted groups skip without a
    bounce, reverted ones are re-driven."""
    add_pool(fake_kube, 3)
    for i in range(3):
        fake_kube.set_node_label(f"node-{i}", CC_MODE_LABEL, "off")
        fake_kube.set_node_label(f"node-{i}", CC_MODE_STATE_LABEL, "off")
    fails = {"node-2"}
    agent_simulator(fake_kube, fail_nodes=fails)
    clk = Clock()
    lease_a = make_lease(fake_kube, "orch-a", clk)
    lease_a.acquire()

    # Rollback reverts newest-first (node-1 then node-0); fail the
    # SECOND revert write so the rollback dies half-done.
    real_patch = fake_kube.patch_node_labels
    state = {"reverts": 0}

    def flaky_patch(name, labels, **kw):
        if labels.get(CC_MODE_LABEL) == "off":
            state["reverts"] += 1
            if state["reverts"] == 2:
                raise KubeApiError(None, "apiserver died mid-rollback")
        return real_patch(name, labels, **kw)

    fake_kube.patch_node_labels = flaky_patch
    try:
        with pytest.raises(KubeApiError):
            make_roller(
                fake_kube, lease=lease_a, rollback_on_failure=True
            ).rollout("on")
    finally:
        fake_kube.patch_node_labels = real_patch
    # The durable record no longer claims EITHER converged group done.
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    rec = rollout_state.record_of_lease(stored)
    assert "node/node-0" not in rec.done
    assert "node/node-1" not in rec.done
    # node-1 was reverted before the crash; node-0 never was.
    assert node_labels(fake_kube.get_node("node-1"))[CC_MODE_LABEL] == "off"
    assert node_labels(fake_kube.get_node("node-0"))[CC_MODE_LABEL] == "on"
    lease_a.release()

    fails.clear()
    lease_b = make_lease(fake_kube, "orch-b", clk)
    record = lease_b.acquire()
    resumed = make_roller(
        fake_kube, lease=lease_b, resume_record=record
    ).rollout("on")
    assert resumed.ok is True
    for i in range(3):
        labels = node_labels(fake_kube.get_node(f"node-{i}"))
        assert labels[CC_MODE_LABEL] == "on"
        assert labels[CC_MODE_STATE_LABEL] == "on"


# ---------------------------------------------------------------------------
# Sharded rollout waves (format v2) + pre-refactor record compatibility
# ---------------------------------------------------------------------------


def add_zoned_pool(fake, n=8, zones=2):
    """n single-host groups spread across zones (the wave partition key)."""
    for i in range(n):
        fake.add_node(
            f"node-{i}",
            {
                "pool": "tpu",
                "topology.kubernetes.io/zone": f"z{i % zones}",
            },
        )


def test_sharded_rollout_converges_with_zone_isolated_waves(fake_kube):
    add_zoned_pool(fake_kube, 8, zones=2)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    roller = make_roller(fake_kube, wave_shards=2, max_unavailable=1)
    result = roller.rollout("on")
    assert result.ok
    assert len(result.groups) == 8
    assert all(counts.get(f"node-{i}") == 1 for i in range(8)), counts


def test_sharded_rollout_rejects_rollback():
    with pytest.raises(ValueError):
        make_roller(FakeKube(), wave_shards=2, rollback_on_failure=True)


def test_sharded_record_is_v2_and_plain_resume_inherits_shards(fake_kube):
    add_zoned_pool(fake_kube, 4)
    agent_simulator(fake_kube)
    clk = Clock()
    lease = make_lease(fake_kube, "orch-a", clk)
    lease.acquire()
    roller = make_roller(fake_kube, lease=lease, wave_shards=2)
    assert roller.rollout("on").ok
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    import json as json_mod

    raw = stored["metadata"]["annotations"][rollout_state.RECORD_ANNOTATION]
    obj = json_mod.loads(raw)
    assert obj["version"] == rollout_state.RECORD_VERSION_NO_SURGE
    assert obj["wave_shards"] == 2
    record = rollout_state.RolloutRecord.from_json(raw)
    assert record.wave_shards == 2


def test_pre_refactor_v1_record_resumes_under_sharded_orchestrator(fake_kube):
    """A PR4-era record — no version field, no wave_shards — must resume
    under the sharded orchestrator: done groups skipped on the record's
    say-so, remaining groups re-driven across waves, every node bounced
    at most once."""
    add_zoned_pool(fake_kube, 6, zones=2)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    # Hand-build the v1 JSON exactly as the PR4 orchestrator serialized
    # it (to_json before this PR): no version, no wave_shards.
    groups = [[f"node/node-{i}", [f"node-{i}"]] for i in range(6)]
    v1 = {
        "mode": "on",
        "selector": POOL,
        "generation": 1,
        "groups": groups,
        "done": {
            "node/node-0": {
                "ok": True,
                "states": {"node-0": "on"},
                "seconds": 0.1,
                "skipped": False,
            }
        },
        "budget_spend": [],
        "max_unavailable": 1,
        "failure_budget": None,
        "status": "in-progress",
    }
    import json as json_mod

    record = rollout_state.RolloutRecord.from_json(json_mod.dumps(v1))
    assert record.wave_shards == 1  # v1 default
    # node-0 converged under the dead v1 orchestrator; reflect its state
    # (state first: the simulated agent must not read a desired/state gap
    # as a fresh transition to perform).
    fake_kube.set_node_label("node-0", CC_MODE_STATE_LABEL, "on")
    fake_kube.set_node_label("node-0", CC_MODE_LABEL, "on")
    clk = Clock()
    lease = make_lease(fake_kube, "orch-b", clk)
    lease.acquire()
    roller = make_roller(
        fake_kube, lease=lease, resume_record=record, wave_shards=3
    )
    result = roller.rollout("on")
    assert result.ok and result.resumed
    done_skipped = [g for g in result.groups if g.skipped]
    assert any(g.group == "node/node-0" for g in done_skipped)
    assert counts.get("node-0") is None, "done group was re-bounced"
    for i in range(1, 6):
        assert counts.get(f"node-{i}") == 1, counts
    # And the resumed record re-persists at v2 with the live shard count.
    stored = fake_kube.get_lease(NS, rollout_state.LEASE_NAME)
    obj = json_mod.loads(
        stored["metadata"]["annotations"][rollout_state.RECORD_ANNOTATION]
    )
    assert obj["version"] == rollout_state.RECORD_VERSION_NO_SURGE
    assert obj["wave_shards"] == 3


def test_newer_record_version_is_refused_loudly():
    import json as json_mod

    data = json_mod.dumps({
        "version": rollout_state.RECORD_VERSION + 1,
        "mode": "on", "selector": POOL, "generation": 1, "groups": [],
    })
    with pytest.raises(rollout_state.RolloutFenced):
        rollout_state.RolloutRecord.from_json(data)


def _run_sharded_crash_resume(kill_at: int):
    """Kill-at-every-crash-point, sharded edition: orchestrator A runs
    wave_shards=2 and dies at the ``kill_at``-th serialized crash point
    (sibling waves stop at their next boundary — a kill that lands a
    moment later); successor B resumes the same record sharded."""
    fake = FakeKube()
    add_zoned_pool(fake, 6, zones=2)
    counts: dict = {}
    agent_simulator(fake, converge_counts=counts)
    clk = Clock()
    metrics = MetricsRegistry()
    hook_calls = {"n": 0}

    def killer(point):
        if hook_calls["n"] == kill_at:
            raise OrchestratorKilled(point, hook_calls["n"])
        hook_calls["n"] += 1

    lease_a = make_lease(fake, "orch-a", clk, metrics=metrics, duration_s=30)
    lease_a.acquire()
    roller_a = make_roller(
        fake, lease=lease_a, crash_hook=killer, wave_shards=2
    )
    killed = False
    try:
        result = roller_a.rollout("on")
    except OrchestratorKilled:
        killed = True
        clk.advance(31)
        lease_b = make_lease(
            fake, "orch-b", clk, metrics=metrics, duration_s=30
        )
        record = lease_b.acquire()
        assert record is not None
        roller_b = make_roller(
            fake, lease=lease_b, resume_record=record, metrics=metrics,
            wave_shards=2,
        )
        result = roller_b.rollout(record.mode)
        assert result.resumed is True
    return killed, counts, result, fake


def test_sharded_successor_converges_after_kill_at_every_crash_point():
    """The sharded extension of the PR4 property test: across every
    serialized crash point of a 2-wave rollout, the successor converges
    with zero double-bounced nodes and zero dropped groups."""
    exhausted = False
    for kill_at in range(48):
        killed, counts, result, fake = _run_sharded_crash_resume(kill_at)
        assert result.ok, f"kill_at={kill_at}: successor did not converge"
        for i in range(6):
            name = f"node-{i}"
            labels = node_labels(fake.get_node(name))
            assert labels[CC_MODE_STATE_LABEL] == "on", f"kill_at={kill_at}"
            assert counts.get(name) == 1, (
                f"kill_at={kill_at}: {name} reconciled {counts.get(name)} "
                "times (must be exactly once)"
            )
        if not killed:
            exhausted = True
            break
    assert exhausted, "never exhausted the sharded crash points"


def test_informer_backed_rollout_matches_legacy_and_stops_listing(fake_kube):
    from tpu_cc_manager.ccmanager.informer import NodeInformer

    add_zoned_pool(fake_kube, 6)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    informer = NodeInformer(fake_kube, POOL).start()
    try:
        roller = make_roller(fake_kube, wave_shards=2, informer=informer)
        baseline_lists = fake_kube.request_counts.get("list", 0)
        result = roller.rollout("on")
        assert result.ok
        assert all(counts.get(f"node-{i}") == 1 for i in range(6))
        # The rollout itself performed ZERO listings: planning, awaits
        # and boundary checks all read the cache.
        assert fake_kube.request_counts.get("list", 0) == baseline_lists
    finally:
        informer.stop()


def test_informer_selector_mismatch_is_rejected(fake_kube):
    from tpu_cc_manager.ccmanager.informer import NodeInformer

    informer = NodeInformer(fake_kube, "pool=other")
    with pytest.raises(ValueError):
        make_roller(fake_kube, informer=informer)


# ---------------------------------------------------------------------------
# SLO-paced rollouts (ISSUE 14): the wave-boundary gate pauses, resumes,
# halts like the failure budget, and survives a crash + --resume.
# ---------------------------------------------------------------------------


def _flight(tmp_path, name="slo.jsonl"):
    from tpu_cc_manager.obs import flight as flight_mod

    return flight_mod.FlightRecorder(str(tmp_path / name))


def _flight_events(recorder):
    from tpu_cc_manager.obs import flight as flight_mod

    events, torn = flight_mod.read_events(recorder.path)
    assert torn == 0
    return [e["event"] for e in events]


def test_slo_breach_pauses_next_wave_and_recovery_resumes_it(
    fake_kube, tmp_path
):
    """Induced burn pauses the next wave within ONE boundary (slo-paused
    journaled before any further window opens), recovery resumes it
    (slo-resumed), and the rollout still converges every node."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    add_pool(fake_kube, 3)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    flight = _flight(tmp_path)
    metrics = MetricsRegistry()
    windows_opened = []

    breached = {"on": False}

    def gate():
        was = breached["on"]
        breached["on"] = False  # recover on the next poll
        return was

    def hook(point):
        if point == "window-boundary" and not windows_opened:
            # Burn starts right after the first window: the SECOND
            # window must pause before opening.
            windows_opened.append(point)
            breached["on"] = True

    roller = make_roller(
        fake_kube, crash_hook=hook, slo_gate=gate,
        slo_config=rolling_mod.SloGateConfig(max_burn_rate=2.0,
                                             max_pause_s=5.0),
        metrics=metrics, flight=flight,
    )
    result = roller.rollout("on")
    assert result.ok
    assert all(counts.get(f"node-{i}") == 1 for i in range(3))
    names = _flight_events(flight)
    # Pause journaled between the first window's close and the second's
    # open — within one boundary of the induced burn.
    assert "slo-paused" in names and "slo-resumed" in names
    first_close = names.index("window-close")
    assert names.index("slo-paused") > first_close
    second_open = [i for i, n in enumerate(names)
                   if n == "window-open"][1]
    assert names.index("slo-paused") < second_open
    assert metrics.rollout_totals()["slo_pauses"] == 1


def test_sustained_slo_burn_halts_like_the_failure_budget(
    fake_kube, tmp_path
):
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    add_pool(fake_kube, 3)
    agent_simulator(fake_kube)
    flight = _flight(tmp_path)
    roller = make_roller(
        fake_kube,
        slo_gate=lambda: True,  # never recovers
        slo_config=rolling_mod.SloGateConfig(max_pause_s=0.2),
        flight=flight,
    )
    result = roller.rollout("on")
    assert result.ok is False
    assert result.halted_reason == "slo-burn-exceeded"
    # Nothing was bounced: the gate held the FIRST window too.
    assert result.groups == []
    names = _flight_events(flight)
    assert "slo-paused" in names and "slo-halt" in names
    assert "window-open" not in names


def test_sharded_waves_all_stop_on_slo_halt(fake_kube):
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    add_zoned_pool(fake_kube, 6)
    agent_simulator(fake_kube)
    polls = {"n": 0}

    def gate():
        polls["n"] += 1
        return polls["n"] > 2  # healthy start, then sustained burn

    roller = make_roller(
        fake_kube, wave_shards=2, slo_gate=gate,
        slo_config=rolling_mod.SloGateConfig(max_pause_s=0.2),
    )
    result = roller.rollout("on")
    assert result.ok is False
    assert result.halted_reason == "slo-burn-exceeded"


def test_kill_while_slo_paused_resume_rearms_the_gate(fake_kube, tmp_path):
    """The chaos acceptance bar: SIGKILL the orchestrator AT the
    slo-paused crash point; the successor's --resume re-arms the gate
    from the record (config persisted, gate polled again) and converges
    with no double bounce."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    add_pool(fake_kube, 3)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    clk = Clock()
    metrics = MetricsRegistry()
    flight = _flight(tmp_path)
    cfg = rolling_mod.SloGateConfig(
        max_burn_rate=3.5, p99_target_ms=250.0, max_pause_s=5.0,
        source="http://serve-pool:9100/metrics",
    )

    def kill_at_pause(point):
        if point == "slo-paused":
            raise OrchestratorKilled(point, 0)

    lease_a = make_lease(fake_kube, "orch-a", clk, metrics=metrics,
                         duration_s=30)
    lease_a.acquire()
    roller_a = make_roller(
        fake_kube, lease=lease_a, crash_hook=kill_at_pause,
        slo_gate=one_breach_gate(), slo_config=cfg, flight=flight,
    )
    with pytest.raises(OrchestratorKilled):
        roller_a.rollout("on")

    clk.advance(31)
    lease_b = make_lease(fake_kube, "orch-b", clk, metrics=metrics,
                         duration_s=30)
    record = lease_b.acquire()
    assert record is not None
    # The full gate config survived the kill, exactly as configured.
    assert record.slo_gate == cfg.to_dict()
    rearmed = rolling_mod.SloGateConfig.from_dict(record.slo_gate)
    assert rearmed.max_burn_rate == 3.5
    assert rearmed.p99_target_ms == 250.0
    assert rearmed.source == "http://serve-pool:9100/metrics"
    gate_b = one_breach_gate()
    roller_b = make_roller(
        fake_kube, lease=lease_b, resume_record=record, metrics=metrics,
        slo_gate=gate_b, slo_config=rearmed, flight=flight,
    )
    result = roller_b.rollout(record.mode)
    assert result.ok and result.resumed
    assert all(counts.get(f"node-{i}") == 1 for i in range(3)), counts
    # The successor checkpointed the gate back into its own record
    # lineage AND actually paused on it (its one-breach gate fired).
    assert metrics.rollout_totals()["slo_pauses"] >= 1
    names = _flight_events(flight)
    assert names.count("slo-paused") >= 2  # one per orchestrator


def test_slo_gate_failure_reads_not_breached(fake_kube):
    """A gate that RAISES must not wedge the rollout: fail-open, logged,
    rollout proceeds (the failure budget still guards real damage)."""
    add_pool(fake_kube, 2)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)

    def broken_gate():
        raise RuntimeError("scrape endpoint died")

    result = make_roller(fake_kube, slo_gate=broken_gate).rollout("on")
    assert result.ok
    assert all(counts.get(f"node-{i}") == 1 for i in range(2))


def test_metrics_gate_judges_scraped_exposition():
    """ctl's remote gate: breached/not-breached judged from a scraped
    /metrics payload via obs/slo.py's parser — the same nearest-rank
    gauges the serving pool exports."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    healthy = (
        'tpu_cc_serve_slo_p99_seconds{window="5"} 0.050000\n'
        'tpu_cc_serve_error_budget_burn{window="5"} 0.200000\n'
    )
    burning = (
        'tpu_cc_serve_slo_p99_seconds{window="5"} 0.900000\n'
        'tpu_cc_serve_error_budget_burn{window="5"} 14.000000\n'
    )
    payload = {"text": healthy}
    cfg = rolling_mod.SloGateConfig(
        max_burn_rate=1.0, p99_target_ms=500.0,
        source="http://pool:9100/metrics",
    )
    gate = rolling_mod.metrics_gate(cfg, fetch=lambda url: payload["text"])
    assert gate() is False
    payload["text"] = burning
    assert gate() is True
    # p99 target alone trips it too (burn below budget).
    payload["text"] = (
        'tpu_cc_serve_slo_p99_seconds{window="5"} 0.900000\n'
        'tpu_cc_serve_error_budget_burn{window="5"} 0.100000\n'
    )
    assert gate() is True
    # A dead scrape endpoint fails OPEN (not breached, logged).
    def dead(url):
        raise OSError("connection refused")

    gate2 = rolling_mod.metrics_gate(cfg, fetch=dead)
    assert gate2() is False
    # An empty scrape (pool exports no SLO yet) is not evidence either.
    payload["text"] = ""
    assert gate() is False


def test_library_resume_of_gated_record_never_proceeds_ungated(fake_kube):
    """A latency-gated record resumed WITHOUT a gate callable must not
    bounce the pool at full speed: with a persisted metrics source the
    gate is rebuilt (fail-open on a dead endpoint, loudly); without one
    the resume is refused."""
    from tpu_cc_manager.ccmanager import rolling as rolling_mod

    add_pool(fake_kube, 2)
    counts: dict = {}
    agent_simulator(fake_kube, converge_counts=counts)
    clk = Clock()

    def run_gated_then_crash(cfg):
        lease = make_lease(fake_kube, "orch-a", clk, duration_s=30)
        lease.acquire()
        roller = make_roller(
            fake_kube, lease=lease,
            crash_hook=lambda p: (_ for _ in ()).throw(
                OrchestratorKilled(p, 0)
            ) if p == "planned" else None,
            slo_gate=lambda: False, slo_config=cfg,
        )
        with pytest.raises(OrchestratorKilled):
            roller.rollout("on")
        clk.advance(31)
        lease_b = make_lease(fake_kube, "orch-b", clk, duration_s=30)
        return lease_b, lease_b.acquire()

    # Sourceless persisted gate (in-process evaluator): refuse.
    lease_b, record = run_gated_then_crash(
        rolling_mod.SloGateConfig(max_pause_s=7.0)
    )
    roller_b = make_roller(fake_kube, lease=lease_b, resume_record=record)
    with pytest.raises(ValueError, match="latency-gated"):
        roller_b.rollout(record.mode)
    lease_b.release(clear_record=True)

    # Persisted source: the remote gate is rebuilt and the rollout
    # converges (the dead endpoint reads NOT breached, fail-open).
    lease_c, record_c = run_gated_then_crash(
        rolling_mod.SloGateConfig(
            max_pause_s=7.0, source="http://127.0.0.1:1/metrics",
        )
    )
    roller_c = make_roller(fake_kube, lease=lease_c, resume_record=record_c)
    result = roller_c.rollout(record_c.mode)
    assert result.ok
    assert roller_c.slo_gate is not None
    assert roller_c.slo_config.max_pause_s == 7.0  # rehydrated, not default
    assert all(counts.get(f"node-{i}") == 1 for i in range(2)), counts
