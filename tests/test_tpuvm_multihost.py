"""Two REAL TpuVmBackend agents through the slice barrier (VERDICT next #7).

Multi-host flows previously ran only on FakeTpuBackend, so tpuvm's
synthesized-chip path, state-dir persistence, systemd cross-checks and
per-host signed evidence never met the barrier. Here two TpuVmBackend
instances — worker 0 and worker 1 of one v5p-16 slice, each with its own
injected metadata server (accelerator type, worker number, slice id, and a
locally-minted RS256 instance-identity JWT), its own state dir, and fake
systemd show/reset commands backed by a monotonic activation-stamp counter
— drive a committed ``slice`` mode through the real CCManager apply path,
and pool attestation then re-verifies BOTH hosts' signed quotes against
the local JWKS (no fake-platform admission).
"""

from __future__ import annotations

import base64
import hashlib
import json
import stat
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.multislice import verify_pool_attestation
from tpu_cc_manager.ccmanager.slicecoord import (
    SLICE_COMMIT_LABEL,
    SLICE_STAGED_LABEL,
)
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import CC_MODE_STATE_LABEL, MODE_SLICE, SLICE_ID_LABEL
from tpu_cc_manager.tpudev import jwks
from tpu_cc_manager.tpudev.tpuvm import TpuVmBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

ACCEL = "v5p-16"  # 8 chips, 2 hosts x 4 chips
SLICE_ID = "it-slice"

# A fixed 2048-bit RSA test keypair (generated once, committed) so this
# test needs NO optional crypto dependency: the repo's verifier
# (tpudev/jwks.py) is pure stdlib, and SIGNING with a known key is just
# EMSA-PKCS1-v1_5 padding + modular exponentiation. Test key only — the
# private exponent is public by construction.
RSA_N = int(
    "72a3234d9582f0f9ece614d82355b4f70f1ae0adc662a593918cb1e46502836d"
    "ec62f57629191ca35764fe0b81787b8a7db54cf6fbecc28e5c6aadc6790f5c38"
    "c835f3715cc4eb9d1bada143b48e439fb1714248acc3dd930e454707b2248ecc"
    "bb4aadfe34982bd0468c0fe5f2a4c65aa4b619f81368e36aee7c53356fc8b379"
    "cd93f75de0f7ec19ee2ab58e8d6793cc8781c69c021be70446ad9aa51fe04d71"
    "80549605148a2802017457df5e86b376657868be29f0da587c826cc442a50a42"
    "5cc16ab6e2c070307a55629ecc6ccd5d1a6f8eab6f1f255eb59c7992a26ce64f"
    "03ee8fa477bad29f3027935b22c195caee29674cf828969736b5d0ea911e3e89",
    16,
)
RSA_E = 65537
RSA_D = int(
    "38443864b138c6dc74d96d6bb4d431717e197c23ef16a61c6b393a6b56e4c7eb"
    "a135e532ecf3256a4ad0081d4f9bfa4f3c6a4b6f82b16fc0fe3d6233e36195ab"
    "4d21a5ee8351283041d09431ae2291b08520891f30a526513294f04b27b5e7dd"
    "37246d8832fa69aedda18b801afba35c04325946b908276f69c4ddf6817a6a14"
    "788b99492fb4500169717d463ceb26be71540b2e25a92205f23598b4d736accd"
    "d88e06b7a6e01a65529f689a268f5f76eefb01ec981fd9e5bea64b95b3689dd1"
    "e60d27c47ca95d7e56c1562d2e72edd167d3e83d6ee79a87a7b560a56d9befa1"
    "034244dce796e49206cfe15422b89c64c58f0927ac5038c6a7944c84781f0501",
    16,
)
# SHA-256 DigestInfo prefix (RFC 8017 §9.2), same constant jwks.py embeds.
_SHA256_DIGESTINFO = bytes.fromhex(
    "3031300d060960864801650304020105000420"
)


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _int_bytes(n: int) -> bytes:
    return n.to_bytes((n.bit_length() + 7) // 8, "big")


@pytest.fixture(scope="module")
def keyset() -> dict:
    return {"keys": [{
        "kty": "RSA", "kid": "it-key", "alg": "RS256", "use": "sig",
        "n": _b64url(_int_bytes(RSA_N)), "e": _b64url(_int_bytes(RSA_E)),
    }]}


def _rs256_sign(message: bytes) -> bytes:
    k = (RSA_N.bit_length() + 7) // 8
    t = _SHA256_DIGESTINFO + hashlib.sha256(message).digest()
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    return pow(int.from_bytes(em, "big"), RSA_D, RSA_N).to_bytes(k, "big")


def mint_jwt(audience: str) -> str:
    """A GCE-shaped instance-identity JWT: Google issuer, caller-chosen
    audience (the nonce binding), RS256 over the fixed test key."""
    header = {"alg": "RS256", "kid": "it-key", "typ": "JWT"}
    claims = {
        "iss": "https://accounts.google.com",
        "aud": audience,
        "sub": "1234567890",
        "iat": int(time.time()),
        "exp": int(time.time()) + 3600,
    }

    def seg(obj) -> str:
        return _b64url(json.dumps(obj).encode())

    signing_input = f"{seg(header)}.{seg(claims)}"
    return f"{signing_input}.{_b64url(_rs256_sign(signing_input.encode()))}"


def start_metadata_server(worker: int):
    """An injected GCE metadata server for ONE host: identity is per-server,
    not per-env-var, so two backends can coexist in one process."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            u = urlparse(self.path)
            answers = {
                "/computeMetadata/v1/instance/attributes/accelerator-type":
                    ACCEL,
                "/computeMetadata/v1/instance/attributes/agent-worker-number":
                    str(worker),
                "/computeMetadata/v1/instance/attributes/tpu-env-slice-id":
                    SLICE_ID,
                "/computeMetadata/v1/instance/id": f"metal-{worker}",
            }
            if u.path in answers:
                body = answers[u.path].encode()
            elif u.path == (
                "/computeMetadata/v1/instance/service-accounts/default/identity"
            ):
                audience = parse_qs(u.query).get("audience", [""])[0]
                body = mint_jwt(audience).encode()
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def write_script(path, content: str) -> str:
    path.write_text("#!/bin/sh\n" + content)
    path.chmod(path.stat().st_mode | stat.S_IXUSR)
    return str(path)


def make_host(tmp_path, worker: int, shared_runtime_dir, guest_dev):
    """One host's TpuVmBackend: own state dir, own metadata identity, own
    systemd counter; SHARED measured runtime files (equal digests) and
    confidential-guest device node."""
    hostdir = tmp_path / f"host{worker}"
    hostdir.mkdir()
    devdir = hostdir / "dev"
    devdir.mkdir()
    for i in range(4):
        (devdir / f"accel{i}").touch()
    # The activation-stamp ground truth: `show` reads the counter, `reset`
    # bumps it — so a reset provably advances the stamp and later queries
    # see a stable post-restart value (no false external-restart reports).
    ctr = hostdir / "stamp"
    ctr.write_text("1\n")
    show = write_script(
        hostdir / "show.sh",
        f'c=$(cat {ctr} 2>/dev/null || echo 1)\n'
        'echo "ActiveState=active"\n'
        'echo "ActiveEnterTimestampMonotonic=$c"\n',
    )
    reset = write_script(
        hostdir / "reset.sh",
        f'c=$(cat {ctr} 2>/dev/null || echo 1)\n'
        f'echo $((c+1)) > {ctr}\n',
    )
    server = start_metadata_server(worker)
    backend = TpuVmBackend(
        state_dir=str(hostdir / "state"),
        reset_cmd=[reset],
        show_cmd=[show],
        metadata_url=(
            f"http://127.0.0.1:{server.server_address[1]}/computeMetadata/v1"
        ),
        device_glob=str(devdir / "accel*"),
        measure_globs=[str(shared_runtime_dir / "*.so")],
        tsm_root=str(hostdir / "no-tsm"),  # absent -> no TSM claim
        cc_guest_devices=(str(guest_dev),),
    )
    return backend, server


def test_two_tpuvm_agents_commit_slice_mode_with_verified_pool_attestation(
    fake_kube, tmp_path, monkeypatch, keyset,
):
    jwks_file = tmp_path / "jwks.json"
    jwks_file.write_text(json.dumps(keyset))
    monkeypatch.setenv(jwks.JWKS_FILE_ENV, str(jwks_file))
    for var in ("TPU_ACCELERATOR_TYPE", "TPU_WORKER_ID", "TPU_SLICE_ID",
                "CC_RUNTIME_SHOW_CMD", "CC_HOST_ROOT", "CC_RUNTIME_ENV_FILE",
                "CC_RUNTIME_HEALTH_PORT"):
        monkeypatch.delenv(var, raising=False)

    # The runtime identity both hosts measure: same files, same hashes —
    # pool attestation's digest-equality check has real content to compare.
    runtime_dir = tmp_path / "runtime"
    runtime_dir.mkdir()
    (runtime_dir / "libtpu.so").write_bytes(b"identical runtime bytes")
    guest_dev = tmp_path / "tdx_guest"
    guest_dev.touch()

    servers = []
    mgrs = []
    backends = []
    try:
        for worker in range(2):
            backend, server = make_host(
                tmp_path, worker, runtime_dir, guest_dev
            )
            servers.append(server)
            backends.append(backend)
            topo = backend.discover()
            assert topo.num_hosts == 2 and topo.host_index == worker
            assert topo.slice_id == SLICE_ID
            assert all(c.slice_cc_supported for c in topo.chips)
            fake_kube.add_node(f"it-node-{worker}", {"pool": "it"})
            mgrs.append(CCManager(
                api=fake_kube,
                backend=backend,
                node_name=f"it-node-{worker}",
                evict_components=False,
                smoke_workload="none",
                metrics=MetricsRegistry(),
                slice_barrier_timeout_s=60.0,
                slice_barrier_poll_interval_s=0.02,
            ))
        assert all(not m.allow_fake_quotes for m in mgrs)  # production path

        results: dict[int, bool] = {}

        def drive(i: int) -> None:
            results[i] = mgrs[i].set_cc_mode(MODE_SLICE)

        threads = [
            threading.Thread(target=drive, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert results == {0: True, 1: True}

        for worker in range(2):
            labels = node_labels(fake_kube.get_node(f"it-node-{worker}"))
            assert labels[CC_MODE_STATE_LABEL] == MODE_SLICE
            assert labels[SLICE_ID_LABEL] == SLICE_ID
            assert SLICE_STAGED_LABEL not in labels
            assert SLICE_COMMIT_LABEL not in labels
            # The committed mode survives in the host's state dir.
            topo = backends[worker].discover()
            assert all(
                backends[worker].query_cc_mode(c) == MODE_SLICE
                for c in topo.chips
            )

        # Pool attestation re-verifies BOTH hosts' platform-signed quotes
        # (RS256 against the local JWKS; allow_fake stays False — a fake
        # quote here would be a forgery).
        slices = verify_pool_attestation(
            fake_kube, "pool=it", MODE_SLICE, expected_slices=1,
            allow_fake=False,
        )
        assert sorted(slices[SLICE_ID]["nodes"]) == ["it-node-0", "it-node-1"]
        assert not slices[SLICE_ID]["missing"]
        assert slices[SLICE_ID]["digest"] not in (None, "MIXED")
    finally:
        for server in servers:
            server.shutdown()
