"""Smoke workloads on the virtual CPU mesh (smoke/)."""

import pytest

from tpu_cc_manager.smoke import runner


def test_matmul_smoke_passes():
    result = runner.run_workload("matmul", size=256, iters=1)
    assert result["ok"] is True
    assert result["workload"] == "matmul"
    assert result["devices"] >= 1
    # Throughput is None when differential timing is swamped by host noise
    # (timing_valid=False); correctness must hold either way.
    if result["timing_valid"]:
        assert result["tflops"] > 0


def test_matmul_uses_all_virtual_devices():
    import jax

    result = runner.run_workload("matmul", size=256, iters=1)
    assert result["devices"] == len(jax.devices())


def test_matmul_pallas_kernel_mode():
    result = runner.run_workload("matmul", size=256, iters=1, kernel="pallas")
    assert result["ok"] is True
    assert result["kernel"] == "pallas"
    assert result["devices"] == 1


def test_llama_smoke_passes():
    result = runner.run_workload("llama", batch=2, prompt_len=8, decode_len=4)
    assert result["ok"] is True
    assert result["oracle_ok"] is True
    assert result["transcript_ok"] is True
    if result["timing_valid"]:
        assert result["tokens_per_sec"] > 0


def test_llama_transcript_oracle_spans_32_decode_positions():
    """The decode oracle covers the full ≥32-token greedy chain, every
    position checked against the no-cache forward (VERDICT r2 item 8)."""
    result = runner.run_workload("llama", batch=2, prompt_len=8, decode_len=32)
    assert result["ok"] is True
    assert result["transcript_ok"] is True
    assert result["transcript_positions"] >= 32


def test_llama_oracle_catches_cache_position_off_by_one():
    """A seeded off-by-one in the cached-decode position MUST trip the
    oracle — proof the smoke can catch the bug class it exists for."""
    from tpu_cc_manager.smoke import llama_infer
    from tpu_cc_manager.smoke.runner import SmokeError

    with pytest.raises(SmokeError):
        # runner.run_workload raises when the workload reports not-ok.
        runner.run_workload(
            "llama", batch=2, prompt_len=8, decode_len=16,
            cache_position_offset=1,
        )
    # And directly: the transcript oracle specifically is what fails.
    result = llama_infer.run(
        batch=2, prompt_len=8, decode_len=16, cache_position_offset=1
    )
    assert result["ok"] is False


def test_profile_dir_captures_a_trace(tmp_path):
    """--profile-dir wraps the workload in a JAX profiler trace; the trace
    artifacts must actually land on disk."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.smoke", "--workload", "matmul",
         "--size", "256", "--profile-dir", str(tmp_path / "trace")],
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-400:]
    assert list((tmp_path / "trace").rglob("*.xplane.pb")), "no trace written"


def test_resnet_smoke_passes():
    result = runner.run_workload("resnet", steps=3)
    assert result["ok"] is True
    assert result["loss_last"] < result["loss_first"]


def test_unknown_workload_rejected():
    with pytest.raises(runner.SmokeError):
        runner.run_workload("does-not-exist")


def test_subprocess_runner_matmul():
    # The manager's production path: workload in a child process so the agent
    # never holds the TPU.
    result = runner.run_workload_subprocess("matmul", timeout_s=300)
    assert result["ok"] is True


def test_llama_size_table_includes_all_family_members():
    from tpu_cc_manager.smoke.llama_infer import _pick_config

    for size in ("tiny", "500m", "llama3.2-1b", "llama3.2-3b", "llama2-7b",
                 "llama3-8b", "llama3.1-8b"):
        got, cfg = _pick_config(size)
        assert got == size
        import jax.numpy as jnp

        assert cfg.param_dtype == jnp.bfloat16  # inference storage dtype
    _, cfg31 = _pick_config("llama3.1-8b")
    assert cfg31.rope_scaling == (8.0, 1.0, 4.0, 8192)
    with pytest.raises(ValueError):
        _pick_config("gpt5")


def test_llama32_configs_fit_v5e_single_chip():
    """The v5e-1 workload-scale evidence path (VERDICT r4 item 5): 3.2-3B
    is the largest family member whose bf16 weights leave real cache/
    activation headroom on a 16 GB chip; 7B is marginal and 3-8B is over."""
    from tpu_cc_manager.models.llama import LlamaConfig

    GiB = 1024**3
    p1 = LlamaConfig.llama3_2_1b().param_count()
    p3 = LlamaConfig.llama3_2_3b().param_count()
    assert 1.0e9 < p1 < 1.6e9
    assert 3.0e9 < p3 < 3.7e9
    assert 2 * p3 < 8 * GiB            # ≥ 8 GiB headroom on 16 GiB v5e
    p7 = LlamaConfig.llama2_7b().param_count()
    assert 2 * p7 > 12 * GiB           # 7B: weights alone ~13.5 GB
    p8 = LlamaConfig.llama3_8b().param_count()
    assert 2 * p8 > 14 * GiB           # 8B + 128k vocab: past the chip


def test_llama_smoke_reports_prefill_throughput():
    """Prefill (MXU-bound) rides along with decode (bandwidth-bound): both
    halves of inference utilization are in one artifact."""
    result = runner.run_workload("llama", batch=2, prompt_len=8, decode_len=4)
    assert result["prefill_tokens_per_sec"] is None or (
        result["prefill_tokens_per_sec"] > 0
    )
    assert "prefill_mfu" in result


def test_resnet_batch_must_divide_devices():
    """--batch tuning values that don't shard evenly fail as structured
    config errors, not raw JAX sharding tracebacks (8 virtual devices via
    conftest)."""
    from tpu_cc_manager.smoke.resnet_train import run
    from tpu_cc_manager.smoke.runner import SmokeConfigError

    with pytest.raises(SmokeConfigError, match="divide evenly"):
        run(size="tiny", batch=100)  # 100 % 8 != 0
