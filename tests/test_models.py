"""Model correctness on the virtual CPU mesh (models/llama.py, resnet.py)."""

import jax
import jax.numpy as jnp
import pytest

from tpu_cc_manager.models.llama import LlamaConfig, LlamaModel
from tpu_cc_manager.models.resnet import ResNetTiny


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, cfg.vocab_size)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, tokens, variables


def test_forward_shapes_and_finiteness(tiny_llama):
    cfg, model, tokens, variables = tiny_llama
    logits, cache = model.apply(variables, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())
    assert cache is None


def test_param_count_matches_analytic(tiny_llama):
    cfg, _, _, variables = tiny_llama
    actual = sum(x.size for x in jax.tree.leaves(variables))
    assert actual == cfg.param_count()


def test_decode_matches_full_forward(tiny_llama):
    """KV-cache decode must reproduce the no-cache forward exactly — the
    indexing/mask/RoPE oracle."""
    cfg, model, tokens, variables = tiny_llama
    full, _ = model.apply(variables, tokens)
    cache = model.init_cache(2, 32)
    for i in range(10):
        step, cache = model.apply(
            variables, tokens[:, i : i + 1], cache=cache, position=i
        )
        err = float(jnp.max(jnp.abs(step[:, 0] - full[:, i])))
        assert err < 1e-4, f"decode diverges at position {i}: {err}"


def test_prefill_then_decode_matches(tiny_llama):
    """Multi-token prefill (S>1 with cache) must agree with token-by-token."""
    cfg, model, tokens, variables = tiny_llama
    prompt = tokens[:, :8]
    cache_a = model.init_cache(2, 32)
    logits_a, cache_a = model.apply(variables, prompt, cache=cache_a, position=0)
    cache_b = model.init_cache(2, 32)
    for i in range(8):
        logits_b, cache_b = model.apply(
            variables, prompt[:, i : i + 1], cache=cache_b, position=i
        )
    assert float(jnp.max(jnp.abs(logits_a[:, -1] - logits_b[:, 0]))) < 1e-4
    # Caches agree on the filled region.
    assert float(jnp.max(jnp.abs(cache_a[0][:, :, :8] - cache_b[0][:, :, :8]))) < 1e-6


def test_causality(tiny_llama):
    """Changing a future token must not change past logits."""
    cfg, model, tokens, variables = tiny_llama
    logits_a, _ = model.apply(variables, tokens)
    tampered = tokens.at[:, 10].set((tokens[:, 10] + 1) % cfg.vocab_size)
    logits_b, _ = model.apply(variables, tampered)
    assert float(jnp.max(jnp.abs(logits_a[:, :10] - logits_b[:, :10]))) < 1e-5
    assert float(jnp.max(jnp.abs(logits_a[:, 10:] - logits_b[:, 10:]))) > 1e-6


def test_gqa_configs():
    """n_kv_heads < n_heads path (Llama-3 style grouped queries)."""
    cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=1)
    model = LlamaModel(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits, _ = model.apply(variables, tokens)
    assert bool(jnp.isfinite(logits).all())


def test_flash_attention_path_matches_einsum():
    """use_flash=True must reproduce the einsum attention path."""
    import flax.traverse_util as tu

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    cfg_flash = LlamaConfig.tiny(dtype=jnp.float32, use_flash=True)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, cfg.vocab_size)
    variables = LlamaModel(cfg).init(jax.random.PRNGKey(0), tokens)
    ref, _ = LlamaModel(cfg).apply(variables, tokens)
    out, _ = LlamaModel(cfg_flash).apply(variables, tokens)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-3


def test_resnet_forward_and_bn_mutation():
    model = ResNetTiny()
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    logits, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    assert "batch_stats" in mutated
    assert bool(jnp.isfinite(logits).all())


def test_ring_attention_model_path_matches_einsum():
    """Long-context path: the same params applied through the in-model ring
    attention (sequence sharded over the sp axis) must reproduce the plain
    einsum forward."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaConfig, LlamaModel
    from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(dcn=1, dp=1, fsdp=1, sp=4, tp=2))
    cfg = LlamaConfig.tiny()
    ring_cfg = dataclasses.replace(cfg, ring_mesh=mesh, ring_axis="sp")

    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, cfg.vocab_size)
    variables = LlamaModel(cfg).init(jax.random.PRNGKey(1), tokens[:, :8])

    ref_logits, _ = jax.jit(LlamaModel(cfg).apply)(variables, tokens)
    with mesh:
        seq_tokens = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
        ring_logits, _ = jax.jit(LlamaModel(ring_cfg).apply)(variables, seq_tokens)

    err = float(jnp.max(jnp.abs(ring_logits - ref_logits)))
    scale = float(jnp.max(jnp.abs(ref_logits))) + 1e-6
    assert err / scale < 3e-2, f"ring forward diverged: rel err {err / scale}"


def test_ring_attention_model_path_trains():
    """Gradients flow through the ring (shard_map + ppermute) path and match
    the plain path's gradients."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaConfig, LlamaModel
    from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(dcn=1, dp=1, fsdp=1, sp=4, tp=2))
    cfg = LlamaConfig.tiny()
    ring_cfg = dataclasses.replace(cfg, ring_mesh=mesh, ring_axis="sp")

    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, cfg.vocab_size)
    variables = LlamaModel(cfg).init(jax.random.PRNGKey(1), tokens[:, :8])

    def loss(model):
        def fn(params, toks):
            logits, _ = model.apply({"params": params}, toks)
            return jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1))

        return fn

    ref_grads = jax.jit(jax.grad(loss(LlamaModel(cfg))))(variables["params"], tokens)
    with mesh:
        seq_tokens = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
        ring_grads = jax.jit(jax.grad(loss(LlamaModel(ring_cfg))))(
            variables["params"], seq_tokens
        )

    for ref, ring in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(ring_grads)):
        scale = float(jnp.max(jnp.abs(ref))) + 1e-6
        err = float(jnp.max(jnp.abs(ring - ref)))
        assert err / scale < 5e-2, f"grad diverged: rel err {err / scale}"
