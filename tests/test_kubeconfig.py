"""Kubeconfig parsing (kubeclient/rest.py ClusterConfig)."""

import base64

import pytest

from tpu_cc_manager.kubeclient.api import KubeApiError
from tpu_cc_manager.kubeclient.rest import ClusterConfig


def write_kubeconfig(tmp_path, user: dict):
    cfg = {
        "current-context": "test",
        "contexts": [{"name": "test", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [
            {
                "name": "c",
                "cluster": {
                    "server": "https://example:6443",
                    "insecure-skip-tls-verify": True,
                },
            }
        ],
        "users": [{"name": "u", "user": user}],
    }
    import yaml

    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump(cfg))
    return str(path)


def test_token_auth(tmp_path):
    path = write_kubeconfig(tmp_path, {"token": "sekret"})
    cfg = ClusterConfig.from_kubeconfig(path)
    assert cfg.server == "https://example:6443"
    assert cfg.token == "sekret"
    assert cfg.insecure_skip_tls_verify is True


def test_client_cert_data_materialized(tmp_path):
    cert = base64.b64encode(b"CERTDATA").decode()
    key = base64.b64encode(b"KEYDATA").decode()
    path = write_kubeconfig(
        tmp_path, {"client-certificate-data": cert, "client-key-data": key}
    )
    cfg = ClusterConfig.from_kubeconfig(path)
    assert cfg.client_cert_file and cfg.client_key_file
    with open(cfg.client_cert_file, "rb") as f:
        assert f.read() == b"CERTDATA"


def test_missing_context_raises(tmp_path):
    import yaml

    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump({"clusters": []}))
    with pytest.raises(KubeApiError):
        ClusterConfig.from_kubeconfig(str(path))


def test_in_cluster_config(tmp_path, monkeypatch):
    """Service-account path: token + CA read from the mounted SA dir, server
    from the KUBERNETES_SERVICE_* env (reference main.py:129-140's
    load_incluster_config analogue)."""
    sa = tmp_path / "serviceaccount"
    sa.mkdir()
    (sa / "token").write_text("sa-token\n")
    (sa / "ca.crt").write_text("CA PEM")
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest.SERVICEACCOUNT_DIR", str(sa)
    )
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "6443")
    cfg = ClusterConfig.in_cluster()
    assert cfg.server == "https://10.0.0.1:6443"
    assert cfg.token == "sa-token"
    assert cfg.ca_file == str(sa / "ca.crt")


def test_in_cluster_requires_sa_mount(monkeypatch, tmp_path):
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest.SERVICEACCOUNT_DIR",
        str(tmp_path / "missing"),
    )
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    with pytest.raises(KubeApiError):
        ClusterConfig.in_cluster()


def test_load_prefers_in_cluster(tmp_path, monkeypatch):
    """load() order: in-cluster first, kubeconfig fallback."""
    sa = tmp_path / "serviceaccount"
    sa.mkdir()
    (sa / "token").write_text("tok")
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest.SERVICEACCOUNT_DIR", str(sa)
    )
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.9.9.9")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
    cfg = ClusterConfig.load(kubeconfig="/nonexistent/kubeconfig")
    assert cfg.server == "https://10.9.9.9:443"
