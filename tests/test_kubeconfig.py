"""Kubeconfig parsing (kubeclient/rest.py ClusterConfig)."""

import base64

import pytest

from tpu_cc_manager.kubeclient.api import KubeApiError
from tpu_cc_manager.kubeclient.rest import ClusterConfig


def write_kubeconfig(tmp_path, user: dict):
    cfg = {
        "current-context": "test",
        "contexts": [{"name": "test", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [
            {
                "name": "c",
                "cluster": {
                    "server": "https://example:6443",
                    "insecure-skip-tls-verify": True,
                },
            }
        ],
        "users": [{"name": "u", "user": user}],
    }
    import yaml

    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump(cfg))
    return str(path)


def test_token_auth(tmp_path):
    path = write_kubeconfig(tmp_path, {"token": "sekret"})
    cfg = ClusterConfig.from_kubeconfig(path)
    assert cfg.server == "https://example:6443"
    assert cfg.token == "sekret"
    assert cfg.insecure_skip_tls_verify is True


def test_client_cert_data_materialized(tmp_path):
    cert = base64.b64encode(b"CERTDATA").decode()
    key = base64.b64encode(b"KEYDATA").decode()
    path = write_kubeconfig(
        tmp_path, {"client-certificate-data": cert, "client-key-data": key}
    )
    cfg = ClusterConfig.from_kubeconfig(path)
    assert cfg.client_cert_file and cfg.client_key_file
    with open(cfg.client_cert_file, "rb") as f:
        assert f.read() == b"CERTDATA"


def test_missing_context_raises(tmp_path):
    import yaml

    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump({"clusters": []}))
    with pytest.raises(KubeApiError):
        ClusterConfig.from_kubeconfig(str(path))


def test_in_cluster_config(tmp_path, monkeypatch):
    """Service-account path: token + CA read from the mounted SA dir, server
    from the KUBERNETES_SERVICE_* env (reference main.py:129-140's
    load_incluster_config analogue)."""
    sa = tmp_path / "serviceaccount"
    sa.mkdir()
    (sa / "token").write_text("sa-token\n")
    (sa / "ca.crt").write_text("CA PEM")
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest.SERVICEACCOUNT_DIR", str(sa)
    )
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "6443")
    cfg = ClusterConfig.in_cluster()
    assert cfg.server == "https://10.0.0.1:6443"
    assert cfg.token == "sa-token"
    assert cfg.ca_file == str(sa / "ca.crt")


def test_in_cluster_requires_sa_mount(monkeypatch, tmp_path):
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest.SERVICEACCOUNT_DIR",
        str(tmp_path / "missing"),
    )
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    with pytest.raises(KubeApiError):
        ClusterConfig.in_cluster()


def test_load_prefers_in_cluster(tmp_path, monkeypatch):
    """load() order: in-cluster first, kubeconfig fallback."""
    sa = tmp_path / "serviceaccount"
    sa.mkdir()
    (sa / "token").write_text("tok")
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest.SERVICEACCOUNT_DIR", str(sa)
    )
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.9.9.9")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
    cfg = ClusterConfig.load(kubeconfig="/nonexistent/kubeconfig")
    assert cfg.server == "https://10.9.9.9:443"


def write_two_context_kubeconfig(tmp_path):
    cfg = {
        "current-context": "local",
        "contexts": [
            {"name": "local", "context": {"cluster": "c1", "user": "u1"}},
            {"name": "region-2", "context": {"cluster": "c2", "user": "u2"}},
        ],
        "clusters": [
            {
                "name": "c1",
                "cluster": {
                    "server": "https://local:6443",
                    "insecure-skip-tls-verify": True,
                },
            },
            {
                "name": "c2",
                "cluster": {
                    "server": "https://region-2:6443",
                    "insecure-skip-tls-verify": True,
                },
            },
        ],
        "users": [
            {"name": "u1", "user": {"token": "t1"}},
            {"name": "u2", "user": {"token": "t2"}},
        ],
    }
    import yaml

    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump(cfg))
    return str(path)


def test_named_context_selects_that_cluster(tmp_path):
    """Per-region federation (--regions r=ctx) picks one context out of
    a shared kubeconfig instead of the file's current-context."""
    path = write_two_context_kubeconfig(tmp_path)
    default = ClusterConfig.from_kubeconfig(path)
    assert default.server == "https://local:6443"
    assert default.token == "t1"
    regional = ClusterConfig.from_kubeconfig(path, context="region-2")
    assert regional.server == "https://region-2:6443"
    assert regional.token == "t2"


def test_named_context_missing_raises(tmp_path):
    path = write_two_context_kubeconfig(tmp_path)
    with pytest.raises(KubeApiError, match="not found"):
        ClusterConfig.from_kubeconfig(path, context="nope")


def test_load_with_named_context_skips_in_cluster(tmp_path, monkeypatch):
    """A named context must NEVER fall back to the local in-cluster
    config — silently getting the local cluster is the cross-region
    mixup the explicit form exists to prevent."""
    # Make the in-cluster probe LOOK available; the named context must
    # not even consult it.
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("in-cluster-token")
    (sa / "ca.crt").write_text("ca")
    monkeypatch.setattr(
        "tpu_cc_manager.kubeclient.rest._SA_MOUNT", str(sa), raising=False
    )
    path = write_two_context_kubeconfig(tmp_path)
    cfg = ClusterConfig.load(path, context="region-2")
    assert cfg.server == "https://region-2:6443"
    assert cfg.token == "t2"
