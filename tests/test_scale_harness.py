"""Scale harness (hack/scale_bench.py) smoke: the O(pool)→O(changes)
drop, measured in-process at CI-sized pools.

The committed SCALE_r01.json carries the 100/1k/10k numbers; these tests
keep the harness itself honest in tier-1 — a 100-node fleet of simulated
agents converges under both orchestrators, and the informer one costs
the apiserver an order of magnitude fewer list requests. The 10k pool
runs behind the ``slow`` marker (minutes, by design).
"""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "hack")
)
import scale_bench  # noqa: E402


def test_100_node_fleet_converges_under_both_orchestrators():
    legacy = scale_bench.run_pool(100, "legacy", seed=11)
    informer = scale_bench.run_pool(100, "informer", seed=11)
    assert legacy["ok"], legacy
    assert informer["ok"], informer
    assert legacy["agent_transitions"] == 100
    assert informer["agent_transitions"] == 100
    llists = legacy["orchestrator_requests"].get("list", 0)
    ilists = informer["orchestrator_requests"].get("list", 0)
    # The acceptance bar is >=10x at 1k; at 100 nodes the drop is already
    # an order of magnitude, because the legacy orchestrator pays a
    # listing per await poll and the informer pays one per relist.
    assert ilists > 0
    assert llists >= 10 * ilists, (llists, ilists)
    # The informer orchestrator holds a watch instead.
    assert informer["orchestrator_requests"].get("watch", 0) >= 1


def test_summary_flags_ok_and_ratio():
    rows = [
        scale_bench.run_pool(60, "legacy", seed=3),
        scale_bench.run_pool(60, "informer", seed=3),
    ]
    summary = scale_bench.summarize(rows)
    assert summary["ok"] is True
    assert summary["list_request_drop"]["60"] >= 10.0


def test_100_node_fleet_over_real_http_apiserver():
    """--apiserver parity smoke: the same rollout, but the orchestrator
    speaks real HTTP (RestKube → hack/mock_apiserver.py) — chunked
    listings, selector watches, merge-patches on the wire. The committed
    SCALE_r02.json carries the 1k-node numbers."""
    legacy = scale_bench.run_pool_apiserver(100, "legacy", seed=11)
    informer = scale_bench.run_pool_apiserver(100, "informer", seed=11)
    assert legacy["ok"], legacy
    assert informer["ok"], informer
    assert legacy["transport"] == informer["transport"] == "http"
    llists = legacy["orchestrator_requests"].get("list", 0)
    ilists = informer["orchestrator_requests"].get("list", 0)
    assert ilists > 0
    assert llists >= 10 * ilists, (llists, ilists)
    # The server's own per-verb ledger agrees with the client's on the
    # O(pool) verb (watch reconnects may differ: a shutdown-interrupted
    # reconnect counts client-side only).
    assert informer["apiserver_requests"].get("list") == ilists


@pytest.mark.slow
def test_10k_node_fleet_full_rollout_informer():
    row = scale_bench.run_pool(10000, "informer", seed=5)
    assert row["ok"], row
    assert row["agent_transitions"] == 10000
    # One chunked listing (10000/500 = 20 pages) plus chaos-triggered
    # relists at most; nothing O(pool).
    assert row["orchestrator_requests"].get("list", 0) <= 60


@pytest.mark.slow
def test_federation_blackout_smoke():
    """--federation-blackout smoke at 4 regions x 400 nodes: healthy
    regions ride a seeded parent blackout and reconcile on reconnect,
    the kill region SIGKILLs at the parent-offline crash point and
    dark-resumes through the skew-proof lease observation window, and
    the escrow region halts escrow-exhausted in the dark on its dead
    slice, then resumes to completion once the parent returns. The
    committed SCALE_r04.json carries the 100k-node numbers."""
    row = scale_bench.run_federation_blackout(
        total_nodes=1600, regions_count=4, shards=4,
        per_shard_unavailable=13, node_timeout_s=3.0,
    )
    assert row["ok"], row
    assert row["budget_spend_exactly_dead_slice"], row["budget_spend"]
    assert row["region_results"][row["killed_region"]]["resumed_dark"]
    assert row["region_results"][row["escrow_region"]]["escrow_halted_dark"]
    assert row["stitch"]["torn_lines"] == 0
    assert row["stitch"]["exactly_once"]
