"""Mesh / sharding / train step / checkpoint / DCN verification on the
8-device virtual CPU mesh (parallel/)."""

import jax
import jax.numpy as jnp
import pytest

from tpu_cc_manager.models.llama import LlamaConfig
from tpu_cc_manager.parallel.checkpoint import TrainCheckpointer
from tpu_cc_manager.parallel.distributed import bootstrap, verify_dcn_mesh
from tpu_cc_manager.parallel.mesh import MeshSpec, default_spec_for, make_mesh, pad_batch_to
from tpu_cc_manager.parallel.sharding import batch_sharding
from tpu_cc_manager.parallel.train import (
    make_llama_train_state,
    make_llama_train_step,
)


def test_mesh_spec_resolution():
    assert MeshSpec(dp=-1, tp=2).resolve(8) == {"dcn": 1, "dp": 4, "fsdp": 1, "sp": 1, "tp": 2}
    assert MeshSpec(dcn=2, dp=2, fsdp=1, tp=2).resolve(8)["dp"] == 2
    with pytest.raises(ValueError):
        MeshSpec(dp=3, tp=3).resolve(8)


def test_default_spec():
    assert default_spec_for(8).resolve(8)["tp"] == 4
    assert default_spec_for(1).resolve(1) == {"dcn": 1, "dp": 1, "fsdp": 1, "sp": 1, "tp": 1}


def test_make_mesh_axes():
    mesh = make_mesh(MeshSpec(dp=-1, tp=2))
    assert mesh.axis_names == ("dcn", "dp", "fsdp", "sp", "tp")
    assert mesh.shape["tp"] == 2
    assert pad_batch_to(3, mesh) == 4


@pytest.fixture(scope="module")
def trained():
    cfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshSpec(dcn=1, dp=2, fsdp=2, tp=2))
    state, shardings = make_llama_train_state(cfg, mesh)
    step = make_llama_train_step(cfg, mesh, shardings)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
        batch_sharding(mesh),
    )
    return cfg, mesh, state, shardings, step, tokens


def test_sharded_init_actually_shards(trained):
    cfg, mesh, state, shardings, _, _ = trained
    wq = state.params["blocks"]["attn"]["wq"]["kernel"]
    spec = wq.sharding.spec
    # heads axis on tp, embed axis on fsdp (LOGICAL_AXIS_RULES).
    assert "tp" in str(spec) and "fsdp" in str(spec)
    # Optimizer state inherits the same sharding.
    mu_wq = state.opt_state[0].mu["blocks"]["attn"]["wq"]["kernel"]
    assert mu_wq.sharding.spec == wq.sharding.spec


def test_train_step_decreases_loss(trained):
    cfg, mesh, state, shardings, step, tokens = trained
    # step donates its input state; work on a copy so the module-scoped
    # fixture's buffers survive for later tests.
    state = jax.tree.map(lambda x: x.copy(), state)
    losses = []
    for _ in range(4):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(l == l for l in losses)  # finite
    assert losses[-1] < losses[0]


def test_dcn_mesh_verification(trained):
    _, mesh, *_ = trained
    assert verify_dcn_mesh(mesh) is True


def test_bootstrap_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert bootstrap() == {"processes": 1, "initialized": False}


def test_bootstrap_requires_coordinator(monkeypatch):
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    with pytest.raises(RuntimeError):
        bootstrap()


def test_checkpoint_roundtrip(tmp_path, trained):
    """Save a trained state, restore into the sharded abstract target, and
    verify training resumes from identical values (the resume-after-CC-
    bounce flow, BASELINE.json configs[3])."""
    cfg, mesh, state, shardings, step, tokens = trained
    state1, _ = step(jax.tree.map(lambda x: x.copy(), state), tokens)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    step_no = int(state1.step)
    ckpt.save(step_no, state1)
    assert ckpt.latest_step() == step_no

    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state1,
        shardings,
    )
    restored = ckpt.restore(abstract)
    for a, b in zip(jax.tree.leaves(state1), jax.tree.leaves(restored)):
        assert jnp.array_equal(a, b), "restored leaf differs"
    # The restored state is usable for further steps.
    state2, loss = step(restored, tokens)
    assert float(loss) == float(loss)
    ckpt.close()
