"""Failure containment (ccmanager/remediation.py + slicecoord fencing).

Covers the escalation ladder (backoff-retry -> device-reset ->
runtime-restart -> quarantine), annotation-persisted ladder state across
agent restarts, quarantine side effects (NoSchedule taint, label, ready
demotion, event, slice fencing), the watchdog-driven probation auto-lift,
the barrier fencing-generation protocol (peers fail fast; stale agents can
neither complete nor re-stage an aborted round), the rolling orchestrator's
quarantine skip + pool failure budget, and the operator CLI overrides.
"""

from __future__ import annotations

import argparse
import threading
import time

import pytest

from tpu_cc_manager import ctl
from tpu_cc_manager.ccmanager import remediation, slicecoord
from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.ccmanager.slicecoord import (
    SLICE_COMMIT_GEN_LABEL,
    SLICE_COMMIT_LABEL,
    SLICE_FENCE_LABEL,
    SLICE_STAGED_GEN_LABEL,
    SLICE_STAGED_LABEL,
    BarrierFenced,
    BarrierTimeout,
    SliceBarrier,
)
from tpu_cc_manager.ccmanager.watchdog import RuntimeHealthWatchdog
from tpu_cc_manager.kubeclient.api import node_annotations, node_labels
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    MODE_ON,
    MODE_SLICE,
    QUARANTINE_TAINT_KEY,
    QUARANTINED_LABEL,
    SLICE_ID_LABEL,
)
from tpu_cc_manager.tpudev.contract import SliceTopology
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "remedy-node-0"
SLICE = "remedy-slice"


class FakeClock:
    def __init__(self) -> None:
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, s: float) -> None:
        self.now += s


def node_taints(node: dict) -> list[dict]:
    return (node.get("spec") or {}).get("taints") or []


def make_ladder(kube, backend=None, **kw):
    events: list[tuple[str, str, str]] = []
    clock = kw.pop("clock", FakeClock())
    ladder = remediation.RemediationLadder(
        kube,
        NODE,
        backend=backend,
        failures_per_step=kw.pop("failures_per_step", 2),
        probation_s=kw.pop("probation_s", 30.0),
        emit_event=lambda *a: events.append(a),
        metrics=kw.pop("metrics", MetricsRegistry()),
        clock=clock,
        **kw,
    )
    return ladder, events, clock


# ---------------------------------------------------------------------------
# Escalation ladder
# ---------------------------------------------------------------------------


def test_ladder_escalates_in_order(fake_kube):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    ladder, events, _ = make_ladder(fake_kube, backend)

    # Rung 0: the manager's own backoff retry — no device action.
    assert ladder.note_failure("apply-failed") == remediation.STEP_RETRY
    assert ladder.note_failure("apply-failed") == remediation.STEP_RETRY
    assert not any(op == "reset" for op, _ in backend.op_log)

    # Rung 1: device re-reset.
    assert ladder.note_failure("apply-failed") == remediation.STEP_DEVICE_RESET
    assert sum(1 for op, _ in backend.op_log if op == "reset") == 1
    assert ladder.note_failure("apply-failed") == remediation.STEP_DEVICE_RESET

    # Rung 2: runtime restart (distinct backend op).
    assert (
        ladder.note_failure("apply-failed") == remediation.STEP_RUNTIME_RESTART
    )
    assert any(op == "restart_runtime" for op, _ in backend.op_log)
    ladder.note_failure("apply-failed")

    # Rung 3: quarantine — terminal.
    assert ladder.note_failure("apply-failed") == remediation.STEP_QUARANTINE
    assert ladder.quarantined
    node = fake_kube.get_node(NODE)
    labels = node_labels(node)
    assert labels[QUARANTINED_LABEL] == "true"
    assert labels[CC_READY_STATE_LABEL] == "false"
    taints = node_taints(node)
    assert any(
        t["key"] == QUARANTINE_TAINT_KEY and t["effect"] == "NoSchedule"
        for t in taints
    )
    assert ("Warning", "CCNodeQuarantined") in {
        (t, r) for t, r, _ in events
    }
    # Further failures stay contained (no re-escalation, no new actions).
    resets = sum(1 for op, _ in backend.op_log if op == "reset")
    assert ladder.note_failure("apply-failed") == remediation.STEP_QUARANTINE
    assert sum(1 for op, _ in backend.op_log if op == "reset") == resets


def test_peer_and_apiserver_failures_do_not_escalate(fake_kube):
    """A fenced/timed-out barrier is a PEER's failure and an apiserver
    outage is nobody's hardware fault: neither climbs the ladder — one
    quarantined host must not cascade its healthy slice-mates into
    resets and quarantine."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    ladder, _, _ = make_ladder(fake_kube, backend)
    for _ in range(20):
        ladder.note_failure("barrier-fenced")
        ladder.note_failure("barrier-timeout")
        ladder.note_failure("apiserver-error")
    assert ladder.failures == 0
    assert not ladder.quarantined
    assert not backend.op_log  # no remediation action ever ran


def test_drain_timeout_skips_hardware_rungs_but_still_quarantines(fake_kube):
    """Resetting chips under workloads that refused to drain would break
    the strict-eviction guarantee; sustained drain failure still ends in
    quarantine (stop scheduling onto a node that cannot drain)."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    ladder, _, _ = make_ladder(fake_kube, backend, metrics=registry)
    for _ in range(7):
        ladder.note_failure("drain-timeout")
    assert ladder.quarantined
    assert not any(
        op in ("reset", "restart_runtime") for op, _ in backend.op_log
    )
    totals = registry.remediation_totals()
    assert totals[(remediation.STEP_DEVICE_RESET, "skipped")] >= 1
    assert totals[(remediation.STEP_RUNTIME_RESTART, "skipped")] >= 1


def test_failed_startup_load_is_retried_before_acting(fake_kube):
    """A quarantined node whose agent rebooted through an apiserver blip
    must re-learn its quarantine before any ladder decision runs."""
    fake_kube.add_node(NODE)
    first, _, _ = make_ladder(fake_kube, FakeTpuBackend())
    first.quarantine(reason="test")

    real_get = fake_kube.get_node
    from tpu_cc_manager.kubeclient.api import KubeApiError

    fail = {"on": True}

    def flaky_get(name):
        if fail["on"]:
            raise KubeApiError(None, "blip")
        return real_get(name)

    fake_kube.get_node = flaky_get
    try:
        reborn, _, _ = make_ladder(fake_kube, FakeTpuBackend())
        assert not reborn.quarantined  # load failed; state unknown so far
        fail["on"] = False
        # The first ladder decision re-loads and rediscovers quarantine:
        # the failure is absorbed (already contained), not escalated.
        assert reborn.note_failure("apply-failed") == remediation.STEP_QUARANTINE
        assert reborn.quarantined
        assert reborn.failures == 0
    finally:
        fake_kube.get_node = real_get


def test_ctl_quarantine_without_backend_still_fences(fake_kube):
    """The operator CLI has no device layer; fencing falls back to the
    node's published slice-membership label."""
    fake_kube.add_node(
        "ctl-f0", {SLICE_ID_LABEL: SLICE, CC_MODE_STATE_LABEL: MODE_SLICE}
    )
    rc = ctl.cmd_quarantine(
        fake_kube, argparse.Namespace(node="ctl-f0", reason="drill")
    )
    assert rc == 0
    assert node_labels(fake_kube.get_node("ctl-f0"))[SLICE_FENCE_LABEL] == "1"


def test_success_resets_the_ladder(fake_kube):
    fake_kube.add_node(NODE)
    ladder, _, _ = make_ladder(fake_kube, FakeTpuBackend())
    for _ in range(3):
        ladder.note_failure("apply-failed")
    assert ladder.failures == 3
    ladder.note_success()
    assert ladder.failures == 0 and ladder.step == remediation.STEP_RETRY
    # The persisted annotation is dropped with it.
    assert remediation.REMEDIATION_ANNOTATION not in node_annotations(
        fake_kube.get_node(NODE)
    )


def test_ladder_state_survives_agent_restart(fake_kube):
    """The annotation is the ladder's crash-safety: a terminally bad node
    cannot dodge quarantine by crash-restarting the agent."""
    fake_kube.add_node(NODE)
    ladder, _, _ = make_ladder(fake_kube, FakeTpuBackend())
    for _ in range(4):
        ladder.note_failure("apply-failed")
    assert ladder.step == remediation.STEP_DEVICE_RESET

    reborn, _, _ = make_ladder(fake_kube, FakeTpuBackend())
    assert reborn.failures == 4
    assert reborn.step == remediation.STEP_DEVICE_RESET
    # Three more failures drive the RESUMED ladder to quarantine — the
    # restart did not reset the count.
    for _ in range(3):
        reborn.note_failure("apply-failed")
    assert reborn.quarantined

    third, _, _ = make_ladder(fake_kube, FakeTpuBackend())
    assert third.quarantined


def test_remediation_action_failure_still_escalates(fake_kube):
    """A rung whose action itself fails (the device is THAT broken) keeps
    counting failures toward the next rung instead of wedging."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    backend.fail_next("reset", times=-1)
    backend.fail_next("restart_runtime", times=-1)
    registry = MetricsRegistry()
    ladder, _, _ = make_ladder(fake_kube, backend, metrics=registry)
    for _ in range(7):
        ladder.note_failure("apply-failed")
    assert ladder.quarantined
    totals = registry.remediation_totals()
    assert totals[(remediation.STEP_DEVICE_RESET, "failed")] >= 1
    assert totals[(remediation.STEP_RUNTIME_RESTART, "failed")] >= 1
    assert any(step == remediation.STEP_QUARANTINE for step, _ in totals)


# ---------------------------------------------------------------------------
# Probation auto-lift
# ---------------------------------------------------------------------------


def test_probation_lifts_quarantine_after_sustained_health(fake_kube):
    fake_kube.add_node(NODE, {CC_MODE_STATE_LABEL: MODE_ON})
    ladder, events, clock = make_ladder(
        fake_kube, FakeTpuBackend(), probation_s=30.0
    )
    ladder.quarantine(reason="test")
    assert node_labels(fake_kube.get_node(NODE))[CC_READY_STATE_LABEL] == "false"

    ladder.note_probe(True)  # probation starts
    clock.advance(10.0)
    ladder.note_probe(False)  # relapse: probation resets
    clock.advance(25.0)
    ladder.note_probe(True)  # new streak starts here
    clock.advance(29.0)
    ladder.note_probe(True)
    assert ladder.quarantined  # 29 s < 30 s probation
    clock.advance(2.0)
    ladder.note_probe(True)
    assert not ladder.quarantined

    node = fake_kube.get_node(NODE)
    labels = node_labels(node)
    assert QUARANTINED_LABEL not in labels
    # Ready restored from the CURRENT mode.state.
    assert labels[CC_READY_STATE_LABEL] == "true"
    assert not any(
        t["key"] == QUARANTINE_TAINT_KEY for t in node_taints(node)
    )
    assert ("Normal", "CCNodeUnquarantined") in {(t, r) for t, r, _ in events}
    # Ladder reset and annotation dropped.
    assert ladder.failures == 0
    assert remediation.REMEDIATION_ANNOTATION not in node_annotations(node)


def test_watchdog_probes_feed_probation(fake_kube):
    """The PR-2 watchdog's recovery signal IS the probation driver: its
    ticks call note_probe, and sustained healthy probes lift quarantine."""
    fake_kube.add_node(NODE, {CC_MODE_STATE_LABEL: MODE_ON})
    backend = FakeTpuBackend()
    clock = FakeClock()
    ladder, _, _ = make_ladder(fake_kube, backend, probation_s=5.0, clock=clock)
    watchdog = RuntimeHealthWatchdog(
        fake_kube, backend, NODE,
        demote_after=1, restore_after=1,
        metrics=MetricsRegistry(),
        on_probe=ladder.note_probe,
        on_condemn=ladder.condemn,
    )
    ladder.quarantine(reason="test")
    backend.healthy = True
    watchdog.tick()  # starts probation
    clock.advance(6.0)
    watchdog.tick()  # probation elapsed -> lift
    assert not ladder.quarantined


# ---------------------------------------------------------------------------
# Slice fencing
# ---------------------------------------------------------------------------


def make_barrier(kube, i: int, num_hosts: int = 2, timeout_s: float = 5.0):
    topo = SliceTopology(
        slice_id=SLICE, accelerator_type="v5p-32",
        num_hosts=num_hosts, host_index=i, chips=(),
    )
    return SliceBarrier(
        kube, f"fence-node-{i}", topo,
        timeout_s=timeout_s, poll_interval_s=0.01,
        complete_timeout_s=0.2,
    )


def test_fenced_peers_fail_fast(fake_kube):
    """The acceptance bullet: a peer waiting at the barrier aborts well
    under the barrier deadline once the slice is fenced."""
    for i in range(2):
        fake_kube.add_node(f"fence-node-{i}", {SLICE_ID_LABEL: SLICE})
    waiter = make_barrier(fake_kube, 0, timeout_s=30.0)
    waiter.publish_staged(MODE_SLICE)

    outcome: dict = {}

    def wait():
        started = time.monotonic()
        try:
            waiter.await_commit(MODE_SLICE)
            outcome["result"] = "committed"
        except BarrierFenced:
            outcome["result"] = "fenced"
        except BarrierTimeout:
            outcome["result"] = "timeout"
        outcome["seconds"] = time.monotonic() - started

    t = threading.Thread(target=wait)
    t.start()
    # cclint: test-sleep-ok(settle window: the waiter thread has no observable parked-in-barrier hook)
    time.sleep(0.05)
    # Host 1 is condemned: it bumps the fencing generation.
    slicecoord.fence_slice(
        fake_kube, "fence-node-1", SLICE, reason="quarantine",
        metrics=MetricsRegistry(),
    )
    t.join(timeout=10)
    assert outcome["result"] == "fenced"
    assert outcome["seconds"] < 5.0, (
        f"peer burned {outcome['seconds']:.1f}s of a 30s deadline"
    )


def test_stale_staged_marker_cannot_satisfy_a_new_round(fake_kube):
    """A pre-fence staged marker never counts as ready for the current
    generation — a stale agent cannot re-stage an aborted barrier."""
    for i in range(2):
        fake_kube.add_node(f"fence-node-{i}", {SLICE_ID_LABEL: SLICE})
    stale = make_barrier(fake_kube, 1)
    stale.publish_staged(MODE_SLICE)  # generation 0
    slicecoord.fence_slice(fake_kube, "fence-node-0", SLICE)
    # fence_slice clears the FENCING node's marker; node 1's stale marker
    # survives (its agent is presumed dead/stalled).
    labels1 = node_labels(fake_kube.get_node("fence-node-1"))
    assert labels1[SLICE_STAGED_LABEL] == MODE_SLICE
    assert labels1[SLICE_STAGED_GEN_LABEL] == "0"

    fresh = make_barrier(fake_kube, 0, timeout_s=0.3)
    fresh.publish_staged(MODE_SLICE)  # enters at generation 1
    assert fresh.generation == 1
    with pytest.raises(BarrierTimeout):
        fresh.await_commit(MODE_SLICE)  # stale peer never reads as ready


def test_stale_commit_marker_cannot_release_a_new_round(fake_kube):
    """A commit marker from a pre-fence round (stale leader) must not let
    a current-round follower reset."""
    for i in range(2):
        fake_kube.add_node(f"fence-node-{i}", {SLICE_ID_LABEL: SLICE})
    # Simulate a pre-fence leader that committed right before dying: its
    # commit marker carries generation 0.
    fake_kube.set_node_label("fence-node-0", SLICE_COMMIT_LABEL, MODE_SLICE)
    fake_kube.set_node_label("fence-node-0", SLICE_COMMIT_GEN_LABEL, "0")
    fake_kube.set_node_label("fence-node-0", SLICE_STAGED_LABEL, MODE_SLICE)
    fake_kube.set_node_label("fence-node-0", SLICE_STAGED_GEN_LABEL, "0")
    slicecoord.fence_slice(fake_kube, "fence-node-1", SLICE)

    follower = make_barrier(fake_kube, 1, timeout_s=0.3)
    follower.publish_staged(MODE_SLICE)  # generation 1
    # Old-gen staged marker doesn't count ready, old-gen commit doesn't
    # count committed: the round times out instead of resetting.
    with pytest.raises(BarrierTimeout):
        follower.await_commit(MODE_SLICE)


def test_stale_leader_stops_completing_a_fenced_round(fake_kube):
    for i in range(2):
        fake_kube.add_node(f"fence-node-{i}", {SLICE_ID_LABEL: SLICE})
    leader = make_barrier(fake_kube, 0)
    leader.publish_staged(MODE_SLICE)
    # Peer staged at the same generation -> barrier forms, leader commits.
    fake_kube.set_node_label("fence-node-1", SLICE_STAGED_LABEL, MODE_SLICE)
    fake_kube.set_node_label("fence-node-1", SLICE_STAGED_GEN_LABEL, "0")
    leader.await_commit(MODE_SLICE)
    assert node_labels(fake_kube.get_node("fence-node-0"))[
        SLICE_COMMIT_LABEL
    ] == MODE_SLICE
    # The slice gets fenced before completion; the stale leader retires
    # its own (now old-generation) commit marker and stops driving.
    slicecoord.fence_slice(fake_kube, "fence-node-1", SLICE)
    leader.complete(MODE_SLICE)
    labels = node_labels(fake_kube.get_node("fence-node-0"))
    assert SLICE_COMMIT_LABEL not in labels
    assert SLICE_COMMIT_GEN_LABEL not in labels


def test_fence_generation_is_monotonic(fake_kube):
    fake_kube.add_node("fence-node-0", {SLICE_ID_LABEL: SLICE})
    assert slicecoord.fence_slice(fake_kube, "fence-node-0", SLICE) == 1
    assert slicecoord.fence_slice(fake_kube, "fence-node-0", SLICE) == 2
    labels = node_labels(fake_kube.get_node("fence-node-0"))
    assert labels[SLICE_FENCE_LABEL] == "2"


def test_quarantine_fences_a_multi_host_slice(fake_kube):
    """Quarantining one host of a multi-host slice aborts the slice
    barrier: the fence generation bumps on the condemned node."""
    backend = FakeTpuBackend(num_hosts=2, host_index=0, slice_id=SLICE)
    fake_kube.add_node(NODE, {SLICE_ID_LABEL: SLICE})
    registry = MetricsRegistry()
    ladder, _, _ = make_ladder(fake_kube, backend, metrics=registry)
    ladder.quarantine(reason="test")
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[SLICE_FENCE_LABEL] == "1"


def test_watchdog_condemn_fences_without_quarantining(fake_kube):
    """The watchdog's demote edge fences peers out of the barrier even
    before the ladder reaches quarantine."""
    backend = FakeTpuBackend(num_hosts=2, host_index=0, slice_id=SLICE)
    fake_kube.add_node(NODE, {SLICE_ID_LABEL: SLICE})
    ladder, _, _ = make_ladder(fake_kube, backend)
    watchdog = RuntimeHealthWatchdog(
        fake_kube, backend, NODE,
        demote_after=1, restore_after=1,
        metrics=MetricsRegistry(),
        on_probe=ladder.note_probe,
        on_condemn=ladder.condemn,
    )
    backend.healthy = False
    watchdog.tick()
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[SLICE_FENCE_LABEL] == "1"
    assert not ladder.quarantined


# ---------------------------------------------------------------------------
# Manager integration
# ---------------------------------------------------------------------------


def test_manager_defers_reconciles_while_quarantined(fake_kube):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    ladder, _, _ = make_ladder(fake_kube, backend)
    ladder.quarantine(reason="test")
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name=NODE,
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(), remediation=ladder,
    )
    ops_before = len(backend.op_log)
    assert mgr.set_cc_mode(MODE_ON) is False
    assert len(backend.op_log) == ops_before  # hardware untouched
    assert mgr.retryable_failure is False  # slow re-check cadence


# ---------------------------------------------------------------------------
# Rolling orchestrator: skip + failure budget
# ---------------------------------------------------------------------------


def converge_reactor(kube):
    """Agents in miniature: desired-mode label edits converge instantly."""

    def reactor(name, node):
        labels = node_labels(node)
        desired = labels.get(CC_MODE_LABEL)
        if desired and labels.get(CC_MODE_STATE_LABEL) != desired:
            kube.set_node_label(name, CC_MODE_STATE_LABEL, desired)

    kube.add_patch_reactor(reactor)


def test_rollout_skips_quarantined_nodes(fake_kube):
    converge_reactor(fake_kube)
    fake_kube.add_node("roll-0", {"pool": "tpu"})
    fake_kube.add_node("roll-1", {"pool": "tpu", QUARANTINED_LABEL: "true"})
    roller = RollingReconfigurator(
        fake_kube, "pool=tpu", node_timeout_s=5.0, poll_interval_s=0.01,
    )
    result = roller.rollout(MODE_ON)
    assert result.ok
    assert result.skipped_quarantined == ["roll-1"]
    # The quarantined node's desired label was never touched.
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("roll-1"))
    assert node_labels(fake_kube.get_node("roll-0"))[
        CC_MODE_STATE_LABEL
    ] == MODE_ON
    assert result.summary()["quarantined_skipped"] == ["roll-1"]


def test_rollout_halts_when_failure_budget_exceeded(fake_kube):
    converge_reactor(fake_kube)
    fake_kube.add_node("roll-0", {"pool": "tpu"})
    fake_kube.add_node("roll-1", {"pool": "tpu", QUARANTINED_LABEL: "true"})
    fake_kube.add_node("roll-2", {"pool": "tpu", QUARANTINED_LABEL: "true"})
    roller = RollingReconfigurator(
        fake_kube, "pool=tpu", node_timeout_s=5.0, poll_interval_s=0.01,
        failure_budget=1,
    )
    result = roller.rollout(MODE_ON)
    assert not result.ok
    assert result.halted_reason == "failure-budget-exceeded"
    assert result.groups == []  # nothing was bounced
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("roll-0"))
    # Budget 2 tolerates the same pool.
    roller2 = RollingReconfigurator(
        fake_kube, "pool=tpu", node_timeout_s=5.0, poll_interval_s=0.01,
        failure_budget=2,
    )
    assert roller2.rollout(MODE_ON).ok


def test_rollout_rechecks_budget_between_windows(fake_kube):
    converge_reactor(fake_kube)
    for i in range(3):
        fake_kube.add_node(f"roll-{i}", {"pool": "tpu"})

    roller = RollingReconfigurator(
        fake_kube, "pool=tpu", node_timeout_s=5.0, poll_interval_s=0.01,
        failure_budget=0,
    )

    # A node gets quarantined the moment the first window converges —
    # mid-rollout, after the start-of-rollout budget check passed.
    def quarantine_mid_rollout(name, node):
        if node_labels(node).get(CC_MODE_STATE_LABEL) == MODE_ON:
            if QUARANTINED_LABEL not in node_labels(
                fake_kube.get_node("roll-2")
            ):
                fake_kube.set_node_label("roll-2", QUARANTINED_LABEL, "true")

    fake_kube.add_patch_reactor(quarantine_mid_rollout)
    result = roller.rollout(MODE_ON)
    assert not result.ok
    assert result.halted_reason == "failure-budget-exceeded"
    assert len(result.groups) < 3  # halted before finishing the pool


# ---------------------------------------------------------------------------
# Pool attestation skips quarantined hosts
# ---------------------------------------------------------------------------


def test_pool_attestation_skips_quarantined_host(fake_kube):
    from tpu_cc_manager.ccmanager.multislice import (
        PoolAttestationError,
        publish_quote,
        verify_pool_attestation,
    )

    quote = FakeTpuBackend(slice_id="s1", initial_mode="on").fetch_attestation("n")
    fake_kube.add_node("att-0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    fake_kube.add_node("att-1", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    publish_quote(fake_kube, "att-0", quote)
    # att-1 never attested: the pool fails ...
    with pytest.raises(PoolAttestationError):
        verify_pool_attestation(fake_kube, "pool=tpu", "on", allow_fake=True)
    # ... until it is quarantined, at which point it is skipped (reported,
    # not enforced) and the healthy host's evidence carries the slice.
    fake_kube.set_node_label("att-1", QUARANTINED_LABEL, "true")
    slices = verify_pool_attestation(
        fake_kube, "pool=tpu", "on", allow_fake=True
    )
    assert slices["s1"]["quarantined"] == ["att-1"]
    # A slice with EVERY host quarantined still fails: containment must
    # not read as verification.
    fake_kube.set_node_label("att-0", QUARANTINED_LABEL, "true")
    with pytest.raises(PoolAttestationError):
        verify_pool_attestation(fake_kube, "pool=tpu", "on", allow_fake=True)


# ---------------------------------------------------------------------------
# Operator CLI
# ---------------------------------------------------------------------------


def test_ctl_quarantine_and_unquarantine(fake_kube, capsys):
    fake_kube.add_node("ctl-0", {CC_MODE_STATE_LABEL: MODE_ON})
    rc = ctl.cmd_quarantine(
        fake_kube, argparse.Namespace(node="ctl-0", reason="maintenance")
    )
    assert rc == 0
    node = fake_kube.get_node("ctl-0")
    assert node_labels(node)[QUARANTINED_LABEL] == "true"
    assert node_labels(node)[CC_READY_STATE_LABEL] == "false"
    assert any(t["key"] == QUARANTINE_TAINT_KEY for t in node_taints(node))
    # Idempotent.
    assert ctl.cmd_quarantine(
        fake_kube, argparse.Namespace(node="ctl-0", reason="maintenance")
    ) == 0

    rc = ctl.cmd_unquarantine(
        fake_kube, argparse.Namespace(node="ctl-0", reason="fixed")
    )
    assert rc == 0
    node = fake_kube.get_node("ctl-0")
    assert QUARANTINED_LABEL not in node_labels(node)
    assert node_labels(node)[CC_READY_STATE_LABEL] == "true"
    assert not any(t["key"] == QUARANTINE_TAINT_KEY for t in node_taints(node))


def test_ctl_status_shows_quarantine_and_ladder_step(fake_kube, capsys):
    fake_kube.add_node(NODE, {"pool": "tpu"})
    ladder, _, _ = make_ladder(fake_kube, FakeTpuBackend())
    for _ in range(3):
        ladder.note_failure("apply-failed")
    rc = ctl.cmd_status(fake_kube, argparse.Namespace(selector="pool=tpu"))
    out = capsys.readouterr().out
    assert rc == 0
    assert "remediation:device-reset(3)" in out

    ladder.quarantine(reason="test-reason")
    rc = ctl.cmd_status(fake_kube, argparse.Namespace(selector="pool=tpu"))
    out = capsys.readouterr().out
    assert rc == 0
    assert "quarantined(test-reason)" in out


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


def test_containment_metrics_render(fake_kube):
    registry = MetricsRegistry()
    fake_kube.add_node(NODE, {SLICE_ID_LABEL: SLICE})
    backend = FakeTpuBackend(num_hosts=2, host_index=0, slice_id=SLICE)
    ladder, _, _ = make_ladder(fake_kube, backend, metrics=registry)
    for _ in range(7):
        ladder.note_failure("apply-failed")
    text = registry.render_prometheus()
    assert "tpu_cc_quarantined 1" in text
    assert 'tpu_cc_remediation_step_total{step="quarantine"' in text
    assert "tpu_cc_barrier_fenced_total 1" in text
    ladder.unquarantine("test")
    assert "tpu_cc_quarantined 0" in registry.render_prometheus()


# ---------------------------------------------------------------------------
# Journal-before-reset (cclint `journal` contract): the hardware rungs
# write a KIND_REMEDIATION intent before touching the device.
# ---------------------------------------------------------------------------


def test_hardware_rungs_journal_an_intent(fake_kube, tmp_path):
    from tpu_cc_manager.ccmanager import intent_journal as intent_mod

    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    intents = intent_mod.IntentJournal.from_state_dir(str(tmp_path))
    ladder, _, _ = make_ladder(fake_kube, backend, intents=intents)

    for _ in range(3):
        ladder.note_failure("apply-failed")  # -> device-reset rung
    # The rung ran and its intent is CLOSED (begin -> reset -> commit).
    assert any(op == "reset" for op, _ in backend.op_log)
    assert intents.open_intents(intent_mod.KIND_REMEDIATION) == []
    recs = intents.snapshot()["recent"]
    begin = [r for r in recs if r.get("t") == "intent"
             and r.get("kind") == intent_mod.KIND_REMEDIATION]
    assert begin and begin[0]["op"] == "device-reset"
    # Intent-before-effect: the begin record's seq exists, and a commit
    # follows it.
    assert any(r.get("t") == "commit" and r.get("txn") == begin[0]["txn"]
               for r in recs)

    # A FAILING rung aborts its intent instead of leaving it open.
    backend.fail_next("restart_runtime", times=1)
    for _ in range(2):
        ladder.note_failure("apply-failed")  # -> runtime-restart rung
    assert intents.open_intents(intent_mod.KIND_REMEDIATION) == []
    aborts = [r for r in intents.snapshot()["recent"] if r.get("t") == "abort"]
    assert aborts, "failed rung should abort its intent"


def test_replay_closes_interrupted_remediation_intent(fake_kube, tmp_path):
    """An agent SIGKILLed mid-rung leaves the intent open; the successor's
    journal replay closes it and counts a rolled-back replay."""
    from tpu_cc_manager.ccmanager import intent_journal as intent_mod

    fake_kube.add_node(NODE)
    intents = intent_mod.IntentJournal.from_state_dir(str(tmp_path))
    intents.begin(intent_mod.KIND_REMEDIATION, op="device-reset", node=NODE)
    del intents  # the crash

    registry = MetricsRegistry()
    successor = intent_mod.IntentJournal.from_state_dir(str(tmp_path))
    manager = CCManager(
        api=fake_kube, backend=FakeTpuBackend(), node_name=NODE,
        intent_journal=successor, metrics=registry,
    )
    manager.recover_from_journal()
    assert successor.open_intents(intent_mod.KIND_REMEDIATION) == []
    assert registry.journal_replay_totals().get("rolled-back") == 1
