"""Real attestation signature verification (tpudev/jwks.py + attestation.py).

The production verifier is pure stdlib; these tests generate a throwaway
RSA keypair with the ``cryptography`` package (test-only dependency), build
a local JWKS, and prove: a correctly signed Google-issuer JWT passes; a bad
signature, a wrong issuer, an expired token, and a foreign key all fail
closed; missing key material fails closed; and fake-platform quotes are
rejected unless explicitly allowed.
"""

from __future__ import annotations

import base64
import json
import time

import pytest

pytest.importorskip("cryptography")  # optional dep: RSA key generation for the JWKS fixtures

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import padding, rsa

from tpu_cc_manager.labels import MODE_ON
from tpu_cc_manager.tpudev import jwks
from tpu_cc_manager.tpudev.attestation import (
    AttestationError,
    fresh_nonce,
    verify_quote,
)
from tpu_cc_manager.tpudev.contract import AttestationQuote


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _int_bytes(n: int) -> bytes:
    return n.to_bytes((n.bit_length() + 7) // 8, "big")


@pytest.fixture(scope="module")
def keypair():
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()
    keyset = {
        "keys": [
            {
                "kty": "RSA",
                "kid": "test-key-1",
                "alg": "RS256",
                "use": "sig",
                "n": _b64url(_int_bytes(pub.n)),
                "e": _b64url(_int_bytes(pub.e)),
            }
        ]
    }
    return key, keyset


def make_jwt(key, claims: dict, kid: str = "test-key-1", alg: str = "RS256") -> str:
    header = {"alg": alg, "kid": kid, "typ": "JWT"}

    def seg(obj) -> str:
        return _b64url(json.dumps(obj).encode())

    signing_input = f"{seg(header)}.{seg(claims)}"
    sig = key.sign(signing_input.encode(), padding.PKCS1v15(), hashes.SHA256())
    return f"{signing_input}.{_b64url(sig)}"


def gce_claims(nonce: str, **over) -> dict:
    claims = {
        "iss": "https://accounts.google.com",
        "aud": f"tpu-cc-manager/{nonce}",
        "sub": "1234567890",
        "iat": int(time.time()),
        "exp": int(time.time()) + 3600,
    }
    claims.update(over)
    return claims


def tpuvm_quote(jwt: str, nonce: str, mode: str = MODE_ON) -> AttestationQuote:
    return AttestationQuote(
        slice_id="slice-a",
        nonce=nonce,
        mode=mode,
        measurements={
            "accelerator_type": "v5p-8",
            "runtime_digest": "d" * 64,
            "cc_mode": mode,
        },
        signature=jwt,
        platform="tpuvm",
    )


@pytest.fixture()
def jwks_env(keypair, tmp_path, monkeypatch):
    """Point the verifier at the local JWKS via the offline-file path."""
    _, keyset = keypair
    path = tmp_path / "jwks.json"
    path.write_text(json.dumps(keyset))
    monkeypatch.setenv(jwks.JWKS_FILE_ENV, str(path))
    return keyset


class TestVerifyRs256:
    def test_valid_signature(self, keypair):
        key, keyset = keypair
        token = make_jwt(key, {"hello": "world"})
        assert jwks.verify_rs256(token, keyset) == {"hello": "world"}

    def test_tampered_payload_fails(self, keypair):
        key, keyset = keypair
        token = make_jwt(key, {"hello": "world"})
        h, p, s = token.split(".")
        p2 = _b64url(json.dumps({"hello": "mallory"}).encode())
        with pytest.raises(jwks.JwksError):
            jwks.verify_rs256(f"{h}.{p2}.{s}", keyset)

    def test_foreign_key_fails(self, keypair):
        _, keyset = keypair
        other = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        token = make_jwt(other, {"hello": "world"})
        with pytest.raises(jwks.JwksError):
            jwks.verify_rs256(token, keyset)

    def test_non_rs256_rejected(self, keypair):
        key, keyset = keypair
        token = make_jwt(key, {"x": 1}, alg="none")
        with pytest.raises(jwks.JwksError):
            jwks.verify_rs256(token, keyset)

    def test_unknown_kid_still_tries_all_keys(self, keypair):
        key, keyset = keypair
        token = make_jwt(key, {"x": 1}, kid="rotated-away")
        assert jwks.verify_rs256(token, keyset) == {"x": 1}

    def test_empty_jwks_fails(self, keypair):
        key, _ = keypair
        token = make_jwt(key, {"x": 1})
        with pytest.raises(jwks.JwksError):
            jwks.verify_rs256(token, {"keys": []})


class TestLoadJwks:
    def test_offline_file_wins(self, jwks_env):
        assert jwks.load_jwks() == jwks_env

    def test_nothing_available_returns_none(self, tmp_path, monkeypatch):
        monkeypatch.delenv(jwks.JWKS_FILE_ENV, raising=False)
        assert (
            jwks.load_jwks(
                cache_file=str(tmp_path / "absent.json"),
                url="http://127.0.0.1:1/certs",
                fetch_timeout_s=0.2,
            )
            is None
        )

    def test_broken_offline_file_fails_closed(self, tmp_path, monkeypatch):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        monkeypatch.setenv(jwks.JWKS_FILE_ENV, str(bad))
        assert jwks.load_jwks() is None

    def test_missing_offline_file_falls_through(self, keypair, tmp_path,
                                                monkeypatch):
        """The DaemonSet sets the offline path unconditionally; absence is
        optional provisioning, not misconfiguration — the cache still
        serves."""
        _, keyset = keypair
        monkeypatch.setenv(jwks.JWKS_FILE_ENV, str(tmp_path / "absent.json"))
        cache = tmp_path / "cache.json"
        cache.write_text(
            json.dumps({"fetched_at": time.time(), "jwks": keyset})
        )
        assert jwks.load_jwks(cache_file=str(cache)) == keyset


class TestTpuvmQuoteVerification:
    def test_valid_quote_passes(self, keypair, jwks_env):
        key, _ = keypair
        nonce = fresh_nonce()
        quote = tpuvm_quote(make_jwt(key, gce_claims(nonce)), nonce)
        assert verify_quote(quote, nonce, MODE_ON, "slice-a") == []

    def test_bad_signature_fails_closed(self, keypair, jwks_env):
        key, _ = keypair
        nonce = fresh_nonce()
        token = make_jwt(key, gce_claims(nonce))
        h, p, _ = token.split(".")
        forged = f"{h}.{p}.{_b64url(b'0' * 256)}"
        with pytest.raises(AttestationError, match="signature"):
            verify_quote(tpuvm_quote(forged, nonce), nonce, MODE_ON, "slice-a")

    def test_wrong_issuer_fails_closed(self, keypair, jwks_env):
        key, _ = keypair
        nonce = fresh_nonce()
        token = make_jwt(key, gce_claims(nonce, iss="https://evil.example"))
        with pytest.raises(AttestationError, match="issuer"):
            verify_quote(tpuvm_quote(token, nonce), nonce, MODE_ON, "slice-a")

    def test_expired_token_fails_closed(self, keypair, jwks_env):
        key, _ = keypair
        nonce = fresh_nonce()
        token = make_jwt(key, gce_claims(nonce, exp=int(time.time()) - 10))
        with pytest.raises(AttestationError, match="expired"):
            verify_quote(tpuvm_quote(token, nonce), nonce, MODE_ON, "slice-a")

    def test_unbound_nonce_fails_closed(self, keypair, jwks_env):
        key, _ = keypair
        nonce = fresh_nonce()
        token = make_jwt(key, gce_claims("a-different-nonce"))
        with pytest.raises(AttestationError, match="nonce"):
            verify_quote(tpuvm_quote(token, nonce), nonce, MODE_ON, "slice-a")

    def test_no_key_material_fails_closed(self, keypair, monkeypatch):
        key, _ = keypair
        nonce = fresh_nonce()
        quote = tpuvm_quote(make_jwt(key, gce_claims(nonce)), nonce)
        from tpu_cc_manager.tpudev import attestation as att_mod

        monkeypatch.setattr(att_mod.jwks, "load_jwks", lambda **kw: None)
        with pytest.raises(AttestationError, match="failing closed"):
            verify_quote(quote, nonce, MODE_ON, "slice-a")


class TestFakeQuotePolicy:
    def test_fake_quote_rejected_by_default(self, fake_tpu):
        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        with pytest.raises(AttestationError, match="fake-platform"):
            verify_quote(quote, nonce, quote.mode)

    def test_fake_quote_allowed_when_opted_in(self, fake_tpu):
        nonce = fresh_nonce()
        quote = fake_tpu.fetch_attestation(nonce)
        assert verify_quote(quote, nonce, quote.mode, allow_fake=True) == []
