"""utils/retry.py edges: Retry-After parsing, jitter bounds, classified
retries, deadline budgets, poll_until, and circuit-breaker transitions —
plus the REST client riding the shared policy (Retry-After honored,
breaker fail-fast)."""

from __future__ import annotations

import random

import pytest

from tpu_cc_manager.kubeclient.api import KubeApiError, classify_kube_error
from tpu_cc_manager.utils import retry
from tpu_cc_manager.utils.metrics import MetricsRegistry


def make_policy(**kwargs):
    kwargs.setdefault("rng", random.Random(42))
    kwargs.setdefault("sleep", lambda s: None)
    kwargs.setdefault("metrics", MetricsRegistry())
    return retry.RetryPolicy(**kwargs)


class TestRetryAfterParsing:
    def test_delta_seconds(self):
        assert retry.parse_retry_after("120") == 120.0
        assert retry.parse_retry_after(" 2.5 ") == 2.5

    def test_negative_clamps_to_zero(self):
        assert retry.parse_retry_after("-3") == 0.0

    def test_http_date(self):
        import email.utils
        import time as _time

        future = email.utils.formatdate(_time.time() + 60, usegmt=True)
        parsed = retry.parse_retry_after(future)
        assert parsed is not None and 50 < parsed <= 61

    def test_past_http_date_clamps_to_zero(self):
        import email.utils
        import time as _time

        past = email.utils.formatdate(_time.time() - 3600, usegmt=True)
        assert retry.parse_retry_after(past) == 0.0

    def test_garbage_and_absent_degrade_to_none(self):
        assert retry.parse_retry_after(None) is None
        assert retry.parse_retry_after("") is None
        assert retry.parse_retry_after("soon-ish") is None


class TestJitter:
    def test_full_jitter_stays_within_exponential_cap(self):
        policy = make_policy(base_delay_s=1.0, max_delay_s=8.0)
        for attempt, cap in ((0, 1.0), (1, 2.0), (2, 4.0), (3, 8.0), (9, 8.0)):
            for _ in range(200):
                d = policy.delay_for(attempt)
                assert 0.0 <= d <= cap, (attempt, d)

    def test_seeded_rng_reproduces_schedule(self):
        a = make_policy(rng=random.Random(7))
        b = make_policy(rng=random.Random(7))
        assert [a.delay_for(i) for i in range(6)] == [
            b.delay_for(i) for i in range(6)
        ]

    def test_retry_after_is_a_floor_not_a_suggestion(self):
        policy = make_policy(base_delay_s=0.001, max_delay_s=0.002)
        for _ in range(50):
            assert policy.delay_for(0, retry_after_s=5.0) >= 5.0

    def test_jitter_off_returns_the_cap(self):
        policy = make_policy(jitter=False, base_delay_s=1.0, max_delay_s=30.0)
        assert policy.delay_for(2) == 4.0


class TestClassifiedCall:
    def test_transient_then_success(self):
        policy = make_policy(base_delay_s=0.001)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise KubeApiError(503, "hiccup")
            return "ok"

        assert policy.call(flaky, op="t", classify=classify_kube_error) == "ok"
        assert calls["n"] == 3

    def test_permanent_raises_immediately(self):
        policy = make_policy()
        calls = {"n": 0}

        def nope():
            calls["n"] += 1
            raise KubeApiError(404, "gone for good")

        with pytest.raises(KubeApiError):
            policy.call(nope, op="t", classify=classify_kube_error)
        assert calls["n"] == 1

    def test_exhaustion_reraises_original_error(self):
        policy = make_policy(max_attempts=2, base_delay_s=0.001)

        def always():
            raise KubeApiError(503, "still down")

        with pytest.raises(KubeApiError) as exc:
            policy.call(always, op="t", classify=classify_kube_error)
        assert exc.value.status == 503

    def test_deadline_budget_stops_retrying(self):
        """A retry whose backoff would cross the operation deadline raises
        instead of sleeping past the budget."""
        clock = {"now": 0.0}
        sleeps = []

        policy = retry.RetryPolicy(
            max_attempts=10,
            base_delay_s=1.0,
            max_delay_s=1.0,
            deadline_s=2.5,
            jitter=False,
            rng=random.Random(0),
            sleep=lambda s: (sleeps.append(s), clock.__setitem__("now", clock["now"] + s)),
            clock=lambda: clock["now"],
            metrics=MetricsRegistry(),
        )
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise KubeApiError(None, "reset")

        with pytest.raises(KubeApiError):
            policy.call(always, op="t", classify=classify_kube_error)
        # 1 s + 1 s fits in the 2.5 s budget; the third sleep would land at
        # 3 s > 2.5 s, so exactly 3 attempts ran.
        assert calls["n"] == 3
        assert sleeps == [1.0, 1.0]

    def test_retries_are_counted_per_op_and_reason(self):
        registry = MetricsRegistry()
        policy = make_policy(base_delay_s=0.001, metrics=registry)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise KubeApiError(429, "slow down")
            return "ok"

        policy.call(flaky, op="kube.get", classify=classify_kube_error)
        assert registry.retry_totals() == {("kube.get", "throttled"): 2}
        text = registry.render_prometheus()
        assert 'tpu_cc_retries_total{op="kube.get",reason="throttled"} 2' in text

    def test_retry_annotates_current_span(self):
        from tpu_cc_manager.obs import journal as journal_mod
        from tpu_cc_manager.obs import trace as trace_mod

        policy = make_policy(base_delay_s=0.001)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 2:
                raise KubeApiError(503, "hiccup")
            return "ok"

        with trace_mod.root_span("t", journal=journal_mod.Journal()) as sp:
            policy.call(flaky, op="kube.get", classify=classify_kube_error)
        assert sp.attributes["retries"][0]["op"] == "kube.get"
        assert sp.attributes["retries"][0]["reason"] == "http-503"


class TestPollUntil:
    def test_converges(self):
        state = {"n": 0}

        def pred():
            state["n"] += 1
            return state["n"] >= 3

        assert retry.poll_until(pred, 10.0, 0.001) is True
        assert state["n"] == 3

    def test_timeout_returns_false_after_at_least_one_poll(self):
        polls = {"n": 0}

        def pred():
            polls["n"] += 1
            return False

        assert retry.poll_until(pred, 0.0, 0.001) is False
        assert polls["n"] == 1

    def test_never_sleeps_past_the_deadline(self):
        clock = {"now": 0.0}
        sleeps = []

        def sleep(s):
            sleeps.append(s)
            clock["now"] += s

        assert (
            retry.poll_until(
                lambda: False, 1.0, 0.4,
                sleep=sleep, clock=lambda: clock["now"],
            )
            is False
        )
        assert sum(sleeps) <= 1.0 + 1e-9
        assert sleeps[-1] <= 0.4


class TestCircuitBreaker:
    def make(self, **kwargs):
        self.clock = {"now": 0.0}
        kwargs.setdefault("failure_threshold", 3)
        kwargs.setdefault("recovery_time_s", 10.0)
        kwargs.setdefault("clock", lambda: self.clock["now"])
        kwargs.setdefault("metrics", MetricsRegistry())
        return retry.CircuitBreaker("dep", **kwargs)

    def test_opens_after_threshold_and_fails_fast(self):
        br = self.make()
        for _ in range(3):
            br.before_call()
            br.record_failure()
        assert br.state == retry.BREAKER_OPEN
        with pytest.raises(retry.CircuitOpenError):
            br.before_call()

    def test_success_resets_the_failure_count(self):
        br = self.make()
        for _ in range(2):
            br.record_failure()
        br.record_success()
        for _ in range(2):
            br.record_failure()
        assert br.state == retry.BREAKER_CLOSED

    def test_half_open_probe_success_closes(self):
        br = self.make()
        for _ in range(3):
            br.record_failure()
        self.clock["now"] = 10.0  # recovery window elapsed
        assert br.state == retry.BREAKER_HALF_OPEN
        br.before_call()  # the single probe
        # A second caller during the probe is still rejected.
        with pytest.raises(retry.CircuitOpenError):
            br.before_call()
        br.record_success()
        assert br.state == retry.BREAKER_CLOSED
        br.before_call()  # closed again: calls flow

    def test_half_open_probe_failure_reopens(self):
        br = self.make()
        for _ in range(3):
            br.record_failure()
        self.clock["now"] = 10.0
        br.before_call()
        br.record_failure()
        assert br.state == retry.BREAKER_OPEN
        with pytest.raises(retry.CircuitOpenError):
            br.before_call()
        # ...until another recovery window passes.
        self.clock["now"] = 20.0
        br.before_call()
        br.record_success()
        assert br.state == retry.BREAKER_CLOSED

    def test_state_exported_to_metrics(self):
        registry = MetricsRegistry()
        br = self.make(metrics=registry)
        assert registry.breaker_states()["dep"] == "closed"
        for _ in range(3):
            br.record_failure()
        assert registry.breaker_states()["dep"] == "open"
        assert 'tpu_cc_breaker_state{path="dep"} 2' in registry.render_prometheus()


class TestRestClientPolicy:
    """The REST client rides the shared policy: Retry-After honored,
    breaker opens after sustained transport failure."""

    def make_client(self, **kwargs):
        from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

        sleeps = []
        policy = retry.RetryPolicy(
            max_attempts=3,
            base_delay_s=0.001,
            max_delay_s=0.01,
            rng=random.Random(1),
            sleep=sleeps.append,
            metrics=MetricsRegistry(),
        )
        client = RestKube(
            ClusterConfig(server="http://x"), retry_policy=policy, **kwargs
        )
        return client, sleeps

    def test_retry_after_header_is_honored(self):
        client, sleeps = self.make_client()
        calls = {"n": 0}

        def throttled(method, path, query=None, body=None, content_type=None,
                      read_timeout=30.0):
            calls["n"] += 1
            if calls["n"] == 1:
                raise KubeApiError(429, "slow down", retry_after_s=3.5)
            import io
            import json as _json

            return io.BytesIO(_json.dumps({"metadata": {}}).encode())

        client._open = throttled  # type: ignore[method-assign]
        client.get_node("n")
        assert calls["n"] == 2
        # The jittered backoff cap is 0.001 s; only the header explains 3.5.
        assert sleeps == [3.5]

    def test_breaker_opens_and_fails_fast(self):
        client, _ = self.make_client(
            breaker=retry.CircuitBreaker(
                "apiserver", failure_threshold=2, recovery_time_s=60.0,
                metrics=MetricsRegistry(),
            )
        )
        calls = {"n": 0}

        def down(method, path, query=None, body=None, content_type=None,
                 read_timeout=30.0):
            calls["n"] += 1
            raise KubeApiError(None, "connection refused")

        client._open = down  # type: ignore[method-assign]
        with pytest.raises(KubeApiError):
            client.get_node("n")
        assert calls["n"] == 2  # third attempt was rejected by the breaker
        # Subsequent calls fail fast without touching the network.
        with pytest.raises(KubeApiError):
            client.get_node("n")
        assert calls["n"] == 2

    def test_definitive_4xx_resets_the_breaker(self):
        client, _ = self.make_client(
            breaker=retry.CircuitBreaker(
                "apiserver", failure_threshold=2, recovery_time_s=60.0,
                metrics=MetricsRegistry(),
            )
        )

        def not_found(method, path, query=None, body=None, content_type=None,
                      read_timeout=30.0):
            raise KubeApiError(404, "no such node")

        client._open = not_found  # type: ignore[method-assign]
        for _ in range(5):
            with pytest.raises(KubeApiError):
                client.get_node("n")
        assert client.breaker.state == retry.BREAKER_CLOSED


class TestBreakerProbeRecovery:
    """Half-open probe slots must never wedge the breaker (review finding:
    a probe ending in a permanent/unclassified failure used to leak
    _probe_in_flight forever)."""

    def make(self):
        self.clock = {"now": 0.0}
        return retry.CircuitBreaker(
            "dep", failure_threshold=2, recovery_time_s=10.0,
            clock=lambda: self.clock["now"], metrics=MetricsRegistry(),
        )

    def trip(self, br):
        for _ in range(2):
            br.record_failure()
        self.clock["now"] += 10.0

    def test_record_permanent_releases_the_probe_slot(self):
        br = self.make()
        self.trip(br)
        br.before_call()           # probe granted
        br.record_permanent()      # probe failed for a health-unrelated reason
        br.before_call()           # next caller can probe immediately
        br.record_success()
        assert br.state == retry.BREAKER_CLOSED

    def test_unrecorded_probe_lease_expires(self):
        br = self.make()
        self.trip(br)
        br.before_call()  # probe granted, then its caller dies silently
        with pytest.raises(retry.CircuitOpenError):
            br.before_call()
        self.clock["now"] += 10.0  # lease expired
        br.before_call()           # a new probe takes over
        br.record_success()
        assert br.state == retry.BREAKER_CLOSED


class TestRestClientBreakerEdges:
    def make_client(self, breaker):
        from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

        sleeps = []
        policy = retry.RetryPolicy(
            max_attempts=3, base_delay_s=0.001, max_delay_s=0.01,
            rng=random.Random(1), sleep=sleeps.append,
            metrics=MetricsRegistry(),
        )
        return RestKube(
            ClusterConfig(server="http://x"),
            retry_policy=policy, breaker=breaker,
        ), sleeps

    def test_open_circuit_fails_fast_without_retry_sleeps(self):
        """A rejected call must not sleep through the retry ladder against
        a known-open circuit (review finding: CircuitOpenError was wrapped
        as a transient KubeApiError)."""
        br = retry.CircuitBreaker(
            "apiserver", failure_threshold=1, recovery_time_s=60.0,
            metrics=MetricsRegistry(),
        )
        client, sleeps = self.make_client(br)

        def down(method, path, query=None, body=None, content_type=None,
                 read_timeout=30.0):
            raise KubeApiError(None, "refused")

        client._open = down  # type: ignore[method-assign]
        with pytest.raises(KubeApiError):
            client.get_node("n")  # trips the breaker
        sleeps.clear()
        with pytest.raises(KubeApiError):
            client.get_node("n")  # rejected by the open breaker
        assert sleeps == []  # fail-fast: zero backoff sleeps

    def test_body_read_failure_is_retried_and_counted(self):
        """OSError/JSONDecodeError after the connection opened ride the
        same retry/breaker bracket as connect-time failures (review
        finding: they used to escape both)."""
        import io

        br = retry.CircuitBreaker(
            "apiserver", failure_threshold=10, recovery_time_s=60.0,
            metrics=MetricsRegistry(),
        )
        client, _ = self.make_client(br)
        calls = {"n": 0}

        class Garbled(io.BytesIO):
            def read(self, *a):
                raise OSError("connection reset mid-body")

        def flaky(method, path, query=None, body=None, content_type=None,
                  read_timeout=30.0):
            import json as _json

            calls["n"] += 1
            if calls["n"] == 1:
                return Garbled()
            return io.BytesIO(_json.dumps({"metadata": {}}).encode())

        client._open = flaky  # type: ignore[method-assign]
        client.get_node("n")  # retried transparently
        assert calls["n"] == 2


def test_retry_after_is_clamped_to_its_ceiling():
    """A proxy saying 'come back in an hour' must not park a control-plane
    thread: Retry-After is a floor only up to retry_after_cap_s."""
    policy = make_policy(base_delay_s=0.001, max_delay_s=0.01,
                         retry_after_cap_s=2.0)
    for _ in range(20):
        assert policy.delay_for(0, retry_after_s=3600.0) <= 2.0
    # Below the ceiling it stays an exact floor.
    assert policy.delay_for(0, retry_after_s=1.5) >= 1.5


def test_incomplete_read_wraps_into_kube_api_error():
    """http.client.IncompleteRead (truncated body) is neither OSError nor
    ValueError; it must still ride the retry/breaker bracket instead of
    escaping raw to callers that only handle KubeApiError."""
    import http.client
    import io
    import json as _json

    from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

    client = RestKube(
        ClusterConfig(server="http://x"),
        retry_policy=retry.RetryPolicy(
            max_attempts=3, base_delay_s=0.001, sleep=lambda s: None,
            rng=random.Random(3), metrics=MetricsRegistry(),
        ),
    )
    calls = {"n": 0}

    class Truncated(io.BytesIO):
        def read(self, *a):
            raise http.client.IncompleteRead(b"partial")

    def flaky(method, path, query=None, body=None, content_type=None,
              read_timeout=30.0):
        calls["n"] += 1
        if calls["n"] == 1:
            return Truncated()
        return io.BytesIO(_json.dumps({"metadata": {}}).encode())

    client._open = flaky  # type: ignore[method-assign]
    client.get_node("n")  # wrapped, classified transient, retried
    assert calls["n"] == 2


def test_faulty_client_forwards_retries_internally_flag():
    """Wrapping must not change the retry layering decision."""
    from tpu_cc_manager.faults import FaultPlan, FaultyKubeClient
    from tpu_cc_manager.kubeclient.api import caller_retry_attempts
    from tpu_cc_manager.kubeclient.fake import FakeKube
    from tpu_cc_manager.kubeclient.rest import ClusterConfig, RestKube

    fake_wrapped = FaultyKubeClient(FakeKube(), FaultPlan(seed=1))
    assert caller_retry_attempts(fake_wrapped) == 3
    rest_wrapped = FaultyKubeClient(
        RestKube(ClusterConfig(server="http://x")), FaultPlan(seed=1)
    )
    assert caller_retry_attempts(rest_wrapped) == 1
