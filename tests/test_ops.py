"""Pallas kernels and ring attention vs the plain-XLA oracle (ops/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_cc_manager.ops.flash_attention import flash_attention, reference_attention
from tpu_cc_manager.ops.matmul import tiled_matmul
from tpu_cc_manager.ops.ring_attention import ring_attention


def attn_inputs(B=1, H=2, S=128, D=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, H, S, D), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


class TestFlashAttention:
    def test_matches_reference_causal(self):
        q, k, v = attn_inputs()
        out = flash_attention(q, k, v, True, 64, 64)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_matches_reference_noncausal(self):
        q, k, v = attn_inputs(S=64)
        out = flash_attention(q, k, v, False, 32, 32)
        ref = reference_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_noncausal_indivisible_seq_masks_padding(self):
        """S=96 with 64-blocks pads the tail key block; phantom keys must
        not enter the softmax normalizer (regression: the padding mask was
        only applied on the causal path)."""
        q, k, v = attn_inputs(S=96)
        out = flash_attention(q, k, v, False, 64, 64)
        ref = reference_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_causal_indivisible_seq(self):
        q, k, v = attn_inputs(S=96)
        out = flash_attention(q, k, v, True, 64, 64)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_unequal_blocks(self):
        q, k, v = attn_inputs(S=128)
        out = flash_attention(q, k, v, True, 64, 32)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_bf16_inputs(self):
        q, k, v = attn_inputs(dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, True, 64, 64)
        ref = reference_attention(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
        )
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref, atol=3e-2, rtol=3e-2
        )

    def test_gradients_flow(self):
        q, k, v = attn_inputs(S=64)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, 32, 32) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(reference_attention(q, k, v) ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(g, rg, atol=1e-4, rtol=1e-4)

    def _grad_check(self, S, causal, block_q, block_k, atol=1e-4):
        """dq/dk/dv of the flash backward (block recomputation, never an
        (S,S) buffer) against the plain-XLA vjp."""
        q, k, v = attn_inputs(S=S)
        # A non-symmetric loss so dq/dk/dv all get distinct cotangents.
        w = jnp.arange(S, dtype=jnp.float32)[None, None, :, None] / S

        def loss(q, k, v):
            return jnp.sum(w * flash_attention(q, k, v, causal, block_q, block_k))

        def ref_loss(q, k, v):
            return jnp.sum(w * reference_attention(q, k, v, causal))

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, rg, name in zip(grads, ref_grads, "q k v".split()):
            np.testing.assert_allclose(
                g, rg, atol=atol, rtol=atol, err_msg=f"d{name} mismatch"
            )

    def test_gradients_indivisible_seq(self):
        # Tail blocks on BOTH the dq (key tail) and dk/dv (query tail)
        # kernels: 96 % 64 != 0.
        self._grad_check(S=96, causal=True, block_q=64, block_k=64)

    def test_gradients_unequal_blocks(self):
        self._grad_check(S=128, causal=True, block_q=64, block_k=32)

    def test_gradients_non_causal(self):
        self._grad_check(S=96, causal=False, block_q=64, block_k=64)

    def test_gradients_bf16(self):
        q, k, v = attn_inputs(S=64, dtype=jnp.bfloat16)

        def loss(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, 32, 32).astype(jnp.float32)
            )

        def ref_loss(q, k, v):
            return jnp.sum(
                reference_attention(
                    q.astype(jnp.float32),
                    k.astype(jnp.float32),
                    v.astype(jnp.float32),
                )
            )

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, rg in zip(grads, ref_grads):
            assert g.dtype == jnp.bfloat16  # grads match primal dtype
            np.testing.assert_allclose(
                g.astype(jnp.float32), rg, atol=5e-2, rtol=5e-2
            )

    def test_gradients_under_jit_and_larger_seq(self):
        # A size where materializing (S,S) per head would dominate memory;
        # the backward must still agree with the reference vjp under jit.
        self._grad_check(S=384, causal=True, block_q=128, block_k=128)


class TestTiledMatmul:
    def test_matches_xla(self):
        a = jax.random.normal(jax.random.PRNGKey(0), (256, 512), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(1), (512, 128), jnp.float32)
        out = tiled_matmul(a, b, block_m=128, block_n=128, block_k=128)
        np.testing.assert_allclose(out, a @ b, atol=1e-3, rtol=1e-5)

    def test_bf16_accumulates_f32(self):
        a = jax.random.normal(jax.random.PRNGKey(0), (256, 256)).astype(jnp.bfloat16)
        b = jax.random.normal(jax.random.PRNGKey(1), (256, 256)).astype(jnp.bfloat16)
        out = tiled_matmul(a, b, block_m=128, block_n=128, block_k=128)
        assert out.dtype == jnp.float32
        ref = jnp.matmul(a, b, preferred_element_type=jnp.float32)
        np.testing.assert_allclose(out, ref, atol=1e-2, rtol=1e-2)

    def test_rejects_indivisible(self):
        a = jnp.zeros((100, 128))
        b = jnp.zeros((128, 128))
        with pytest.raises(ValueError):
            tiled_matmul(a, b, block_m=64, block_n=64, block_k=64)

    def test_full_k_single_step(self):
        # k_steps=1 (full-K block): zero-init and writeback fire on the
        # same (only) grid step — the path the sweep's full-K rungs use.
        a = jax.random.normal(jax.random.PRNGKey(0), (256, 512)).astype(jnp.bfloat16)
        b = jax.random.normal(jax.random.PRNGKey(1), (512, 128)).astype(jnp.bfloat16)
        out = tiled_matmul(a, b, block_m=128, block_n=128, block_k=512)
        ref = jnp.matmul(a, b, preferred_element_type=jnp.float32)
        np.testing.assert_allclose(out, ref, atol=1e-2, rtol=1e-2)


class TestDefaultBlocks:
    def test_known_generation_table(self, monkeypatch):
        # A synthetic entry distinct from the fallback, so this actually
        # proves the per-generation dispatch (today's v5e entry happens
        # to equal the fallback, which would make the assertion vacuous).
        from tpu_cc_manager.ops import matmul

        monkeypatch.setitem(matmul.DEFAULT_BLOCKS, "vtest", (1024, 512, 2048))
        assert matmul.default_blocks("vtest", 4096) == (1024, 512, 2048)

    def test_unknown_generation_inherits_fallback(self):
        from tpu_cc_manager.ops import matmul

        assert matmul.default_blocks(None, 4096) == matmul._FALLBACK_BLOCKS
        assert matmul.default_blocks("v99x", 4096) == matmul._FALLBACK_BLOCKS

    def test_clamped_to_divide_size(self):
        from tpu_cc_manager.ops.matmul import default_blocks

        # 256 < 512: clamp; every returned dim divides the size.
        assert default_blocks("v5e", 256) == (256, 256, 256)
        # Non-power-of-two multiple of a small power of two: halve until
        # dividing (384 = 3 * 128 -> clamp 512 -> 384 divides).
        for dim in default_blocks("v5e", 384):
            assert 384 % dim == 0 and dim >= 1


class TestRingAttention:
    def test_matches_reference_on_ring(self):
        from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(dcn=1, dp=4, fsdp=1, tp=1),
                         devices=jax.devices()[:4])
        q, k, v = attn_inputs(B=2, H=2, S=64, D=16)
        out = ring_attention(q, k, v, mesh, axis="dp")
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_rejects_indivisible_sequence(self):
        from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(dcn=1, dp=4, fsdp=1, tp=1),
                         devices=jax.devices()[:4])
        q, k, v = attn_inputs(S=30, D=16)
        with pytest.raises(ValueError):
            ring_attention(q, k, v, mesh, axis="dp")
