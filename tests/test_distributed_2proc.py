"""Real 2-process ``jax.distributed`` execution (VERDICT r3 item 3).

Everything else in the suite exercises multi-chip sharding on a
single-process virtual mesh; this test spawns TWO OS processes that
perform the actual coordinator handshake (``jax.distributed.initialize``
via ``parallel.distributed.bootstrap``), build a ``dcn=2`` mesh whose dcn
axis crosses the process boundary, pass ``verify_dcn_mesh``, and run one
train step whose gradient reduction crosses processes (tests/dcn_child.py).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# Environment limitations (not code bugs): jaxlib builds whose CPU
# backend cannot run cross-process computations, and coordinator
# handshakes that cannot complete inside sandboxed/loopback-restricted
# containers. A child failing with one of these skips the test cleanly;
# any other failure still fails it.
_ENV_SKIP_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "failed to connect to all addresses",
    "Barrier timed out",
    "DEADLINE_EXCEEDED: Barrier",
    "coordination service",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_dcn_mesh_and_train_step():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "dcn_child.py"),
             str(port), str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        if rc != 0:
            blob = out + err
            marker = next(
                (m for m in _ENV_SKIP_MARKERS if m in blob), None
            )
            if marker is not None:
                pytest.skip(
                    "environment cannot run 2-process jax.distributed: "
                    f"{marker!r}"
                )
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err}"
        assert "DCN_CHILD_OK" in out
    # Replicated results must agree across processes (same losses printed).
    assert outs[0][1].split("losses=")[1] == outs[1][1].split("losses=")[1]
