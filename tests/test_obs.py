"""Unit tests for the tracing subsystem (tpu_cc_manager/obs/): span
nesting, contextvar propagation across threads, journal ring bounding,
and JSONL sink rotation."""

from __future__ import annotations

import json
import threading

import pytest

from tpu_cc_manager.obs import journal as journal_mod
from tpu_cc_manager.obs import trace


@pytest.fixture()
def journal():
    return journal_mod.Journal(capacity=64, trace_file="")


def test_span_nesting_shares_trace_and_links_parents(journal):
    with trace.root_span("reconcile", journal=journal, mode="on") as root:
        assert trace.current_span() is root
        with trace.span("drain") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            with trace.span("drain.await_pods") as grandchild:
                assert grandchild.trace_id == root.trace_id
                assert grandchild.parent_id == child.span_id
    assert trace.current_span() is None
    spans = journal.spans()
    # Finish order is innermost-first.
    assert [s["name"] for s in spans] == [
        "drain.await_pods", "drain", "reconcile",
    ]
    assert len({s["trace_id"] for s in spans}) == 1
    assert all(s["status"] == "ok" for s in spans)
    assert spans[2]["attributes"]["mode"] == "on"


def test_root_span_ignores_ambient_span(journal):
    with trace.root_span("outer", journal=journal) as outer:
        with trace.root_span("inner", journal=journal) as inner:
            assert inner.trace_id != outer.trace_id
            assert inner.parent_id is None


def test_escaping_exception_marks_span_error(journal):
    with pytest.raises(ValueError):
        with trace.root_span("reconcile", journal=journal):
            with trace.span("reset"):
                raise ValueError("chip gone")
    reset, reconcile = journal.spans()
    assert reset["name"] == "reset"
    assert reset["status"] == "error"
    assert "chip gone" in reset["error"]
    assert reconcile["status"] == "error"


def test_child_inherits_parent_journal(journal):
    """A child span must land in the root's journal, not the global one,
    even when the opener never names a journal (the drain/barrier/smoke
    layers never do)."""
    before = len(journal_mod.JOURNAL.spans())
    with trace.root_span("reconcile", journal=journal):
        with trace.span("drain"):
            pass
    assert len(journal.spans()) == 2
    assert len(journal_mod.JOURNAL.spans()) == before


def test_contextvar_does_not_leak_to_bare_threads(journal):
    """threading.Thread targets start with a fresh context: without the
    propagation helper a span opened in the thread is a new root."""
    seen = {}

    def worker():
        with trace.span("inner", journal=journal) as sp:
            seen["trace_id"] = sp.trace_id
            seen["parent_id"] = sp.parent_id

    with trace.root_span("outer", journal=journal) as outer:
        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=5)
    assert seen["trace_id"] != outer.trace_id
    assert seen["parent_id"] is None


def test_in_current_context_propagates_across_threads(journal):
    seen = {}

    def worker():
        with trace.span("inner") as sp:
            seen["trace_id"] = sp.trace_id
            seen["parent_id"] = sp.parent_id

    with trace.root_span("outer", journal=journal) as outer:
        t = threading.Thread(target=trace.in_current_context(worker))
        t.start()
        t.join(timeout=5)
    assert seen["trace_id"] == outer.trace_id
    assert seen["parent_id"] == outer.span_id
    # And the inner span landed in the root's journal via inheritance.
    assert "inner" in [s["name"] for s in journal.spans()]


def test_journal_ring_is_bounded():
    j = journal_mod.Journal(capacity=4, trace_file="")
    for i in range(10):
        with trace.root_span(f"span-{i}", journal=j):
            pass
    spans = j.spans()
    assert len(spans) == 4
    assert [s["name"] for s in spans] == [
        "span-6", "span-7", "span-8", "span-9",
    ]


def test_journal_filters_and_trees(journal):
    with trace.root_span("a", journal=journal) as a:
        with trace.span("a.child"):
            pass
    with trace.root_span("b", journal=journal) as b:
        pass
    assert set(journal.trace_ids()) == {a.trace_id, b.trace_id}
    only_a = journal.spans(trace_id=a.trace_id)
    assert [s["name"] for s in only_a] == ["a.child", "a"]
    tree = journal.span_tree(only_a)
    assert len(tree) == 1
    assert tree[0]["name"] == "a"
    assert [c["name"] for c in tree[0]["children"]] == ["a.child"]
    assert journal.spans(limit=1)[-1]["name"] == "b"


def test_active_spans_visible_in_flight(journal):
    with trace.root_span("reconcile", journal=journal):
        with trace.span("drain"):
            live = {s["name"] for s in journal.active_spans()}
            assert live == {"reconcile", "drain"}
    assert journal.active_spans() == []


def test_jsonl_sink_writes_one_object_per_line(tmp_path):
    path = tmp_path / "trace.jsonl"
    j = journal_mod.Journal(capacity=16, trace_file=str(path))
    with trace.root_span("reconcile", journal=j, mode="on"):
        with trace.span("drain"):
            pass
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2
    parsed = [json.loads(line) for line in lines]
    assert [p["name"] for p in parsed] == ["drain", "reconcile"]
    assert len({p["trace_id"] for p in parsed}) == 1


def test_jsonl_sink_rotates_at_size_cap(tmp_path):
    path = tmp_path / "trace.jsonl"
    j = journal_mod.Journal(
        capacity=1024, trace_file=str(path), max_file_bytes=2048
    )
    for i in range(64):
        with trace.root_span(f"span-{i}", journal=j, filler="x" * 64):
            pass
    rotated = tmp_path / "trace.jsonl.1"
    assert rotated.exists(), "no rotation happened"
    assert path.stat().st_size <= 2048 + 512  # one line of slack
    assert rotated.stat().st_size <= 2048 + 512
    # Both files still parse line-by-line (rotation never splits a line).
    for f in (path, rotated):
        for line in f.read_text().strip().splitlines():
            json.loads(line)


def test_json_log_lines_carry_trace_ids(journal):
    """JsonFormatter picks trace_id/span_id up from the contextvar, so
    every log line emitted inside a reconcile correlates with its span
    tree; outside any span the fields are absent."""
    import logging

    from tpu_cc_manager.utils.logging import JsonFormatter

    fmt = JsonFormatter()

    def record(msg):
        return logging.LogRecord(
            "test", logging.INFO, __file__, 1, msg, (), None
        )

    with trace.root_span("reconcile", journal=journal) as root:
        with trace.span("drain") as child:
            line = json.loads(fmt.format(record("pausing components")))
    assert line["trace_id"] == root.trace_id
    assert line["span_id"] == child.span_id
    outside = json.loads(fmt.format(record("idle")))
    assert "trace_id" not in outside


def test_journal_phase_durations(journal):
    with trace.root_span("reconcile", journal=journal):
        with trace.span("drain"):
            pass
        with trace.span("drain"):
            pass
        with trace.span("reset"):
            pass
    durations = journal.phase_durations(("drain", "reset"))
    assert len(durations["drain"]) == 2
    assert len(durations["reset"]) == 1
    assert "reconcile" not in durations
