"""Informer cache (ccmanager/informer.py): consistency under chaos.

The contract the fleet-scale refactor stands on: after the watch stream
catches up, the cache equals a fresh listing of the same selector — for
any seeded FaultPlan schedule of watch hangups, stale-rv 410s and
blackouts, and across label churn that moves nodes in and out of the
selector. If this holds, every consumer that swapped its O(pool)
listings for cache reads (rolling, pool attestation, the slice barrier)
reads the same truth it used to pay round trips for.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from tpu_cc_manager.ccmanager.informer import NodeInformer
from tpu_cc_manager.faults.kube import FaultyKubeClient
from tpu_cc_manager.faults.plan import FaultPlan
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.kubeclient.api import (
    KubeApi,
    KubeApiError,
    node_labels,
)
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import SLICE_ID_LABEL

POOL = "pool=tpu"


def make_informer(api, **kw):
    kw.setdefault("reconnect_delay_s", 0.01)
    kw.setdefault("reconnect_max_delay_s", 0.05)
    return NodeInformer(api, POOL, **kw)


def pool_view(fake):
    """The ground truth the cache must converge to: name -> labels."""
    return {
        n["metadata"]["name"]: dict(node_labels(n))
        for n in fake.list_nodes(POOL)
    }


def cache_view(informer):
    return {
        n["metadata"]["name"]: dict(node_labels(n))
        for n in informer.list()
    }


def await_consistent(fake, informer, timeout_s=8.0):
    return retry_mod.poll_until(
        lambda: cache_view(informer) == pool_view(fake), timeout_s, 0.02
    )


def test_initial_sync_is_paginated_and_selector_scoped():
    fake = FakeKube()
    for i in range(9):
        fake.add_node(f"n{i}", {"pool": "tpu"})
    fake.add_node("outsider", {"pool": "other"})
    with make_informer(fake, page_limit=4) as inf:
        assert inf.synced
        assert inf.names() == {f"n{i}" for i in range(9)}
    # 9 nodes at page_limit=4 -> 3 chunked pages, one listing.
    assert fake.request_counts["list"] == 3


def test_events_update_cache_without_listing():
    fake = FakeKube()
    fake.add_node("n0", {"pool": "tpu"})
    with make_informer(fake) as inf:
        baseline = fake.request_counts.get("list", 0)
        for i in range(5):
            fake.set_node_label("n0", "step", str(i))
        assert inf.wait_for(
            lambda i: (node_labels(i.get("n0") or {})).get("step") == "4",
            5.0,
        )
        # O(changes): the updates arrived via the watch, not listings.
        assert fake.request_counts.get("list", 0) == baseline


def test_node_leaving_selector_is_dropped():
    fake = FakeKube()
    fake.add_node("n0", {"pool": "tpu"})
    fake.add_node("n1", {"pool": "tpu"})
    with make_informer(fake) as inf:
        fake.set_node_label("n1", "pool", "drained")
        assert inf.wait_for(lambda i: "n1" not in i.names(), 5.0)
        fake.set_node_label("n1", "pool", "tpu")
        assert inf.wait_for(lambda i: "n1" in i.names(), 5.0)


def test_slice_index_tracks_membership():
    fake = FakeKube()
    fake.add_node("a", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    fake.add_node("b", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    fake.add_node("c", {"pool": "tpu"})
    with make_informer(fake) as inf:
        assert {n["metadata"]["name"] for n in inf.slice_members("s1")} == {
            "a", "b",
        }
        fake.set_node_label("c", SLICE_ID_LABEL, "s1")
        assert inf.wait_for(
            lambda i: len(i.slice_members("s1")) == 3, 5.0
        )
        fake.set_node_label("a", SLICE_ID_LABEL, "s2")
        assert inf.wait_for(
            lambda i: {n["metadata"]["name"] for n in i.slice_members("s1")}
            == {"b", "c"},
            5.0,
        )


def test_compaction_410_triggers_relist():
    fake = FakeKube()
    fake.add_node("n0", {"pool": "tpu"})
    with make_informer(fake, watch_timeout_s=1) as inf:
        relists_before = inf.relists
        fake.compact()
        # A change recorded after compaction still reaches the cache —
        # either via the still-open stream or the 410→relist resync once
        # the stream expires and reconnects below the floor.
        fake.set_node_label("n0", "after", "compact")
        assert inf.wait_for(
            lambda i: node_labels(i.get("n0") or {}).get("after")
            == "compact",
            6.0,
        )
        assert inf.relists >= relists_before


def test_unsupported_client_fails_start_loudly():
    class MinimalKube(KubeApi):
        def get_node(self, name):
            raise KubeApiError(404, "nope")

        def patch_node_labels(self, name, labels):
            raise KubeApiError(404, "nope")

        def list_nodes(self, label_selector=None):
            return []

        def list_pods(self, namespace, label_selector=None, field_selector=None):
            return []

        def watch_nodes(self, name, resource_version=None, timeout_seconds=300):
            return iter(())

    with pytest.raises(KubeApiError):
        NodeInformer(MinimalKube(), POOL).start()


def test_wait_wakes_on_change_not_poll():
    fake = FakeKube()
    fake.add_node("n0", {"pool": "tpu"})
    with make_informer(fake) as inf:
        v = inf.version
        t0 = time.monotonic()

        def fire():
            # cclint: test-sleep-ok(deliberate delay proving the wait wakes on the event, not a poll)
            time.sleep(0.05)
            fake.set_node_label("n0", "poke", "1")

        threading.Thread(target=fire, daemon=True).start()
        new_version = inf.wait(v, timeout_s=5.0)
        waited = time.monotonic() - t0
        assert new_version > v
        # Event-driven: woke on the change, far before the 5 s timeout.
        assert waited < 2.0


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [1, 7, 20260803])
def test_cache_equals_fresh_list_under_seeded_chaos(seed):
    """The consistency property: random label churn (including nodes
    entering/leaving the selector) driven while the informer's transport
    suffers a seeded schedule of hangups, 410s and blackout windows —
    afterwards the cache must equal a fresh listing exactly."""
    fake = FakeKube()
    rng = random.Random(seed)
    for i in range(12):
        labels = {"pool": "tpu"}
        if i % 3 == 0:
            labels[SLICE_ID_LABEL] = f"s{i // 3}"
        fake.add_node(f"n{i}", labels)
    plan = FaultPlan(
        seed=seed, rate=0.2, watch_rate=0.5,
        blackout_rate=0.05, blackout_min_calls=2, blackout_max_calls=5,
        retry_after_s=0.01, slow_s=0.005, max_faults=40,
    )
    faulty = FaultyKubeClient(fake, plan, watch_hangup_after=1)
    with make_informer(faulty, watch_timeout_s=1) as inf:
        for step in range(60):
            name = f"n{rng.randrange(12)}"
            op = rng.random()
            if op < 0.5:
                fake.set_node_label(name, "churn", str(step))
            elif op < 0.7:
                # Leave / rejoin the selector.
                fake.set_node_label(
                    name, "pool", rng.choice(["tpu", "parked"])
                )
            elif op < 0.85:
                fake.set_node_label(
                    name, SLICE_ID_LABEL,
                    rng.choice([None, "s0", "s1", "s9"]),
                )
            else:
                fake.set_node_label(
                    name, "cloud.google.com/tpu-cc.mode",
                    rng.choice(["on", "off"]),
                )
            if rng.random() < 0.1:
                time.sleep(0.005)  # cclint: test-sleep-ok(seeded timing jitter is part of the chaos weather)
        plan.end_blackout()  # clean weather to converge in
        assert await_consistent(fake, inf), (
            f"seed {seed}: cache diverged from the pool listing\n"
            f"cache={cache_view(inf)}\npool={pool_view(fake)}"
        )
        # And the slice index agrees with the converged cache.
        for sid in {"s0", "s1", "s9"}:
            expect = {
                name
                for name, labels in pool_view(fake).items()
                if labels.get(SLICE_ID_LABEL) == sid
            }
            got = {
                n["metadata"]["name"] for n in inf.slice_members(sid)
            }
            assert got == expect, f"slice {sid}: {got} != {expect}"


@pytest.mark.chaos
def test_informer_backed_sharded_rollout_converges_under_chaos():
    """Acceptance (ISSUE 6): the informer-backed sharded orchestrator
    drives a pool to convergence while its ONLY apiserver transport
    suffers seeded blackout windows and watch hangups — with zero
    stale-read reconcile losses (every node bounced exactly once, every
    node converged; a stale cache read that skipped or double-drove a
    group would break one of the two)."""
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
    from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL

    fake = FakeKube()
    for i in range(10):
        fake.add_node(
            f"n{i}",
            {
                "pool": "tpu",
                "topology.kubernetes.io/zone": f"z{i % 2}",
                CC_MODE_STATE_LABEL: "off",
            },
        )
    counts: dict = {}
    in_flight: set = set()

    def reactor(name, node):
        labels = node_labels(node)
        desired = labels.get(CC_MODE_LABEL)
        state = labels.get(CC_MODE_STATE_LABEL)
        if desired and state != desired and name not in in_flight:
            in_flight.add(name)
            counts[name] = counts.get(name, 0) + 1

            def fire():
                in_flight.discard(name)
                fake.set_node_label(name, CC_MODE_STATE_LABEL, desired)

            t = threading.Timer(0.03, fire)
            t.daemon = True
            t.start()

    fake.add_patch_reactor(reactor)
    plan = FaultPlan(
        seed=20260803, rate=0.15, watch_rate=0.4,
        blackout_rate=0.04, blackout_min_calls=2, blackout_max_calls=6,
        retry_after_s=0.01, slow_s=0.005, max_faults=30,
    )
    faulty = FaultyKubeClient(fake, plan, watch_hangup_after=2)
    informer = make_informer(faulty, watch_timeout_s=1).start()
    try:
        roller = RollingReconfigurator(
            faulty, POOL,
            informer=informer,
            wave_shards=2,
            max_unavailable=2,
            node_timeout_s=20,
            poll_interval_s=0.05,
        )
        result = roller.rollout("on")
        assert result.ok, result.summary()
    finally:
        informer.stop()
    for i in range(10):
        labels = node_labels(fake.get_node(f"n{i}"))
        assert labels.get(CC_MODE_STATE_LABEL) == "on"
        assert counts.get(f"n{i}") == 1, (
            f"n{i} reconciled {counts.get(f'n{i}')} times under chaos "
            "(stale-read loss or double bounce)"
        )
