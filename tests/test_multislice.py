"""Cross-slice attestation coordination (ccmanager/multislice.py)."""

import time

import pytest

from tpu_cc_manager.ccmanager import multislice
from tpu_cc_manager.ccmanager.rolling import SLICE_ID_LABEL
from tpu_cc_manager.tpudev.fake import FakeTpuBackend

POOL = "pool=tpu"


def make_quote(slice_id, mode="on"):
    backend = FakeTpuBackend(slice_id=slice_id, initial_mode=mode)
    return backend.fetch_attestation("nonce")


def add_attested_node(fake_kube, name, slice_id, quote):
    fake_kube.add_node(name, {"pool": "tpu", SLICE_ID_LABEL: slice_id})
    multislice.publish_quote(fake_kube, name, quote)


def test_publish_and_collect(fake_kube):
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    add_attested_node(fake_kube, "n1", "s1", q)
    slices = multislice.collect_pool_quotes(fake_kube, POOL)
    assert set(slices) == {"s1"}
    assert sorted(slices["s1"]["nodes"]) == ["n0", "n1"]
    assert slices["s1"]["digest"] != "MIXED"


def test_verify_pool_ok_two_slices(fake_kube):
    """Two healthy slices of one DP pool: identical runtimes must produce
    identical digests (quote_digest excludes slice identity), so the pool
    verifies — the BASELINE configs[4] multi-slice flow."""
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    add_attested_node(fake_kube, "n1", "s2", make_quote("s2"))
    slices = multislice.verify_pool_attestation(
        fake_kube, POOL, "on", expected_slices=2, allow_fake=True
    )
    assert len(slices) == 2
    assert slices["s1"]["digest"] == slices["s2"]["digest"]


def test_verify_detects_mode_mismatch(fake_kube):
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1", mode="off"))
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "mode" in str(exc.value)


def test_verify_detects_digest_divergence(fake_kube):
    # s2 runs a genuinely different runtime fingerprint (chip count differs).
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    q2 = FakeTpuBackend(
        slice_id="s2", initial_mode="on", num_chips=8
    ).fetch_attestation("nonce")
    add_attested_node(fake_kube, "n1", "s2", q2)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "distinct runtime digests" in str(exc.value)


def test_verify_detects_intra_slice_divergence(fake_kube):
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    # Second host of s1 publishes a different digest (tampered runtime).
    q2 = FakeTpuBackend(
        slice_id="s1", initial_mode="on", num_chips=8
    ).fetch_attestation("nonce")
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    multislice.publish_quote(fake_kube, "n1", q2)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "disagree" in str(exc.value)


def test_verify_detects_missing_attestation(fake_kube):
    fake_kube.add_node("n0", {"pool": "tpu"})
    with pytest.raises(multislice.PoolAttestationError):
        multislice.verify_pool_attestation(fake_kube, POOL, "on")


def test_verify_detects_unattested_host_of_attested_slice(fake_kube):
    """One host attested, its slice-mate did not: must fail, not pass on the
    attested host's evidence alone."""
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s1"})  # no quote
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "without attestation" in str(exc.value)


def test_idempotent_reconcile_republishes_coordination(fake_kube):
    """A restarted agent on an already-CC-on node must re-publish slice id
    and a fresh quote (rolling grouping + quote aging depend on it)."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    fake_kube.add_node("n0")
    backend = FakeTpuBackend(slice_id="slice-x", initial_mode="on")
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name="n0",
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode("on") is True
    assert "reset" not in [op for op, _ in backend.op_log]  # still idempotent
    labels = node_labels(fake_kube.get_node("n0"))
    assert labels[SLICE_ID_LABEL] == "slice-x"
    assert f"{multislice.QUOTE_ANNOTATION}.digest" in labels


def test_idempotent_reconcile_reattests_on_failure(fake_kube):
    """If re-attestation fails on the idempotent path, the full apply runs."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    fake_kube.add_node("n0")
    backend = FakeTpuBackend(initial_mode="on")
    backend.fail_next("attest")  # first (idempotent-path) attest fails
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name="n0",
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode("on") is True
    ops = [op for op, _ in backend.op_log]
    assert "reset" in ops  # fell through to the full apply


def test_verify_detects_stale_quote(fake_kube, monkeypatch):
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    future = time.time() + 7200
    monkeypatch.setattr(time, "time", lambda: future)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on", max_age_s=3600)
    assert "stale" in str(exc.value)


def test_forged_ts_label_degrades_to_stale_not_crash(fake_kube):
    """A non-numeric .ts label (anything with node-patch RBAC could write
    one) must surface as the staleness problem inside the verifier's
    PoolAttestationError contract — never escape as a ValueError."""
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    fake_kube.patch_node_labels(
        "n0", {f"{multislice.QUOTE_ANNOTATION}.ts": "yesterday-ish"}
    )
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", max_age_s=3600, allow_fake=True
        )
    assert "stale" in str(exc.value)


def test_expected_slice_count(fake_kube):
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on", expected_slices=2)
    assert "expected 2 slices" in str(exc.value)


def test_pool_report(fake_kube):
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    report = multislice.pool_report(fake_kube, POOL)
    assert "s1" in report and "SLICE" in report


def test_manager_publishes_coordination_labels(fake_kube):
    """End-to-end: a successful reconcile leaves slice id + digest labels."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    fake_kube.add_node("n0")
    backend = FakeTpuBackend(slice_id="fake-slice-0")
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name="n0",
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode("on") is True
    labels = node_labels(fake_kube.get_node("n0"))
    assert labels[SLICE_ID_LABEL] == "fake-slice-0"
    assert f"{multislice.QUOTE_ANNOTATION}.digest" in labels
    assert labels[f"{multislice.QUOTE_ANNOTATION}.mode"] == "on"
    # And the pool now verifies — signatures included (the manager also
    # published the full signed quote annotation).
    fake_kube.set_node_label("n0", "pool", "tpu")
    multislice.verify_pool_attestation(fake_kube, POOL, "on", allow_fake=True)
    # Flipping to off clears the attestation evidence (no stale quotes).
    assert mgr.set_cc_mode("off") is True
    labels = node_labels(fake_kube.get_node("n0"))
    assert f"{multislice.QUOTE_ANNOTATION}.digest" not in labels
    from tpu_cc_manager.kubeclient.api import node_annotations

    assert multislice.QUOTE_FULL_ANNOTATION not in node_annotations(
        fake_kube.get_node("n0")
    )
    with pytest.raises(multislice.PoolAttestationError):
        multislice.verify_pool_attestation(
            fake_kube, POOL, "off", allow_fake=True
        )


# ---------------------------------------------------------------------------
# Peer-verifiable signatures (VERDICT r4 missing #1): digest labels alone
# are RBAC-trust — any principal that can patch labels can claim any
# digest. The published signed quote closes that.
# ---------------------------------------------------------------------------


def test_claimed_digest_without_signed_quote_fails(fake_kube):
    """A node that CLAIMS the pool's digest via labels but publishes no
    verifiable signed quote must fail pool verification."""
    honest = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", honest)
    # The forger: copies n0's digest labels verbatim (it has node-patch
    # RBAC) but has no platform-signed quote to publish.
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s2"})
    fake_kube.patch_node_labels("n1", multislice.quote_label_patch(honest))
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True
        )
    assert "without a verifiable signed quote" in str(exc.value)
    # The r4 digest-labels-only mode would have accepted the forgery —
    # that downgrade is explicit now.
    multislice.verify_pool_attestation(
        fake_kube, POOL, "on", allow_fake=True, verify_signatures=False
    )


def test_forged_signature_fails_even_with_matching_digest(fake_kube):
    """Right digest, invalid signature: the quote body is copied from an
    honest node so the digest equality holds, but the platform signature
    does not verify — the pool must reject it."""
    import dataclasses

    honest = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", honest)
    forged = dataclasses.replace(honest, slice_id="s2", signature="garbage")
    add_attested_node(fake_kube, "n1", "s2", forged)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True
        )
    assert "HMAC mismatch" in str(exc.value)


def test_label_digest_not_matching_signed_quote_fails(fake_kube):
    """Labels claiming a digest the signed measurements don't hash to."""
    honest = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", honest)
    other = FakeTpuBackend(
        slice_id="s2", initial_mode="on", num_chips=8
    ).fetch_attestation("nonce")
    # n1 publishes s2's (validly signed) quote but claims n0's digest on
    # its labels so the cross-slice equality check would pass.
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s2"})
    fake_kube.patch_node_labels("n1", multislice.quote_label_patch(honest))
    multislice.publish_quote_annotation(fake_kube, "n1", other)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True
        )
    assert "does not match the signed" in str(exc.value)


def test_replayed_whole_evidence_from_another_slice_fails(fake_kube):
    """Verbatim replay of another node's ENTIRE evidence — digest labels
    AND signed quote annotation — must fail: the signature verifies and
    the digest matches, but the signed quote names the victim's slice,
    not the replayer's (slice binding)."""
    honest = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", honest)
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s2"})
    fake_kube.patch_node_labels("n1", multislice.quote_label_patch(honest))
    multislice.publish_quote_annotation(fake_kube, "n1", honest)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", expected_slices=2, allow_fake=True
        )
    assert "replayed evidence" in str(exc.value)


def test_fake_platform_quotes_rejected_without_opt_in(fake_kube):
    """allow_fake is an explicit operator decision: a production pool must
    treat fake-platform quotes as forgeries."""
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "fake-platform quote rejected" in str(exc.value)


def test_unmeasured_runtime_fails_pool(fake_kube):
    """runtime_files=0 means every host would attest the constant
    'unmeasured-runtime' digest and equality would be vacuous — the pool
    verifier must flag it (ADVICE r4 #4)."""
    import dataclasses

    q = make_quote("s1")
    unmeasured = dataclasses.replace(
        q, measurements={**q.measurements, "runtime_files": "0"},
    )
    from tpu_cc_manager.tpudev.fake import sign_fake_quote

    unmeasured = dataclasses.replace(
        unmeasured,
        signature=sign_fake_quote(
            unmeasured.slice_id, unmeasured.nonce, unmeasured.mode,
            unmeasured.measurements,
        ),
    )
    add_attested_node(fake_kube, "n0", "s1", unmeasured)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True
        )
    assert "never measured" in str(exc.value)


def test_quote_serialization_roundtrip():
    from tpu_cc_manager.tpudev.attestation import (
        deserialize_quote,
        serialize_quote,
    )

    q = make_quote("s1")
    assert deserialize_quote(serialize_quote(q)) == q


# ---------------------------------------------------------------------------
# Verifier-challenge re-attestation (VERDICT weak #5)
# ---------------------------------------------------------------------------


def test_replayed_quote_passes_exp_only_but_fails_challenged_path(fake_kube):
    """THE replay scenario: a same-slice quote with a valid platform
    signature, matching digest labels and correct slice binding passes
    today's (exp-only) check — and must FAIL once the verifier issues a
    challenge, because the replayed quote cannot be bound to a nonce the
    verifier only just minted."""
    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    replayed = backend.fetch_attestation("old-self-chosen-nonce")
    add_attested_node(fake_kube, "n0", "s1", replayed)

    # Exp-only policy: the replay sails through (this is the weakness).
    multislice.verify_pool_attestation(fake_kube, POOL, "on", allow_fake=True)

    # Challenged policy: the same evidence is refused.
    challenges = multislice.issue_pool_challenges(fake_kube, POOL)
    assert set(challenges) == {"n0"}
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True, challenges=challenges
        )
    assert "challenge" in str(exc.value)

    # A live agent re-quotes bound to the challenge -> verification
    # passes again, now with challenged freshness.
    answered = backend.fetch_attestation(challenges["n0"])
    multislice.publish_quote(fake_kube, "n0", answered)
    multislice.verify_pool_attestation(
        fake_kube, POOL, "on", allow_fake=True, challenges=challenges
    )


def test_challenge_annotation_is_read_opportunistically(fake_kube):
    """Without the verifier-held dict, an outstanding challenge
    annotation on the node still arms the challenged check."""
    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    add_attested_node(fake_kube, "n0", "s1",
                      backend.fetch_attestation("stale"))
    multislice.issue_pool_challenges(fake_kube, POOL)
    with pytest.raises(multislice.PoolAttestationError):
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True
        )


def test_quarantined_nodes_are_not_challenged(fake_kube):
    from tpu_cc_manager.labels import QUARANTINED_LABEL

    fake_kube.add_node("q0", {"pool": "tpu", QUARANTINED_LABEL: "true"})
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    challenges = multislice.issue_pool_challenges(fake_kube, POOL)
    assert set(challenges) == {"n0"}


def test_await_challenge_answers_converges_and_times_out(fake_kube):
    import threading

    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    add_attested_node(fake_kube, "n0", "s1",
                      backend.fetch_attestation("stale"))
    fake_kube.add_node("dead", {"pool": "tpu"})
    challenges = multislice.issue_pool_challenges(fake_kube, POOL)
    assert set(challenges) == {"n0", "dead"}

    def answer():
        multislice.publish_quote(
            fake_kube, "n0", backend.fetch_attestation(challenges["n0"])
        )

    t = threading.Timer(0.05, answer)
    t.daemon = True
    t.start()
    # n0 answers inside the window; "dead" (no agent) never does and is
    # reported, not waited on forever.
    pending = multislice.await_challenge_answers(
        fake_kube, POOL, challenges, timeout_s=2.0, poll_interval_s=0.02
    )
    assert pending == ["dead"]


def test_manager_answers_challenge_bound_to_verifier_nonce(fake_kube):
    """The agent side: a challenge annotation on the node makes the
    manager re-quote bound to the verifier's nonce and republish — the
    full challenged verification then passes end-to-end."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain import state as drain_state

    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    fake_kube.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    drain_state.set_cc_state_label(fake_kube, "n0", "on")
    mgr = CCManager(fake_kube, backend, "n0", evict_components=False,
                    smoke_workload="none")
    challenges = multislice.issue_pool_challenges(fake_kube, POOL)
    mgr._maybe_answer_challenge(fake_kube.get_node("n0"))
    from tpu_cc_manager.kubeclient.api import node_annotations
    from tpu_cc_manager.tpudev.attestation import deserialize_quote

    raw = node_annotations(fake_kube.get_node("n0"))[
        multislice.QUOTE_FULL_ANNOTATION
    ]
    assert deserialize_quote(raw).nonce == challenges["n0"]
    multislice.verify_pool_attestation(
        fake_kube, POOL, "on", allow_fake=True, challenges=challenges
    )
    # The answered challenge annotation is RETIRED in the same patch: a
    # one-time challenge must not re-arm forever (it would fail every
    # later unchallenged verification once a reconcile republishes a
    # self-nonce quote, and make the agent re-answer it endlessly).
    assert multislice.challenge_nonce_of(fake_kube.get_node("n0")) is None
    # Idempotent: the MODIFIED event from our own answer does not loop.
    patches_before = fake_kube.patch_calls
    mgr._maybe_answer_challenge(fake_kube.get_node("n0"))
    assert fake_kube.patch_calls == patches_before
    # A later reconcile republishing a self-nonce quote no longer trips
    # over the (now retired) challenge in a plain verification.
    multislice.publish_quote(
        fake_kube, "n0", backend.fetch_attestation("fresh-self-nonce")
    )
    multislice.verify_pool_attestation(fake_kube, POOL, "on", allow_fake=True)


def test_manager_ignores_challenge_with_no_attested_mode(fake_kube):
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_annotations

    backend = FakeTpuBackend(slice_id="s1", initial_mode="off")
    fake_kube.add_node("n0", {"pool": "tpu"})
    mgr = CCManager(fake_kube, backend, "n0", evict_components=False,
                    smoke_workload="none")
    multislice.issue_pool_challenges(fake_kube, POOL)
    mgr._maybe_answer_challenge(fake_kube.get_node("n0"))
    assert multislice.QUOTE_FULL_ANNOTATION not in node_annotations(
        fake_kube.get_node("n0")
    )


def test_failed_challenge_issuance_still_fails_challenged_verification(
    fake_kube,
):
    """A node whose challenge patch flaked stays IN the challenge set: it
    must fail challenged verification loudly, not silently verify
    exp-only in the very mode built to defeat replay."""
    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    add_attested_node(fake_kube, "n0", "s1",
                      backend.fetch_attestation("stale"))
    from tpu_cc_manager.kubeclient.api import KubeApiError

    real_patch = fake_kube.patch_node_annotations
    fake_kube.patch_node_annotations = (
        lambda *a, **kw: (_ for _ in ()).throw(KubeApiError(503, "flake"))
    )
    try:
        challenges = multislice.issue_pool_challenges(fake_kube, POOL)
    finally:
        fake_kube.patch_node_annotations = real_patch
    assert set(challenges) == {"n0"}  # kept despite the failed patch
    with pytest.raises(multislice.PoolAttestationError):
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True, challenges=challenges
        )


def test_manager_retries_challenge_answer_after_annotation_flake(fake_kube):
    """A flaked quote-annotation patch must NOT mark the challenge
    answered: the next watch event re-answers instead of the verifier
    timing out on a healthy node."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain import state as drain_state
    from tpu_cc_manager.kubeclient.api import KubeApiError, node_annotations
    from tpu_cc_manager.tpudev.attestation import deserialize_quote

    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    fake_kube.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    drain_state.set_cc_state_label(fake_kube, "n0", "on")
    mgr = CCManager(fake_kube, backend, "n0", evict_components=False,
                    smoke_workload="none")
    challenges = multislice.issue_pool_challenges(fake_kube, POOL)

    real_patch = fake_kube.patch_node_annotations
    fake_kube.patch_node_annotations = (
        lambda *a, **kw: (_ for _ in ()).throw(KubeApiError(503, "flake"))
    )
    try:
        mgr._maybe_answer_challenge(fake_kube.get_node("n0"))
    finally:
        fake_kube.patch_node_annotations = real_patch
    assert mgr._answered_challenge_nonce is None  # NOT marked answered
    # Next watch event: the answer goes through.
    mgr._maybe_answer_challenge(fake_kube.get_node("n0"))
    raw = node_annotations(fake_kube.get_node("n0"))[
        multislice.QUOTE_FULL_ANNOTATION
    ]
    assert deserialize_quote(raw).nonce == challenges["n0"]


def test_challenge_issuance_degrades_on_annotationless_client(fake_kube):
    """A client that structurally cannot patch annotations degrades to
    the documented exp-only fallback ({}), instead of arming challenges
    no node could ever receive and failing the whole healthy pool."""
    from tpu_cc_manager.kubeclient.api import KubeApiError
    from tpu_cc_manager.kubeclient.fake import FakeKube

    class NoAnnotations(FakeKube):
        def patch_node_annotations(self, name, annotations):
            raise KubeApiError(
                None, "annotation patching not supported by this client"
            )

    api = NoAnnotations()
    api.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    assert multislice.issue_pool_challenges(api, POOL) == {}


def test_answering_does_not_erase_a_newer_challenge(fake_kube):
    """A challenge issued WHILE the agent was fetching its quote (the
    device round trip takes seconds) must survive the agent's answer to
    the older one — an unconditional clear would erase it unseen and the
    new verifier's await would time out on a healthy node."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.drain import state as drain_state

    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    fake_kube.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    drain_state.set_cc_state_label(fake_kube, "n0", "on")
    mgr = CCManager(fake_kube, backend, "n0", evict_components=False,
                    smoke_workload="none")
    multislice.issue_pool_challenges(fake_kube, POOL)
    stale_snapshot = fake_kube.get_node("n0")  # agent read N1 here
    # Second verifier round lands while the agent is mid-answer.
    newer = multislice.issue_pool_challenges(fake_kube, POOL)
    mgr._maybe_answer_challenge(stale_snapshot)
    # N2 survives the answer to N1...
    assert multislice.challenge_nonce_of(
        fake_kube.get_node("n0")
    ) == newer["n0"]
    # ...and the next watch event answers it.
    mgr._maybe_answer_challenge(fake_kube.get_node("n0"))
    pending = multislice.await_challenge_answers(
        fake_kube, POOL, newer, timeout_s=0.2, poll_interval_s=0.02
    )
    assert pending == []
    # Now fully answered: the annotation is retired.
    assert multislice.challenge_nonce_of(fake_kube.get_node("n0")) is None


def test_missed_challenge_reports_one_problem_not_two(fake_kube):
    """A replayed quote under a challenge is one defect, reported once —
    not a 'nonce mismatch' AND a 'not bound to the challenge' pair."""
    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    quote = backend.fetch_attestation("old-nonce")
    from tpu_cc_manager.tpudev.attestation import quote_digest

    problems = multislice._peer_verify_node_quote(
        "s1", "n0", quote, quote_digest(quote), "on",
        allow_fake=True, challenge_nonce="fresh-challenge",
    )
    assert len(problems) == 1, problems
    assert "challenge" in problems[0]


def test_exp_only_downgrade_logs_once_per_verification(fake_kube, caplog):
    """The exp-only downgrade is ONE aggregated warning per verification
    run, not O(pool) identical lines on every plain attest."""
    import logging

    q = make_quote("s1")
    for i in range(3):
        add_attested_node(fake_kube, f"n{i}", "s1", q)
    with caplog.at_level(logging.WARNING, logger=multislice.__name__):
        multislice.verify_pool_attestation(
            fake_kube, POOL, "on", allow_fake=True
        )
    downgrades = [r for r in caplog.records if "exp-only" in r.getMessage()]
    assert len(downgrades) == 1
    assert "3 node(s)" in downgrades[0].getMessage()


def test_await_challenge_answers_rides_out_transient_listing_failures(
    fake_kube,
):
    """One throttle/blip during the bounded wait must not abort the
    challenged attestation; a permanent failure still raises."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    add_attested_node(fake_kube, "n0", "s1",
                      backend.fetch_attestation("stale"))
    challenges = multislice.issue_pool_challenges(fake_kube, POOL)
    multislice.publish_quote(
        fake_kube, "n0", backend.fetch_attestation(challenges["n0"])
    )
    real_list = fake_kube.list_nodes
    blips = {"n": 1}

    def flaky_list(selector=None):
        if blips["n"] > 0:
            blips["n"] -= 1
            raise KubeApiError(429, "throttled", retry_after_s=0.01)
        return real_list(selector)

    fake_kube.list_nodes = flaky_list
    try:
        pending = multislice.await_challenge_answers(
            fake_kube, POOL, challenges, timeout_s=2.0, poll_interval_s=0.02
        )
    finally:
        fake_kube.list_nodes = real_list
    assert pending == []

    fake_kube.list_nodes = lambda selector=None: (_ for _ in ()).throw(
        KubeApiError(403, "forbidden")
    )
    try:
        with pytest.raises(KubeApiError):
            multislice.await_challenge_answers(
                fake_kube, POOL, challenges, timeout_s=0.2,
                poll_interval_s=0.02,
            )
    finally:
        fake_kube.list_nodes = real_list
