"""Cross-slice attestation coordination (ccmanager/multislice.py)."""

import time

import pytest

from tpu_cc_manager.ccmanager import multislice
from tpu_cc_manager.ccmanager.rolling import SLICE_ID_LABEL
from tpu_cc_manager.tpudev.fake import FakeTpuBackend

POOL = "pool=tpu"


def make_quote(slice_id, mode="on"):
    backend = FakeTpuBackend(slice_id=slice_id, initial_mode=mode)
    return backend.fetch_attestation("nonce")


def add_attested_node(fake_kube, name, slice_id, quote):
    fake_kube.add_node(name, {"pool": "tpu", SLICE_ID_LABEL: slice_id})
    multislice.publish_quote(fake_kube, name, quote)


def test_publish_and_collect(fake_kube):
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    add_attested_node(fake_kube, "n1", "s1", q)
    slices = multislice.collect_pool_quotes(fake_kube, POOL)
    assert set(slices) == {"s1"}
    assert sorted(slices["s1"]["nodes"]) == ["n0", "n1"]
    assert slices["s1"]["digest"] != "MIXED"


def test_verify_pool_ok_two_slices(fake_kube):
    """Two healthy slices of one DP pool: identical runtimes must produce
    identical digests (quote_digest excludes slice identity), so the pool
    verifies — the BASELINE configs[4] multi-slice flow."""
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    add_attested_node(fake_kube, "n1", "s2", make_quote("s2"))
    slices = multislice.verify_pool_attestation(
        fake_kube, POOL, "on", expected_slices=2
    )
    assert len(slices) == 2
    assert slices["s1"]["digest"] == slices["s2"]["digest"]


def test_verify_detects_mode_mismatch(fake_kube):
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1", mode="off"))
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "mode" in str(exc.value)


def test_verify_detects_digest_divergence(fake_kube):
    # s2 runs a genuinely different runtime fingerprint (chip count differs).
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    q2 = FakeTpuBackend(
        slice_id="s2", initial_mode="on", num_chips=8
    ).fetch_attestation("nonce")
    add_attested_node(fake_kube, "n1", "s2", q2)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "distinct runtime digests" in str(exc.value)


def test_verify_detects_intra_slice_divergence(fake_kube):
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    # Second host of s1 publishes a different digest (tampered runtime).
    q2 = FakeTpuBackend(
        slice_id="s1", initial_mode="on", num_chips=8
    ).fetch_attestation("nonce")
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    multislice.publish_quote(fake_kube, "n1", q2)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "disagree" in str(exc.value)


def test_verify_detects_missing_attestation(fake_kube):
    fake_kube.add_node("n0", {"pool": "tpu"})
    with pytest.raises(multislice.PoolAttestationError):
        multislice.verify_pool_attestation(fake_kube, POOL, "on")


def test_verify_detects_unattested_host_of_attested_slice(fake_kube):
    """One host attested, its slice-mate did not: must fail, not pass on the
    attested host's evidence alone."""
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    fake_kube.add_node("n1", {"pool": "tpu", SLICE_ID_LABEL: "s1"})  # no quote
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on")
    assert "without attestation" in str(exc.value)


def test_idempotent_reconcile_republishes_coordination(fake_kube):
    """A restarted agent on an already-CC-on node must re-publish slice id
    and a fresh quote (rolling grouping + quote aging depend on it)."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    fake_kube.add_node("n0")
    backend = FakeTpuBackend(slice_id="slice-x", initial_mode="on")
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name="n0",
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode("on") is True
    assert "reset" not in [op for op, _ in backend.op_log]  # still idempotent
    labels = node_labels(fake_kube.get_node("n0"))
    assert labels[SLICE_ID_LABEL] == "slice-x"
    assert f"{multislice.QUOTE_ANNOTATION}.digest" in labels


def test_idempotent_reconcile_reattests_on_failure(fake_kube):
    """If re-attestation fails on the idempotent path, the full apply runs."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    fake_kube.add_node("n0")
    backend = FakeTpuBackend(initial_mode="on")
    backend.fail_next("attest")  # first (idempotent-path) attest fails
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name="n0",
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode("on") is True
    ops = [op for op, _ in backend.op_log]
    assert "reset" in ops  # fell through to the full apply


def test_verify_detects_stale_quote(fake_kube, monkeypatch):
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    future = time.time() + 7200
    monkeypatch.setattr(time, "time", lambda: future)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on", max_age_s=3600)
    assert "stale" in str(exc.value)


def test_expected_slice_count(fake_kube):
    q = make_quote("s1")
    add_attested_node(fake_kube, "n0", "s1", q)
    with pytest.raises(multislice.PoolAttestationError) as exc:
        multislice.verify_pool_attestation(fake_kube, POOL, "on", expected_slices=2)
    assert "expected 2 slices" in str(exc.value)


def test_pool_report(fake_kube):
    add_attested_node(fake_kube, "n0", "s1", make_quote("s1"))
    report = multislice.pool_report(fake_kube, POOL)
    assert "s1" in report and "SLICE" in report


def test_manager_publishes_coordination_labels(fake_kube):
    """End-to-end: a successful reconcile leaves slice id + digest labels."""
    from tpu_cc_manager.ccmanager.manager import CCManager
    from tpu_cc_manager.kubeclient.api import node_labels
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    fake_kube.add_node("n0")
    backend = FakeTpuBackend(slice_id="fake-slice-0")
    mgr = CCManager(
        api=fake_kube, backend=backend, node_name="n0",
        evict_components=False, smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode("on") is True
    labels = node_labels(fake_kube.get_node("n0"))
    assert labels[SLICE_ID_LABEL] == "fake-slice-0"
    assert f"{multislice.QUOTE_ANNOTATION}.digest" in labels
    assert labels[f"{multislice.QUOTE_ANNOTATION}.mode"] == "on"
    # And the pool now verifies.
    fake_kube.set_node_label("n0", "pool", "tpu")
    multislice.verify_pool_attestation(fake_kube, POOL, "on")
    # Flipping to off clears the attestation evidence (no stale quotes).
    assert mgr.set_cc_mode("off") is True
    labels = node_labels(fake_kube.get_node("n0"))
    assert f"{multislice.QUOTE_ANNOTATION}.digest" not in labels
    with pytest.raises(multislice.PoolAttestationError):
        multislice.verify_pool_attestation(fake_kube, POOL, "off")
