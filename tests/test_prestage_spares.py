"""Zero-bounce flips, pre-staged-spare half (ISSUE 15 / ROADMAP item 5).

Agent side (ccmanager/manager.py): a PRESTAGE annotation makes the agent
run the FULL journaled transition + warmup to the named mode ahead of
the rollout wave, report the truthful state label, publish a PRESTAGED
status record and HOLD there — across watch noise and its own restarts —
until the wave's desired write lands (instant convergence via the
idempotent re-attest path), a different desired mode supersedes it, or
the request annotation is deleted (the abort path).

Orchestrator side (ccmanager/rolling.py): `surge=N, prestage=True` arms
spares, awaits their records, journals `spare-prestaged` flight events
and opens a flip window that converges in ~drain+readmit time; spares
armed AHEAD of the rollout via `prestage_spares()` (`ctl rollout
--prestage-only`) flip instantly with no in-rollout arming wait.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from tpu_cc_manager.ccmanager.manager import (
    CCManager,
    PRESTAGE_ANNOTATION,
    PRESTAGED_ANNOTATION,
)
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.drain.sim import add_drainable_node
from tpu_cc_manager.kubeclient.api import node_annotations, node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL
from tpu_cc_manager.obs import flight as flight_mod
from tpu_cc_manager.obs.journal import Journal
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

NS = "tpu-operator"


def make_agent(kube, name, backend, metrics=None):
    return CCManager(
        api=kube,
        backend=backend,
        node_name=name,
        default_mode="off",
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=metrics or MetricsRegistry(),
        journal=Journal(trace_file=""),
        eviction_poll_interval_s=0.02,
        watch_timeout_s=1,
        reconnect_delay_s=0.0,
    )


class AgentPool:
    """N drainable nodes, each with a real agent watch loop."""

    def __init__(self, n=1, prefix="ps-node", pool_label=None, **backend_kw):
        self.kube = FakeKube()
        self.names = [f"{prefix}-{i}" for i in range(n)]
        self.backends = {}
        self.metrics = {}
        self.stop = threading.Event()
        self.threads = []
        for i, name in enumerate(self.names):
            extra = {"pool": pool_label} if pool_label else None
            add_drainable_node(self.kube, name, NS, extra_labels=extra)
            backend = FakeTpuBackend(
                num_chips=2, slice_id=f"{prefix}-slice-{i}", **backend_kw
            )
            self.backends[name] = backend
            self.metrics[name] = MetricsRegistry()
            mgr = make_agent(self.kube, name, backend, self.metrics[name])
            self.threads.append(threading.Thread(
                target=mgr.watch_and_apply, args=(self.stop,), daemon=True,
            ))
        for t in self.threads:
            t.start()

    def settled(self, mode="off", timeout=20.0) -> bool:
        return retry_mod.poll_until(
            lambda: all(
                node_labels(self.kube.get_node(n)).get(CC_MODE_STATE_LABEL)
                == mode
                for n in self.names
            ),
            timeout, 0.05,
        )

    def state(self, name):
        return node_labels(self.kube.get_node(name)).get(CC_MODE_STATE_LABEL)

    def shutdown(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=10)


def await_prestaged(kube, name, timeout=20.0) -> dict | None:
    def ready():
        return node_annotations(kube.get_node(name)).get(
            PRESTAGED_ANNOTATION
        ) is not None

    if not retry_mod.poll_until(ready, timeout, 0.05):
        return None
    return json.loads(
        node_annotations(kube.get_node(name))[PRESTAGED_ANNOTATION]
    )


# ---------------------------------------------------------------------------
# Agent half
# ---------------------------------------------------------------------------


def test_agent_prestages_on_annotation_holds_and_flips_instantly():
    pool = AgentPool(1)
    name = pool.names[0]
    try:
        assert pool.settled()
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: "on"})
        record = await_prestaged(pool.kube, name)
        assert record is not None and record["mode"] == "on"
        assert record["prior"] == "off"
        assert record["seconds"] >= 0
        assert pool.state(name) == "on"  # truthful state, desired unchanged
        assert node_labels(pool.kube.get_node(name)).get(CC_MODE_LABEL) is None
        # Watch noise (an unrelated annotation write) must not revert
        # the hold or re-run the pass.
        pool.kube.patch_node_annotations(name, {"poke": "1"})
        retry_mod.wait(0.5, None)  # cclint: test-sleep-ok(negative assertion: the hold must still be in place after the event was processed)
        assert pool.state(name) == "on"
        # The prestage metric exported.
        assert "tpu_cc_spare_prestage_seconds" in (
            pool.metrics[name].render_prometheus()
        )
        # The wave arrives: desired=on consumes the request and
        # converges with NO second transition (the reset count proves
        # it below), near-instantly.
        def resets() -> int:
            return sum(
                1 for op in pool.backends[name].op_log
                if str(op[0]).startswith("reset")
            )

        resets_before = resets()
        pool.kube.set_node_label(name, CC_MODE_LABEL, "on")
        assert retry_mod.poll_until(
            lambda: node_annotations(pool.kube.get_node(name)).get(
                PRESTAGE_ANNOTATION
            ) is None and pool.state(name) == "on",
            10.0, 0.02,
        )
        assert resets() == resets_before, (
            "the pre-staged flip must not reset again at the wave"
        )
        # The status record SURVIVES the flip — the operator-visible
        # explanation of why the wave opened instantly (ctl status).
        assert node_annotations(pool.kube.get_node(name)).get(
            PRESTAGED_ANNOTATION
        ) is not None
    finally:
        pool.shutdown()


def test_prestage_hold_survives_agent_restart():
    pool = AgentPool(1)
    name = pool.names[0]
    try:
        assert pool.settled()
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: "on"})
        assert await_prestaged(pool.kube, name) is not None
    finally:
        pool.shutdown()
    # Fresh agent process, same node + hardware: the initial apply of
    # the (unchanged) desired mode must HOLD, not bounce the spare back.
    stop = threading.Event()
    mgr = make_agent(pool.kube, name, pool.backends[name])
    t = threading.Thread(target=mgr.watch_and_apply, args=(stop,), daemon=True)
    t.start()
    try:
        retry_mod.wait(1.0, None)  # cclint: test-sleep-ok(negative assertion: the restarted agent's initial apply must have run and NOT reverted)
        assert pool.state(name) == "on"
        assert node_annotations(pool.kube.get_node(name)).get(
            PRESTAGE_ANNOTATION
        ) == "on"
    finally:
        stop.set()
        t.join(timeout=10)


def test_prestage_abort_reverts_to_desired():
    pool = AgentPool(1)
    name = pool.names[0]
    try:
        assert pool.settled()
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: "on"})
        assert await_prestaged(pool.kube, name) is not None
        # The operator deletes the request: the hold breaks, the node
        # reconciles back to the desired mode and the status record is
        # cleared.
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: None})
        assert retry_mod.poll_until(
            lambda: pool.state(name) == "off"
            and node_annotations(pool.kube.get_node(name)).get(
                PRESTAGED_ANNOTATION
            ) is None,
            15.0, 0.05,
        )
    finally:
        pool.shutdown()


def test_prestage_record_cleared_when_pool_moves_past_it():
    """A rollout to a DIFFERENT mode than the pre-staged one supersedes
    the prestage: both annotations clear so the hold cannot re-engage on
    a stale record."""
    pool = AgentPool(1)
    name = pool.names[0]
    try:
        assert pool.settled()
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: "on"})
        assert await_prestaged(pool.kube, name) is not None
        pool.kube.set_node_label(name, CC_MODE_LABEL, "on")
        assert retry_mod.poll_until(lambda: pool.state(name) == "on", 10, 0.02)
        # The pool moves on: desired=off must both converge and clear
        # the now-stale prestaged record.
        pool.kube.set_node_label(name, CC_MODE_LABEL, "off")
        assert retry_mod.poll_until(
            lambda: pool.state(name) == "off"
            and node_annotations(pool.kube.get_node(name)).get(
                PRESTAGED_ANNOTATION
            ) is None
            and node_annotations(pool.kube.get_node(name)).get(
                PRESTAGE_ANNOTATION
            ) is None,
            10.0, 0.05,
        )
    finally:
        pool.shutdown()


def test_cc_prestage_env_opt_out(monkeypatch):
    monkeypatch.setenv("CC_PRESTAGE", "0")
    pool = AgentPool(1)
    name = pool.names[0]
    try:
        assert pool.settled()
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: "on"})
        retry_mod.wait(1.0, None)  # cclint: test-sleep-ok(negative assertion: the request must have been seen and ignored)
        assert pool.state(name) == "off"
        assert node_annotations(pool.kube.get_node(name)).get(
            PRESTAGED_ANNOTATION
        ) is None
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# Orchestrator half
# ---------------------------------------------------------------------------


def test_surge_prestage_rollout_flips_spare_in_drain_plus_readmit_time(
    tmp_path,
):
    """The BENCH_r08 shape in tier-1: a surge+prestage rollout arms the
    spare, awaits its pre-staged record, journals spare-prestaged, and
    the spare's flip window converges far faster than the full-flip
    windows its pool-mates pay in the SAME rollout."""
    pool = AgentPool(
        3, pool_label="tpu-ps", reset_latency_s=0.3, boot_latency_s=0.3,
    )
    try:
        assert pool.settled()
        fpath = str(tmp_path / "flight.jsonl")
        flight = flight_mod.FlightRecorder(fpath)
        roller = RollingReconfigurator(
            pool.kube, "pool=tpu-ps", max_unavailable=1,
            node_timeout_s=30, poll_interval_s=0.05,
            surge=1, prestage=True, flight=flight,
            metrics=MetricsRegistry(),
        )
        result = roller.rollout("on")
        assert result.ok, result.summary()
        assert len(result.surged) == 1
        spare = result.surged[0]
        events, torn = flight_mod.read_events(fpath)
        assert torn == 0
        rec = flight_mod.reconstruct(events)
        assert rec["prestaged"] == [spare]
        surge_close = [
            e for e in events
            if e["event"] == flight_mod.EVENT_WINDOW_CLOSE
            and e.get("wave") == "surge"
        ]
        full_close = [
            e for e in events
            if e["event"] == flight_mod.EVENT_WINDOW_CLOSE
            and e.get("wave") == 0
        ]
        assert surge_close and full_close
        spare_flip = surge_close[0]["seconds"]
        full_flip = min(e["seconds"] for e in full_close)
        assert spare_flip < 0.5 * full_flip, (
            f"pre-staged spare flip ({spare_flip}s) must be well under "
            f"the full path ({full_flip}s)"
        )
        # Taints reclaimed, everyone on.
        for name in pool.names:
            assert not (
                pool.kube.get_node(name).get("spec") or {}
            ).get("taints")
            assert pool.state(name) == "on"
    finally:
        pool.shutdown()


def test_prestage_only_arm_then_rollout_opens_instantly(tmp_path):
    """The `ctl rollout --prestage-only` shape: arm ahead of the
    rollout (overlapping the pre-staging with whatever the pool is
    doing), then the real surge rollout detects the armed spare and its
    surge phase — arming wait included — is near-instant."""
    pool = AgentPool(
        2, pool_label="tpu-pa", reset_latency_s=0.2, boot_latency_s=0.2,
    )
    try:
        assert pool.settled()
        armer = RollingReconfigurator(
            pool.kube, "pool=tpu-pa", node_timeout_s=30,
            poll_interval_s=0.05, surge=1, prestage=True,
            metrics=MetricsRegistry(),
        )
        summary = armer.prestage_spares("on")
        assert summary["ok"], summary
        assert len(summary["prestaged"]) == 1
        spare = summary["prestaged"][0]
        # Spare holds, taint kept until the real rollout reclaims it.
        assert pool.state(spare) == "on"
        assert any(
            t.get("key") for t in
            (pool.kube.get_node(spare).get("spec") or {}).get("taints") or []
        )
        fpath = str(tmp_path / "flight.jsonl")
        roller = RollingReconfigurator(
            pool.kube, "pool=tpu-pa", max_unavailable=1,
            node_timeout_s=30, poll_interval_s=0.05,
            surge=1, prestage=True,
            flight=flight_mod.FlightRecorder(fpath),
            metrics=MetricsRegistry(),
        )
        t0 = time.monotonic()
        result = roller.rollout("on")
        assert result.ok, result.summary()
        events, _ = flight_mod.read_events(fpath)
        surge_close = [
            e for e in events
            if e["event"] == flight_mod.EVENT_WINDOW_CLOSE
            and e.get("wave") == "surge"
        ][0]
        # The pre-armed spare's whole surge phase (detection + flip) is
        # a tiny fraction of the full path its pool-mate paid.
        full_close = [
            e for e in events
            if e["event"] == flight_mod.EVENT_WINDOW_CLOSE
            and e.get("wave") == 0
        ][0]
        assert surge_close["seconds"] < 0.5 * full_close["seconds"]
        assert not (
            pool.kube.get_node(spare).get("spec") or {}
        ).get("taints"), "the rollout must reclaim the pre-armed taint"
        del t0
    finally:
        pool.shutdown()


def test_prestage_timeout_falls_back_to_full_flip(monkeypatch):
    """Agents that never pre-stage (CC_PRESTAGE=0, older binaries) must
    cost the surge phase only the bounded await — the flip itself then
    takes the normal full path and the rollout still converges."""
    monkeypatch.setenv("CC_PRESTAGE", "0")
    pool = AgentPool(2, pool_label="tpu-pf")
    try:
        assert pool.settled()
        roller = RollingReconfigurator(
            pool.kube, "pool=tpu-pf", max_unavailable=1,
            node_timeout_s=30, poll_interval_s=0.05,
            surge=1, prestage=True, prestage_timeout_s=0.3,
            metrics=MetricsRegistry(),
        )
        result = roller.rollout("on")
        assert result.ok, result.summary()
        for name in pool.names:
            assert pool.state(name) == "on"
    finally:
        pool.shutdown()


def test_ctl_status_shows_prestaged_note(capsys):
    from tpu_cc_manager import ctl as ctl_mod

    pool = AgentPool(1, pool_label="tpu-st")
    name = pool.names[0]
    try:
        assert pool.settled()
        pool.kube.patch_node_annotations(name, {PRESTAGE_ANNOTATION: "on"})
        assert await_prestaged(pool.kube, name) is not None

        class Args:
            selector = "pool=tpu-st"
            lease_namespace = None

        ctl_mod.cmd_status(pool.kube, Args())
        out = capsys.readouterr().out
        assert "PRESTAGED(on," in out
        assert "holding" in out, out
    finally:
        pool.shutdown()
