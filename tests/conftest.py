"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; sharding correctness is validated
on 8 virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). Must run before any
jax import, hence the env mutation at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def fake_kube():
    from tpu_cc_manager.kubeclient.fake import FakeKube

    return FakeKube()


@pytest.fixture()
def fake_tpu():
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend

    return FakeTpuBackend()
