"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Two traps in this image make the obvious env vars insufficient:

1. The image exports ``JAX_PLATFORMS=axon`` (a tunnel to one real TPU chip)
   and a sitecustomize that — whenever ``PALLAS_AXON_POOL_IPS`` is set —
   registers the axon backend and calls
   ``jax.config.update("jax_platforms", "axon,cpu")``, overriding any env
   value. Tests must never touch that tunnel (it is single-client and a
   concurrent test run can wedge it), so we delete the trigger variable
   (inherited by smoke-workload subprocesses) and force the config back.
2. ``xla_force_host_platform_device_count`` must be in XLA_FLAGS before the
   CPU backend initializes; conftest import time is early enough.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # keep smoke subprocesses off the TPU
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def fake_kube():
    from tpu_cc_manager.kubeclient.fake import FakeKube

    return FakeKube()


@pytest.fixture()
def fake_tpu():
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend

    return FakeTpuBackend()
