"""Preemption fast-drain + handoff (spot/preemptible churn).

The scenario the 300 s drain budget cannot survive: a platform preemption
notice leaves a hard termination deadline ≪ the budget (GCE gives ~30 s).
The stack must

1. checkpoint-before-pause FIRST (the training job's unsaved state is the
   one thing the kill destroys for good), with the deadline published as
   a label hint so subscribers can size their checkpoint to the window;
2. compress component eviction into whatever budget remains, proceeding
   on timeout (the VM dies at the deadline either way);
3. journal the interrupted transition as a ``handoff`` intent AND mirror
   it to the node's handoff annotation — the replacement VM has a fresh
   disk, so the apiserver copy is the only record that survives;
4. on a multi-host slice, bump the fencing generation so peers mid-
   barrier abort fast (BarrierFenced) instead of burning their barrier
   deadline on the departed host's absent staged marker;
5. let the replacement node resume the flip from the handoff record with
   exactly ONE reset across the handoff.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from tpu_cc_manager.ccmanager import intent_journal as ij
from tpu_cc_manager.ccmanager.manager import CCManager, HANDOFF_ANNOTATION
from tpu_cc_manager.drain import evict, handshake
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.faults.plan import FaultPlan
from tpu_cc_manager.kubeclient.api import node_annotations, node_labels
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_ON,
    MODE_SLICE,
    SLICE_ID_LABEL,
    STATE_FAILED,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry
from tpu_cc_manager.utils import retry as retry_mod

NODE = "spot-node-0"
NS = "tpu-operator"
SLICE = "spot-slice-0"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


class VmKilled(BaseException):
    """The platform's hard kill landing at the termination deadline: no
    Python cleanup runs in the reconcile below the kill point, exactly
    like the SIGKILL a reclaimed VM gets."""


def resets_of(backend) -> int:
    return sum(1 for op, _ in backend.op_log if op == "reset")


def operator_controller(kube) -> None:
    """Paused component labels delete the pods; unpaused restore them."""

    def reactor(name, node):
        labels = node_labels(node)
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if key not in labels:
                continue
            if is_paused(labels.get(key)):
                kube.delete_pods_matching(NS, f"app={app}")
            elif not kube.list_pods(NS, f"app={app}"):
                kube.add_pod(NS, f"{app}-pod", name, labels={"app": app})

    kube.add_patch_reactor(reactor)


def make_manager(kube, backend, tmp_path, suffix, **kw):
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault(
        "intent_journal",
        ij.IntentJournal.from_state_dir(str(tmp_path / f"vm-{suffix}")),
    )
    return CCManager(
        api=kube,
        backend=backend,
        node_name=kw.pop("node_name", NODE),
        operator_namespace=NS,
        evict_components=kw.pop("evict_components", True),
        smoke_workload="none",
        eviction_timeout_s=2.0,
        eviction_poll_interval_s=0.01,
        preemption_deadline_s=kw.pop("preemption_deadline_s", 2.0),
        preemption_poll_s=kw.pop("preemption_poll_s", 0.0),
        readiness_file=str(tmp_path / f"ready-{suffix}"),
        **kw,
    )


# ---------------------------------------------------------------------------
# The seeded chaos acceptance test (tier-1): notice mid-flip → checkpoint
# + handoff published before the kill → replacement resumes, ONE reset.
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_preemption_mid_flip_hands_off_and_replacement_resumes_one_reset(
    fake_kube, tmp_path,
):
    plan = FaultPlan(
        seed=int(os.environ.get("CC_CHAOS_SEED", "20260803")),
        preemption_rate=1.0, preemption_deadline_s=2.0,
    )
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "dp-pod", NODE, labels={"app": DP_APP})
    operator_controller(fake_kube)
    # A registered training job: its checkpoint callback is modeled by a
    # reactor that acks the drain cycle — recording the deadline hint the
    # fast drain published, so "checkpoint sized to the window" is real.
    sub = handshake.subscriber_label("trainer")
    fake_kube.set_node_label(NODE, sub, handshake.ACTIVE)
    checkpoint_hints: list[str | None] = []

    def acker(name, node):
        labels = node_labels(node)
        token = handshake.request_token(
            labels.get(handshake.DRAIN_REQUESTED_LABEL)
        )
        if token and labels.get(sub) == handshake.ACTIVE:
            checkpoint_hints.append(
                labels.get(handshake.DRAIN_DEADLINE_LABEL)
            )
            fake_kube.set_node_label(NODE, sub, handshake.ack_value(token))

    fake_kube.add_patch_reactor(acker)

    holder: dict = {}

    class PreemptedBackend(FakeTpuBackend):
        """The preemption notice lands while the transition is in flight
        (just after staging); the VM is killed once the fast drain +
        handoff publish finish — before its reset ever runs."""

        def stage_cc_mode(self, chips, mode):
            super().stage_cc_mode(chips, mode)
            plan.seed_preemption(self)
            holder["outcome"] = holder["mgr"].handle_preemption_notice()
            # The platform kill lands here. Snapshot what the fast drain
            # achieved INSIDE the window (the in-process VmKilled below
            # still runs ``finally`` blocks a real SIGKILL would not, so
            # post-kill state is not evidence).
            holder["pods_at_kill"] = fake_kube.list_pods(NS, f"app={DP_APP}")
            holder["dp_paused_at_kill"] = is_paused(
                node_labels(fake_kube.get_node(NODE)).get(DP_LABEL)
            )
            raise VmKilled()

    backend_a = PreemptedBackend(num_chips=4, accelerator_type="v5p-8")
    registry_a = MetricsRegistry()
    mgr_a = make_manager(
        fake_kube, backend_a, tmp_path, "a", metrics=registry_a,
    )
    holder["mgr"] = mgr_a
    fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
    with pytest.raises(VmKilled):
        mgr_a.set_cc_mode(MODE_ON)

    # Before the kill: checkpoint handshake ran FIRST, with the hard
    # deadline published as the label hint…
    assert checkpoint_hints == ["2"]
    # …eviction completed inside the compressed window (components paused,
    # pods gone at the moment the kill landed)…
    assert holder["pods_at_kill"] == []
    assert holder["dp_paused_at_kill"] is True
    # …and the handoff record reached BOTH the local journal (crash truth
    # for a cancelled reclaim) and the node annotation (the only copy
    # that survives the reclaimed disk).
    record = json.loads(
        node_annotations(fake_kube.get_node(NODE))[HANDOFF_ANNOTATION]
    )
    assert record["mode"] == MODE_ON
    assert record["from"] == NODE
    journal_kinds = [
        (r.get("t"), r.get("kind"))
        for r in ij.IntentJournal.from_state_dir(
            str(tmp_path / "vm-a")
        ).replay().records
    ]
    assert ("intent", ij.KIND_HANDOFF) in journal_kinds
    assert registry_a.preemption_totals() == {"handoff": 1}
    assert resets_of(backend_a) == 0  # killed before its reset
    assert len(plan.injected) == 1 and plan.injected[0].kind == "preemption"

    # The replacement VM: same node name, FRESH disk (new journal dir),
    # fresh hardware. It consumes the handoff at startup and commits the
    # flip with exactly one reset.
    backend_b = FakeTpuBackend(num_chips=4, accelerator_type="v5p-8")
    registry_b = MetricsRegistry()
    mgr_b = make_manager(
        fake_kube, backend_b, tmp_path, "b", metrics=registry_b,
    )
    mgr_b.consume_handoff()
    assert mgr_b.intents.last_desired_mode == MODE_ON  # dark-boot truth
    assert mgr_b.set_cc_mode(MODE_ON) is True

    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON
    assert labels[DP_LABEL] == "true"  # components re-admitted
    assert HANDOFF_ANNOTATION not in node_annotations(
        fake_kube.get_node(NODE)
    )
    assert registry_b.preemption_totals() == {"resumed": 1}
    total_resets = resets_of(backend_a) + resets_of(backend_b)
    assert total_resets == 1, (
        f"expected exactly one reset across the handoff, got {total_resets}"
    )
    print(
        "PREEMPTION_SUMMARY "
        f"seed={plan.seed} deadline_s={plan.preemption_deadline_s} "
        f"outcome={holder['outcome']} resumed=1 resets={total_resets} "
        f"checkpoint_hinted={checkpoint_hints == ['2']}"
    )


@pytest.mark.chaos
def test_slice_peer_fences_fast_instead_of_burning_barrier_deadline(
    fake_kube, tmp_path,
):
    """A host of a 2-host slice is preempted mid-flip: its handler bumps
    the fencing generation, so the surviving peer aborts its barrier wait
    with BarrierFenced in well under the barrier deadline instead of
    polling the departed host's staged marker until timeout."""

    def host(i, **kw):
        backend = FakeTpuBackend(
            num_chips=4, accelerator_type="v5p-32",
            num_hosts=2, host_index=i, slice_id=SLICE,
        )
        registry = MetricsRegistry()
        mgr = make_manager(
            fake_kube, backend, tmp_path, f"h{i}",
            node_name=f"spot-node-{i}", metrics=registry,
            evict_components=False, **kw,
        )
        return mgr, backend, registry

    fake_kube.add_node("spot-node-0", {SLICE_ID_LABEL: SLICE})
    fake_kube.add_node("spot-node-1")
    mgr0, _backend0, registry0 = host(0)
    mgr1, backend1, registry1 = host(
        1, slice_barrier_timeout_s=20.0, slice_barrier_poll_interval_s=0.02,
    )

    result: dict = {}

    def drive_peer():
        result["ok"] = mgr1.set_cc_mode(MODE_SLICE)

    t = threading.Thread(target=drive_peer, daemon=True)
    started = time.monotonic()
    t.start()
    # cclint: test-sleep-ok(settle window: the peer thread has no observable parked-in-barrier hook)
    time.sleep(0.3)
    # Host 0 was preempted mid-flip (it never staged): its notice handler
    # publishes the handoff AND fences the slice on its way out.
    mgr0._inflight_transition = {
        "mode": MODE_SLICE, "chips": [0, 1, 2, 3],
        "phase": ij.PHASE_BEGUN, "slice_id": SLICE, "multi_host": True,
    }
    assert mgr0.handle_preemption_notice() == "handoff"
    t.join(timeout=10.0)
    elapsed = time.monotonic() - started
    assert not t.is_alive(), "peer never left its barrier wait"
    assert result["ok"] is False
    assert elapsed < 10.0, (
        f"peer burned {elapsed:.1f}s; fencing should abort it fast"
    )
    labels = node_labels(fake_kube.get_node("spot-node-1"))
    assert labels[CC_MODE_STATE_LABEL] == STATE_FAILED
    # The departing host counted the fence; the surviving peer recorded
    # the fenced abort as its failure reason (not a timeout).
    assert "tpu_cc_barrier_fenced_total 1" in registry0.render_prometheus()
    assert 'tpu_cc_failures_total{reason="barrier-fenced"}' in (
        registry1.render_prometheus()
    )
    assert resets_of(backend1) == 0  # fenced before any hardware touch
    print(
        f"PREEMPTION_SUMMARY scenario=slice-fence elapsed_s={elapsed:.2f} "
        "fenced=1"
    )


# ---------------------------------------------------------------------------
# The notice monitor (the production path from signal to handler)
# ---------------------------------------------------------------------------


def test_monitor_polls_the_seeded_notice_and_retires(fake_kube, tmp_path):
    plan = FaultPlan(seed=7, preemption_rate=1.0)
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    mgr = make_manager(
        fake_kube, backend, tmp_path, "m", metrics=registry,
        evict_components=False,
        preemption_poll_s=0.01, preemption_deadline_s=1.0,
    )
    assert plan.schedule_preemption(backend) is True
    mgr._start_preemption_monitor()
    retry_mod.poll_until(
        lambda: bool(registry.preemption_totals()), 5.0, 0.01
    )
    # No transition was in flight: a clean fast drain, and the monitor
    # thread retires (the signal is level-triggered; one per VM lifetime).
    assert registry.preemption_totals() == {"clean": 1}
    mgr._preemption_thread.join(timeout=2.0)
    assert not mgr._preemption_thread.is_alive()
    assert mgr.handle_preemption_notice() == "duplicate"
    assert registry.preemption_totals() == {"clean": 1}
    mgr._stop_preemption_monitor()


def test_monitor_disabled_by_zero_deadline(fake_kube, tmp_path):
    mgr = make_manager(
        fake_kube, FakeTpuBackend(), tmp_path, "d",
        evict_components=False,
        preemption_poll_s=0.01, preemption_deadline_s=0.0,
    )
    mgr._start_preemption_monitor()
    assert mgr._preemption_thread is None


def test_flaky_notice_source_never_kills_the_monitor(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    mgr = make_manager(
        fake_kube, backend, tmp_path, "f", metrics=registry,
        evict_components=False,
        preemption_poll_s=0.01, preemption_deadline_s=1.0,
    )
    backend.fail_next("preemption_notice", times=3)
    mgr._start_preemption_monitor()
    try:
        backend.set_preempted(True)
        retry_mod.poll_until(
            lambda: bool(registry.preemption_totals()), 5.0, 0.01
        )
        assert registry.preemption_totals() == {"clean": 1}
    finally:
        mgr._stop_preemption_monitor()


def test_handoff_published_even_when_eviction_fails(fake_kube, tmp_path):
    """The handoff publish is the part that matters most — an eviction
    failure (any shape) must not consume its window or skip it."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})

    class BrokenPods:
        def __getattr__(self, name):
            return getattr(fake_kube, name)

        def list_pods(self, *a, **kw):
            raise RuntimeError("pods listing wedged")

    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    mgr = make_manager(
        BrokenPods(), backend, tmp_path, "e", metrics=registry,
    )
    mgr._inflight_transition = {
        "mode": MODE_ON, "chips": [0], "phase": ij.PHASE_STAGED,
        "slice_id": None, "multi_host": False,
    }
    assert mgr.handle_preemption_notice() == "handoff"
    record = json.loads(
        node_annotations(fake_kube.get_node(NODE))[HANDOFF_ANNOTATION]
    )
    assert record["phase"] == ij.PHASE_STAGED
    assert registry.preemption_totals() == {"handoff": 1}


def test_garbled_handoff_annotation_is_cleared_not_trusted(
    fake_kube, tmp_path,
):
    fake_kube.add_node(NODE)
    fake_kube.patch_node_annotations(
        NODE, {HANDOFF_ANNOTATION: "not json at all"}
    )
    mgr = make_manager(
        fake_kube, FakeTpuBackend(), tmp_path, "g", evict_components=False,
    )
    mgr.consume_handoff()
    assert mgr._handoff is None
    assert HANDOFF_ANNOTATION not in node_annotations(
        fake_kube.get_node(NODE)
    )
    # Valid JSON that is not an object must clear too, not crash startup.
    fake_kube.patch_node_annotations(NODE, {HANDOFF_ANNOTATION: "[]"})
    mgr2 = make_manager(
        fake_kube, FakeTpuBackend(), tmp_path, "g2", evict_components=False,
    )
    mgr2.consume_handoff()
    assert mgr2._handoff is None
    assert HANDOFF_ANNOTATION not in node_annotations(
        fake_kube.get_node(NODE)
    )


def test_superseded_handoff_still_retires(fake_kube, tmp_path):
    """The desired mode moved on while the VM was being replaced: the
    replacement converges on the NEW mode and the stale handoff record is
    still cleared (the flip it described was superseded, not lost)."""
    fake_kube.add_node(NODE, {CC_MODE_LABEL: "devtools"})
    fake_kube.patch_node_annotations(NODE, {
        HANDOFF_ANNOTATION: json.dumps({
            "mode": "on", "phase": "begun", "chips": [0],
            "slice_id": None, "from": NODE, "ts": 1.0,
        })
    })
    registry = MetricsRegistry()
    mgr = make_manager(
        fake_kube, FakeTpuBackend(), tmp_path, "s", metrics=registry,
        evict_components=False,
    )
    mgr.consume_handoff()
    assert mgr.set_cc_mode("devtools") is True
    assert HANDOFF_ANNOTATION not in node_annotations(
        fake_kube.get_node(NODE)
    )
    assert registry.preemption_totals() == {"resumed": 1}


# ---------------------------------------------------------------------------
# Fast drain vs normal drain: identical pause-label algebra
# ---------------------------------------------------------------------------


def _component_state(kube, node):
    labels = node_labels(kube.get_node(node))
    return {
        k: labels.get(k) for k in DRAIN_COMPONENT_LABELS if k in labels
    }


def _fresh_node(kube_cls, values: dict):
    kube = kube_cls()
    kube.add_node(NODE, dict(values))
    return kube


def test_fast_drain_and_normal_drain_produce_identical_pause_labels():
    """Property: over every combination of component-label presence and
    prior pausedness, the fast drain applies EXACTLY the pause algebra of
    the normal drain — only timings (and the deadline hint + withheld
    readmit) differ."""
    from tpu_cc_manager.drain.pause import pause_value
    from tpu_cc_manager.kubeclient.fake import FakeKube

    keys = sorted(DRAIN_COMPONENT_LABELS)
    cases = []
    for mask in range(2 ** len(keys)):
        values = {}
        for i, key in enumerate(keys):
            if mask & (1 << i):
                values[key] = "true"
        cases.append(values)
        paused = {
            k: (pause_value(v) or v) for k, v in values.items()
        }
        if paused != values:
            cases.append(paused)  # crashed-run leftovers: already paused
    for values in cases:
        slow = _fresh_node(FakeKube, values)
        fast = _fresh_node(FakeKube, values)
        original_slow = evict.evict_components(
            slow, NODE, NS, timeout_s=0.05, poll_interval_s=0.01,
        )
        original_fast = evict.fast_drain_components(
            fast, NODE, NS, deadline_s=0.05, poll_interval_s=0.01,
        )
        assert original_slow == original_fast, values
        slow_state = _component_state(slow, NODE)
        fast_state = _component_state(fast, NODE)
        assert slow_state == fast_state, (
            f"pause algebra diverged for {values}: "
            f"normal={slow_state} fast={fast_state}"
        )


def test_fast_drain_proceeds_to_return_when_eviction_cannot_finish(
    fake_kube,
):
    """Deadline exhaustion: pods never leave (no operator), the workload
    never acks — the fast drain must still pause, wait out ONLY the
    compressed deadline, and return so the caller gets its handoff
    window. The drain request (and deadline hint) stay up for the
    replacement's crash-recovery readmit."""
    fake_kube.add_node(
        NODE,
        {DP_LABEL: "true", handshake.subscriber_label("wedged"): "active"},
    )
    fake_kube.add_pod(NS, "dp-pod", NODE, labels={"app": DP_APP})
    started = time.monotonic()
    original = evict.fast_drain_components(
        fake_kube, NODE, NS, deadline_s=0.3, poll_interval_s=0.01,
    )
    elapsed = time.monotonic() - started
    assert elapsed < 3.0, f"fast drain overran its deadline: {elapsed:.1f}s"
    assert original == {DP_LABEL: "true"}
    labels = node_labels(fake_kube.get_node(NODE))
    assert is_paused(labels[DP_LABEL])
    assert handshake.request_token(
        labels.get(handshake.DRAIN_REQUESTED_LABEL)
    ) is not None
    assert labels.get(handshake.DRAIN_DEADLINE_LABEL) == "1"
    # The wedged pod is still there — the VM dies at the deadline and the
    # kill, not the drain, removes it.
    assert fake_kube.list_pods(NS, f"app={DP_APP}")


def test_readmit_clears_the_deadline_hint(fake_kube):
    """A cancelled preemption (or the replacement's crash-recovery
    readmit) must not leak the fast drain's deadline hint into the next
    normal drain cycle."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    original = evict.fast_drain_components(
        fake_kube, NODE, NS, deadline_s=0.1, poll_interval_s=0.01,
    )
    evict.readmit_components(fake_kube, NODE, original)
    labels = node_labels(fake_kube.get_node(NODE))
    assert handshake.DRAIN_REQUESTED_LABEL not in labels
    assert handshake.DRAIN_DEADLINE_LABEL not in labels
    assert labels[DP_LABEL] == "true"


def test_subscriber_reads_the_deadline_hint(fake_kube):
    """DrainSubscriber surfaces the fast drain's deadline so a checkpoint
    callback can size itself to the window."""
    seen: list[float | None] = []
    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "job-a",
        on_drain=lambda: seen.append(sub.drain_deadline_s),
        on_resume=lambda: None,
        poll_interval_s=0.01,
    )
    fake_kube.add_node(NODE)
    sub.register()
    handshake.request_drain(fake_kube, NODE, deadline_s=27.4)
    sub.check_once()
    assert seen == [27.0]  # whole-seconds label hint
    # A normal drain carries no hint.
    handshake.clear_drain_request(fake_kube, NODE)
    sub.check_once()
    handshake.request_drain(fake_kube, NODE)
    sub.check_once()
    assert sub.drain_deadline_s is None
