"""Unit tests for bench.py's headline-smoke selection.

The rule under test (select_headline_smoke): prefer the best backend any
run reached, report the median-by-tflops run on it with every raw value
disclosed, and in the degraded no-timed-smoke case fall back to the
control run's own backend — CPU numbers must never wear the TPU label
(VERDICT r4 weak #7: the headline MFU must not come from one
tunnel-noise-dominated run)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import select_headline_smoke


def _smoke(backend, tflops, mfu=None):
    return {"backend": backend, "tflops": tflops, "mfu": mfu}


class TestSelectHeadlineSmoke:
    def test_median_across_tpu_runs(self):
        smokes = [
            _smoke("tpu", 195.0, 0.99),  # control
            _smoke("tpu", 188.0, 0.95),
            _smoke("tpu", 196.0, 0.995),
        ]
        backend, smoke, timed = select_headline_smoke(smokes, "tpu")
        assert backend == "tpu"
        assert smoke["tflops"] == 195.0  # median of {188, 195, 196}
        assert [s["tflops"] for s in timed] == [188.0, 195.0, 196.0]

    def test_even_count_takes_lower_median(self):
        smokes = [_smoke("tpu", 190.0), _smoke("tpu", 196.0)]
        backend, smoke, timed = select_headline_smoke(smokes, "tpu")
        assert smoke["tflops"] == 190.0
        assert len(timed) == 2

    def test_tpu_preferred_over_cpu_fallback_runs(self):
        # Control degraded to CPU but a realistic run reached the chip:
        # the TPU evidence wins the headline.
        smokes = [
            _smoke("cpu", 0.3),  # control fell back
            _smoke("tpu", 195.0, 0.99),
        ]
        backend, smoke, timed = select_headline_smoke(smokes, "cpu")
        assert backend == "tpu"
        assert smoke["tflops"] == 195.0
        assert [s["tflops"] for s in timed] == [195.0]

    def test_untimed_tpu_run_falls_back_to_control_backend(self):
        # The one TPU run had timing_valid=false (tflops None): reporting
        # CPU numbers as backend="tpu" would be a lie. Fall back to the
        # control backend AND recompute the disclosure list for it.
        smokes = [
            _smoke("cpu", 0.3),
            _smoke("cpu", 0.25),
            _smoke("tpu", None),
        ]
        backend, smoke, timed = select_headline_smoke(smokes, "cpu")
        assert backend == "cpu"
        assert smoke["tflops"] == 0.25  # lower median of {0.25, 0.3}
        assert [s["tflops"] for s in timed] == [0.25, 0.3]

    def test_nothing_timed_returns_control_smoke(self):
        control = _smoke("cpu", None)
        backend, smoke, timed = select_headline_smoke(
            [control, _smoke("cpu", None)], "cpu"
        )
        assert backend == "cpu"
        assert smoke is control
        assert timed == []
