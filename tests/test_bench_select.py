"""Unit tests for the bench harnesses' reporting rules.

- bench.select_headline_smoke: prefer the best backend any run reached,
  report the median-by-tflops run on it with every raw value disclosed,
  and in the degraded no-timed-smoke case fall back to the control run's
  own backend — CPU numbers must never wear the TPU label (VERDICT r4
  weak #7: the headline MFU must not come from one tunnel-noise-
  dominated run).
- bench_ab.summarize_ab: median_low per arm (a REAL sample), loss sign
  convention, worst-across-workloads headline, and `ok` that can never
  be true when nothing was measured."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

from bench import phase_accounting, select_headline_smoke
from bench_ab import summarize_ab


class TestPhaseAccounting:
    """The wait_ready∥COMPILE warmup's serialized-equivalent fold-in:
    only the PRE-release compile overlap is added to the sum (post-
    release compile already sits inside the measured smoke phase), so
    the verify cost is never double-counted."""

    DURATIONS = {"drain": [3.0], "reset": [7.5], "wait_ready": [20.0],
                 "smoke": [0.6]}

    def test_warmup_overlap_extends_serial_sum_not_wall(self):
        base = phase_accounting(self.DURATIONS, 31.0)
        with_warmup = phase_accounting(
            self.DURATIONS, 31.0, smoke_compile_overlap_s=2.2,
        )
        assert with_warmup["wall_seconds"] == base["wall_seconds"]
        assert with_warmup["sum_phase_seconds"] == pytest.approx(
            base["sum_phase_seconds"] + 2.2
        )
        assert with_warmup["overlap_saved_s"] == pytest.approx(
            base["overlap_saved_s"] + 2.2
        )

    def test_zero_or_negative_overlap_is_a_noop(self):
        base = phase_accounting(self.DURATIONS, 31.0)
        assert phase_accounting(
            self.DURATIONS, 31.0, smoke_compile_overlap_s=0.0,
        ) == base
        assert phase_accounting(
            self.DURATIONS, 31.0, smoke_compile_overlap_s=-1.0,
        ) == base


def _smoke(backend, tflops, mfu=None):
    return {"backend": backend, "tflops": tflops, "mfu": mfu}


class TestSelectHeadlineSmoke:
    def test_median_across_tpu_runs(self):
        smokes = [
            _smoke("tpu", 195.0, 0.99),  # control
            _smoke("tpu", 188.0, 0.95),
            _smoke("tpu", 196.0, 0.995),
        ]
        backend, smoke, timed = select_headline_smoke(smokes, "tpu")
        assert backend == "tpu"
        assert smoke["tflops"] == 195.0  # median of {188, 195, 196}
        assert [s["tflops"] for s in timed] == [188.0, 195.0, 196.0]

    def test_even_count_takes_lower_median(self):
        smokes = [_smoke("tpu", 190.0), _smoke("tpu", 196.0)]
        backend, smoke, timed = select_headline_smoke(smokes, "tpu")
        assert smoke["tflops"] == 190.0
        assert len(timed) == 2

    def test_tpu_preferred_over_cpu_fallback_runs(self):
        # Control degraded to CPU but a realistic run reached the chip:
        # the TPU evidence wins the headline.
        smokes = [
            _smoke("cpu", 0.3),  # control fell back
            _smoke("tpu", 195.0, 0.99),
        ]
        backend, smoke, timed = select_headline_smoke(smokes, "cpu")
        assert backend == "tpu"
        assert smoke["tflops"] == 195.0
        assert [s["tflops"] for s in timed] == [195.0]

    def test_untimed_tpu_run_falls_back_to_control_backend(self):
        # The one TPU run had timing_valid=false (tflops None): reporting
        # CPU numbers as backend="tpu" would be a lie. Fall back to the
        # control backend AND recompute the disclosure list for it.
        smokes = [
            _smoke("cpu", 0.3),
            _smoke("cpu", 0.25),
            _smoke("tpu", None),
        ]
        backend, smoke, timed = select_headline_smoke(smokes, "cpu")
        assert backend == "cpu"
        assert smoke["tflops"] == 0.25  # lower median of {0.25, 0.3}
        assert [s["tflops"] for s in timed] == [0.25, 0.3]

    def test_nothing_timed_returns_control_smoke(self):
        control = _smoke("cpu", None)
        backend, smoke, timed = select_headline_smoke(
            [control, _smoke("cpu", None)], "cpu"
        )
        assert backend == "cpu"
        assert smoke is control
        assert timed == []


def _ab_inputs(workloads, off=(), on=()):
    """Minimal summarize_ab inputs: one workload's sample triples."""
    w = workloads[0]
    samples = {w: {"off": list(off), "on": list(on)}}
    detail = {w: {m: {"backend": "cpu", "generation": None}
                  for m in ("off", "on")}}
    wall = {w: {"off": 1.0, "on": 1.0}}
    errors = {w: []}
    return dict(
        workloads=workloads, samples=samples, detail=detail, wall=wall,
        errors=errors, retired=set(), planned_reps=3, target_pct=3.0,
    )


class TestSummarizeAb:
    def test_loss_positive_when_cc_on_slower(self):
        r = summarize_ab(**_ab_inputs(
            ["matmul"],
            off=[(100.0, 0.9, None)], on=[(98.0, 0.88, None)],
        ))
        assert r["workloads"]["matmul"]["loss_pct"] == 2.0
        assert r["value"] == 2.0
        assert r["ok"] is True  # 2% <= 3% target

    def test_loss_over_target_fails(self):
        r = summarize_ab(**_ab_inputs(
            ["matmul"],
            off=[(100.0, 0.9, None)], on=[(90.0, 0.8, None)],
        ))
        assert r["value"] == 10.0
        assert r["ok"] is False

    def test_median_low_is_a_real_sample(self):
        # Even count: the LOWER median sample's whole triple is reported,
        # never an average of two runs nobody observed.
        r = summarize_ab(**_ab_inputs(
            ["matmul"],
            off=[(100.0, 0.90, None), (104.0, 0.94, None)],
            on=[(99.0, 0.89, None)],
        ))
        arm = r["workloads"]["matmul"]["off"]
        assert arm["throughput"] == 100.0
        assert arm["mfu"] == 0.90
        assert arm["throughput_samples"] == [100.0, 104.0]
        assert arm["reps"] == 2 and arm["planned_reps"] == 3

    def test_empty_arm_yields_no_loss_and_not_ok(self):
        # An A/B that measured nothing must never read as passing.
        r = summarize_ab(**_ab_inputs(["matmul"], off=[], on=[]))
        assert r["workloads"]["matmul"]["loss_pct"] is None
        assert r["ok"] is False

    def test_worst_loss_across_workloads_wins(self):
        base = _ab_inputs(["matmul"], off=[(100.0, None, None)],
                          on=[(99.5, None, None)])
        extra = _ab_inputs(["llama"], off=[(3300.0, 0.01, 0.66)],
                           on=[(3100.0, 0.009, 0.62)])
        base["workloads"] = ["matmul", "llama"]
        base["samples"].update(extra["samples"])
        base["detail"].update(extra["detail"])
        base["wall"].update(extra["wall"])
        base["errors"].update(extra["errors"])
        r = summarize_ab(**base)
        assert r["workloads"]["matmul"]["loss_pct"] == 0.5
        assert r["workloads"]["llama"]["loss_pct"] == 6.06
        assert r["value"] == 6.06
        assert r["ok"] is False

    def test_negative_loss_clamps_headline_at_zero(self):
        # CC-on measured FASTER (noise): per-workload discloses the
        # negative loss, but the headline never goes below 0.
        r = summarize_ab(**_ab_inputs(
            ["matmul"],
            off=[(100.0, None, None)], on=[(101.0, None, None)],
        ))
        assert r["workloads"]["matmul"]["loss_pct"] == -1.0
        assert r["value"] == 0.0
        assert r["ok"] is True

    def test_errors_and_retirement_ride_along(self):
        inputs = _ab_inputs(["matmul"], off=[(100.0, None, None)],
                            on=[(99.0, None, None)])
        inputs["errors"] = {"matmul": ["boom", "boom again"]}
        inputs["retired"] = {"matmul"}
        r = summarize_ab(**inputs)
        assert r["workloads"]["matmul"]["errors"] == ["boom", "boom again"]
        assert r["workloads"]["matmul"]["retired_early"] is True


# ---- property coverage: the invariants the A/B claims rest on ----------

from _hypothesis_compat import given, st  # noqa: E402

_tflops = st.one_of(st.none(), st.floats(0.01, 1e4, allow_nan=False))
_smokes = st.lists(
    st.tuples(st.sampled_from(["cpu", "tpu"]), _tflops), min_size=1,
    max_size=8,
).map(lambda rows: [_smoke(b, t) for b, t in rows])


class TestSelectHeadlineSmokeProperties:
    @given(smokes=_smokes)
    def test_invariants(self, smokes):
        backend, smoke, timed = select_headline_smoke(smokes, smokes[0]["backend"])
        # The headline smoke is always a REAL measurement from the input.
        assert smoke in smokes
        # Disclosure list: sorted, non-None, single-backend, and when
        # non-empty the headline is its median_low element.
        tf = [s["tflops"] for s in timed]
        assert tf == sorted(tf) and None not in tf
        assert all(s["backend"] == backend for s in timed)
        if timed:
            assert smoke is timed[(len(timed) - 1) // 2]
        # TPU evidence wins whenever any TPU run carried a timing.
        if any(s["backend"] == "tpu" and s["tflops"] is not None
               for s in smokes):
            assert backend == "tpu"


_arm = st.lists(
    st.tuples(st.floats(0.1, 1e4, allow_nan=False), st.none(), st.none()),
    max_size=5,
)


class TestSummarizeAbProperties:
    @given(off=_arm, on=_arm, target=st.floats(0.0, 50.0))
    def test_invariants(self, off, on, target):
        inputs = _ab_inputs(["matmul"], off=off, on=on)
        inputs["target_pct"] = target
        r = summarize_ab(**inputs)
        modes = r["workloads"]["matmul"]
        # Headline never negative; per-arm medians are real samples.
        assert r["value"] >= 0.0
        for mode, got in (("off", off), ("on", on)):
            arm = modes[mode]
            if got:
                assert arm["throughput"] in [s[0] for s in got]
            else:
                assert arm["throughput"] is None
        # ok demands a measured pair within target; an empty A/B never
        # passes.
        if not off or not on:
            assert modes["loss_pct"] is None
            assert r["ok"] is False
        else:
            assert r["ok"] == (r["value"] <= target)


class TestAbPowerDisclosure:
    """Mean ± 95% CI half-width per arm + propagated loss half-width
    (the reps>=5 power satellite: an underpowered delta must be visible
    in the artifact, not masquerade as a measurement)."""

    def test_mean_ci95_small_samples(self):
        from bench_ab import mean_ci95

        mean, hw = mean_ci95([10.0, 12.0, 11.0, 13.0, 9.0])
        assert mean == 11.0
        assert hw == pytest.approx(2.78 * (2.5 ** 0.5) / (5 ** 0.5), rel=1e-6)
        # Below 2 samples there is no variance estimate — say so.
        assert mean_ci95([5.0]) == (5.0, None)
        assert mean_ci95([]) == (None, None)

    def test_powered_loss_flagged_true(self):
        off = [(100.0 + d, None, None) for d in (-0.2, -0.1, 0.0, 0.1, 0.2)]
        on = [(90.0 + d, None, None) for d in (-0.2, -0.1, 0.0, 0.1, 0.2)]
        r = summarize_ab(**_ab_inputs(["matmul"], off=off, on=on))
        m = r["workloads"]["matmul"]
        assert m["off"]["mean"] == 100.0
        assert m["off"]["ci95_half_width"] is not None
        assert m["loss_pct"] == pytest.approx(10.0)
        assert m["loss_powered"] is True
        assert m["loss_pct_ci95_half_width"] < 1.0

    def test_underpowered_loss_flagged_false(self):
        # The r4 failure shape: a "loss" far inside the arms' jitter.
        off = [(100.0 + d, None, None) for d in (-8.0, -3.0, 0.0, 3.0, 8.0)]
        on = [(99.5 + d, None, None) for d in (-8.0, -3.0, 0.0, 3.0, 8.0)]
        r = summarize_ab(**_ab_inputs(["matmul"], off=off, on=on))
        m = r["workloads"]["matmul"]
        assert m["loss_powered"] is False
        assert m["loss_pct_ci95_half_width"] > abs(m["loss_pct"])

    def test_single_sample_arm_reports_unknown_power(self):
        r = summarize_ab(**_ab_inputs(
            ["matmul"],
            off=[(100.0, None, None)], on=[(97.0, None, None)],
        ))
        m = r["workloads"]["matmul"]
        assert m["loss_pct"] == pytest.approx(3.0)
        assert m["loss_pct_ci95_half_width"] is None
        assert m["loss_powered"] is None
