"""End-to-end configs[3] simulation on the virtual CPU mesh: rolling CC
reconfiguration of a pool UNDER a live (simulated) training job, with
checkpoint before the bounce and sharded restore after.

This ties together the pieces that the per-module tests cover separately —
rolling orchestrator (ccmanager/rolling.py), checkpoint/resume
(parallel/checkpoint.py), sharded training (parallel/train.py), and
multi-slice attestation coherence (ccmanager/multislice.py) — into the
BASELINE.json configs[3]/[4] storyline: train → snapshot → bounce the pool
to CC-on → restore → training continues EXACTLY (bit-equal losses vs an
uninterrupted run; the restore captured params, optimizer moments and step
counter completely).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from tpu_cc_manager.ccmanager import multislice
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.drain.state import set_cc_state_label
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    SLICE_ID_LABEL,
)
from tpu_cc_manager.models.llama import LlamaConfig
from tpu_cc_manager.parallel.checkpoint import TrainCheckpointer
from tpu_cc_manager.parallel.distributed import verify_dcn_mesh
from tpu_cc_manager.parallel.mesh import MeshSpec, make_mesh
from tpu_cc_manager.parallel.sharding import batch_sharding
from tpu_cc_manager.parallel.train import (
    make_llama_train_state,
    make_llama_train_step,
)
from tpu_cc_manager.tpudev.attestation import fresh_nonce, verify_quote
from tpu_cc_manager.tpudev.fake import FakeTpuBackend

POOL = {  # two 2-host "slices" (the 2x mini version of 2x v5p-64)
    "slice-a": ("node-a0", "node-a1"),
    "slice-b": ("node-b0", "node-b1"),
}


def _make_pool(fake_kube):
    for slice_id, nodes in POOL.items():
        for name in nodes:
            fake_kube.add_node(name, {SLICE_ID_LABEL: slice_id})


def _agent_reactor(fake_kube):
    """Emulate the per-node DaemonSet agents: when a node's desired label
    changes, 'apply' it (fake backend per slice) and report state +
    attestation, as CCManager does after a real reconfigure."""
    backends = {s: FakeTpuBackend(num_chips=2, slice_id=s) for s in POOL}
    applying: set[str] = set()  # the reactor's own patches re-trigger it

    def reactor(name, patched):
        labels = node_labels(patched)
        desired = labels.get(CC_MODE_LABEL)
        if name in applying:
            return
        if not desired or labels.get(CC_MODE_STATE_LABEL) == desired:
            return
        applying.add(name)
        try:
            slice_id = labels[SLICE_ID_LABEL]
            backend = backends[slice_id]
            chips = backend.discover().chips
            backend.stage_cc_mode(chips, desired)
            backend.reset(chips)
            backend.wait_ready(chips, timeout_s=5.0)
            nonce = fresh_nonce()
            quote = backend.fetch_attestation(nonce)
            verify_quote(quote, nonce, expected_mode=desired, allow_fake=True)
            multislice.publish_quote(fake_kube, name, quote)
            set_cc_state_label(fake_kube, name, desired)
        finally:
            applying.discard(name)

    fake_kube.add_patch_reactor(reactor)


@pytest.fixture(scope="module")
def training():
    cfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshSpec(dcn=2, dp=1, fsdp=2, tp=2))
    state, shardings = make_llama_train_state(cfg, mesh)
    step = make_llama_train_step(cfg, mesh, shardings)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(7), (8, 32), 0, cfg.vocab_size),
        batch_sharding(mesh),
    )
    return cfg, mesh, state, shardings, step, tokens


def test_rolling_bounce_under_training_resumes_exactly(
    fake_kube, tmp_path_factory, training
):
    cfg, mesh, state0, shardings, step, tokens = training
    _make_pool(fake_kube)
    _agent_reactor(fake_kube)

    # The train step donates its input state, so clone the shared initial
    # state per branch (array copy keeps shardings and the static pytree
    # metadata — apply_fn/tx — identical, which a re-init would not).
    def clone(state):
        return jax.tree.map(jnp.copy, state)

    # --- reference run: 6 uninterrupted steps --------------------------
    ref_state = clone(state0)
    ref_losses = []
    for _ in range(6):
        ref_state, loss = step(ref_state, tokens)
        ref_losses.append(float(loss))

    # --- interrupted run: 3 steps, snapshot, bounce pool, restore ------
    state = clone(state0)
    for _ in range(3):
        state, _ = step(state, tokens)

    ckpt = TrainCheckpointer(str(tmp_path_factory.mktemp("ckpt")))
    ckpt.save(3, state)

    # Rolling CC-on bounce, one slice group at a time (the training job
    # is "paused" here: drained nodes can't serve collectives).
    rollout = RollingReconfigurator(
        fake_kube, selector="", poll_interval_s=0.01, node_timeout_s=5.0
    ).rollout("on")
    assert rollout.ok, rollout.summary()
    assert len(rollout.groups) == 2  # slice-atomic groups
    assert all(len(g.nodes) == 2 for g in rollout.groups)

    # Every slice must attest to the same runtime digest before the DCN
    # mesh is re-formed (configs[4] invariant); raises on any divergence.
    slices = multislice.verify_pool_attestation(
        fake_kube, selector="", expected_mode="on", expected_slices=2,
        allow_fake=True,
    )
    assert set(slices) == {"slice-a", "slice-b"}

    # Re-form the mesh (same topology after the bounce) and verify the
    # collective path actually works before resuming.
    assert verify_dcn_mesh(mesh)

    # Restore into the sharded abstract target — arrays come back
    # distributed, never replicated through one host.
    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        jax.eval_shape(lambda: state),
        shardings,
    )
    restored = ckpt.restore(abstract)
    ckpt.close()
    assert int(restored.step) == 3
    for leaf, sh in zip(
        jax.tree.leaves(restored), jax.tree.leaves(shardings)
    ):
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim)

    # --- resumed training must match the uninterrupted run bit-for-bit —
    # params, adamw moments and step counter all survived the bounce.
    resumed_losses = []
    for _ in range(3):
        restored, loss = step(restored, tokens)
        resumed_losses.append(float(loss))
    assert resumed_losses == ref_losses[3:], (
        f"resume diverged: {resumed_losses} != {ref_losses[3:]}"
    )
