"""Crash-safe continuous prestage under the capacity ledger (ISSUE 19).

The tentpole contract, held to in tier-1:

- The **CapacityLedger** (rollout_state.py, record format v7) conserves
  headroom charges: every reserve is refused past the limit or on an
  existing entry (the no-double-charge proof), every release settles
  exactly one charge, and ``balanced()`` holds across any interleaving
  (property-tested below via the hypothesis shim).
- **Continuous prestage** (rolling.py): the window loop tops up wave
  N+1's prestage while window N flips, bounded by
  ``min(headroom_gate(), max_unavailable)``; held nodes flip zero-bounce
  in ~drain+readmit; a prestage-path failure degrades that node to the
  full flip path and the rollout presses on; sustained SLO burn pauses
  prestage — never the wave.
- **Resume** adopts checkpointed entries as-is (no re-surge, no second
  ledger charge) and invalidates entries whose plan digest drifted — a
  stale prestaged node re-flips, never converges against an old plan.

The chaos-marked soak (``-m chaos -s``) kills the orchestrator
mid-prestage of wave N+1 while wave N drains (FaultPlan's seeded
``seed_prestage_kill``) and prints the PRESTAGE_SUMMARY line
hack/chaos_soak.sh scrapes.
"""

from __future__ import annotations

import json
import threading

import pytest

from _hypothesis_compat import given, settings, st
from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.rolling import (
    RollingReconfigurator,
    headroom_gate_from_source,
)
from tpu_cc_manager.faults.plan import FaultPlan, OrchestratorKilled
from tpu_cc_manager.kubeclient.api import node_annotations, node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    PRESTAGE_ANNOTATION,
    PRESTAGED_ANNOTATION,
)
from tpu_cc_manager.obs import flight as flight_mod
from tpu_cc_manager.obs import slo as slo_mod
from tpu_cc_manager.serve import sweep as sweep_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

POOL = "pool=tpu"
NS = "tpu-operator"


def add_pool(fake, n=4, slice_map=None):
    for i in range(n):
        labels = {"pool": "tpu"}
        if slice_map and i in slice_map:
            labels["cloud.google.com/tpu-slice-id"] = slice_map[i]
        fake.add_node(f"node-{i}", labels)


def prestage_agent_simulator(
    fake, counts=None, prestaged=None, obey_prestage=True,
):
    """Emulate prestage-capable per-node agents: a PRESTAGE annotation
    runs the flip ahead of the wave (state label to the mode, PRESTAGED
    status record published, ``prestaged`` counted); the wave's desired
    write then converges instantly with NO reconcile — ``counts`` only
    grows on the full flip path, so it is the double-bounce detector
    AND the zero-bounce proof."""
    in_flight = set()

    def reactor(name, node):
        ann = node_annotations(node)
        labels = node_labels(node)
        want = ann.get(PRESTAGE_ANNOTATION)
        state = labels.get(CC_MODE_STATE_LABEL)
        if obey_prestage and want and state != want and name not in in_flight:
            in_flight.add(name)
            if prestaged is not None:
                prestaged[name] = prestaged.get(name, 0) + 1

            def hold():
                # State label first, record second: re-entrant reactor
                # invocations from these patches see state == want and
                # do nothing.
                fake.set_node_label(name, CC_MODE_STATE_LABEL, want)
                fake.patch_node_annotations(name, {
                    PRESTAGED_ANNOTATION: json.dumps({
                        "mode": want, "prior": state or "off",
                        "seconds": 0.01, "ts": 0,
                    }),
                })
                in_flight.discard(name)
                # Re-evaluate: a write that landed while this transition
                # was in flight was skipped by the in_flight guard.
                reactor(name, fake.get_node(name))

            t = threading.Timer(0.03, hold)
            t.daemon = True
            t.start()
            return
        rec_raw = ann.get(PRESTAGED_ANNOTATION)
        if rec_raw and not want and name not in in_flight:
            # The arm was deleted (abort / invalidation): the agent
            # breaks its hold and reverts to the desired mode (or its
            # pre-prestage prior), clearing the stale status record —
            # the node re-flips via the full path.
            try:
                prior = json.loads(rec_raw).get("prior") or "off"
            except ValueError:
                prior = "off"
            target = labels.get(CC_MODE_LABEL) or prior
            if node_labels(fake.get_node(name)).get(
                CC_MODE_STATE_LABEL
            ) != target:
                in_flight.add(name)

                def revert():
                    fake.set_node_label(name, CC_MODE_STATE_LABEL, target)
                    fake.patch_node_annotations(
                        name, {PRESTAGED_ANNOTATION: None}
                    )
                    in_flight.discard(name)
                    reactor(name, fake.get_node(name))

                t = threading.Timer(0.03, revert)
                t.daemon = True
                t.start()
                return
        desired = labels.get(CC_MODE_LABEL)
        if desired and state != desired and name not in in_flight:
            in_flight.add(name)
            if counts is not None:
                counts[name] = counts.get(name, 0) + 1

            def fire():
                fake.set_node_label(name, CC_MODE_STATE_LABEL, desired)
                in_flight.discard(name)
                reactor(name, fake.get_node(name))

            t = threading.Timer(0.03, fire)
            t.daemon = True
            t.start()

    fake.add_patch_reactor(reactor)
    # FakeKube only fires patch reactors on LABEL patches; the PRESTAGE
    # arm is an annotation patch, so the simulated agent also watches
    # those.
    real_ann = fake.patch_node_annotations

    def patched_ann(name, annotations):
        node = real_ann(name, annotations)
        reactor(name, node)
        return node

    fake.patch_node_annotations = patched_ann


def make_roller(fake, **kw):
    kw.setdefault("node_timeout_s", 5)
    kw.setdefault("poll_interval_s", 0.02)
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("continuous_prestage", True)
    kw.setdefault("prestage_timeout_s", 1.0)
    return RollingReconfigurator(fake, POOL, **kw)


class Clock:
    """Injectable wall/monotonic clock for deterministic lease expiry."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def make_lease(fake, holder, clk, duration_s=30.0, metrics=None):
    return rollout_state.RolloutLease(
        fake, holder=holder, namespace=NS, duration_s=duration_s,
        metrics=metrics or MetricsRegistry(), wall=clk, clock=clk,
    )


# ---------------------------------------------------------------------------
# CapacityLedger conservation (unit + property)
# ---------------------------------------------------------------------------


def test_ledger_reserve_mark_release_conserves_charges():
    led = rollout_state.CapacityLedger()
    assert led.reserve("n0", "g0", "d0", 1, limit=2)
    assert led.reserve("n1", "g1", "d1", 1, limit=2)
    # At the limit: a third reservation is refused, nothing charged.
    assert not led.reserve("n2", "g2", "d2", 1, limit=2)
    assert "n2" not in led.charged
    # Re-reserving an existing entry IS the double charge the ledger
    # exists to prevent: refused, charge count untouched.
    assert not led.reserve("n0", "g0", "d0", 2, limit=99)
    assert led.charged["n0"] == 1
    assert led.in_transition() == 2
    # Held entries serve again: they free transition headroom, so the
    # next reservation fits — this is what pipelines wave N+1.
    led.mark("n0", rollout_state.LEDGER_HELD)
    assert led.in_transition() == 1
    assert led.reserve("n2", "g2", "d2", 1, limit=2)
    assert led.balanced()
    # Release settles exactly one charge; releasing an absent node is
    # an idempotent no-op (crash between release and checkpoint).
    assert led.release("n0")
    assert not led.release("n0")
    assert led.released["n0"] == 1
    for n in ("n1", "n2"):
        assert led.release(n)
    assert led.balanced() and not led.entries
    assert led.charges_total() == 3 == led.releases_total()
    assert led.double_charged() == []


def test_ledger_round_trips_through_record_v7():
    led = rollout_state.CapacityLedger()
    led.reserve("n0", "g0", "d0", 3, limit=1)
    led.mark("n0", rollout_state.LEDGER_ARMED)
    led.release("n0")
    led.reserve("n1", "g1", "d1", 3, limit=1)
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=3,
        groups=[("g1", ("n1",))], ledger=led,
    )
    raw = rec.to_json()
    obj = json.loads(raw)
    # A touched ledger forces format v7 — the loud-refusal boundary for
    # older binaries (they reject versions above their own). Demand-driven
    # versioning keeps it AT 7 even as RECORD_VERSION advances for other
    # features (v8 = fail-slow verdicts): a ledger-only record must not
    # lock out v7 binaries.
    assert obj["version"] == 7
    assert rollout_state.RECORD_VERSION >= 7
    back = rollout_state.RolloutRecord.from_json(raw)
    assert back.ledger is not None
    assert back.ledger.entry("n1")["state"] == rollout_state.LEDGER_RESERVED
    assert back.ledger.entry("n1")["gid"] == "g1"
    assert back.ledger.charged == {"n0": 1, "n1": 1}
    assert back.ledger.released == {"n0": 1}
    assert back.ledger.balanced()
    # No ledger (or an untouched one) keeps the downgrade-compatible
    # pre-v7 format: a non-prestaging rollout never locks out older
    # binaries.
    plain = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=3, groups=[("g1", ("n1",))],
        ledger=rollout_state.CapacityLedger(),
    )
    pobj = json.loads(plain.to_json())
    assert pobj.get("version", 1) < rollout_state.RECORD_VERSION
    assert "ledger" not in pobj


@settings(max_examples=60, deadline=None)
@given(
    ops=st.lists(
        st.tuples(
            st.sampled_from(["reserve", "hold", "release", "kill-resume"]),
            st.integers(min_value=0, max_value=5),
        ),
        max_size=40,
    ),
    limit=st.integers(min_value=0, max_value=3),
)
def test_ledger_invariants_under_interleaved_ops(ops, limit):
    """The acceptance property: across ANY interleaving of
    reserve/hold/release — including a kill+resume, modeled as a
    serialize/deserialize round trip mid-sequence — the in-transition
    count never exceeds the limit (concurrent prestages can never
    violate ``max_unavailable``), the ledger stays balanced, and no
    node is ever double-charged without an intervening release."""
    led = rollout_state.CapacityLedger()
    live_charges: dict[str, int] = {}
    for op, i in ops:
        node = f"n{i}"
        if op == "reserve":
            before = led.in_transition()
            ok = led.reserve(node, f"g{i}", f"d{i}", 1, limit=limit)
            if ok:
                live_charges[node] = live_charges.get(node, 0) + 1
                assert before < limit
            else:
                assert node in led.entries or before >= limit
        elif op == "hold":
            led.mark(node, rollout_state.LEDGER_HELD)
        elif op == "release":
            led.release(node)
        else:  # kill-resume: only the checkpointed state survives
            led = rollout_state.CapacityLedger.from_dict(led.to_dict())
        assert led.in_transition() <= max(limit, 0)
        assert led.balanced()
        # A node's lifetime charges can only exceed one via an
        # intervening release (a legitimate re-reservation) — never a
        # straight double charge.
        for n, c in led.charged.items():
            assert c - led.released.get(n, 0) <= 1


def test_ledger_invariants_random_interleavings_seeded():
    """The same conservation property as the hypothesis test above, as
    a seeded plain-random fuzz so the invariant is exercised even on
    images without hypothesis (the shim skips the property test
    visibly there)."""
    import random

    rng = random.Random(20260807)
    for _trial in range(200):
        limit = rng.randrange(0, 4)
        led = rollout_state.CapacityLedger()
        for _ in range(30):
            op = rng.choice(["reserve", "hold", "release", "kill-resume"])
            node = f"n{rng.randrange(6)}"
            if op == "reserve":
                before = led.in_transition()
                if led.reserve(node, "g", "d", 1, limit=limit):
                    assert before < limit
            elif op == "hold":
                led.mark(node, rollout_state.LEDGER_HELD)
            elif op == "release":
                led.release(node)
            else:
                led = rollout_state.CapacityLedger.from_dict(led.to_dict())
            assert led.in_transition() <= limit
            assert led.balanced()
            for n, c in led.charged.items():
                assert c - led.released.get(n, 0) <= 1


@settings(max_examples=60, deadline=None)
@given(
    knee=st.floats(min_value=1.0, max_value=1e5),
    offered=st.floats(min_value=0.0, max_value=2e5),
    n=st.integers(min_value=1, max_value=64),
    reserve=st.integers(min_value=0, max_value=4),
)
def test_knee_slack_reservation_never_exceeds_slack(knee, offered, n, reserve):
    """Reserved headroom never exceeds knee slack, and the allowance
    always leaves the configured reserve (>=1 node in BENCH_r09's
    shape) un-spendable."""
    slack = sweep_mod.knee_slack_nodes(knee, offered, n)
    allow = sweep_mod.prestage_allowance(knee, offered, n, reserve)
    assert 0 <= allow <= slack <= n or (slack >= 0 and offered < knee)
    assert allow <= max(0, slack - reserve) or reserve == 0
    # Whole nodes only, and slack * per-node capacity fits under the
    # knee minus the offered load (never oversubscribes).
    assert slack * (knee / n) <= max(0.0, knee - offered) + 1e-6


def test_prestage_allowance_caps_at_max_unavailable_and_fails_closed():
    fake = FakeKube()
    add_pool(fake, 2)
    roller = make_roller(fake, max_unavailable=2, headroom_gate=lambda: 99)
    assert roller._prestage_allowance() == 2
    roller.headroom_gate = lambda: 1
    assert roller._prestage_allowance() == 1
    roller.headroom_gate = lambda: -3
    assert roller._prestage_allowance() == 0
    # A gate that RAISES reads zero slack — fail-CLOSED (the mirror of
    # the SLO gate's fail-open): prestage must never consume headroom
    # it cannot prove exists. The wave is never paused by this.
    def broken():
        raise OSError("scrape endpoint died")

    roller.headroom_gate = broken
    assert roller._prestage_allowance() == 0
    roller.headroom_gate = None
    assert roller._prestage_allowance() == 2


def test_headroom_gate_from_source_scrapes_offered_rps():
    text = (
        "tpu_cc_serve_goodput_rps 790.0\n"
        "tpu_cc_serve_offered_rps 800.0\n"
    )
    assert slo_mod.parse_serve_offered_rps(text) == 800.0
    assert slo_mod.parse_serve_offered_rps("nothing here") is None
    gate = headroom_gate_from_source(
        "http://pool:9100/metrics", knee_rps=1000.0, n_nodes=10,
        fetch=lambda url: text,
    )
    # 200 rps of slack at 100 rps/node = 2 whole nodes.
    assert gate() == 2
    # No offered gauge exported: zero slack, not an invented number.
    empty_gate = headroom_gate_from_source(
        "http://pool:9100/metrics", knee_rps=1000.0, n_nodes=10,
        fetch=lambda url: "",
    )
    assert empty_gate() == 0

    # A dead endpoint RAISES — _prestage_allowance turns that into
    # zero slack (fail-closed), asserted above.
    def dead(url):
        raise OSError("connection refused")

    dead_gate = headroom_gate_from_source(
        "http://pool:9100/metrics", knee_rps=1000.0, n_nodes=10, fetch=dead,
    )
    with pytest.raises(OSError):
        dead_gate()


# ---------------------------------------------------------------------------
# Continuous prestage end-to-end (fake pool, prestage-capable agents)
# ---------------------------------------------------------------------------


def test_continuous_prestage_pipelines_zero_bounce_windows(tmp_path):
    """The tentpole happy path: with prestage-capable agents every
    window's nodes are reserved, armed and HELD before their flip
    window opens — so every flip converges zero-bounce (the full-path
    reconcile counter never moves), the ledger balances to zero, and
    the flight journal carries the whole reserve→arm→hold→release
    lifecycle."""
    fake = FakeKube()
    add_pool(fake, 4, slice_map={0: "s1", 1: "s1"})
    counts: dict = {}
    prestaged: dict = {}
    prestage_agent_simulator(fake, counts=counts, prestaged=prestaged)
    fpath = str(tmp_path / "flight.jsonl")
    metrics = MetricsRegistry()
    roller = make_roller(
        fake, max_unavailable=2, headroom_gate=lambda: 8,
        flight=flight_mod.FlightRecorder(fpath), metrics=metrics,
    )
    result = roller.rollout("on")
    assert result.ok, result.summary()
    # Zero full-path reconciles anywhere: every node flipped via its
    # held prestage.
    assert counts == {}, f"full-path reconciles on {counts}"
    assert all(prestaged.get(f"node-{i}") == 1 for i in range(4)), prestaged
    led = roller._ledger
    assert led is not None and led.balanced() and not led.entries
    assert led.charges_total() == 4 == led.releases_total()
    assert led.double_charged() == []
    events, torn = flight_mod.read_events(fpath)
    assert torn == 0
    rec = flight_mod.reconstruct(events)
    pre = rec["prestage"]
    assert pre is not None
    assert sorted(pre["reserved"]) == [f"node-{i}" for i in range(4)]
    assert sorted(pre["held"]) == [f"node-{i}" for i in range(4)]
    assert pre["released"] == {"converged": 4}
    assert pre["invalidated"] == [] and pre["paused"] == 0
    # The metric families exported (the cclint triangle's runtime leg).
    text = metrics.render_prometheus()
    assert "tpu_cc_prestage_reserved 0" in text
    assert 'tpu_cc_prestage_total{outcome="held"} 4' in text
    assert 'tpu_cc_prestage_total{outcome="converged"} 4' in text


def test_prestage_timeout_degrades_to_full_flip_and_presses_on(tmp_path):
    """Graceful degradation: agents that never honor the PRESTAGE
    annotation (older binaries, CC_PRESTAGE=0) cost each window only
    the bounded finalize await — the entry is invalidated as degraded,
    the node takes the PR-10 full flip path, and the rollout still
    converges every node exactly once. A prestage-path failure never
    halts."""
    fake = FakeKube()
    add_pool(fake, 3)
    counts: dict = {}
    prestage_agent_simulator(fake, counts=counts, obey_prestage=False)
    fpath = str(tmp_path / "flight.jsonl")
    metrics = MetricsRegistry()
    roller = make_roller(
        fake, max_unavailable=1, prestage_timeout_s=0.2,
        flight=flight_mod.FlightRecorder(fpath), metrics=metrics,
    )
    result = roller.rollout("on")
    assert result.ok, result.summary()
    assert all(counts.get(f"node-{i}") == 1 for i in range(3)), counts
    led = roller._ledger
    assert led.balanced() and not led.entries
    totals = metrics.prestage_totals()
    assert totals.get("degraded", 0) == 3
    assert totals.get("held", 0) == 0
    rec = flight_mod.reconstruct(flight_mod.read_events(fpath)[0])
    assert sorted(rec["prestage"]["invalidated"]) == [
        f"node-{i}" for i in range(3)
    ]
    # The arm annotations were aborted, not left to re-engage later.
    for i in range(3):
        assert PRESTAGE_ANNOTATION not in node_annotations(
            fake.get_node(f"node-{i}")
        )


def test_slo_burn_pauses_prestage_never_the_wave(tmp_path):
    """Sustained SLO burn pauses prestage top-up — and ONLY that: the
    wave keeps flipping (no slo-paused window pause), the paused
    boundary is journaled and counted, and once the burn clears the
    top-up resumes."""
    fake = FakeKube()
    add_pool(fake, 3)
    counts: dict = {}
    prestaged: dict = {}
    prestage_agent_simulator(fake, counts=counts, prestaged=prestaged)
    calls = {"n": 0}

    def gate() -> bool:
        # Call 1 is window 0's wave-gate poll (healthy); call 2 is the
        # maintenance pass's burn check (burning: prestage pauses while
        # the wave proceeds); later calls are healthy again.
        calls["n"] += 1
        return calls["n"] == 2

    fpath = str(tmp_path / "flight.jsonl")
    metrics = MetricsRegistry()
    roller = make_roller(
        fake, max_unavailable=1, slo_gate=gate,
        flight=flight_mod.FlightRecorder(fpath), metrics=metrics,
    )
    result = roller.rollout("on")
    assert result.ok, result.summary()
    names = [e["event"] for e in flight_mod.read_events(fpath)[0]]
    assert "prestage-paused" in names
    assert "slo-paused" not in names, "the WAVE must never pause for this"
    assert names.count("window-open") == 3
    assert metrics.prestage_totals().get("paused", 0) == 1
    # Window 0 flipped full-path under the paused top-up; the burn
    # cleared and later windows prestaged again.
    assert counts.get("node-0") == 1
    assert prestaged.get("node-1") == 1 and prestaged.get("node-2") == 1
    rec = flight_mod.reconstruct(flight_mod.read_events(fpath)[0])
    assert rec["prestage"]["paused"] == 1


# ---------------------------------------------------------------------------
# Crash + resume: adopt-as-is, no second charge, digest invalidation
# ---------------------------------------------------------------------------


def test_resume_adopts_armed_entry_without_second_charge(tmp_path):
    """Satellite 1 (the re-pick hazard): SIGKILL between prestage-armed
    and the flip; the successor adopts the held node AS-IS — no
    re-surge, no second ledger charge (``reserve()`` refusing an
    existing entry is the proof), mirroring the prestaged-spare resume
    rule — and the adopted node still flips zero-bounce."""
    fake = FakeKube()
    add_pool(fake, 4, slice_map={0: "s1", 1: "s1"})
    counts: dict = {}
    prestaged: dict = {}
    prestage_agent_simulator(fake, counts=counts, prestaged=prestaged)
    metrics = MetricsRegistry()
    clk = Clock()
    lease_a = make_lease(fake, "orch-a", clk, metrics=metrics)
    lease_a.acquire()
    armed_once = {"fired": False}

    def kill_after_first_arm(point):
        if point == "prestage-armed" and not armed_once["fired"]:
            armed_once["fired"] = True
            raise OrchestratorKilled(point, 0)

    roller_a = make_roller(
        fake, lease=lease_a, max_unavailable=1, headroom_gate=lambda: 4,
        crash_hook=kill_after_first_arm,
    )
    with pytest.raises(OrchestratorKilled):
        roller_a.rollout("on")
    clk.advance(31)
    lease_b = make_lease(fake, "orch-b", clk, metrics=metrics)
    record = lease_b.acquire()
    assert record is not None and record.ledger is not None
    armed = [
        n for n in record.ledger.entries
        if record.ledger.entry(n)["state"] == rollout_state.LEDGER_ARMED
    ]
    assert armed, "the kill landed after a durable armed checkpoint"
    roller_b = make_roller(
        fake, lease=lease_b, resume_record=record, metrics=metrics,
        max_unavailable=1, headroom_gate=lambda: 4,
    )
    result = roller_b.rollout(record.mode)
    assert result.ok and result.resumed
    led = roller_b._ledger
    assert led.balanced() and not led.entries
    assert led.double_charged() == []
    for n in armed:
        # Adopted as-is: exactly ONE lifetime charge, one prestage run,
        # zero full-path reconciles.
        assert led.charged[n] == 1
        assert prestaged.get(n) == 1
        assert counts.get(n, 0) == 0
    for i in range(4):
        assert node_labels(fake.get_node(f"node-{i}"))[
            CC_MODE_STATE_LABEL
        ] == "on"


def test_resume_invalidates_digest_drift_and_releases_exactly_once():
    """Fence/plan-digest invalidation on resume: a checkpointed entry
    whose digest no longer matches the live plan is invalidated and
    released exactly once — the node's hold is aborted and it re-flips
    via the full path, never converging against the old plan."""
    fake = FakeKube()
    add_pool(fake, 2)
    counts: dict = {}
    prestage_agent_simulator(fake, counts=counts)
    # The dead orchestrator armed node-0 under a plan that has since
    # drifted (digest mismatch); the agent pre-staged and holds.
    fake.patch_node_annotations("node-0", {PRESTAGE_ANNOTATION: "on"})
    from tpu_cc_manager.utils import retry as retry_mod
    assert retry_mod.poll_until(
        lambda: node_annotations(fake.get_node("node-0")).get(
            PRESTAGED_ANNOTATION
        ) is not None,
        5.0, 0.02,
    )
    led = rollout_state.CapacityLedger()
    led.reserve("node-0", "node/node-0", "stale-digest", 1, limit=1)
    led.mark("node-0", rollout_state.LEDGER_ARMED)
    record = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[(f"node/node-{i}", (f"node-{i}",)) for i in range(2)],
        ledger=led,
    )
    metrics = MetricsRegistry()
    roller = make_roller(
        fake, resume_record=record, metrics=metrics,
        max_unavailable=1, headroom_gate=lambda: 0,  # no re-reserve noise
    )
    result = roller.rollout("on")
    assert result.ok and result.resumed
    assert led.released.get("node-0") == 1
    assert led.balanced() and not led.entries
    assert metrics.prestage_totals().get("invalidated", 0) == 1
    # The stale arm was aborted, and the node re-flipped the FULL path.
    assert PRESTAGE_ANNOTATION not in node_annotations(
        fake.get_node("node-0")
    )
    assert counts.get("node-0") == 1


def test_no_prestage_resume_drains_the_ledger():
    """The --no-prestage degraded-mode escape: resuming a ledgered
    record with continuous prestage OFF releases every checkpointed
    entry (aborted), balances the ledger, and every node takes the
    full flip path."""
    fake = FakeKube()
    add_pool(fake, 2)
    counts: dict = {}
    prestage_agent_simulator(fake, counts=counts)
    fake.patch_node_annotations("node-0", {PRESTAGE_ANNOTATION: "on"})
    from tpu_cc_manager.utils import retry as retry_mod
    assert retry_mod.poll_until(
        lambda: node_annotations(fake.get_node("node-0")).get(
            PRESTAGED_ANNOTATION
        ) is not None,
        5.0, 0.02,
    )
    led = rollout_state.CapacityLedger()
    gid = "node/node-0"
    digest = rollout_state.plan_digest("on", gid, ("node-0",))
    led.reserve("node-0", gid, digest, 1, limit=1)
    led.mark("node-0", rollout_state.LEDGER_ARMED)
    record = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[(f"node/node-{i}", (f"node-{i}",)) for i in range(2)],
        ledger=led,
    )
    metrics = MetricsRegistry()
    roller = make_roller(
        fake, resume_record=record, metrics=metrics,
        continuous_prestage=False, max_unavailable=1,
    )
    result = roller.rollout("on")
    assert result.ok
    assert led.balanced() and not led.entries
    assert metrics.prestage_totals().get("aborted", 0) == 1
    assert counts.get("node-0") == 1 and counts.get("node-1") == 1


def test_ctl_status_prints_prestage_ledger_block(fake_kube, capsys):
    """The degraded-mode runbook's first read: `ctl status` on a
    ledgered in-progress record prints the PRESTAGE block with
    per-state counts and the charge/release balance."""
    import argparse

    from tpu_cc_manager import ctl

    fake_kube.add_node("node-0", {"pool": "tpu"})
    clk = Clock()
    lease = make_lease(fake_kube, "orch-a", clk)
    lease.acquire()
    led = rollout_state.CapacityLedger()
    led.reserve("node-0", "node/node-0", "d0", 1, limit=2)
    led.mark("node-0", rollout_state.LEDGER_ARMED)
    led.reserve("node-9", "node/node-9", "d9", 1, limit=2)
    led.mark("node-9", rollout_state.LEDGER_HELD)
    record = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[("node/node-0", ("node-0",))], ledger=led,
    )
    lease.checkpoint(record)
    args = argparse.Namespace(selector=POOL, lease_namespace=NS)
    assert ctl.cmd_status(fake_kube, args) == 0
    out = capsys.readouterr().out
    assert "PRESTAGE ledger: 0 reserved, 1 armed, 1 held" in out
    assert "charges=2 releases=0 (balanced)" in out


def test_ctl_prestage_flag_validation(fake_kube):
    import argparse

    from tpu_cc_manager import ctl

    fake_kube.add_node("node-0", {"pool": "tpu"})

    def ns(**kw):
        base = dict(
            selector=POOL, mode="on", max_unavailable=1, node_timeout=5.0,
            continue_on_failure=False, rollback_on_failure=False,
            failure_budget=None, resume=False, abort_rollout=False,
            no_lease=True, lease_duration=30.0, lease_namespace=NS,
        )
        base.update(kw)
        return argparse.Namespace(**base)

    with pytest.raises(ValueError, match="contradictory"):
        ctl.cmd_rollout(
            fake_kube, ns(prestage_continuous=True, no_prestage=True)
        )
    with pytest.raises(ValueError, match="--prestage-continuous"):
        ctl.cmd_rollout(fake_kube, ns(prestage_knee_rps=1000.0))
    with pytest.raises(ValueError, match="--slo-source"):
        ctl.cmd_rollout(
            fake_kube,
            ns(prestage_continuous=True, prestage_knee_rps=1000.0),
        )


# ---------------------------------------------------------------------------
# Seeded chaos: kill mid-prestage of wave N+1 while wave N drains
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_seeded_kill_mid_prestage_of_next_wave():
    """The BENCH_r09 crash-leg shape in the soak: FaultPlan's seeded
    ``seed_prestage_kill`` SIGKILLs the orchestrator at one of the
    prestage crash points — mid-prestage of wave N+1 while wave N
    drains — and across however many successors it takes, BOTH waves
    resume, the capacity ledger balances to zero with no node
    double-charged, and no node is lost or double-bounced. Prints the
    PRESTAGE_SUMMARY line hack/chaos_soak.sh scrapes."""
    fake = FakeKube()
    add_pool(fake, 6, slice_map={0: "s1", 1: "s1"})
    counts: dict = {}
    prestaged: dict = {}
    prestage_agent_simulator(fake, counts=counts, prestaged=prestaged)
    metrics = MetricsRegistry()
    # Soak-seeded (CC_CHAOS_SEED) like the other chaos legs. Reserve/arm
    # points only: prestage-invalidate never fires in clean weather (no
    # digest drift, no timeout), so arming it would make the kill a
    # seed-dependent no-op — that point's coverage lives in the
    # exhaustive kill-at-every-crash-point test (test_rollout_resume).
    plan = FaultPlan.from_env(default_seed=20260807, rate=0.0, kill_rate=0.0)
    target = plan.seed_prestage_kill(
        points=("prestage-reserved", "prestage-armed"),
    )
    assert target in ("prestage-reserved", "prestage-armed")

    result = None
    last_led = None
    clk = Clock()
    for attempt in range(8):
        lease = make_lease(fake, f"orch-{attempt}", clk, metrics=metrics)
        record = lease.acquire()
        roller = make_roller(
            fake, lease=lease,
            resume_record=(
                record
                if record is not None
                and record.status == rollout_state.RECORD_IN_PROGRESS
                else None
            ),
            metrics=metrics, max_unavailable=2, headroom_gate=lambda: 6,
            crash_hook=plan.decide_orchestrator_kill,
        )
        try:
            result = roller.rollout("on")
            last_led = roller._ledger
            lease.release(clear_record=result.ok)
            break
        except OrchestratorKilled:
            clk.advance(31)
    assert result is not None and result.ok
    kills = [f for f in plan.injected if f.kind == "orch-kill"]
    assert kills and kills[0].op == target, (
        "the seeded prestage kill must land at the drawn crash point"
    )
    for i in range(6):
        name = f"node-{i}"
        assert node_labels(fake.get_node(name))[CC_MODE_STATE_LABEL] == "on"
        assert counts.get(name, 0) + (1 if prestaged.get(name) else 0) >= 1, (
            f"{name} was lost"
        )
        assert counts.get(name, 0) <= 1, f"{name} double-bounced"
    assert last_led is not None
    assert last_led.balanced() and not last_led.entries
    assert last_led.double_charged() == []
    assert metrics.rollout_totals()["resumes"] == len(kills)
    print("PRESTAGE_SUMMARY " + json.dumps({
        "kills": len(kills),
        "kill_point": target,
        "charges": last_led.charges_total(),
        "releases": last_led.releases_total(),
        "double_charged": last_led.double_charged(),
        "held": sum(1 for n, c in prestaged.items() if c),
        "full_path_flips": sum(counts.values()),
        "nodes": 6,
        "resumes": metrics.rollout_totals()["resumes"],
        "balanced": last_led.balanced(),
    }))
