"""Gate for the optional ``hypothesis`` dependency.

Not every image ships hypothesis (and nothing may be pip-installed into
the baked toolchain), but most modules that use it also carry plenty of
plain tests. Importing through this shim keeps those running everywhere:

- with hypothesis installed: re-exports the real ``given``/``settings``/
  ``strategies`` unchanged;
- without it: ``given(...)`` becomes a visible ``pytest.mark.skip``
  decorator (the property tests report as skipped, not silently vanish),
  and ``st`` becomes an inert object so module-level strategy
  definitions still evaluate.

Modules that are hypothesis through and through (the stateful state
machines) should use ``pytest.importorskip("hypothesis")`` instead.
"""

from __future__ import annotations

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:
    import pytest

    HAVE_HYPOTHESIS = False

    class _InertStrategy:
        """Absorbs any attribute access / call chain at module scope."""

        def __getattr__(self, name):
            return self

        def __call__(self, *args, **kwargs):
            return self

    st = _InertStrategy()

    def given(*args, **kwargs):
        return pytest.mark.skip(reason="hypothesis not installed")

    def settings(*args, **kwargs):  # decorator factory form only
        return lambda fn: fn

__all__ = ["HAVE_HYPOTHESIS", "given", "settings", "st"]
