"""SLO window math (tpu_cc_manager/obs/slo.py): property tests.

The evaluator is the single implementation behind both the
``tpu_cc_serve_slo_*`` gauges and the poll contract a latency-gated
rollout will use, so its math gets held to invariants, not examples:

- p99 is MONOTONE under added slow requests (a latency-gated rollout
  must never read "better" after the pool got slower);
- error counts are CONSERVED across window splits (budget accounting
  cannot double-count or drop errors at a boundary);
- an empty window reports no p99 and zero burn (no evidence is not bad
  evidence — a traffic pause must not halt a rollout).

Seeded-rng property loops (the repo's deterministic-property idiom; no
hypothesis dependency).
"""

from __future__ import annotations

import random

import pytest

from tpu_cc_manager.obs.slo import SloEvaluator, merge_p99, percentile


class Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def make(clock=None, **kw):
    kw.setdefault("windows_s", (10.0, 60.0))
    return SloEvaluator(clock=clock or Clock(), **kw)


# ---------------------------------------------------------------------------
# p99 monotonicity
# ---------------------------------------------------------------------------


def test_p99_monotone_under_added_slow_requests():
    """Property: appending requests at or above the current p99 can
    never LOWER the reported p99. 50 seeded rounds."""
    rng = random.Random(20260804)
    for round_i in range(50):
        clk = Clock()
        ev = make(clock=clk)
        n = rng.randint(1, 200)
        for _ in range(n):
            ev.observe(rng.uniform(0.001, 1.0))
        before = ev.stats(10.0)["p99_s"]
        assert before is not None
        # Add strictly-slower traffic.
        extra = rng.randint(1, 50)
        for _ in range(extra):
            ev.observe(before + rng.uniform(0.0, 2.0))
        after = ev.stats(10.0)["p99_s"]
        assert after >= before, (
            f"round {round_i}: p99 dropped {before} -> {after} after "
            "adding slower requests"
        )


def test_merge_p99_matches_percentile_of_union():
    rng = random.Random(7)
    for _ in range(20):
        a = sorted(rng.uniform(0, 1) for _ in range(rng.randint(0, 40)))
        b = sorted(rng.uniform(0, 2) for _ in range(rng.randint(0, 40)))
        expect = percentile(sorted(a + b), 0.99)
        assert merge_p99(a, b) == expect


# ---------------------------------------------------------------------------
# burn-rate conservation across window splits
# ---------------------------------------------------------------------------


def test_error_counts_conserved_across_window_splits():
    """Property: (samples, errors) over [t0, t2) equals the sum over
    [t0, t1) + [t1, t2) for EVERY split point t1 — the conservation the
    budget accounting rests on. 30 seeded rounds."""
    rng = random.Random(42)
    for round_i in range(30):
        clk = Clock(0.0)
        ev = make(clock=clk, windows_s=(100.0,))
        t_end = rng.uniform(5.0, 50.0)
        n = rng.randint(1, 300)
        times = sorted(rng.uniform(0.0, t_end) for _ in range(n))
        for t in times:
            ev.observe(
                rng.uniform(0.001, 0.2), ok=rng.random() > 0.3, now=t
            )
        clk.t = t_end  # pruning horizon covers everything
        whole = ev.counts_between(0.0, t_end + 1.0)
        for _ in range(5):
            t1 = rng.uniform(0.0, t_end)
            left = ev.counts_between(0.0, t1)
            right = ev.counts_between(t1, t_end + 1.0)
            assert (
                left[0] + right[0], left[1] + right[1]
            ) == whole, f"round {round_i}: split at {t1} not conserved"


def test_burn_rate_is_weighted_mean_of_split_burn_rates():
    """The whole window's burn rate equals the sample-count-weighted
    mean of any split's burn rates (directly implied by count
    conservation; asserted explicitly because THIS is the number the
    pacing loop acts on)."""
    clk = Clock(0.0)
    ev = make(clock=clk, windows_s=(100.0,), error_budget=0.01)
    rng = random.Random(3)
    for i in range(200):
        ev.observe(0.05, ok=rng.random() > 0.2, now=i * 0.1)
    clk.t = 20.0
    t1 = 10.0
    (n_all, e_all) = ev.counts_between(0.0, 20.0)
    (n_l, e_l) = ev.counts_between(0.0, t1)
    (n_r, e_r) = ev.counts_between(t1, 20.0)
    burn = (e_all / n_all) / ev.error_budget
    burn_l = (e_l / n_l) / ev.error_budget
    burn_r = (e_r / n_r) / ev.error_budget
    weighted = (burn_l * n_l + burn_r * n_r) / (n_l + n_r)
    assert burn == pytest.approx(weighted)


# ---------------------------------------------------------------------------
# empty-window behavior
# ---------------------------------------------------------------------------


def test_empty_window_reports_no_p99_and_zero_burn():
    ev = make()
    s = ev.stats(10.0)
    assert s["count"] == 0
    assert s["p99_s"] is None
    assert s["error_rate"] == 0.0
    assert s["burn_rate"] == 0.0
    assert s["goodput_rps"] == 0.0
    # And the halt predicate does NOT fire on no evidence.
    assert ev.breached(max_burn_rate=1.0) is False


def test_window_expiry_empties_the_readout():
    clk = Clock()
    ev = make(clock=clk)
    for _ in range(10):
        ev.observe(0.05, ok=False)
    assert ev.stats(10.0)["burn_rate"] > 0
    clk.advance(61.0)  # past the longest window; observe prunes
    ev.observe(0.01)
    s = ev.stats(10.0)
    assert s["errors"] == 0
    assert s["count"] == 1
    # Lifetime totals survive the window.
    snap = ev.snapshot()
    assert snap["errors_total"] == 10
    assert snap["total"] == 11


# ---------------------------------------------------------------------------
# the poll contract
# ---------------------------------------------------------------------------


def test_breached_on_burn_and_p99_target():
    clk = Clock()
    ev = SloEvaluator(
        windows_s=(10.0,), error_budget=0.01, p99_target_s=0.5, clock=clk,
    )
    for _ in range(99):
        ev.observe(0.01)
    assert ev.breached() is False
    # One error in 100 = 1% error rate = burn 1.0 exactly (not > 1.0).
    ev.observe_error()
    assert ev.breached(max_burn_rate=1.0) is False
    ev.observe_error()
    assert ev.breached(max_burn_rate=1.0) is True
    # p99 over target trips it even with zero errors.
    ev2 = SloEvaluator(
        windows_s=(10.0,), error_budget=0.01, p99_target_s=0.5, clock=clk,
    )
    for _ in range(100):
        ev2.observe(0.9)
    assert ev2.breached() is True


def test_snapshot_shape_is_the_documented_contract():
    ev = make()
    ev.observe(0.1)
    snap = ev.snapshot()
    assert set(snap) == {
        "error_budget", "p99_target_s", "windows", "total", "errors_total",
    }
    for w in snap["windows"]:
        assert {
            "window_s", "count", "errors", "ok", "error_rate",
            "burn_rate", "p99_s", "p50_s", "goodput_rps",
        } <= set(w)


def test_constructor_rejects_degenerate_configs():
    with pytest.raises(ValueError):
        SloEvaluator(windows_s=())
    with pytest.raises(ValueError):
        SloEvaluator(error_budget=0.0)


def test_parse_serve_slo_text_roundtrips_the_exported_gauges():
    """The remote gate's parser reads back exactly what the registry
    renders — the two ends of the ctl --slo-source loop cannot drift."""
    from tpu_cc_manager.obs import slo as slo_mod
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    registry = MetricsRegistry()
    registry.set_serve_slo(5.0, 0.042, 1.25)
    registry.set_serve_slo(30.0, None, 0.0)  # empty window: burn only
    parsed = slo_mod.parse_serve_slo_text(registry.render_prometheus())
    assert parsed[5.0]["p99_s"] == pytest.approx(0.042)
    assert parsed[5.0]["burn_rate"] == pytest.approx(1.25)
    assert "p99_s" not in parsed[30.0]  # no invented sample
    assert parsed[30.0]["burn_rate"] == 0.0
    # breached judges the FASTEST window by default, like the evaluator.
    assert slo_mod.breached_from_metrics_text(
        registry.render_prometheus(), max_burn_rate=1.0,
    ) is True
    assert slo_mod.breached_from_metrics_text(
        registry.render_prometheus(), max_burn_rate=1.0, window_s=30.0,
    ) is False
    assert slo_mod.breached_from_metrics_text(
        registry.render_prometheus(), max_burn_rate=2.0,
        p99_target_s=0.01,
    ) is True  # p99 target trips it even under budget
    assert slo_mod.breached_from_metrics_text("", 1.0) is False
