"""Watch loop semantics (ccmanager/manager.py watch_and_apply vs reference
main.py:600-684): initial apply, change detection, 410 resync, error cap,
readiness file."""

import threading

import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.kubeclient.api import KubeApiError, WatchEvent, node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    MODE_OFF,
    MODE_ON,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "tpu-node-0"


class ScriptedKube(FakeKube):
    """FakeKube whose watch stream is a script: each segment is either a list
    of WatchEvents or an exception to raise. When the script runs out the
    stop event fires, ending watch_and_apply deterministically."""

    def __init__(self):
        super().__init__()
        self.segments = []
        self.stop = threading.Event()

    def watch_nodes(self, name, resource_version=None, timeout_seconds=300):
        if not self.segments:
            self.stop.set()
            return iter(())
        seg = self.segments.pop(0)
        if callable(seg):
            seg = seg()  # side-effects mid-script (may raise)
        if isinstance(seg, Exception):
            raise seg
        return iter(seg)


def modified_event(labels, rv="100"):
    return WatchEvent(
        "MODIFIED",
        {"metadata": {"name": NODE, "labels": labels, "resourceVersion": rv}},
    )


def make_manager(kube, backend, **kw):
    kw.setdefault("evict_components", False)
    kw.setdefault("smoke_workload", "none")
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("reconnect_delay_s", 0.0)
    return CCManager(api=kube, backend=backend, node_name=NODE, **kw)


@pytest.fixture()
def kube(tmp_path):
    k = ScriptedKube()
    k.add_node(NODE)
    return k


def run_to_completion(mgr, kube):
    mgr.watch_and_apply(stop=kube.stop)


def test_initial_apply_uses_default(kube, fake_tpu, tmp_path):
    mgr = make_manager(
        kube, fake_tpu, default_mode=MODE_ON,
        readiness_file=str(tmp_path / "ready"),
    )
    run_to_completion(mgr, kube)
    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == MODE_ON
    assert (tmp_path / "ready").exists()  # reference main.py:612


def test_label_change_triggers_apply(kube, fake_tpu, tmp_path):
    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_OFF)
    kube.segments = [[modified_event({CC_MODE_LABEL: MODE_ON})]]
    mgr = make_manager(kube, fake_tpu, readiness_file=str(tmp_path / "r"))
    run_to_completion(mgr, kube)
    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == MODE_ON


def test_unchanged_label_does_not_reapply(kube, tmp_path):
    backend = FakeTpuBackend(initial_mode=MODE_ON)
    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
    kube.segments = [
        [modified_event({CC_MODE_LABEL: MODE_ON, "unrelated": "edit"})],
    ]
    mgr = make_manager(kube, backend, readiness_file=str(tmp_path / "r"))
    run_to_completion(mgr, kube)
    # Exactly one discover from the initial apply; the unrelated label edit
    # must not trigger a second reconcile (reference main.py:646-657).
    assert [op for op, _ in backend.op_log].count("discover") == 1


def test_bookmark_tracks_rv_without_reconciling(kube, tmp_path):
    """BOOKMARK events carry only metadata.resourceVersion — no labels.
    They must advance the tracked rv (their whole purpose: quiet nodes
    stop 410-expiring) and must NOT be misread as 'desired label absent',
    which would fire a spurious reconcile to the default mode."""
    backend = FakeTpuBackend(initial_mode=MODE_ON)
    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
    seen_rvs = []
    real_watch = kube.watch_nodes

    def recording_watch(name, resource_version=None, timeout_seconds=300):
        seen_rvs.append(resource_version)
        return real_watch(name, resource_version, timeout_seconds)

    kube.watch_nodes = recording_watch
    kube.segments = [
        [WatchEvent(
            "BOOKMARK",
            {"metadata": {"name": NODE, "resourceVersion": "bm-777"}},
        )],
        [],  # one more connect so the bookmark rv is observable
    ]
    mgr = make_manager(kube, backend, readiness_file=str(tmp_path / "r"))
    run_to_completion(mgr, kube)
    # No second reconcile: the bookmark's empty labels were not misread.
    assert [op for op, _ in backend.op_log].count("discover") == 1
    # The reconnect after the bookmark used the bookmark's rv.
    assert seen_rvs[-1] == "bm-777"


def test_410_resyncs_via_get(kube, fake_tpu, tmp_path):
    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_OFF)

    def break_watch():
        # The desired mode changes "while the watch was broken" — only the
        # resync re-GET (reference main.py:670-682) can observe it.
        kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
        return KubeApiError(410, "gone")

    kube.segments = [break_watch]
    mgr = make_manager(kube, fake_tpu, readiness_file=str(tmp_path / "r"))
    run_to_completion(mgr, kube)
    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == MODE_ON


def test_error_event_410_resyncs(kube, fake_tpu, tmp_path):
    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_OFF)

    def label_change_then_error_event():
        kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
        return [WatchEvent("ERROR", {"code": 410, "message": "too old"})]

    kube.segments = [label_change_then_error_event]
    mgr = make_manager(kube, fake_tpu, readiness_file=str(tmp_path / "r"))
    run_to_completion(mgr, kube)
    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == MODE_ON


def test_consecutive_error_cap_is_fatal(kube, fake_tpu, tmp_path):
    kube.segments = [KubeApiError(None, "boom")] * 3
    mgr = make_manager(
        kube, fake_tpu, max_watch_errors=3, readiness_file=str(tmp_path / "r")
    )
    # Reference main.py:661-668: cap exhaustion raises; pod restart recovers.
    with pytest.raises(RuntimeError):
        run_to_completion(mgr, kube)


def test_error_counter_resets_on_success(kube, fake_tpu, tmp_path):
    # Two errors, a good event, two more errors: never hits cap=3
    # (documented reference quirk, SURVEY.md §8.6).
    kube.segments = [
        KubeApiError(None, "e1"),
        KubeApiError(None, "e2"),
        [modified_event({CC_MODE_LABEL: MODE_OFF})],
        KubeApiError(None, "e3"),
        KubeApiError(None, "e4"),
    ]
    mgr = make_manager(
        kube, fake_tpu, max_watch_errors=3, readiness_file=str(tmp_path / "r")
    )
    run_to_completion(mgr, kube)  # completes without RuntimeError


def test_error_event_cap_is_fatal(kube, fake_tpu, tmp_path):
    kube.segments = [[WatchEvent("ERROR", {"code": 500})] for _ in range(3)]
    mgr = make_manager(
        kube, fake_tpu, max_watch_errors=3, readiness_file=str(tmp_path / "r")
    )
    with pytest.raises(RuntimeError):
        run_to_completion(mgr, kube)


def test_graceful_stop_removes_readiness_file(kube, fake_tpu, tmp_path):
    """A stop-event shutdown withdraws the readiness signal in-process —
    the counterpart of the preStop /bin/rm hook for paths where the hook
    doesn't run."""
    mgr = make_manager(kube, fake_tpu, readiness_file=str(tmp_path / "r"))
    mgr.run(kube.stop)  # ScriptedKube sets stop when its script runs out
    assert not (tmp_path / "r").exists()


def test_failed_reconcile_retries_without_label_change(kube, fake_tpu, tmp_path):
    """A transient device fault must converge via the backoff retry, with
    NO label edit (VERDICT r2 item 6; the reference leaves the node
    'failed' until the label is touched again)."""
    import time

    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
    fake_tpu.fail_next("reset")  # first apply fails transiently

    def idle_past_backoff():
        # cclint: test-sleep-ok(simulated idle watch-stream segment outlasting the backoff)
        time.sleep(0.08)
        return []

    kube.segments = [idle_past_backoff, idle_past_backoff]
    mgr = make_manager(
        kube, fake_tpu,
        readiness_file=str(tmp_path / "r"),
        retry_backoff_s=0.05,
        retry_backoff_max_s=0.2,
    )
    run_to_completion(mgr, kube)
    # Converged to 'on' with zero desired-label edits after the failure.
    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == MODE_ON
    ops = [op for op, _ in fake_tpu.op_log]
    # Two applies: the failed one (its reset raised before logging) and the
    # successful retry.
    assert ops.count("stage") == 2
    assert ops.count("reset") == 1


def test_stable_misconfiguration_retries_only_at_slow_cadence(kube, tmp_path):
    """A ModeUnsupported failure skips the fast doubling ladder: it is
    retried only at retry_backoff_max_s (so a later hardware/pool fix still
    converges) — NOT every few seconds like a transient fault."""
    import time

    backend = FakeTpuBackend(slice_cc_supported=[True, True, True, False])
    kube.set_node_label(NODE, CC_MODE_LABEL, "slice")

    def idle():
        # cclint: test-sleep-ok(simulated idle watch-stream segment)
        time.sleep(0.08)
        return []

    kube.segments = [idle, idle, idle]
    mgr = make_manager(
        kube, backend,
        readiness_file=str(tmp_path / "r"),
        retry_backoff_s=0.02,   # fast cadence: would fire every window
        retry_backoff_max_s=30,  # slow cadence: far beyond the test run
    )
    run_to_completion(mgr, kube)
    from tpu_cc_manager.labels import STATE_FAILED

    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == STATE_FAILED
    # Exactly one reconcile attempt (the initial apply): the fast ladder
    # never fired despite several idle watch windows past 0.02s.
    assert [op for op, _ in backend.op_log].count("discover") == 1


def test_invalid_mode_reports_failed_with_reason(kube, fake_tpu, tmp_path):
    """A typo'd desired label is surfaced as failed + reason (the reference
    refuses silently, leaving no outward signal)."""
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL, STATE_FAILED

    kube.set_node_label(NODE, CC_MODE_LABEL, "bogus")
    mgr = make_manager(kube, fake_tpu, readiness_file=str(tmp_path / "r"))
    run_to_completion(mgr, kube)
    labels = node_labels(kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == STATE_FAILED
    assert labels[CC_FAILED_REASON_LABEL] == "invalid-mode"


def test_retry_backoff_disabled_keeps_reference_behavior(kube, fake_tpu, tmp_path):
    import time

    kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
    fake_tpu.fail_next("reset")

    def idle():
        # cclint: test-sleep-ok(simulated idle watch-stream segment)
        time.sleep(0.05)
        return []

    kube.segments = [idle, idle]
    mgr = make_manager(
        kube, fake_tpu,
        readiness_file=str(tmp_path / "r"),
        retry_backoff_s=0,  # disabled: reference parity
    )
    run_to_completion(mgr, kube)
    from tpu_cc_manager.labels import STATE_FAILED

    assert node_labels(kube.get_node(NODE))[CC_MODE_STATE_LABEL] == STATE_FAILED
    # One apply only (its reset raised before logging); no retry.
    assert [op for op, _ in fake_tpu.op_log].count("stage") == 1
    assert [op for op, _ in fake_tpu.op_log].count("reset") == 0
