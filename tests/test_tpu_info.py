"""TPU generation detection / peak-FLOPs table (utils/tpu_info.py)."""

import pytest

from tpu_cc_manager.utils import tpu_info


@pytest.mark.parametrize(
    ("raw", "want"),
    [
        ("v5e", "v5e"),
        ("v5litepod", "v5e"),
        ("v5lite", "v5e"),
        ("TPU v5 lite", "v5e"),
        ("TPU v5p", "v5p"),
        ("v5p", "v5p"),
        ("v4", "v4"),
        ("v6e", "v6e"),
        ("TPU v6 lite", "v6e"),
        ("v6lite", "v6e"),
        ("cpu", None),
        ("", None),
    ],
)
def test_normalize(raw, want):
    assert tpu_info._normalize(raw) == want


def test_env_override_wins(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
    assert tpu_info.tpu_generation() == "v5p"
    assert tpu_info.peak_flops_per_chip() == 459.0e12


def test_accelerator_type_env(monkeypatch):
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    assert tpu_info.tpu_generation() == "v5e"
    assert tpu_info.peak_flops_per_chip() == 197.0e12


def test_unknown_falls_back_conservative(monkeypatch):
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "wat-9000")
    assert tpu_info.tpu_generation() is None
    assert tpu_info.peak_flops_per_chip() == 197.0e12
