"""The in-memory apiserver fake itself (kubeclient/fake.py)."""

import pytest

from tpu_cc_manager.kubeclient.api import KubeApiError, node_labels


def test_node_crud_and_labels(fake_kube):
    fake_kube.add_node("n1", {"a": "1"})
    node = fake_kube.get_node("n1")
    assert node_labels(node) == {"a": "1"}
    fake_kube.patch_node_labels("n1", {"b": "2", "a": None})
    assert node_labels(fake_kube.get_node("n1")) == {"b": "2"}
    with pytest.raises(KubeApiError) as exc:
        fake_kube.get_node("missing")
    assert exc.value.status == 404


def test_pod_selectors(fake_kube):
    fake_kube.add_pod("ns", "p1", "n1", labels={"app": "x"})
    fake_kube.add_pod("ns", "p2", "n2", labels={"app": "x"})
    fake_kube.add_pod("ns", "p3", "n1", labels={"app": "y"})
    pods = fake_kube.list_pods("ns", label_selector="app=x", field_selector="spec.nodeName=n1")
    assert [p["metadata"]["name"] for p in pods] == ["p1"]
    assert len(fake_kube.list_pods("ns", label_selector="app=x")) == 2
    assert fake_kube.list_pods("other") == []


def test_node_label_selector(fake_kube):
    fake_kube.add_node("n1", {"pool": "tpu"})
    fake_kube.add_node("n2", {"pool": "cpu"})
    assert len(fake_kube.list_nodes("pool=tpu")) == 1
    assert len(fake_kube.list_nodes("pool")) == 2
    assert len(fake_kube.list_nodes()) == 2


def test_watch_sees_patches(fake_kube):
    fake_kube.add_node("n1")
    rv = fake_kube.get_node("n1")["metadata"]["resourceVersion"]
    fake_kube.patch_node_labels("n1", {"k": "v"})
    events = list(fake_kube.watch_nodes("n1", rv, timeout_seconds=1))
    assert len(events) == 1
    assert events[0].type == "MODIFIED"
    assert node_labels(events[0].object) == {"k": "v"}


def test_watch_410_after_compaction(fake_kube):
    fake_kube.add_node("n1")
    rv = fake_kube.get_node("n1")["metadata"]["resourceVersion"]
    fake_kube.patch_node_labels("n1", {"k": "v"})
    fake_kube.patch_node_labels("n1", {"k": "v2"})
    fake_kube.compact()
    with pytest.raises(KubeApiError) as exc:
        list(fake_kube.watch_nodes("n1", rv, timeout_seconds=1))
    assert exc.value.status == 410


def test_watch_fault_injection(fake_kube):
    fake_kube.add_node("n1")
    fake_kube.inject_watch_fault(KubeApiError(None, "boom"))
    with pytest.raises(KubeApiError):
        list(fake_kube.watch_nodes("n1", None, timeout_seconds=1))
    # Next watch works again (rv=None replays from the beginning: ADDED).
    events = list(fake_kube.watch_nodes("n1", None, timeout_seconds=0))
    assert [e.type for e in events] == ["ADDED"]


def test_patch_reactor_fires(fake_kube):
    fake_kube.add_node("n1")
    seen = []
    fake_kube.add_patch_reactor(lambda name, node: seen.append(name))
    fake_kube.patch_node_labels("n1", {"x": "1"})
    assert seen == ["n1"]


def test_lease_crud_and_optimistic_concurrency(fake_kube):
    """The fake's Lease verbs carry honest apiserver semantics: create
    conflicts on an existing name, update is a resourceVersion CAS (409
    on mismatch — the hinge the rollout fencing token hangs on)."""
    lease = fake_kube.create_lease("ns", "l1", {"holderIdentity": "a"})
    assert lease["spec"]["holderIdentity"] == "a"
    with pytest.raises(KubeApiError) as exc:
        fake_kube.create_lease("ns", "l1", {"holderIdentity": "b"})
    assert exc.value.status == 409

    fresh = fake_kube.get_lease("ns", "l1")
    stale = dict(fresh, metadata=dict(fresh["metadata"]))
    fresh["spec"] = {"holderIdentity": "a2"}
    updated = fake_kube.update_lease("ns", "l1", fresh)
    assert updated["spec"]["holderIdentity"] == "a2"
    # The loser of the race (stale resourceVersion) must get 409, never
    # last-write-wins.
    stale["spec"] = {"holderIdentity": "b"}
    with pytest.raises(KubeApiError) as exc:
        fake_kube.update_lease("ns", "l1", stale)
    assert exc.value.status == 409
    assert fake_kube.get_lease("ns", "l1")["spec"]["holderIdentity"] == "a2"

    fake_kube.delete_lease("ns", "l1")
    with pytest.raises(KubeApiError) as exc:
        fake_kube.get_lease("ns", "l1")
    assert exc.value.status == 404
