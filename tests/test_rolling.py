"""Rolling pool reconfiguration (ccmanager/rolling.py)."""

import json
import threading
import time

import pytest

from tpu_cc_manager.ccmanager.rolling import (
    SLICE_ID_LABEL,
    RollingReconfigurator,
    plan_groups,
)
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    STATE_FAILED,
)
from tpu_cc_manager.utils import retry as retry_mod

POOL = "pool=tpu"


def add_pool(fake_kube, n=4, slice_map=None):
    for i in range(n):
        labels = {"pool": "tpu"}
        if slice_map and i in slice_map:
            labels[SLICE_ID_LABEL] = slice_map[i]
        fake_kube.add_node(f"node-{i}", labels)


def agent_simulator(fake_kube, fail_nodes=(), delay_patches=1):
    """Emulate per-node agents: when the desired label lands, converge the
    state label (or 'failed' for nodes in fail_nodes)."""

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired:
            target = STATE_FAILED if name in fail_nodes else desired
            # Converge asynchronously, as a real agent would.
            t = threading.Timer(
                0.05, lambda: fake_kube.set_node_label(name, CC_MODE_STATE_LABEL, target)
            )
            t.daemon = True
            t.start()

    fake_kube.add_patch_reactor(reactor)


def make_roller(fake_kube, **kw):
    kw.setdefault("node_timeout_s", 5)
    kw.setdefault("poll_interval_s", 0.02)
    return RollingReconfigurator(fake_kube, POOL, **kw)


def test_plan_groups_by_slice(fake_kube):
    add_pool(fake_kube, 4, slice_map={0: "s1", 1: "s1", 2: "s2"})
    groups = dict(plan_groups(fake_kube, POOL))
    assert groups["s1"] == ("node-0", "node-1")
    assert groups["s2"] == ("node-2",)
    assert groups["node/node-3"] == ("node-3",)


def test_rollout_rejects_invalid_mode(fake_kube):
    """A typo'd mode must fail fast, before any node's desired label is
    written (otherwise the pool hangs for node_timeout_s per group)."""
    import pytest

    from tpu_cc_manager.labels import CC_MODE_LABEL
    from tpu_cc_manager.kubeclient.api import node_labels

    add_pool(fake_kube, 2)
    roller = RollingReconfigurator(fake_kube, POOL)
    with pytest.raises(ValueError, match="invalid CC mode"):
        roller.rollout("onn")
    for node in fake_kube.list_nodes(POOL):
        assert CC_MODE_LABEL not in node_labels(node)


def test_rollout_accepts_ppcie_alias(fake_kube):
    """The deprecated reference alias canonicalizes instead of erroring."""
    add_pool(fake_kube, 1)
    roller = RollingReconfigurator(
        fake_kube, POOL, node_timeout_s=0.5, poll_interval_s=0.01
    )
    result = roller.rollout("ppcie")  # -> slice; no agents run, so timeout
    assert result.mode == "slice"


def test_rollout_converges_all_nodes(fake_kube):
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube)
    result = make_roller(fake_kube).rollout("on")
    assert result.ok is True
    assert len(result.groups) == 3
    for i in range(3):
        labels = node_labels(fake_kube.get_node(f"node-{i}"))
        assert labels[CC_MODE_LABEL] == "on"
        assert labels[CC_MODE_STATE_LABEL] == "on"
    assert result.summary()["nodes"] == 3


def test_rollout_is_strictly_rolling(fake_kube):
    """With max_unavailable=1, node N+1 must not receive its desired label
    until node N converged."""
    add_pool(fake_kube, 3)
    order = []

    def tracking_reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired:
            # At the moment a node is asked to reconfigure, every previously
            # asked node must already have converged.
            for other in order:
                other_state = node_labels(fake_kube.get_node(other)).get(
                    CC_MODE_STATE_LABEL
                )
                assert other_state == desired, (
                    f"{name} asked while {other} still {other_state}"
                )
            order.append(name)
            fake_kube.set_node_label(name, CC_MODE_STATE_LABEL, desired)

    fake_kube.add_patch_reactor(tracking_reactor)
    result = make_roller(fake_kube, max_unavailable=1).rollout("on")
    assert result.ok and len(order) == 3


def test_rollout_halts_on_failure(fake_kube):
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube, fail_nodes={"node-1"})
    result = make_roller(fake_kube).rollout("on")
    assert result.ok is False
    # node-2 was never asked (halt before its group).
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("node-2"))


def test_rollout_continue_on_failure(fake_kube):
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube, fail_nodes={"node-1"})
    result = make_roller(fake_kube, continue_on_failure=True).rollout("on")
    assert result.ok is False
    assert len(result.groups) == 3
    assert node_labels(fake_kube.get_node("node-2"))[CC_MODE_STATE_LABEL] == "on"


def test_multihost_slice_bounced_together(fake_kube):
    """Both hosts of a slice get their label in the same window."""
    add_pool(fake_kube, 4, slice_map={0: "s1", 1: "s1", 2: "s2", 3: "s2"})
    agent_simulator(fake_kube)
    result = make_roller(fake_kube).rollout("slice")
    assert result.ok
    assert [g.group for g in result.groups] == ["s1", "s2"]
    assert result.groups[0].nodes == ("node-0", "node-1")


def test_window_fully_awaited_on_failure(fake_kube):
    """With max_unavailable=2 and one group failing, the other group in the
    same window already got its label and must still be awaited/reported."""
    add_pool(fake_kube, 2)
    agent_simulator(fake_kube, fail_nodes={"node-0"})
    result = make_roller(fake_kube, max_unavailable=2).rollout("on")
    assert result.ok is False
    assert len(result.groups) == 2  # both window members reported
    states = {g.nodes[0]: g.states[g.nodes[0]] for g in result.groups}
    assert states["node-0"] == STATE_FAILED
    assert states["node-1"] == "on"


def test_wall_time_uses_windows_not_group_sums(fake_kube):
    add_pool(fake_kube, 4)
    agent_simulator(fake_kube)
    result = make_roller(fake_kube, max_unavailable=2).rollout("on")
    assert result.ok
    assert len(result.window_seconds) == 2  # 4 groups / window of 2
    # Total is the window sum, strictly less than the overlapping group sum.
    assert result.seconds <= sum(g.seconds for g in result.groups) + 1e-6


def test_rollout_timeout_reported(fake_kube):
    add_pool(fake_kube, 1)  # no agent simulator: nothing converges
    result = make_roller(fake_kube, node_timeout_s=0.1).rollout("on")
    assert result.ok is False
    assert result.groups[0].states["node-0"] == "timeout"


def test_rollback_on_failure_reverts_converged_groups(fake_kube):
    """Group 0/1 converge to 'on', group 2 fails -> halt + groups 0/1
    reverted to their prior desired mode ('off'); the failed group is left
    for the operator."""
    add_pool(fake_kube, 3)
    for i in range(3):
        fake_kube.set_node_label(f"node-{i}", CC_MODE_LABEL, "off")
        fake_kube.set_node_label(f"node-{i}", CC_MODE_STATE_LABEL, "off")
    agent_simulator(fake_kube, fail_nodes=("node-2",))
    result = make_roller(fake_kube, rollback_on_failure=True).rollout("on")
    assert result.ok is False
    assert [g.group for g in result.rolled_back] == ["node/node-1", "node/node-0"]
    for g in result.rolled_back:
        assert g.ok, g.states
    for i in (0, 1):
        labels = node_labels(fake_kube.get_node(f"node-{i}"))
        assert labels[CC_MODE_LABEL] == "off"
        assert labels[CC_MODE_STATE_LABEL] == "off"
    # The failed node keeps its target desired label and failed state.
    labels = node_labels(fake_kube.get_node("node-2"))
    assert labels[CC_MODE_LABEL] == "on"
    assert labels[CC_MODE_STATE_LABEL] == STATE_FAILED


def test_rollback_removes_previously_absent_label(fake_kube):
    """Nodes that had no desired label get it removed on rollback (the
    default mode applies again) and are not awaited."""
    add_pool(fake_kube, 2)
    agent_simulator(fake_kube, fail_nodes=("node-1",))
    result = make_roller(fake_kube, rollback_on_failure=True).rollout("on")
    assert result.ok is False
    assert len(result.rolled_back) == 1
    assert result.rolled_back[0].states == {"node-0": "reverted-unawaited"}
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("node-0"))
    # The summary must not report an unawaited revert success-shaped.
    assert result.summary()["rolled_back"] == {"node/node-0": "unverified"}


def test_no_rollback_by_default(fake_kube):
    add_pool(fake_kube, 2)
    agent_simulator(fake_kube, fail_nodes=("node-1",))
    result = make_roller(fake_kube).rollout("on")
    assert result.ok is False
    assert result.rolled_back == []
    assert node_labels(fake_kube.get_node("node-0"))[CC_MODE_LABEL] == "on"


def test_rollback_and_continue_are_mutually_exclusive(fake_kube):
    with pytest.raises(ValueError):
        make_roller(fake_kube, continue_on_failure=True, rollback_on_failure=True)


def test_summary_reports_failed_rollback(fake_kube):
    """A revert that times out must read as 'failed', not silently OK."""
    add_pool(fake_kube, 2)
    for i in range(2):
        fake_kube.set_node_label(f"node-{i}", CC_MODE_LABEL, "off")
        fake_kube.set_node_label(f"node-{i}", CC_MODE_STATE_LABEL, "off")

    # Agent that converges forward transitions but wedges on the revert.
    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired == "on" and state != desired:
            target = STATE_FAILED if name == "node-1" else "on"
            t = threading.Timer(
                0.05,
                lambda: fake_kube.set_node_label(name, CC_MODE_STATE_LABEL, target),
            )
            t.daemon = True
            t.start()

    fake_kube.add_patch_reactor(reactor)
    result = make_roller(
        fake_kube, rollback_on_failure=True, node_timeout_s=0.3
    ).rollout("on")
    assert result.ok is False
    assert result.summary()["rolled_back"] == {"node/node-0": "failed"}


def test_await_polls_with_one_listing_not_per_node_gets(fake_kube):
    """Pool-scale polling (VERDICT r3 weak #7): awaiting a group costs one
    selector listing per poll, not one GET per node per poll."""
    add_pool(fake_kube, 4)
    agent_simulator(fake_kube)
    gets = []
    real_get = fake_kube.get_node
    fake_kube.get_node = lambda name: (gets.append(name), real_get(name))[1]
    result = make_roller(fake_kube, max_unavailable=4).rollout("on")
    assert result.ok is True
    assert gets == []  # every state read rode a list_nodes call


def test_stale_failed_with_dead_agent_fails_fast(fake_kube):
    """A node carrying a leftover 'failed' label whose agent is DOWN must
    fail the group after the bounded stale-failed grace, not consume the
    full node timeout (ADVICE r4 #5)."""
    import time as _time

    fake_kube.add_node("node-0", {"pool": "tpu",
                                  CC_MODE_STATE_LABEL: STATE_FAILED})
    # No agent reactor at all: nothing will ever change the state label.
    roller = make_roller(fake_kube, node_timeout_s=30)
    t0 = _time.monotonic()
    result = roller.rollout("on")
    elapsed = _time.monotonic() - t0
    assert result.ok is False
    assert result.groups[0].states["node-0"] == STATE_FAILED  # not "timeout"
    # Grace is a few polls (5 × 0.02 s); far under the 30 s node timeout.
    assert elapsed < 5


def test_stale_failed_still_gets_agent_retry_grace(fake_kube):
    """The original stale-failed behavior survives the grace cap: a LIVE
    agent that reacts within the grace gets its retry and converges."""
    fake_kube.add_node("node-0", {"pool": "tpu",
                                  CC_MODE_STATE_LABEL: STATE_FAILED})
    agent_simulator(fake_kube)  # healthy agent: converges on desired
    result = make_roller(fake_kube).rollout("on")
    assert result.ok is True
    assert result.groups[0].states["node-0"] == "on"


def deleted_agent_simulator(fake_kube):
    """node-0's agent converges normally; node-1 has NO agent (it is
    being reclaimed) and the autoscaler deletes its Node object shortly
    after its desired label lands."""

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if not desired or state == desired:
            return
        if name == "node-1":
            t = threading.Timer(0.05, lambda: fake_kube.delete_node("node-1"))
        else:
            t = threading.Timer(
                0.05,
                lambda: fake_kube.set_node_label(
                    name, CC_MODE_STATE_LABEL, desired
                ),
            )
        t.daemon = True
        t.start()

    fake_kube.add_patch_reactor(reactor)


def test_deleted_node_resolves_its_slot_immediately(fake_kube):
    """A node whose Node object vanishes mid-window (autoscaler
    scale-down) must resolve as 'deleted' as soon as the deletion is
    observed — not sit as a phantom timeout-in-progress until the window
    deadline — and must not fail the group."""
    import time as _time

    add_pool(fake_kube, 2)
    deleted_agent_simulator(fake_kube)
    roller = make_roller(fake_kube, max_unavailable=2, node_timeout_s=30)
    t0 = _time.monotonic()
    result = roller.rollout("on")
    elapsed = _time.monotonic() - t0
    assert elapsed < 10, "deleted node consumed the window deadline"
    by_group = {g.group: g for g in result.groups}
    assert by_group["node/node-1"].states["node-1"] == "deleted"
    assert by_group["node/node-1"].ok is True
    assert by_group["node/node-0"].states["node-0"] == "on"
    assert result.ok is True


def test_deleted_node_resolves_under_informer(fake_kube):
    """Same scale-down, informer-backed: the DELETED watch event wakes the
    await and resolves the slot without a fallback GET storm."""
    from tpu_cc_manager.ccmanager.informer import NodeInformer

    add_pool(fake_kube, 2)
    deleted_agent_simulator(fake_kube)
    informer = NodeInformer(fake_kube, POOL).start()
    try:
        result = make_roller(
            fake_kube, max_unavailable=2, node_timeout_s=30,
            informer=informer,
        ).rollout("on")
    finally:
        informer.stop()
    assert result.ok is True
    by_group = {g.group: g for g in result.groups}
    assert by_group["node/node-1"].states["node-1"] == "deleted"


def test_scale_down_during_sharded_rollout_spends_no_budget(fake_kube):
    """Chaos acceptance (tentpole b): an autoscaler scale-down DURING a
    sharded, lease-fenced rollout retires the node with ZERO
    failure-budget spend — with failure_budget=0, any charge would halt
    the rollout, so ok=True proves the deleted node was never charged."""
    from tpu_cc_manager.ccmanager import rollout_state

    add_pool(
        fake_kube, 4,
        slice_map={i: f"s{i}" for i in range(4)},
    )
    for i in range(4):
        fake_kube.set_node_label(
            f"node-{i}", "topology.kubernetes.io/zone", f"zone-{i % 2}"
        )
    deleted_agent_simulator(fake_kube)
    lease = rollout_state.RolloutLease(fake_kube, holder="t-scale-down")
    assert lease.acquire() is None
    roller = make_roller(
        fake_kube, max_unavailable=2, node_timeout_s=30,
        wave_shards=2, failure_budget=0, lease=lease,
    )
    result = roller.rollout("on")
    lease.release(clear_record=result.ok)
    assert result.ok is True
    assert result.halted_reason is None
    assert result.retired_deleted == ["node-1"]
    for i in (0, 2, 3):
        assert node_labels(fake_kube.get_node(f"node-{i}"))[
            CC_MODE_STATE_LABEL
        ] == "on"


def test_scale_up_node_is_adopted_into_trailing_wave(fake_kube):
    """Chaos acceptance (tentpole b): a node the autoscaler creates
    mid-rollout that matches the selector is adopted into a trailing
    wave and converges to the desired mode + generation label."""
    from tpu_cc_manager.ccmanager import rollout_state

    add_pool(fake_kube, 2)
    agent_simulator(fake_kube)
    created = threading.Event()

    def scale_up(name, node):
        # The autoscaler reacts to the first desired-mode write: a new
        # node joins the pool while the rollout is mid-window.
        if not created.is_set() and node_labels(node).get(CC_MODE_LABEL):
            created.set()
            fake_kube.add_node("node-9", {"pool": "tpu"})

    fake_kube.add_patch_reactor(scale_up)
    lease = rollout_state.RolloutLease(fake_kube, holder="t-scale-up")
    assert lease.acquire() is None
    result = make_roller(fake_kube, lease=lease).rollout("on")
    lease.release(clear_record=result.ok)
    assert result.ok is True
    assert result.adopted == ["node-9"]
    labels = node_labels(fake_kube.get_node("node-9"))
    assert labels[CC_MODE_LABEL] == "on"
    assert labels[CC_MODE_STATE_LABEL] == "on"
    assert labels[rollout_state.ROLLOUT_GEN_LABEL] == str(result.generation)


def test_adoption_disabled_leaves_new_node_alone(fake_kube):
    add_pool(fake_kube, 1)
    agent_simulator(fake_kube)
    seen = threading.Event()

    def scale_up(name, node):
        if not seen.is_set() and node_labels(node).get(CC_MODE_LABEL):
            seen.set()
            fake_kube.add_node("node-9", {"pool": "tpu"})

    fake_kube.add_patch_reactor(scale_up)
    result = make_roller(fake_kube, adopt_new_nodes=False).rollout("on")
    assert result.ok is True
    assert result.adopted == []
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("node-9"))


def surge_taints_of(fake_kube, name):
    from tpu_cc_manager.ccmanager.rolling import SURGE_TAINT_KEY

    node = fake_kube.get_node(name)
    return [
        t for t in (node.get("spec") or {}).get("taints") or []
        if t.get("key") == SURGE_TAINT_KEY
    ]


def test_surge_rollout_flips_spares_first_and_reclaims(fake_kube):
    """Tentpole (c): --surge N flips N spare nodes FIRST behind the
    surge NoSchedule taint, reclaims them on convergence, and the
    measured (non-surge) pool unavailability never exceeds
    max_unavailable."""
    add_pool(fake_kube, 4)
    tainted_during_flip = {}

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired:
            # Snapshot the taint the moment the flip is requested: surge
            # spares must be unschedulable-for-workloads for their whole
            # flip window.
            tainted_during_flip[name] = bool(surge_taints_of(fake_kube, name))
            t = threading.Timer(
                0.05,
                lambda: fake_kube.set_node_label(
                    name, CC_MODE_STATE_LABEL, desired
                ),
            )
            t.daemon = True
            t.start()

    fake_kube.add_patch_reactor(reactor)
    result = make_roller(fake_kube, max_unavailable=1, surge=2).rollout("on")
    assert result.ok is True
    assert result.surged == ["node-0", "node-1"]
    # The spares flipped behind the taint; the regular nodes did not.
    assert tainted_during_flip == {
        "node-0": True, "node-1": True, "node-2": False, "node-3": False,
    }
    # Reclaimed: no surge taint survives the rollout.
    for i in range(4):
        assert surge_taints_of(fake_kube, f"node-{i}") == []
        assert node_labels(fake_kube.get_node(f"node-{i}"))[
            CC_MODE_STATE_LABEL
        ] == "on"
    # Measured serving-capacity disruption: the 2 concurrent surge spares
    # never count (they are behind the taint); the rolling remainder
    # stays within max_unavailable.
    assert result.max_unavailable_observed <= 1
    assert result.summary()["surged"] == ["node-0", "node-1"]


def test_surge_failed_spare_keeps_taint_and_halts(fake_kube):
    """A spare that cannot flip keeps its NoSchedule taint (a node that
    failed its transition must not receive workloads) and halts the
    rollout before the regular waves touch serving capacity."""
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube, fail_nodes={"node-0"})
    roller = make_roller(
        fake_kube, max_unavailable=1, surge=1, node_timeout_s=5,
    )
    result = roller.rollout("on")
    assert result.ok is False
    assert surge_taints_of(fake_kube, "node-0"), "failed spare lost its taint"
    # The regular groups were never attempted.
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("node-2"))


def test_surge_failed_spare_fails_verdict_even_under_continue(fake_kube):
    """continue_on_failure presses past a failed spare, but the rollout's
    verdict must still be False — a node sits failed (and tainted)
    behind it."""
    add_pool(fake_kube, 3)
    agent_simulator(fake_kube, fail_nodes={"node-0"})
    result = make_roller(
        fake_kube, max_unavailable=1, surge=1, node_timeout_s=5,
        continue_on_failure=True,
    ).rollout("on")
    assert result.ok is False
    assert surge_taints_of(fake_kube, "node-0")
    # The regular groups were still driven.
    assert node_labels(fake_kube.get_node("node-2"))[
        CC_MODE_STATE_LABEL
    ] == "on"


def test_resume_never_resurges_and_reclaims_stale_taints(fake_kube):
    """A resumed surge rollout must NOT greedily re-pick 'spares' from
    what are now serving nodes (a NoSchedule taint evicts nothing, so
    that would silently exceed max_unavailable); surviving groups roll
    normally and a stale surge taint from the interrupted surge phase is
    reclaimed."""
    from tpu_cc_manager.ccmanager import rollout_state

    add_pool(fake_kube, 3)
    agent_simulator(fake_kube)
    # The dead orchestrator's leftovers: node-0 done (surged, converged),
    # node-1 crashed mid-surge with its taint still on.
    fake_kube.set_node_label("node-0", CC_MODE_LABEL, "on")
    fake_kube.set_node_label("node-0", CC_MODE_STATE_LABEL, "on")
    fake_kube.patch_node_taints(
        "node-1",
        [{"key": "cloud.google.com/tpu-cc.surge", "value": "true",
          "effect": "NoSchedule"}], [],
    )
    record = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[(f"node/node-{i}", (f"node-{i}",)) for i in range(3)],
        surge=2,
    )
    record.note_group(
        "node/node-0", ok=True, states={"node-0": "on"}, seconds=0.1,
    )
    # Round-trip through JSON: a surge record is format v3.
    assert json.loads(record.to_json())["version"] == 3
    record = rollout_state.RolloutRecord.from_json(record.to_json())
    assert record.surge == 2
    roller = make_roller(fake_kube, surge=record.surge, resume_record=record)
    result = roller.rollout("on")
    assert result.ok is True
    assert result.surged == []  # no re-surge on resume
    for i in range(3):
        assert surge_taints_of(fake_kube, f"node-{i}") == []
        assert node_labels(fake_kube.get_node(f"node-{i}"))[
            CC_MODE_STATE_LABEL
        ] == "on"


def test_surge_refuses_rollback_on_failure(fake_kube):
    """Same refusal as wave_shards: a surge halt would either have to
    revert tainted spares or silently skip the rollback the operator
    asked for — reject the combination up front."""
    add_pool(fake_kube, 2)
    with pytest.raises(ValueError, match="surge"):
        make_roller(fake_kube, surge=1, rollback_on_failure=True)


def test_rollback_on_failure_skips_adoption(fake_kube):
    """Adopted nodes have no prior desired mode to revert to, so a
    rollback-armed rollout leaves mid-rollout joiners to the NEXT
    rollout instead of flipping what it could never restore."""
    add_pool(fake_kube, 1)
    for i in range(1):
        fake_kube.set_node_label(f"node-{i}", CC_MODE_LABEL, "off")
        fake_kube.set_node_label(f"node-{i}", CC_MODE_STATE_LABEL, "off")
    agent_simulator(fake_kube)
    seen = threading.Event()

    def scale_up(name, node):
        if not seen.is_set() and node_labels(node).get(CC_MODE_LABEL) == "on":
            seen.set()
            fake_kube.add_node("node-9", {"pool": "tpu"})

    fake_kube.add_patch_reactor(scale_up)
    result = make_roller(fake_kube, rollback_on_failure=True).rollout("on")
    assert result.ok is True
    assert result.adopted == []
    assert CC_MODE_LABEL not in node_labels(fake_kube.get_node("node-9"))


def test_surge_larger_than_any_group_rolls_normally(fake_kube):
    """surge smaller than the smallest (multi-host) group: nothing fits
    the spare budget — the rollout degrades to a normal one instead of
    splitting a slice."""
    add_pool(fake_kube, 4, slice_map={0: "s1", 1: "s1", 2: "s1", 3: "s1"})
    agent_simulator(fake_kube)
    result = make_roller(fake_kube, surge=2).rollout("on")
    assert result.ok is True
    assert result.surged == []
    assert surge_taints_of(fake_kube, "node-0") == []


def test_interrupted_rollout_resumes_idempotently(fake_kube):
    """A re-run after a halt skips already-converged groups: no label
    rewrite, no second bounce (VERDICT r3 item 7)."""
    add_pool(fake_kube, 2)
    fails = {"node-1"}
    converge_counts = {"node-0": 0, "node-1": 0}
    in_flight = set()
    paused = threading.Event()  # set = agents stop scheduling reconciles

    def reactor(name, node):
        # Like the real agent: reconcile whenever desired != state (the
        # failed-reconcile backoff retry), one reconcile in flight at a
        # time.
        if paused.is_set():
            return
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired and name not in in_flight:
            in_flight.add(name)
            converge_counts[name] += 1

            def fire():
                target = STATE_FAILED if name in fails else desired
                in_flight.discard(name)
                fake_kube.set_node_label(name, CC_MODE_STATE_LABEL, target)

            t = threading.Timer(0.05, fire)
            t.daemon = True
            t.start()

    fake_kube.add_patch_reactor(reactor)

    first = make_roller(fake_kube).rollout("on")
    assert first.ok is False  # halted on node-1
    assert [g.ok for g in first.groups] == [True, False]

    # Quiesce node-1's failed-reconcile retry storm before "fixing" it:
    # otherwise the next retry tick (50 ms cadence) converges node-1 on
    # its own, racing the second rollout's planning — on a loaded box the
    # plan then sees node-1 already at `on` and skips it, which is not
    # what this test is about. Pausing the agents first makes the
    # re-drive deterministically the second rollout's doing.
    paused.set()
    retry_mod.poll_until(lambda: not in_flight, 5.0, 0.01)
    assert not in_flight

    # Operator fixes node-1; the re-run must not re-bounce node-0.
    fails.clear()
    paused.clear()
    second = make_roller(fake_kube).rollout("on")
    assert second.ok is True
    by_group = {g.group: g for g in second.groups}
    assert by_group["node/node-0"].skipped is True
    assert by_group["node/node-0"].seconds == 0.0
    assert by_group["node/node-1"].skipped is False
    # The decisive property: node-0 was reconciled exactly once across both
    # rollouts — the resume never re-bounced it. (node-1's count depends on
    # its retry cadence while failed; only its convergence matters.)
    assert converge_counts["node-0"] == 1
    assert converge_counts["node-1"] >= 2
    assert second.summary()["skipped_groups"] == 1
