"""Drain / re-admit against the fake apiserver (drain/evict.py)."""

import pytest

from tpu_cc_manager.drain import evict
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS, PAUSED_VALUE

NODE = "tpu-node-0"
NS = "tpu-operator"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


def operator_controller(fake_kube):
    """Emulate the operator: when a component label is paused, delete its pods
    (the external behavior the reference relies on, SURVEY.md §5)."""

    def reactor(name, node):
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if is_paused(node_labels(node).get(key)):
                fake_kube.delete_pods_matching(NS, f"app={app}")

    fake_kube.add_patch_reactor(reactor)


def test_evict_pauses_and_waits(fake_kube):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "dp-pod", NODE, labels={"app": DP_APP})
    operator_controller(fake_kube)

    original = evict.evict_components(fake_kube, NODE, NS, timeout_s=5, poll_interval_s=0.01)
    assert original == {DP_LABEL: "true"}
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[DP_LABEL] == PAUSED_VALUE
    assert fake_kube.list_pods(NS, label_selector=f"app={DP_APP}") == []


def test_readmit_restores_labels(fake_kube):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    operator_controller(fake_kube)
    original = evict.evict_components(fake_kube, NODE, NS, timeout_s=1, poll_interval_s=0.01)
    evict.readmit_components(fake_kube, NODE, original)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "true"


def test_custom_value_roundtrip(fake_kube):
    fake_kube.add_node(NODE, {DP_LABEL: "custom-flavor"})
    operator_controller(fake_kube)
    original = evict.evict_components(fake_kube, NODE, NS, timeout_s=1, poll_interval_s=0.01)
    assert is_paused(node_labels(fake_kube.get_node(NODE))[DP_LABEL])
    evict.readmit_components(fake_kube, NODE, original)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "custom-flavor"


def test_disabled_component_untouched(fake_kube):
    fake_kube.add_node(NODE, {DP_LABEL: "false"})
    original = evict.evict_components(fake_kube, NODE, NS, timeout_s=1, poll_interval_s=0.01)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "false"
    evict.readmit_components(fake_kube, NODE, original)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "false"


def test_timeout_proceeds_by_default(fake_kube):
    # No controller: the pod never goes away.
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "stuck", NODE, labels={"app": DP_APP})
    # Reference behavior: warn and continue (gpu_operator_eviction.py:205-207).
    evict.evict_components(fake_kube, NODE, NS, timeout_s=0.05, poll_interval_s=0.01)


def test_timeout_strict_raises(fake_kube):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "stuck", NODE, labels={"app": DP_APP})
    with pytest.raises(evict.EvictionTimeout):
        evict.evict_components(
            fake_kube, NODE, NS, timeout_s=0.05, poll_interval_s=0.01,
            proceed_on_timeout=False,
        )


def test_already_paused_labels_still_waited_on(fake_kube):
    """Crash recovery: a previous run paused the component and died; the
    retry must still wait for the component's pods to finish terminating
    even though there is nothing new to patch."""
    from tpu_cc_manager.labels import PAUSED_VALUE as PV

    fake_kube.add_node(NODE, {DP_LABEL: PV})
    fake_kube.add_pod(NS, "terminating", NODE, labels={"app": DP_APP})
    calls_before = fake_kube.list_pod_calls
    evict.evict_components(fake_kube, NODE, NS, timeout_s=0.05, poll_interval_s=0.01)
    # It polled (and timed out per the proceed-on-timeout default).
    assert fake_kube.list_pod_calls > calls_before


def test_readmit_after_crash_recovery_does_not_strand_paused(fake_kube):
    """If the remembered 'original' snapshot is itself a paused value (taken
    by a crash-recovery run), readmit must not write it back."""
    from tpu_cc_manager.labels import PAUSED_VALUE as PV

    fake_kube.add_node(NODE, {DP_LABEL: PV})
    original = evict.evict_components(
        fake_kube, NODE, NS, timeout_s=0.05, poll_interval_s=0.01
    )
    assert original == {DP_LABEL: PV}
    evict.readmit_components(fake_kube, NODE, original)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "true"


def test_readmit_respects_concurrent_user_disable(fake_kube):
    """A user disabling a component mid-drain wins over the unpause."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    operator_controller(fake_kube)
    original = evict.evict_components(fake_kube, NODE, NS, timeout_s=1, poll_interval_s=0.01)
    fake_kube.set_node_label(NODE, DP_LABEL, "false")  # concurrent user edit
    evict.readmit_components(fake_kube, NODE, original)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "false"


def test_overlong_custom_value_drains_and_restores_exactly(fake_kube):
    """A custom value too long to carry the paused suffix within the
    63-char label limit: the drain still proceeds (truncated-but-valid
    paused label; the suffix the operator reacts to is intact) and the
    re-admit restores the UNTRUNCATED original from the remembered
    pre-drain labels (drain/pause.py truncation contract)."""
    from tpu_cc_manager.drain.pause import _MAX_CUSTOM

    long_value = "a-very-long-custom-component-flavor-beyond-the-budget"
    assert len(long_value) > _MAX_CUSTOM  # would exceed 63 chars with suffix
    fake_kube.add_node(NODE, {DP_LABEL: long_value})
    operator_controller(fake_kube)
    original = evict.evict_components(
        fake_kube, NODE, NS, timeout_s=1, poll_interval_s=0.01
    )
    paused = node_labels(fake_kube.get_node(NODE))[DP_LABEL]
    assert is_paused(paused)
    assert len(paused) <= 63  # a real apiserver would accept the patch
    evict.readmit_components(fake_kube, NODE, original)
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == long_value
