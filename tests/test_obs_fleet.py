"""Fleet observability plane (tpu_cc_manager/obs/fleet.py).

The acceptance bars (ISSUE 16):

- merged histograms preserve bucket monotonicity and EXACT
  ``_sum``/``_count`` conservation; counters/gauges sum label-preserving;
  HELP/TYPE pairing survives federation (the merged exposition passes
  the same lint the per-agent render does);
- ``merge_p99`` (obs/slo.py) agrees with the pooled-sample percentile
  on seeded random shards;
- the gateway marks killed agents stale within 2 sweeps — listed in
  ``/fleetz``, excluded from the rollups — and catches a frozen
  ``snapshot_ts`` (a dead agent behind a replaying proxy);
- the capacity ledger excludes quarantined/offline/prestaging/saturated
  nodes from ``tpu_cc_fleet_headroom_nodes``;
- ``stitch_timelines`` merges N shard flight streams into one
  seq-consistent federated timeline (generation-then-timestamp order,
  cross-stream duplicates collapsed, torn tails tolerated) from which
  ``reconstruct`` reads exactly-once node outcomes across a kill.

The chaos-marked soak prints the FLEET_SUMMARY line
hack/chaos_soak.sh scrapes (the gateway keeps serving merged truth
while seeded chaos kills scraped agents).
"""

from __future__ import annotations

import json
import random
import threading
import urllib.request

import pytest

from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.faults.plan import OrchestratorKilled
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.lint import expo as expo_lint
from tpu_cc_manager.obs import fleet as fleet_mod
from tpu_cc_manager.obs import flight as flight_mod
from tpu_cc_manager.obs import slo as slo_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

SEED = 20260807


def seeded_registry(name: str, rng: random.Random) -> MetricsRegistry:
    reg = MetricsRegistry()
    for _ in range(rng.randint(2, 12)):
        reg.observe_serve_request(name, rng.uniform(0.005, 2.0))
    reg.set_serve_queue_depth(name, rng.randint(0, 5))
    reg.record_serve_outcome(name, "completed", rng.randint(1, 30))
    reg.set_serve_hbm_bw_util(name, rng.uniform(0.2, 0.8))
    return reg


# ---------------------------------------------------------------------------
# Merge correctness (the property-test satellite)
# ---------------------------------------------------------------------------


def test_merged_histograms_conserve_sum_count_and_stay_monotonic():
    rng = random.Random(SEED)
    observations: dict[str, list[float]] = {}
    scrapes: dict[str, str] = {}
    for a in range(4):
        reg = MetricsRegistry()
        # Two agents share node names (a restarted agent re-reporting)
        # so same-key summation is exercised, not just disjoint unions.
        for node in (f"n{a % 2}", f"n{a}-own"):
            vals = [rng.uniform(0.001, 40.0) for _ in range(rng.randint(1, 20))]
            observations.setdefault(node, []).extend(vals)
            for v in vals:
                reg.observe_serve_request(node, v)
        scrapes[f"agent-{a}"] = reg.render_prometheus()

    merged = fleet_mod.merge_expositions(scrapes)
    assert expo_lint.lint(merged) == []  # monotonic, +Inf, _count==+Inf

    parsed = fleet_mod.parse_exposition(merged)
    sums = {
        labels["node"]: value
        for labels, value in parsed.series_values(
            "tpu_cc_serve_request_seconds_sum"
        )
    }
    counts = {
        labels["node"]: value
        for labels, value in parsed.series_values(
            "tpu_cc_serve_request_seconds_count"
        )
    }
    assert set(sums) == set(observations)
    for node, vals in observations.items():
        assert counts[node] == len(vals)
        # Exact conservation bounded only by the render's own %.6f.
        assert sums[node] == pytest.approx(sum(vals), abs=1e-5 * len(vals))


def test_counters_and_gauges_sum_label_preserving():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.record_serve_outcome("shared", "completed", 7)
    b.record_serve_outcome("shared", "completed", 5)
    a.record_serve_outcome("only-a", "bounced", 2)
    b.set_serve_queue_depth("only-b", 3)
    merged = fleet_mod.merge_expositions({
        "a": a.render_prometheus(), "b": b.render_prometheus(),
    })
    assert (
        'tpu_cc_serve_requests_total{node="shared",outcome="completed"} 12'
        in merged
    )
    assert (
        'tpu_cc_serve_requests_total{node="only-a",outcome="bounced"} 2'
        in merged
    )
    assert 'tpu_cc_serve_queue_depth{node="only-b"} 3' in merged
    assert expo_lint.lint(merged) == []


def test_help_type_pairing_survives_federation_with_hostile_labels():
    # The lint driver's own seeded registry carries the hostile label
    # values (quotes, newlines, backslashes); federated twice over plus
    # a partial agent, the pairing and escaping must survive intact.
    merged = expo_lint._seeded_fleet_text()
    assert expo_lint.lint(merged) == []
    assert merged.count("# TYPE tpu_cc_serve_request_seconds ") == 1
    assert "tpu_cc_fleet_nodes 3" in merged
    assert "tpu_cc_fleet_headroom_nodes" in merged


def test_merge_p99_agrees_with_pooled_percentile_on_seeded_shards():
    rng = random.Random(SEED + 1)
    for trial in range(20):
        shards = [
            sorted(rng.uniform(0.0, 10.0) for _ in range(rng.randint(0, 50)))
            for _ in range(rng.randint(1, 8))
        ]
        pooled = sorted(v for s in shards for v in s)
        want = slo_mod.percentile(pooled, 0.99)
        got = fleet_mod.fleet_p99(shards)
        assert got == want, f"trial {trial}: {got} != {want}"
    assert fleet_mod.fleet_p99([]) is None
    assert fleet_mod.fleet_p99([[], []]) is None


def test_histogram_shard_reconstruction_matches_bucket_counts():
    reg = MetricsRegistry()
    vals = [0.003, 0.04, 0.04, 1.7, 250.0]  # last lands in +Inf overflow
    for v in vals:
        reg.observe_serve_request("n0", v)
    shard = fleet_mod.histogram_shard(
        fleet_mod.parse_exposition(reg.render_prometheus())
    )
    assert len(shard) == len(vals)
    assert shard == sorted(shard)
    # Every reconstructed sample is a bucket upper bound >= its original
    # (the +Inf overflow is clamped to the top finite bound).
    finite_top = max(s for s in shard)
    assert all(s <= finite_top for s in shard)


# ---------------------------------------------------------------------------
# Gateway: scrape, staleness, capacity ledger
# ---------------------------------------------------------------------------


def build_targets(n: int, alive: dict):
    rng = random.Random(SEED + 2)
    targets = {}
    for i in range(n):
        name = f"fleet-{i}"
        alive[name] = True
        inner = fleet_mod.local_target(seeded_registry(name, rng))

        def fetch(path, name=name, inner=inner):
            if not alive[name]:
                raise ConnectionError("killed")
            return inner(path)

        targets[name] = fetch
    return targets


def test_killed_agent_goes_stale_within_two_sweeps_and_stays_listed():
    alive: dict[str, bool] = {}
    gateway = fleet_mod.FleetGateway(
        targets=build_targets(5, alive), stale_after_sweeps=2,
    )
    gateway.scrape_once()
    assert gateway.fleetz()["fleet"]["stale"] == 0
    alive["fleet-3"] = False
    one = gateway.scrape_once()
    assert one["nodes"]["fleet-3"]["error"]  # failure surfaced at once
    assert not one["nodes"]["fleet-3"]["stale"]  # but one miss != dead
    two = gateway.scrape_once()
    assert two["fleet"]["stale_nodes"] == ["fleet-3"]
    assert two["nodes"]["fleet-3"]["stale"] is True
    # Excluded from the rollups, listed in the ledger.
    merged = gateway.metrics_text()
    assert 'tpu_cc_serve_queue_depth{node="fleet-3"}' not in merged
    assert "tpu_cc_fleet_nodes_stale 1" in merged
    assert expo_lint.lint(merged) == []
    # Resurrection: a fresh scrape clears the staleness immediately.
    alive["fleet-3"] = True
    back = gateway.scrape_once()
    assert back["fleet"]["stale"] == 0
    assert 'tpu_cc_serve_queue_depth{node="fleet-3"}' in gateway.metrics_text()


def test_frozen_snapshot_ts_marks_a_replayed_exposition_stale():
    reg = seeded_registry("frozen", random.Random(SEED + 3))
    body = {
        "/metrics": reg.render_prometheus(),
        "/statusz": json.dumps({"agent_version": "0.0.0", "snapshot_ts": 17.0}),
        "/rolloutz": json.dumps({"enabled": False}),
    }
    gateway = fleet_mod.FleetGateway(
        targets={"frozen": lambda path: body[path]}, stale_after_sweeps=2,
    )
    gateway.scrape_once()  # first scrape: nothing to compare against
    gateway.scrape_once()  # same snapshot_ts: replayed body detected
    fleetz = gateway.scrape_once()
    assert fleetz["nodes"]["frozen"]["stale"] is True
    assert fleetz["nodes"]["frozen"]["error"] == "snapshot-ts-not-advancing"


def test_capacity_ledger_headroom_rules():
    def agent(**kw):
        reg = MetricsRegistry()
        reg.observe_serve_request("x", 0.05)
        reg.set_serve_hbm_bw_util("x", kw.get("hbm", 0.5))
        reg.set_serve_queue_depth("x", kw.get("queue", 1))
        if kw.get("quarantined"):
            reg.set_quarantined(True)
        if kw.get("prestaging"):
            reg.set_prestage_in_progress(True)
        if kw.get("offline"):
            reg.set_apiserver_connected(False)
        return fleet_mod.local_target(reg)

    gateway = fleet_mod.FleetGateway(targets={
        "fine": agent(),
        "hot": agent(hbm=0.97),
        "deep": agent(queue=40),
        "quar": agent(quarantined=True),
        "prestage": agent(prestaging=True),
        "offline": agent(offline=True),
    })
    fleetz = gateway.scrape_once()
    headroom = {
        name: entry["has_headroom"]
        for name, entry in fleetz["nodes"].items()
    }
    assert headroom == {
        "fine": True, "hot": False, "deep": False,
        "quar": False, "prestage": False, "offline": False,
    }
    assert "tpu_cc_fleet_headroom_nodes 1" in gateway.metrics_text()
    assert fleetz["nodes"]["quar"]["quarantined"] is True
    assert fleetz["nodes"]["prestage"]["prestage_in_progress"] is True
    assert fleetz["nodes"]["offline"]["offline"] is True


def test_gateway_http_endpoints_serve_merged_truth():
    alive: dict[str, bool] = {}
    gateway = fleet_mod.FleetGateway(targets=build_targets(3, alive))
    gateway.scrape_once()
    server = gateway.serve(port=0)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            merged = resp.read().decode()
        assert "tpu_cc_fleet_nodes 3" in merged
        assert expo_lint.lint(merged) == []
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleetz?rollout=", timeout=5
        ) as resp:
            fleetz = json.load(resp)
        assert fleetz["fleet"]["nodes"] == 3
        assert fleetz["rollout"]["streams"] == 0  # no flight recorders
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Timeline stitching
# ---------------------------------------------------------------------------


def test_stitch_orders_by_generation_then_ts_and_tags_streams():
    stream_a = [
        {"event": "plan", "gen": 1, "ts": 10.0, "seq": 1},
        {"event": "window-open", "gen": 1, "ts": 12.0, "seq": 2},
    ]
    stream_b = [
        {"event": "resume", "gen": 2, "ts": 11.0, "seq": 1},
        {"event": "complete", "gen": 2, "ts": 13.0, "seq": 2},
    ]
    # Handed over in the wrong order on purpose.
    stitched = flight_mod.stitch_timelines(
        [stream_b, stream_a], labels=["b", "a"]
    )
    assert [e["event"] for e in stitched] == [
        "plan", "window-open", "resume", "complete",
    ]  # gen 1 entirely before gen 2, despite b's earlier wall-clock
    assert [e["stream"] for e in stitched] == ["a", "a", "b", "b"]


def test_stitch_collapses_cross_stream_duplicates_and_orders_none_gen_last():
    shared = {"event": "node-converged", "gen": 1, "ts": 5.0, "seq": 3,
              "node": "n1"}
    pre_lease = {"event": "plan", "gen": None, "ts": 1.0, "seq": 1}
    stitched = flight_mod.stitch_timelines(
        [[shared, pre_lease], [dict(shared)]]
    )
    assert len(stitched) == 2  # the duplicate collapsed
    # None generation ranks after numbered ones (type-stable ordering).
    assert [e["event"] for e in stitched] == ["node-converged", "plan"]


def test_stitch_files_tolerates_torn_tails_per_stream(tmp_path):
    paths = []
    for i in range(2):
        path = str(tmp_path / f"shard-{i}.jsonl")
        fr = flight_mod.FlightRecorder(path, generation=i + 1)
        fr.record("plan", mode="on", shard=i)
        fr.record("complete", ok=True, shard=i)
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"event": "window-open", "torn mid-wri')
        paths.append(path)
    stitched, torn = flight_mod.stitch_files(paths)
    assert torn == 2
    assert len(stitched) == 4
    assert [e["gen"] for e in stitched] == [1, 1, 2, 2]


def add_pool(fake, n):
    for i in range(n):
        fake.add_node(f"node-{i}", {"pool": "tpu"})


def agent_simulator(fake):
    in_flight = set()

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired and name not in in_flight:
            in_flight.add(name)

            def fire():
                in_flight.discard(name)
                fake.set_node_label(name, CC_MODE_STATE_LABEL, desired)

            t = threading.Timer(0.03, fire)
            t.daemon = True
            t.start()

    fake.add_patch_reactor(reactor)


class Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _sharded_kill_resume(tmp_path, kill_at: int):
    """A sharded (wave_shards=2) rollout killed mid-flight; successor
    resumes writing its OWN flight file — per-region orchestrators."""
    fake = FakeKube()
    add_pool(fake, 4)
    agent_simulator(fake)
    clk = Clock()
    metrics = MetricsRegistry()
    calls = {"n": 0}

    def killer(point):
        if calls["n"] == kill_at:
            raise OrchestratorKilled(point, calls["n"])
        calls["n"] += 1

    path_a = str(tmp_path / "orch-a.jsonl")
    path_b = str(tmp_path / "orch-b.jsonl")

    def lease_for(holder):
        return rollout_state.RolloutLease(
            fake, holder=holder, namespace="tpu-operator", duration_s=30.0,
            metrics=metrics, wall=clk, clock=clk,
        )

    lease_a = lease_for("orch-a")
    lease_a.acquire()
    roller_a = RollingReconfigurator(
        fake, "pool=tpu", max_unavailable=1, node_timeout_s=5,
        poll_interval_s=0.02, wave_shards=2, lease=lease_a,
        crash_hook=killer, metrics=metrics,
        flight=flight_mod.FlightRecorder(path_a, generation=lease_a.generation),
    )
    killed = False
    try:
        result = roller_a.rollout("on")
    except OrchestratorKilled:
        killed = True
        clk.advance(31)
        lease_b = lease_for("orch-b")
        record = lease_b.acquire()
        assert record is not None
        roller_b = RollingReconfigurator(
            fake, "pool=tpu", max_unavailable=1, node_timeout_s=5,
            poll_interval_s=0.02, wave_shards=2, lease=lease_b,
            resume_record=record, metrics=metrics,
            flight=flight_mod.FlightRecorder(
                path_b, generation=lease_b.generation
            ),
        )
        result = roller_b.rollout(record.mode)
    return killed, result, path_a, path_b


def test_stitched_sharded_rollout_reconstructs_exactly_once(tmp_path):
    killed, result, path_a, path_b = _sharded_kill_resume(tmp_path, kill_at=5)
    assert killed and result.ok
    stitched, torn = flight_mod.stitch_files([path_a, path_b])
    assert torn == 0
    rec = flight_mod.reconstruct(stitched)
    assert set(rec["nodes"]) == {f"node-{i}" for i in range(4)}
    assert rec["duplicate_node_events"] == []
    assert all(
        n["outcome"] == "node-converged" for n in rec["nodes"].values()
    )
    assert rec["resumes"] == 1
    assert len(rec["generations"]) == 2
    # The federated timeline never interleaves generations.
    gens = [e["gen"] for e in stitched if e.get("gen") is not None]
    assert gens == sorted(gens)


def test_ctl_rollout_timeline_stitch_renders_federated_view(
    tmp_path, capsys
):
    from tpu_cc_manager import ctl

    killed, result, path_a, path_b = _sharded_kill_resume(tmp_path, kill_at=3)
    assert killed and result.ok
    args = ctl.build_parser().parse_args(
        ["rollout-timeline", "--stitch", path_a, path_b]
    )
    assert ctl.cmd_rollout_timeline(None, args) == 0
    out = capsys.readouterr().out
    assert "reconstruction:" in out
    assert "resumes=1" in out
    for i in range(4):
        assert f"node node-{i}: node-converged" in out
    # --json over the same stitch returns machine-readable streams.
    args = ctl.build_parser().parse_args(
        ["rollout-timeline", "--stitch", path_a, path_b, "--json"]
    )
    assert ctl.cmd_rollout_timeline(None, args) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["reconstruction"]["resumes"] == 1
    assert {e["stream"] for e in payload["events"]} == {
        "orch-a.jsonl", "orch-b.jsonl",
    }


def test_gateway_fleetz_rollout_stitches_scraped_rolloutz_streams(tmp_path):
    recorders = {}
    targets = {}
    for i in range(2):
        fr = flight_mod.FlightRecorder(
            str(tmp_path / f"agent-{i}.jsonl"), generation=1
        )
        fr.record("window-open", wave=i, window=0)
        fr.record("node-converged", node=f"node-{i}", wave=i, state="on")
        recorders[f"agent-{i}"] = fr
        targets[f"agent-{i}"] = fleet_mod.local_target(
            seeded_registry(f"agent-{i}", random.Random(SEED + 10 + i)),
            flight=fr,
        )
    gateway = fleet_mod.FleetGateway(targets=targets)
    gateway.scrape_once()
    rollout = gateway.stitched_rollout()
    assert rollout["streams"] == 2
    assert rollout["events"] == 4
    assert set(rollout["reconstruction"]["nodes"]) == {"node-0", "node-1"}


# ---------------------------------------------------------------------------
# Chaos soak: merged truth survives agents dying mid-sweep
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_gateway_serves_merged_truth_while_chaos_kills_agents():
    """Seeded chaos kills (and resurrects) scraped agents between
    sweeps; every sweep's merged exposition must stay lint-clean, stale
    marking must track the kill schedule within stale_after_sweeps, and
    the fleet families must never disappear. Prints the FLEET_SUMMARY
    line hack/chaos_soak.sh scrapes."""
    rng = random.Random(SEED + 4)
    alive: dict[str, bool] = {}
    n = 12
    gateway = fleet_mod.FleetGateway(
        targets=build_targets(n, alive), stale_after_sweeps=2,
    )
    sweeps = 0
    kills = 0
    resurrections = 0
    max_stale = 0
    for round_no in range(10):
        for name in list(alive):
            if alive[name] and rng.random() < 0.25:
                alive[name] = False
                kills += 1
            elif not alive[name] and rng.random() < 0.5:
                alive[name] = True
                resurrections += 1
        fleetz = gateway.scrape_once()
        sweeps += 1
        merged = gateway.metrics_text()
        problems = expo_lint.lint(merged)
        assert problems == [], f"round {round_no}: {problems}"
        assert f"tpu_cc_fleet_nodes {n}" in merged
        assert "tpu_cc_fleet_headroom_nodes" in merged
        # Every node is LISTED every sweep, dead or alive.
        assert len(fleetz["nodes"]) == n
        # Anything stale genuinely missed >= 2 consecutive sweeps.
        for name in fleetz["fleet"]["stale_nodes"]:
            assert not alive[name] or fleetz["nodes"][name]["error"]
        max_stale = max(max_stale, fleetz["fleet"]["stale"])
    assert kills > 0 and max_stale > 0  # the chaos actually bit
    print("FLEET_SUMMARY " + json.dumps({
        "sweeps": sweeps, "agents": n, "kills": kills,
        "resurrections": resurrections, "max_stale": max_stale,
        "scrape_errors": fleetz["fleet"]["scrape_errors_total"],
        "merged_lint_problems": 0,
    }))
