"""Workload drain handshake (drain/handshake.py; VERDICT r3 item 4).

The decisive property: the checkpoint is triggered BY the drain protocol —
the manager requests, the job's DrainSubscriber checkpoints and acks, and
only then does the component drain proceed — and training resumes bit-exact
from that protocol-triggered snapshot. (test_rolling_training.py covers the
checkpoint/restore math; here the trigger and the ordering are the system
under test.)
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.drain import handshake
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_ON,
)
from tpu_cc_manager.parallel.checkpoint import TrainCheckpointer
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "train-node-0"
NS = "tpu-operator"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


# ---------------------------------------------------------------------------
# Protocol units
# ---------------------------------------------------------------------------


def test_request_token_parsing_is_strict():
    """Tokens parse only from 'requested' (legacy, '') or
    'requested-<token>': a malformed value must read as no-drain, not as
    a garbage token a subscriber would checkpoint against."""
    assert handshake.request_token(None) is None
    assert handshake.request_token("requested") == ""
    assert handshake.request_token("requested-abc12") == "abc12"
    assert handshake.request_token("requestedabc") is None
    assert handshake.request_token("draining") is None
    # Round-trips with the writer side.
    assert handshake.request_token(handshake.request_value("tok")) == "tok"
    assert handshake.request_token(handshake.request_value("")) == ""


def test_request_drain_resets_stale_acks(fake_kube):
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ACKED})  # stale from r-1
    cycle = handshake.request_drain(fake_kube, NODE)
    assert cycle.subscribers == [sub_label]
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[handshake.DRAIN_REQUESTED_LABEL] == handshake.request_value(
        cycle.token
    )
    # The stale ack cannot satisfy this cycle's wait.
    assert labels[sub_label] == handshake.ACTIVE
    laggards = handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=0.05, poll_interval_s=0.01,
        token=cycle.token,
    )
    assert laggards == [sub_label]


def test_await_acks_returns_when_all_acked(fake_kube):
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ack_value("t1")})
    assert handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=1, token="t1"
    ) == []


def test_stale_ack_from_previous_cycle_never_satisfies(fake_kube):
    """The r4 race: a subscriber's in-flight 'acked' patch from cycle N-1
    lands AFTER cycle N's reset-to-active patch. With cycle-scoped ack
    values the stale ack carries the old token and cannot read as a fresh
    checkpoint (ADVICE r4 #1)."""
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ACTIVE})
    old = handshake.request_drain(fake_kube, NODE)
    # Subscriber acks cycle N-1... but the patch is still in flight.
    in_flight_ack = {sub_label: handshake.ack_value(old.token)}
    # Manager opens cycle N (crash-restart): fresh token, reset to active.
    new = handshake.request_drain(fake_kube, NODE)
    assert new.token != old.token
    # The stale ack lands now, after the reset.
    fake_kube.patch_node_labels(NODE, in_flight_ack)
    laggards = handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=0.05, poll_interval_s=0.01,
        token=new.token,
    )
    assert laggards == [sub_label]  # old-token ack did NOT satisfy cycle N


def test_legacy_bare_ack_still_satisfies_during_skew(fake_kube):
    """A pre-token subscriber (old training image) acks with bare 'acked';
    a new manager must accept it rather than stall every drain for the
    full ack timeout during the version-skew window."""
    sub_label = handshake.subscriber_label("old-image-job")
    fake_kube.add_node(NODE, {sub_label: handshake.ACTIVE})
    cycle = handshake.request_drain(fake_kube, NODE)
    fake_kube.patch_node_labels(NODE, {sub_label: handshake.ACKED})
    assert handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=1, poll_interval_s=0.01,
        token=cycle.token,
    ) == []


def test_concurrent_registration_is_awaited(fake_kube):
    """A subscriber registering between request_drain's read and its patch
    is in the returned server-view set (VERDICT r4 weak #5)."""
    fake_kube.add_node(NODE)
    sub_label = handshake.subscriber_label("late")
    registered = {"done": False}

    def register_on_patch(name, patched):
        # Fires during request_drain's own patch — after its read, before
        # its re-read: the precise window of the race.
        if not registered["done"]:
            registered["done"] = True
            fake_kube.patch_node_labels(NODE, {sub_label: handshake.ACTIVE})

    fake_kube.add_patch_reactor(register_on_patch)
    cycle = handshake.request_drain(fake_kube, NODE)
    assert sub_label in cycle.subscribers


def test_unregistered_subscriber_counts_as_done(fake_kube):
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ACTIVE})

    def finish_job():
        # cclint: test-sleep-ok(deliberate delay: the subscriber finishes AFTER the await starts)
        time.sleep(0.05)
        fake_kube.patch_node_labels(NODE, {sub_label: None})

    t = threading.Thread(target=finish_job)
    t.start()
    assert handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=5, poll_interval_s=0.01
    ) == []
    t.join()


# ---------------------------------------------------------------------------
# End-to-end: drain blocks until the job checkpoints; resume is bit-exact
# ---------------------------------------------------------------------------


@jax.jit
def _train_step(state, batch):
    w, step = state
    grad = jax.grad(lambda w: jnp.mean((batch @ w - 1.0) ** 2))(w)
    return (w - 0.1 * grad, step + 1), jnp.mean((batch @ w - 1.0) ** 2)


def _make_state():
    return (jnp.ones((4, 4), jnp.float32), jnp.int32(0))


def test_drain_blocks_until_job_checkpoints_then_resumes_exactly(
    fake_kube, tmp_path
):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "dp-pod", NODE, labels={"app": DP_APP})

    events: list[str] = []

    def reactor(name, patched):
        labels = node_labels(patched)
        if is_paused(labels.get(DP_LABEL)):
            events.append("component-paused")
            fake_kube.delete_pod(NS, "dp-pod")

    fake_kube.add_patch_reactor(reactor)

    # The "training job": steps in its own thread, checkpointing ONLY when
    # the drain protocol asks it to.
    batch = jnp.eye(4, dtype=jnp.float32)
    job = {"state": _make_state(), "ckpt_step": None}
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    resumed = threading.Event()

    def on_drain():
        # The handshake's whole point: at checkpoint time the component
        # drain has NOT started (pods still present, label unpaused).
        labels = node_labels(fake_kube.get_node(NODE))
        assert not is_paused(labels.get(DP_LABEL))
        assert fake_kube.list_pods(NS, label_selector=f"app={DP_APP}")
        events.append("checkpointed")
        step = int(job["state"][1])
        ckpt.save(step, job["state"])
        job["ckpt_step"] = step

    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "sim-train", on_drain=on_drain,
        on_resume=lambda: resumed.set(), poll_interval_s=0.01,
    )

    # A few steps before the bounce; record the uninterrupted reference.
    for _ in range(3):
        job["state"], _ = _train_step(job["state"], batch)
    ref_state = job["state"]
    ref_continue = _train_step(ref_state, batch)[0]

    sub.start()
    try:
        mgr = CCManager(
            api=fake_kube,
            backend=FakeTpuBackend(),
            node_name=NODE,
            operator_namespace=NS,
            evict_components=True,
            smoke_workload="none",
            metrics=MetricsRegistry(),
            eviction_timeout_s=5,
            eviction_poll_interval_s=0.01,
            drain_ack_timeout_s=10,
        )
        assert mgr.set_cc_mode(MODE_ON) is True
        # The subscriber observes the withdrawn request and resumes; only
        # then stop it.
        assert resumed.wait(5)
    finally:
        sub.stop()

    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_ON
    # Protocol-triggered: the checkpoint happened, and BEFORE the pause.
    assert job["ckpt_step"] == 3
    assert events.index("checkpointed") < events.index("component-paused")
    # Cleanup: request withdrawn, component restored.
    assert handshake.DRAIN_REQUESTED_LABEL not in labels
    assert labels.get(DP_LABEL) == "true"

    # Resume from the protocol-triggered snapshot: bit-exact.
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), ref_state
    )
    restored = ckpt.restore(abstract)
    ckpt.close()
    assert int(restored[1]) == 3
    assert jnp.array_equal(restored[0], ref_state[0])
    resumed_next = _train_step(restored, batch)[0]
    assert jnp.array_equal(resumed_next[0], ref_continue[0])
    assert int(resumed_next[1]) == int(ref_continue[1])


def test_subscriber_survives_transient_api_errors(fake_kube):
    """A poll that raises KubeApiError must not kill the subscriber
    thread — the next poll still observes the request and acks."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    fake_kube.add_node(NODE)
    fail_once = {"n": 1}
    real_get = fake_kube.get_node

    def flaky_get(name):
        # Fail only the SUBSCRIBER thread's poll: the main thread also
        # calls get_node (request_drain / await_workload_acks), and
        # consuming the injected failure there would error the test
        # instead of exercising the resilience path.
        if fail_once["n"] and threading.current_thread().name.startswith(
            "drain-sub-"
        ):
            fail_once["n"] -= 1
            raise KubeApiError(503, "hiccup")
        return real_get(name)

    fake_kube.get_node = flaky_get
    acked = threading.Event()
    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "resilient", on_drain=lambda: acked.set(),
        poll_interval_s=0.01,
    )
    sub.start()
    try:
        cycle = handshake.request_drain(fake_kube, NODE)
        assert handshake.await_workload_acks(
            fake_kube, NODE, timeout_s=5, poll_interval_s=0.01,
            token=cycle.token,
        ) == []
        assert acked.is_set()
    finally:
        sub.stop()


def test_subscriber_survives_callback_failure(fake_kube):
    """A failing checkpoint callback must not kill the subscriber thread:
    it stays registered and un-acked, and the next poll retries (a disk
    hiccup mid-checkpoint is transient; dying would also unregister and
    silently drop the job from every future cycle)."""
    fake_kube.add_node(NODE)
    attempts = {"n": 0}
    acked = threading.Event()

    def flaky_checkpoint():
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise OSError("disk hiccup mid-checkpoint")
        acked.set()

    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "flaky-ckpt", on_drain=flaky_checkpoint,
        poll_interval_s=0.01,
    )
    sub.start()
    try:
        cycle = handshake.request_drain(fake_kube, NODE)
        assert handshake.await_workload_acks(
            fake_kube, NODE, timeout_s=5, poll_interval_s=0.01,
            token=cycle.token,
        ) == []
        assert acked.is_set()
        assert attempts["n"] == 2  # first failed, retry succeeded
    finally:
        sub.stop()


def test_failed_resume_callback_is_retried(fake_kube):
    """A transiently-failing on_resume is retried on the next poll, not
    silently dropped: the cycle memory clears only after resume succeeds."""
    fake_kube.add_node(NODE)
    resumed = {"attempts": 0}

    def flaky_resume():
        resumed["attempts"] += 1
        if resumed["attempts"] == 1:
            raise OSError("notify endpoint hiccup")

    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "resume-retry", on_drain=lambda: None,
        on_resume=flaky_resume, poll_interval_s=0.01,
    )
    sub.register()
    cycle = handshake.request_drain(fake_kube, NODE)
    assert sub.check_once() is True  # checkpoint + ack
    handshake.clear_drain_request(fake_kube, NODE)
    with pytest.raises(OSError):
        sub.check_once()  # resume fails once...
    assert sub._acked_token == cycle.token  # ...cycle NOT forgotten
    sub.check_once()
    assert resumed["attempts"] == 2  # ...so the next poll retried it
    assert sub._acked_token is None


def test_wedged_job_cannot_veto_the_drain(fake_kube):
    """A registered subscriber that never acks delays the drain by at most
    the bounded ack timeout (lenient policy, SURVEY.md §8.5)."""
    sub_label = handshake.subscriber_label("wedged")
    fake_kube.add_node(NODE, {DP_LABEL: "true", sub_label: handshake.ACTIVE})
    mgr = CCManager(
        api=fake_kube,
        backend=FakeTpuBackend(),
        node_name=NODE,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=MetricsRegistry(),
        eviction_timeout_s=1,
        eviction_poll_interval_s=0.01,
        drain_ack_timeout_s=0.2,
    )
    t0 = time.monotonic()
    assert mgr.set_cc_mode(MODE_ON) is True
    elapsed = time.monotonic() - t0
    assert elapsed < 5  # bounded, not a veto
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_ON


def test_handshake_disabled_by_default(fake_kube):
    """drain_ack_timeout_s=0 (the default): no drain-request label is ever
    published — the reference-shaped flow is unchanged."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    seen = []
    fake_kube.add_patch_reactor(
        lambda name, patched: seen.append(dict(node_labels(patched)))
    )
    mgr = CCManager(
        api=fake_kube,
        backend=FakeTpuBackend(),
        node_name=NODE,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=MetricsRegistry(),
        eviction_timeout_s=1,
        eviction_poll_interval_s=0.01,
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert not any(
        handshake.request_token(labels.get(handshake.DRAIN_REQUESTED_LABEL))
        is not None
        for labels in seen
    )


def test_subscriber_backs_off_when_idle(fake_kube):
    """No drain requested → the subscriber polls at the idle interval;
    a request switches it to the fast interval (VERDICT r4 weak #5: fleet
    GET load)."""
    fake_kube.add_node(NODE)
    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "idle-job", on_drain=lambda: None,
        poll_interval_s=0.01,
    )
    assert sub.idle_poll_interval_s == pytest.approx(
        handshake.IDLE_POLL_MULTIPLIER * 0.01
    )
    sub.check_once()
    assert sub._drain_requested is False  # run() will sleep the idle interval
    handshake.request_drain(fake_kube, NODE)
    sub.check_once()
    assert sub._drain_requested is True  # back to the fast interval


def test_abandoned_drain_clears_request_label(fake_kube):
    """A transport error that abandons the drain mid-pause must clear the
    drain-request label so subscribers don't stay parked (ADVICE r4 #3)."""
    from tpu_cc_manager.drain.evict import evict_components
    from tpu_cc_manager.kubeclient.api import KubeApiError

    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {DP_LABEL: "true", sub_label: handshake.ACTIVE})
    real_patch = fake_kube.patch_node_labels
    calls = {"n": 0}

    def failing_patch(name, patch):
        calls["n"] += 1
        if any(k in DRAIN_COMPONENT_LABELS for k in patch):
            raise KubeApiError(503, "apiserver unavailable")
        return real_patch(name, patch)

    fake_kube.patch_node_labels = failing_patch
    with pytest.raises(KubeApiError):
        evict_components(
            fake_kube, NODE, NS,
            timeout_s=0.1, poll_interval_s=0.01,
            workload_ack_timeout_s=0.05,
        )
    labels = node_labels(fake_kube.get_node(NODE))
    assert handshake.DRAIN_REQUESTED_LABEL not in labels  # cleared, not parked
