"""Workload drain handshake (drain/handshake.py; VERDICT r3 item 4).

The decisive property: the checkpoint is triggered BY the drain protocol —
the manager requests, the job's DrainSubscriber checkpoints and acks, and
only then does the component drain proceed — and training resumes bit-exact
from that protocol-triggered snapshot. (test_rolling_training.py covers the
checkpoint/restore math; here the trigger and the ordering are the system
under test.)
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.drain import handshake
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_ON,
)
from tpu_cc_manager.parallel.checkpoint import TrainCheckpointer
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "train-node-0"
NS = "tpu-operator"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


# ---------------------------------------------------------------------------
# Protocol units
# ---------------------------------------------------------------------------


def test_request_drain_resets_stale_acks(fake_kube):
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ACKED})  # stale from r-1
    subs = handshake.request_drain(fake_kube, NODE)
    assert subs == [sub_label]
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[handshake.DRAIN_REQUESTED_LABEL] == handshake.DRAIN_REQUESTED
    # The stale ack cannot satisfy this cycle's wait.
    assert labels[sub_label] == handshake.ACTIVE
    laggards = handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=0.05, poll_interval_s=0.01
    )
    assert laggards == [sub_label]


def test_await_acks_returns_when_all_acked(fake_kube):
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ACKED})
    assert handshake.await_workload_acks(fake_kube, NODE, timeout_s=1) == []


def test_unregistered_subscriber_counts_as_done(fake_kube):
    sub_label = handshake.subscriber_label("jobA")
    fake_kube.add_node(NODE, {sub_label: handshake.ACTIVE})

    def finish_job():
        time.sleep(0.05)
        fake_kube.patch_node_labels(NODE, {sub_label: None})

    t = threading.Thread(target=finish_job)
    t.start()
    assert handshake.await_workload_acks(
        fake_kube, NODE, timeout_s=5, poll_interval_s=0.01
    ) == []
    t.join()


# ---------------------------------------------------------------------------
# End-to-end: drain blocks until the job checkpoints; resume is bit-exact
# ---------------------------------------------------------------------------


@jax.jit
def _train_step(state, batch):
    w, step = state
    grad = jax.grad(lambda w: jnp.mean((batch @ w - 1.0) ** 2))(w)
    return (w - 0.1 * grad, step + 1), jnp.mean((batch @ w - 1.0) ** 2)


def _make_state():
    return (jnp.ones((4, 4), jnp.float32), jnp.int32(0))


def test_drain_blocks_until_job_checkpoints_then_resumes_exactly(
    fake_kube, tmp_path
):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "dp-pod", NODE, labels={"app": DP_APP})

    events: list[str] = []

    def reactor(name, patched):
        labels = node_labels(patched)
        if is_paused(labels.get(DP_LABEL)):
            events.append("component-paused")
            fake_kube.delete_pod(NS, "dp-pod")

    fake_kube.add_patch_reactor(reactor)

    # The "training job": steps in its own thread, checkpointing ONLY when
    # the drain protocol asks it to.
    batch = jnp.eye(4, dtype=jnp.float32)
    job = {"state": _make_state(), "ckpt_step": None}
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    resumed = threading.Event()

    def on_drain():
        # The handshake's whole point: at checkpoint time the component
        # drain has NOT started (pods still present, label unpaused).
        labels = node_labels(fake_kube.get_node(NODE))
        assert not is_paused(labels.get(DP_LABEL))
        assert fake_kube.list_pods(NS, label_selector=f"app={DP_APP}")
        events.append("checkpointed")
        step = int(job["state"][1])
        ckpt.save(step, job["state"])
        job["ckpt_step"] = step

    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "sim-train", on_drain=on_drain,
        on_resume=lambda: resumed.set(), poll_interval_s=0.01,
    )

    # A few steps before the bounce; record the uninterrupted reference.
    for _ in range(3):
        job["state"], _ = _train_step(job["state"], batch)
    ref_state = job["state"]
    ref_continue = _train_step(ref_state, batch)[0]

    sub.start()
    try:
        mgr = CCManager(
            api=fake_kube,
            backend=FakeTpuBackend(),
            node_name=NODE,
            operator_namespace=NS,
            evict_components=True,
            smoke_workload="none",
            metrics=MetricsRegistry(),
            eviction_timeout_s=5,
            eviction_poll_interval_s=0.01,
            drain_ack_timeout_s=10,
        )
        assert mgr.set_cc_mode(MODE_ON) is True
        # The subscriber observes the withdrawn request and resumes; only
        # then stop it.
        assert resumed.wait(5)
    finally:
        sub.stop()

    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_ON
    # Protocol-triggered: the checkpoint happened, and BEFORE the pause.
    assert job["ckpt_step"] == 3
    assert events.index("checkpointed") < events.index("component-paused")
    # Cleanup: request withdrawn, component restored.
    assert handshake.DRAIN_REQUESTED_LABEL not in labels
    assert labels.get(DP_LABEL) == "true"

    # Resume from the protocol-triggered snapshot: bit-exact.
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), ref_state
    )
    restored = ckpt.restore(abstract)
    ckpt.close()
    assert int(restored[1]) == 3
    assert jnp.array_equal(restored[0], ref_state[0])
    resumed_next = _train_step(restored, batch)[0]
    assert jnp.array_equal(resumed_next[0], ref_continue[0])
    assert int(resumed_next[1]) == int(ref_continue[1])


def test_subscriber_survives_transient_api_errors(fake_kube):
    """A poll that raises KubeApiError must not kill the subscriber
    thread — the next poll still observes the request and acks."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    fake_kube.add_node(NODE)
    fail_once = {"n": 1}
    real_get = fake_kube.get_node

    def flaky_get(name):
        # Fail only the SUBSCRIBER thread's poll: the main thread also
        # calls get_node (request_drain / await_workload_acks), and
        # consuming the injected failure there would error the test
        # instead of exercising the resilience path.
        if fail_once["n"] and threading.current_thread().name.startswith(
            "drain-sub-"
        ):
            fail_once["n"] -= 1
            raise KubeApiError(503, "hiccup")
        return real_get(name)

    fake_kube.get_node = flaky_get
    acked = threading.Event()
    sub = handshake.DrainSubscriber(
        fake_kube, NODE, "resilient", on_drain=lambda: acked.set(),
        poll_interval_s=0.01,
    )
    sub.start()
    try:
        handshake.request_drain(fake_kube, NODE)
        assert handshake.await_workload_acks(
            fake_kube, NODE, timeout_s=5, poll_interval_s=0.01
        ) == []
        assert acked.is_set()
    finally:
        sub.stop()


def test_wedged_job_cannot_veto_the_drain(fake_kube):
    """A registered subscriber that never acks delays the drain by at most
    the bounded ack timeout (lenient policy, SURVEY.md §8.5)."""
    sub_label = handshake.subscriber_label("wedged")
    fake_kube.add_node(NODE, {DP_LABEL: "true", sub_label: handshake.ACTIVE})
    mgr = CCManager(
        api=fake_kube,
        backend=FakeTpuBackend(),
        node_name=NODE,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=MetricsRegistry(),
        eviction_timeout_s=1,
        eviction_poll_interval_s=0.01,
        drain_ack_timeout_s=0.2,
    )
    t0 = time.monotonic()
    assert mgr.set_cc_mode(MODE_ON) is True
    elapsed = time.monotonic() - t0
    assert elapsed < 5  # bounded, not a veto
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_ON


def test_handshake_disabled_by_default(fake_kube):
    """drain_ack_timeout_s=0 (the default): no drain-request label is ever
    published — the reference-shaped flow is unchanged."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    seen = []
    fake_kube.add_patch_reactor(
        lambda name, patched: seen.append(dict(node_labels(patched)))
    )
    mgr = CCManager(
        api=fake_kube,
        backend=FakeTpuBackend(),
        node_name=NODE,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=MetricsRegistry(),
        eviction_timeout_s=1,
        eviction_poll_interval_s=0.01,
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert not any(
        labels.get(handshake.DRAIN_REQUESTED_LABEL) == handshake.DRAIN_REQUESTED
        for labels in seen
    )
