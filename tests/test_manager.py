"""CCManager reconciler state machine (ccmanager/manager.py vs reference
call stacks SURVEY.md §3.2/§3.3)."""

import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_DEVTOOLS,
    MODE_OFF,
    MODE_ON,
    MODE_SLICE,
    STATE_FAILED,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "tpu-node-0"
NS = "tpu-operator"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


def make_manager(fake_kube, backend, **kw):
    kw.setdefault("evict_components", False)
    kw.setdefault("smoke_workload", "none")
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("eviction_timeout_s", 1)
    kw.setdefault("eviction_poll_interval_s", 0.01)
    return CCManager(
        api=fake_kube,
        backend=backend,
        node_name=NODE,
        operator_namespace=NS,
        **kw,
    )


def state_of(fake_kube):
    labels = node_labels(fake_kube.get_node(NODE))
    return labels.get(CC_MODE_STATE_LABEL), labels.get(CC_READY_STATE_LABEL)


def test_mode_on_happy_path(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert state_of(fake_kube) == (MODE_ON, "true")
    ops = [op for op, _ in fake_tpu.op_log]
    # stage-all before reset-all before wait (reference main.py:502-529).
    assert ops.index("stage") < ops.index("reset") < ops.index("wait_ready")
    assert "attest" in ops


def test_mode_off_skips_attestation(fake_kube):
    backend = FakeTpuBackend(initial_mode=MODE_ON)
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_OFF) is True
    assert state_of(fake_kube) == (MODE_OFF, "false")
    assert "attest" not in [op for op, _ in backend.op_log]


def test_idempotent_apply_skips_reset(fake_kube):
    backend = FakeTpuBackend(initial_mode=MODE_ON)
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert "reset" not in [op for op, _ in backend.op_log]
    # State is still reported (reference main.py:255-258).
    assert state_of(fake_kube) == (MODE_ON, "true")


def test_idempotent_apply_clears_stale_staged_marker(fake_kube):
    # A crash between barrier commit and clear_staged leaves the node's
    # slice staged marker behind; the idempotent path after restart must
    # retire it so ctl status stops advertising "mid-transition" (ADVICE r3).
    from tpu_cc_manager.ccmanager.slicecoord import SLICE_STAGED_LABEL

    backend = FakeTpuBackend(
        initial_mode=MODE_SLICE, accelerator_type="v5p-32",
        num_hosts=2, host_index=0, slice_id="slice-a",
    )
    fake_kube.add_node(NODE, {SLICE_STAGED_LABEL: MODE_SLICE})
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_SLICE) is True
    labels = node_labels(fake_kube.get_node(NODE))
    assert SLICE_STAGED_LABEL not in labels
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_SLICE
    assert "reset" not in [op for op, _ in backend.op_log]


def test_mixed_capability_exits(fake_kube):
    backend = FakeTpuBackend(num_chips=4, cc_supported=[True, True, False, False])
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    # Crash-as-retry (reference main.py:237-240).
    with pytest.raises(SystemExit):
        mgr.set_cc_mode(MODE_ON)


def test_no_cc_capable_chips_reports_off(fake_kube):
    backend = FakeTpuBackend(cc_supported=False)
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert state_of(fake_kube) == (MODE_OFF, "false")


def test_slice_mode_requires_all_chips(fake_kube):
    backend = FakeTpuBackend(slice_cc_supported=[True, True, True, False])
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    # Reference PPCIe all-must-support rule (main.py:279-282) — but unlike
    # the reference's sys.exit(1) crash loop, stable hardware
    # misconfiguration fails SOFT with a reason label.
    assert mgr.set_cc_mode(MODE_SLICE) is False
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_MODE_STATE_LABEL) == STATE_FAILED
    assert labels.get(CC_FAILED_REASON_LABEL) == "slice-mode-unsupported"
    # Hardware untouched.
    assert "reset" not in [op for op, _ in backend.op_log]


def test_failed_reason_cleared_on_recovery(fake_kube):
    backend = FakeTpuBackend(slice_cc_supported=[True, True, True, False])
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_SLICE) is False
    # Operator fixes the desired mode; the reason label must not linger.
    assert mgr.set_cc_mode(MODE_ON) is True
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_ON
    assert CC_FAILED_REASON_LABEL not in labels


def test_slice_mode_happy_path(fake_kube):
    # Single-host slice topology: mode 'slice' without the multi-host
    # barrier (the cross-host case is covered by tests/test_slicecoord.py).
    backend = FakeTpuBackend(accelerator_type="v5p-8")
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_SLICE) is True
    assert state_of(fake_kube) == (MODE_SLICE, "true")


def test_ppcie_alias(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode("ppcie") is True
    assert state_of(fake_kube) == (MODE_SLICE, "true")


def test_invalid_mode_rejected(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode("bogus") is False
    # Divergence from the reference (which refuses silently): the node
    # reports failed + a machine-readable reason. Hardware untouched.
    assert state_of(fake_kube) == (STATE_FAILED, "")
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    labels = node_labels(fake_kube.get_node(NODE))
    assert labels.get(CC_FAILED_REASON_LABEL) == "invalid-mode"
    assert "reset" not in [op for op, _ in fake_tpu.op_log]


def test_reset_failure_labels_failed(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    fake_tpu.fail_next("reset")
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_ON) is False
    assert state_of(fake_kube) == (STATE_FAILED, "")


def test_verification_mismatch_labels_failed(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    orig_reset = fake_tpu.reset

    def sabotaged_reset(chips):
        fake_tpu.staged.clear()  # staged mode never lands
        orig_reset(chips)

    fake_tpu.reset = sabotaged_reset
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_ON) is False
    assert state_of(fake_kube) == (STATE_FAILED, "")


def test_attestation_failure_labels_failed(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    fake_tpu.fail_next("attest")
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_ON) is False
    assert state_of(fake_kube) == (STATE_FAILED, "")


def test_devtools_mode_applies(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_DEVTOOLS) is True
    assert state_of(fake_kube) == (MODE_DEVTOOLS, "debug")
    # devtools is backend-visible, not just an attestation-policy flag:
    # the committed runtime env carries the debug flags (labels.py).
    assert fake_tpu.runtime_env.get("TPU_CC_MODE") == MODE_DEVTOOLS
    assert fake_tpu.runtime_env.get("TPU_MIN_LOG_LEVEL") == "0"


def test_eviction_wraps_reconfigure(fake_kube, fake_tpu):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "dp", NODE, labels={"app": DP_APP})

    observed = {}

    def reactor(name, node):
        value = node_labels(node).get(DP_LABEL)
        if is_paused(value):
            observed.setdefault(
                "paused_before_reset",
                "reset" not in [op for op, _ in fake_tpu.op_log],
            )
            fake_kube.delete_pods_matching(NS, f"app={DP_APP}")

    fake_kube.add_patch_reactor(reactor)
    mgr = make_manager(fake_kube, fake_tpu, evict_components=True)
    assert mgr.set_cc_mode(MODE_ON) is True
    # Drain happened before the hardware reset (reference main.py:544-578).
    assert observed.get("paused_before_reset") is True
    # Component label restored afterward.
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "true"


def test_readmit_even_on_failure(fake_kube, fake_tpu):
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_tpu.fail_next("reset")
    mgr = make_manager(fake_kube, fake_tpu, evict_components=True)
    assert mgr.set_cc_mode(MODE_ON) is False
    # Never left paused by a failed toggle.
    assert node_labels(fake_kube.get_node(NODE))[DP_LABEL] == "true"
    assert state_of(fake_kube)[0] == STATE_FAILED


def test_smoke_failure_labels_failed(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)

    def failing_smoke(workload):
        raise RuntimeError("numerics mismatch")

    mgr = make_manager(
        fake_kube, fake_tpu, smoke_workload="matmul", smoke_runner=failing_smoke
    )
    assert mgr.set_cc_mode(MODE_ON) is False
    assert state_of(fake_kube) == (STATE_FAILED, "")


def test_smoke_runner_invoked_with_workload(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    calls = []

    def smoke(workload):
        calls.append(workload)
        return {"ok": True}

    mgr = make_manager(fake_kube, fake_tpu, smoke_workload="matmul", smoke_runner=smoke)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert calls == ["matmul"]


def test_with_default(fake_kube, fake_tpu):
    mgr = make_manager(fake_kube, fake_tpu, default_mode=MODE_ON)
    assert mgr.with_default(None) == MODE_ON
    assert mgr.with_default("") == MODE_ON
    assert mgr.with_default(MODE_OFF) == MODE_OFF
    assert mgr.with_default("ppcie") == MODE_SLICE


def test_escaping_exception_not_recorded_ok(fake_kube, fake_tpu):
    """A KubeApiError escaping mid-drain must not count as a successful
    reconcile in the metrics."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    registry = MetricsRegistry()

    real_list_pods = fake_kube.list_pods

    def exploding_list_pods(*a, **kw):
        raise KubeApiError(500, "apiserver down")

    fake_kube.list_pods = exploding_list_pods
    mgr = make_manager(fake_kube, fake_tpu, evict_components=True, metrics=registry)
    with pytest.raises(KubeApiError):
        mgr.set_cc_mode(MODE_ON)
    fake_kube.list_pods = real_list_pods
    assert registry.last().result == "failed"


def test_phase_metrics_recorded(fake_kube, fake_tpu):
    fake_kube.add_node(NODE)
    registry = MetricsRegistry()
    mgr = make_manager(fake_kube, fake_tpu, metrics=registry)
    mgr.set_cc_mode(MODE_ON)
    m = registry.last()
    assert m is not None and m.result == "ok"
    names = [p.name for p in m.phases]
    assert names == ["stage", "reset", "wait_ready", "attest"]
    text = registry.render_prometheus()
    assert "tpu_cc_reconcile_seconds" in text
    assert 'phase="reset"' in text
    # Cumulative histogram series survive the bounded history: a scraper
    # that misses a reconcile still sees its latency in the totals.
    assert 'tpu_cc_phase_seconds_sum{mode="on",phase="reset"}' in text
    assert 'tpu_cc_phase_seconds_count{mode="on",phase="reset"} 1' in text
    assert (
        'tpu_cc_phase_seconds_bucket{mode="on",phase="reset",le="+Inf"} 1'
        in text
    )
    assert 'tpu_cc_reconciles_total{result="ok"} 1' in text
    mgr.set_cc_mode(MODE_OFF)
    text = registry.render_prometheus()
    assert 'tpu_cc_phase_seconds_count{mode="off",phase="reset"} 1' in text
    assert 'tpu_cc_reconciles_total{result="ok"} 2' in text


def test_strict_eviction_timeout_fails_without_touching_hardware(
    fake_kube, fake_tpu
):
    """CC_STRICT_EVICTION semantics (SURVEY.md §8.5): a drain timeout fails
    the reconcile — 'failed' state, components re-admitted, chips never
    staged/reset — instead of the reference's proceed-anyway."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "stuck", NODE, labels={"app": DP_APP})  # never drains
    mgr = make_manager(
        fake_kube, fake_tpu,
        evict_components=True, strict_eviction=True,
        eviction_timeout_s=0.05,
    )
    assert mgr.set_cc_mode("on") is False
    assert state_of(fake_kube)[0] == "failed"
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[DP_LABEL] == "true"  # re-admitted, not left paused
    assert not [op for op in fake_tpu.op_log if op[0] == "reset"]  # hardware untouched
    for chip in fake_tpu.discover().chips:
        assert fake_tpu.query_cc_mode(chip) == "off"


def test_lenient_eviction_timeout_proceeds(fake_kube, fake_tpu):
    """Default (reference) behavior: timeout warns and proceeds to the
    hardware phase."""
    fake_kube.add_node(NODE, {DP_LABEL: "true"})
    fake_kube.add_pod(NS, "stuck", NODE, labels={"app": DP_APP})
    mgr = make_manager(
        fake_kube, fake_tpu,
        evict_components=True, eviction_timeout_s=0.05,
    )
    assert mgr.set_cc_mode("on") is True
    assert state_of(fake_kube)[0] == "on"


def test_events_emitted_on_success_and_failure(fake_kube, fake_tpu):
    """Reconcile outcomes surface as core/v1 Events on the node (kubectl
    describe node visibility; the reference's only outward signals are
    labels and a file)."""
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert [(e["type"], e["reason"]) for e in fake_kube.events] == [
        ("Normal", "CCModeApplied")
    ]
    ev = fake_kube.events[0]
    assert ev["involvedObject"] == {
        "kind": "Node", "name": NODE, "apiVersion": "v1"
    }
    # Cluster-scoped involvedObject => the apiserver only accepts events
    # in the "default" namespace.
    assert ev["namespace"] == "default"

    fake_tpu.fail_next("reset")
    assert mgr.set_cc_mode(MODE_OFF) is False
    assert [(e["type"], e["reason"]) for e in fake_kube.events][-1] == (
        "Warning", "CCModeFailed"
    )


def test_events_deduplicated_across_retries(fake_kube):
    """A retry loop re-failing identically must not spam the event stream;
    a CHANGED outcome emits again."""
    backend = FakeTpuBackend(slice_cc_supported=False)
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, backend)
    assert mgr.set_cc_mode(MODE_SLICE) is False
    assert mgr.set_cc_mode(MODE_SLICE) is False  # identical re-fail
    assert len(fake_kube.events) == 1
    assert fake_kube.events[0]["reason"] == "CCModeUnsupported"
    # Recovery is a different outcome: emitted.
    assert mgr.set_cc_mode(MODE_ON) is True
    assert [e["reason"] for e in fake_kube.events] == [
        "CCModeUnsupported", "CCModeApplied"
    ]


def test_event_emission_failure_is_nonfatal(fake_kube, fake_tpu):
    """A client without event support (KubeApi default raises) must not
    fail the reconcile."""
    from tpu_cc_manager.kubeclient.api import KubeApiError

    def no_events(namespace, event):
        raise KubeApiError(403, "events forbidden")

    fake_kube.create_event = no_events
    fake_kube.add_node(NODE)
    mgr = make_manager(fake_kube, fake_tpu)
    assert mgr.set_cc_mode(MODE_ON) is True
    assert state_of(fake_kube) == (MODE_ON, "true")


def test_metrics_server_binds_configured_interface():
    """The unauthenticated metrics endpoint honors an explicit bind
    (VERDICT r3 weak #7: it previously hardcoded 0.0.0.0)."""
    import urllib.request

    from tpu_cc_manager.ccmanager.metrics_server import start_metrics_server

    registry = MetricsRegistry()
    server = start_metrics_server(0, registry, bind="127.0.0.1")
    try:
        host, port = server.server_address
        assert host == "127.0.0.1"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            assert r.status == 200
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
            assert b"tpu_cc" in r.read()
    finally:
        server.shutdown()
        server.server_close()
