"""Stateful property test of the cross-host slice commit barrier.

The invariant the barrier exists for (slicecoord.py; the reference's
fabric-atomic PPCIe stage-all/reset-all at main.py:362-368, stretched
across machines): **no host of a slice may pass the barrier — i.e. be
allowed to reset its runtime — unless every host of the slice is staged
for the mode or has already committed it.**

Hypothesis drives interleavings of: hosts staging, hosts polling the
barrier (one bounded poll per step — await_commit with a tiny timeout is
a non-blocking "try"), hosts aborting (re-admit path), and hosts crashing
and restarting mid-barrier (markers survive, in-memory state doesn't).
At every successful barrier passage the invariant is checked against the
apiserver's label state at that instant — the orderings explored include
the crash/abort races the hand-written tests (test_slicecoord.py) pin
individually.
"""

from __future__ import annotations

import pytest

pytest.importorskip("hypothesis")  # optional dep; skip the whole stateful module without it

import hypothesis.strategies as st
from hypothesis import settings
from hypothesis.stateful import (
    RuleBasedStateMachine,
    initialize,
    invariant,
    rule,
)

from tpu_cc_manager.ccmanager.slicecoord import (
    SLICE_COMMIT_LABEL,
    SLICE_STAGED_LABEL,
    BarrierTimeout,
    SliceBarrier,
)
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import CC_MODE_STATE_LABEL, SLICE_ID_LABEL
from tpu_cc_manager.tpudev.contract import SliceTopology

MODE = "slice"
N_HOSTS = 3
NAMES = [f"sb-node-{i}" for i in range(N_HOSTS)]


def _barrier(kube: FakeKube, host: int) -> SliceBarrier:
    topo = SliceTopology(
        slice_id="prop-slice",
        accelerator_type="v5p-64",
        num_hosts=N_HOSTS,
        host_index=host,
        chips=(),
    )
    # Tiny timeouts: await_commit becomes a single poll ("try"), and
    # complete() never stalls the machine.
    return SliceBarrier(
        kube, NAMES[host], topo,
        timeout_s=0.0, poll_interval_s=0.0, complete_timeout_s=0.0,
    )


class BarrierMachine(RuleBasedStateMachine):
    @initialize()
    def setup(self) -> None:
        self.kube = FakeKube()
        for name in NAMES:
            self.kube.add_node(name, {SLICE_ID_LABEL: "prop-slice"})
        self.barriers = [_barrier(self.kube, i) for i in range(N_HOSTS)]
        self.staged: set[int] = set()     # hosts whose marker we published
        self.committed: set[int] = set()  # hosts that passed the barrier

    # ---- actions ---------------------------------------------------------

    hosts = st.integers(0, N_HOSTS - 1)

    @rule(host=hosts)
    def stage(self, host: int) -> None:
        if host in self.committed:
            return  # this round is over for that host
        self.barriers[host].publish_staged(MODE)
        self.staged.add(host)

    @rule(host=hosts)
    def try_barrier(self, host: int) -> None:
        """One bounded barrier poll; passage must respect the invariant."""
        if host not in self.staged or host in self.committed:
            return
        # Snapshot BEFORE passage decides: what the barrier saw.
        snapshot = {
            name: node_labels(self.kube.get_node(name)) for name in NAMES
        }
        try:
            self.barriers[host].await_commit(MODE)
        except BarrierTimeout:
            return  # not yet — peers missing; keep exploring
        # PASSED: every host must have been staged-or-committed. This is
        # the fabric-atomicity theorem under test.
        for name, labels in snapshot.items():
            assert (
                labels.get(SLICE_STAGED_LABEL) == MODE
                or labels.get(CC_MODE_STATE_LABEL) == MODE
            ), (
                f"host {host} passed the barrier while {name} was neither "
                f"staged nor committed: {labels}"
            )
        # Emulate the manager's post-barrier tail: reset happens here, the
        # state label publishes the new truth, the staged marker retires.
        self.committed.add(host)
        self.kube.patch_node_labels(
            NAMES[host], {CC_MODE_STATE_LABEL: MODE}
        )
        self.barriers[host].complete(MODE)

    @rule(host=hosts)
    def abort(self, host: int) -> None:
        """Re-admit path: drain failed / barrier timed out upstream."""
        if host in self.committed or host not in self.staged:
            return
        self.barriers[host].abort()
        self.staged.discard(host)

    @rule(host=hosts)
    def crash_restart(self, host: int) -> None:
        """Agent dies mid-barrier: labels survive, memory doesn't. The
        restarted agent re-enters the barrier by re-staging (the apply
        re-runs idempotently)."""
        if host in self.committed:
            return
        self.barriers[host] = _barrier(self.kube, host)
        if host in self.staged:
            self.barriers[host].publish_staged(MODE)

    # ---- invariants ------------------------------------------------------

    @invariant()
    def commit_marker_only_with_full_staging_history(self) -> None:
        """A commit marker for MODE implies the leader passed the barrier,
        which implies every host was ready at that instant — so at least
        the leader must be in committed (the marker is never the leader's
        first move)."""
        if not hasattr(self, "kube"):
            return
        labels = node_labels(self.kube.get_node(NAMES[0]))
        if labels.get(SLICE_COMMIT_LABEL) == MODE:
            assert 0 in self.committed, (
                "leader's commit marker exists but the leader never "
                "passed the barrier"
            )

    @invariant()
    def no_partial_fabric_after_quiescence(self) -> None:
        """Whoever committed, committed the same mode the others will —
        there is only one mode per machine run, so the check is that a
        committed host's state label survives (nothing un-commits it)."""
        if not hasattr(self, "kube"):
            return
        for host in self.committed:
            labels = node_labels(self.kube.get_node(NAMES[host]))
            assert labels.get(CC_MODE_STATE_LABEL) == MODE


TestBarrierMachine = BarrierMachine.TestCase
TestBarrierMachine.settings = settings(
    max_examples=40, stateful_step_count=25, deadline=None
)


def test_machine_rules_can_reach_full_commit():
    """Anti-vacuity: the machine's own rules, driven in the happy order,
    commit every host — so the invariant assertions in try_barrier are
    exercised on real passages, not only on timeouts."""
    m = BarrierMachine()
    m.setup()
    for host in range(N_HOSTS):
        m.stage(host)
    m.try_barrier(0)          # leader publishes the commit marker
    assert m.committed == {0}
    for host in range(1, N_HOSTS):
        m.try_barrier(host)   # followers see marker (or committed peers)
    assert m.committed == set(range(N_HOSTS))
    m.commit_marker_only_with_full_staging_history()
    m.no_partial_fabric_after_quiescence()


def test_machine_blocks_follower_when_a_peer_aborts():
    """Anti-vacuity for the refusal path: after an abort the remaining
    hosts cannot pass (BarrierTimeout swallowed → no commit recorded)."""
    m = BarrierMachine()
    m.setup()
    m.stage(0)
    m.stage(1)
    m.stage(2)
    m.abort(2)                # host 2 re-admits; no longer staged
    m.try_barrier(0)
    m.try_barrier(1)
    assert m.committed == set()
