"""HF → flax Llama weight conversion: logits parity against transformers.

The strongest possible oracle: a randomly-initialized tiny HF
``LlamaForCausalLM`` and our ``LlamaModel`` loaded with the converted
weights must produce (near-)identical logits on the same tokens. Catches
transposition, head-ordering, RoPE-convention and norm-placement mistakes
in one assert.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from tpu_cc_manager.models.convert import (  # noqa: E402
    config_from_hf,
    hf_state_dict_to_params,
)


def _tiny_hf_model():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        attn_implementation="eager",
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(hf_cfg).eval(), hf_cfg


def test_logits_match_transformers():
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaModel

    hf_model, hf_cfg = _tiny_hf_model()
    cfg = config_from_hf(hf_cfg)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32})
    variables = hf_state_dict_to_params(hf_model.state_dict(), cfg)

    tokens = np.array([[1, 5, 9, 42, 7, 99, 3, 11]], dtype=np.int32)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens).long()).logits.numpy()

    ours, _ = LlamaModel(cfg).apply(variables, jnp.asarray(tokens))
    ours = np.asarray(ours)

    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)
    # Greedy decode paths must agree exactly.
    assert (ours.argmax(-1) == ref.argmax(-1)).all()


def test_llama3_rope_scaling_parity():
    """Llama-3.1-style rope_scaling must be carried into our RoPE phases;
    logits parity with transformers is the oracle."""
    import jax.numpy as jnp

    from tpu_cc_manager.models.convert import config_from_hf
    from tpu_cc_manager.models.llama import LlamaModel

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        attn_implementation="eager",
        tie_word_embeddings=False,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 16,
        },
    )
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 16)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32})
    variables = hf_state_dict_to_params(hf_model.state_dict(), cfg)

    # Longer than original_max_position_embeddings so scaling matters.
    tokens = np.arange(1, 33, dtype=np.int32)[None, :] % 128
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens).long()).logits.numpy()
    ours, _ = LlamaModel(cfg).apply(variables, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4, rtol=2e-3)


def test_unsupported_rope_scaling_rejected():
    from tpu_cc_manager.models.convert import config_from_hf

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        rope_scaling={"rope_type": "yarn", "factor": 4.0},
    )
    with pytest.raises(NotImplementedError):
        config_from_hf(hf_cfg)


def test_gqa_and_tied_embeddings_roundtrip():
    """Tied lm_head falls back to embed_tokens; shapes land stacked."""
    import jax.numpy as jnp

    from tpu_cc_manager.models.llama import LlamaModel

    hf_model, hf_cfg = _tiny_hf_model()
    cfg = config_from_hf(hf_cfg)
    sd = {k: v for k, v in hf_model.state_dict().items() if k != "lm_head.weight"}
    variables = hf_state_dict_to_params(sd, cfg)
    p = variables["params"]
    assert p["blocks"]["attn"]["wq"]["kernel"].shape == (2, 64, 64)
    assert p["blocks"]["attn"]["wk"]["kernel"].shape == (2, 64, 32)  # GQA kv
    assert p["lm_head"].shape == (64, 128)
    np.testing.assert_array_equal(p["lm_head"], p["embedding"].T)

    logits, _ = LlamaModel(cfg).apply(
        variables, jnp.asarray([[1, 2, 3, 4]], dtype=jnp.int32)
    )
    assert np.isfinite(np.asarray(logits)).all()
