"""The node-local intent WAL (ccmanager/intent_journal.py) and the
manager's disconnected-mode / boot-recovery integration.

Covers, in order:

- journal mechanics: framed append/replay roundtrip, torn-tail
  truncation, pending-patch merge, compaction;
- the corruption fuzz property: truncating, bit-flipping, or duplicating
  records at EVERY byte offset of a valid journal either recovers a
  consistent prefix or fails closed (JournalCorrupt) — never a
  half-applied view;
- replay recovery decisions: complete (hardware holds the mode, no
  second reset), roll back (crash before reset clears the staging),
  reset-incomplete (backend crash markers force a clean re-apply);
- boot ordering: journal → hardware truth → apiserver, with the
  stale-first-read guard (regression: a blackout ending mid-boot serves
  one stale label and must not trigger a spurious transition);
- disconnected mode: engaged-outage state reports defer into the
  journal, flush idempotently (RMW) on reconnect, and the watchdog's
  condemn-while-offline rides the same path.
"""

from __future__ import annotations

import threading
import time

import pytest

from tpu_cc_manager.ccmanager import intent_journal as ij
from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.watchdog import RuntimeHealthWatchdog
from tpu_cc_manager.kubeclient.api import KubeApiError, node_labels
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    MODE_DEVTOOLS,
    MODE_OFF,
    MODE_ON,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "journal-node-0"


# ---------------------------------------------------------------------------
# Journal mechanics
# ---------------------------------------------------------------------------


def make_journal(tmp_path, **kwargs) -> ij.IntentJournal:
    return ij.IntentJournal.from_state_dir(str(tmp_path), **kwargs)


def test_append_replay_roundtrip(tmp_path):
    j = make_journal(tmp_path)
    txn = j.begin("transition", mode="on", chips=[0, 1, 2, 3])
    j.mark(txn, ij.PHASE_STAGED)
    j.note_desired("on")
    j.defer_patch({"a": "1"})
    j.commit(txn)

    j2 = make_journal(tmp_path)
    replay = j2.replay()
    assert replay.truncated_bytes == 0
    assert [r["t"] for r in replay.records] == [
        "intent", "mark", "desired", "patch", "commit",
    ]
    assert j2.open_intents() == []
    assert j2.last_desired_mode == "on"
    assert j2.pending_patches() == {"a": "1"}
    # Sequence numbers are strictly increasing and survive the reload.
    seqs = [r["seq"] for r in replay.records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    j2.patches_flushed()
    assert not j2.has_pending_patches()


def test_torn_tail_is_truncated_and_replay_is_stable(tmp_path):
    j = make_journal(tmp_path)
    t1 = j.begin("transition", mode="on", chips=[0])
    j.commit(t1)
    j.begin("transition", mode="off", chips=[0])
    with open(j.path, "ab") as f:
        f.write(b"TCCJ1 deadbeef {\"seq\": 99, \"t\": \"commit\"")  # torn

    j2 = make_journal(tmp_path)
    replay = j2.replay()
    assert replay.truncated_bytes > 0
    assert len(replay.records) == 3
    assert len(j2.open_intents("transition")) == 1
    # The file was physically truncated: a second replay sees a clean log.
    j3 = make_journal(tmp_path)
    replay2 = j3.replay()
    assert replay2.truncated_bytes == 0
    assert [r["seq"] for r in replay2.records] == [
        r["seq"] for r in replay.records
    ]


def test_pending_patches_merge_in_order_and_flush_marker(tmp_path):
    j = make_journal(tmp_path)
    j.defer_patch({"k": "old", "x": "1"})
    j.defer_patch({"k": "new", "y": None})
    assert j.pending_patches() == {"k": "new", "x": "1", "y": None}
    j.patches_flushed()
    assert j.pending_patches() == {}
    j.defer_patch({"z": "2"})
    # Only post-flush patches survive a reload.
    j2 = make_journal(tmp_path)
    j2.replay()
    assert j2.pending_patches() == {"z": "2"}


def test_compaction_preserves_live_state(tmp_path):
    j = make_journal(tmp_path, max_bytes=1)  # force compaction on close
    keep = j.begin("transition", mode="on", chips=[0])
    j.mark(keep, ij.PHASE_RESET)
    j.note_desired("on")
    j.defer_patch({"a": "1"})
    done = j.begin("drain", mode="on")
    j.commit(done)  # commit, abort and flush all trigger compaction
    j.patches_flushed()
    j.defer_patch({"b": "2"})
    gone = j.begin("transition", mode="off", chips=[0])
    j.abort(gone)  # triggers the size-based compaction

    j2 = make_journal(tmp_path)
    j2.replay()
    opens = j2.open_intents()
    assert [i["txn"] for i in opens] == [keep]
    assert opens[0]["phase"] == ij.PHASE_RESET
    assert j2.last_desired_mode == "on"
    assert j2.pending_patches() == {"b": "2"}


def test_kill_between_readmit_start_and_close_replays_open_drain(tmp_path):
    """Kill-at-PHASE_READMIT: the manager marks the drain intent
    PHASE_READMIT when re-admission STARTS (_ReadmitOnce on_start) and
    closes it only after readmit succeeded — so a SIGKILL in between
    must replay to an OPEN drain intent at phase readmit (the successor
    re-runs the idempotent readmit), and the successful close retires
    it."""
    j = make_journal(tmp_path)
    dtxn = j.begin("drain", mode="on")
    j.mark(dtxn, ij.PHASE_READMIT)
    # modeled SIGKILL here: no commit reaches the journal
    j2 = make_journal(tmp_path)
    j2.replay()
    opens = j2.open_intents()
    assert [i["kind"] for i in opens] == ["drain"]
    assert opens[0]["phase"] == ij.PHASE_READMIT
    # The successor's successful readmit closes every recovered drain.
    j2.close_open("drain", recovered="readmitted")
    j3 = make_journal(tmp_path)
    j3.replay()
    assert j3.open_intents() == []


def test_newline_less_tail_is_torn_even_when_crc_verifies(tmp_path):
    """A crash that cuts the final append exactly one byte short (frame
    minus the trailing newline) leaves a CRC-valid fragment. Replay must
    treat it as a torn tail — accepting it would leave the file ending
    mid-line, the next append would glue onto it, and the replay after
    THAT would fail closed over a benign torn write."""
    j = make_journal(tmp_path)
    t1 = j.begin("transition", mode="on", chips=[0])
    j.mark(t1, ij.PHASE_STAGED)
    with open(j.path, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 1)  # eat ONLY the final newline

    j2 = make_journal(tmp_path)
    replay = j2.replay()
    # The newline-less mark record is torn tail: dropped and truncated.
    assert replay.truncated_bytes > 0
    assert [r["t"] for r in replay.records] == ["intent"]
    assert j2.open_intents()[0]["phase"] == ij.PHASE_BEGUN
    # The survivor appends cleanly and the NEXT replay must not fail
    # closed (the regression this guards: record glued onto the tail).
    j2.mark(t1, ij.PHASE_RESET)
    j3 = make_journal(tmp_path)
    replay3 = j3.replay()
    assert replay3.truncated_bytes == 0
    assert [r["t"] for r in replay3.records] == ["intent", "mark"]
    assert j3.open_intents()[0]["phase"] == ij.PHASE_RESET


def test_commits_alone_trigger_compaction(tmp_path):
    """The healthy success path (begin/mark/commit, no aborts, no
    deferred patches) must still bound the file: compaction fires from
    commit as well."""
    import os

    j = make_journal(tmp_path, max_bytes=2048)
    for _ in range(200):
        txn = j.begin("transition", mode="on", chips=[0, 1, 2, 3])
        j.mark(txn, ij.PHASE_STAGED)
        j.mark(txn, ij.PHASE_RESET)
        j.commit(txn)
    # One full transition (~4 records) can land between compactions, so
    # the bound is max_bytes plus a handful of records, not unbounded.
    assert os.path.getsize(j.path) < 4096
    assert j.open_intents() == []


def test_disk_fault_rolls_back_seq_and_raises(tmp_path):
    j = make_journal(tmp_path)
    j.note_desired("on")
    j.fail_appends = 1
    with pytest.raises(ij.JournalError):
        j.defer_patch({"a": "1"})
    # The failed append left no trace: the next record lands cleanly.
    j.defer_patch({"b": "2"})
    j2 = make_journal(tmp_path)
    replay = j2.replay()
    assert [r["t"] for r in replay.records] == ["desired", "patch"]
    assert j2.pending_patches() == {"b": "2"}


# ---------------------------------------------------------------------------
# Corruption fuzz: prefix-or-fail-closed at every byte offset
# ---------------------------------------------------------------------------


def _valid_journal_bytes(tmp_path):
    j = make_journal(tmp_path / "seed")
    t1 = j.begin("transition", mode="on", chips=[0, 1])
    j.mark(t1, ij.PHASE_STAGED)
    j.mark(t1, ij.PHASE_RESET)
    j.commit(t1)
    j.note_desired("on")
    j.defer_patch({CC_MODE_STATE_LABEL: "on"})
    t2 = j.begin("drain", mode="devtools")
    j.abort(t2)
    with open(j.path, "rb") as f:
        data = f.read()
    j2 = make_journal(tmp_path / "seed")
    original = [tuple(sorted(r.items())) for r in j2.replay().records]
    return data, original


def _replay_mutant(tmp_path, name, data):
    d = tmp_path / name
    d.mkdir()
    j = ij.IntentJournal.from_state_dir(str(d))
    with open(j.path, "wb") as f:
        f.write(data)
    return j


def _assert_prefix_or_fail_closed(j, original, what):
    """The fuzz property: replay yields a prefix of the original record
    list, or raises JournalCorrupt — never a record the original journal
    did not contain, never a reordered/half view."""
    try:
        replay = j.replay()
    except ij.JournalCorrupt:
        return "failed-closed"
    got = [tuple(sorted(r.items())) for r in replay.records]
    assert got == original[: len(got)], f"{what}: not a consistent prefix"
    return "prefix"


def test_fuzz_truncation_at_every_byte_offset(tmp_path):
    data, original = _valid_journal_bytes(tmp_path)
    outcomes = set()
    for cut in range(len(data)):
        j = _replay_mutant(tmp_path, f"trunc{cut}", data[:cut])
        outcomes.add(
            _assert_prefix_or_fail_closed(j, original, f"truncate@{cut}")
        )
    # Truncation is always a torn tail — it must never fail closed.
    assert outcomes == {"prefix"}


def test_fuzz_bitflip_at_every_byte_offset(tmp_path):
    data, original = _valid_journal_bytes(tmp_path)
    outcomes = set()
    for pos in range(len(data)):
        flipped = bytearray(data)
        flipped[pos] ^= 0x40
        j = _replay_mutant(tmp_path, f"flip{pos}", bytes(flipped))
        outcomes.add(
            _assert_prefix_or_fail_closed(j, original, f"bitflip@{pos}")
        )
    # Mid-file flips fail closed; tail flips recover the prefix. Both
    # must occur across the sweep or the property isn't being exercised.
    assert outcomes == {"prefix", "failed-closed"}


def test_fuzz_duplicated_records(tmp_path):
    data, original = _valid_journal_bytes(tmp_path)
    lines = data.split(b"\n")[:-1]
    for i in range(len(lines)):
        for j_pos in range(len(lines) + 1):
            mutated = lines[:j_pos] + [lines[i]] + lines[j_pos:]
            j = _replay_mutant(
                tmp_path, f"dup{i}at{j_pos}",
                b"\n".join(mutated) + b"\n",
            )
            _assert_prefix_or_fail_closed(
                j, original, f"duplicate record {i} at {j_pos}"
            )


def test_failed_closed_journal_is_quarantined_and_feeds_the_ladder(
    fake_kube, tmp_path,
):
    """Mid-file corruption → JournalCorrupt → the manager fails closed:
    the remediation ladder is fed (reason journal-corrupt), the corrupt
    file is moved aside, and the metric counts the outcome."""
    data, _ = _valid_journal_bytes(tmp_path)
    flipped = bytearray(data)
    flipped[10] ^= 0xFF  # first record's frame: verifiable data follows
    j = _replay_mutant(tmp_path, "corrupt", bytes(flipped))

    fed = []

    class Ladder:
        quarantined = False

        def note_failure(self, reason):
            fed.append(reason)

    registry = MetricsRegistry()
    fake_kube.add_node(NODE)
    mgr = CCManager(
        api=fake_kube, backend=FakeTpuBackend(), node_name=NODE,
        evict_components=False, smoke_workload="none",
        metrics=registry, intent_journal=j, remediation=Ladder(),
        readiness_file=str(tmp_path / "ready"),
    )
    mgr.recover_from_journal()
    assert fed == ["journal-corrupt"]
    assert registry.journal_replay_totals() == {"failed-closed": 1}
    import os

    assert os.path.exists(j.path + ".corrupt")
    assert not os.path.exists(j.path)


# ---------------------------------------------------------------------------
# Replay recovery decisions against hardware truth
# ---------------------------------------------------------------------------


def make_manager(fake_kube, backend, tmp_path, **kwargs):
    kwargs.setdefault("metrics", MetricsRegistry())
    kwargs.setdefault("intent_journal", make_journal(tmp_path))
    return CCManager(
        api=kwargs.pop("api", fake_kube),
        backend=backend,
        node_name=NODE,
        default_mode=MODE_OFF,
        evict_components=kwargs.pop("evict_components", False),
        smoke_workload="none",
        watch_timeout_s=1,
        reconnect_delay_s=0.01,
        retry_backoff_s=0.02,
        retry_backoff_max_s=0.2,
        readiness_file=str(tmp_path / "ready"),
        **kwargs,
    )


def test_reconcile_journals_intent_then_commit(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    j = make_journal(tmp_path)
    mgr = make_manager(fake_kube, backend, tmp_path, intent_journal=j)
    assert mgr.set_cc_mode(MODE_ON)
    assert j.open_intents() == []
    assert j.last_desired_mode == MODE_ON
    kinds = [r["t"] for r in make_journal(tmp_path).replay().records]
    assert "intent" in kinds and "commit" in kinds


def test_replay_rolls_back_a_pre_reset_crash_without_any_reset(
    fake_kube, tmp_path,
):
    """Crash after stage, before reset: replay clears the staging and
    aborts the intent — the chips were never disrupted and must not be."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    j = make_journal(tmp_path)
    txn = j.begin(
        "transition", mode=MODE_ON, chips=[c.index for c in backend.discover().chips],
    )
    j.mark(txn, ij.PHASE_STAGED)
    backend.stage_cc_mode(backend.discover().chips, MODE_ON)

    registry = MetricsRegistry()
    j2 = make_journal(tmp_path)
    mgr = make_manager(
        fake_kube, backend, tmp_path, intent_journal=j2, metrics=registry,
    )
    mgr.recover_from_journal()
    assert j2.open_intents() == []
    assert backend.staged == {}  # rolled back
    assert all(m == MODE_OFF for m in backend.committed.values())
    assert not any(op == "reset" for op, _ in backend.op_log)
    assert registry.journal_replay_totals() == {"rolled-back": 1}


def test_replay_completes_a_committed_reset_without_a_second_reset(
    fake_kube, tmp_path,
):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    chips = backend.discover().chips
    j = make_journal(tmp_path)
    txn = j.begin("transition", mode=MODE_ON, chips=[c.index for c in chips])
    j.mark(txn, ij.PHASE_RESET)
    backend.stage_cc_mode(chips, MODE_ON)
    backend.reset(chips)  # the reset landed; the crash ate the commit
    resets = sum(1 for op, _ in backend.op_log if op == "reset")

    registry = MetricsRegistry()
    j2 = make_journal(tmp_path)
    mgr = make_manager(
        fake_kube, backend, tmp_path, intent_journal=j2, metrics=registry,
    )
    mgr.recover_from_journal()
    assert j2.open_intents() == []
    assert registry.journal_replay_totals() == {"completed": 1}
    assert sum(1 for op, _ in backend.op_log if op == "reset") == resets
    # Connected at replay time → the truthful state lands immediately.
    assert node_labels(fake_kube.get_node(NODE))[
        CC_MODE_STATE_LABEL
    ] == MODE_ON


def test_replay_restores_stranded_paused_components(fake_kube, tmp_path):
    """An open drain intent (crash between pause and readmit) re-admits
    the paused components at replay time when the apiserver answers."""
    dp = "google.com/tpu.deploy.device-plugin"
    fake_kube.add_node(NODE, {dp: "true"})
    from tpu_cc_manager.drain.pause import pause_value

    fake_kube.set_node_label(NODE, dp, pause_value("true"))
    j = make_journal(tmp_path)
    j.begin("drain", mode=MODE_ON)

    backend = FakeTpuBackend()
    j2 = make_journal(tmp_path)
    mgr = make_manager(fake_kube, backend, tmp_path, intent_journal=j2)
    mgr.recover_from_journal()
    assert node_labels(fake_kube.get_node(NODE))[dp] == "true"
    assert j2.open_intents("drain") == []


# ---------------------------------------------------------------------------
# Boot ordering: journal → hardware truth → apiserver
# ---------------------------------------------------------------------------


class StaleThenLiveKube:
    """Wrapper modeling a blackout ending mid-boot: the FIRST get_node
    serves a stale snapshot (an old desired label), later reads serve the
    live store. Every other verb passes through."""

    def __init__(self, inner, stale_node):
        self.inner = inner
        self._stale = stale_node
        self.stale_reads = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def get_node(self, name):
        if self._stale is not None:
            self.stale_reads += 1
            node, self._stale = self._stale, None
            return node
        return self.inner.get_node(name)


def test_stale_boot_read_cannot_trigger_a_spurious_transition(
    fake_kube, tmp_path,
):
    """Regression (ISSUE 5 satellite): the agent converged to devtools,
    crashed, and boots through a flaky apiserver whose first answer is a
    STALE node (desired=on, from before the last transition). Boot-time
    ordering journal → hardware → apiserver must confirm the read before
    acting: the node must NOT bounce through a spurious transition to the
    stale mode."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    j = make_journal(tmp_path)
    mgr1 = make_manager(fake_kube, backend, tmp_path, intent_journal=j)
    fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
    assert mgr1.set_cc_mode(MODE_ON)
    stale_node = fake_kube.get_node(NODE)  # desired=on, about to go stale
    fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_DEVTOOLS)
    assert mgr1.set_cc_mode(MODE_DEVTOOLS)
    resets = sum(1 for op, _ in backend.op_log if op == "reset")

    api = StaleThenLiveKube(fake_kube, stale_node)
    j2 = make_journal(tmp_path)
    mgr2 = make_manager(fake_kube, backend, tmp_path, api=api, intent_journal=j2)
    mgr2.recover_from_journal()
    label, rv = mgr2._startup_mode_read()
    # The stale read was served and DISAGREED with the journal; the
    # confirming read returned the live value, which won.
    assert api.stale_reads == 1
    assert label == MODE_DEVTOOLS
    assert mgr2.set_cc_mode(mgr2.with_default(label))
    # Idempotent: the stale 'on' never caused a transition.
    assert sum(1 for op, _ in backend.op_log if op == "reset") == resets
    assert all(m == MODE_DEVTOOLS for m in backend.committed.values())


def test_boot_without_local_truth_keeps_crash_as_retry(fake_kube, tmp_path):
    """A fresh node (empty journal, no last-known mode) keeps the
    reference's fatal startup GET — autonomy needs local truth."""
    class DeadKube:
        def __getattr__(self, name):
            def dead(*a, **k):
                raise KubeApiError(None, "connection refused")
            return dead

    backend = FakeTpuBackend()
    mgr = make_manager(fake_kube, backend, tmp_path, api=DeadKube())
    with pytest.raises(KubeApiError):
        mgr._startup_mode_read()


def test_confirm_read_api_error_is_fatal_and_outage_waits_the_ladder(
    fake_kube, tmp_path,
):
    """The confirming read keeps the first read's semantics: a server
    that ANSWERED with an error (403, not a transport failure) is fatal,
    and an outage error waits out the jittered ladder instead of
    busy-looping read pairs against a flapping apiserver."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    j = make_journal(tmp_path)
    j.note_desired(MODE_DEVTOOLS)  # disagrees with the label below
    fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)

    class FlakyConfirmKube:
        def __init__(self, inner, error):
            self.inner = inner
            self.error = error
            self.reads = 0

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def get_node(self, name):
            self.reads += 1
            if self.reads > 1:
                raise self.error
            return self.inner.get_node(name)

    # Answered error on the confirm read: fatal, like the first read.
    api = FlakyConfirmKube(fake_kube, KubeApiError(403, "forbidden"))
    mgr = make_manager(fake_kube, backend, tmp_path, api=api, intent_journal=j)
    with pytest.raises(KubeApiError):
        mgr._startup_mode_read()
    assert api.reads == 2

    # Outage error on the confirm read: ladder wait, not a hot loop —
    # bounded read count over the window, clean exit on stop.
    j2 = make_journal(tmp_path)
    j2.replay()
    api2 = FlakyConfirmKube(fake_kube, KubeApiError(None, "conn reset"))
    mgr2 = make_manager(
        fake_kube, backend, tmp_path, api=api2, intent_journal=j2,
    )
    mgr2._reconnect_policy = mgr2._reconnect_policy.__class__(
        base_delay_s=0.05, max_delay_s=0.05, jitter=False,
    )
    stop = threading.Event()
    result = {}
    t = threading.Thread(
        target=lambda: result.update(read=mgr2._startup_mode_read(stop)),
        daemon=True,
    )
    t.start()
    time.sleep(0.2)  # cclint: test-sleep-ok(negative assertion: the read must STILL be parked after this window)
    assert t.is_alive()
    stop.set()
    t.join(timeout=5)
    assert not t.is_alive()
    assert result["read"] is None
    # ~0.2 s at >=0.05 s per failed confirm: a busy loop would have read
    # hundreds of times.
    assert api2.reads <= 20


def test_boot_waits_out_outage_with_local_truth(fake_kube, tmp_path):
    """With a journaled desired mode, a dark apiserver at boot is ridden
    out (retry loop) instead of crashing; stop exits cleanly."""
    j = make_journal(tmp_path)
    j.note_desired(MODE_ON)

    class DeadKube:
        def __getattr__(self, name):
            def dead(*a, **k):
                raise KubeApiError(None, "connection refused")
            return dead

    j2 = make_journal(tmp_path)
    j2.replay()
    backend = FakeTpuBackend()
    mgr = make_manager(
        fake_kube, backend, tmp_path, api=DeadKube(), intent_journal=j2,
    )
    stop = threading.Event()
    result = {}

    def boot():
        result["read"] = mgr._startup_mode_read(stop)

    t = threading.Thread(target=boot, daemon=True)
    t.start()
    time.sleep(0.15)  # cclint: test-sleep-ok(negative assertion: boot must STILL be riding out the outage)
    assert t.is_alive(), "boot must ride out the outage, not crash"
    stop.set()
    t.join(timeout=5)
    assert not t.is_alive()
    assert result["read"] is None


# ---------------------------------------------------------------------------
# Disconnected mode: deferral + idempotent flush + watchdog condemn
# ---------------------------------------------------------------------------


class BlackoutKube:
    """Pass-through wrapper with a manual blackout switch."""

    def __init__(self, inner):
        self.inner = inner
        self.dark = False

    def __getattr__(self, name):
        inner_fn = getattr(self.inner, name)

        def call(*a, **k):
            if self.dark:
                raise KubeApiError(None, "blackout")
            return inner_fn(*a, **k)

        return call


def engaged_offline_manager(fake_kube, backend, tmp_path, **kwargs):
    api = BlackoutKube(fake_kube)
    mgr = make_manager(
        fake_kube, backend, tmp_path, api=api,
        offline_grace_s=0.01, **kwargs,
    )
    api.dark = True
    mgr.offline.note_failure()
    time.sleep(0.02)  # cclint: test-sleep-ok(must outlast the real-clock offline grace window)
    assert mgr.offline.engaged
    return api, mgr


def test_engaged_outage_defers_state_reports_and_flushes_rmw(
    fake_kube, tmp_path,
):
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    api, mgr = engaged_offline_manager(
        fake_kube, backend, tmp_path, metrics=registry,
    )
    # The reconcile succeeds against hardware; the state report defers.
    assert mgr.set_cc_mode(MODE_ON)
    assert all(m == MODE_ON for m in backend.committed.values())
    pending = mgr.intents.pending_patches()
    assert pending[CC_MODE_STATE_LABEL] == MODE_ON
    assert pending[CC_READY_STATE_LABEL] == "true"
    assert CC_MODE_STATE_LABEL not in node_labels(fake_kube.get_node(NODE))

    # Reconnect: the flush is RMW — a key some other writer already
    # landed is not re-patched (no blind replay), missing keys are.
    fake_kube.set_node_label(NODE, CC_READY_STATE_LABEL, "true")
    api.dark = False
    patches_before = fake_kube.patch_calls
    mgr._note_api_ok()
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON
    assert labels[CC_READY_STATE_LABEL] == "true"
    assert not mgr.intents.has_pending_patches()
    assert fake_kube.patch_calls == patches_before + 1
    # A second reconnect edge flushes nothing (idempotent).
    mgr._note_api_ok()
    assert fake_kube.patch_calls == patches_before + 1
    assert registry.journal_replay_totals() == {}


def test_flush_preserves_order_of_conflicting_deferred_writes(
    fake_kube, tmp_path,
):
    """Journal order is flush order: a later deferred demote (ready=false)
    beats the earlier deferred ready=true from the same outage."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    api, mgr = engaged_offline_manager(fake_kube, backend, tmp_path)
    assert mgr.set_cc_mode(MODE_ON)
    assert mgr.defer_patch_if_offline(
        {CC_READY_STATE_LABEL: "false"}, KubeApiError(None, "blackout")
    )
    api.dark = False
    mgr._note_api_ok()
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON
    assert labels[CC_READY_STATE_LABEL] == "false"


def test_direct_write_supersedes_stale_pending_patches(fake_kube, tmp_path):
    """A label write that LANDS while stale deferred patches are still
    queued (an earlier flush failed) must not be clobbered back by the
    eventual flush: the direct write journals a superseding patch record,
    so the journal-order merge carries the fresh values."""
    from tpu_cc_manager.drain.state import STATE_FAILED

    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    api, mgr = engaged_offline_manager(fake_kube, backend, tmp_path)
    assert mgr.set_cc_mode(MODE_ON)  # defers mode.state=on / ready=true
    assert mgr.intents.pending_patches()[CC_MODE_STATE_LABEL] == MODE_ON

    # Connectivity returns; a DIRECT state write (a failed reconcile)
    # lands before any successful flush of the stale 'on' patches.
    api.dark = False
    mgr._report_state(STATE_FAILED, reason="smoke-failed")
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == STATE_FAILED
    assert labels[CC_READY_STATE_LABEL] == ""  # failed -> unknown-ready
    assert not mgr.intents.has_pending_patches()
    # Another flush edge changes nothing — the stale 'on' never returns.
    mgr._note_api_ok()
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == STATE_FAILED
    assert labels[CC_READY_STATE_LABEL] == ""


def test_patch_deferred_during_flush_is_not_lost(fake_kube, tmp_path):
    """A patch deferred concurrently with a flush — AFTER the flush's
    snapshot — must stay queued (the flushed marker covers only the
    snapshot), and the next flush writes it."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    api, mgr = engaged_offline_manager(fake_kube, backend, tmp_path)
    mgr._defer_patch({CC_MODE_STATE_LABEL: MODE_ON})
    api.dark = False

    # Model the race: mid-flush (between the snapshot and the flushed
    # marker), another thread defers a demote.
    real_get = fake_kube.get_node

    def get_and_race(name):
        node = real_get(name)
        if mgr._flushing_patches and not raced["done"]:
            raced["done"] = True
            mgr.intents.defer_patch({CC_READY_STATE_LABEL: "false"})
        return node

    raced = {"done": False}
    fake_kube.get_node = get_and_race
    try:
        mgr._note_api_ok()
    finally:
        fake_kube.get_node = real_get
    # The snapshot flushed; the racing demote is STILL pending.
    assert raced["done"]
    assert mgr.intents.pending_patches() == {CC_READY_STATE_LABEL: "false"}
    mgr._note_api_ok()
    assert not mgr.intents.has_pending_patches()
    assert node_labels(fake_kube.get_node(NODE))[
        CC_READY_STATE_LABEL
    ] == "false"


def test_watchdog_condemn_while_offline_is_journaled(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    fake_kube.set_node_label(NODE, CC_MODE_STATE_LABEL, MODE_ON)
    fake_kube.set_node_label(NODE, CC_READY_STATE_LABEL, "true")
    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    api, mgr = engaged_offline_manager(
        fake_kube, backend, tmp_path, metrics=registry,
    )
    watchdog = RuntimeHealthWatchdog(
        api, backend, NODE, demote_after=2, restore_after=1,
        metrics=registry, defer_patch=mgr.defer_patch_if_offline,
    )
    backend.healthy = False
    watchdog.tick()
    watchdog.tick()
    # The demote could not reach the apiserver but was NOT lost: it is
    # journaled and the watchdog state advanced.
    assert watchdog.degraded
    assert mgr.intents.pending_patches()[CC_READY_STATE_LABEL] == "false"
    api.dark = False
    mgr._note_api_ok()
    assert node_labels(fake_kube.get_node(NODE))[
        CC_READY_STATE_LABEL
    ] == "false"


def test_short_blip_under_grace_still_fails_the_reconcile(
    fake_kube, tmp_path,
):
    """Deferral is an ENGAGED-outage behavior: a blip shorter than the
    grace window keeps the existing fail-and-backoff semantics, so a
    healthy apiserver hiccup cannot silently buffer label writes."""
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    api = BlackoutKube(fake_kube)
    mgr = make_manager(
        fake_kube, backend, tmp_path, api=api, offline_grace_s=60.0,
    )
    api.dark = True
    with pytest.raises(KubeApiError):
        mgr._report_state(MODE_ON)
    assert not mgr.intents.has_pending_patches()


# ---------------------------------------------------------------------------
# /journalz debug endpoint + `tpu-cc-ctl journal`
# ---------------------------------------------------------------------------


def test_journalz_endpoint_and_ctl_journal(fake_kube, tmp_path, capsys):
    from tpu_cc_manager import ctl
    from tpu_cc_manager.ccmanager.metrics_server import start_metrics_server

    j = make_journal(tmp_path)
    j.note_desired(MODE_ON)
    j.begin("transition", mode=MODE_ON, chips=[0, 1])
    j.defer_patch({CC_MODE_STATE_LABEL: MODE_ON})

    registry = MetricsRegistry()
    server = start_metrics_server(
        0, registry, bind="127.0.0.1", intent_journal=j,
    )
    try:
        port = server.server_address[1]
        url = f"http://127.0.0.1:{port}/journalz"
        args = ctl.build_parser().parse_args(["journal", "--url", url])
        assert ctl.cmd_journal(fake_kube, args) == 0
        out = capsys.readouterr().out
        assert "last desired mode: on" in out
        assert "open intents: 1" in out
        assert "kind=transition" in out
        assert CC_MODE_STATE_LABEL in out
        # --json round-trips the raw snapshot.
        args = ctl.build_parser().parse_args(
            ["journal", "--url", url, "--json"]
        )
        assert ctl.cmd_journal(fake_kube, args) == 0
        import json as json_mod

        payload = json_mod.loads(capsys.readouterr().out)
        assert payload["last_desired_mode"] == MODE_ON
        assert len(payload["open_intents"]) == 1
    finally:
        server.shutdown()


def test_ctl_journal_resolves_node_address(fake_kube, capsys):
    """Without --url, `ctl journal` dials the node's InternalIP from
    status.addresses; an address-less node gets the actionable error."""
    from tpu_cc_manager import ctl

    fake_kube.add_node(NODE)
    args = ctl.build_parser().parse_args(["journal", "--node", NODE])
    with pytest.raises(ValueError, match="status.addresses"):
        ctl.cmd_journal(fake_kube, args)
    node = fake_kube.get_node(NODE)
    assert (
        ctl._node_debug_address(
            type("K", (), {"get_node": staticmethod(lambda n: {
                "status": {"addresses": [
                    {"type": "Hostname", "address": "host-a"},
                    {"type": "InternalIP", "address": "10.0.0.7"},
                ]},
            })})(), NODE,
        )
        == "10.0.0.7"
    )
    assert node  # the apiserver lookup path was exercised above
