"""Full-stack integration: rolling orchestrator driving REAL per-node
agents (CCManager.watch_and_apply in threads) over multi-host slices.

This is the closest no-hardware approximation of BASELINE.json configs[3]
(flip a pool one ICI-slice group at a time): the orchestrator writes
desired labels; real watch loops observe them; each slice's hosts drain,
stage, cross the slice commit barrier (ccmanager/slicecoord.py), reset,
attest, and report — and the orchestrator's max_unavailable=1 window
keeps slice B untouched until slice A converged.
"""

from __future__ import annotations

import threading
import time

import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    MODE_OFF,
    MODE_ON,
    SLICE_ID_LABEL,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry
from tpu_cc_manager.utils import retry as retry_mod

POOL = {  # two 2-host slices
    "slice-a": ("node-a0", "node-a1"),
    "slice-b": ("node-b0", "node-b1"),
}


class SeqBackend(FakeTpuBackend):
    """Mirrors stage/reset into a shared sequence tagged (slice, host)."""

    def __init__(self, seq, lock, tag, **kw):
        super().__init__(**kw)
        self._seq, self._seq_lock, self._tag = seq, lock, tag

    def stage_cc_mode(self, chips, mode):
        super().stage_cc_mode(chips, mode)
        with self._seq_lock:
            self._seq.append((*self._tag, "stage"))

    def reset(self, chips):
        with self._seq_lock:
            self._seq.append((*self._tag, "reset"))
        super().reset(chips)


def test_rollout_over_multi_host_slices_with_real_agents(fake_kube, tmp_path):
    seq: list = []
    seq_lock = threading.Lock()
    stop = threading.Event()
    agents, backends, threads = [], {}, []

    for slice_id, nodes in POOL.items():
        for host_index, name in enumerate(nodes):
            fake_kube.add_node(name, {"pool": "tpu"})
            backend = SeqBackend(
                seq, seq_lock, (slice_id, name),
                num_chips=2, accelerator_type="v5p-32",
                num_hosts=len(nodes), host_index=host_index,
                slice_id=slice_id,
            )
            backends[name] = backend
            mgr = CCManager(
                api=fake_kube,
                backend=backend,
                node_name=name,
                default_mode=MODE_OFF,
                operator_namespace="tpu-operator",
                evict_components=False,
                smoke_workload="none",
                metrics=MetricsRegistry(),
                watch_timeout_s=1,
                reconnect_delay_s=0.0,
                slice_barrier_timeout_s=20.0,
                slice_barrier_poll_interval_s=0.01,
                readiness_file=str(tmp_path / f"ready-{name}"),
            )
            agents.append(mgr)
            t = threading.Thread(
                target=mgr.watch_and_apply, args=(stop,), daemon=True
            )
            threads.append(t)

    for t in threads:
        t.start()

    try:
        # Agents settle at the default mode and publish slice membership
        # (the orchestrator's group-by-slice needs the labels agents write).
        all_nodes = [n for nodes in POOL.values() for n in nodes]

        def settled() -> bool:
            labels = {
                n: node_labels(fake_kube.get_node(n)) for n in all_nodes
            }
            return all(
                l.get(CC_MODE_STATE_LABEL) == MODE_OFF
                and l.get(SLICE_ID_LABEL)
                for l in labels.values()
            )

        if not retry_mod.poll_until(settled, 30.0, 0.02):
            pytest.fail(
                "agents never settled: "
                f"{ {n: node_labels(fake_kube.get_node(n)) for n in all_nodes} }"
            )

        roller = RollingReconfigurator(
            fake_kube, "pool=tpu", max_unavailable=1,
            node_timeout_s=30, poll_interval_s=0.02,
        )
        result = roller.rollout(MODE_ON)
        assert result.ok, result.summary()
        assert [g.group for g in result.groups] == ["slice-a", "slice-b"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)

    # Every host converged on the hardware, not just in labels.
    for name, backend in backends.items():
        assert set(backend.committed.values()) == {MODE_ON}, name
        assert node_labels(fake_kube.get_node(name))[CC_MODE_STATE_LABEL] == MODE_ON

    # Barrier invariant per slice: both hosts staged before either reset.
    for slice_id in POOL:
        ops = [(h, op) for s, h, op in seq if s == slice_id]
        first_reset = next(i for i, (_, op) in enumerate(ops) if op == "reset")
        staged_hosts = {h for h, op in ops[:first_reset] if op == "stage"}
        assert staged_hosts == set(POOL[slice_id]), (slice_id, ops)

    # Rolling window invariant (max_unavailable=1): slice-a finished all
    # its hardware ops before slice-b started any.
    slice_order = [s for s, _, _ in seq]
    last_a = max(i for i, s in enumerate(slice_order) if s == "slice-a")
    first_b = min(i for i, s in enumerate(slice_order) if s == "slice-b")
    assert last_a < first_b, seq
