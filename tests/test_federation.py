"""Federated region-sharded rollouts (ccmanager/federation.py).

The acceptance bars (ISSUE 17), all in tier-1:

- a 2-region federated rollout over a 100-node pool converges both
  regional shards and completes the parent record exactly once;
- a parent-record CAS race between two shards charges the single global
  failure budget exactly once (set-union merge under honest 409s);
- ONE global budget halts EVERY region: a region that blows the budget
  pushes HALTED to the parent, and every other shard stops at its next
  wave-boundary sync without bouncing another node;
- a regional apiserver blackout stalls ONLY that region — the siblings
  keep settling the global budget through the parent and finish — and a
  successor resumes the blacked-out region from its regional record;
- a force-aborted federation fences a wedged shard on its next write
  (parent generation bump, the federated analogue of release_lease);
- downgrade compat: a federation-unaware (record v4) orchestrator
  refuses a federated record loudly, and a single-region federated
  record serializes <= v4 and round-trips through the legacy resume
  path.

The chaos-marked soak (hack/chaos_soak.sh) re-runs the kill + blackout
legs under any CC_CHAOS_SEED and prints the FEDERATION_SUMMARY line.
"""

import json
import os
import random
import threading

import pytest

from tpu_cc_manager.ccmanager import federation as federation_mod
from tpu_cc_manager.ccmanager import rollout_state
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.faults.kube import FaultyKubeClient
from tpu_cc_manager.faults.plan import FaultPlan, OrchestratorKilled
from tpu_cc_manager.kubeclient.api import KubeApiError, node_labels
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    QUARANTINED_LABEL,
)
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

POOL = "pool=tpu"
NS = "tpu-operator"


class Clock:
    """Injectable wall/monotonic clock for deterministic lease expiry."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def converge_reactor(kube):
    """Agents in miniature: desired-mode label edits converge instantly."""

    def reactor(name, node):
        labels = node_labels(node)
        desired = labels.get(CC_MODE_LABEL)
        if desired and labels.get(CC_MODE_STATE_LABEL) != desired:
            kube.set_node_label(name, CC_MODE_STATE_LABEL, desired)

    kube.add_patch_reactor(reactor)


def add_region_pool(fake, region, n, quarantined=0):
    for i in range(n):
        labels = {"pool": "tpu", federation_mod.REGION_LABEL: region}
        if i < quarantined:
            labels[QUARANTINED_LABEL] = "true"
        fake.add_node(f"{region}-node-{i}", labels)


def make_parent(fake, regions=("r1", "r2"), mode="on", **kw):
    store = federation_mod.ParentStore(fake, namespace=NS)
    parent = store.initialize(
        federation_mod.ParentRecord.fresh(mode, POOL, list(regions), **kw),
        resume=False,
    )
    return store, parent


def regional_lease(api, region, holder, clk, metrics=None):
    return rollout_state.RolloutLease(
        api, holder=holder, namespace=NS,
        name=federation_mod.regional_lease_name(region),
        duration_s=30.0, metrics=metrics or MetricsRegistry(),
        wall=clk, clock=clk,
    )


def regional_roller(api, region, gate, **kw):
    kw.setdefault("node_timeout_s", 5)
    kw.setdefault("poll_interval_s", 0.02)
    kw.setdefault("metrics", MetricsRegistry())
    return RollingReconfigurator(
        api, federation_mod.regional_selector(POOL, region),
        federation=gate, **kw
    )


def region_converged(fake, region, mode="on"):
    nodes = fake.list_nodes(federation_mod.regional_selector(POOL, region))
    return nodes and all(
        node_labels(n).get(CC_MODE_STATE_LABEL) == mode
        for n in nodes
        if QUARANTINED_LABEL not in node_labels(n)
    )


# ---------------------------------------------------------------------------
# The 100-node two-region smoke: the tier-1 federation acceptance path
# ---------------------------------------------------------------------------


def test_two_region_federated_rollout_converges_100_nodes(fake_kube):
    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "r1", 50)
    add_region_pool(fake_kube, "r2", 50)
    store, parent = make_parent(fake_kube)
    clk = Clock()
    results = {}

    def run_region(region):
        lease = regional_lease(fake_kube, region, f"orch-{region}", clk)
        lease.acquire()
        gate = federation_mod.FederationGate(store, region)
        gate.attach(parent)
        roller = regional_roller(
            fake_kube, region, gate, lease=lease, max_unavailable=10,
        )
        results[region] = roller.rollout("on")
        lease.release(clear_record=True)

    threads = [
        threading.Thread(target=run_region, args=(r,), daemon=True)
        for r in ("r1", "r2")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results["r1"].ok and results["r2"].ok
    assert region_converged(fake_kube, "r1")
    assert region_converged(fake_kube, "r2")
    final = store.load()
    assert final is not None
    assert final.status == federation_mod.PARENT_COMPLETE
    assert final.budget_spend == []
    assert set(final.regions) == {"r1", "r2"}
    assert all(
        r["status"] == federation_mod.PARENT_COMPLETE
        for r in final.regions.values()
    )


# ---------------------------------------------------------------------------
# Exactly-once budget accounting under a parent-record CAS race
# ---------------------------------------------------------------------------


def test_parent_cas_race_charges_budget_exactly_once(fake_kube):
    store, parent = make_parent(fake_kube, regions=("r1", "r2"))
    gates = {}
    for region in ("r1", "r2"):
        gates[region] = federation_mod.FederationGate(store, region)
        gates[region].attach(parent)

    # Both shards charge an overlapping spend set concurrently: the CAS
    # loser re-runs its merge against the winner's write, and the
    # set-union makes the retried charge idempotent.
    barrier = threading.Barrier(2)
    views = {}

    def charge(region, spend):
        barrier.wait()
        views[region] = gates[region].sync(spend)

    t1 = threading.Thread(
        target=charge, args=("r1", ["shared-node", "r1-only"]), daemon=True
    )
    t2 = threading.Thread(
        target=charge, args=("r2", ["shared-node", "r2-only"]), daemon=True
    )
    for t in (t1, t2):
        t.start()
    for t in (t1, t2):
        t.join(timeout=10)
    final = store.load()
    assert set(final.budget_spend) == {"shared-node", "r1-only", "r2-only"}
    # Whichever shard synced LAST saw the full union folded back down.
    assert set(views["r1"]["spend"]) | set(views["r2"]["spend"]) == {
        "shared-node", "r1-only", "r2-only",
    }
    # Re-syncing the same spend stays exactly-once.
    gates["r1"].sync(["shared-node", "r1-only"])
    assert set(store.load().budget_spend) == {
        "shared-node", "r1-only", "r2-only",
    }


def test_parent_cas_race_many_shards_each_charge_lands_once(fake_kube):
    regions = [f"z{i}" for i in range(8)]
    store, parent = make_parent(fake_kube, regions=regions)
    barrier = threading.Barrier(len(regions))

    def charge(region):
        gate = federation_mod.FederationGate(store, region)
        gate.attach(parent)
        barrier.wait()
        gate.sync([f"{region}-failed"])

    threads = [
        threading.Thread(target=charge, args=(r,), daemon=True)
        for r in regions
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert set(store.load().budget_spend) == {f"{r}-failed" for r in regions}


# ---------------------------------------------------------------------------
# One global budget halts every region
# ---------------------------------------------------------------------------


def test_global_budget_blown_in_one_region_halts_the_others(fake_kube):
    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "r1", 4, quarantined=2)
    add_region_pool(fake_kube, "r2", 4)
    store, parent = make_parent(fake_kube, failure_budget=1)
    clk = Clock()

    # r1 blows the single global budget (2 quarantined > budget 1) and
    # pushes HALTED to the parent.
    lease_1 = regional_lease(fake_kube, "r1", "orch-r1", clk)
    lease_1.acquire()
    gate_1 = federation_mod.FederationGate(store, "r1")
    gate_1.attach(parent)
    roller_1 = regional_roller(
        fake_kube, "r1", gate_1, lease=lease_1, failure_budget=1,
    )
    result_1 = roller_1.rollout("on")
    assert not result_1.ok
    assert result_1.halted_reason == "failure-budget-exceeded"
    mid = store.load()
    assert mid.status == federation_mod.PARENT_HALTED

    # r2 is perfectly healthy, but the GLOBAL budget is spent: its very
    # first wave-boundary sync sees the halted parent and stops before
    # bouncing a single node.
    lease_2 = regional_lease(fake_kube, "r2", "orch-r2", clk)
    lease_2.acquire()
    gate_2 = federation_mod.FederationGate(store, "r2")
    gate_2.attach(parent)
    roller_2 = regional_roller(
        fake_kube, "r2", gate_2, lease=lease_2, failure_budget=1,
    )
    result_2 = roller_2.rollout("on")
    assert not result_2.ok
    assert result_2.halted_reason
    assert result_2.groups == []
    for n in fake_kube.list_nodes(
        federation_mod.regional_selector(POOL, "r2")
    ):
        assert CC_MODE_LABEL not in node_labels(n)


def test_sibling_spend_folds_into_regional_budget_math(fake_kube):
    """A region that never failed anything still halts when SIBLING
    spend pushed through the parent exhausts the shared budget — the
    whole point of one global ledger."""
    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "r2", 4)
    store, parent = make_parent(fake_kube, failure_budget=1)
    gate_1 = federation_mod.FederationGate(store, "r1")
    gate_1.attach(parent)
    # r1 (not under test) reports two dead nodes, still in-progress.
    gate_1.sync(["r1-node-0", "r1-node-1"])

    clk = Clock()
    lease_2 = regional_lease(fake_kube, "r2", "orch-r2", clk)
    lease_2.acquire()
    gate_2 = federation_mod.FederationGate(store, "r2")
    gate_2.attach(parent)
    roller_2 = regional_roller(
        fake_kube, "r2", gate_2, lease=lease_2, failure_budget=1,
    )
    result_2 = roller_2.rollout("on")
    assert not result_2.ok
    assert result_2.halted_reason == "failure-budget-exceeded"
    # The halt came from folded-down sibling spend, not local failures.
    assert result_2.groups == []


# ---------------------------------------------------------------------------
# Regional apiserver blackout: stalls one region, not the federation
# ---------------------------------------------------------------------------


def run_blackout_leg(fake_kube, seed=0):
    """One full blackout scenario; shared by the tier-1 test and the
    chaos soak. Returns a summary dict."""
    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "r1", 4, quarantined=1)
    add_region_pool(fake_kube, "r2", 4)
    store, parent = make_parent(fake_kube, failure_budget=2)
    clk = Clock()

    # r1's REGIONAL apiserver traffic rides a faulty client; the parent
    # store stays on the (separate, healthy) control plane.
    plan = FaultPlan(seed=seed, rate=0.0)
    faulty = FaultyKubeClient(fake_kube, plan)
    lease_1 = regional_lease(faulty, "r1", "orch-r1-a", clk)
    lease_1.acquire()
    gate_1 = federation_mod.FederationGate(store, "r1")
    gate_1.attach(parent)
    boundaries = {"n": 0}

    def blackout_mid_rollout(point):
        if point == "window-boundary":
            boundaries["n"] += 1
            if boundaries["n"] == 1:
                plan.begin_blackout()

    roller_1 = regional_roller(
        faulty, "r1", gate_1, lease=lease_1, failure_budget=2,
        crash_hook=blackout_mid_rollout,
    )
    with pytest.raises(KubeApiError):
        roller_1.rollout("on")
    assert plan.in_blackout

    # The blackout stalls ONLY r1: r2 runs to completion against the
    # healthy apiserver, and the global ledger it folds down already
    # carries r1's quarantined node.
    lease_2 = regional_lease(fake_kube, "r2", "orch-r2", clk)
    lease_2.acquire()
    gate_2 = federation_mod.FederationGate(store, "r2")
    gate_2.attach(parent)
    roller_2 = regional_roller(
        fake_kube, "r2", gate_2, lease=lease_2, failure_budget=2,
    )
    result_2 = roller_2.rollout("on")
    assert result_2.ok
    assert region_converged(fake_kube, "r2")
    mid = store.load()
    assert mid.status == federation_mod.PARENT_IN_PROGRESS
    assert "r1-node-0" in mid.budget_spend

    # Apiserver back: a successor takes the lapsed regional lease,
    # re-attaches to the live parent from the persisted record, and
    # finishes r1. The federation completes exactly once.
    plan.end_blackout()
    clk.advance(31.0)
    lease_1b = regional_lease(fake_kube, "r1", "orch-r1-b", clk)
    record = lease_1b.acquire()
    assert record is not None and record.federation
    gate_1b = federation_mod.FederationGate.from_record_dict(
        fake_kube, record.federation
    )
    roller_1b = regional_roller(
        fake_kube, "r1", gate_1b, lease=lease_1b,
        resume_record=record, failure_budget=2,
    )
    result_1b = roller_1b.rollout(record.mode)
    assert result_1b.ok
    assert region_converged(fake_kube, "r1")
    final = store.load()
    assert final.status == federation_mod.PARENT_COMPLETE
    return {
        "blackout_refusals": plan.blackout_refusals,
        "budget_spend": sorted(final.budget_spend),
        "r1_groups": len(result_1b.groups),
    }


def test_regional_blackout_stalls_only_that_region(fake_kube):
    summary = run_blackout_leg(fake_kube)
    assert summary["blackout_refusals"] > 0
    assert summary["budget_spend"] == ["r1-node-0"]


# ---------------------------------------------------------------------------
# Force-abort: the wedged shard self-fences on its next write
# ---------------------------------------------------------------------------


def test_force_abort_fences_live_shard_on_next_sync(fake_kube):
    metrics = MetricsRegistry()
    store, parent = make_parent(fake_kube)
    gate = federation_mod.FederationGate(store, "r1", metrics=metrics)
    gate.attach(parent)
    assert gate.sync([])["parent_status"] == federation_mod.PARENT_IN_PROGRESS

    aborted = store.abort("operator gave up on this plan")
    assert aborted.status == federation_mod.PARENT_ABORTED
    assert aborted.generation == parent.generation + 1
    with pytest.raises(rollout_state.RolloutFenced):
        gate.sync([])
    text = metrics.render_prometheus()
    assert 'tpu_cc_federation_fences_total{reason="parent-generation"}' in text


def test_force_abort_stops_a_running_regional_rollout(fake_kube):
    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "r1", 6)
    store, parent = make_parent(fake_kube, regions=("r1", "r2"))
    clk = Clock()
    lease = regional_lease(fake_kube, "r1", "orch-r1", clk)
    lease.acquire()
    gate = federation_mod.FederationGate(store, "r1")
    gate.attach(parent)
    fired = {"n": 0}

    def abort_mid_rollout(point):
        if point == "window-boundary":
            fired["n"] += 1
            if fired["n"] == 1:
                store.abort("chaos: operator force-abort")

    roller = regional_roller(
        fake_kube, "r1", gate, lease=lease, max_unavailable=1,
        crash_hook=abort_mid_rollout,
    )
    with pytest.raises(rollout_state.RolloutFenced):
        roller.rollout("on")
    # The wedged shard stopped before converging its whole region.
    assert not region_converged(fake_kube, "r1")


# ---------------------------------------------------------------------------
# Downgrade compatibility
# ---------------------------------------------------------------------------


def _federated_record(regions_total=2):
    return rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=3,
        groups=[("g0", ("r1-node-0",))], done=[],
        federation={
            "region": "r1", "regions": regions_total,
            "parent_namespace": NS,
            "parent_name": federation_mod.PARENT_LEASE_NAME,
            "generation": 1, "digest": "abc123",
        },
    )


def test_federation_unaware_orchestrator_refuses_v5_record(monkeypatch):
    """A v4-era orchestrator (no federation support) must refuse the
    record loudly, never resume a regional slice as a plain rollout."""
    data = _federated_record().to_json()
    assert json.loads(data)["version"] == rollout_state.RECORD_VERSION_NO_ESCROW
    monkeypatch.setattr(
        rollout_state, "RECORD_VERSION",
        rollout_state.RECORD_VERSION_NO_FEDERATION,
    )
    with pytest.raises(rollout_state.RolloutFenced, match="newer than"):
        rollout_state.RolloutRecord.from_json(data)


def test_escrow_unaware_orchestrator_refuses_v6_record(monkeypatch):
    """An escrow ledger in the federation dict forces v6: a v5 binary
    resuming it would drop the escrow balance and keep charging
    unbounded while the parent plane is dark — refuse loudly instead."""
    record = _federated_record()
    record.federation = dict(
        record.federation,
        escrow=2, acked_spend=[], charged=["r1-node-9"],
    )
    data = record.to_json()
    # Versioning is demand-driven: escrow demands exactly v6 (a touched
    # capacity ledger would demand v7, but there is none here).
    assert json.loads(data)["version"] == (
        rollout_state.RECORD_VERSION_NO_LEDGER
    )
    monkeypatch.setattr(
        rollout_state, "RECORD_VERSION",
        rollout_state.RECORD_VERSION_NO_ESCROW,
    )
    with pytest.raises(rollout_state.RolloutFenced, match="newer than"):
        rollout_state.RolloutRecord.from_json(data)


def test_resume_of_federated_record_without_gate_is_refused(fake_kube):
    record = _federated_record()
    roller = RollingReconfigurator(
        fake_kube, POOL, resume_record=record, node_timeout_s=1,
    )
    with pytest.raises(ValueError, match="federation gate"):
        roller.rollout("on")


def test_single_region_federated_record_roundtrips_legacy_resume(fake_kube):
    """regions=1 is not a federation: the record serializes <= v4 with
    no federation field, so a legacy orchestrator resumes it."""
    record = _federated_record(regions_total=1)
    data = record.to_json()
    obj = json.loads(data)
    assert obj["version"] <= rollout_state.RECORD_VERSION_NO_FEDERATION
    assert "federation" not in obj
    back = rollout_state.RolloutRecord.from_json(data)
    assert back.federation is None

    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "r1", 1)
    result = RollingReconfigurator(
        fake_kube, POOL, resume_record=back,
        node_timeout_s=5, poll_interval_s=0.02,
    ).rollout("on")
    assert result.ok


# ---------------------------------------------------------------------------
# Chaos soak: seeded regional kill + blackout (FEDERATION_SUMMARY)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_federation_soak_seeded_regional_kill_and_blackout(fake_kube):
    """One seeded federation weather pass: a regional orchestrator is
    killed at a seeded crash point and resumed from its record, then the
    blackout leg runs on a fresh pool. Prints the FEDERATION_SUMMARY
    line hack/chaos_soak.sh scrapes."""
    seed = int(os.environ.get("CC_CHAOS_SEED", "20260807"))
    rng = random.Random(seed)

    converge_reactor(fake_kube)
    add_region_pool(fake_kube, "k1", 8)
    add_region_pool(fake_kube, "k2", 8)
    store, parent = make_parent(fake_kube, regions=("k1", "k2"))
    clk = Clock()

    # Leg 1: seeded kill in k1, clean run in k2.
    kill_at = rng.randrange(2, 12)
    calls = {"n": 0}

    def killer(point):
        if calls["n"] == kill_at:
            raise OrchestratorKilled(point, calls["n"])
        calls["n"] += 1

    lease_a = regional_lease(fake_kube, "k1", "orch-k1-a", clk)
    lease_a.acquire()
    gate_a = federation_mod.FederationGate(store, "k1")
    gate_a.attach(parent)
    killed = False
    try:
        result_1 = regional_roller(
            fake_kube, "k1", gate_a, lease=lease_a, max_unavailable=1,
            crash_hook=killer,
        ).rollout("on")
    except OrchestratorKilled:
        killed = True
        clk.advance(31.0)
        lease_b = regional_lease(fake_kube, "k1", "orch-k1-b", clk)
        record = lease_b.acquire()
        assert record is not None and record.federation
        gate_b = federation_mod.FederationGate.from_record_dict(
            fake_kube, record.federation
        )
        result_1 = regional_roller(
            fake_kube, "k1", gate_b, lease=lease_b, resume_record=record,
            max_unavailable=1,
        ).rollout(record.mode)
    assert result_1.ok
    assert region_converged(fake_kube, "k1")

    lease_2 = regional_lease(fake_kube, "k2", "orch-k2", clk)
    lease_2.acquire()
    gate_2 = federation_mod.FederationGate(store, "k2")
    gate_2.attach(parent)
    result_2 = regional_roller(
        fake_kube, "k2", gate_2, lease=lease_2, max_unavailable=2,
    ).rollout("on")
    assert result_2.ok
    assert store.load().status == federation_mod.PARENT_COMPLETE

    # Leg 2: the blackout scenario on a fresh pool + fresh parent.
    from tpu_cc_manager.kubeclient.fake import FakeKube

    blackout = run_blackout_leg(FakeKube(), seed=seed)

    # Leg 3: the PARENT-plane partition (escrow weather) — degraded
    # mode, dark escrow spend, escrow-exhausted halt, exactly-once
    # reconciliation on reconnect.
    parent_blackout = run_parent_blackout_leg(seed=seed)

    print(
        "FEDERATION_SUMMARY "
        + json.dumps({
            "seed": seed,
            "kill_at": kill_at,
            "killed": killed,
            "regions": 2,
            "parent_complete": True,
            "blackout_refusals": blackout["blackout_refusals"],
            "budget_spend": blackout["budget_spend"],
            "parent_blackout": parent_blackout,
        })
    )


# ---------------------------------------------------------------------------
# Budget escrow & parent-plane partition tolerance (ISSUE 18)
# ---------------------------------------------------------------------------


class DarkSwitchKube:
    """Client wrapper that refuses the parent-lease transport (status
    None — a genuine outage, not a served error) while ``.dark``. Node
    and regional-lease verbs pass through untouched, so only the parent
    PLANE goes dark, exactly the federated failure domain under test."""

    def __init__(self, inner):
        self._inner = inner
        self.dark = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _refuse(self):
        if self.dark:
            raise KubeApiError(None, "parent plane dark: connection refused")

    def get_lease(self, *a, **kw):
        self._refuse()
        return self._inner.get_lease(*a, **kw)

    def update_lease(self, *a, **kw):
        self._refuse()
        return self._inner.update_lease(*a, **kw)

    def create_lease(self, *a, **kw):
        self._refuse()
        return self._inner.create_lease(*a, **kw)


def fast_store(api):
    """A ParentStore whose retry ladder gives up instantly — dark-path
    tests should not pay real backoff sleeps."""
    return federation_mod.ParentStore(
        api, namespace=NS,
        retry_policy=retry_mod.RetryPolicy(
            max_attempts=1, base_delay_s=0.0, max_delay_s=0.0,
        ),
    )


def dark_gate(fake, clk, **parent_kw):
    """A gate attached while the parent plane is LIGHT, plus the switch
    to cut it. Returns (plain_store, switch, gate)."""
    store, parent = make_parent(fake, **parent_kw)
    switch = DarkSwitchKube(fake)
    gate = federation_mod.FederationGate(
        fast_store(switch), "r1", offline_grace_s=1.0, clock=clk,
    )
    gate.attach(parent)
    return store, switch, gate


def run_parent_blackout_leg(seed=0):
    """One seeded parent-plane partition pass, shared with the chaos
    soak: the shard rides a total parent blackout past grace, charges
    its dark spend against the escrowed budget slice, halts
    ``escrow-exhausted`` when the slice runs dry, and reconciles the
    ledger exactly once on reconnect. Returns the escrow counters the
    FEDERATION_SUMMARY line carries."""
    from tpu_cc_manager.kubeclient.fake import FakeKube

    rng = random.Random(seed)
    clk = Clock()
    store, switch, gate = dark_gate(
        FakeKube(), clk, failure_budget=2, regions=("r1", "r2")
    )
    escrow = gate.escrow_balance  # fair share: ceil(2 / 2 regions) = 1
    switch.dark = True
    gate.sync([])  # the outage clock starts at the first refusal
    clk.advance(rng.uniform(1.5, 30.0))  # past the 1.0 s grace
    first = [f"r1-node-{rng.randrange(100)}"]
    view = gate.sync(first)
    degraded = bool(view["degraded"])
    dark_spend = sorted(
        set(first) | {f"r1-node-{100 + rng.randrange(100)}"}
    )
    view = gate.sync(dark_spend)
    halted_dark = (
        bool(view["halted"])
        and view["reason"] == federation_mod.ESCROW_EXHAUSTED_REASON
    )
    parent_untouched = not store.load().budget_spend
    switch.dark = False
    view = gate.sync(dark_spend)
    reconnected = bool(view["reconnected"])
    reconciled = sorted(store.load().budget_spend) == dark_spend
    gate.sync(dark_spend)  # replay must not double-charge
    exactly_once = sorted(store.load().budget_spend) == dark_spend
    return {
        "escrow": escrow,
        "degraded": degraded,
        "escrow_halted_dark": halted_dark,
        "parent_untouched_while_dark": parent_untouched,
        "reconnected": reconnected,
        "reconciled": reconciled,
        "reconciled_exactly_once": exactly_once,
        "dark_spend": dark_spend,
    }


def test_parent_blackout_leg_counters_hold_for_any_seed():
    for seed in (0, 7, 20260807):
        leg = run_parent_blackout_leg(seed=seed)
        assert leg["degraded"]
        assert leg["escrow_halted_dark"]
        assert leg["parent_untouched_while_dark"]
        assert leg["reconnected"]
        assert leg["reconciled_exactly_once"]


def test_attach_reserves_escrow_and_sum_never_exceeds_budget(fake_kube):
    store, parent = make_parent(fake_kube, failure_budget=3)
    g1 = federation_mod.FederationGate(store, "r1")
    g1.attach(parent)
    assert g1.escrow_balance == 2  # ceil(3 / 2 regions)
    assert store.load().escrow == {"r1": 2}
    g2 = federation_mod.FederationGate(store, "r2")
    g2.attach(parent)
    # r2's fair share is also 2, but only 3 - 2 = 1 is free: the
    # invariant len(spend) + sum(escrow) <= failure_budget holds.
    assert g2.escrow_balance == 1
    live = store.load()
    assert sum(live.escrow.values()) <= live.failure_budget


def test_terminal_sync_returns_unused_escrow(fake_kube):
    store, parent = make_parent(fake_kube, failure_budget=4)
    gate = federation_mod.FederationGate(store, "r1")
    gate.attach(parent)
    assert store.load().escrow["r1"] == 2
    gate.sync([], status=federation_mod.PARENT_COMPLETE, done=5, total=5)
    assert store.load().escrow["r1"] == 0


def test_budgetless_federation_carries_no_escrow_and_serializes_v5():
    rec = rollout_state.RolloutRecord(
        mode="on", selector=POOL, generation=1,
        groups=[("g0", ("r1-node-0",))], done=[],
        federation={"region": "r1", "regions": 2, "generation": 1,
                    "digest": "abc"},
    )
    obj = json.loads(rec.to_json())
    assert obj["version"] == rollout_state.RECORD_VERSION_NO_ESCROW


def test_dark_shard_charges_escrow_then_halts_exhausted(fake_kube):
    clk = Clock()
    store, switch, gate = dark_gate(fake_kube, clk, failure_budget=4)
    assert gate.escrow_balance == 2
    switch.dark = True

    # First dark sync: inside both the grace window and the escrow.
    view = gate.sync(["r1-node-0"])
    assert view["offline"] and not view["halted"]
    assert not view["degraded"] and not view["offline_edge"]
    assert view["escrow_pending"] == 1

    # Past the grace the shard declares degraded mode exactly once.
    clk.advance(5.0)
    view = gate.sync(["r1-node-0", "r1-node-1"])
    assert view["degraded"] and view["offline_edge"]
    assert not view["halted"]  # pending 2 == escrow 2: still covered
    view = gate.sync(["r1-node-0", "r1-node-1"])
    assert not view["offline_edge"]  # edge fires once per outage

    # A third dark bounce would exceed the slice: halt, don't overspend.
    view = gate.sync(["r1-node-0", "r1-node-1", "r1-node-2"])
    assert view["halted"]
    assert view["reason"] == federation_mod.ESCROW_EXHAUSTED_REASON

    # Nothing leaked to the (unreachable) parent ledger.
    assert store.load().budget_spend == []


def test_reconnect_reconciles_dark_spend_exactly_once(fake_kube):
    clk = Clock()
    store, switch, gate = dark_gate(fake_kube, clk, failure_budget=4)
    switch.dark = True
    gate.sync(["r1-node-0"])  # starts the outage clock
    clk.advance(5.0)
    gate.sync(["r1-node-0", "r1-node-1"])
    assert gate.degraded

    switch.dark = False
    view = gate.sync(["r1-node-0", "r1-node-1"])
    assert view["reconnected"] and not view["offline"]
    assert not gate.degraded
    live = store.load()
    assert live.budget_spend == ["r1-node-0", "r1-node-1"]
    assert live.region_charged("r1") == {"r1-node-0", "r1-node-1"}
    # Escrow re-targeted to the remaining fair share, not the original.
    assert gate.escrow_balance == 1  # ceil((4-2)/2)

    # Replaying the same spend (crash-resume double-sync) charges nothing.
    gate.sync(["r1-node-0", "r1-node-1"])
    assert store.load().budget_spend == ["r1-node-0", "r1-node-1"]


def test_regional_cap_halts_only_that_region(fake_kube):
    store, parent = make_parent(
        fake_kube, failure_budget=4, region_budgets={"r1": 1, "r2": 3},
    )
    g1 = federation_mod.FederationGate(store, "r1")
    g1.attach(parent)
    assert g1.escrow_balance == 1  # heterogeneous cap bounds the slice

    view = g1.sync(["r1-node-0", "r1-node-1"])
    assert view["halted"]
    assert federation_mod.REGION_BUDGET_REASON in view["reason"]

    # The halted shard pushes its terminal status: the PARENT stays
    # in-progress (regional-only halt), so the sibling keeps rolling.
    g1.sync(
        ["r1-node-0", "r1-node-1"],
        status=federation_mod.PARENT_HALTED, halted_reason=view["reason"],
    )
    assert store.load().status == federation_mod.PARENT_IN_PROGRESS

    g2 = federation_mod.FederationGate(store, "r2")
    g2.attach(parent)
    view2 = g2.sync(["r2-node-0"])
    assert not view2["halted"]


def test_dark_resume_adopts_persisted_escrow_ledger(fake_kube):
    clk = Clock()
    store, switch, gate = dark_gate(fake_kube, clk, failure_budget=4)
    switch.dark = True
    clk.advance(5.0)
    gate.sync(["r1-node-0"])
    fed = gate.to_record_dict()
    assert fed["escrow"] == 2 and fed["charged"] == ["r1-node-0"]

    # SIGKILL mid-blackout: the successor rebuilds its gate from the
    # regional record with the parent STILL dark — and keeps rolling on
    # the persisted ledger instead of wedging.
    successor = federation_mod.FederationGate.from_record_dict(
        switch, fed, offline_grace_s=1.0, clock=clk,
    )
    assert successor.escrow_balance == 2
    assert successor.charged == {"r1-node-0"}
    assert successor.generation == gate.generation
    view = successor.sync(["r1-node-0", "r1-node-1"])
    assert view["offline"] and not view["halted"]
    clk.advance(5.0)
    view = successor.sync(["r1-node-0", "r1-node-1"])
    assert view["degraded"]

    # Reconnect: the dark spend of BOTH incarnations lands exactly once.
    switch.dark = False
    view = successor.sync(["r1-node-0", "r1-node-1"])
    assert view["reconnected"]
    assert store.load().budget_spend == ["r1-node-0", "r1-node-1"]


def test_generation_bump_during_blackout_fences_reconnecting_shard(
    fake_kube,
):
    clk = Clock()
    store, switch, gate = dark_gate(fake_kube, clk, failure_budget=4)
    switch.dark = True
    clk.advance(5.0)
    gate.sync(["r1-node-0"])

    # Operator force-aborts through the (healthy-elsewhere) parent plane
    # while this shard is partitioned from it.
    store.abort("operator-abort")

    switch.dark = False
    with pytest.raises(rollout_state.RolloutFenced):
        gate.sync(["r1-node-0"])


def test_corrupt_parent_abort_entombs_a_tombstone(fake_kube):
    store, _parent = make_parent(fake_kube)
    lease = fake_kube.get_lease(NS, federation_mod.PARENT_LEASE_NAME)
    lease["metadata"]["annotations"][
        rollout_state.RECORD_ANNOTATION
    ] = "{definitely not json"
    fake_kube.update_lease(NS, federation_mod.PARENT_LEASE_NAME, lease)

    with pytest.raises(federation_mod.ParentUnreadable):
        store.load()
    tomb = store.abort("operator-abort")
    assert tomb.status == federation_mod.PARENT_ABORTED
    assert tomb.digest == "discarded-corrupt"
    # The tombstone is a readable record again: the documented recovery.
    assert store.load().status == federation_mod.PARENT_ABORTED


def test_escrow_unaware_parser_refuses_v2_parent(monkeypatch):
    rec = federation_mod.ParentRecord.fresh(
        "on", POOL, ["r1", "r2"], failure_budget=4,
    )
    rec.escrow["r1"] = 2
    data = rec.to_json()
    assert json.loads(data)["parentVersion"] == federation_mod.PARENT_VERSION

    monkeypatch.setattr(
        federation_mod, "PARENT_VERSION",
        federation_mod.PARENT_VERSION_NO_ESCROW,
    )
    with pytest.raises(rollout_state.RolloutFenced, match="newer than"):
        federation_mod.ParentRecord.from_json(data)


def test_describe_parent_shows_escrow_and_staleness(fake_kube):
    clk = Clock()
    store, parent = make_parent(fake_kube, failure_budget=4)
    gate = federation_mod.FederationGate(
        store, "r1", offline_grace_s=1.0, clock=clk, wall=clk,
    )
    gate.attach(parent)
    gate.sync(["r1-node-0"], done=1, total=5)
    text = federation_mod.describe_parent(
        store.load(), wall=lambda: clk.t + 600.0, offline_grace_s=60.0,
    )
    assert "escrowed=" in text
    assert "STALE" in text  # last sync 600 s ago >> 60 s grace
