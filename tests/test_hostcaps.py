"""Host CC capability probing (ccmanager/hostcaps.py vs reference
main.py:80-103)."""

from tpu_cc_manager.ccmanager.hostcaps import is_host_cc_enabled


def test_no_probes_match(tmp_path):
    probes = (("missing", str(tmp_path / "nope"), None),)
    assert is_host_cc_enabled(probes) is False


def test_device_node_presence(tmp_path):
    dev = tmp_path / "tdx_guest"
    dev.touch()
    probes = (("TDX guest", str(dev), None),)
    assert is_host_cc_enabled(probes) is True


def test_sysfs_param_content(tmp_path):
    param = tmp_path / "tdx"
    param.write_text("Y\n")
    probes = (("KVM TDX", str(param), "Y"),)
    assert is_host_cc_enabled(probes) is True
    param.write_text("N\n")
    assert is_host_cc_enabled(probes) is False
