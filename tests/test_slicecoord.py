"""Slice-wide multi-host commit barrier (ccmanager/slicecoord.py).

The invariant under test is the cross-host generalization of the reference's
PPCIe fabric atomicity (reference main.py:362-368): **no host of an ICI
slice resets its runtime before every host of the slice is staged and
drained**, plus the crash/timeout recovery semantics around it.
"""

from __future__ import annotations

import threading

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.slicecoord import (
    SLICE_COMMIT_LABEL,
    SLICE_STAGED_LABEL,
)
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import (
    CC_MODE_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_OFF,
    MODE_ON,
    MODE_SLICE,
    SLICE_ID_LABEL,
    STATE_FAILED,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NS = "tpu-operator"
SLICE = "test-slice-0"
DP_LABEL = "google.com/tpu.deploy.device-plugin"
DP_APP = DRAIN_COMPONENT_LABELS[DP_LABEL]


class SeqBackend(FakeTpuBackend):
    """Fake backend that mirrors stage/reset ops into a shared, lock-guarded
    global sequence so cross-host ordering can be asserted."""

    def __init__(self, seq: list, seq_lock: threading.Lock, host: int, **kw):
        super().__init__(**kw)
        self._seq = seq
        self._seq_lock = seq_lock
        self._host = host

    def _note(self, op: str) -> None:
        with self._seq_lock:
            self._seq.append((self._host, op))

    def stage_cc_mode(self, chips, mode):
        super().stage_cc_mode(chips, mode)
        self._note("stage")  # after: "staged" is true once this returns

    def reset(self, chips):
        self._note("reset")  # before: "resetting" is true once this starts
        super().reset(chips)


def node_name(i: int) -> str:
    return f"tpu-node-{i}"


def make_host(
    kube, seq, seq_lock, i: int, num_hosts: int = 2, *, evict=False, **kw
) -> tuple[CCManager, SeqBackend]:
    backend = SeqBackend(
        seq,
        seq_lock,
        i,
        num_chips=4,
        accelerator_type="v5p-32",
        num_hosts=num_hosts,
        host_index=i,
        slice_id=SLICE,
    )
    mgr = CCManager(
        api=kube,
        backend=backend,
        node_name=node_name(i),
        operator_namespace=NS,
        evict_components=evict,
        smoke_workload="none",
        metrics=MetricsRegistry(),
        eviction_timeout_s=2,
        eviction_poll_interval_s=0.01,
        slice_barrier_timeout_s=kw.pop("slice_barrier_timeout_s", 10.0),
        slice_barrier_poll_interval_s=0.01,
        **kw,
    )
    return mgr, backend


def run_all(mgrs, mode):
    results = {}

    def drive(i, mgr):
        results[i] = mgr.set_cc_mode(mode)

    threads = [
        threading.Thread(target=drive, args=(i, mgr)) for i, mgr in enumerate(mgrs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results


def test_no_reset_before_all_hosts_staged_and_drained(fake_kube):
    """The core invariant, with eviction on: every host's drain + stage
    completes before ANY host's reset begins."""
    seq, seq_lock = [], threading.Lock()
    mgrs, backends = [], []
    for i in range(2):
        fake_kube.add_node(node_name(i), labels={DP_LABEL: "true"})
        fake_kube.add_pod(NS, f"dp-{i}", node_name(i), labels={"app": DP_APP})
        mgr, be = make_host(fake_kube, seq, seq_lock, i, evict=True)
        mgrs.append(mgr)
        backends.append(be)

    # Emulate the operator controller: a paused component label on a node
    # deletes that node's component pods (reference relies on the external
    # GPU operator for this, gpu_operator_eviction.py:185-207).
    def reactor(name, node):
        if is_paused(node_labels(node).get(DP_LABEL, "")):
            i = int(name.rsplit("-", 1)[1])
            fake_kube.delete_pod(NS, f"dp-{i}")

    fake_kube.add_patch_reactor(reactor)

    results = run_all(mgrs, MODE_SLICE)
    assert results == {0: True, 1: True}

    # Ordering invariant: both hosts staged before either reset.
    first_reset = next(k for k, (_, op) in enumerate(seq) if op == "reset")
    staged_hosts = {h for h, op in seq[:first_reset] if op == "stage"}
    assert staged_hosts == {0, 1}, f"reset before full staging: {seq}"

    for i, be in enumerate(backends):
        labels = node_labels(fake_kube.get_node(node_name(i)))
        assert labels.get(CC_MODE_STATE_LABEL) == MODE_SLICE
        assert labels.get(SLICE_ID_LABEL) == SLICE
        # Barrier markers are cleaned up after completion.
        assert SLICE_STAGED_LABEL not in labels
        assert SLICE_COMMIT_LABEL not in labels
        assert set(be.committed.values()) == {MODE_SLICE}
        # Components re-admitted (drain labels restored).
        assert labels.get(DP_LABEL) == "true"


def test_multi_host_barrier_applies_to_any_mode(fake_kube):
    """A plain 'on' change on a multi-host slice also rides the barrier:
    the whole ICI domain bounces, so fabric atomicity applies regardless
    of the mode value."""
    seq, seq_lock = [], threading.Lock()
    mgrs = []
    for i in range(2):
        fake_kube.add_node(node_name(i))
        mgr, _ = make_host(fake_kube, seq, seq_lock, i)
        mgrs.append(mgr)
    results = run_all(mgrs, MODE_ON)
    assert results == {0: True, 1: True}
    first_reset = next(k for k, (_, op) in enumerate(seq) if op == "reset")
    assert {h for h, op in seq[:first_reset] if op == "stage"} == {0, 1}


def test_barrier_timeout_fails_soft(fake_kube):
    """A host whose peers never show up must not touch the hardware: it
    fails the reconcile, labels itself failed, withdraws its staged marker,
    and re-admits its components."""
    seq, seq_lock = [], threading.Lock()
    fake_kube.add_node(node_name(0), labels={DP_LABEL: "true"})
    fake_kube.add_pod(NS, "dp-0", node_name(0), labels={"app": DP_APP})

    def reactor(name, node):
        if is_paused(node_labels(node).get(DP_LABEL, "")):
            fake_kube.delete_pod(NS, "dp-0")

    fake_kube.add_patch_reactor(reactor)
    mgr, backend = make_host(
        fake_kube, seq, seq_lock, 0, evict=True, slice_barrier_timeout_s=0.3
    )
    assert mgr.set_cc_mode(MODE_SLICE) is False
    labels = node_labels(fake_kube.get_node(node_name(0)))
    assert labels.get(CC_MODE_STATE_LABEL) == STATE_FAILED
    assert SLICE_STAGED_LABEL not in labels  # withdrew from the barrier
    assert labels.get(DP_LABEL) == "true"  # components re-admitted
    assert ("reset") not in [op for _, op in seq]  # hardware untouched
    assert set(backend.committed.values()) == {MODE_OFF}


def test_crash_mid_barrier_peer_recovers(fake_kube):
    """Host 1 crashes after staging (its marker is on the node but its agent
    is gone). Host 0 — the leader — correctly proceeds: the invariant is
    'all staged+drained before reset', which held. When host 1's agent
    restarts, its re-apply converges against the already-committed peer."""
    seq, seq_lock = [], threading.Lock()
    fake_kube.add_node(node_name(0))
    fake_kube.add_node(node_name(1))
    # Host 1 staged, then crashed: marker present, agent dead.
    fake_kube.set_node_label(node_name(1), SLICE_ID_LABEL, SLICE)
    fake_kube.set_node_label(node_name(1), SLICE_STAGED_LABEL, MODE_SLICE)

    mgr0, be0 = make_host(fake_kube, seq, seq_lock, 0)
    assert mgr0.set_cc_mode(MODE_SLICE) is True
    assert set(be0.committed.values()) == {MODE_SLICE}

    # Host 1's agent restarts and re-runs the apply. Its peer has already
    # committed (state label says so), so the barrier admits the straggler
    # without requiring the peer to re-stage.
    mgr1, be1 = make_host(fake_kube, seq, seq_lock, 1)
    assert mgr1.set_cc_mode(MODE_SLICE) is True
    assert set(be1.committed.values()) == {MODE_SLICE}

    for i in range(2):
        labels = node_labels(fake_kube.get_node(node_name(i)))
        assert labels.get(CC_MODE_STATE_LABEL) == MODE_SLICE
        assert SLICE_STAGED_LABEL not in labels


def test_leader_crash_after_commit_recovers(fake_kube):
    """The leader crashed after publishing its commit marker; the follower
    (who saw the marker) completed. The restarted leader's re-apply clears
    its stale marker and converges via the peers-already-committed path."""
    seq, seq_lock = [], threading.Lock()
    fake_kube.add_node(node_name(0))
    fake_kube.add_node(node_name(1))
    # Leftover state from the crashed round: leader committed marker + its
    # own staged marker; follower completed fully.
    fake_kube.set_node_label(node_name(0), SLICE_ID_LABEL, SLICE)
    fake_kube.set_node_label(node_name(0), SLICE_STAGED_LABEL, MODE_SLICE)
    fake_kube.set_node_label(node_name(0), SLICE_COMMIT_LABEL, MODE_SLICE)
    fake_kube.set_node_label(node_name(1), SLICE_ID_LABEL, SLICE)
    fake_kube.set_node_label(node_name(1), CC_MODE_STATE_LABEL, MODE_SLICE)

    mgr0, be0 = make_host(fake_kube, seq, seq_lock, 0)
    assert mgr0.set_cc_mode(MODE_SLICE) is True
    assert set(be0.committed.values()) == {MODE_SLICE}
    labels = node_labels(fake_kube.get_node(node_name(0)))
    assert labels.get(CC_MODE_STATE_LABEL) == MODE_SLICE
    assert SLICE_STAGED_LABEL not in labels
    assert SLICE_COMMIT_LABEL not in labels


def test_stuck_drain_on_one_host_fails_the_slice_soft(fake_kube):
    """Strict eviction + barrier interplay: host 1's drain never completes
    (a stuck pod), so it withdraws before touching hardware; host 0 times
    out at the barrier. NEITHER host resets, both fail soft with
    components re-admitted — the fabric is never half-bounced."""
    seq, seq_lock = [], threading.Lock()
    mgrs, backends = [], []
    for i in range(2):
        fake_kube.add_node(node_name(i), labels={DP_LABEL: "true"})
        fake_kube.add_pod(NS, f"dp-{i}", node_name(i), labels={"app": DP_APP})
        mgr, be = make_host(
            fake_kube, seq, seq_lock, i, evict=True,
            slice_barrier_timeout_s=1.0, strict_eviction=True,
        )
        mgrs.append(mgr)
        backends.append(be)

    # The operator controller drains host 0's pod but host 1's pod is
    # stuck (never deleted).
    def reactor(name, node):
        if name == node_name(0) and is_paused(node_labels(node).get(DP_LABEL, "")):
            fake_kube.delete_pod(NS, "dp-0")

    fake_kube.add_patch_reactor(reactor)

    results = run_all(mgrs, MODE_SLICE)
    assert results == {0: False, 1: False}
    for i, be in enumerate(backends):
        labels = node_labels(fake_kube.get_node(node_name(i)))
        assert labels.get(CC_MODE_STATE_LABEL) == STATE_FAILED, i
        assert SLICE_STAGED_LABEL not in labels, i
        assert labels.get(DP_LABEL) == "true", i  # re-admitted
        assert set(be.committed.values()) == {MODE_OFF}, i  # untouched
    assert "reset" not in [op for _, op in seq]


def test_barrier_tolerates_transient_peer_listing_failures(fake_kube):
    """A flaky list_nodes during the barrier poll must be retried, not
    surfaced as a reconcile failure."""
    from tpu_cc_manager.ccmanager.slicecoord import SliceBarrier
    from tpu_cc_manager.kubeclient.api import KubeApiError
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend

    flaky = {"n": 2}
    orig = fake_kube.list_nodes

    def flaky_list(selector=None):
        if flaky["n"] > 0:
            flaky["n"] -= 1
            raise KubeApiError(503, "hiccup")
        return orig(selector)

    fake_kube.list_nodes = flaky_list  # type: ignore[method-assign]
    fake_kube.add_node(node_name(0))
    topo = FakeTpuBackend(
        num_hosts=1, host_index=0, slice_id=SLICE
    ).discover()
    barrier = SliceBarrier(
        fake_kube, node_name(0), topo, timeout_s=5.0, poll_interval_s=0.01
    )
    barrier.publish_staged(MODE_ON)
    barrier.await_commit(MODE_ON)  # leader of a 1-host "slice": no peers
    assert flaky["n"] == 0  # the failures were consumed, not fatal


def test_leader_leaves_commit_marker_when_peer_never_finishes(fake_kube):
    """complete() must NOT retire the commit marker while a peer is still
    staged (a follower mid-poll would be stranded); it leaves the marker
    for the next barrier entry to clear."""
    from tpu_cc_manager.ccmanager.slicecoord import SliceBarrier
    from tpu_cc_manager.tpudev.fake import FakeTpuBackend

    fake_kube.add_node(node_name(0))
    fake_kube.add_node(node_name(1))
    fake_kube.set_node_label(node_name(1), SLICE_ID_LABEL, SLICE)
    fake_kube.set_node_label(node_name(1), SLICE_STAGED_LABEL, MODE_ON)

    topo = FakeTpuBackend(
        num_hosts=2, host_index=0, slice_id=SLICE
    ).discover()
    barrier = SliceBarrier(
        fake_kube, node_name(0), topo,
        timeout_s=5.0, poll_interval_s=0.01, complete_timeout_s=0.1,
    )
    barrier.publish_staged(MODE_ON)
    barrier.await_commit(MODE_ON)  # leader commits (both staged)
    barrier.complete(MODE_ON)  # peer still staged; completion window closes
    labels = node_labels(fake_kube.get_node(node_name(0)))
    assert SLICE_STAGED_LABEL not in labels  # own marker withdrawn
    assert labels.get(SLICE_COMMIT_LABEL) == MODE_ON  # left for the peer
    # The next barrier round on this node clears the stale marker.
    barrier.publish_staged(MODE_ON)
    labels = node_labels(fake_kube.get_node(node_name(0)))
    assert SLICE_COMMIT_LABEL not in labels


def test_single_host_topology_skips_barrier(fake_kube, fake_tpu):
    """Single-host nodes never publish barrier markers (no peers to wait
    for); the apply is the plain reference-shaped phase sequence."""
    fake_kube.add_node(node_name(0))
    mgr = CCManager(
        api=fake_kube,
        backend=fake_tpu,
        node_name=node_name(0),
        operator_namespace=NS,
        evict_components=False,
        smoke_workload="none",
        metrics=MetricsRegistry(),
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    labels = node_labels(fake_kube.get_node(node_name(0)))
    assert SLICE_STAGED_LABEL not in labels
    assert SLICE_COMMIT_LABEL not in labels
