"""Serving under the flip (tpu_cc_manager/serve/): a rolling CC flip over
a fake pool of REAL agents under sustained traffic loses ZERO requests,
the drain-deadline hint bounds checkpoint time, in-flight requests
checkpoint-and-requeue with progress intact, and the batch ladder climbs
the conservative hbm_bw_util headroom without overshooting.

Chaos-marked (tier-1 runs the short soak; hack/chaos_soak.sh reruns it
with -s and scrapes the SERVE_SUMMARY line) and — like the other chaos
suites — everything here runs with the CC_LOCKCHECK runtime lock-order
checker on, so the serve/ thread soup is machine-checked for inversions
on every run.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from tpu_cc_manager.drain import handshake
from tpu_cc_manager.serve.driver import TrafficDriver
from tpu_cc_manager.serve.harness import ServeHarness
from tpu_cc_manager.serve.server import NodeServer, Request, SimulatedExecutor
from tpu_cc_manager.utils import retry as retry_mod

pytestmark = pytest.mark.chaos

NODE = "serve-test-0"


@pytest.fixture(autouse=True)
def _lockcheck(monkeypatch):
    """Chaos-suite convention (tests/test_chaos.py): the runtime
    lock-order checker is ON for every scenario here, and the
    process-wide order graph is reset around each test."""
    from tpu_cc_manager.utils import locks as locks_rt

    locks_rt.GRAPH.reset()
    monkeypatch.setenv("CC_LOCKCHECK", "1")
    yield
    locks_rt.GRAPH.reset()


def collecting_callbacks():
    done, requeued = [], []
    lock = threading.Lock()

    def on_complete(node, req, util):
        with lock:
            done.append(req)

    def on_requeue(node, reqs):
        with lock:
            requeued.extend(reqs)

    return done, requeued, on_complete, on_requeue


# ---------------------------------------------------------------------------
# The headline: rolling flip under traffic, zero requests lost
# ---------------------------------------------------------------------------


def test_rolling_flip_under_traffic_loses_zero_requests(tmp_path):
    """Short soak (the long one is slow-marked): 3 real agents, live
    driver traffic, a real rolling CC flip mid-stream. Zero requests
    lost, every node bounced exactly once through its drain handshake,
    and the during-rollout latency bucket actually has data."""
    harness = ServeHarness(
        n_nodes=3, tmp_dir=str(tmp_path), checkpoint_full_s=0.05,
    )
    harness.build()
    try:
        report = harness.run(traffic_s=3.0, rollout_mode="on")
    finally:
        harness.shutdown()
    print("SERVE_SUMMARY " + json.dumps({
        k: report[k] for k in (
            "requests_issued", "requests_completed", "requests_lost",
            "requests_requeued", "error_rate", "nodes_bounced",
            "requests_lost_per_node_bounced", "latency",
            "latency_during_rollout", "latency_steady_state",
            "batch_ladder", "rollout_wall_s",
        )
    }))
    assert report["rollout_ok"], report["rollout_summary"]
    assert report["nodes_bounced"] == 3
    assert report["requests_lost"] == 0, report
    assert report["requests_lost_per_node_bounced"] == 0
    assert report["error_rate"] == 0.0
    assert report["requests_completed"] > 0
    assert report["latency_during_rollout"]["count"] > 0, (
        "the rollout window must have served traffic"
    )
    assert report["latency_steady_state"]["count"] > 0
    # Every server went through exactly one drain/resume handshake.
    for name, d in report["drains"].items():
        assert d["drains"] == 1, report["drains"]
        assert d["resumes"] == 1, report["drains"]


def test_live_serve_metrics_scraped_DURING_the_flip(tmp_path):
    """ISSUE 12 acceptance bar: a ServeHarness rolling flip exports
    live tpu_cc_serve_* metrics (latency histogram + queue/inflight
    gauges + outcome counters) and a windowed p99/burn-rate readout
    MID-RUN — asserted by scraping /metrics (and /rolloutz) from inside
    the orchestrator's mid-window hook, so "during the flip" is true by
    construction, not by sleep-timing."""
    import urllib.request

    harness = ServeHarness(
        n_nodes=3, tmp_dir=str(tmp_path), checkpoint_full_s=0.05,
        metrics_port=0,  # ephemeral; harness serves its SHARED registry
        slo_windows_s=(2.0, 30.0),
    )
    harness.build()
    addr = harness.metrics_address()
    assert addr is not None
    scraped: dict = {}

    def scrape_mid_window(point: str) -> None:
        # Runs on the orchestrator thread at named rollout points; one
        # scrape at the first mid-window (a node is draining RIGHT NOW).
        if point != "mid-window" or scraped:
            return
        with urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=5
        ) as resp:
            scraped["metrics"] = resp.read().decode()
        with urllib.request.urlopen(
            f"http://{addr}/rolloutz", timeout=5
        ) as resp:
            scraped["rolloutz"] = json.loads(resp.read().decode())

    try:
        report = harness.run(
            traffic_s=3.0, rollout_mode="on",
            rollout_hook=scrape_mid_window,
        )
    finally:
        harness.shutdown()
    assert report["rollout_ok"]
    assert report["requests_lost"] == 0
    text = scraped.get("metrics")
    assert text, "the mid-window hook never scraped"
    # Live latency histogram with per-node labels and fixed buckets.
    assert "tpu_cc_serve_request_seconds_bucket" in text
    assert 'node="serve-node-0"' in text
    assert 'le="+Inf"' in text
    # Queue-depth / in-flight gauges and outcome counters are live.
    assert "tpu_cc_serve_queue_depth" in text
    assert "tpu_cc_serve_inflight" in text
    assert 'tpu_cc_serve_requests_total{node="serve-node-0",outcome="completed"}' in text
    # The windowed SLO readout exists MID-RUN: a p99 gauge with data
    # and a burn-rate gauge (zero burn — nothing lost).
    assert "tpu_cc_serve_slo_p99_seconds" in text
    assert 'tpu_cc_serve_error_budget_burn{window="2"}' in text
    assert "tpu_cc_serve_goodput_rps" in text
    # The scrape passes the exposition lint — the live render is as
    # well-formed as the seeded one.
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "hack",
    ))
    import check_metrics_lint

    assert check_metrics_lint.lint(text) == []
    # /rolloutz served the LIVE flight recorder mid-flip: the plan is
    # there, the rollout is not complete yet.
    rz = scraped["rolloutz"]
    assert rz["enabled"] is True
    live_events = {e["event"] for e in rz["recent"]}
    assert "plan" in live_events
    assert "complete" not in live_events
    assert rz["trace_id"]
    # Post-run: the SLO snapshot rode into the report and the final
    # timeline completed.
    assert report["slo"]["windows"][0]["count"] >= 0
    assert report["slo"]["errors_total"] == 0
    from tpu_cc_manager.obs import flight as flight_mod

    events, torn = flight_mod.read_events(harness.flight.path)
    assert torn == 0
    assert {e["event"] for e in events} >= {"plan", "complete"}


@pytest.mark.slow
def test_rolling_flip_long_soak(tmp_path):
    """The long-form soak (chaos_soak.sh / manual): more nodes, longer
    window, max_unavailable=2 so two nodes drain concurrently."""
    harness = ServeHarness(
        n_nodes=5, tmp_dir=str(tmp_path), checkpoint_full_s=0.1,
    )
    harness.build()
    try:
        report = harness.run(
            traffic_s=20.0, rollout_mode="on", max_unavailable=2,
        )
    finally:
        harness.shutdown()
    print("SERVE_SUMMARY " + json.dumps(report))
    assert report["rollout_ok"]
    assert report["requests_lost"] == 0
    assert report["nodes_bounced"] == 5


# ---------------------------------------------------------------------------
# Drain-deadline hint bounds checkpoint time
# ---------------------------------------------------------------------------


def test_drain_deadline_hint_bounds_checkpoint_time(fake_kube):
    """A fast-drain deadline hint (drain.deadline-s, published by the
    preemption path) must SIZE the checkpoint: the server writes an
    incremental checkpoint that fits its budget share of the window
    instead of the full write the kill would truncate."""
    fake_kube.add_node(NODE)
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue,
        poll_interval_s=0.02, checkpoint_full_s=0.9,
        checkpoint_budget_fraction=0.5,
    )
    server.start()
    try:
        # The hint label carries WHOLE seconds (handshake.request_drain
        # floors at 1) — use second-scale values like the real 30 s path.
        cycle = handshake.request_drain(fake_kube, NODE, deadline_s=1.0)
        assert retry_mod.poll_until(lambda: server.drains >= 1, 5.0, 0.02)
        assert server.last_checkpoint_deadline_s == pytest.approx(1.0)
        # Budget = 1.0 * 0.5 = 0.5 s — the 0.9 s full write was cut down.
        assert server.last_checkpoint_s < 0.9
        assert server.last_checkpoint_s <= 0.5 + 0.2  # bracket overhead
        # The hinted cycle still acked (the manager's wait is satisfied).
        from tpu_cc_manager.kubeclient.api import node_labels

        labels = node_labels(fake_kube.get_node(NODE))
        assert labels[server.subscriber.label] == handshake.ack_value(cycle.token)

        # A NORMAL drain (no hint) pays the full checkpoint.
        handshake.clear_drain_request(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.resumes >= 1, 5.0, 0.02)
        handshake.request_drain(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.drains >= 2, 5.0, 0.02)
        assert server.last_checkpoint_deadline_s is None
        assert server.last_checkpoint_s >= 0.9
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Checkpoint-and-requeue: in-flight requests survive with progress
# ---------------------------------------------------------------------------


def test_inflight_requests_checkpoint_and_requeue_with_progress(fake_kube):
    fake_kube.add_node(NODE)
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue,
        executor=SimulatedExecutor(base_s=0.0, per_token_s=0.01),
        poll_interval_s=0.02, checkpoint_full_s=0.05,
    )
    server.start()
    try:
        now = time.monotonic()
        batch = [Request(req_id=i, decode_tokens=200, submitted_at=now)
                 for i in range(4)]
        assert server.submit(batch)
        # Mid-decode (200 tokens × 10 ms = 2 s of work), drain the node.
        time.sleep(0.15)  # cclint: test-sleep-ok(real decode time must elapse so the drain lands mid-batch)
        handshake.request_drain(fake_kube, NODE, deadline_s=1.0)
        assert retry_mod.poll_until(lambda: server.drains >= 1, 5.0, 0.02)
        assert retry_mod.poll_until(lambda: len(requeued) == 4, 5.0, 0.02)
        assert done == [], "a 2 s batch cannot have completed in 0.15 s"
        for r in requeued:
            assert 0 < r.tokens_done < 200, (
                "checkpointed progress must be preserved, not reset"
            )
            assert r.checkpoints >= 1
        # Draining server refuses new work — the driver must route around.
        assert server.submit([Request(99, 8, now)]) is False
    finally:
        server.stop()


def test_resume_reopens_intake(fake_kube):
    fake_kube.add_node(NODE)
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue,
        poll_interval_s=0.02, checkpoint_full_s=0.02,
    )
    server.start()
    try:
        handshake.request_drain(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.drains >= 1, 5.0, 0.02)
        assert not server.accepting()
        handshake.clear_drain_request(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.resumes >= 1, 5.0, 0.02)
        assert server.accepting()
        now = time.monotonic()
        assert server.submit([Request(1, 4, now)]) is True
        assert retry_mod.poll_until(lambda: len(done) == 1, 5.0, 0.02)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Batch ladder: conservative headroom off the hbm_bw_util lower bound
# ---------------------------------------------------------------------------


def test_batch_ladder_climbs_headroom_without_overshooting(fake_kube):
    """util(b) = 0.3 + 0.05·b: headroom up to b=12 at the 0.9 ceiling.
    The ladder must climb one rung per interval (the util read is a
    lower bound — smoke/llama_infer.py — so no rung-jumping) and settle
    without blowing past the ceiling."""
    fake_kube.add_node(NODE)
    executor = SimulatedExecutor(
        base_s=0.0, per_token_s=0.001, weight_frac=0.30, kv_frac=0.05,
    )
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue, executor=executor,
        poll_interval_s=5.0,  # no drain in this test; quiet the poller
    )
    driver = TrafficDriver(
        {NODE: server}, request_tokens=4, initial_batch=1, max_batch=16,
        util_ceiling=0.9, ladder_interval_s=0.05, submit_interval_s=0.005,
    )
    server._on_complete = driver.on_complete
    server._on_requeue = driver.on_requeue
    server.start()
    driver.start()
    try:
        assert retry_mod.poll_until(
            lambda: driver.snapshot_batches()[NODE] >= 12, 10.0, 0.05,
        ), f"ladder stalled at {driver.snapshot_batches()}"
        retry_mod.wait(0.3, None)
        final = driver.snapshot_batches()[NODE]
        # One overshoot rung is the most the ladder can carry past the
        # ceiling before the next util read steps it back.
        assert final <= 13, f"ladder overshot: batch={final}"
        assert executor.hbm_bw_util(final - 1) <= 0.9
    finally:
        driver.stop()
        server.stop()
    report = driver.report()
    assert report["requests_lost"] >= 0  # shape check
    assert report["batch_ladder"][NODE] == final


def test_executor_calibration_from_smoke_result():
    smoke = {"ms_per_token": 2.5, "hbm_bw_util": 0.6, "batch": 4,
             "hbm_bw_util_lower_bound": True}
    ex = SimulatedExecutor.from_smoke_result(smoke)
    assert ex.per_token_s == pytest.approx(0.0025)
    # The measured point is reproduced at the smoke's batch.
    assert ex.hbm_bw_util(4) == pytest.approx(0.6, abs=0.01)
    # And the model stays a monotone, capped lower-bound shape.
    assert ex.hbm_bw_util(8) > ex.hbm_bw_util(4)
    assert ex.hbm_bw_util(1000) == 1.0


# ---------------------------------------------------------------------------
# Open-loop overload serving (ISSUE 14 / SERVE_r02): rate-driven arrivals,
# admission control, knee finding — seeded property tests + tier-1 smoke
# ---------------------------------------------------------------------------


def test_poisson_schedule_is_seeded_and_matches_rate():
    from tpu_cc_manager.serve.driver import PoissonSchedule

    a = PoissonSchedule(200.0, seed=42)
    b = PoissonSchedule(200.0, seed=42)
    gaps_a = [a.next_interarrival_s(0.0) for _ in range(3000)]
    gaps_b = [b.next_interarrival_s(0.0) for _ in range(3000)]
    assert gaps_a == gaps_b, "same seed must give the same schedule"
    mean = sum(gaps_a) / len(gaps_a)
    # 3000 exponential samples: the mean interarrival is 1/rate within a
    # few percent (sigma/sqrt(n) ~ 1.8%).
    assert abs(mean - 1 / 200.0) < 0.1 / 200.0 * 10, mean
    assert PoissonSchedule(200.0, seed=1).next_interarrival_s(0.0) != gaps_a[0]


def test_ramp_schedule_rate_ramps_linearly():
    from tpu_cc_manager.serve.driver import RampSchedule

    s = RampSchedule(100.0, 500.0, duration_s=10.0, seed=3)
    assert s.rate_at(0.0) == pytest.approx(100.0)
    assert s.rate_at(5.0) == pytest.approx(300.0)
    assert s.rate_at(10.0) == pytest.approx(500.0)
    assert s.rate_at(99.0) == pytest.approx(500.0)  # holds after the ramp
    assert s.next_interarrival_s(0.0) > 0


def test_open_loop_offered_matches_scheduled_rate():
    """The no-coordinated-omission property: the driver mints arrivals at
    the SCHEDULE's rate, regardless of what the pool absorbs. Measured
    against a real (fast) pool over ~1s of traffic."""
    from tpu_cc_manager.serve import sweep as sweep_mod
    from tpu_cc_manager.serve.server import SimulatedExecutor

    rate = 400.0
    row = sweep_mod.run_rate_point(
        rate, n_nodes=1, traffic_s=1.0, deadline_s=0.5, seed=11,
        executor_factory=lambda: SimulatedExecutor(
            base_s=0.0005, per_token_s=0.0005,
        ),
    )
    # Poisson noise at ~400 samples is ~5%; 15% tolerance is safely wide
    # while still catching a closed-loop regression (which would track
    # the pool's capacity, not the schedule).
    assert row["offered_rps"] == pytest.approx(rate, rel=0.15), row
    assert row["conserved"], row


def test_open_loop_conservation_under_overload():
    """shed + completed + lost == issued at ~4x overload, with lost == 0:
    every refused request is an explicit, counted shed — nothing leaks."""
    from tpu_cc_manager.serve import sweep as sweep_mod
    from tpu_cc_manager.serve.server import SimulatedExecutor

    row = sweep_mod.run_rate_point(
        2000.0, n_nodes=1, traffic_s=1.0, deadline_s=0.3, seed=5,
        executor_factory=lambda: SimulatedExecutor(
            base_s=0.001, per_token_s=0.002,
        ),
    )
    assert row["conserved"], row
    assert row["lost"] == 0, row
    assert row["shed"] > 0, "4x overload must shed"
    assert row["issued"] == row["completed"] + row["shed"] + row["lost"]


def test_admission_control_sheds_spent_deadlines_at_intake(fake_kube):
    """Intake estimates queue delay from queue depth x the executor's
    calibrated per-token rate and sheds requests whose deadline budget
    is already spent — BEFORE they burn capacity."""
    fake_kube.add_node(NODE)
    shed, lock = [], threading.Lock()

    def on_shed(node, reqs):
        with lock:
            shed.extend(reqs)

    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue, on_shed=on_shed,
        executor=SimulatedExecutor(base_s=0.0, per_token_s=0.01),
        poll_interval_s=5.0,
    )
    server.start()
    try:
        now = time.monotonic()
        # 100 tokens x 10ms = 1s of work in flight: the queue estimate.
        busy = [Request(1, 100, now)]
        assert server.submit(busy)
        retry_mod.poll_until(lambda: server.queue_delay_estimate_s() > 0.5,
                             2.0, 0.01)
        # Deadline budget 50ms < ~1s estimated queue delay: shed.
        doomed = Request(2, 8, now, deadline_at=now + 0.05)
        assert server.submit([doomed])
        assert retry_mod.poll_until(lambda: len(shed) == 1, 2.0, 0.01)
        assert shed[0].req_id == 2
        assert doomed.attempts == 0, "a shed request was never admitted"
        # A generous budget is admitted alongside.
        fine = Request(3, 8, now, deadline_at=now + 30.0)
        assert server.submit([fine])
        assert fine.attempts == 1
        assert retry_mod.poll_until(
            lambda: any(r.req_id == 3 for r in done), 10.0, 0.02,
        )
    finally:
        server.stop()


def test_deadline_miss_counted_separately_from_shed():
    """An ACCEPTED request completing past its deadline is a miss (burns
    the error budget, counted per node) — not a shed, not a loss."""
    from tpu_cc_manager.obs.slo import SloEvaluator
    from tpu_cc_manager.utils.metrics import MetricsRegistry

    metrics = MetricsRegistry()
    slo = SloEvaluator(windows_s=(30.0,), error_budget=1e-2)
    driver = TrafficDriver({}, metrics=metrics, slo=slo)
    now = 1000.0
    hit = Request(1, 8, submitted_at=now, deadline_at=now + 1.0,
                  completed_at=now + 0.5)
    miss = Request(2, 8, submitted_at=now, deadline_at=now + 1.0,
                   completed_at=now + 2.0)
    driver._outstanding["n0"] = 2
    driver.on_complete("n0", hit, 0.5)
    driver.on_complete("n0", miss, 0.5)
    totals = metrics.serve_totals()
    assert totals["deadline_misses"] == {"n0": 1}
    assert totals["outcomes"][("n0", "completed")] == 2
    report = driver.report()
    assert report["deadline_misses"] == 1
    assert report["completed_within_deadline"] == 1
    # The miss burned the budget; the hit did not.
    assert slo.snapshot()["errors_total"] == 1


def test_find_knee_properties():
    """Pure-function knee properties on synthetic sweep rows: the knee is
    the LAST tracking+bounded rate; goodput monotone non-increasing past
    the knee holds; a fully-collapsed sweep has no knee."""
    from tpu_cc_manager.serve import sweep as sweep_mod

    def row(rate, goodput, qd99):
        return {"rate_rps": rate, "offered_rps": rate,
                "goodput_rps": goodput, "queue_delay_p99_ms": qd99,
                "deadline_ms": 500.0}

    rows = [
        row(100, 100, 20), row(200, 199, 30), row(400, 396, 60),
        row(800, 640, 480), row(1600, 560, 490),
    ]
    knee = sweep_mod.find_knee(rows)
    assert knee["rate_rps"] == 400  # 800 tracks only 80% < 0.95
    assert sweep_mod.goodput_holds_past_knee(rows, knee)  # >= 0.8*396
    # Goodput past the knee is monotone non-increasing here — the shape
    # shedding is supposed to produce (collapse would violate hold).
    past = [r["goodput_rps"] for r in rows if r["rate_rps"] > 400]
    assert past == sorted(past, reverse=True)
    # An unbounded queue delay disqualifies an otherwise-tracking rate.
    rows2 = [row(100, 100, 20), row(200, 199, 9000)]
    assert sweep_mod.find_knee(rows2)["rate_rps"] == 100
    # Collapse (goodput to ~zero) past the knee fails the hold bar.
    rows3 = [row(100, 100, 20), row(200, 30, 40), row(400, 5, 50)]
    knee3 = sweep_mod.find_knee(rows3)
    assert knee3["rate_rps"] == 100
    assert not sweep_mod.goodput_holds_past_knee(rows3, knee3)
    # Every rate already past the knee: no knee at all.
    assert sweep_mod.find_knee([row(100, 10, 9000)]) is None


def test_tiny_open_loop_sweep_smoke():
    """The tier-1 seconds-scale smoke: a 3-rate sweep on a fast fake
    executor finds a knee, conserves every request, and holds goodput
    past the knee (the SERVE_r02 shape end to end, minus the flip)."""
    from tpu_cc_manager.serve import sweep as sweep_mod
    from tpu_cc_manager.serve.server import SimulatedExecutor

    factory = lambda: SimulatedExecutor(base_s=0.0005, per_token_s=0.0005)
    rows = [
        sweep_mod.run_rate_point(
            rate, n_nodes=1, traffic_s=0.8, deadline_s=0.25, seed=9,
            executor_factory=factory,
        )
        for rate in (300.0, 1200.0, 2400.0)
    ]
    assert all(r["conserved"] and r["lost"] == 0 for r in rows), rows
    knee = sweep_mod.find_knee(rows)
    assert knee is not None, rows
    assert any(r["rate_rps"] > knee["rate_rps"] for r in rows), (
        "the sweep must go past the knee to prove anything"
    )
    # hold_frac=0.5: the tier-1 smoke runs on a REAL clock inside a
    # loaded suite, so scheduler jitter eats into goodput past the knee
    # far more than the dedicated BENCH_r0x runs — the claim here is
    # "sheds instead of collapsing", not the bench's 0.8 bar.
    assert sweep_mod.goodput_holds_past_knee(rows, knee, hold_frac=0.5), rows
    # Overload sheds; the knee does not (or barely).
    assert rows[-1]["shed_rate"] > rows[0]["shed_rate"]


def test_open_loop_overload_flip_sheds_but_never_loses(tmp_path):
    """The SERVE_r02 acceptance shape in tier-1: a rolling CC flip while
    an OPEN-LOOP overload-adjacent load keeps arriving on schedule.
    Accepted requests are never lost; refusals are explicit sheds; the
    during-rollout shed/miss buckets use the same overlap rule as
    latency."""
    from tpu_cc_manager.serve.driver import PoissonSchedule

    harness = ServeHarness(
        n_nodes=3, tmp_dir=str(tmp_path), checkpoint_full_s=0.05,
        driver_kwargs={
            "schedule": PoissonSchedule(900.0, seed=13),
            "deadline_s": 0.5,
            "initial_batch": 8, "min_batch": 8, "max_batch": 8,
        },
        slo_windows_s=(2.0, 30.0),
        slo_error_budget=0.05,
    )
    harness.build()
    try:
        report = harness.run(
            traffic_s=3.0, rollout_mode="on",
            slo_max_burn_rate=5.0, slo_window_s=2.0, slo_max_pause_s=20.0,
        )
    finally:
        harness.shutdown()
    print("SERVE_OVERLOAD_SUMMARY " + json.dumps({
        k: report[k] for k in (
            "requests_issued", "requests_completed", "requests_lost",
            "requests_shed", "shed_rate", "deadline_misses",
            "offered_rps", "goodput_rps", "conserved", "nodes_bounced",
            "shed_during_rollout", "shed_steady_state",
            "deadline_miss_during_rollout", "deadline_miss_steady_state",
            "rollout_slo_pauses", "rollout_wall_s",
        )
    }))
    assert report["rollout_ok"], report["rollout_summary"]
    assert report["nodes_bounced"] == 3
    assert report["requests_lost"] == 0, report
    assert report["conserved"], report
    assert report["requests_completed"] > 0
    assert report["offered_rps"] == pytest.approx(900.0, rel=0.2)
    # Shed/miss bucket splits are conserved against their totals.
    assert (report["shed_during_rollout"] + report["shed_steady_state"]
            == report["requests_shed"])
    assert (report["deadline_miss_during_rollout"]
            + report["deadline_miss_steady_state"]
            == report["deadline_misses"])

# ---------------------------------------------------------------------------
# Zero-bounce flips: serving-state handoff to accepting peers (SERVE_r03)
# ---------------------------------------------------------------------------


def _handoff_pool(fake_kube, n=2, per_token_s=0.01, checkpoint_full_s=0.05):
    """N servers + a driver with the handoff sink wired (the harness's
    construction-cycle pattern: servers first, sink assigned after).
    Completions tee into the returned ``done`` list so tests that
    submit directly (outside the driver's minting) can still inspect
    the Request objects."""
    done: list[Request] = []
    done_lock = threading.Lock()
    servers = {}
    for i in range(n):
        name = f"ho-node-{i}"
        fake_kube.add_node(name)
        servers[name] = NodeServer(
            fake_kube, name, lambda *a: None, lambda *a: None,
            executor=SimulatedExecutor(base_s=0.0, per_token_s=per_token_s),
            poll_interval_s=0.02, checkpoint_full_s=checkpoint_full_s,
        )
    driver = TrafficDriver(servers, submit_interval_s=0.005)

    def on_complete(node, req, util):
        with done_lock:
            done.append(req)
        driver.on_complete(node, req, util)

    for server in servers.values():
        server._on_complete = on_complete
        server._on_requeue = driver.on_requeue
        server._on_handoff = driver.on_handoff
    return servers, driver, done


def test_handoff_migrates_parked_requests_to_accepting_peer(fake_kube):
    """The zero-bounce path itself: a draining node's parked in-flight
    batch lands DIRECTLY in an accepting peer's queue inside the ack
    window — progress intact, latency still stamped at original
    arrival, the restore charged at the peer — and completes there
    without ever returning to the driver's queue."""
    servers, driver, done = _handoff_pool(fake_kube)
    a, b = servers["ho-node-0"], servers["ho-node-1"]
    for server in servers.values():
        server.start()
    try:
        now = time.monotonic()
        batch = [Request(req_id=i, decode_tokens=100, submitted_at=now)
                 for i in range(4)]
        assert a.submit(batch)
        time.sleep(0.1)  # cclint: test-sleep-ok(real decode time must elapse so the drain lands mid-batch)
        handshake.request_drain(fake_kube, "ho-node-0")
        assert retry_mod.poll_until(lambda: a.drains >= 1, 5.0, 0.02)
        assert a.last_handoff_accepted == 4, (
            "every parked request must migrate to the accepting peer"
        )
        # The migrated batch finishes on the PEER.
        assert retry_mod.poll_until(lambda: len(done) == 4, 10.0, 0.02)
        report = driver.report()
        assert report["handoffs"] == {"accepted": 4, "fallback": 0}
        for r in done:
            assert r.handoffs == 1
            assert not r.restore_pending, "restore must be consumed at dispatch"
            assert r.submitted_at == now, "latency stays stamped at arrival"
            assert r.tokens_done == 100
    finally:
        for server in servers.values():
            server.stop()


def test_handoff_without_accepting_peer_falls_back_to_requeue(fake_kube):
    """Every peer draining: the sink must fall back to today's local
    requeue (front of the driver queue) — counted outcome=fallback,
    conserved, completed after the pool resumes."""
    servers, driver, done = _handoff_pool(fake_kube)
    a, b = servers["ho-node-0"], servers["ho-node-1"]
    for server in servers.values():
        server.start()
    try:
        # Drain B FIRST so A's later drain finds no accepting peer.
        handshake.request_drain(fake_kube, "ho-node-1")
        assert retry_mod.poll_until(lambda: b.drains >= 1, 5.0, 0.02)
        now = time.monotonic()
        batch = [Request(req_id=i, decode_tokens=100, submitted_at=now)
                 for i in range(3)]
        assert a.submit(batch)
        time.sleep(0.1)  # cclint: test-sleep-ok(real decode time must elapse so the drain lands mid-batch)
        handshake.request_drain(fake_kube, "ho-node-0")
        assert retry_mod.poll_until(lambda: a.drains >= 1, 5.0, 0.02)
        report = driver.report()
        assert report["handoffs"]["accepted"] == 0
        assert report["handoffs"]["fallback"] == 3
        # Resume the pool; drain_outstanding pumps dispatch rounds
        # (mint-free) until the fallback batch completes on a peer.
        handshake.clear_drain_request(fake_kube, "ho-node-0")
        handshake.clear_drain_request(fake_kube, "ho-node-1")
        assert retry_mod.poll_until(lambda: a.accepting() and b.accepting(),
                                    5.0, 0.02)
        driver.drain_outstanding(grace_s=10.0)
        assert len(done) == 3, done
        for r in done:
            assert r.handoffs == 0, "a fallback request took the requeue path"
    finally:
        for server in servers.values():
            server.stop()


def test_handoff_conservation_property_under_randomized_drain_races():
    """Seeded property (the ISSUE's conservation bar): across randomized
    drain/resume races — peers accepting, refusing, or mid-drain
    themselves when the sink offers them work — every request ends
    exactly one way. With closed-loop traffic and no deadlines nothing
    may be shed or lost, so conservation pins every parked request to
    completed (possibly via handoff and/or requeue hops)."""
    import random

    from tpu_cc_manager.kubeclient.fake import FakeKube

    rng = random.Random(20260804)
    kube = FakeKube()
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    servers = {}
    for i in range(3):
        name = f"race-node-{i}"
        kube.add_node(name)
        servers[name] = NodeServer(
            kube, name, on_complete, on_requeue,
            executor=SimulatedExecutor(base_s=0.0, per_token_s=0.002),
            poll_interval_s=0.01, checkpoint_full_s=0.01,
        )
    driver = TrafficDriver(
        servers, request_tokens=16, submit_interval_s=0.002,
        initial_batch=4, min_batch=4, max_batch=4,
    )
    for server in servers.values():
        server._on_complete = driver.on_complete
        server._on_requeue = driver.on_requeue
        server._on_handoff = driver.on_handoff
        server.start()
    driver.start()
    draining: set = set()
    try:
        for _ in range(30):
            name = rng.choice(sorted(servers))
            if name in draining:
                handshake.clear_drain_request(kube, name)
                draining.discard(name)
            else:
                handshake.request_drain(kube, name)
                draining.add(name)
            retry_mod.wait(rng.uniform(0.01, 0.06), None)
    finally:
        for name in sorted(draining):
            handshake.clear_drain_request(kube, name)
        driver.stop()
    driver.drain_outstanding(grace_s=15.0)
    report = driver.report()
    for server in servers.values():
        server.stop()
    print("HANDOFF_RACE_SUMMARY " + json.dumps({
        k: report[k] for k in (
            "requests_issued", "requests_completed", "requests_lost",
            "requests_requeued", "handoffs", "conserved",
        )
    }))
    assert report["conserved"], report
    assert report["requests_lost"] == 0, report
    assert report["requests_shed"] == 0
    assert report["requests_issued"] == report["requests_completed"]
    # The races must actually have exercised the sink.
    total = report["handoffs"]["accepted"] + report["handoffs"]["fallback"]
    assert total > 0, "the race schedule never handed anything off"


def test_rolling_flip_with_handoff_keeps_p99_near_steady(tmp_path):
    """The SERVE_r03 shape in tier-1 (chaos-marked; chaos_soak.sh
    scrapes the HANDOFF_SUMMARY line): a rolling flip with the handoff
    sink wired loses zero requests, hands off a nonzero number of
    parked requests, and keeps the during-rollout latency bucket from
    exploding (a loose 3x envelope here — the committed SERVE_r03
    artifact holds the real <=1.3x bar at the knee)."""
    harness = ServeHarness(
        n_nodes=3, tmp_dir=str(tmp_path), checkpoint_full_s=0.05,
        handoff=True,
    )
    harness.build()
    try:
        report = harness.run(traffic_s=3.0, rollout_mode="on")
    finally:
        harness.shutdown()
    print("HANDOFF_SUMMARY " + json.dumps({
        k: report[k] for k in (
            "requests_issued", "requests_completed", "requests_lost",
            "requests_requeued", "handoffs", "conserved", "nodes_bounced",
            "latency_during_rollout", "latency_steady_state",
            "rollout_wall_s",
        )
    }))
    assert report["rollout_ok"], report["rollout_summary"]
    assert report["nodes_bounced"] == 3
    assert report["requests_lost"] == 0, report
    assert report["conserved"], report
    assert report["handoffs"]["accepted"] > 0, report["handoffs"]
    during = report["latency_during_rollout"]["p99_ms"]
    steady = report["latency_steady_state"]["p99_ms"]
    assert during is not None and steady is not None
    assert during <= 3.0 * steady, (
        f"during-rollout p99 {during}ms vs steady {steady}ms: the "
        "handoff path should keep the flip close to invisible"
    )
