"""Serving under the flip (tpu_cc_manager/serve/): a rolling CC flip over
a fake pool of REAL agents under sustained traffic loses ZERO requests,
the drain-deadline hint bounds checkpoint time, in-flight requests
checkpoint-and-requeue with progress intact, and the batch ladder climbs
the conservative hbm_bw_util headroom without overshooting.

Chaos-marked (tier-1 runs the short soak; hack/chaos_soak.sh reruns it
with -s and scrapes the SERVE_SUMMARY line) and — like the other chaos
suites — everything here runs with the CC_LOCKCHECK runtime lock-order
checker on, so the serve/ thread soup is machine-checked for inversions
on every run.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from tpu_cc_manager.drain import handshake
from tpu_cc_manager.serve.driver import TrafficDriver
from tpu_cc_manager.serve.harness import ServeHarness
from tpu_cc_manager.serve.server import NodeServer, Request, SimulatedExecutor
from tpu_cc_manager.utils import retry as retry_mod

pytestmark = pytest.mark.chaos

NODE = "serve-test-0"


@pytest.fixture(autouse=True)
def _lockcheck(monkeypatch):
    """Chaos-suite convention (tests/test_chaos.py): the runtime
    lock-order checker is ON for every scenario here, and the
    process-wide order graph is reset around each test."""
    from tpu_cc_manager.utils import locks as locks_rt

    locks_rt.GRAPH.reset()
    monkeypatch.setenv("CC_LOCKCHECK", "1")
    yield
    locks_rt.GRAPH.reset()


def collecting_callbacks():
    done, requeued = [], []
    lock = threading.Lock()

    def on_complete(node, req, util):
        with lock:
            done.append(req)

    def on_requeue(node, reqs):
        with lock:
            requeued.extend(reqs)

    return done, requeued, on_complete, on_requeue


# ---------------------------------------------------------------------------
# The headline: rolling flip under traffic, zero requests lost
# ---------------------------------------------------------------------------


def test_rolling_flip_under_traffic_loses_zero_requests(tmp_path):
    """Short soak (the long one is slow-marked): 3 real agents, live
    driver traffic, a real rolling CC flip mid-stream. Zero requests
    lost, every node bounced exactly once through its drain handshake,
    and the during-rollout latency bucket actually has data."""
    harness = ServeHarness(
        n_nodes=3, tmp_dir=str(tmp_path), checkpoint_full_s=0.05,
    )
    harness.build()
    try:
        report = harness.run(traffic_s=3.0, rollout_mode="on")
    finally:
        harness.shutdown()
    print("SERVE_SUMMARY " + json.dumps({
        k: report[k] for k in (
            "requests_issued", "requests_completed", "requests_lost",
            "requests_requeued", "error_rate", "nodes_bounced",
            "requests_lost_per_node_bounced", "latency",
            "latency_during_rollout", "latency_steady_state",
            "batch_ladder", "rollout_wall_s",
        )
    }))
    assert report["rollout_ok"], report["rollout_summary"]
    assert report["nodes_bounced"] == 3
    assert report["requests_lost"] == 0, report
    assert report["requests_lost_per_node_bounced"] == 0
    assert report["error_rate"] == 0.0
    assert report["requests_completed"] > 0
    assert report["latency_during_rollout"]["count"] > 0, (
        "the rollout window must have served traffic"
    )
    assert report["latency_steady_state"]["count"] > 0
    # Every server went through exactly one drain/resume handshake.
    for name, d in report["drains"].items():
        assert d["drains"] == 1, report["drains"]
        assert d["resumes"] == 1, report["drains"]


def test_live_serve_metrics_scraped_DURING_the_flip(tmp_path):
    """ISSUE 12 acceptance bar: a ServeHarness rolling flip exports
    live tpu_cc_serve_* metrics (latency histogram + queue/inflight
    gauges + outcome counters) and a windowed p99/burn-rate readout
    MID-RUN — asserted by scraping /metrics (and /rolloutz) from inside
    the orchestrator's mid-window hook, so "during the flip" is true by
    construction, not by sleep-timing."""
    import urllib.request

    harness = ServeHarness(
        n_nodes=3, tmp_dir=str(tmp_path), checkpoint_full_s=0.05,
        metrics_port=0,  # ephemeral; harness serves its SHARED registry
        slo_windows_s=(2.0, 30.0),
    )
    harness.build()
    addr = harness.metrics_address()
    assert addr is not None
    scraped: dict = {}

    def scrape_mid_window(point: str) -> None:
        # Runs on the orchestrator thread at named rollout points; one
        # scrape at the first mid-window (a node is draining RIGHT NOW).
        if point != "mid-window" or scraped:
            return
        with urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=5
        ) as resp:
            scraped["metrics"] = resp.read().decode()
        with urllib.request.urlopen(
            f"http://{addr}/rolloutz", timeout=5
        ) as resp:
            scraped["rolloutz"] = json.loads(resp.read().decode())

    try:
        report = harness.run(
            traffic_s=3.0, rollout_mode="on",
            rollout_hook=scrape_mid_window,
        )
    finally:
        harness.shutdown()
    assert report["rollout_ok"]
    assert report["requests_lost"] == 0
    text = scraped.get("metrics")
    assert text, "the mid-window hook never scraped"
    # Live latency histogram with per-node labels and fixed buckets.
    assert "tpu_cc_serve_request_seconds_bucket" in text
    assert 'node="serve-node-0"' in text
    assert 'le="+Inf"' in text
    # Queue-depth / in-flight gauges and outcome counters are live.
    assert "tpu_cc_serve_queue_depth" in text
    assert "tpu_cc_serve_inflight" in text
    assert 'tpu_cc_serve_requests_total{node="serve-node-0",outcome="completed"}' in text
    # The windowed SLO readout exists MID-RUN: a p99 gauge with data
    # and a burn-rate gauge (zero burn — nothing lost).
    assert "tpu_cc_serve_slo_p99_seconds" in text
    assert 'tpu_cc_serve_error_budget_burn{window="2"}' in text
    assert "tpu_cc_serve_goodput_rps" in text
    # The scrape passes the exposition lint — the live render is as
    # well-formed as the seeded one.
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "hack",
    ))
    import check_metrics_lint

    assert check_metrics_lint.lint(text) == []
    # /rolloutz served the LIVE flight recorder mid-flip: the plan is
    # there, the rollout is not complete yet.
    rz = scraped["rolloutz"]
    assert rz["enabled"] is True
    live_events = {e["event"] for e in rz["recent"]}
    assert "plan" in live_events
    assert "complete" not in live_events
    assert rz["trace_id"]
    # Post-run: the SLO snapshot rode into the report and the final
    # timeline completed.
    assert report["slo"]["windows"][0]["count"] >= 0
    assert report["slo"]["errors_total"] == 0
    from tpu_cc_manager.obs import flight as flight_mod

    events, torn = flight_mod.read_events(harness.flight.path)
    assert torn == 0
    assert {e["event"] for e in events} >= {"plan", "complete"}


@pytest.mark.slow
def test_rolling_flip_long_soak(tmp_path):
    """The long-form soak (chaos_soak.sh / manual): more nodes, longer
    window, max_unavailable=2 so two nodes drain concurrently."""
    harness = ServeHarness(
        n_nodes=5, tmp_dir=str(tmp_path), checkpoint_full_s=0.1,
    )
    harness.build()
    try:
        report = harness.run(
            traffic_s=20.0, rollout_mode="on", max_unavailable=2,
        )
    finally:
        harness.shutdown()
    print("SERVE_SUMMARY " + json.dumps(report))
    assert report["rollout_ok"]
    assert report["requests_lost"] == 0
    assert report["nodes_bounced"] == 5


# ---------------------------------------------------------------------------
# Drain-deadline hint bounds checkpoint time
# ---------------------------------------------------------------------------


def test_drain_deadline_hint_bounds_checkpoint_time(fake_kube):
    """A fast-drain deadline hint (drain.deadline-s, published by the
    preemption path) must SIZE the checkpoint: the server writes an
    incremental checkpoint that fits its budget share of the window
    instead of the full write the kill would truncate."""
    fake_kube.add_node(NODE)
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue,
        poll_interval_s=0.02, checkpoint_full_s=0.9,
        checkpoint_budget_fraction=0.5,
    )
    server.start()
    try:
        # The hint label carries WHOLE seconds (handshake.request_drain
        # floors at 1) — use second-scale values like the real 30 s path.
        cycle = handshake.request_drain(fake_kube, NODE, deadline_s=1.0)
        assert retry_mod.poll_until(lambda: server.drains >= 1, 5.0, 0.02)
        assert server.last_checkpoint_deadline_s == pytest.approx(1.0)
        # Budget = 1.0 * 0.5 = 0.5 s — the 0.9 s full write was cut down.
        assert server.last_checkpoint_s < 0.9
        assert server.last_checkpoint_s <= 0.5 + 0.2  # bracket overhead
        # The hinted cycle still acked (the manager's wait is satisfied).
        from tpu_cc_manager.kubeclient.api import node_labels

        labels = node_labels(fake_kube.get_node(NODE))
        assert labels[server.subscriber.label] == handshake.ack_value(cycle.token)

        # A NORMAL drain (no hint) pays the full checkpoint.
        handshake.clear_drain_request(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.resumes >= 1, 5.0, 0.02)
        handshake.request_drain(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.drains >= 2, 5.0, 0.02)
        assert server.last_checkpoint_deadline_s is None
        assert server.last_checkpoint_s >= 0.9
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Checkpoint-and-requeue: in-flight requests survive with progress
# ---------------------------------------------------------------------------


def test_inflight_requests_checkpoint_and_requeue_with_progress(fake_kube):
    fake_kube.add_node(NODE)
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue,
        executor=SimulatedExecutor(base_s=0.0, per_token_s=0.01),
        poll_interval_s=0.02, checkpoint_full_s=0.05,
    )
    server.start()
    try:
        now = time.monotonic()
        batch = [Request(req_id=i, decode_tokens=200, submitted_at=now)
                 for i in range(4)]
        assert server.submit(batch)
        # Mid-decode (200 tokens × 10 ms = 2 s of work), drain the node.
        time.sleep(0.15)  # cclint: test-sleep-ok(real decode time must elapse so the drain lands mid-batch)
        handshake.request_drain(fake_kube, NODE, deadline_s=1.0)
        assert retry_mod.poll_until(lambda: server.drains >= 1, 5.0, 0.02)
        assert retry_mod.poll_until(lambda: len(requeued) == 4, 5.0, 0.02)
        assert done == [], "a 2 s batch cannot have completed in 0.15 s"
        for r in requeued:
            assert 0 < r.tokens_done < 200, (
                "checkpointed progress must be preserved, not reset"
            )
            assert r.checkpoints >= 1
        # Draining server refuses new work — the driver must route around.
        assert server.submit([Request(99, 8, now)]) is False
    finally:
        server.stop()


def test_resume_reopens_intake(fake_kube):
    fake_kube.add_node(NODE)
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue,
        poll_interval_s=0.02, checkpoint_full_s=0.02,
    )
    server.start()
    try:
        handshake.request_drain(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.drains >= 1, 5.0, 0.02)
        assert not server.accepting()
        handshake.clear_drain_request(fake_kube, NODE)
        assert retry_mod.poll_until(lambda: server.resumes >= 1, 5.0, 0.02)
        assert server.accepting()
        now = time.monotonic()
        assert server.submit([Request(1, 4, now)]) is True
        assert retry_mod.poll_until(lambda: len(done) == 1, 5.0, 0.02)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Batch ladder: conservative headroom off the hbm_bw_util lower bound
# ---------------------------------------------------------------------------


def test_batch_ladder_climbs_headroom_without_overshooting(fake_kube):
    """util(b) = 0.3 + 0.05·b: headroom up to b=12 at the 0.9 ceiling.
    The ladder must climb one rung per interval (the util read is a
    lower bound — smoke/llama_infer.py — so no rung-jumping) and settle
    without blowing past the ceiling."""
    fake_kube.add_node(NODE)
    executor = SimulatedExecutor(
        base_s=0.0, per_token_s=0.001, weight_frac=0.30, kv_frac=0.05,
    )
    done, requeued, on_complete, on_requeue = collecting_callbacks()
    server = NodeServer(
        fake_kube, NODE, on_complete, on_requeue, executor=executor,
        poll_interval_s=5.0,  # no drain in this test; quiet the poller
    )
    driver = TrafficDriver(
        {NODE: server}, request_tokens=4, initial_batch=1, max_batch=16,
        util_ceiling=0.9, ladder_interval_s=0.05, submit_interval_s=0.005,
    )
    server._on_complete = driver.on_complete
    server._on_requeue = driver.on_requeue
    server.start()
    driver.start()
    try:
        assert retry_mod.poll_until(
            lambda: driver.snapshot_batches()[NODE] >= 12, 10.0, 0.05,
        ), f"ladder stalled at {driver.snapshot_batches()}"
        retry_mod.wait(0.3, None)
        final = driver.snapshot_batches()[NODE]
        # One overshoot rung is the most the ladder can carry past the
        # ceiling before the next util read steps it back.
        assert final <= 13, f"ladder overshot: batch={final}"
        assert executor.hbm_bw_util(final - 1) <= 0.9
    finally:
        driver.stop()
        server.stop()
    report = driver.report()
    assert report["requests_lost"] >= 0  # shape check
    assert report["batch_ladder"][NODE] == final


def test_executor_calibration_from_smoke_result():
    smoke = {"ms_per_token": 2.5, "hbm_bw_util": 0.6, "batch": 4,
             "hbm_bw_util_lower_bound": True}
    ex = SimulatedExecutor.from_smoke_result(smoke)
    assert ex.per_token_s == pytest.approx(0.0025)
    # The measured point is reproduced at the smoke's batch.
    assert ex.hbm_bw_util(4) == pytest.approx(0.6, abs=0.01)
    # And the model stays a monotone, capped lower-bound shape.
    assert ex.hbm_bw_util(8) > ex.hbm_bw_util(4)
    assert ex.hbm_bw_util(1000) == 1.0
