"""Peer-relative fail-slow vetting (obs/failslow.py) and its
containment surfaces: the false-positive bound under healthy jitter,
the detection-latency bound, the remediation ladder's non-probe
escalation, the serve driver's suspect de-weighting, the rolling
orchestrator's journaled exactly-once acting + straggler wall, and the
fleet gateway's slow-vs-dead scrape distinction."""

import random

import pytest

from tpu_cc_manager.ccmanager.remediation import (
    STEP_QUARANTINE,
    STEP_RUNTIME_RESTART,
    RemediationLadder,
)
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import FAILSLOW_SUSPECT_LABEL
from tpu_cc_manager.obs.failslow import (
    VERDICT_CLEARED,
    VERDICT_CONFIRMED,
    FailslowVetter,
    publish_suspect_labels,
)

NODES = [f"n{i}" for i in range(6)]


def feed_window(vetter, latencies_by_node, samples=4):
    for node, lat in latencies_by_node.items():
        for _ in range(samples):
            vetter.observe(node, lat)


# ---------------------------------------------------------------------------
# The false-positive bound (the ISSUE's seeded property test)
# ---------------------------------------------------------------------------


def test_healthy_fleet_under_20pct_jitter_is_never_suspected():
    """The documented FP bound: with threshold 2.0, +/-20 % latency
    jitter on a homogeneous fleet caps the peer ratio at 1.2/0.8 = 1.5
    — strictly inside the threshold — so across 200 seeded trials of
    8 windows each, NO node may ever collect a strike, let alone a
    verdict. This is the property that makes fail-slow containment safe
    to leave on: jitter alone can never quarantine a healthy node."""
    for trial in range(200):
        rng = random.Random(31_000 + trial)
        vetter = FailslowVetter(
            window_s=1.0, threshold=2.0, min_windows=2, min_peers=3,
            min_samples=3,
        )
        base = 0.02 + rng.random() * 0.2  # fleet-wide latency level
        for _ in range(8):
            for node in NODES:
                for _ in range(5):
                    jitter = 0.8 + rng.random() * 0.4  # +/-20 %
                    vetter.observe(node, base * jitter)
            vetter.vet()
        assert vetter.concluded() == [], f"trial {trial} concluded"
        assert vetter.suspects() == set(), f"trial {trial} suspected"


def test_detection_within_min_windows_of_onset():
    """Detection-latency bound: a node going 3x deviant is confirmed on
    exactly the ``min_windows``-th window after onset (default 2) —
    one strike window of hysteresis, then the verdict. No faster (one
    bad window is weather), no slower (the bound ctl/ops quote)."""
    vetter = FailslowVetter(min_windows=2, min_peers=3, min_samples=3)
    healthy = {n: 0.05 for n in NODES}
    feed_window(vetter, healthy)
    assert vetter.vet() == []
    # Onset: n0 triples. Window 1 after onset -> strike, suspect.
    feed_window(vetter, {**healthy, "n0": 0.15})
    assert vetter.vet() == []
    assert vetter.suspects() == {"n0"}
    # Window 2 after onset -> confirmed: latency <= 2 windows.
    feed_window(vetter, {**healthy, "n0": 0.15})
    verdicts = vetter.vet()
    assert [v["verdict"] for v in verdicts] == [VERDICT_CONFIRMED]
    assert verdicts[0]["node"] == "n0"
    assert verdicts[0]["deviation"] == pytest.approx(3.0, abs=0.01)


def test_reconcluding_verdicts_get_fresh_monotonic_ids():
    """A still-deviant confirmed node re-concludes every window under a
    NEW id — the consumer's escalation edge (verdict 1 restart,
    verdict 2 quarantine) and the dedup key for journaled acting."""
    vetter = FailslowVetter(min_windows=1, min_peers=3, min_samples=3)
    healthy = {n: 0.05 for n in NODES}
    for _ in range(3):
        feed_window(vetter, {**healthy, "n0": 0.2})
        vetter.vet()
    ids = [v["id"] for v in vetter.concluded()]
    assert ids == [1, 2, 3]
    assert all(v["verdict"] == VERDICT_CONFIRMED for v in vetter.concluded())
    # Non-draining: reading twice sees the same list.
    assert [v["id"] for v in vetter.concluded()] == ids


def test_clear_requires_consecutive_recovered_windows():
    """Flapping is not recovery: one recovered window followed by one
    deviant window resets the clear streak; only ``clear_windows``
    CONSECUTIVE recovered windows conclude a cleared verdict (and drop
    the node from the suspect set)."""
    vetter = FailslowVetter(
        min_windows=1, clear_windows=2, min_peers=3, min_samples=3,
    )
    healthy = {n: 0.05 for n in NODES}
    feed_window(vetter, {**healthy, "n0": 0.2})
    vetter.vet()
    assert vetter.confirmed() == {"n0"}
    # Recovered... then deviant again: streak resets, still confirmed.
    feed_window(vetter, healthy)
    vetter.vet()
    feed_window(vetter, {**healthy, "n0": 0.2})
    vetter.vet()
    feed_window(vetter, healthy)
    vetter.vet()
    assert vetter.confirmed() == {"n0"}
    # Second consecutive recovered window -> cleared.
    feed_window(vetter, healthy)
    verdicts = vetter.vet()
    assert [v["verdict"] for v in verdicts] == [VERDICT_CLEARED]
    assert vetter.confirmed() == set()
    assert vetter.suspects() == set()


def test_abstains_below_min_peers_and_strikes_hold():
    """No fleet, no verdict: below min_peers participating nodes the
    window abstains — strikes neither advance nor reset — so a partial
    outage cannot push a half-struck node over the line."""
    vetter = FailslowVetter(min_windows=2, min_peers=3, min_samples=3)
    healthy = {n: 0.05 for n in NODES}
    feed_window(vetter, {**healthy, "n0": 0.2})
    assert vetter.vet() == []
    assert vetter.suspects() == {"n0"}
    # Only 2 nodes produce samples: abstain, strike count holds.
    feed_window(vetter, {"n0": 0.2, "n1": 0.05})
    assert vetter.vet() == []
    assert vetter.suspects() == {"n0"}
    # Fleet back: the held strike plus this one confirm.
    feed_window(vetter, {**healthy, "n0": 0.2})
    assert [v["verdict"] for v in vetter.vet()] == [VERDICT_CONFIRMED]


def test_ingest_exposition_deltas_cumulative_families():
    """The scrape-fed path: cumulative sum/count deltas become window
    samples (first call only primes), so a FleetGateway rollup can feed
    the vetter without per-request hooks."""
    vetter = FailslowVetter(min_windows=1, min_peers=3, min_samples=1)

    def expo(sums, counts):
        lines = []
        for n in sums:
            lines.append(
                'tpu_cc_serve_request_seconds_sum{node="%s"} %s' % (n, sums[n])
            )
            lines.append(
                'tpu_cc_serve_request_seconds_count{node="%s"} %s'
                % (n, counts[n])
            )
        return "\n".join(lines) + "\n"

    nodes = ["a", "b", "c", "d"]
    assert vetter.ingest_exposition(
        expo({n: 0.0 for n in nodes}, {n: 0 for n in nodes})
    ) == 0  # priming read contributes nothing
    # Interval means: a/b/c at 50 ms, d at 300 ms.
    sums = {"a": 0.5, "b": 0.5, "c": 0.5, "d": 3.0}
    counts = {n: 10 for n in nodes}
    assert vetter.ingest_exposition(expo(sums, counts)) == 4
    verdicts = vetter.vet()
    assert [v["node"] for v in verdicts] == ["d"]
    assert verdicts[0]["verdict"] == VERDICT_CONFIRMED


def test_publish_suspect_labels_sets_and_clears():
    fake = FakeKube()
    fake.add_node("n0", {})
    publish_suspect_labels(fake, added=["n0"], removed=[])
    assert node_labels(fake.get_node("n0"))[FAILSLOW_SUSPECT_LABEL] == "true"
    publish_suspect_labels(fake, added=[], removed=["n0"])
    assert FAILSLOW_SUSPECT_LABEL not in node_labels(fake.get_node("n0"))


# ---------------------------------------------------------------------------
# Remediation ladder: the non-probe fail-slow rungs
# ---------------------------------------------------------------------------


def test_ladder_failslow_escalates_restart_then_quarantine():
    """Confirmed verdict 1 -> runtime restart (the cheapest action that
    un-wedges a degraded runtime); a re-concluded verdict after that ->
    quarantine with reason=fail-slow. The watchdog was green the whole
    time — this path never consumed a probe failure."""
    fake = FakeKube()
    fake.add_node("gray-0", {})
    ladder = RemediationLadder(fake, "gray-0")
    assert ladder.note_failslow(3.4) == STEP_RUNTIME_RESTART
    assert not ladder.quarantined
    assert ladder.note_failslow(3.2) == STEP_QUARANTINE
    assert ladder.quarantined
    assert ladder.last_reason == "fail-slow"
    # Already contained: further verdicts are no-ops, not re-taints.
    assert ladder.note_failslow(3.1) == STEP_QUARANTINE


def test_ladder_failslow_state_survives_agent_restart():
    """The escalation counter persists in the node annotation: a FRESH
    ladder (agent restart, or the rolling orchestrator's successor
    acting a journaled verdict) resumes at the next rung instead of
    restarting the runtime forever — the cross-process half of
    exactly-once containment."""
    fake = FakeKube()
    fake.add_node("gray-1", {})
    assert RemediationLadder(fake, "gray-1").note_failslow(3.0) == (
        STEP_RUNTIME_RESTART
    )
    successor = RemediationLadder(fake, "gray-1")
    assert successor.note_failslow(3.0) == STEP_QUARANTINE
    assert successor.last_reason == "fail-slow"


def test_ladder_failslow_recovered_resets_escalation():
    """A cleared verdict before quarantine forgets the escalation (the
    restart fixed it): the NEXT confirmed verdict starts at the cheap
    rung again. A quarantined node is NOT released here — that goes
    through probation, same as every quarantine."""
    fake = FakeKube()
    fake.add_node("gray-2", {})
    ladder = RemediationLadder(fake, "gray-2")
    ladder.note_failslow(2.5)
    ladder.note_failslow_recovered()
    assert ladder.note_failslow(2.5) == STEP_RUNTIME_RESTART


# ---------------------------------------------------------------------------
# Serve driver: suspect de-weighting
# ---------------------------------------------------------------------------


class StubServer:
    def __init__(self) -> None:
        self.got: list = []

    def accepting(self) -> bool:
        return True

    def submit(self, batch, front: bool = False) -> bool:
        self.got.extend(batch)
        return True


def _drain_rounds(driver, rounds=8):
    for _ in range(rounds):
        driver._dispatch_round(top_up=False)


def test_driver_caps_suspects_at_min_batch_in_flight():
    """A suspect node is capped at min_batch IN FLIGHT (its trickle is
    bounded by its own service rate): with nothing completing, repeated
    dispatch rounds give it exactly min_batch requests while healthy
    peers fill their full pipes."""
    from tpu_cc_manager.serve.driver import Request, TrafficDriver

    servers = {"h0": StubServer(), "h1": StubServer(), "gray": StubServer()}
    driver = TrafficDriver(
        servers, initial_batch=4, min_batch=1, max_batch=4, pipe_depth=1,
    )
    driver.set_suspects({"gray"})
    with driver._lock:
        driver._pending = [Request(req_id=i, decode_tokens=1, submitted_at=0.0) for i in range(32)]
    _drain_rounds(driver)
    assert len(servers["gray"].got) == 1, "suspect trickle must be min_batch"
    assert len(servers["h0"].got) == 4
    assert len(servers["h1"].got) == 4


def test_driver_suspect_trickle_survives_fleet_headroom():
    """The starvation regression: suspects draw their one-in-flight
    trickle FIRST, so a fleet with spare capacity (healthy nodes could
    absorb everything) still feeds the suspect the samples vetting
    needs to ever clear it."""
    from tpu_cc_manager.serve.driver import Request, TrafficDriver

    servers = {"h0": StubServer(), "gray": StubServer()}
    driver = TrafficDriver(
        servers, initial_batch=8, min_batch=1, max_batch=8, pipe_depth=2,
    )
    driver.set_suspects({"gray"})
    # Fewer pending than the healthy node's pipe: without
    # suspect-first ordering, h0 would drink the whole queue.
    with driver._lock:
        driver._pending = [Request(req_id=i, decode_tokens=1, submitted_at=0.0) for i in range(4)]
    _drain_rounds(driver)
    assert len(servers["gray"].got) == 1
    assert len(servers["h0"].got) == 3


def test_driver_deweight_disabled_when_all_accepting_are_suspect():
    """De-weighting the WHOLE pool would just shed it: when every
    accepting node is suspect, the cap is ignored and dispatch proceeds
    at full batch."""
    from tpu_cc_manager.serve.driver import Request, TrafficDriver

    servers = {"g0": StubServer(), "g1": StubServer()}
    driver = TrafficDriver(
        servers, initial_batch=4, min_batch=1, max_batch=4, pipe_depth=1,
    )
    driver.set_suspects({"g0", "g1"})
    with driver._lock:
        driver._pending = [Request(req_id=i, decode_tokens=1, submitted_at=0.0) for i in range(8)]
    _drain_rounds(driver)
    assert len(servers["g0"].got) == 4
    assert len(servers["g1"].got) == 4


# ---------------------------------------------------------------------------
# Rolling orchestrator: journaled acting, group skip, straggler wall
# ---------------------------------------------------------------------------

POOL = "pool=tpu"


def _add_pool(fake, n=4):
    for i in range(n):
        fake.add_node(f"node-{i}", {"pool": "tpu"})


def _agent_simulator(fake):
    import threading

    from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL

    def reactor(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired:
            t = threading.Timer(
                0.05,
                lambda: fake.set_node_label(
                    name, CC_MODE_STATE_LABEL, desired
                ),
            )
            t.daemon = True
            t.start()

    fake.add_patch_reactor(reactor)


class ScriptedVetter:
    """Concludes a fixed verdict list; non-draining like the real one."""

    def __init__(self, verdicts, suspects=frozenset()):
        self._verdicts = list(verdicts)
        self._suspects = set(suspects)

    def concluded(self):
        return [dict(v) for v in self._verdicts]

    def suspects(self):
        return set(self._suspects)


def test_rolling_acts_confirmed_verdict_and_skips_its_group():
    """A confirmed verdict flowing through the rollout: journaled in
    the record path, acted through failslow_act exactly once, the
    victim's group skipped (never bounced — its members are already
    being contained) and its disruption budget charged."""
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL

    fake = FakeKube()
    _add_pool(fake, 4)
    _agent_simulator(fake)
    acts: list[tuple] = []
    roller = RollingReconfigurator(
        fake, POOL, node_timeout_s=5, poll_interval_s=0.02,
        failslow_vetter=ScriptedVetter(
            [{"id": 1, "node": "node-3", "verdict": "confirmed",
              "deviation": 3.5}],
            suspects={"node-3"},
        ),
        failslow_act=lambda node, e: acts.append((node, e["id"], e["verdict"])),
    )
    result = roller.rollout("on")
    assert result.ok
    assert acts == [("node-3", "1", "confirmed")]
    labels = node_labels(fake.get_node("node-3"))
    assert labels.get(CC_MODE_STATE_LABEL) != "on", (
        "confirmed fail-slow group must be skipped, not bounced"
    )
    for i in range(3):
        assert node_labels(
            fake.get_node(f"node-{i}")
        )[CC_MODE_STATE_LABEL] == "on"


def test_rolling_cleared_verdict_acts_without_skipping():
    """A cleared verdict is acted (the consumer lifts its escalation)
    but never charges budget or skips the node's group."""
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
    from tpu_cc_manager.labels import CC_MODE_STATE_LABEL

    fake = FakeKube()
    _add_pool(fake, 3)
    _agent_simulator(fake)
    acts: list[str] = []
    roller = RollingReconfigurator(
        fake, POOL, node_timeout_s=5, poll_interval_s=0.02,
        failslow_vetter=ScriptedVetter(
            [{"id": 1, "node": "node-0", "verdict": "cleared",
              "deviation": 0.9}],
        ),
        failslow_act=lambda node, e: acts.append(e["verdict"]),
    )
    result = roller.rollout("on")
    assert result.ok
    assert acts == ["cleared"]
    for i in range(3):
        assert node_labels(
            fake.get_node(f"node-{i}")
        )[CC_MODE_STATE_LABEL] == "on"


def test_straggler_wall_is_peer_relative():
    """The wall is max(floor, factor * median(peer convergence)) once
    enough history exists — and absent (None) below min_peers samples
    or when the factor is unset, so early waves fall back to the
    absolute node timeout."""
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator

    fake = FakeKube()
    _add_pool(fake, 2)
    roller = RollingReconfigurator(
        fake, POOL, node_timeout_s=5, poll_interval_s=0.02,
        straggler_factor=3.0, straggler_min_peers=3,
        straggler_floor_s=0.1,
    )
    assert roller._straggler_wall() is None  # no history yet
    for s in (0.2, 0.4, 0.2):
        roller._note_converge_seconds(s)
    assert roller._straggler_wall() == pytest.approx(0.6)  # 3.0 * 0.2
    # The floor wins over a tiny median.
    fast = RollingReconfigurator(
        fake, POOL, node_timeout_s=5, poll_interval_s=0.02,
        straggler_factor=2.0, straggler_min_peers=2,
        straggler_floor_s=1.0,
    )
    for s in (0.01, 0.01):
        fast._note_converge_seconds(s)
    assert fast._straggler_wall() == pytest.approx(1.0)
    # Unset factor: the feature is off.
    plain = RollingReconfigurator(
        fake, POOL, node_timeout_s=5, poll_interval_s=0.02,
    )
    plain._note_converge_seconds(0.2)
    assert plain._straggler_wall() is None


def test_straggler_factor_must_exceed_one():
    from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator

    fake = FakeKube()
    _add_pool(fake, 2)
    with pytest.raises(ValueError):
        RollingReconfigurator(
            fake, POOL, node_timeout_s=5, poll_interval_s=0.02,
            straggler_factor=0.9,
        )


# ---------------------------------------------------------------------------
# Fleet gateway: slow-vs-dead scrape distinction
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self) -> None:
        self.t = 100.0

    def __call__(self) -> float:
        return self.t


def test_gateway_distinguishes_slow_from_dead():
    """A scrape that SUCCEEDS but overruns slow_scrape_s is a gray
    signal, not an outage: the node stays in the rollups (the vetter
    needs its samples) but loses has_headroom, and /fleetz reports it
    under slow_nodes — apart from dead/stale."""
    from tpu_cc_manager.obs import fleet as fleet_mod

    clk = FakeClock()
    body = (
        '# HELP tpu_cc_serve_request_seconds request latency\n'
        '# TYPE tpu_cc_serve_request_seconds histogram\n'
        'tpu_cc_serve_request_seconds_bucket{node="x",le="+Inf"} 3\n'
        'tpu_cc_serve_request_seconds_sum{node="x"} 0.3\n'
        'tpu_cc_serve_request_seconds_count{node="x"} 3\n'
    )

    def fast_fetch(path):
        return body if path == "/metrics" else "{}"

    def slow_fetch(path):
        clk.t += 0.9  # each hop drags; total scrape >> slow_scrape_s
        return body if path == "/metrics" else "{}"

    def dead_fetch(path):
        raise OSError("connection refused")

    gateway = fleet_mod.FleetGateway(
        targets={
            "fast-0": fast_fetch, "slow-0": slow_fetch, "dead-0": dead_fetch,
        },
        scrape_deadline_s=2.0, slow_scrape_s=1.0, clock=clk, workers=1,
        stale_after_sweeps=1,
    )
    fleetz = gateway.scrape_once()
    nodes = fleetz["nodes"]
    assert nodes["fast-0"]["scrape_slow"] is False
    assert nodes["fast-0"]["stale"] is False
    assert nodes["slow-0"]["scrape_slow"] is True
    assert nodes["slow-0"]["stale"] is False
    assert nodes["slow-0"]["has_headroom"] is False, (
        "slow capacity is phantom: the prestage pacer must not spend it"
    )
    assert nodes["dead-0"]["stale"] is True
    assert fleetz["fleet"]["slow_nodes"] == ["slow-0"]
    assert fleetz["fleet"]["stale_nodes"] == ["dead-0"]
    text = gateway.metrics_text()
    assert "tpu_cc_fleet_nodes_slow 1" in text
    # Slow != dead in the rollups: the slow node's histogram is merged.
    assert 'tpu_cc_serve_request_seconds_count{node="x"} 6' in text


# ---------------------------------------------------------------------------
# Chaos leg (hack/chaos_soak.sh scrapes the GRAY_SUMMARY line)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_brownout_detected_contained_and_lifted_under_traffic(
    tmp_path, monkeypatch,
):
    """The short-soak gray-failure loop, end to end under live traffic
    (the long, calibrated form is `hack/serve_bench.py --brownout` ->
    GRAY_r01.json): a mid-run brownout slows one node 6x without
    failing anything; the peer-relative vetter must detect it, the vet
    loop must escalate runtime-restart -> quarantine(reason=fail-slow),
    the fleet must lose ZERO requests, and once the brownout clears the
    cleared verdict + probation must lift the quarantine — full cycle,
    one test."""
    import json
    import threading
    import time as time_mod

    from tpu_cc_manager.labels import QUARANTINED_LABEL
    from tpu_cc_manager.serve.harness import ServeHarness
    from tpu_cc_manager.utils import locks as locks_rt

    locks_rt.GRAPH.reset()
    monkeypatch.setenv("CC_LOCKCHECK", "1")
    harness = ServeHarness(
        n_nodes=4, tmp_dir=str(tmp_path), checkpoint_full_s=0.02,
        failslow=True,
        failslow_kwargs={
            "window_s": 0.4, "threshold": 2.0, "min_windows": 1,
            "min_peers": 3, "min_samples": 3, "clear_windows": 2,
        },
        failslow_probation_s=0.8,
    )
    harness.build()
    victim = "serve-node-1"
    marks: dict = {}

    def chaos():
        time_mod.sleep(1.2)
        harness.set_brownout(victim, 6.0)
        marks["onset"] = time_mod.monotonic()
        deadline = marks["onset"] + 3.5
        while time_mod.monotonic() < deadline:
            if QUARANTINED_LABEL in node_labels(harness.kube.get_node(victim)):
                marks["quarantined"] = time_mod.monotonic()
                break
            time_mod.sleep(0.02)
        harness.set_brownout(victim, 1.0)
        marks["cleared"] = time_mod.monotonic()

    thread = threading.Thread(target=chaos, daemon=True)
    thread.start()
    try:
        report = harness.run(traffic_s=7.0, rollout_mode=None)
        thread.join(timeout=10)
        # The vet loop is still pacing windows: give the cleared
        # verdict + probation a bounded tail to lift the quarantine.
        ladder = harness.ladders[victim]
        deadline = time_mod.monotonic() + 10.0
        while time_mod.monotonic() < deadline:
            if not ladder.quarantined and QUARANTINED_LABEL not in (
                node_labels(harness.kube.get_node(victim))
            ):
                break
            time_mod.sleep(0.05)
    finally:
        harness.shutdown()
    detection_s = (
        round(marks["quarantined"] - marks["onset"], 3)
        if "quarantined" in marks else None
    )
    verdicts = {
        f"{n}/{v}": c
        for (n, v), c in harness.metrics.failslow_totals()["verdicts"].items()
    }
    print("GRAY_SUMMARY " + json.dumps({
        "requests_issued": report["requests_issued"],
        "requests_completed": report["requests_completed"],
        "requests_lost": report["requests_lost"],
        "victim": victim,
        "detection_s": detection_s,
        "quarantined": "quarantined" in marks,
        "restored": not harness.ladders[victim].quarantined,
        "verdicts": verdicts,
    }))
    assert report["requests_lost"] == 0, report
    assert "quarantined" in marks, (
        f"brownout never contained; deviation="
        f"{harness.failslow_vetter.deviation(victim)}"
    )
    assert detection_s is not None and detection_s <= 3.5
    assert harness.ladders[victim].last_reason == "fail-slow"
    assert not harness.ladders[victim].quarantined, (
        "cleared brownout must lift the quarantine via probation"
    )
    assert QUARANTINED_LABEL not in node_labels(harness.kube.get_node(victim))
    assert verdicts.get(f"{victim}/confirmed", 0) >= 2
    assert verdicts.get(f"{victim}/cleared", 0) >= 1
