"""HTTP-level tests for the observability surface: /metrics (histograms,
label escaping), /statusz, /tracez — via a real start_metrics_server on an
ephemeral port — plus the acceptance scenario: one full fake-backend
reconcile produces one trace whose span tree carries every phase, readable
from /tracez AND the JSONL journal."""

from __future__ import annotations

import json
import urllib.request

import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.metrics_server import start_metrics_server
from tpu_cc_manager.labels import DRAIN_COMPONENT_LABELS, MODE_ON
from tpu_cc_manager.obs.journal import Journal
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "obs-node-0"
NS = "tpu-operator"


def _get(server, path: str) -> tuple[int, str]:
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # noqa: F821 - urllib.request imports it
        return e.code, e.read().decode()


@pytest.fixture()
def served():
    registry = MetricsRegistry()
    journal = Journal(capacity=256, trace_file="")
    server = start_metrics_server(
        0, registry, bind="127.0.0.1", journal=journal
    )
    try:
        yield server, registry, journal
    finally:
        server.shutdown()


def _run_reconcile(fake_kube, registry, journal, smoke_runner=None, **kw):
    fake_kube.add_node(NODE, {k: "true" for k in DRAIN_COMPONENT_LABELS})
    mgr = CCManager(
        api=fake_kube,
        backend=FakeTpuBackend(num_chips=2),
        node_name=NODE,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="matmul",
        smoke_runner=smoke_runner
        or (lambda w: {"ok": True, "workload": w, "backend": "cpu"}),
        eviction_poll_interval_s=0.01,
        metrics=registry,
        journal=journal,
        **kw,
    )
    return mgr.set_cc_mode(MODE_ON)


EXPECTED_PHASES = {"drain", "stage", "reset", "wait_ready", "attest", "smoke", "readmit"}


def test_full_reconcile_trace_via_tracez_and_jsonl(
    served, fake_kube, tmp_path
):
    """Acceptance: one fake-backend reconcile → one trace whose span tree
    contains drain, reset, wait_ready, attest, smoke and readmit spans
    sharing a single trace_id, retrievable from /tracez AND the JSONL
    journal; /metrics exposes the phase histogram and failure counters."""
    server, registry, _ = served
    trace_file = tmp_path / "trace.jsonl"
    journal = Journal(capacity=256, trace_file=str(trace_file))
    # Re-serve with the journal the manager writes to.
    server2 = start_metrics_server(
        0, registry, bind="127.0.0.1", journal=journal
    )
    try:
        assert _run_reconcile(fake_kube, registry, journal) is True
        trace_id = registry.last().trace_id
        assert trace_id

        status, body = _get(server2, f"/tracez?trace_id={trace_id}")
        assert status == 200
        payload = json.loads(body)
        names = {s["name"] for s in payload["spans"]}
        assert EXPECTED_PHASES <= names, names
        assert {s["trace_id"] for s in payload["spans"]} == {trace_id}
        # The nested tree has the reconcile root with the phases under it.
        (root,) = [t for t in payload["tree"] if t["name"] == "reconcile"]
        child_names = {c["name"] for c in root["children"]}
        assert EXPECTED_PHASES <= child_names
        # Sub-spans nest deeper: the drain phase carries its pause step.
        (drain,) = [c for c in root["children"] if c["name"] == "drain"]
        assert "drain.pause_components" in {
            c["name"] for c in drain["children"]
        }

        # Same trace in the JSONL file, one JSON object per line.
        lines = [
            json.loads(line)
            for line in trace_file.read_text().strip().splitlines()
        ]
        jsonl_names = {
            s["name"] for s in lines if s["trace_id"] == trace_id
        }
        assert EXPECTED_PHASES <= jsonl_names
    finally:
        server2.shutdown()

    # /metrics histogram contract (the registry is served by the fixture
    # server; both servers share it).
    status, text = _get(server, "/metrics")
    assert status == 200
    assert 'tpu_cc_phase_seconds_bucket{mode="on",phase="reset",le="+Inf"} 1' in text
    assert 'tpu_cc_phase_seconds_count{mode="on",phase="reset"} 1' in text


def test_statusz_reports_last_reconcile_and_totals(served, fake_kube):
    server, registry, journal = served
    assert _run_reconcile(fake_kube, registry, journal) is True
    status, body = _get(server, "/statusz")
    assert status == 200
    payload = json.loads(body)
    assert payload["mode"] == "on"
    assert payload["last_reconcile"]["result"] == "ok"
    assert payload["last_reconcile"]["trace_id"] == registry.last().trace_id
    assert set(payload["last_reconcile"]["phases"]) >= EXPECTED_PHASES
    assert payload["result_totals"]["ok"] == 1
    assert payload["in_flight"] == []  # nothing running now
    assert payload["journal_traces"] >= 1


def test_statusz_in_flight_span_tree(served):
    from tpu_cc_manager.obs import trace

    server, _, journal = served
    with trace.root_span("reconcile", journal=journal, mode="on"):
        with trace.span("drain"):
            status, body = _get(server, "/statusz")
    assert status == 200
    tree = json.loads(body)["in_flight"]
    (root,) = tree
    assert root["name"] == "reconcile"
    assert [c["name"] for c in root["children"]] == ["drain"]
    assert root["status"] == "in_progress"


def test_tracez_filters_and_limits(served):
    from tpu_cc_manager.obs import trace

    server, _, journal = served
    ids = []
    for i in range(3):
        with trace.root_span(f"op-{i}", journal=journal) as sp:
            ids.append(sp.trace_id)
    status, body = _get(server, "/tracez")
    assert status == 200
    payload = json.loads(body)
    assert payload["count"] == 3
    assert payload["trace_ids"] == ids

    status, body = _get(server, f"/tracez?trace_id={ids[1]}")
    payload = json.loads(body)
    assert [s["name"] for s in payload["spans"]] == ["op-1"]

    status, body = _get(server, "/tracez?limit=2")
    assert json.loads(body)["count"] == 2

    # Unparseable limit falls back to the default instead of erroring.
    status, body = _get(server, "/tracez?limit=bogus")
    assert status == 200


def test_metrics_failure_counter_and_exposition_lint(served, fake_kube):
    """A failing reconcile increments tpu_cc_failures_total{reason=...};
    the full exposition (with a hostile mode string in the labels) passes
    the Prometheus lint."""
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "hack")
    )
    from check_metrics_lint import lint

    from tpu_cc_manager.smoke.runner import SmokeError

    server, registry, journal = served

    def failing_smoke(workload):
        raise SmokeError("smoke exploded")

    assert (
        _run_reconcile(fake_kube, registry, journal, smoke_runner=failing_smoke)
        is False
    )
    status, text = _get(server, "/metrics")
    assert status == 200
    assert 'tpu_cc_failures_total{reason="smoke-failed"} 1' in text

    # Inject a label-hostile mode via the registry directly: the render
    # must escape it so the scrape still parses.
    m = registry.start('evil"mode\nwith\\stuff')
    with m.phase("reset"):
        pass
    m.finish("ok")
    status, text = _get(server, "/metrics")
    assert status == 200
    problems = lint(text)
    assert problems == [], problems
    assert r'evil\"mode\nwith\\stuff' in text


def test_escaped_label_values_roundtrip():
    from tpu_cc_manager.utils.metrics import _escape_label_value

    assert _escape_label_value('a"b') == r"a\"b"
    assert _escape_label_value("a\nb") == r"a\nb"
    assert _escape_label_value("a\\b") == r"a\\b"
    assert _escape_label_value("plain") == "plain"


def test_unknown_path_is_404(served):
    server, _, _ = served
    status, _ = _get(server, "/nope")
    assert status == 404


def test_rolloutz_disabled_without_a_flight_recorder(served):
    server, _, _ = served
    status, text = _get(server, "/rolloutz")
    assert status == 200
    assert json.loads(text) == {"enabled": False}


def test_rolloutz_serves_the_live_flight_snapshot(tmp_path):
    from tpu_cc_manager.obs.flight import FlightRecorder

    flight = FlightRecorder(
        str(tmp_path / "f.jsonl"), generation=2, trace_id="deadbeef"
    )
    flight.record("plan", mode="on", groups=3)
    flight.record("window-open", wave=0, window=0)
    server = start_metrics_server(
        0, MetricsRegistry(), bind="127.0.0.1",
        journal=Journal(trace_file=""), flight=flight,
    )
    try:
        status, text = _get(server, "/rolloutz")
        assert status == 200
        payload = json.loads(text)
        assert payload["enabled"] is True
        assert payload["generation"] == 2
        assert payload["trace_id"] == "deadbeef"
        assert payload["torn_lines"] == 0
        assert [e["event"] for e in payload["recent"]] == [
            "plan", "window-open",
        ]
        # Live: a later event appears on the next scrape.
        flight.record("window-close", wave=0, window=0, seconds=1.0)
        _, text = _get(server, "/rolloutz")
        assert "window-close" in text
    finally:
        server.shutdown()
