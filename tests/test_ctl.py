"""Operator CLI command functions (ctl.py) against the fake apiserver."""

import argparse

from tpu_cc_manager import ctl
from tpu_cc_manager.ccmanager.multislice import publish_quote
from tpu_cc_manager.ccmanager.rolling import SLICE_ID_LABEL
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils import retry as retry_mod


def ns(**kw):
    return argparse.Namespace(**kw)


def test_status_lists_nodes(fake_kube, capsys):
    fake_kube.add_node("n0", {"pool": "tpu", CC_MODE_LABEL: "on",
                              CC_MODE_STATE_LABEL: "on"})
    rc = ctl.cmd_status(fake_kube, ns(selector="pool=tpu"))
    out = capsys.readouterr().out
    assert rc == 0 and "n0" in out and "on" in out


def test_status_surfaces_barrier_and_failure_reason(fake_kube, capsys):
    from tpu_cc_manager.ccmanager.slicecoord import SLICE_STAGED_LABEL
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    fake_kube.add_node("n1", {
        "pool": "tpu",
        SLICE_STAGED_LABEL: "slice",  # mid-barrier
    })
    fake_kube.add_node("n2", {
        "pool": "tpu",
        CC_MODE_STATE_LABEL: "failed",
        CC_FAILED_REASON_LABEL: "slice-mode-unsupported",
    })
    rc = ctl.cmd_status(fake_kube, ns(selector="pool=tpu"))
    out = capsys.readouterr().out
    assert rc == 0
    assert "barrier:staged=slice" in out
    assert "reason=slice-mode-unsupported" in out


def test_attest_ok_and_fail(fake_kube, capsys):
    quote = FakeTpuBackend(slice_id="s1", initial_mode="on").fetch_attestation("n")
    fake_kube.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    publish_quote(fake_kube, "n0", quote)
    assert ctl.cmd_attest(
        fake_kube, ns(selector="pool=tpu", mode="on", slices=None,
                      max_age=3600, allow_fake=True)
    ) == 0
    # Without --allow-fake the same pool FAILS: fake-platform quotes are
    # forgeries to a production verifier.
    assert ctl.cmd_attest(
        fake_kube, ns(selector="pool=tpu", mode="on", slices=None,
                      max_age=3600)
    ) == 1
    assert ctl.cmd_attest(
        fake_kube, ns(selector="pool=tpu", mode="off", slices=None,
                      max_age=3600, allow_fake=True)
    ) == 1
    assert "FAIL" in capsys.readouterr().out


def test_rbac_check_on_non_rest_client(fake_kube, capsys):
    """self_subject_access_review is part of the KubeApi contract (ADVICE
    r4 #2): rbac-check must run — not AttributeError — on any client."""
    assert ctl.cmd_rbac_check(fake_kube, ns(namespace="tpu-operator")) == 0
    assert "OK: RBAC sufficient" in capsys.readouterr().out
    # Narrowed grants surface as failures, proving the fake consults them.
    fake_kube.rbac_rules = {("get", "nodes"): True}  # everything else denied
    assert ctl.cmd_rbac_check(fake_kube, ns(namespace="tpu-operator")) == 1
    assert "DENIED" in capsys.readouterr().out


def test_rbac_check_base_client_raises_cleanly():
    """The ABC default raises KubeApiError, not AttributeError."""
    import pytest

    from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError

    class Minimal(KubeApi):
        def get_node(self, name):  # pragma: no cover - unused
            raise NotImplementedError

        def patch_node_labels(self, name, labels):  # pragma: no cover
            raise NotImplementedError

        def list_nodes(self, label_selector=None):  # pragma: no cover
            raise NotImplementedError

        def list_pods(self, *a, **kw):  # pragma: no cover
            raise NotImplementedError

        def watch_nodes(self, *a, **kw):  # pragma: no cover
            raise NotImplementedError

    with pytest.raises(KubeApiError):
        Minimal().self_subject_access_review("get", "nodes")


def test_drain_subscribe_sidecar(fake_kube, tmp_path):
    """The code-free handshake sidecar: a drain request runs the
    checkpoint command, acks with the cycle token, and the request
    clearing runs the resume command; SIGTERM-equivalent stop
    unregisters."""
    import threading
    import time

    from tpu_cc_manager.drain import handshake
    from tpu_cc_manager.kubeclient.api import node_labels

    fake_kube.add_node("n0")
    marker = tmp_path / "ckpt"
    resume_marker = tmp_path / "resumed"
    args = ns(
        job="side-job", node="n0",
        on_drain=f"touch {marker}",
        on_resume=f"touch {resume_marker}",
        poll_interval=0.01,
    )
    t = threading.Thread(
        target=ctl.cmd_drain_subscribe, args=(fake_kube, args), daemon=True
    )
    t.start()
    try:
        sub_label = handshake.subscriber_label("side-job")
        retry_mod.poll_until(
            lambda: sub_label in node_labels(fake_kube.get_node("n0")),
            5.0, 0.01,
        )
        cycle = handshake.request_drain(fake_kube, "n0")
        assert handshake.await_workload_acks(
            fake_kube, "n0", timeout_s=5, poll_interval_s=0.01,
            token=cycle.token,
        ) == []
        assert marker.exists()  # the checkpoint command actually ran
        handshake.clear_drain_request(fake_kube, "n0")
        assert retry_mod.poll_until(resume_marker.exists, 5.0, 0.01)
    finally:
        # What the SIGTERM handler does in a real pod shutdown.
        args.subscriber.stop(timeout_s=0)
        t.join(timeout=5)
    assert not t.is_alive()
    # Clean exit unregistered the subscriber: no ghost for the manager.
    assert sub_label not in node_labels(fake_kube.get_node("n0"))


def test_drain_subscribe_requires_node(fake_kube, monkeypatch):
    monkeypatch.delenv("NODE_NAME", raising=False)
    import pytest

    with pytest.raises(ValueError):
        ctl.cmd_drain_subscribe(
            fake_kube, ns(job="j", node=None, on_drain="true",
                          on_resume=None, poll_interval=0.01)
        )


def test_rollout_command(fake_kube, capsys):
    fake_kube.add_node("n0", {"pool": "tpu"})

    def agent(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired:
            fake_kube.set_node_label(name, CC_MODE_STATE_LABEL, desired)

    fake_kube.add_patch_reactor(agent)
    rc = ctl.cmd_rollout(
        fake_kube,
        ns(selector="pool=tpu", mode="on", max_unavailable=1,
           node_timeout=5.0, continue_on_failure=False,
           rollback_on_failure=False),
    )
    assert rc == 0
    assert '"ok": true' in capsys.readouterr().out


def test_attest_challenge_round(fake_kube, capsys):
    """`attest --challenge`: issue, await the (simulated) agent's answer,
    verify with challenged freshness; a silent pool fails instead."""
    import threading

    from tpu_cc_manager.ccmanager import multislice

    backend = FakeTpuBackend(slice_id="s1", initial_mode="on")
    fake_kube.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    publish_quote(fake_kube, "n0", backend.fetch_attestation("stale"))

    def answer_when_challenged():
        if retry_mod.poll_until(
            lambda: multislice.challenge_nonce_of(fake_kube.get_node("n0")),
            5.0, 0.01,
        ):
            nonce = multislice.challenge_nonce_of(fake_kube.get_node("n0"))
            publish_quote(fake_kube, "n0", backend.fetch_attestation(nonce))

    t = threading.Thread(target=answer_when_challenged, daemon=True)
    t.start()
    rc = ctl.cmd_attest(
        fake_kube,
        ns(selector="pool=tpu", mode="on", slices=None, max_age=3600,
           allow_fake=True, challenge=True, challenge_timeout=5.0),
    )
    t.join(timeout=5)
    out = capsys.readouterr().out
    assert rc == 0 and "challenged re-attestation" in out

    # No agent answering the NEXT challenge round -> the stale quote
    # fails the challenged path loudly.
    rc = ctl.cmd_attest(
        fake_kube,
        ns(selector="pool=tpu", mode="on", slices=None, max_age=3600,
           allow_fake=True, challenge=True, challenge_timeout=0.05),
    )
    out = capsys.readouterr().out
    assert rc == 1 and "FAIL" in out


def test_attest_challenge_rejects_no_verify_signatures(fake_kube):
    """--challenge + --no-verify-signatures is contradictory: the
    challenge binding lives inside the signed quote the other flag says
    not to read."""
    import pytest

    with pytest.raises(ValueError, match="no-verify-signatures"):
        ctl.cmd_attest(
            fake_kube,
            ns(selector="pool=tpu", mode="on", slices=None, max_age=3600,
               allow_fake=True, challenge=True, challenge_timeout=1.0,
               no_verify_signatures=True),
        )


# -- per-region flag syntax (ISSUE 18) --------------------------------------


def test_parse_regions_plain_and_with_contexts():
    regions, contexts = ctl._parse_regions("r1,r2,r3")
    assert regions == ["r1", "r2", "r3"] and contexts == {}
    regions, contexts = ctl._parse_regions("r1=ctx-a, r2=ctx-b")
    assert regions == ["r1", "r2"]
    assert contexts == {"r1": "ctx-a", "r2": "ctx-b"}


def test_parse_regions_refuses_duplicates_and_partial_contexts():
    import pytest

    with pytest.raises(ValueError, match="duplicate"):
        ctl._parse_regions("r1,r1")
    # All-or-nothing on contexts: half a federation silently sharing the
    # local cluster is the mixup the explicit form prevents.
    with pytest.raises(ValueError, match="EVERY"):
        ctl._parse_regions("r1=ctx-a,r2")
    with pytest.raises(ValueError, match="empty kubeconfig context"):
        ctl._parse_regions("r1=")


def test_parse_per_region_int_defaults_and_overrides():
    regions = ["r1", "r2"]
    assert ctl._parse_per_region_int(None, "--x", regions) == (None, {})
    assert ctl._parse_per_region_int("3", "--x", regions) == (3, {})
    default, per = ctl._parse_per_region_int("2,r2=5", "--x", regions)
    assert default == 2 and per == {"r2": 5}


def test_parse_per_region_int_refusals():
    import pytest

    regions = ["r1", "r2"]
    with pytest.raises(ValueError, match="unknown region"):
        ctl._parse_per_region_int("zz=3", "--x", regions)
    with pytest.raises(ValueError, match="duplicate region"):
        ctl._parse_per_region_int("r1=1,r1=2", "--x", regions)
    with pytest.raises(ValueError, match="more than one bare"):
        ctl._parse_per_region_int("1,2", "--x", regions)


def test_plain_int_flag_refuses_per_region_syntax_without_regions():
    import pytest

    assert ctl._plain_int_flag(None, "--x") is None
    assert ctl._plain_int_flag(4, "--x") == 4
    assert ctl._plain_int_flag("7", "--x") == 7
    with pytest.raises(ValueError, match="requires --regions"):
        ctl._plain_int_flag("r1=2", "--x")
    with pytest.raises(ValueError, match="requires --regions"):
        ctl._plain_int_flag("2,3", "--x")
