"""Operator CLI command functions (ctl.py) against the fake apiserver."""

import argparse

from tpu_cc_manager import ctl
from tpu_cc_manager.ccmanager.multislice import publish_quote
from tpu_cc_manager.ccmanager.rolling import SLICE_ID_LABEL
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.labels import CC_MODE_LABEL, CC_MODE_STATE_LABEL
from tpu_cc_manager.tpudev.fake import FakeTpuBackend


def ns(**kw):
    return argparse.Namespace(**kw)


def test_status_lists_nodes(fake_kube, capsys):
    fake_kube.add_node("n0", {"pool": "tpu", CC_MODE_LABEL: "on",
                              CC_MODE_STATE_LABEL: "on"})
    rc = ctl.cmd_status(fake_kube, ns(selector="pool=tpu"))
    out = capsys.readouterr().out
    assert rc == 0 and "n0" in out and "on" in out


def test_status_surfaces_barrier_and_failure_reason(fake_kube, capsys):
    from tpu_cc_manager.ccmanager.slicecoord import SLICE_STAGED_LABEL
    from tpu_cc_manager.labels import CC_FAILED_REASON_LABEL

    fake_kube.add_node("n1", {
        "pool": "tpu",
        SLICE_STAGED_LABEL: "slice",  # mid-barrier
    })
    fake_kube.add_node("n2", {
        "pool": "tpu",
        CC_MODE_STATE_LABEL: "failed",
        CC_FAILED_REASON_LABEL: "slice-mode-unsupported",
    })
    rc = ctl.cmd_status(fake_kube, ns(selector="pool=tpu"))
    out = capsys.readouterr().out
    assert rc == 0
    assert "barrier:staged=slice" in out
    assert "reason=slice-mode-unsupported" in out


def test_attest_ok_and_fail(fake_kube, capsys):
    quote = FakeTpuBackend(slice_id="s1", initial_mode="on").fetch_attestation("n")
    fake_kube.add_node("n0", {"pool": "tpu", SLICE_ID_LABEL: "s1"})
    publish_quote(fake_kube, "n0", quote)
    assert ctl.cmd_attest(
        fake_kube, ns(selector="pool=tpu", mode="on", slices=None,
                      max_age=3600, allow_fake=True)
    ) == 0
    # Without --allow-fake the same pool FAILS: fake-platform quotes are
    # forgeries to a production verifier.
    assert ctl.cmd_attest(
        fake_kube, ns(selector="pool=tpu", mode="on", slices=None,
                      max_age=3600)
    ) == 1
    assert ctl.cmd_attest(
        fake_kube, ns(selector="pool=tpu", mode="off", slices=None,
                      max_age=3600, allow_fake=True)
    ) == 1
    assert "FAIL" in capsys.readouterr().out


def test_rbac_check_on_non_rest_client(fake_kube, capsys):
    """self_subject_access_review is part of the KubeApi contract (ADVICE
    r4 #2): rbac-check must run — not AttributeError — on any client."""
    assert ctl.cmd_rbac_check(fake_kube, ns(namespace="tpu-operator")) == 0
    assert "OK: RBAC sufficient" in capsys.readouterr().out
    # Narrowed grants surface as failures, proving the fake consults them.
    fake_kube.rbac_rules = {("get", "nodes"): True}  # everything else denied
    assert ctl.cmd_rbac_check(fake_kube, ns(namespace="tpu-operator")) == 1
    assert "DENIED" in capsys.readouterr().out


def test_rbac_check_base_client_raises_cleanly():
    """The ABC default raises KubeApiError, not AttributeError."""
    import pytest

    from tpu_cc_manager.kubeclient.api import KubeApi, KubeApiError

    class Minimal(KubeApi):
        def get_node(self, name):  # pragma: no cover - unused
            raise NotImplementedError

        def patch_node_labels(self, name, labels):  # pragma: no cover
            raise NotImplementedError

        def list_nodes(self, label_selector=None):  # pragma: no cover
            raise NotImplementedError

        def list_pods(self, *a, **kw):  # pragma: no cover
            raise NotImplementedError

        def watch_nodes(self, *a, **kw):  # pragma: no cover
            raise NotImplementedError

    with pytest.raises(KubeApiError):
        Minimal().self_subject_access_review("get", "nodes")


def test_rollout_command(fake_kube, capsys):
    fake_kube.add_node("n0", {"pool": "tpu"})

    def agent(name, node):
        desired = node_labels(node).get(CC_MODE_LABEL)
        state = node_labels(node).get(CC_MODE_STATE_LABEL)
        if desired and state != desired:
            fake_kube.set_node_label(name, CC_MODE_STATE_LABEL, desired)

    fake_kube.add_patch_reactor(agent)
    rc = ctl.cmd_rollout(
        fake_kube,
        ns(selector="pool=tpu", mode="on", max_unavailable=1,
           node_timeout=5.0, continue_on_failure=False,
           rollback_on_failure=False),
    )
    assert rc == 0
    assert '"ok": true' in capsys.readouterr().out
