"""Chaos fault injection (tpu_cc_manager/faults/) and the seeded soak.

The fast deterministic subset here runs in tier-1 under the ``chaos``
marker; hack/chaos_soak.sh re-runs the soak with more rounds
(CC_CHAOS_ROUNDS) and any seed (CC_CHAOS_SEED). The soak's contract is the
robustness acceptance bar: drive the REAL manager loop (watch, drain,
stage/reset, attest, readmit) through a seeded schedule of apiserver and
device faults plus a watchdog demote→restore cycle, then prove
convergence — correct final mode labels, no stuck pause labels, retries
within budget.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from tpu_cc_manager.ccmanager import remediation as remediation_mod
from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.ccmanager.rolling import RollingReconfigurator
from tpu_cc_manager.ccmanager.watchdog import RuntimeHealthWatchdog
from tpu_cc_manager.drain.pause import is_paused
from tpu_cc_manager.faults import FaultPlan, FaultyKubeClient
from tpu_cc_manager.kubeclient.api import KubeApiError, node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    CC_READY_STATE_LABEL,
    DRAIN_COMPONENT_LABELS,
    MODE_DEVTOOLS,
    MODE_OFF,
    MODE_ON,
    QUARANTINE_TAINT_KEY,
    QUARANTINED_LABEL,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils import retry as retry_mod
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "chaos-node-0"
NS = "tpu-operator"

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _lockcheck(monkeypatch):
    """Every chaos scenario runs with the runtime lock-order checker on
    (utils/locks.py, CC_LOCKCHECK=1): objects built inside the test get
    CheckedLocks, so a cycle-forming lock inversion anywhere in the
    thread soup fails the suite deterministically instead of deadlocking
    one run in a thousand. The process-wide order graph is reset around
    each test — lock names are stable per class, so leaked edges from
    one scenario's wiring could otherwise flag a cross-test 'inversion'
    neither test exhibits alone."""
    from tpu_cc_manager.utils import locks as locks_rt

    locks_rt.GRAPH.reset()
    monkeypatch.setenv("CC_LOCKCHECK", "1")
    yield
    locks_rt.GRAPH.reset()


# ---------------------------------------------------------------------------
# Determinism: same seed -> same fault schedule
# ---------------------------------------------------------------------------


def drive_fixed_sequence(seed: int) -> list[tuple]:
    """A fixed, thread-free call sequence through the faulty client; the
    returned schedule must be a pure function of the seed."""
    kube = FakeKube()
    kube.add_node(NODE, {"pool": "tpu"})
    plan = FaultPlan(seed=seed, rate=0.35, watch_rate=0.5,
                     retry_after_s=0.0, slow_s=0.0)
    api = FaultyKubeClient(kube, plan, sleep=lambda s: None)
    for i in range(40):
        try:
            if i % 4 == 0:
                api.get_node(NODE)
            elif i % 4 == 1:
                api.list_nodes("pool=tpu")
            elif i % 4 == 2:
                api.patch_node_labels(NODE, {"x": str(i)})
            else:
                list(api.watch_nodes(NODE, None, 0))
        except KubeApiError:
            pass
    return [(f.kind, f.op, f.seq, f.status) for f in plan.injected]


def test_same_seed_reproduces_the_fault_schedule():
    assert drive_fixed_sequence(1234) == drive_fixed_sequence(1234)


def test_different_seeds_produce_different_schedules():
    assert drive_fixed_sequence(1234) != drive_fixed_sequence(4321)


def test_fault_budget_does_not_skew_the_rng_stream():
    """max_faults caps injections but must not change WHICH calls would
    have been faulted — the schedule prefix is identical."""
    full = drive_fixed_sequence(99)

    kube = FakeKube()
    kube.add_node(NODE, {"pool": "tpu"})
    plan = FaultPlan(seed=99, rate=0.35, watch_rate=0.5, max_faults=3,
                     retry_after_s=0.0, slow_s=0.0)
    api = FaultyKubeClient(kube, plan, sleep=lambda s: None)
    for i in range(40):
        try:
            if i % 4 == 0:
                api.get_node(NODE)
            elif i % 4 == 1:
                api.list_nodes("pool=tpu")
            elif i % 4 == 2:
                api.patch_node_labels(NODE, {"x": str(i)})
            else:
                list(api.watch_nodes(NODE, None, 0))
        except KubeApiError:
            pass
    capped = [(f.kind, f.op, f.seq, f.status) for f in plan.injected]
    assert capped == full[:3]


# ---------------------------------------------------------------------------
# Fault kinds behave as advertised
# ---------------------------------------------------------------------------


def test_fault_kinds_map_to_the_right_errors():
    kube = FakeKube()
    kube.add_node(NODE)
    plan = FaultPlan(seed=5, rate=1.0, retry_after_s=1.5)
    api = FaultyKubeClient(kube, plan, sleep=lambda s: None)
    seen: dict[str, KubeApiError] = {}
    for _ in range(60):
        try:
            api.get_node(NODE)
        except KubeApiError as e:
            seen[plan.injected[-1].kind] = e
    assert set(seen) >= {"http-429", "http-5xx", "conn-reset"}
    assert seen["http-429"].status == 429
    assert seen["http-429"].retry_after_s == 1.5
    assert seen["http-5xx"].status in (500, 502, 503, 504)
    assert seen["conn-reset"].status is None


def test_watch_faults_hang_up_and_expire():
    kube = FakeKube()
    kube.add_node(NODE)
    plan = FaultPlan(seed=2, watch_rate=1.0)
    api = FaultyKubeClient(kube, plan, sleep=lambda s: None)
    kinds = set()
    for _ in range(20):
        try:
            list(api.watch_nodes(NODE, None, 0))
        except KubeApiError as e:
            kinds.add((plan.injected[-1].kind, e.status))
    assert ("stale-rv", 410) in kinds
    assert ("watch-hangup", None) in kinds


# ---------------------------------------------------------------------------
# The seeded chaos soak
# ---------------------------------------------------------------------------


def operator_controller(kube: FakeKube) -> None:
    """Emulate the operator: paused component labels delete the pods,
    unpaused labels bring them back (so every drain has real pods to wait
    out and every readmit is observable)."""

    def reactor(name, node):
        labels = node_labels(node)
        for key, app in DRAIN_COMPONENT_LABELS.items():
            if key not in labels:
                continue
            if is_paused(labels.get(key)):
                kube.delete_pods_matching(NS, f"app={app}")
            elif not kube.list_pods(NS, f"app={app}"):
                kube.add_pod(NS, f"{app}-pod", name, labels={"app": app})

    kube.add_patch_reactor(reactor)


def await_state(kube, desired: str, timeout_s: float = 20.0) -> None:
    converged = retry_mod.poll_until(
        lambda: node_labels(kube.get_node(NODE)).get(
            CC_MODE_STATE_LABEL
        ) == desired,
        timeout_s, 0.02,
    )
    if not converged:
        raise AssertionError(
            f"node never converged to {desired}; labels="
            f"{node_labels(kube.get_node(NODE))}"
        )


def test_chaos_soak_converges_with_bounded_retries(fake_kube, tmp_path):
    """The acceptance-bar soak: seeded apiserver faults (429/5xx/resets/
    watch hangups/410s) + seeded device faults + a watchdog demote→restore
    cycle, against the REAL watch loop with drains enabled. After the
    fault budget dries up the node must converge to every driven mode, no
    pause label may stay stuck, and total retries stay within budget."""
    rounds = int(os.environ.get("CC_CHAOS_ROUNDS", "2"))
    plan = FaultPlan.from_env(
        rate=0.15, watch_rate=0.25,
        max_faults=30 * rounds, retry_after_s=0.005, slow_s=0.002,
    )
    api = FaultyKubeClient(fake_kube, plan)
    dp_label = "google.com/tpu.deploy.device-plugin"
    fake_kube.add_node(NODE, {dp_label: "true"})
    operator_controller(fake_kube)
    fake_kube.add_pod(
        NS, "dp-pod", NODE, labels={"app": DRAIN_COMPONENT_LABELS[dp_label]}
    )

    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    mgr = CCManager(
        api=api,
        backend=backend,
        node_name=NODE,
        default_mode=MODE_OFF,
        operator_namespace=NS,
        evict_components=True,
        smoke_workload="none",
        metrics=registry,
        eviction_timeout_s=2.0,
        eviction_poll_interval_s=0.01,
        watch_timeout_s=1,
        reconnect_delay_s=0.01,
        retry_backoff_s=0.02,
        retry_backoff_max_s=0.2,
        readiness_file=str(tmp_path / "ready"),
    )
    watchdog = RuntimeHealthWatchdog(
        api, backend, NODE,
        demote_after=2, restore_after=2,
        is_busy=lambda: mgr.reconciling,
        emit_event=mgr._emit_node_event,
        metrics=registry,
    )
    stop = threading.Event()

    def agent():
        """The per-node agent with DaemonSet semantics: a startup apiserver
        fault or an exhausted watch-error cap crashes the process and the
        kubelet restarts it — crash-as-retry, exactly as deployed."""
        while not stop.is_set():
            try:
                mgr.watch_and_apply(stop)
                return
            except (KubeApiError, RuntimeError):
                time.sleep(0.01)  # cclint: test-sleep-ok(simulated pod restart latency of the crashed DaemonSet agent)

    thread = threading.Thread(target=agent, daemon=True)
    thread.start()
    try:
        modes = ([MODE_ON, MODE_OFF, MODE_DEVTOOLS] * rounds) + [MODE_ON]
        for mode in modes:
            # Device-layer chaos from the same seed, armed between drives.
            plan.schedule_backend_fault(
                backend, ops=("stage", "reset", "wait_ready", "attest")
            )
            fake_kube.set_node_label(NODE, CC_MODE_LABEL, mode)
            await_state(fake_kube, mode)

        # Watchdog demote→restore cycle mid-soak, with faults still flying.
        backend.healthy = False

        def tick_until_degraded() -> bool:
            watchdog.tick()
            return watchdog.degraded

        retry_mod.poll_until(tick_until_degraded, 2.0, 0.005)
        assert watchdog.degraded
        assert node_labels(fake_kube.get_node(NODE))[
            CC_READY_STATE_LABEL
        ] == "false"
        backend.healthy = True

        def tick_until_healthy() -> bool:
            watchdog.tick()
            return not watchdog.degraded

        retry_mod.poll_until(tick_until_healthy, 2.0, 0.005)
        assert not watchdog.degraded
        assert node_labels(fake_kube.get_node(NODE))[
            CC_READY_STATE_LABEL
        ] == "true"

        # Final convergence: not just the state label (which lands BEFORE
        # re-admission) but the whole node — components unpaused and their
        # pods back. A readmit lost to a fault is retried by the agent's
        # backoff ladder, so with the agent still running this must settle.
        fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)

        def fully_converged() -> bool:
            labels = node_labels(fake_kube.get_node(NODE))
            return (
                labels.get(CC_MODE_STATE_LABEL) == MODE_ON
                and labels.get(CC_READY_STATE_LABEL) == "true"
                and not is_paused(labels.get(dp_label))
                and bool(fake_kube.list_pods(
                    NS, f"app={DRAIN_COMPONENT_LABELS[dp_label]}"
                ))
            )

        assert retry_mod.poll_until(fully_converged, 20.0, 0.02), (
            "node never fully converged (state+ready+unpaused+pods); "
            f"labels={node_labels(fake_kube.get_node(NODE))}"
        )
    finally:
        stop.set()
        thread.join(timeout=10)
    assert not thread.is_alive()

    labels = node_labels(fake_kube.get_node(NODE))
    # Converged to the labeled mode with readiness restored.
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON
    assert labels[CC_READY_STATE_LABEL] == "true"
    # No stuck pause labels: the component label is back to an unpaused
    # value and its pods are back.
    assert not is_paused(labels.get(dp_label))
    assert fake_kube.list_pods(
        NS, f"app={DRAIN_COMPONENT_LABELS[dp_label]}"
    ), "component pods never re-admitted"
    # No lingering barrier markers on this single-host topology.
    from tpu_cc_manager.ccmanager.slicecoord import (
        SLICE_COMMIT_LABEL,
        SLICE_STAGED_LABEL,
    )

    assert SLICE_STAGED_LABEL not in labels
    assert SLICE_COMMIT_LABEL not in labels
    # Bounded recovery cost: every injected fault is worth at most a few
    # classified retries (policy ladders are <=3 deep) plus the watch
    # reconnects the hangups force.
    total_retries = sum(registry.retry_totals().values())
    budget = 4 * len(plan.injected) + 40
    assert total_retries <= budget, (
        f"retry storm: {total_retries} retries for {len(plan.injected)} "
        f"injected faults (budget {budget}); "
        f"totals={registry.retry_totals()}"
    )
    print(
        "CHAOS_SOAK_SUMMARY "
        f"seed={plan.seed} rounds={rounds} faults={len(plan.injected)} "
        f"retries={total_retries} budget={budget}"
    )


# ---------------------------------------------------------------------------
# Terminal-fault mode: the remediation ladder end-to-end
# ---------------------------------------------------------------------------


def await_cond(cond, what: str, timeout_s: float = 30.0) -> None:
    assert retry_mod.poll_until(cond, timeout_s, 0.02), (
        f"never reached: {what}"
    )


def test_terminal_fault_escalates_full_ladder_to_quarantine_and_lifts(
    fake_kube, tmp_path,
):
    """The failure-containment acceptance bar: a seeded TERMINAL device
    fault (never clears on its own) drives the real watch loop through the
    whole remediation ladder — backoff retries, a device re-reset, a
    runtime restart — to quarantine (NoSchedule taint, cc.quarantined
    label, ready.state=false, CCNodeQuarantined event); the rolling
    orchestrator skips the node and its pool failure budget halts the
    rollout; and once the hardware recovers, the watchdog's probes lift
    the quarantine after probation and the node converges to the desired
    mode again."""
    plan = FaultPlan.from_env(rate=0.0, watch_rate=0.0)
    api = FaultyKubeClient(fake_kube, plan)
    backend = FakeTpuBackend()
    # The condemned op is a pure function of the seed; any of these three
    # defeats every reconcile attempt until the fault is cleared.
    condemned = plan.seed_terminal_backend_fault(
        backend, ops=("stage", "reset", "attest")
    )
    fake_kube.add_node(NODE)

    registry = MetricsRegistry()
    ladder = remediation_mod.RemediationLadder(
        api, NODE, backend=backend,
        failures_per_step=1,   # one failure per rung: 4 failures to the top
        probation_s=0.1,
        metrics=registry,
    )
    mgr = CCManager(
        api=api,
        backend=backend,
        node_name=NODE,
        default_mode=MODE_OFF,
        evict_components=False,
        smoke_workload="none",
        metrics=registry,
        watch_timeout_s=1,
        reconnect_delay_s=0.01,
        retry_backoff_s=0.02,
        retry_backoff_max_s=0.2,
        readiness_file=str(tmp_path / "ready"),
        remediation=ladder,
    )
    ladder.emit_event = mgr._emit_node_event
    stop = threading.Event()
    thread = threading.Thread(
        target=lambda: mgr.watch_and_apply(stop), daemon=True
    )
    thread.start()
    try:
        # Drive a mode the terminal fault defeats; the agent's failed
        # reconciles feed the ladder until it quarantines the node.
        fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
        await_cond(lambda: ladder.quarantined, "quarantine")
        # The event is the LAST side effect of quarantine(); once it has
        # landed, the label/taint/ready writes all have too.
        await_cond(
            lambda: any(
                e.get("reason") == "CCNodeQuarantined"
                for e in fake_kube.events
            ),
            "CCNodeQuarantined event",
        )
        node = fake_kube.get_node(NODE)
        labels = node_labels(node)
        assert labels[QUARANTINED_LABEL] == "true"
        assert labels[CC_READY_STATE_LABEL] == "false"
        assert any(
            t["key"] == QUARANTINE_TAINT_KEY and t["effect"] == "NoSchedule"
            for t in (node.get("spec") or {}).get("taints") or []
        )
        # The ladder walked every rung on the way down.
        totals = registry.remediation_totals()
        for step in remediation_mod.STEPS:
            assert any(s == step for s, _ in totals), (
                f"rung {step} never ran: {totals}"
            )

        # Rolling orchestrator: the quarantined node is skipped, and the
        # pool failure budget halts the rollout entirely (fleet breaker).
        fake_kube.add_node("chaos-peer-0", {"pool": "tpu"})
        fake_kube.set_node_label(NODE, "pool", "tpu")

        def peer_converges(name, node):
            if name == "chaos-peer-0":
                desired = node_labels(node).get(CC_MODE_LABEL)
                state = node_labels(node).get(CC_MODE_STATE_LABEL)
                if desired and state != desired:
                    fake_kube.set_node_label(
                        name, CC_MODE_STATE_LABEL, desired
                    )

        fake_kube.add_patch_reactor(peer_converges)
        result = RollingReconfigurator(
            api, "pool=tpu", node_timeout_s=5.0, poll_interval_s=0.01,
        ).rollout(MODE_OFF)
        assert result.ok and result.skipped_quarantined == [NODE]
        halted = RollingReconfigurator(
            api, "pool=tpu", node_timeout_s=5.0, poll_interval_s=0.01,
            failure_budget=0,
        ).rollout(MODE_OFF)
        assert not halted.ok
        assert halted.halted_reason == "failure-budget-exceeded"

        # Hardware recovers: the terminal fault clears, the watchdog's
        # healthy probes run probation down, and quarantine auto-lifts.
        backend.fail.pop(condemned, None)
        backend.healthy = True
        watchdog = RuntimeHealthWatchdog(
            api, backend, NODE,
            demote_after=2, restore_after=1,
            is_busy=lambda: mgr.reconciling,
            metrics=registry,
            on_probe=ladder.note_probe,
            on_condemn=ladder.condemn,
        )
        def probe_until_lifted():
            watchdog.tick()
            return not ladder.quarantined
        await_cond(probe_until_lifted, "probation lift")
        # The agent's pending backoff retry now re-applies the desired
        # mode and the node converges for real.
        await_cond(
            lambda: node_labels(fake_kube.get_node(NODE)).get(
                CC_MODE_STATE_LABEL
            ) == MODE_ON,
            "post-lift convergence",
        )
        assert any(
            e.get("reason") == "CCNodeUnquarantined" for e in fake_kube.events
        )
        labels = node_labels(fake_kube.get_node(NODE))
        assert QUARANTINED_LABEL not in labels
        assert labels[CC_READY_STATE_LABEL] == "true"
    finally:
        stop.set()
        thread.join(timeout=10)
    assert not thread.is_alive()
    totals = registry.remediation_totals()
    print(
        "REMEDIATION_SUMMARY "
        f"seed={plan.seed} condemned_op={condemned} "
        f"steps={sorted((f'{s}:{o}', c) for (s, o), c in totals.items())} "
        f"quarantines={sum(c for (s, _), c in totals.items() if s == 'quarantine')}"
    )


# ---------------------------------------------------------------------------
# Apiserver-blackout mode: the disconnected-mode ladder + intent journal
# ---------------------------------------------------------------------------


def test_blackout_refuses_every_verb_including_watch(fake_kube):
    """During a blackout window EVERY verb — watch connects included —
    refuses with a connection reset (status=None), the signature of a
    dead apiserver; ending the window restores the inner client."""
    from tpu_cc_manager.faults.plan import BLACKOUT_KIND

    fake_kube.add_node(NODE)
    plan = FaultPlan(seed=7, rate=0.0, watch_rate=0.0)
    api = FaultyKubeClient(fake_kube, plan, sleep=lambda s: None)
    plan.begin_blackout()
    for call in (
        lambda: api.get_node(NODE),
        lambda: api.patch_node_labels(NODE, {"x": "1"}),
        lambda: api.list_nodes(),
        lambda: list(api.watch_nodes(NODE, None, 0)),
        lambda: api.create_event("default", {}),
    ):
        with pytest.raises(KubeApiError) as exc:
            call()
        assert exc.value.status is None
        assert BLACKOUT_KIND in str(exc.value)
    plan.end_blackout()
    assert api.get_node(NODE)["metadata"]["name"] == NODE
    assert plan.blackout_refusals == 5


def test_seeded_blackout_windows_are_deterministic_and_bounded():
    """blackout_rate opens seeded windows of seeded length: same seed →
    same refusal schedule, and the windows draw from a DERIVED stream so
    the main per-call fault schedule is not reshuffled."""
    def refusal_pattern(seed):
        kube = FakeKube()
        kube.add_node(NODE)
        plan = FaultPlan(
            seed=seed, rate=0.0, watch_rate=0.0,
            blackout_rate=0.12, blackout_min_calls=2, blackout_max_calls=5,
        )
        api = FaultyKubeClient(kube, plan, sleep=lambda s: None)
        pattern = []
        for _ in range(120):
            try:
                api.get_node(NODE)
                pattern.append(0)
            except KubeApiError:
                pattern.append(1)
        return pattern, plan

    p1, plan1 = refusal_pattern(31)
    p2, plan2 = refusal_pattern(31)
    assert p1 == p2
    assert plan1.blackout_windows >= 1
    # Window lengths bounded by the configured span.
    runs, run = [], 0
    for bit in p1 + [0]:
        if bit:
            run += 1
        elif run:
            runs.append(run)
            run = 0
    # Each window spans 2..5 calls; adjacent windows may merge into one
    # longer refusal run, so runs are bounded below by the min span and
    # never exceed windows*max-span overall.
    assert runs and all(r >= 2 for r in runs)
    assert sum(runs) <= plan1.blackout_windows * 5
    assert len(runs) <= plan1.blackout_windows
    # The main stream is untouched: a blackout-free plan with the same
    # seed injects the same (non-blackout) faults on the same calls.
    base = FaultPlan(seed=31, rate=0.35, watch_rate=0.0)
    with_blackout = FaultPlan(
        seed=31, rate=0.35, watch_rate=0.0,
        blackout_rate=0.12, blackout_min_calls=2, blackout_max_calls=5,
    )

    def key(f):
        return None if f is None else (f.kind, f.status)

    base_draws = [key(base.decide("op")) for _ in range(60)]
    # Blackout refusals DISPLACE main-stream draws (the call never reaches
    # the apiserver), so the drawn decisions — Nones included — must be a
    # prefix of the blackout-free plan's draw sequence.
    overlay_draws = []
    for _ in range(60):
        f = with_blackout.decide("op")
        if f is not None and f.kind == "blackout":
            continue
        overlay_draws.append(key(f))
    assert overlay_draws == base_draws[: len(overlay_draws)]


class AgentKilled(BaseException):
    """Models a SIGKILL landing inside the agent: BaseException so no
    except-Exception path (the manager's failure handler included) can
    run 'cleanup' a real SIGKILL would never run — the intent journal's
    open record and the hardware are all the successor gets."""


def test_blackout_sigkill_mid_reset_converges_from_journal_alone(
    fake_kube, tmp_path,
):
    """The apiserver-outage acceptance bar (ISSUE 5): a blackout covers an
    entire mode transition AND the agent is SIGKILLed right after the
    device reset commits (before any label write). The restarted agent
    must converge the hardware from the intent journal alone while still
    dark — each chip reset exactly ONCE across the crash — and on
    reconnect the node labels must reach the truthful state with zero
    lost or duplicated patches."""
    from tpu_cc_manager.ccmanager.intent_journal import IntentJournal

    plan = FaultPlan(seed=11, rate=0.0, watch_rate=0.0)
    api = FaultyKubeClient(fake_kube, plan)
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    registry1 = MetricsRegistry()
    journal1 = IntentJournal.from_state_dir(str(tmp_path))

    mgr1 = CCManager(
        api=api, backend=backend, node_name=NODE,
        default_mode=MODE_OFF, evict_components=False,
        smoke_workload="none", metrics=registry1,
        watch_timeout_s=1, reconnect_delay_s=0.01,
        retry_backoff_s=0.02, retry_backoff_max_s=0.2,
        readiness_file=str(tmp_path / "ready1"),
        intent_journal=journal1, offline_grace_s=0.05,
    )
    stop1 = threading.Event()

    def agent1():
        try:
            mgr1.watch_and_apply(stop1)
        except AgentKilled:
            pass  # the process is dead; nothing else runs

    t1 = threading.Thread(target=agent1, daemon=True)
    t1.start()
    try:
        fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_ON)
        await_state(fake_kube, MODE_ON)

        # Arm the kill: the NEXT reset commits on the device, then the
        # blackout begins and the SIGKILL lands — intent open at
        # phase=reset, labels untouched, apiserver dark.
        real_reset = backend.reset

        def killer_reset(chips):
            real_reset(chips)
            plan.begin_blackout()
            raise AgentKilled()

        backend.reset = killer_reset
        resets_before = sum(
            1 for op, _ in backend.op_log if op == "reset"
        )
        fake_kube.set_node_label(NODE, CC_MODE_LABEL, MODE_DEVTOOLS)
        t1.join(timeout=10)
        assert not t1.is_alive(), "the modeled SIGKILL never landed"
    finally:
        stop1.set()
        backend.reset = backend.__class__.reset.__get__(backend)

    # Crash truth: the device holds devtools, the journal holds an open
    # reset-phase intent, the labels still claim the OLD mode.
    assert all(m == MODE_DEVTOOLS for m in backend.committed.values())
    open_intents = journal1.open_intents("transition")
    assert len(open_intents) == 1
    assert open_intents[0]["phase"] == "reset"
    assert open_intents[0]["mode"] == MODE_DEVTOOLS
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON  # stale: blackout held it

    # ---- restart while still dark ------------------------------------
    registry2 = MetricsRegistry()
    journal2 = IntentJournal.from_state_dir(str(tmp_path))
    mgr2 = CCManager(
        api=api, backend=backend, node_name=NODE,
        default_mode=MODE_OFF, evict_components=False,
        smoke_workload="none", metrics=registry2,
        watch_timeout_s=1, reconnect_delay_s=0.01,
        retry_backoff_s=0.02, retry_backoff_max_s=0.2,
        readiness_file=str(tmp_path / "ready2"),
        intent_journal=journal2, offline_grace_s=0.05,
    )
    stop2 = threading.Event()
    t2 = threading.Thread(
        target=lambda: mgr2.watch_and_apply(stop2), daemon=True
    )
    # Record every post-restart write of the state label so "zero lost or
    # duplicated patches" is checked against actual writes, not just the
    # final value.
    state_writes: list[str] = []
    fake_kube.add_patch_reactor(
        lambda name, node: state_writes.append(
            node_labels(node).get(CC_MODE_STATE_LABEL)
        )
    )
    t2.start()
    try:
        # While dark: the journal alone converges the node — the open
        # intent completes against hardware truth with NO second reset,
        # and the truthful state report queues as a pending patch (wait on
        # the patch, the LAST step of the recovery, so every assert below
        # sees the finished recovery).
        await_cond(
            lambda: CC_MODE_STATE_LABEL in journal2.pending_patches(),
            "recovery queued the deferred state report",
        )
        assert journal2.pending_patches()[CC_MODE_STATE_LABEL] == MODE_DEVTOOLS
        assert not journal2.open_intents("transition")
        resets_after = sum(1 for op, _ in backend.op_log if op == "reset")
        assert resets_after == resets_before + 1, (
            "the crashed transition's reset must happen exactly once"
        )
        assert registry2.journal_replay_totals().get("completed") == 1
        assert t2.is_alive(), "agent must ride out the outage, not crash"
        # Labels are still stale — the apiserver is dark and stays dark.
        assert node_labels(fake_kube.get_node(NODE))[
            CC_MODE_STATE_LABEL
        ] == MODE_ON

        # ---- reconnect ----------------------------------------------
        plan.end_blackout()
        await_state(fake_kube, MODE_DEVTOOLS)
        await_cond(
            lambda: not journal2.has_pending_patches(),
            "deferred patches flushed",
        )
        labels = node_labels(fake_kube.get_node(NODE))
        assert labels[CC_READY_STATE_LABEL] == "debug"
    finally:
        stop2.set()
        t2.join(timeout=10)
    assert not t2.is_alive()
    # Zero lost or duplicated patches: every post-restart state-label
    # write carried the truthful mode — no stale value was replayed back
    # and nothing bounced through 'failed'.
    assert state_writes, "the deferred state report never flushed"
    assert set(state_writes) == {MODE_DEVTOOLS}
    print(
        "OFFLINE_ACCEPTANCE "
        f"resets_across_crash=1 replays={registry2.journal_replay_totals()} "
        f"state_writes={len(state_writes)}"
    )


def test_blackout_soak_serves_last_known_mode_and_flushes(
    fake_kube, tmp_path,
):
    """Seeded blackout windows composed with the ordinary fault weather:
    the agent (journal + disconnected mode) keeps converging every driven
    mode; transitions that finish inside a window defer their state
    report and flush it on reconnect. Prints the OFFLINE_SUMMARY line the
    chaos soak harness (hack/chaos_soak.sh) records."""
    from tpu_cc_manager.ccmanager.intent_journal import IntentJournal

    rounds = int(os.environ.get("CC_CHAOS_ROUNDS", "2"))
    plan = FaultPlan.from_env(
        rate=0.08, watch_rate=0.1,
        blackout_rate=0.04, blackout_min_calls=2, blackout_max_calls=6,
        max_blackouts=2 * rounds, max_faults=20 * rounds,
        retry_after_s=0.005, slow_s=0.002,
    )
    api = FaultyKubeClient(fake_kube, plan)
    fake_kube.add_node(NODE)
    backend = FakeTpuBackend()
    registry = MetricsRegistry()
    journal = IntentJournal.from_state_dir(str(tmp_path))
    mgr = CCManager(
        api=api, backend=backend, node_name=NODE,
        default_mode=MODE_OFF, evict_components=False,
        smoke_workload="none", metrics=registry,
        watch_timeout_s=1, reconnect_delay_s=0.01,
        retry_backoff_s=0.02, retry_backoff_max_s=0.2,
        readiness_file=str(tmp_path / "ready"),
        intent_journal=journal, offline_grace_s=0.05,
    )
    stop = threading.Event()

    def agent():
        while not stop.is_set():
            try:
                mgr.watch_and_apply(stop)
                return
            except (KubeApiError, RuntimeError):
                time.sleep(0.01)  # cclint: test-sleep-ok(simulated DaemonSet crash-restart latency)

    thread = threading.Thread(target=agent, daemon=True)
    thread.start()
    try:
        # Seed a journal disk fault from the same stream now and then —
        # the agent must reconcile (unjournaled, loudly) through it.
        for mode in ([MODE_ON, MODE_OFF, MODE_DEVTOOLS] * rounds) + [MODE_ON]:
            plan.schedule_journal_fault(journal)
            fake_kube.set_node_label(NODE, CC_MODE_LABEL, mode)
            await_state(fake_kube, mode, timeout_s=30.0)
        await_cond(
            lambda: not journal.has_pending_patches(),
            "deferred patches flushed after the blackout weather",
        )
    finally:
        stop.set()
        thread.join(timeout=10)
    assert not thread.is_alive()
    labels = node_labels(fake_kube.get_node(NODE))
    assert labels[CC_MODE_STATE_LABEL] == MODE_ON
    assert not journal.open_intents()
    print(
        "OFFLINE_SUMMARY "
        f"seed={plan.seed} windows={plan.blackout_windows} "
        f"refusals={plan.blackout_refusals} "
        f"replays={registry.journal_replay_totals()} "
        f"pending_left={len(journal.pending_patches())}"
    )


def test_seed_blackout_window_arms_one_seeded_span():
    """seed_blackout_window opens exactly one outage window whose length
    is a pure function of the seed (the SCALE_r04 parent-blackout drill
    needs the scenario, not the odds) — same seed, same span, and the
    wrapped client refuses exactly that many calls before recovering."""
    def run(seed):
        kube = FakeKube()
        kube.add_node(NODE)
        plan = FaultPlan(
            seed=seed, rate=0.0, watch_rate=0.0,
            blackout_min_calls=3, blackout_max_calls=7,
        )
        span = plan.seed_blackout_window()
        assert 3 <= span <= 7
        api = FaultyKubeClient(kube, plan, sleep=lambda s: None)
        refused = 0
        for _ in range(span + 5):
            try:
                api.get_node(NODE)
            except KubeApiError:
                refused += 1
        return span, refused, plan

    span1, refused1, plan1 = run(42)
    span2, refused2, _ = run(42)
    assert (span1, refused1) == (span2, refused2)
    assert refused1 == span1
    assert not plan1.in_blackout
    assert any(
        f.kind == "blackout" and f.op == "seeded-window"
        for f in plan1.injected
    )
