"""wait_ready∥COMPILE smoke warmup: the two-phase dispatch gate
(smoke/runner.py) and its manager wiring (ccmanager/manager.py).

Invariants pinned here:

- **ordering**: the warmup child is spawned BEFORE wait_ready but its
  dispatch is released only AFTER wait_ready returned and attestation
  passed — never earlier, on any path;
- **no dispatch on failure**: attestation failure, digest-fast-path hit
  and a modeled SIGKILL all CANCEL the gated child instead of releasing;
- **orphan protection**: a child whose parent died mid-warmup exits on
  its own (the real-SIGKILL case no finally can cover);
- **crash recovery**: after a kill during the warmup the successor runs
  a FULL smoke (no digest was persisted — the fast path is unaffected).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import pytest

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.labels import MODE_OFF, MODE_ON
from tpu_cc_manager.obs.journal import Journal
from tpu_cc_manager.smoke import runner as runner_mod
from tpu_cc_manager.smoke.runner import SmokeError
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry
from tpu_cc_manager.utils import retry as retry_mod

NODE = "warm-node-0"
NS = "tpu-operator"


class AgentKilled(BaseException):
    """Models a SIGKILL landing inside the agent (same convention as
    tests/test_pipeline.py)."""


def make_manager(kube, backend, **kw):
    kw.setdefault("evict_components", False)
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("journal", Journal(trace_file=""))
    kw.setdefault("smoke_workload", "matmul")
    return CCManager(
        api=kube, backend=backend, node_name=NODE,
        operator_namespace=NS, **kw,
    )


class SeqBackend(FakeTpuBackend):
    """Appends the pipeline's observable milestones to a shared list."""

    def __init__(self, seq, **kw):
        super().__init__(**kw)
        self._seq = seq

    def wait_ready(self, chips, timeout_s):
        super().wait_ready(chips, timeout_s)
        self._seq.append("wait_ready")

    def fetch_attestation(self, nonce):
        quote = super().fetch_attestation(nonce)
        self._seq.append("attest")
        return quote


class FakeWarmup:
    """Records the warmup handle contract the manager drives."""

    def __init__(self, seq):
        self.seq = seq
        self.released = False
        self.cancelled = None
        self.died = False
        seq.append("spawned")

    def died_during_warmup(self):
        return self.died

    def release_and_result(self):
        self.released = True
        self.seq.append("released")
        return {
            "ok": True, "workload": "matmul",
            "warmup_compile_s": 0.0, "warmup_overlap_s": 0.0,
            "warmup_dispatch_s": 0.0,
        }

    def cancel(self, reason=""):
        if self.cancelled is None:
            self.cancelled = reason or "cancelled"
        self.seq.append(f"cancelled:{reason}")


def warmup_recorder(seq):
    warmups = []

    def factory(workload):
        w = FakeWarmup(seq)
        warmups.append(w)
        return w

    return warmups, factory


# ---------------------------------------------------------------------------
# Manager ordering
# ---------------------------------------------------------------------------


def test_dispatch_released_only_after_ready_and_attestation(fake_kube):
    """THE ordering pin for BENCH_r07: the child spawns before the boot
    wait (that's the overlap) and dispatch releases strictly after both
    wait_ready and the attestation verify."""
    fake_kube.add_node(NODE)
    seq: list[str] = []
    backend = SeqBackend(seq)
    warmups, factory = warmup_recorder(seq)
    mgr = make_manager(
        fake_kube, backend, smoke_warmup_factory=factory,
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert len(warmups) == 1 and warmups[0].released
    assert warmups[0].cancelled is None
    assert seq.index("spawned") < seq.index("wait_ready"), (
        f"warmup must start before the boot wait: {seq}"
    )
    assert seq.index("released") > seq.index("wait_ready"), seq
    assert seq.index("released") > seq.index("attest"), seq


def test_warmup_disabled_keeps_synchronous_smoke(fake_kube):
    fake_kube.add_node(NODE)
    seq: list[str] = []
    warmups, factory = warmup_recorder(seq)
    calls = []
    mgr = make_manager(
        fake_kube, FakeTpuBackend(),
        smoke_warmup=False, smoke_warmup_factory=factory,
        smoke_runner=lambda w: (calls.append(w), {"ok": True})[1],
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert warmups == [] and calls == ["matmul"]


def test_injected_smoke_runner_without_factory_disables_warmup(fake_kube):
    """An injected smoke_runner (tests, bench fallback paths) must keep
    its synchronous contract unless a warmup factory rides along."""
    fake_kube.add_node(NODE)
    calls = []
    mgr = make_manager(
        fake_kube, FakeTpuBackend(),
        smoke_runner=lambda w: (calls.append(w), {"ok": True})[1],
    )
    assert mgr.smoke_warmup is True  # default on…
    assert mgr.set_cc_mode(MODE_ON) is True
    assert calls == ["matmul"]  # …but the sync runner still served


def test_attestation_failure_cancels_warmup_without_release(fake_kube):
    from tpu_cc_manager.tpudev import attestation

    fake_kube.add_node(NODE)
    seq: list[str] = []

    class BadAttestBackend(SeqBackend):
        def fetch_attestation(self, nonce):
            raise attestation.AttestationError("modeled bad quote")

    warmups, factory = warmup_recorder(seq)
    mgr = make_manager(
        fake_kube, BadAttestBackend(seq), smoke_warmup_factory=factory,
    )
    assert mgr.set_cc_mode(MODE_ON) is False
    assert len(warmups) == 1
    assert not warmups[0].released, "dispatch must NOT release on a failed attest"
    assert warmups[0].cancelled == "pipeline-unwound"


def test_digest_fastpath_hit_cancels_warmup(fake_kube, tmp_path):
    fake_kube.add_node(NODE)
    seq: list[str] = []
    warmups, factory = warmup_recorder(seq)
    backend = SeqBackend(seq)
    mgr = make_manager(
        fake_kube, backend, smoke_warmup_factory=factory,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
    )
    # on (full smoke, digest persisted) → off → on (unchanged digest).
    assert mgr.set_cc_mode(MODE_ON) is True
    assert mgr.set_cc_mode(MODE_OFF) is True
    assert mgr.set_cc_mode(MODE_ON) is True
    assert warmups[0].released
    last = warmups[-1]
    assert not last.released, "fast-path hit must not dispatch the warmup"
    assert last.cancelled == "digest-fastpath"


def test_child_death_during_warmup_falls_back_to_synchronous_smoke(fake_kube):
    """A child that died before any release (e.g. client init against
    the mid-boot runtime) is a warmup-infrastructure failure, not a
    smoke verdict: the manager runs the serial smoke against the
    now-ready runtime instead of failing the flip."""
    fake_kube.add_node(NODE)
    seq: list[str] = []
    warmups, factory = warmup_recorder(seq)
    calls = []

    def dying_factory(workload):
        w = factory(workload)
        w.died = True
        return w

    mgr = make_manager(
        fake_kube, SeqBackend(seq), smoke_warmup_factory=dying_factory,
        smoke_runner=lambda w: (calls.append(w), {"ok": True})[1],
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert len(warmups) == 1
    assert not warmups[0].released
    assert warmups[0].cancelled == "died-during-warmup"
    assert calls == ["matmul"], "the synchronous smoke must still verify"


def test_spawn_failure_falls_back_to_synchronous_smoke(fake_kube):
    fake_kube.add_node(NODE)
    calls = []

    def exploding_factory(workload):
        raise OSError("modeled fork failure")

    mgr = make_manager(
        fake_kube, FakeTpuBackend(),
        smoke_warmup_factory=exploding_factory,
        smoke_runner=lambda w: (calls.append(w), {"ok": True})[1],
    )
    assert mgr.set_cc_mode(MODE_ON) is True
    assert calls == ["matmul"]


# ---------------------------------------------------------------------------
# Crash during the warmup: cancel + successor runs a FULL smoke
# ---------------------------------------------------------------------------


def test_kill_during_warmup_cancels_child_and_successor_runs_full_smoke(
    fake_kube, tmp_path,
):
    fake_kube.add_node(NODE)
    seq: list[str] = []
    kill = {"armed": True}

    class KillInWaitReady(SeqBackend):
        def wait_ready(self, chips, timeout_s):
            if kill["armed"]:
                raise AgentKilled()
            super().wait_ready(chips, timeout_s)

    backend = KillInWaitReady(seq)
    warmups, factory = warmup_recorder(seq)
    registry = MetricsRegistry()
    mgr = make_manager(
        fake_kube, backend, smoke_warmup_factory=factory,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
        metrics=registry,
    )
    with pytest.raises(AgentKilled):
        mgr.set_cc_mode(MODE_ON)
    # The modeled kill unwound the pipeline: the gated child was
    # cancelled (a REAL SIGKILL is covered child-side — see the orphan
    # test below) and, crucially, no verified digest was persisted.
    assert len(warmups) == 1
    assert not warmups[0].released
    assert warmups[0].cancelled is not None
    assert not (tmp_path / "verified_digest.json").exists()

    # Successor: the fast path has nothing on record → its next real
    # flip (the kill landed post-reset, so 'on' is already committed;
    # bounce through off) runs the FULL smoke, outcome "cold", never a
    # hit — the crash could not have minted a digest.
    kill["armed"] = False
    registry2 = MetricsRegistry()
    mgr2 = make_manager(
        fake_kube, backend, smoke_warmup_factory=factory,
        smoke_digest_fastpath=True, state_dir=str(tmp_path),
        metrics=registry2,
    )
    assert mgr2.set_cc_mode(MODE_OFF) is True
    assert mgr2.set_cc_mode(MODE_ON) is True
    assert warmups[-1].released, "successor must run the full smoke"
    totals = registry2.smoke_fastpath_totals()
    assert totals.get("cold") == 1 and not totals.get("hit")
    assert (tmp_path / "verified_digest.json").exists()


# ---------------------------------------------------------------------------
# Gate protocol (child side)
# ---------------------------------------------------------------------------


def test_gate_noop_without_env(monkeypatch):
    monkeypatch.delenv(runner_mod.DISPATCH_GATE_ENV, raising=False)
    assert runner_mod.await_dispatch_gate() is False


def test_gate_timeout_raises_and_sentinel_lands(monkeypatch, tmp_path):
    gate = str(tmp_path / "gate")
    monkeypatch.setenv(runner_mod.DISPATCH_GATE_ENV, gate)
    monkeypatch.setenv(runner_mod.GATE_TIMEOUT_ENV, "0.2")
    monkeypatch.delenv(runner_mod.GATE_PARENT_PID_ENV, raising=False)
    with pytest.raises(SmokeError, match="not released"):
        runner_mod.await_dispatch_gate()
    assert os.path.exists(runner_mod.compiled_sentinel(gate)), (
        "the compiled sentinel must land before the wait"
    )


def test_gate_opens_when_released(monkeypatch, tmp_path):
    gate = str(tmp_path / "gate")
    monkeypatch.setenv(runner_mod.DISPATCH_GATE_ENV, gate)
    monkeypatch.setenv(runner_mod.GATE_TIMEOUT_ENV, "10")
    compiled = []

    def release_soon():
        # cclint: test-sleep-ok(deliberate delay: the gate must open only when released)
        time.sleep(0.15)
        with open(gate, "w", encoding="utf-8") as f:
            f.write("released")

    t = threading.Thread(target=release_soon, daemon=True)
    t.start()
    assert runner_mod.await_dispatch_gate(
        compile_fns=(lambda: compiled.append(True),)
    ) is True
    t.join()
    assert compiled == [True], "compile fns must run before the wait"


def test_gate_advisory_compile_failure_does_not_block(monkeypatch, tmp_path):
    gate = str(tmp_path / "gate")
    with open(gate, "w", encoding="utf-8") as f:
        f.write("released")  # pre-released: wait returns immediately
    monkeypatch.setenv(runner_mod.DISPATCH_GATE_ENV, gate)

    def broken_compile():
        raise RuntimeError("modeled AOT failure")

    assert runner_mod.await_dispatch_gate(
        compile_fns=(broken_compile,)
    ) is True


def test_gate_orphan_child_exits_when_parent_dies(tmp_path):
    """A SIGKILLed manager leaves NO orphan warmup subprocess: the child's
    gate wait watches the parent pid and exits (non-zero, no dispatch)
    when it disappears. Real processes, real SIGKILL."""
    gate = str(tmp_path / "gate")
    parent = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"],
    )
    env = dict(os.environ)
    env[runner_mod.DISPATCH_GATE_ENV] = gate
    env[runner_mod.GATE_PARENT_PID_ENV] = str(parent.pid)
    env[runner_mod.GATE_TIMEOUT_ENV] = "60"
    child = subprocess.Popen(
        [sys.executable, "-c",
         "from tpu_cc_manager.smoke.runner import await_dispatch_gate; "
         "await_dispatch_gate(); print('DISPATCHED')"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # Wait for the child to reach the gate (sentinel), then SIGKILL
        # the fake parent — the child must notice and die on its own.
        sentinel = runner_mod.compiled_sentinel(gate)
        assert retry_mod.poll_until(
            lambda: os.path.exists(sentinel), 30.0, 0.05
        ), "child never reached the gate"
        parent.kill()
        parent.wait()  # reap: the pid must actually disappear
        stdout, stderr = child.communicate(timeout=30)
        assert child.returncode != 0, (
            f"orphaned child must exit non-zero, got rc=0: {stdout}"
        )
        assert "DISPATCHED" not in stdout, "orphan must never dispatch"
        assert "orphan" in stderr.lower() or "gone" in stderr.lower()
    finally:
        for p in (parent, child):
            if p.poll() is None:
                p.kill()
                p.wait()


# ---------------------------------------------------------------------------
# SmokeWarmup end-to-end: one real gated smoke subprocess
# ---------------------------------------------------------------------------


def test_smoke_warmup_end_to_end_real_subprocess():
    """The full two-phase contract with a real child: compile lands while
    the gate is closed, the child blocks (no dispatch), release() lets it
    finish, and the parsed result carries the warmup timing."""
    w = runner_mod.SmokeWarmup(
        "matmul", timeout_s=240.0, force_cpu=True,
        extra_args=["--size", "128"],
    )
    try:
        def compiled_or_dead() -> bool:
            assert w._proc.poll() is None, "child died during COMPILE"
            return w.compiled_after_s() is not None

        retry_mod.poll_until(compiled_or_dead, 180.0, 0.1)
        compile_s = w.compiled_after_s()
        assert compile_s is not None, "compile sentinel never landed"
        # Gated: the child must still be alive and NOT have finished.
        time.sleep(0.3)  # cclint: test-sleep-ok(negative assertion: the child must STILL be blocked on the gate)
        assert w._proc.poll() is None, "child must block on the gate"
        result = w.release_and_result()
    except BaseException:
        w.cancel("test-failure")
        raise
    assert result["ok"] is True and result["workload"] == "matmul"
    assert result["warmup_compile_s"] is not None
    assert result["warmup_overlap_s"] >= 0.0
    assert result["warmup_dispatch_s"] >= 0.0
    # The whole compile ran pre-release, so the overlap covers it.
    assert result["warmup_overlap_s"] == pytest.approx(
        result["warmup_compile_s"], abs=0.5,
    )


def test_smoke_warmup_cancel_kills_child(tmp_path):
    w = runner_mod.SmokeWarmup(
        "matmul", timeout_s=240.0, force_cpu=True,
        extra_args=["--size", "128"],
    )
    assert w._proc.poll() is None
    w.cancel("test")
    assert w._proc.poll() is not None, "cancel must reap the child"
    assert not os.path.exists(w.gate_path), "gate dir cleaned up"
