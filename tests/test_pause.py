"""Pause/unpause label algebra (reference gpu_operator_eviction.py:43-95)."""

import pytest
from _hypothesis_compat import given, st

from tpu_cc_manager.drain.pause import (
    MAX_LABEL_LEN,
    _MAX_CUSTOM,
    is_paused,
    pause_value,
    unpause_value,
)
from tpu_cc_manager.labels import PAUSED_SUFFIX, PAUSED_VALUE


@pytest.mark.parametrize(
    "value,expected",
    [
        ("true", PAUSED_VALUE),            # enabled -> paused
        ("custom", "custom" + PAUSED_SUFFIX),  # custom value preserved
        ("false", None),                   # user-disabled: untouched
        ("", None),                        # empty: untouched
        (None, None),                      # absent: untouched
        (PAUSED_VALUE, None),              # already paused: idempotent
        ("custom" + PAUSED_SUFFIX, None),  # already paused custom: idempotent
    ],
)
def test_pause_value(value, expected):
    assert pause_value(value) == expected


@pytest.mark.parametrize(
    "value,expected",
    [
        (PAUSED_VALUE, "true"),
        ("custom" + PAUSED_SUFFIX, "custom"),
        ("true", None),
        ("false", None),
        ("", None),
        (None, None),
    ],
)
def test_unpause_value(value, expected):
    assert unpause_value(value) == expected


def test_pause_unpause_roundtrip():
    for original in ("true", "vfio", "some-custom-value"):
        paused = pause_value(original)
        assert paused is not None and is_paused(paused)
        assert unpause_value(paused) == original


def test_is_paused():
    assert is_paused(PAUSED_VALUE)
    assert is_paused("x" + PAUSED_SUFFIX)
    assert not is_paused("true")
    assert not is_paused(None)


# ---------------------------------------------------------------------------
# Property-based coverage of the protocol core (the pause values are the
# external operator's API; an algebra bug here strands components).
# ---------------------------------------------------------------------------

# Valid-ish k8s label values: alnum/-/_/. up to 63 chars. Embedded copies
# of PAUSED_SUFFIX are deliberately reachable (st.text over these chars
# plus the explicit composites below) — the truncation edge where a cut
# exposes a suffix is exactly what the normalization must survive.
label_values = st.one_of(
    st.text(
        alphabet=st.characters(
            whitelist_categories=(), whitelist_characters=(
                "abcdefghijklmnopqrstuvwxyz"
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."
            ),
        ),
        min_size=1, max_size=MAX_LABEL_LEN,
    ),
    # Adversarial composites around the suffix and the cut point.
    st.builds(
        lambda pre, post: (pre + PAUSED_SUFFIX + post)[:MAX_LABEL_LEN],
        st.text(alphabet="ab", max_size=6),
        st.text(alphabet="xy", min_size=1, max_size=20),
    ),
)


@given(label_values)
def test_pause_invariants(value):
    paused = pause_value(value)
    if value in ("false",) or is_paused(value):
        assert paused is None
        return
    # Pausing produces a recognized-paused, length-legal label value.
    assert paused is not None
    assert is_paused(paused)
    assert len(paused) <= MAX_LABEL_LEN
    # Pausing is idempotent: a paused value never re-pauses.
    assert pause_value(paused) is None
    # Unpausing a paused value NEVER yields something that still reads
    # paused (a double-suffix bug would strand the component forever).
    restored = unpause_value(paused)
    assert restored is not None
    assert not is_paused(restored)
    # After one (possibly lossy, documented) normalization cycle, the
    # algebra is a fixpoint: a second pause/unpause cycle is lossless.
    if restored not in ("", "false"):
        assert unpause_value(pause_value(restored)) == restored


@given(label_values)
def test_exact_roundtrip_for_values_that_fit(value):
    """Values short enough to carry the suffix round-trip bit-exact."""
    if value in ("true", "false") or is_paused(value):
        return
    if len(value) <= _MAX_CUSTOM:
        assert unpause_value(pause_value(value)) == value


@given(label_values)
def test_unpause_never_touches_non_paused(value):
    if not is_paused(value):
        assert unpause_value(value) is None
