"""Pause/unpause label algebra (reference gpu_operator_eviction.py:43-95)."""

import pytest

from tpu_cc_manager.drain.pause import is_paused, pause_value, unpause_value
from tpu_cc_manager.labels import PAUSED_SUFFIX, PAUSED_VALUE


@pytest.mark.parametrize(
    "value,expected",
    [
        ("true", PAUSED_VALUE),            # enabled -> paused
        ("custom", "custom" + PAUSED_SUFFIX),  # custom value preserved
        ("false", None),                   # user-disabled: untouched
        ("", None),                        # empty: untouched
        (None, None),                      # absent: untouched
        (PAUSED_VALUE, None),              # already paused: idempotent
        ("custom" + PAUSED_SUFFIX, None),  # already paused custom: idempotent
    ],
)
def test_pause_value(value, expected):
    assert pause_value(value) == expected


@pytest.mark.parametrize(
    "value,expected",
    [
        (PAUSED_VALUE, "true"),
        ("custom" + PAUSED_SUFFIX, "custom"),
        ("true", None),
        ("false", None),
        ("", None),
        (None, None),
    ],
)
def test_unpause_value(value, expected):
    assert unpause_value(value) == expected


def test_pause_unpause_roundtrip():
    for original in ("true", "vfio", "some-custom-value"):
        paused = pause_value(original)
        assert paused is not None and is_paused(paused)
        assert unpause_value(paused) == original


def test_is_paused():
    assert is_paused(PAUSED_VALUE)
    assert is_paused("x" + PAUSED_SUFFIX)
    assert not is_paused("true")
    assert not is_paused(None)
