"""Stateful property test of the reconcile loop (VERDICT r4 item 9).

Hypothesis drives random sequences of desired-label edits, backend fault
injections, and manager crash-restarts against the fake apiserver + fake
device layer, then reconciles. Two invariants, checked after every step:

1. **Truthful state label** — whenever the node's state label names a CC
   mode, every chip's queried committed mode IS that mode. A reconcile
   that died mid-way (injected fault, crash) may leave ``failed`` or a
   stale *previous truth*, but never a label claiming a transition that
   didn't commit. This is the reference's read-truth-back principle
   (/root/reference/main.py:524-528) as a machine-checked property.
2. **Convergence** — a fault-free reconcile always lands the state label
   on the (canonical) desired mode, or on ``failed`` + a reason label for
   stable misconfigurations (invalid mode, slice on unsupported hardware),
   and the failed/reason pair is consistent (never one without the other
   after a failing reconcile).

The single-rule-based machine subsumes the hand-written fault tests'
combinatorics: Hypothesis explores orderings (fault→edit→crash→reconcile,
double faults, reconcile-after-reconcile idempotency…) no table of cases
would enumerate.
"""

from __future__ import annotations

import pytest

pytest.importorskip("hypothesis")  # optional dep; skip the whole stateful module without it

import hypothesis.strategies as st
from hypothesis import settings
from hypothesis.stateful import (
    RuleBasedStateMachine,
    initialize,
    invariant,
    rule,
)

from tpu_cc_manager.ccmanager.manager import CCManager
from tpu_cc_manager.kubeclient.api import node_labels
from tpu_cc_manager.kubeclient.fake import FakeKube
from tpu_cc_manager.labels import (
    CC_FAILED_REASON_LABEL,
    CC_MODE_LABEL,
    CC_MODE_STATE_LABEL,
    STATE_FAILED,
    VALID_MODES,
    canonical_mode,
)
from tpu_cc_manager.tpudev.fake import FakeTpuBackend
from tpu_cc_manager.utils.metrics import MetricsRegistry

NODE = "prop-node-0"

# 'slice' on this single-host fake is a STABLE misconfiguration (fail-soft
# with reason), 'bogus' a typo'd label: both must land failed+reason, not
# crash, not lie.
DESIRED_MODES = ["on", "off", "devtools", "ppcie", "slice", "bogus"]
FAULT_OPS = ["discover", "query", "stage", "reset", "wait_ready", "attest"]


class ReconcileMachine(RuleBasedStateMachine):
    @initialize()
    def setup(self) -> None:
        self.kube = FakeKube()
        self.kube.add_node(NODE, {CC_MODE_LABEL: "off"})
        self.backend = FakeTpuBackend()
        self._new_manager()
        self.last_reconcile_faulted = False

    def _new_manager(self) -> None:
        # A fresh CCManager over the SAME kube + backend is exactly what a
        # container restart gives a real node: all in-memory state gone,
        # device + apiserver state persisting.
        self.mgr = CCManager(
            api=self.kube,
            backend=self.backend,
            node_name=NODE,
            evict_components=False,
            smoke_workload="none",
            metrics=MetricsRegistry(),
            allow_fake_quotes=True,
        )

    # ---- actions ---------------------------------------------------------

    @rule(mode=st.sampled_from(DESIRED_MODES))
    def edit_desired_label(self, mode: str) -> None:
        self.kube.patch_node_labels(NODE, {CC_MODE_LABEL: mode})

    @rule(op=st.sampled_from(FAULT_OPS), times=st.integers(1, 2))
    def inject_backend_fault(self, op: str, times: int) -> None:
        self.backend.fail_next(op, times)

    @rule()
    def crash_restart_manager(self) -> None:
        self._new_manager()

    @rule()
    def reconcile(self) -> None:
        desired = node_labels(self.kube.get_node(NODE)).get(
            CC_MODE_LABEL, "off"
        )
        faults_armed = any(self.backend.fail.get(op, 0) for op in FAULT_OPS)
        ok = self.mgr.set_cc_mode(desired)
        labels = node_labels(self.kube.get_node(NODE))
        state = labels.get(CC_MODE_STATE_LABEL)
        reason = labels.get(CC_FAILED_REASON_LABEL)
        self.last_reconcile_faulted = faults_armed
        if ok:
            # Success must mean the label tells the canonical truth and no
            # stale failure reason survives.
            assert state == canonical_mode(desired), (desired, state)
            assert reason is None, reason
        else:
            # Failure must be outwardly visible: failed + reason together.
            assert state == STATE_FAILED, state
            assert reason, "failed state without a reason label"
        # Fault-FREE reconciles must never report failure for a valid,
        # hardware-supported mode (on/off/devtools all run on the fake).
        if not faults_armed and canonical_mode(desired) in (
            "on", "off", "devtools"
        ):
            assert ok, f"fault-free reconcile of {desired!r} failed"

    # ---- invariants ------------------------------------------------------

    @invariant()
    def state_label_never_lies(self) -> None:
        if not hasattr(self, "kube"):
            return  # before @initialize
        labels = node_labels(self.kube.get_node(NODE))
        state = labels.get(CC_MODE_STATE_LABEL)
        if state in VALID_MODES:
            # Read the fake's committed map directly — going through the
            # contract (discover/query) would trip faults armed for the
            # NEXT reconcile, not observe state.
            committed = set(self.backend.committed.values())
            assert committed == {state}, (
                f"state label claims {state!r} but chips committed "
                f"{sorted(committed)}"
            )

    @invariant()
    def failed_state_always_has_reason(self) -> None:
        if not hasattr(self, "kube"):
            return
        labels = node_labels(self.kube.get_node(NODE))
        if labels.get(CC_MODE_STATE_LABEL) == STATE_FAILED:
            assert labels.get(CC_FAILED_REASON_LABEL), (
                "failed state label without a failed.reason label"
            )


# Each step is a full reconcile against in-memory fakes (~ms); the budget
# below keeps the machine under a few seconds while still exploring
# hundreds of action orderings.
TestReconcileMachine = ReconcileMachine.TestCase
TestReconcileMachine.settings = settings(
    max_examples=25, stateful_step_count=15, deadline=None
)
