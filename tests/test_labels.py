"""Mode canonicalization and ready-state derivation (labels.py)."""

import re

from _hypothesis_compat import given, st

from tpu_cc_manager.labels import (
    MODE_DEVTOOLS,
    MODE_OFF,
    MODE_ON,
    MODE_SLICE,
    STATE_FAILED,
    canonical_mode,
    label_safe,
    ready_state_for,
)


def test_canonical_mode_passthrough():
    for m in (MODE_ON, MODE_OFF, MODE_DEVTOOLS, MODE_SLICE):
        assert canonical_mode(m) == m


def test_ppcie_alias_maps_to_slice():
    assert canonical_mode("ppcie") == MODE_SLICE


def test_ready_state():
    # Reference semantics (gpu_operator_eviction.py:275-288): on/fabric-wide
    # modes are ready, off is not, failed/unknown are indeterminate.
    assert ready_state_for(MODE_ON) == "true"
    assert ready_state_for(MODE_SLICE) == "true"
    assert ready_state_for(MODE_OFF) == "false"
    assert ready_state_for(STATE_FAILED) == ""
    assert ready_state_for("unknown") == ""
    # Deliberate divergence (SURVEY.md §8.4): devtools is explicit.
    assert ready_state_for(MODE_DEVTOOLS) == "debug"


# ---------------------------------------------------------------------------
# label_safe: the single shared sanitizer — every module writing derived
# label values (slice ids, failure reasons) flows through it, so its
# output must ALWAYS be a valid k8s label value.
# ---------------------------------------------------------------------------

# The apiserver's label-value regex (ASCII only — writing this property
# surfaced that Python's isalnum admits unicode the apiserver rejects).
K8S_LABEL_VALUE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")


@given(st.text(max_size=200))
def test_label_safe_always_produces_valid_label_values(value):
    out = label_safe(value)
    assert 1 <= len(out) <= 63
    assert K8S_LABEL_VALUE.match(out), out
    # Idempotent: sanitizing a sanitized value changes nothing.
    assert label_safe(out) == out


@given(st.text(
    alphabet="abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789",
    min_size=1, max_size=63,
))
def test_label_safe_preserves_already_valid_values(value):
    assert label_safe(value) == value


def test_label_safe_rejects_unicode_alnum():
    """'\u00c0' and '\u0663' are Python-alnum but NOT k8s-label-legal —
    they must be replaced, not passed through."""
    out = label_safe("slice-\u00c0-\u0663x")
    assert "\u00c0" not in out and "\u0663" not in out
    assert K8S_LABEL_VALUE.match(out)
