"""Mode canonicalization and ready-state derivation (labels.py)."""

from tpu_cc_manager.labels import (
    MODE_DEVTOOLS,
    MODE_OFF,
    MODE_ON,
    MODE_SLICE,
    STATE_FAILED,
    canonical_mode,
    ready_state_for,
)


def test_canonical_mode_passthrough():
    for m in (MODE_ON, MODE_OFF, MODE_DEVTOOLS, MODE_SLICE):
        assert canonical_mode(m) == m


def test_ppcie_alias_maps_to_slice():
    assert canonical_mode("ppcie") == MODE_SLICE


def test_ready_state():
    # Reference semantics (gpu_operator_eviction.py:275-288): on/fabric-wide
    # modes are ready, off is not, failed/unknown are indeterminate.
    assert ready_state_for(MODE_ON) == "true"
    assert ready_state_for(MODE_SLICE) == "true"
    assert ready_state_for(MODE_OFF) == "false"
    assert ready_state_for(STATE_FAILED) == ""
    assert ready_state_for("unknown") == ""
    # Deliberate divergence (SURVEY.md §8.4): devtools is explicit.
    assert ready_state_for(MODE_DEVTOOLS) == "debug"
